"""Benchmark: Llama decoder training throughput on the available TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

``vs_baseline`` is measured MFU divided by 0.40 — the A100-class MFU the
north-star asks to match (BASELINE.json: "match A100 MFU on Llama-2";
the reference publishes no numbers, BASELINE.md). vs_baseline >= 1.0 means
A100-parity-or-better utilization on this chip.

Usage: python bench.py [--smoke] [--steps N]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

# per-chip peak bf16 FLOP/s by TPU generation
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "v6 lite": 918e12,   # v6e reports device_kind "TPU v6 lite"
}
A100_CLASS_MFU = 0.40


def detect_peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, flops in PEAK_FLOPS.items():
        if key in kind:
            return flops
    return 197e12  # conservative default


# per-chip HBM bandwidth (bytes/s) by TPU generation — the decode
# roofline (BASELINE.md serving table): tokens/s ≈ BW / bytes-per-token
PEAK_HBM_BW = {
    "v5e": 819e9,
    "v5 lite": 819e9,
    "v5p": 2765e9,
    "v4": 1228e9,
    "v6e": 1638e9,
    "v6 lite": 1638e9,   # v6e reports device_kind "TPU v6 lite"
}


def detect_peak_bandwidth(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, bw in PEAK_HBM_BW.items():
        if key in kind:
            return bw
    return 819e9


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny fast config")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--remat-policy", default=None,
                    help="override cfg.remat_policy (sweep tool)")
    ap.add_argument("--lm-head-mode", default=None,
                    choices=["dense", "fused", "chunked", "auto"],
                    help="override cfg.lm_head_mode (sweep tool)")
    ap.add_argument("--sustained", action="store_true",
                    help="one long window (>=50 steps, 5-step sync chunks)"
                         " reporting p50/p95 step time alongside the rate")
    ap.add_argument("--compare", metavar="SHA", default=None,
                    help="A/B: run this working tree AND a git worktree of"
                         " SHA back-to-back (same default config each),"
                         " print both results + the ratio")
    args = ap.parse_args()

    if args.compare:
        return run_compare(args)

    import jax
    import jax.numpy as jnp

    import paddle_tpu
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import lr as lr_mod
    from paddle_tpu.parallel import mesh as M

    dev = jax.devices()[0]
    n_chips = len(jax.devices())
    peak = detect_peak_flops(dev)

    if args.smoke:
        cfg = LlamaConfig.tiny(num_layers=2)
        batch, seq = 4, 128
    else:
        # ~1B-param Llama (the largest that fits one v5e chip in bf16 with
        # fp32 AdamW moments). Pallas kernels (flash attention, fused
        # rms_norm/rope, fused lm-head⊗xent) dispatch automatically on TPU.
        # Measured round-4 sweep (this chip): the fused linear⊗xent head
        # (logits never materialized) frees enough HBM that bs4 +
        # save_mlp_dots_attn (skip recomputing the mlp gate/up matmuls and
        # the flash fwd) beats r3's bs8 + nothing_saveable 18.2k vs 17.5k
        # tok/s (MFU 0.602 vs 0.583); bs8 variants of the partial-save
        # policies and bs5 still OOM, and the dense head at this config
        # measures 16.6k (XLA spills near capacity).
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=16, num_heads=16, num_kv_heads=16, max_seq_len=2048,
            dtype="bfloat16", remat=True, remat_policy="save_mlp_dots_attn",
            lm_head_mode="fused")
        batch, seq = 4, 2048
    if args.batch:
        batch = args.batch
    if args.seq:
        seq = args.seq
        cfg = dataclasses.replace(cfg, max_seq_len=max(cfg.max_seq_len, seq))
    if args.remat_policy:
        cfg = dataclasses.replace(cfg, remat_policy=args.remat_policy)
    if args.lm_head_mode:
        cfg = dataclasses.replace(cfg, lm_head_mode=args.lm_head_mode)

    paddle_tpu.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = cfg.num_params()

    strategy = dist.DistributedStrategy()
    if n_chips > 1:
        strategy.sharding.enable = True
        strategy.sharding.stage = 3
        strategy.sharding.degree = n_chips
    mesh = M.mesh_from_strategy(strategy, jax.devices())
    with M.MeshContext(mesh):
        sched = lr_mod.warmup_cosine(3e-4, 100, 10000)
        step = dist.fleet.build_train_step(
            model,
            optimizer=optim.AdamW(sched,
                                  grad_clip=optim.ClipGradByGlobalNorm(1.0)),
            strategy=strategy, mesh=mesh)
        state = step.init_state(model)
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        data = step.shard_batch({"input_ids": jnp.asarray(ids),
                                 "labels": jnp.asarray(ids)})

        for i in range(args.warmup):
            state, metrics = step(state, data, jax.random.PRNGKey(i))
        jax.block_until_ready(metrics["loss"])

        # sync once at the end of each window: each step's (donated) state
        # feeds the next, so the chain is a real device-side dependency
        # and the final float() drains it. (Round-1's per-step sync was
        # guarding against dispatch-side caching of *identical* dispatches
        # — these aren't: the carried state differs every step.)
        # Best-of-3 windows: the shared tunnel shows ~20% transient
        # run-to-run spread; the fastest window estimates true device
        # throughput (standard min-over-repetitions practice).
        p50_step = p95_step = None
        if args.sustained:
            # sustained mode (north-star regression protocol): one long
            # window of >=50 steps synced every 5-step chunk — the
            # long-window rate can't be flattered by a lucky window, and
            # the chunk quantiles expose tunnel-transient tails
            chunk = 5
            n_chunks = max(10, args.steps // chunk)
            chunk_dts = []
            k = 0
            for _ in range(n_chunks):
                t0 = time.perf_counter()
                for _ in range(chunk):
                    state, metrics = step(state, data,
                                          jax.random.PRNGKey(100 + k))
                    k += 1
                float(metrics["loss"])
                chunk_dts.append(time.perf_counter() - t0)
            dt = sum(chunk_dts)
            median_dt = dt
            args.steps = n_chunks * chunk
            steps_sorted = sorted(d / chunk for d in chunk_dts)
            p50_step = steps_sorted[len(steps_sorted) // 2]
            p95_step = steps_sorted[
                min(len(steps_sorted) - 1,
                    int(round(0.95 * (len(steps_sorted) - 1))))]
        else:
            n_windows = 1 if args.smoke else 3
            window_dts = []
            for w in range(n_windows):
                t0 = time.perf_counter()
                for i in range(args.steps):
                    state, metrics = step(state, data,
                                          jax.random.PRNGKey(100 + i))
                float(metrics["loss"])
                window_dts.append(time.perf_counter() - t0)
            dt = min(window_dts)
            # median alongside the min: the min estimates peak device
            # throughput through the tunnel's ~20% spread, the median
            # guards against regressions the min would mask
            median_dt = sorted(window_dts)[len(window_dts) // 2]

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * args.steps / dt
    tokens_per_sec_chip = tokens_per_sec / n_chips
    # training FLOPs/token: 6N weight flops + attention 12*L*E*T
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
    mfu = tokens_per_sec_chip * flops_per_token / peak

    result = {
        "metric": (f"llama-{n_params/1e6:.0f}M bf16 train throughput "
                   f"({'sustained, ' if args.sustained else ''}seq={seq}, "
                   f"bs={batch}, "
                   f"{'zero3' if n_chips > 1 else 'single-chip'}, "
                   f"{getattr(dev, 'device_kind', 'unknown')})"),
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu / A100_CLASS_MFU, 4),
    }
    print(json.dumps(result))
    extra = ""
    if p50_step is not None:
        extra = (f"p50_step={p50_step*1e3:.1f}ms "
                 f"p95_step={p95_step*1e3:.1f}ms ")
    median_tps = tokens_per_step * args.steps / median_dt / n_chips
    print(f"# mfu={mfu:.3f} steps/sec={args.steps/dt:.3f} "
          f"median_tokens_per_sec_chip={median_tps:.1f} "
          f"median_mfu={mfu * dt / median_dt:.3f} {extra}"
          f"loss={float(metrics['loss']):.4f} params={n_params/1e6:.1f}M",
          file=sys.stderr)
    return result


def run_compare(args):
    """A/B protocol (BASELINE.md: 'never compare across days'): bench the
    current tree and a detached worktree of --compare SHA back-to-back in
    the same session, each on its own default headline config, and print
    one comparison JSON line. The reference's analogue is its op-benchmark
    regression gate (``tools/check_op_benchmark_result.py``)."""
    import os
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    sha = args.compare
    fwd = ["--steps", str(args.steps), "--warmup", str(args.warmup)]
    if args.smoke:
        fwd.append("--smoke")
    if args.sustained:
        fwd.append("--sustained")
    for flag, val in (("--batch", args.batch), ("--seq", args.seq),
                      ("--remat-policy", args.remat_policy),
                      ("--lm-head-mode", args.lm_head_mode)):
        if val:
            fwd.extend([flag, str(val)])

    def run_one(cwd, label, argv):
        proc = subprocess.run([sys.executable, os.path.join(cwd, "bench.py"),
                               *argv], capture_output=True, text=True,
                              cwd=cwd)
        if (proc.returncode == 2 and "unrecognized arguments" in proc.stderr
                and len(argv) > 4):
            # older SHAs predate the sweep/sustained flags: fall back to
            # the flags every bench.py revision understands; the caller
            # re-runs HEAD on the SAME reduced flags so the ratio never
            # mixes estimators/configs
            sys.stderr.write(f"# [{label}] does not know "
                             f"{' '.join(argv[4:])}; falling back to "
                             "--steps/--warmup only for BOTH sides\n")
            return run_one(cwd, label, argv[:4])
        line = next((ln for ln in reversed(proc.stdout.splitlines())
                     if ln.startswith("{")), None)
        if line is None:
            sys.stderr.write(proc.stdout + proc.stderr)
            raise RuntimeError(f"bench at {label} produced no JSON line")
        sys.stderr.write(f"# [{label}] {line}\n")
        for ln in proc.stderr.splitlines():
            if ln.startswith("#"):
                sys.stderr.write(f"# [{label}] {ln[1:].strip()}\n")
        return json.loads(line), argv

    wt = os.path.join(repo, ".bench_worktrees", sha)
    created = False
    if not os.path.isdir(wt):
        subprocess.run(["git", "worktree", "add", "--detach", wt, sha],
                       check=True, cwd=repo,
                       stdout=subprocess.DEVNULL)
        created = True
    try:
        # baseline first: if it falls back to the common flag set, HEAD
        # must run the identical protocol for the ratio to mean anything
        old, used = run_one(wt, sha[:12], fwd)
        cur, _ = run_one(repo, "HEAD", used)
    finally:
        if created:
            subprocess.run(["git", "worktree", "remove", "--force", wt],
                           cwd=repo, stdout=subprocess.DEVNULL)
    ratio = cur["value"] / old["value"] if old["value"] else float("nan")
    print(json.dumps({
        "metric": f"A/B {cur['metric']} vs {sha[:12]}",
        "value": round(ratio, 4),
        "unit": "x (HEAD tokens/sec over baseline sha, same session)",
        "vs_baseline": cur["vs_baseline"],
        "head": cur["value"], "baseline_sha": old["value"],
    }))


if __name__ == "__main__":
    main()
