"""Secondary benchmarks: per-family training throughput on one chip.

Fills the BASELINE.md "functional + throughput" rows beyond the headline
Llama proxy (`bench.py` stays the driver's single-JSON-line entry).
Prints one JSON line per model family. Timing follows bench.py: chained
donated state (the tunnel caches identical dispatches) and best-of-3
windows (transient tunnel spread).
"""

from __future__ import annotations

import json
import time

import numpy as np


def measure(step, state, data, steps=8, windows=3):
    import jax

    state, metrics = step(state, data, jax.random.PRNGKey(0))
    jax.block_until_ready(metrics["loss"])
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = step(state, data, jax.random.PRNGKey(i))
        float(metrics["loss"])
        times.append(time.perf_counter() - t0)
    return min(times) / steps, float(metrics["loss"])


def lm_bench(name, model, vocab, batch, seq, n_params):
    import jax
    import jax.numpy as jnp

    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.parallel import mesh as M

    mesh = M.create_mesh({"dp": 1}, jax.devices()[:1])
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.AdamW(1e-4), mesh=mesh)
        state = step.init_state(model)
        ids = np.random.RandomState(0).randint(
            0, vocab, (batch, seq)).astype(np.int32)
        data = step.shard_batch({"input_ids": jnp.asarray(ids),
                                 "labels": jnp.asarray(ids)})
        sec_per_step, loss = measure(step, state, data)
    print(json.dumps({
        "model": name, "params_m": round(n_params / 1e6, 1),
        "tokens_per_sec": round(batch * seq / sec_per_step, 1),
        "loss": round(loss, 3)}), flush=True)


def main(only: str | None = None):
    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, MambaConfig, MambaForCausalLM,
        MoEConfig, MoEForCausalLM, ErnieConfig, ErnieForPretraining,
    )

    paddle_tpu.seed(0)
    want = lambda name: only is None or only in name

    if want("gpt"):
        # GPT (gpt3-1.3b geometry trimmed to fit the chip + Adam moments)
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=12,
                        num_heads=16, max_seq_len=2048, dtype="bfloat16",
                        remat=True)
        n = 50304 * 2048 * 2 + 12 * 12 * 2048 * 2048
        lm_bench("gpt-0.7B", GPTForCausalLM(cfg), 50304, 8, 2048, n)

    if want("mamba"):
        # Mamba (Pallas selective-scan kernel; per-layer remat)
        mcfg = MambaConfig(vocab_size=50304, hidden_size=1024,
                           num_layers=24, dtype="bfloat16", remat=True)
        # exact count (tied embedding once) — the old 405M estimate
        # double-counted the tied table; true size is ~212M
        lm_bench("mamba-0.2B", MambaForCausalLM(mcfg), 50304, 8, 2048,
                 mcfg.num_params())

    if want("moe"):
        # MoE (8 experts, ~4x active sparsity)
        ecfg = MoEConfig(vocab_size=32000, hidden_size=1024,
                         intermediate_size=2816, num_layers=8, num_heads=16,
                         num_kv_heads=16, max_seq_len=1024,
                         dtype="bfloat16", num_experts=8, top_k=2)
        lm_bench("moe-8x", MoEForCausalLM(ecfg), 32000, 8, 1024,
                 ecfg.num_params())

    if want("longctx"):
        # Long-context single-chip: seq 16384 through the Pallas flash
        # attention (O(T) memory) + per-layer remat — the on-hardware leg
        # of the long-context story (ring/Ulysses extend it across chips)
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        lcfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=8, num_heads=16, num_kv_heads=8,
            max_seq_len=16384, dtype="bfloat16", remat=True,
            remat_policy="nothing_saveable")
        n = lcfg.num_params()
        lm_bench("llama-longctx-16k", LlamaForCausalLM(lcfg), 32000, 1,
                 16384, n)

    if want("decode"):
        # Autoregressive decode throughput (the serving-side number):
        # greedy generate on the bench llama geometry through the static
        # KV cache (models/generation.py), whole loop jitted. Decode is
        # HBM-bandwidth-bound (reads all weights + cache per token), so
        # tokens/s ≈ bandwidth / (params+cache bytes) — reported per
        # sequence (batch amortizes the weight reads).
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.models.generation import generate

        dcfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=16, num_heads=16, num_kv_heads=16,
            max_seq_len=1024, dtype="bfloat16", remat=False)
        import paddle_tpu as _pt
        _pt.seed(0)
        dmodel = LlamaForCausalLM(dcfg)
        db, prompt_len, new_toks = 8, 128, 512
        dids = jnp.asarray(np.random.RandomState(0).randint(
            0, dcfg.vocab_size, (db, prompt_len)).astype(np.int32))

        def decode_rate(model, ids=None, cache_dtype=None, reps=3):
            ids = dids if ids is None else ids
            gen = jax.jit(lambda m, i: generate(m, i, new_toks,
                                                cache_dtype=cache_dtype))
            out = gen(model, ids)
            np.asarray(out)                               # compile + run
            # time WITH a host fetch per rep: through the tunnel plugin,
            # block_until_ready alone can report dispatch-only time for
            # repeated identical executions (measured: 0.2ms vs the
            # real 4.3s) — fetching the tokens is the barrier
            t0 = time.perf_counter()
            for _ in range(reps):
                out = np.asarray(gen(model, ids))
            dt = (time.perf_counter() - t0) / reps
            assert out.shape == (db, ids.shape[1] + new_toks)
            return db * new_toks / dt

        from paddle_tpu.quant import quantize_weights_int8

        bf16_rate = decode_rate(dmodel)
        int8_rate = decode_rate(quantize_weights_int8(dmodel))
        print(json.dumps({
            "model": "llama-953M-decode",
            "params_m": round(dcfg.num_params() / 1e6, 1),
            "decode_tokens_per_sec": round(bf16_rate, 1),
            "tokens_per_sec_per_seq": round(bf16_rate / db, 1),
            "int8_weight_only_tokens_per_sec": round(int8_rate, 1),
            "batch": db, "new_tokens": new_toks}), flush=True)

        # GPT decode (learned positions, fused-QKV MHA) through the same
        # shared cache contract
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        gdcfg = GPTConfig(vocab_size=50304, hidden_size=2048,
                          num_layers=12, num_heads=16, max_seq_len=1024,
                          dropout=0.0, dtype="bfloat16", remat=False)
        _pt.seed(0)
        gmodel = GPTForCausalLM(gdcfg)
        gpt_rate = decode_rate(gmodel)
        gpt_int8 = decode_rate(quantize_weights_int8(gmodel))
        print(json.dumps({
            "model": "gpt-0.8B-decode",
            "params_m": round(gdcfg.num_params() / 1e6, 1),
            "decode_tokens_per_sec": round(gpt_rate, 1),
            "tokens_per_sec_per_seq": round(gpt_rate / db, 1),
            "int8_weight_only_tokens_per_sec": round(gpt_int8, 1),
            "batch": db, "new_tokens": new_toks}), flush=True)

        # Mamba stateful decode: the recurrent O(1)-per-token path — no
        # KV cache growth, constant state (conv tail + [Ei, N] SSM
        # state per layer), so per-token cost is flat in context length
        from paddle_tpu.models import MambaConfig, MambaForCausalLM

        mdcfg = MambaConfig(vocab_size=50304, hidden_size=1024,
                            num_layers=24, dtype="bfloat16")
        # long-context decode: the int8 KV cache's design point — the
        # cache bytes dominate the per-token reads at deep contexts
        import dataclasses

        lc_cfg = dataclasses.replace(dcfg, max_seq_len=4096)
        _pt.seed(0)
        lc_model = LlamaForCausalLM(lc_cfg)
        lc_ids = jnp.asarray(np.random.RandomState(0).randint(
            0, lc_cfg.vocab_size, (db, 3328)).astype(np.int32))
        lc_bf16 = decode_rate(lc_model, ids=lc_ids, reps=2)
        lc_int8 = decode_rate(lc_model, ids=lc_ids, cache_dtype=jnp.int8,
                              reps=2)
        print(json.dumps({
            "model": "llama-953M-decode-longctx",
            "live_context": 3328 + new_toks,
            "decode_tokens_per_sec": round(lc_bf16, 1),
            "int8_kv_cache_tokens_per_sec": round(lc_int8, 1),
            "batch": db, "new_tokens": new_toks}), flush=True)

        _pt.seed(0)
        mmodel = MambaForCausalLM(mdcfg)
        mam_rate = decode_rate(mmodel)
        mam_int8 = decode_rate(quantize_weights_int8(mmodel))
        print(json.dumps({
            "model": "mamba-0.2B-decode",
            "params_m": round(mdcfg.num_params() / 1e6, 1),
            "decode_tokens_per_sec": round(mam_rate, 1),
            "tokens_per_sec_per_seq": round(mam_rate / db, 1),
            "int8_weight_only_tokens_per_sec": round(mam_int8, 1),
            "batch": db, "new_tokens": new_toks}), flush=True)

    # ERNIE base MLM (encoder side)
    import paddle_tpu.distributed as dist
    from paddle_tpu.parallel import mesh as M
    from paddle_tpu import optimizer as optim

    mesh = M.create_mesh({"dp": 1}, jax.devices()[:1])
    rs = np.random.RandomState(0)

    if want("ernie"):
        bcfg = ErnieConfig(vocab_size=40000, hidden_size=768, num_layers=12,
                           num_heads=12, intermediate_size=3072,
                           max_seq_len=512, dtype="bfloat16", dropout=0.0,
                           remat=True)
        model = ErnieForPretraining(bcfg)
        ids = rs.randint(5, 40000, (16, 512)).astype(np.int32)
        labels = np.where(rs.rand(16, 512) < 0.15, ids,
                          -100).astype(np.int32)

        def loss_fn(m, batch, training=True):
            return m.loss(batch["input_ids"], batch["labels"],
                          training=training)

        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-4), loss_fn=loss_fn,
                mesh=mesh)
            state = step.init_state(model)
            data = step.shard_batch({"input_ids": jnp.asarray(ids),
                                     "labels": jnp.asarray(labels)})
            sec, loss = measure(step, state, data)
        print(json.dumps({"model": "ernie-base", "params_m": 110.0,
                          "tokens_per_sec": round(16 * 512 / sec, 1),
                          "loss": round(loss, 3)}), flush=True)

    if want("vit"):
        _vit_bench(dist, M, optim, mesh, rs)

    if want("ppyoloe"):
        _det_bench(dist, M, optim, mesh, rs)


def _vit_bench(dist, M, optim, mesh, rs):
    """ViT-L/16 image classification — bf16 AMP (autocast to bfloat16
    via the strategy compiler; fp32 master weights), with an MFU figure so
    the vision family has a hardware-utilization number like the LM
    rows."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.vision.models import vit_l_16

    rs = np.random.RandomState(11)   # own stream: results must not depend
    # on which earlier families ran (the `only` filter)
    vit = vit_l_16(num_classes=1000, remat=True)
    vb = 64   # per-layer remat frees activation memory; bs128 measured slower
    imgs = jnp.asarray(rs.randn(vb, 3, 224, 224).astype(np.float32))
    vlabels = jnp.asarray(rs.randint(0, 1000, (vb,)))

    def vit_loss(m, batch, training=True):
        import jax.numpy as jnp

        from paddle_tpu.nn import functional as F

        logits = m(batch["x"], training=training)
        return F.cross_entropy(logits.astype(jnp.float32), batch["y"])

    vs = dist.DistributedStrategy()
    vs.amp.enable = True
    vs.amp.dtype = "bfloat16"
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            vit, optimizer=optim.AdamW(1e-4), loss_fn=vit_loss,
            strategy=vs, mesh=mesh)
        state = step.init_state(vit)
        data = step.shard_batch({"x": imgs, "y": vlabels})
        sec, loss = measure(step, state, data)
    # fwd FLOPs/img from dims (E=1024 L=24 T=197 mlp=4E): per block the
    # matmuls are qkv 6TE^2 + out-proj 2TE^2 + mlp 16TE^2 = 24TE^2, plus
    # attention 4T^2E; patch embed 2*T*E*(3*16*16); x3 for training
    E, L, T = 1024, 24, (224 // 16) ** 2 + 1
    fwd = L * (24 * T * E * E + 4 * T * T * E) + 2 * T * E * 3 * 16 * 16
    from bench import detect_peak_flops
    vit_mfu = (vb / sec) * 3 * fwd / detect_peak_flops(jax.devices()[0])
    print(json.dumps({"model": "vit-l-16", "params_m": 304.0,
                      "images_per_sec": round(vb / sec, 1),
                      "amp": "bfloat16", "mfu": round(vit_mfu, 4),
                      "loss": round(loss, 3)}), flush=True)


def _det_bench(dist, M, optim, mesh, rs):
    """PP-YOLOE-s detection training (TAL + VFL/DFL/GIoU), 640x640."""
    import jax.numpy as jnp

    from paddle_tpu.vision.models import ppyoloe_s

    rs = np.random.RandomState(12)   # own stream (see _vit_bench)
    det = ppyoloe_s(num_classes=80)
    db = 8
    dimgs = jnp.asarray(rs.randn(db, 3, 640, 640).astype(np.float32) * 0.1)
    gtb = np.zeros((db, 8, 4), np.float32)
    gtl = np.full((db, 8), -1, np.int32)
    for i in range(db):
        for g in range(rs.randint(1, 9)):
            cx, cy = rs.rand(2) * 560 + 40
            w, h = rs.rand(2) * 120 + 30
            gtb[i, g] = [max(cx - w, 0), max(cy - h, 0),
                         min(cx + w, 640), min(cy + h, 640)]
            gtl[i, g] = rs.randint(0, 80)

    def det_loss(m, batch, training=True):
        return m.loss(batch["x"], batch["boxes"], batch["labels"],
                      training=training)

    # scoped bf16 AMP (r4): backbone/neck/head convs autocast to bf16 and
    # BatchNorm emits its input dtype (f32 statistics math), while
    # model.loss pins decode/TAL/VFL/DFL/GIoU fp32 via amp.suspend —
    # measured 175.8 vs 136.4 img/s fp32 (1.29x) with step-1 loss parity
    # 0.4%; r3's whole-model autocast measured 9.3 img/s (15x SLOWER)
    ds = dist.DistributedStrategy()
    ds.amp.enable = True
    ds.amp.dtype = "bfloat16"
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            det, optimizer=optim.AdamW(1e-4), loss_fn=det_loss,
            strategy=ds, mesh=mesh)
        state = step.init_state(det)
        data = step.shard_batch({"x": dimgs, "boxes": jnp.asarray(gtb),
                                 "labels": jnp.asarray(gtl)})
        sec, loss = measure(step, state, data)
    print(json.dumps({"model": "ppyoloe-s-640", "params_m": 6.7,
                      "images_per_sec": round(db / sec, 1),
                      "loss": round(loss, 3)}), flush=True)


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None)
