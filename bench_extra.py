"""Secondary benchmarks: per-family training throughput on one chip.

Fills the BASELINE.md "functional + throughput" rows beyond the headline
Llama proxy (`bench.py` stays the driver's single-JSON-line entry).
Prints one JSON line per model family. Timing follows bench.py: chained
donated state (the tunnel caches identical dispatches) and best-of-3
windows (transient tunnel spread).
"""

from __future__ import annotations

import json
import time

import numpy as np


def measure(step, state, data, steps=8, windows=3):
    import jax

    state, metrics = step(state, data, jax.random.PRNGKey(0))
    jax.block_until_ready(metrics["loss"])
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = step(state, data, jax.random.PRNGKey(i))
        float(metrics["loss"])
        times.append(time.perf_counter() - t0)
    return min(times) / steps, float(metrics["loss"])


def lm_bench(name, model, vocab, batch, seq, n_params):
    import jax
    import jax.numpy as jnp

    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.parallel import mesh as M

    mesh = M.create_mesh({"dp": 1}, jax.devices()[:1])
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.AdamW(1e-4), mesh=mesh)
        state = step.init_state(model)
        ids = np.random.RandomState(0).randint(
            0, vocab, (batch, seq)).astype(np.int32)
        data = step.shard_batch({"input_ids": jnp.asarray(ids),
                                 "labels": jnp.asarray(ids)})
        sec_per_step, loss = measure(step, state, data)
    print(json.dumps({
        "model": name, "params_m": round(n_params / 1e6, 1),
        "tokens_per_sec": round(batch * seq / sec_per_step, 1),
        "loss": round(loss, 3)}), flush=True)


def main(only: str | None = None):
    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, MambaConfig, MambaForCausalLM,
        MoEConfig, MoEForCausalLM, ErnieConfig, ErnieForPretraining,
    )

    paddle_tpu.seed(0)
    want = lambda name: only is None or only in name

    if want("gpt"):
        # GPT (gpt3-1.3b geometry trimmed to fit the chip + Adam moments)
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=12,
                        num_heads=16, max_seq_len=2048, dtype="bfloat16",
                        remat=True)
        n = 50304 * 2048 * 2 + 12 * 12 * 2048 * 2048
        lm_bench("gpt-0.7B", GPTForCausalLM(cfg), 50304, 8, 2048, n)

    if want("mamba"):
        # Mamba (Pallas selective-scan kernel; per-layer remat)
        mcfg = MambaConfig(vocab_size=50304, hidden_size=1024,
                           num_layers=24, dtype="bfloat16", remat=True)
        # exact count (tied embedding once) — the old 405M estimate
        # double-counted the tied table; true size is ~212M
        lm_bench("mamba-0.2B", MambaForCausalLM(mcfg), 50304, 8, 2048,
                 mcfg.num_params())

    if want("moe"):
        # MoE (8 experts, ~4x active sparsity). r5: blocks are
        # scan-stacked (the pp×ep enabler); the unrolled no-remat graph
        # now exceeds the remote-compile helper's budget, and
        # dots_saveable per-layer remat is the measured optimum of the
        # policies that compile (47.0k vs full-recompute 40.5k vs the
        # r4 python-loop no-remat 49.7k — the scan conversion costs ~5%
        # on this single-chip leg in exchange for pipeline support)
        ecfg = MoEConfig(vocab_size=32000, hidden_size=1024,
                         intermediate_size=2816, num_layers=8, num_heads=16,
                         num_kv_heads=16, max_seq_len=1024,
                         dtype="bfloat16", num_experts=8, top_k=2,
                         remat=True, remat_policy="dots_saveable")
        lm_bench("moe-8x", MoEForCausalLM(ecfg), 32000, 8, 1024,
                 ecfg.num_params())

    if want("longctx"):
        # Long-context single-chip: seq 16384 through the Pallas flash
        # attention (O(T) memory) + per-layer remat — the on-hardware leg
        # of the long-context story (ring/Ulysses extend it across chips)
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        lcfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=8, num_heads=16, num_kv_heads=8,
            max_seq_len=16384, dtype="bfloat16", remat=True,
            remat_policy="nothing_saveable")
        n = lcfg.num_params()
        lm_bench("llama-longctx-16k", LlamaForCausalLM(lcfg), 32000, 1,
                 16384, n)

    if want("decode"):
        _decode_benches(only)

    # ERNIE base MLM (encoder side)
    import paddle_tpu.distributed as dist
    from paddle_tpu.parallel import mesh as M
    from paddle_tpu import optimizer as optim

    mesh = M.create_mesh({"dp": 1}, jax.devices()[:1])
    rs = np.random.RandomState(0)

    if want("ernie"):
        bcfg = ErnieConfig(vocab_size=40000, hidden_size=768, num_layers=12,
                           num_heads=12, intermediate_size=3072,
                           max_seq_len=512, dtype="bfloat16", dropout=0.0,
                           remat=True)
        model = ErnieForPretraining(bcfg)
        ids = rs.randint(5, 40000, (16, 512)).astype(np.int32)
        labels = np.where(rs.rand(16, 512) < 0.15, ids,
                          -100).astype(np.int32)

        def loss_fn(m, batch, training=True):
            return m.loss(batch["input_ids"], batch["labels"],
                          training=training)

        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-4), loss_fn=loss_fn,
                mesh=mesh)
            state = step.init_state(model)
            data = step.shard_batch({"input_ids": jnp.asarray(ids),
                                     "labels": jnp.asarray(labels)})
            sec, loss = measure(step, state, data)
        print(json.dumps({"model": "ernie-base", "params_m": 110.0,
                          "tokens_per_sec": round(16 * 512 / sec, 1),
                          "loss": round(loss, 3)}), flush=True)

    if want("vit"):
        _vit_bench(dist, M, optim, mesh, rs)

    if want("ppyoloe"):
        _det_bench(dist, M, optim, mesh, rs)


def _gen_time(model, ids, n_new, cache_dtype=None, reps=3):
    """Best-of-reps wall time of one jitted generate() call. Times WITH
    a host fetch per rep: through the tunnel plugin, block_until_ready
    alone can report dispatch-only time for repeated identical
    executions (measured: 0.2 ms vs the real 4.3 s) — fetching the
    tokens is the barrier."""
    import jax

    from paddle_tpu.models.generation import generate

    gen = jax.jit(lambda m, i: generate(m, i, n_new,
                                        cache_dtype=cache_dtype))
    out = np.asarray(gen(model, ids))                 # compile + run
    assert out.shape == (ids.shape[0], ids.shape[1] + n_new)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(gen(model, ids))
        best = min(best, time.perf_counter() - t0)
    return best


def _decode_leg(name, model, ids, n_new, *, cache_dtype=None,
                weight_bytes=None, kv_bytes_per_tok=0.0, reps=3,
                extra=None):
    """One serving leg, reported the way serving systems report:
    prefill latency (the 16-token run ≈ TTFT) and steady-state decode
    rate (marginal tokens between the 16- and n_new-token runs — free
    of prefill amortization), plus roofline accounting: bytes/step =
    full weight read + average live KV-cache read, vs the chip's HBM
    peak. Decode is HBM-bandwidth-bound, so achieved/peak is the
    utilization number that matters."""
    import jax

    from bench import detect_peak_bandwidth

    B, T0 = ids.shape
    # warm run length keeps T0+warm a multiple of the decode kernel's
    # block size (128): a misaligned cache would push the warm run onto
    # the einsum fallback and skew the marginal-rate subtraction
    warm = 128
    t_small = _gen_time(model, ids, warm, cache_dtype=cache_dtype,
                        reps=reps)
    t_full = _gen_time(model, ids, n_new, cache_dtype=cache_dtype,
                       reps=reps)
    steady = B * (n_new - warm) / (t_full - t_small)
    total = B * n_new / t_full
    sec_per_step = (t_full - t_small) / (n_new - warm)

    rec = {"model": name, "batch": B, "new_tokens": n_new,
           "decode_tokens_per_sec": round(steady, 1),
           "tokens_per_sec_per_seq": round(steady / B, 1),
           "total_tokens_per_sec_incl_prefill": round(total, 1),
           "prefill_plus_warm_s": round(t_small, 3)}
    if weight_bytes is not None:
        avg_live = T0 + (warm + n_new) / 2
        step_bytes = weight_bytes + kv_bytes_per_tok * B * avg_live
        bw = detect_peak_bandwidth(jax.devices()[0])
        rec["achieved_gb_per_s"] = round(step_bytes / sec_per_step / 1e9,
                                         1)
        rec["hbm_roofline_frac"] = round(
            step_bytes / sec_per_step / bw, 3)
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)
    return steady


def _model_weight_bytes(model, exclude_embed_attrs=("embed", "pos_embed")):
    """Bytes of parameters a decode step actually re-reads: every leaf
    at its stored dtype (int8 weights count 1 byte + their scales),
    minus embedding tables (a gather reads one row per token)."""
    import jax

    total = sum(l.nbytes for l in jax.tree_util.tree_leaves(model)
                if hasattr(l, "nbytes"))
    for attr in exclude_embed_attrs:
        emb = getattr(model, attr, None)
        if emb is not None:
            total -= sum(l.nbytes for l in jax.tree_util.tree_leaves(emb)
                         if hasattr(l, "nbytes"))
    return total


def _decode_benches(only=None):
    """Serving-side decode legs: llama batch frontier (bf16 and
    int8-weights ∘ int8-KV-cache), GPT, long-context, MoE, Mamba —
    all through the shared cache contract + the fused decode-attention
    kernel (ops/pallas/decode_attention.py)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    import paddle_tpu as _pt
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, LlamaConfig, LlamaForCausalLM,
        MambaConfig, MambaForCausalLM, MoEConfig, MoEForCausalLM)
    from paddle_tpu.quant import quantize_weights_int8

    dcfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=16, num_heads=16, num_kv_heads=16,
        max_seq_len=1024, dtype="bfloat16", remat=False)
    _pt.seed(0)
    dmodel = LlamaForCausalLM(dcfg)
    qmodel = quantize_weights_int8(dmodel)
    prompt_len, new_toks = 128, 512
    kv_tok = 2 * dcfg.num_layers * dcfg.num_kv_heads * \
        (dcfg.hidden_size // dcfg.num_heads)          # elems per token

    def ids_for(B):
        return jnp.asarray(np.random.RandomState(0).randint(
            0, dcfg.vocab_size, (B, prompt_len)).astype(np.int32))

    wb, wq = _model_weight_bytes(dmodel), _model_weight_bytes(qmodel)
    # batch frontier: weights amortize across the batch until the live
    # KV cache fills HBM (bf16 tops out near bs96 on 16 GB; the int8
    # pair reaches bs128) — the aggregate-throughput lever
    for B in (8, 32, 96):
        _decode_leg("llama-953M-decode", dmodel, ids_for(B), new_toks,
                    weight_bytes=wb, kv_bytes_per_tok=kv_tok * 2,
                    extra={"params_m": round(dcfg.num_params() / 1e6, 1)})
    for B in (8, 32, 128):
        _decode_leg("llama-953M-decode-int8w-int8kv", qmodel,
                    ids_for(B), new_toks, cache_dtype=jnp.int8,
                    weight_bytes=wq,
                    kv_bytes_per_tok=kv_tok * 1 + 2 * 4 * dcfg.num_layers
                    * dcfg.num_kv_heads)
    del qmodel

    # GPT decode (learned positions, fused-QKV MHA), same contract
    gdcfg = GPTConfig(vocab_size=50304, hidden_size=2048,
                      num_layers=12, num_heads=16, max_seq_len=1024,
                      dropout=0.0, dtype="bfloat16", remat=False)
    _pt.seed(0)
    gmodel = GPTForCausalLM(gdcfg)
    gids = jnp.asarray(np.random.RandomState(0).randint(
        0, gdcfg.vocab_size, (8, prompt_len)).astype(np.int32))
    gkv = 2 * gdcfg.num_layers * gdcfg.num_heads * \
        (gdcfg.hidden_size // gdcfg.num_heads)
    _decode_leg("gpt-0.8B-decode", gmodel, gids, new_toks,
                weight_bytes=_model_weight_bytes(gmodel),
                kv_bytes_per_tok=gkv * 2,
                extra={"params_m": round(gdcfg.num_params() / 1e6, 1)})
    gq = quantize_weights_int8(gmodel)
    _decode_leg("gpt-0.8B-decode-int8w", gq, gids, new_toks,
                weight_bytes=_model_weight_bytes(gq),
                kv_bytes_per_tok=gkv * 2)
    del gmodel, gq

    # long-context: S=4096, live context ~3.8k — the int8-KV design
    # point (cache bytes dominate); prefill reported separately (its
    # cost includes quantizing the 3328-token prompt into the cache)
    lc_cfg = dataclasses.replace(dcfg, max_seq_len=4096)
    _pt.seed(0)
    lc_model = LlamaForCausalLM(lc_cfg)
    lc_ids = jnp.asarray(np.random.RandomState(0).randint(
        0, lc_cfg.vocab_size, (8, 3328)).astype(np.int32))
    _decode_leg("llama-953M-decode-longctx", lc_model, lc_ids, new_toks,
                weight_bytes=wb, kv_bytes_per_tok=kv_tok * 2, reps=2,
                extra={"live_context": 3328 + new_toks})
    _decode_leg("llama-953M-decode-longctx-int8kv", lc_model, lc_ids,
                new_toks, cache_dtype=jnp.int8,
                weight_bytes=wb,
                kv_bytes_per_tok=kv_tok * 1 + 2 * 4 * dcfg.num_layers
                * dcfg.num_kv_heads, reps=2,
                extra={"live_context": 3328 + new_toks})
    del lc_model

    # MoE decode: expert weights dominate the per-step read (every
    # expert is resident even though top-k route per token), so the
    # int8-weight win is the largest of any family
    ecfg = MoEConfig(vocab_size=32000, hidden_size=1024,
                     intermediate_size=2816, num_layers=8, num_heads=16,
                     num_kv_heads=16, max_seq_len=1024,
                     dtype="bfloat16", num_experts=8, top_k=2)
    _pt.seed(0)
    emodel = MoEForCausalLM(ecfg)
    eids = jnp.asarray(np.random.RandomState(0).randint(
        0, ecfg.vocab_size, (8, prompt_len)).astype(np.int32))
    ekv = 2 * ecfg.num_layers * ecfg.num_kv_heads * \
        (ecfg.hidden_size // ecfg.num_heads)
    _decode_leg("moe-8x-decode", emodel, eids, new_toks,
                weight_bytes=_model_weight_bytes(emodel),
                kv_bytes_per_tok=ekv * 2,
                extra={"params_m": round(ecfg.num_params() / 1e6, 1)})
    eq = quantize_weights_int8(emodel)
    _decode_leg("moe-8x-decode-int8w", eq, eids, new_toks,
                weight_bytes=_model_weight_bytes(eq),
                kv_bytes_per_tok=ekv * 2)
    del emodel, eq

    # Mamba stateful decode: the recurrent O(1)-per-token path — no KV
    # cache growth, constant state, per-token cost flat in context
    mdcfg = MambaConfig(vocab_size=50304, hidden_size=1024,
                        num_layers=24, dtype="bfloat16")
    _pt.seed(0)
    mmodel = MambaForCausalLM(mdcfg)
    mids = jnp.asarray(np.random.RandomState(0).randint(
        0, mdcfg.vocab_size, (8, prompt_len)).astype(np.int32))
    _decode_leg("mamba-0.2B-decode", mmodel, mids, new_toks,
                weight_bytes=_model_weight_bytes(mmodel),
                extra={"params_m": round(mdcfg.num_params() / 1e6, 1)})
    mq = quantize_weights_int8(mmodel)
    _decode_leg("mamba-0.2B-decode-int8w", mq, mids, new_toks,
                weight_bytes=_model_weight_bytes(mq))


def _vit_bench(dist, M, optim, mesh, rs):
    """ViT-L/16 image classification — bf16 AMP (autocast to bfloat16
    via the strategy compiler; fp32 master weights), with an MFU figure so
    the vision family has a hardware-utilization number like the LM
    rows."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.vision.models import vit_l_16

    rs = np.random.RandomState(11)   # own stream: results must not depend
    # on which earlier families ran (the `only` filter)
    vit = vit_l_16(num_classes=1000, remat=True)
    vb = 64   # per-layer remat frees activation memory; bs128 measured slower
    imgs = jnp.asarray(rs.randn(vb, 3, 224, 224).astype(np.float32))
    vlabels = jnp.asarray(rs.randint(0, 1000, (vb,)))

    def vit_loss(m, batch, training=True):
        import jax.numpy as jnp

        from paddle_tpu.nn import functional as F

        logits = m(batch["x"], training=training)
        return F.cross_entropy(logits.astype(jnp.float32), batch["y"])

    vs = dist.DistributedStrategy()
    vs.amp.enable = True
    vs.amp.dtype = "bfloat16"
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            vit, optimizer=optim.AdamW(1e-4), loss_fn=vit_loss,
            strategy=vs, mesh=mesh)
        state = step.init_state(vit)
        data = step.shard_batch({"x": imgs, "y": vlabels})
        sec, loss = measure(step, state, data)
    # fwd FLOPs/img from dims (E=1024 L=24 T=197 mlp=4E): per block the
    # matmuls are qkv 6TE^2 + out-proj 2TE^2 + mlp 16TE^2 = 24TE^2, plus
    # attention 4T^2E; patch embed 2*T*E*(3*16*16); x3 for training
    E, L, T = 1024, 24, (224 // 16) ** 2 + 1
    fwd = L * (24 * T * E * E + 4 * T * T * E) + 2 * T * E * 3 * 16 * 16
    from bench import detect_peak_flops
    vit_mfu = (vb / sec) * 3 * fwd / detect_peak_flops(jax.devices()[0])
    print(json.dumps({"model": "vit-l-16", "params_m": 304.0,
                      "images_per_sec": round(vb / sec, 1),
                      "amp": "bfloat16", "mfu": round(vit_mfu, 4),
                      "loss": round(loss, 3)}), flush=True)


def _det_bench(dist, M, optim, mesh, rs):
    """PP-YOLOE-s detection training (TAL + VFL/DFL/GIoU), 640x640."""
    import jax.numpy as jnp

    from paddle_tpu.vision.models import ppyoloe_s

    rs = np.random.RandomState(12)   # own stream (see _vit_bench)
    det = ppyoloe_s(num_classes=80)
    db = 8
    dimgs = jnp.asarray(rs.randn(db, 3, 640, 640).astype(np.float32) * 0.1)
    gtb = np.zeros((db, 8, 4), np.float32)
    gtl = np.full((db, 8), -1, np.int32)
    for i in range(db):
        for g in range(rs.randint(1, 9)):
            cx, cy = rs.rand(2) * 560 + 40
            w, h = rs.rand(2) * 120 + 30
            gtb[i, g] = [max(cx - w, 0), max(cy - h, 0),
                         min(cx + w, 640), min(cy + h, 640)]
            gtl[i, g] = rs.randint(0, 80)

    def det_loss(m, batch, training=True):
        return m.loss(batch["x"], batch["boxes"], batch["labels"],
                      training=training)

    # scoped bf16 AMP (r4): backbone/neck/head convs autocast to bf16 and
    # BatchNorm emits its input dtype (f32 statistics math), while
    # model.loss pins decode/TAL/VFL/DFL/GIoU fp32 via amp.suspend —
    # measured 175.8 vs 136.4 img/s fp32 (1.29x) with step-1 loss parity
    # 0.4%; r3's whole-model autocast measured 9.3 img/s (15x SLOWER)
    ds = dist.DistributedStrategy()
    ds.amp.enable = True
    ds.amp.dtype = "bfloat16"
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            det, optimizer=optim.AdamW(1e-4), loss_fn=det_loss,
            strategy=ds, mesh=mesh)
        state = step.init_state(det)
        data = step.shard_batch({"x": dimgs, "boxes": jnp.asarray(gtb),
                                 "labels": jnp.asarray(gtl)})
        sec, loss = measure(step, state, data)
    print(json.dumps({"model": "ppyoloe-s-640", "params_m": 6.7,
                      "images_per_sec": round(db / sec, 1),
                      "loss": round(loss, 3)}), flush=True)


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None)
