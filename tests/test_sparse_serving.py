"""PS-backed sparse embedding serving (``FLAGS_serving_emb``, hard-off).

The load-bearing contracts: the hot-row LRU de-duplicates and batches
cache misses into ONE ``PSClient`` pull (with TTL expiry and capacity
eviction); the batched CTR endpoint's wire outputs match solo
predictions and stamp every response row with exactly one table
version; an online version rollover under concurrent load drops
nothing, restarts nothing, and never mixes two versions' rows inside
one response; PS outages degrade to counted stale serves rather than
errors for rows we still hold; and with the flag off (the default) the
server constructs no tier, ships no ``emb`` health block, and reads no
``serving_emb`` flags on the hot path (spy-pinned).  Satellite: live
tenant-quota reconfig (``GenScheduler.set_quotas`` + the
``sched_quotas`` wire op + the controller push, decision-logged).
"""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.core.flags import flag, set_flags
from paddle_tpu.distributed.ps import InProcClient, ParameterServer, PSClient
from paddle_tpu.io.serving import InferenceClient, InferenceServer
from paddle_tpu.serving import MetricsHub, RoutedClient, ServingController
from paddle_tpu.serving.control import InProcSpawner
from paddle_tpu.serving.scheduler import GenScheduler
from paddle_tpu.serving.sparse import EmbeddingServingTier, SparseCTRPredictor

pytestmark = pytest.mark.sparse

DIM = 8
SLOTS = 3


class _CountingPS:
    """Delegates to an InProcClient but counts versioned pulls."""

    def __init__(self, inner):
        self.inner = inner
        self.pulls = 0
        self.pulled_ids: list[np.ndarray] = []
        self.fail = False

    def pull_versioned(self, name, ids):
        if self.fail:
            raise ConnectionError("ps fleet unreachable (injected)")
        self.pulls += 1
        self.pulled_ids.append(np.asarray(ids, np.int64).copy())
        return self.inner.pull_versioned(name, ids)

    def versions(self):
        if self.fail:
            raise ConnectionError("ps fleet unreachable (injected)")
        return self.inner.versions()


def _mk_ps(seed=3):
    ps = InProcClient()
    ps.create_table("emb", DIM, optimizer="sgd", lr=0.5, seed=seed)
    return ps


@pytest.fixture
def emb_flags():
    """Enable the tier for a test; always restore the hard-off default."""
    def enable(cache_rows=256, ttl_s=0.0, batch_max=0):
        f = {"serving_emb": True, "serving_emb_cache_rows": cache_rows,
             "serving_emb_ttl_s": ttl_s}
        if batch_max:
            f.update({"serving_batch_max": batch_max,
                      "serving_batch_timeout_s": 0.02,
                      "serving_batch_min_queue": 0})
        set_flags(f)
    yield enable
    set_flags({"serving_emb": False, "serving_emb_cache_rows": 4096,
               "serving_emb_ttl_s": 0.0, "serving_batch_max": 0,
               "serving_batch_timeout_s": 0.005,
               "serving_batch_min_queue": 2})


# ---------------------------------------------------------------------------
# hot-row cache units
# ---------------------------------------------------------------------------

def test_cache_miss_dedup_then_hits():
    ps = _mk_ps()
    counting = _CountingPS(ps)
    tier = EmbeddingServingTier(counting, cache_rows=64, ttl_s=0.0)
    ids = np.array([5, 7, 5, 9, 7], np.int64)
    rows, ver = tier.lookup("emb", ids)
    assert rows.shape == (5, DIM) and ver == 0
    np.testing.assert_array_equal(rows, ps.pull("emb", ids))
    # duplicated ids were de-duplicated into ONE pull of the uniques
    assert counting.pulls == 1
    np.testing.assert_array_equal(counting.pulled_ids[0],
                                  np.array([5, 7, 9], np.int64))
    # second lookup: pure cache hits, zero pulls
    rows2, _ = tier.lookup("emb", ids)
    np.testing.assert_array_equal(rows2, rows)
    assert counting.pulls == 1
    s = tier.stats()["tables"]["emb"]
    assert s["misses"] == 3 and s["hits"] >= 3
    assert s["cached_rows"] == 3 and s["version"] == 0


def test_lookup_preserves_id_shape():
    ps = _mk_ps()
    tier = EmbeddingServingTier(ps, cache_rows=64, ttl_s=0.0)
    ids = np.arange(6, dtype=np.int64).reshape(2, 3)
    rows, _ = tier.lookup("emb", ids)
    assert rows.shape == (2, 3, DIM)
    np.testing.assert_array_equal(rows.reshape(6, DIM),
                                  ps.pull("emb", ids.reshape(-1)))


def test_ttl_expiry_repulls():
    counting = _CountingPS(_mk_ps())
    tier = EmbeddingServingTier(counting, cache_rows=64, ttl_s=0.05)
    ids = np.array([1, 2], np.int64)
    tier.lookup("emb", ids)
    tier.lookup("emb", ids)                       # within TTL: hits
    assert counting.pulls == 1
    time.sleep(0.08)
    tier.lookup("emb", ids)                       # expired: re-pulled
    assert counting.pulls == 2
    assert tier.stats()["tables"]["emb"]["misses"] == 4


def test_lru_eviction_at_capacity():
    counting = _CountingPS(_mk_ps())
    tier = EmbeddingServingTier(counting, cache_rows=2, ttl_s=0.0)
    tier.lookup("emb", np.array([1], np.int64))
    tier.lookup("emb", np.array([2], np.int64))
    tier.lookup("emb", np.array([3], np.int64))   # evicts 1 (LRU)
    st = tier.stats()["tables"]["emb"]
    assert st["evictions"] == 1 and st["cached_rows"] == 2
    pulls = counting.pulls
    tier.lookup("emb", np.array([3], np.int64))   # still cached
    assert counting.pulls == pulls
    tier.lookup("emb", np.array([1], np.int64))   # evicted: re-pulled
    assert counting.pulls == pulls + 1


def test_ps_outage_serves_stale_counted_and_reraises_unknown():
    counting = _CountingPS(_mk_ps())
    tier = EmbeddingServingTier(counting, cache_rows=64, ttl_s=0.01)
    ids = np.array([4, 5], np.int64)
    warm, _ = tier.lookup("emb", ids)
    time.sleep(0.03)                              # rows now TTL-expired
    counting.fail = True
    rows, ver = tier.lookup("emb", ids)           # outage: stale fallback
    np.testing.assert_array_equal(rows, warm)
    st = tier.stats()["tables"]["emb"]
    assert st["stale_serves"] == 2 and ver == 0
    with pytest.raises(ConnectionError):          # uncached id: no fallback
        tier.lookup("emb", np.array([4, 99], np.int64))
    counting.fail = False
    tier.lookup("emb", ids)                       # recovery: pulls again
    assert tier.stats()["tables"]["emb"]["stale_serves"] == 2


# ---------------------------------------------------------------------------
# version rollover
# ---------------------------------------------------------------------------

def test_pull_reply_version_flips_generation():
    ps = _mk_ps()
    tier = EmbeddingServingTier(ps, cache_rows=64, ttl_s=0.0)
    _, v0 = tier.lookup("emb", np.array([1, 2], np.int64))
    assert v0 == 0
    assert ps.publish_version("emb") == 1
    # the next MISS pull comes back stamped v1 -> the whole response
    # (cached ids included) re-resolves at v1; nothing mixes versions
    rows, v1 = tier.lookup("emb", np.array([1, 2, 3], np.int64))
    assert v1 == 1 and rows.shape == (3, DIM)
    st = tier.stats()["tables"]["emb"]
    assert st["rollovers"] == 1 and st["version"] == 1


def test_maybe_rollover_polls_and_rate_limits():
    ps = _mk_ps()
    tier = EmbeddingServingTier(ps, cache_rows=64, ttl_s=0.0)
    tier.lookup("emb", np.array([1], np.int64))
    ps.publish_version("emb")
    assert tier.maybe_rollover() == {"emb": 1}
    assert tier.stats()["tables"]["emb"]["version"] == 1
    assert tier.maybe_rollover() is None          # rate-limited


def test_publish_version_writes_manifest_before_bump(tmp_path):
    ps = _mk_ps()
    ps.pull("emb", np.array([1, 2, 3], np.int64))
    root = str(tmp_path / "pub")
    v = ps.publish_version("emb", root=root)
    assert v == 1
    import json
    import os
    man = json.load(open(os.path.join(root, "v1", "MANIFEST.json")))
    assert man["table"] == "emb" and man["version"] == 1
    assert man["rows"] == 3 and man["shards"] == 1
    assert ps.table_version("emb") == 1


def test_tcp_publish_is_fleetwide_and_monotonic():
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    try:
        c = PSClient([s1.endpoint, s2.endpoint])
        c.create_table("emb", 4, optimizer="sgd", lr=0.5, seed=9)
        ids = np.arange(8, dtype=np.int64)
        rows, ver = c.pull_versioned("emb", ids)
        assert rows.shape == (8, 4) and ver == 0
        assert c.publish_version("emb") == 1
        assert c.versions() == {"emb": 1}
        # every shard answers the new version inside pull replies too
        assert c.pull_versioned("emb", ids)[1] == 1
        # replayed publish of an older version never regresses
        for conn in c._conns:
            conn.request("publish", {"name": "emb", "version": 1})
        assert c.table_version("emb") == 1
        c.close()
    finally:
        s1.stop(), s2.stop()


# ---------------------------------------------------------------------------
# batched CTR endpoint over the wire
# ---------------------------------------------------------------------------

def _expected_scores(tier_client, pred, ids):
    """Solo reference: a fresh tier over the same PS state."""
    ref_tier = EmbeddingServingTier(tier_client, cache_rows=1024, ttl_s=0.0)
    ref = SparseCTRPredictor(ref_tier, "emb", SLOTS, emb_dim=DIM, seed=0)
    return ref.run(ids)


def test_batched_endpoint_matches_solo_and_stamps_version(emb_flags):
    emb_flags(batch_max=8)
    ps = _mk_ps()
    counting = _CountingPS(ps)
    srv = InferenceServer({})
    try:
        tier = srv.attach_embeddings(counting)
        assert tier is not None
        srv.add_model("ctr", SparseCTRPredictor(tier, "emb", SLOTS,
                                                emb_dim=DIM, seed=0))
        srv.start()
        rs = np.random.RandomState(0)
        queries = [rs.randint(0, 32, (2, SLOTS)).astype(np.int64)
                   for _ in range(6)]
        out, errs = {}, []
        gate = threading.Barrier(len(queries))

        def one(i):
            try:
                gate.wait()
                cli = InferenceClient(srv.endpoint)
                out[i] = cli.infer("ctr", queries[i])
                cli.close()
            except Exception as e:  # pragma: no cover - reporting
                errs.append((i, e))

        ts = [threading.Thread(target=one, args=(i,))
              for i in range(len(queries))]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert not errs, errs
        solo = InProcClient()
        solo.create_table("emb", DIM, optimizer="sgd", lr=0.5, seed=3)
        for i, q in enumerate(queries):
            scores, ver = out[i]
            ref_scores, _ = _expected_scores(solo, None, q)
            np.testing.assert_allclose(scores, ref_scores, rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_array_equal(
                ver, np.zeros((q.shape[0], 1), np.int64))
        # coalescing + dedup: far fewer PS pulls than requests
        assert counting.pulls <= len(queries)
        doc = srv.health()
        assert doc["emb"]["tables"]["emb"]["version"] == 0
        assert doc["emb"]["hit_rate"] >= 0.0
    finally:
        srv.stop()


def test_rollover_under_concurrent_load_single_version_per_response(
        emb_flags):
    """A trainer publish lands while the fleet serves: zero dropped
    requests, every response resolves entirely at ONE version (old
    in-flight requests finish on the old generation), and the version
    column tells which — scores always match that version's table."""
    emb_flags(batch_max=4)
    ps = _mk_ps()
    srv = InferenceServer({})
    try:
        tier = srv.attach_embeddings(ps)
        srv.add_model("ctr", SparseCTRPredictor(tier, "emb", SLOTS,
                                                emb_dim=DIM, seed=0))
        srv.start()
        q = np.arange(4 * SLOTS, dtype=np.int64).reshape(4, SLOTS)
        # warm every id at v0, then change the table AND publish: the
        # v0 cache keeps serving old values until the flip
        tier.lookup("emb", q)
        exp0, _ = _expected_scores(ps, None, q)
        g = np.random.RandomState(1).randn(
            q.size, DIM).astype(np.float32)
        ps.push_grad("emb", q.reshape(-1), g)
        fresh = InProcClient()
        fresh.create_table("emb", DIM, optimizer="sgd", lr=0.5, seed=3)
        fresh.push_grad("emb", q.reshape(-1), g)
        exp1, _ = _expected_scores(fresh, None, q)
        expected = {0: exp0, 1: exp1}

        stop, errs = threading.Event(), []
        seen = {0: 0, 1: 0}
        lock = threading.Lock()

        def hammer():
            cli = InferenceClient(srv.endpoint)
            try:
                while not stop.is_set():
                    scores, ver = cli.infer("ctr", q)
                    v = int(ver[0, 0])
                    assert (ver == v).all(), "mixed versions in response"
                    np.testing.assert_allclose(
                        scores, expected[v], rtol=1e-5, atol=1e-6)
                    with lock:
                        seen[v] += 1
            except Exception as e:  # pragma: no cover - reporting
                errs.append(e)
            finally:
                cli.close()

        ts = [threading.Thread(target=hammer) for _ in range(4)]
        [t.start() for t in ts]
        time.sleep(0.15)
        ps.publish_version("emb")                 # the trainer's push
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            doc = srv.health()                    # health tick = rollover
            if doc.get("emb", {}) \
                    .get("tables", {}).get("emb", {}) \
                    .get("version") == 1:
                break
            time.sleep(0.1)
        time.sleep(0.2)                           # serve a while on v1
        stop.set()
        [t.join() for t in ts]
        assert not errs, errs
        assert seen[0] > 0 and seen[1] > 0        # both sides observed
        st = srv.health()["emb"]
        assert st["rollovers"] == 1 and st["stale_serves"] == 0
    finally:
        srv.stop()


def test_fleet_emb_rollup_and_version_spread():
    hub = MetricsHub()
    emb_a = {"hits": 6, "misses": 2, "pulled_rows": 2, "pulled_bytes": 64,
             "stale_serves": 0, "rollovers": 1, "evictions": 0,
             "hit_rate": 0.75,
             "tables": {"emb": {"version": 1}}}
    emb_b = {"hits": 2, "misses": 2, "pulled_rows": 2, "pulled_bytes": 64,
             "stale_serves": 1, "rollovers": 0, "evictions": 0,
             "hit_rate": 0.5,
             "tables": {"emb": {"version": 0}}}
    base = {"status": "ok", "inflight": 0, "generators": {}, "stats": {}}
    hub.ingest({"a:1": dict(base, emb=emb_a),
                "b:1": dict(base, emb=emb_b),
                "c:1": dict(base)})               # no tier on c
    f = hub.fleet_emb()
    assert f["replicas"] == 2
    assert f["hit_rate"] == pytest.approx(8 / 12)
    assert f["pulled_rows"] == 4 and f["stale_serves"] == 1
    assert f["rollovers"] == 1
    # version spread > 1: a rollover is still propagating
    assert f["versions"] == {"emb": [0, 1]}
    assert MetricsHub().fleet_emb() is None       # flag off fleet-wide


# ---------------------------------------------------------------------------
# live tenant-quota reconfig (PR-18 residue satellite)
# ---------------------------------------------------------------------------

class _FakeEngine:
    def __init__(self, sched):
        self.sched = sched


def test_scheduler_set_quotas_live(monkeypatch):
    import paddle_tpu.serving.scheduler as sched_mod
    real = sched_mod.flag
    monkeypatch.setattr(
        sched_mod, "flag",
        lambda n: "a=1" if n == "gen_sched_quotas" else real(n))
    sched = GenScheduler()
    assert sched._quotas == {"a": 1.0}
    assert sched.set_quotas("a=2,b=1") == {"a": 2.0, "b": 1.0}
    assert sched._quotas == {"a": 2.0, "b": 1.0}
    # dict form; junk shares and blank names are skipped, never fatal
    assert sched.set_quotas({"x": "3", "y": "nope", "": 2, "z": -1}) \
        == {"x": 3.0}
    assert sched.set_quotas(None) == {}           # clear -> unweighted


def test_sched_quotas_wire_op(emb_flags):
    srv = InferenceServer({})
    sched = GenScheduler()
    with srv._lock:
        srv._generators["g"] = _FakeEngine(sched)
    try:
        srv.start()
        cli = InferenceClient(srv.endpoint)
        assert cli.sched_quotas({"t1": 3, "t2": 1}) == ["g"]
        assert sched._quotas == {"t1": 3.0, "t2": 1.0}
        cli.close()
    finally:
        with srv._lock:
            srv._generators.clear()
        srv.stop()
    # a scheduler-less replica answers [] rather than erroring
    bare = InferenceServer({})
    try:
        bare.start()
        cli = InferenceClient(bare.endpoint)
        assert cli.sched_quotas({"t1": 1}) == []
        cli.close()
    finally:
        bare.stop()


def test_controller_quota_push_is_decision_logged():
    srv = InferenceServer({})
    sched = GenScheduler()
    with srv._lock:
        srv._generators["g"] = _FakeEngine(sched)
    ctl = None
    try:
        srv.start()
        rc = RoutedClient([srv.endpoint], probe_interval_s=0)
        ctl = ServingController(InProcSpawner(lambda: InferenceServer({})),
                                router=rc, interval_s=0)
        applied = ctl.set_quotas({"gold": 4, "free": 1})
        assert applied == {srv.endpoint: ["g"]}
        assert sched._quotas == {"gold": 4.0, "free": 1.0}
        d = [d for d in ctl.decisions() if d["action"] == "set_quotas"][-1]
        assert d["clean"] is True
        assert d["signals"]["quotas"] == {"gold": 4.0, "free": 1.0}
        assert d["signals"]["updated"] == {srv.endpoint: ["g"]}
    finally:
        if ctl is not None:
            ctl.close(stop_replicas=False)
        with srv._lock:
            srv._generators.clear()
        srv.stop()


# ---------------------------------------------------------------------------
# hard-off defaults
# ---------------------------------------------------------------------------

def test_defaults_off_no_tier_no_hot_path_flag_reads(monkeypatch):
    """serving_emb defaults off: attach_embeddings is a None no-op, no
    tier is constructed, health ships no "emb" block, and serving reads
    no serving_emb flags past construction."""
    assert flag("serving_emb") is False
    import paddle_tpu.io.serving as io_mod
    import paddle_tpu.serving.sparse as sparse_mod

    reads: list[str] = []
    real_flag = io_mod.flag

    def spy(name):
        reads.append(name)
        return real_flag(name)

    monkeypatch.setattr(io_mod, "flag", spy)
    monkeypatch.setattr(sparse_mod, "flag", spy)

    srv = InferenceServer({})
    try:
        assert "serving_emb" in reads
        reads.clear()
        assert srv.attach_embeddings(_mk_ps()) is None
        assert srv._emb_tier is None
        srv.start()
        doc = srv.health()
        assert "emb" not in doc
        assert not [r for r in reads if r.startswith("serving_emb")]
    finally:
        srv.stop()
