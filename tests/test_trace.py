"""Observability subsystem: span recorder (nesting, ring cap, disabled
no-op), cross-wire trace-id propagation, latency histograms + Prometheus
export, Chrome-trace JSON validity, structured JSON logging, health
stats-prefix filtering, and the StepTimer lock fix. All CPU-only and
tier-1 fast."""

import json
import logging
import threading

import pytest

from paddle_tpu.core import monitor, trace
from paddle_tpu.core.flags import get_flags, set_flags
from paddle_tpu.core.wire import FrameClient, FrameService, send_frame

pytestmark = pytest.mark.obs

_FLAGS = ["trace", "trace_buffer", "log_json"]


@pytest.fixture(autouse=True)
def _restore_obs_flags():
    """Tracing/logging must be back at production defaults (off) after
    each test — a leaked tracer would record every other suite."""
    saved = get_flags(_FLAGS)
    yield
    set_flags(saved)
    trace.clear()


def _tracing_on(capacity=4096):
    set_flags({"trace_buffer": capacity, "trace": True})


class _Echo(FrameService):
    op_names = {1: "echo"}

    def _dispatch(self, sock, op, header, payload):
        send_frame(sock, 0, {"echo": header.get("x")})
        return True


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------

def test_disabled_mode_is_noop():
    """Production default: no tracer, span() returns one shared no-op
    object (no per-call allocation), nothing is recorded."""
    assert not trace.enabled()
    s = trace.span("x", k=1)
    assert s is trace.span("y"), "disabled span must be a shared singleton"
    with s:
        assert trace.current() is None
    assert trace.get_spans() == []
    assert trace.snapshot() == {"enabled": False, "spans": []}


def test_span_nesting_and_linkage():
    _tracing_on()
    with trace.span("outer", phase="a") as outer:
        assert trace.current() == (outer.trace_id, outer.span_id)
        with trace.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    assert trace.current() is None, "stack must unwind"
    names = [s["name"] for s in trace.get_spans()]
    assert names == ["inner", "outer"], "children record before parents"
    outer_rec = trace.get_spans()[1]
    assert outer_rec["attrs"] == {"phase": "a"}
    assert outer_rec["parent_id"] is None
    assert outer_rec["dur"] >= 0


def test_sibling_traces_get_distinct_ids():
    _tracing_on()
    with trace.span("a"):
        pass
    with trace.span("b"):
        pass
    a, b = trace.get_spans()
    assert a["trace_id"] != b["trace_id"]


def test_ring_buffer_caps_memory():
    _tracing_on(capacity=8)
    for n in range(30):
        with trace.span(f"s{n}"):
            pass
    spans = trace.get_spans()
    assert len(spans) == 8, "ring must evict oldest"
    assert [s["name"] for s in spans] == [f"s{n}" for n in range(22, 30)]


def test_live_resize_keeps_newest_spans():
    """Regression: resizing the buffer on a LIVE tracer used to swap in
    an empty ring, silently dropping every buffered span. A shrink must
    keep the newest spans that still fit; a grow must keep everything."""
    _tracing_on(capacity=16)
    for n in range(10):
        with trace.span(f"s{n}"):
            pass
    set_flags({"trace_buffer": 4})           # live shrink
    spans = trace.get_spans()
    assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"], \
        "shrink keeps the newest tail, not an empty ring"
    set_flags({"trace_buffer": 64})          # live grow
    assert [s["name"] for s in trace.get_spans()] == \
        ["s6", "s7", "s8", "s9"], "grow keeps every surviving span"
    with trace.span("after"):
        pass
    assert trace.get_spans()[-1]["name"] == "after"
    assert trace.snapshot()["capacity"] == 64


def test_span_records_exception_type():
    _tracing_on()
    with pytest.raises(ValueError):
        with trace.span("boom"):
            raise ValueError("x")
    assert trace.get_spans()[-1]["attrs"]["error"] == "ValueError"


def test_record_event_emits_span():
    from paddle_tpu.core import profiler

    _tracing_on()
    with profiler.RecordEvent("annotated"):
        pass
    assert any(s["name"] == "annotated" for s in trace.get_spans())


# ---------------------------------------------------------------------------
# cross-wire propagation (acceptance)
# ---------------------------------------------------------------------------

def test_wire_round_trip_joins_one_trace():
    """Acceptance: a traced round-trip produces a client span and a
    server span sharing one trace id, the server's parent being the
    client span; both latency histograms fill; trace_dump scrapes it."""
    _tracing_on()
    monitor.reset_stats("wire/")
    srv = _Echo().start()
    c = FrameClient(srv.endpoint, {"echo": 1}, service="test", timeout=5.0)
    assert c._request("echo", {"x": 7})[0]["echo"] == 7

    spans = trace.get_spans()
    client = [s for s in spans if s["name"] == "wire/test.echo"]
    server = [s for s in spans if s["name"] == "wire/_Echo.echo"]
    assert len(client) == 1 and len(server) == 1
    assert client[0]["trace_id"] == server[0]["trace_id"]
    assert server[0]["parent_id"] == client[0]["span_id"]
    assert client[0]["tid"] != server[0]["tid"]

    hists = monitor.export_histograms("wire/")
    assert hists["wire/op_latency_s/test.echo"]["count"] == 1
    assert hists["wire/server_latency_s/_Echo.echo"]["count"] == 1

    # remote scrape returns the same spans (server shares the process
    # tracer here; the op itself is what obs_dump uses cross-process)
    dump = c.trace_dump()
    assert dump["enabled"] and dump["service"] == "_Echo"
    assert {s["span_id"] for s in dump["spans"]} >= {
        client[0]["span_id"], server[0]["span_id"]}
    c.close()
    srv.stop()


def test_untraced_client_headers_are_clean():
    """With FLAGS_trace off no trace keys ride the wire."""
    captured = {}

    class _Capture(FrameService):
        def _dispatch(self, sock, op, header, payload):
            captured.update(header)
            send_frame(sock, 0, {})
            return True

    srv = _Capture().start()
    c = FrameClient(srv.endpoint, {"go": 1}, timeout=5.0)
    c._request("go", {"x": 1})
    assert "tr" not in captured and "sp" not in captured
    c.close()
    srv.stop()


def test_trace_dump_clear_drains_server_buffer():
    _tracing_on()
    srv = _Echo().start()
    c = FrameClient(srv.endpoint, {"echo": 1}, service="t", timeout=5.0)
    c._request("echo", {})
    assert c.trace_dump(clear=True)["spans"]
    # buffer now holds only spans recorded after the drain (the dump
    # request itself lands post-snapshot)
    remaining = {s["name"] for s in c.trace_dump()["spans"]}
    assert "wire/t.echo" not in remaining
    c.close()
    srv.stop()


# ---------------------------------------------------------------------------
# histograms + exporters (acceptance)
# ---------------------------------------------------------------------------

def test_histogram_quantiles():
    monitor.reset_stats("t/")
    for v in [0.001] * 50 + [0.010] * 45 + [0.100] * 5:
        monitor.observe("t/lat_s", v)
    h = monitor.get_histogram("t/lat_s")
    assert h["count"] == 100
    assert h["sum"] == pytest.approx(1.0)
    assert h["min"] == pytest.approx(0.001)
    assert h["max"] == pytest.approx(0.100)
    assert h["p50"] <= h["p95"] <= h["p99"] <= h["max"]
    assert 0.0005 <= h["p50"] <= 0.002
    assert 0.005 <= h["p95"] <= 0.02
    assert monitor.get_histogram("t/never") is None
    monitor.reset_stats("t/")
    assert monitor.get_histogram("t/lat_s") is None, "reset clears hists"


def test_export_prometheus_emits_wire_quantiles():
    """Acceptance: export_prometheus() carries histogram quantiles for
    wire/* op latency after a traced round-trip."""
    _tracing_on()
    monitor.reset_stats("wire/")
    srv = _Echo().start()
    with FrameClient(srv.endpoint, {"echo": 1}, service="svc",
                     timeout=5.0) as c:
        c._request("echo", {})
    srv.stop()
    text = monitor.export_prometheus("wire/")
    assert 'wire_op_latency_s_svc_echo{quantile="0.5"}' in text
    assert 'wire_op_latency_s_svc_echo{quantile="0.99"}' in text
    assert "wire_op_latency_s_svc_echo_count 1" in text
    assert "# TYPE wire_op_latency_s_svc_echo summary" in text


def test_export_chrome_is_valid_json(tmp_path):
    """Acceptance: export_chrome output is valid JSON with well-formed
    Chrome trace events."""
    _tracing_on()
    with trace.span("parent", step=1):
        with trace.span("child"):
            pass
    path = str(tmp_path / "trace.json")
    trace.export_chrome(path)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert len(events) == 2
    for e in events:
        assert e["ph"] == "X"
        assert set(e) >= {"name", "ts", "dur", "pid", "tid", "args"}
        assert e["args"]["trace_id"]
    child = next(e for e in events if e["name"] == "child")
    parent = next(e for e in events if e["name"] == "parent")
    assert child["args"]["parent_id"] == parent["args"]["span_id"]
    assert parent["args"]["step"] == 1


def _load_obs_dump():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "obs_dump", os.path.join(os.path.dirname(__file__), "..", "tools",
                                 "obs_dump.py"))
    obs_dump = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_dump)
    return obs_dump


def test_obs_dump_merges_endpoints(tmp_path):
    """tools/obs_dump.py probes two live services and writes one merged
    Chrome trace with per-endpoint pids."""
    obs_dump = _load_obs_dump()
    _tracing_on()
    a, b = _Echo().start(), _Echo().start()
    with FrameClient(a.endpoint, {"echo": 1}, timeout=5.0) as c:
        c._request("echo", {})
    out = str(tmp_path / "fleet.json")
    rc = obs_dump.main([a.endpoint, b.endpoint, "-o", out,
                        "--stats-prefix", "wire/"])
    assert rc == 0
    with open(out) as f:
        doc = json.load(f)
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert pids >= {1, 2}, "each endpoint gets its own pid"
    names = {e["name"] for e in doc["traceEvents"]}
    assert "process_name" in names
    a.stop()
    b.stop()


# ---------------------------------------------------------------------------
# satellites: health stats prefix, JSON logs, StepTimer lock
# ---------------------------------------------------------------------------

def test_health_stats_prefix_filters_payload():
    monitor.reset_stats()
    monitor.stat_add("wire/x", 1)
    monitor.stat_add("ckpt/y", 2)
    srv = _Echo().start()
    with FrameClient(srv.endpoint, {}, timeout=5.0) as probe:
        full = probe.health()
        wire_only = probe.health(stats_prefix="wire/")
        none = probe.health(stats_prefix="no-such-prefix/")
    assert "ckpt/y" in full["stats"]
    assert "wire/x" in wire_only["stats"]
    assert not any(not k.startswith("wire/") for k in wire_only["stats"])
    assert none["stats"] == {}
    # the filtered probe still carries the load fields
    assert wire_only["status"] == "ok" and "inflight" in wire_only
    srv.stop()


def test_log_json_mode_correlates_with_trace(capsys):
    from paddle_tpu.core import logging as plog

    _tracing_on()
    records = []

    class _Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = _Sink()
    logger = plog.get_logger()
    logger.addHandler(sink)
    try:
        set_flags({"log_json": True})
        with trace.span("op") as sp:
            plog.info("inside %s", "span")
        plog.warning("outside")
    finally:
        set_flags({"log_json": False})
        logger.removeHandler(sink)
    inside = json.loads(records[0])
    outside = json.loads(records[1])
    assert inside["msg"] == "inside span"
    assert inside["level"] == "INFO"
    assert inside["trace_id"] == sp.trace_id
    assert inside["span_id"] == sp.span_id
    assert isinstance(inside["ts"], float)
    assert outside["level"] == "WARNING" and "trace_id" not in outside


def test_step_timer_concurrent_ticks():
    """The PR-2 era StepTimer mutated its window list unlocked; hammer it
    from threads and assert the window stays consistent."""
    monitor.reset_stats("race/")
    t = monitor.StepTimer("race", window=8)
    errors = []

    def hammer():
        try:
            for _ in range(500):
                t.tick(tokens=4)
        except Exception as e:              # noqa: BLE001 - collected
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors
    assert monitor.get_stat("race/steps") == 2000
    assert len(t._ticks) == t.window + 1, "window must not over/undergrow"
    assert monitor.get_stat("race/steps_per_sec") > 0


# ---------------------------------------------------------------------------
# satellites: histogram exposition, stream_traces under speculation +
# ledger failover joins
# ---------------------------------------------------------------------------

def test_export_prometheus_histogram_exposition():
    """Golden format: alongside the summary family, each histogram
    exports a real le-labeled cumulative ``_bucket`` family (sibling
    ``_hist`` name — one metric name cannot carry two TYPEs) that
    Prometheus' histogram_quantile() can consume: le values strictly
    increasing, counts cumulative, ``+Inf`` == ``_count``."""
    import re

    monitor.reset_stats("t/")
    monitor.observe("t/lat_s", 0.5)
    monitor.observe("t/lat_s", 0.5)
    monitor.observe("t/lat_s", 2.0)
    text = monitor.export_prometheus("t/")
    assert "# TYPE t_lat_s summary" in text
    assert "# TYPE t_lat_s_hist histogram" in text
    rows = re.findall(r't_lat_s_hist_bucket\{le="([^"]+)"\} (\d+)',
                      text)
    assert rows and rows[-1][0] == "+Inf"
    les = [float(le) for le, _ in rows[:-1]]
    counts = [int(c) for _, c in rows]
    assert les == sorted(les) and len(set(les)) == len(les)
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert counts[-1] == 3 and "t_lat_s_hist_count 3" in text
    m = re.search(r"t_lat_s_hist_sum ([0-9.e+-]+)", text)
    assert m and float(m.group(1)) == pytest.approx(3.0)
    # the two 0.5s are cumulative at the first bound >= 0.5; 2.0 only
    # joins at the first bound >= 2.0
    at = {float(le): int(c) for le, c in rows[:-1]}
    lo = min(b for b in les if b >= 0.5)
    hi = min(b for b in les if b >= 2.0)
    assert at[lo] == 2 and at[hi] == 3
    monitor.reset_stats("t/")


@pytest.fixture(scope="module")
def _gen_model():
    import paddle_tpu
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _drain_gen(eng, gid):
    toks, n = [], 0
    while True:
        doc = eng.poll(gid, start=n, wait_s=0.5)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


def test_stream_traces_spec_accept_under_stream_id(_gen_model):
    """A speculating engine's per-generation ``gen/spec_accept`` spans
    (emitted when drafts are accepted and per-token sampling is on)
    group under the SAME stream trace id as the lifecycle spans, so
    stream_traces() shows speculation inside the request timeline."""
    import numpy as np

    from paddle_tpu.serving import GenerationEngine

    obs_dump = _load_obs_dump()
    saved = get_flags(["trace_sample"])
    _tracing_on(8192)
    set_flags({"trace_sample": 1})       # spec/sample spans are per-token
    try:
        rs = np.random.RandomState(1)
        prompts = [rs.randint(1, 96, size=rs.randint(4, 10))
                   .astype(np.int32) for _ in range(6)]
        with GenerationEngine(_gen_model, slots=3, max_len=40,
                              queue_max=8, spec_k=4, spec_mode="ngram",
                              spec_shed_occupancy=1.0) as eng:
            gids = [eng.start(p, 12, trace_id=f"t-spec-{i}")
                    for i, p in enumerate(prompts)]
            for g in gids:
                _, err = _drain_gen(eng, g)
                assert err is None
            assert eng.stats()["spec"]["accepted"] > 0
    finally:
        set_flags(saved)
    scrape = {"endpoint": "a", "service": "gen",
              "spans": trace.get_spans()}
    streams = obs_dump.stream_traces([scrape])
    accepted = [tid for tid, d in streams.items()
                if "gen/spec_accept" in d["names"]]
    assert accepted and all(tid.startswith("t-spec-") for tid in accepted)
    for tid in accepted:
        assert streams[tid]["retired"] == "complete"
        assert "gen/admitted" in streams[tid]["names"]


def test_stream_traces_ledger_spans_join_failover_resume(_gen_model):
    """The ``gen/ledger`` finalize events ride the stream's trace id, so
    a failed-over stream — cancelled on replica A, replayed with
    ``rng_skip`` on replica B — shows BOTH replicas' ledger finalizes in
    ONE stream_traces() entry, scraped at different times."""
    import numpy as np

    from paddle_tpu.serving import GenerationEngine

    obs_dump = _load_obs_dump()
    _tracing_on(8192)
    rs = np.random.RandomState(5)
    prompt = rs.randint(1, 96, size=(6,)).astype(np.int32)
    tid = "t-failover"
    # replica A: the stream dies mid-flight (cancel stands in for the
    # replica loss); its spans are scraped from its buffer
    with GenerationEngine(_gen_model, slots=2, max_len=32, queue_max=4,
                          step_wait_s=0.05, ledger=True) as a:
        gid = a.start(prompt, 12, trace_id=tid, tenant="acme")
        while len(a.poll(gid, wait_s=1.0)["tokens"]) < 2:
            pass
        a.cancel(gid)
        deadline_recs = None
        import time as _time
        t_end = _time.monotonic() + 5.0
        while _time.monotonic() < t_end:
            deadline_recs = a.ledger_dump()["records"]
            if deadline_recs:
                break
            _time.sleep(0.02)
        assert deadline_recs and deadline_recs[-1]["outcome"] == "cancelled"
    scrape_a = {"endpoint": "a", "service": "gen",
                "spans": trace.get_spans()}
    trace.clear()
    # replica B: the router's replay — same trace id, rng_skip past the
    # tokens already delivered
    with GenerationEngine(_gen_model, slots=2, max_len=32,
                          queue_max=4, ledger=True) as b:
        gid2 = b.start(prompt, 12, trace_id=tid, rng_skip=2,
                       tenant="acme")
        _, err = _drain_gen(b, gid2)
        assert err is None
        rec = b.ledger_dump()["records"][-1]
    assert rec["outcome"] == "complete" and rec["tenant"] == "acme"
    assert rec["resume"] == {"rng_skip": 2}
    scrape_b = {"endpoint": "b", "service": "gen",
                "spans": trace.get_spans()}
    streams = obs_dump.stream_traces([scrape_a, scrape_b])
    d = streams[tid]
    assert d["endpoints"] == ["a", "b"]
    assert "gen/ledger" in d["names"]
    assert d["retired"] == "complete"    # B's completion wins the join
