"""Server-side overload protection: admission control (shed with the
retryable status code 2), the universal health op, graceful drain, the
barrier-timeout flag, idle-connection reaping, and the stop() race fix.
All CPU-only and tier-1 fast."""

import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from paddle_tpu import io
from paddle_tpu.core import monitor
from paddle_tpu.core.flags import get_flags, set_flags
from paddle_tpu.core.wire import (CODE_SHED, FrameClient, FrameService,
                                  send_frame)
from paddle_tpu.distributed.ps import ParameterServer, PSClient
from paddle_tpu.distributed.ps.heter import HeterWorker

pytestmark = pytest.mark.overload

_FLAGS = ["wire_max_inflight", "wire_max_conns", "wire_server_idle_s",
          "wire_drain_s", "ps_barrier_timeout_s", "wire_backoff_max_s"]


@pytest.fixture(autouse=True)
def _restore_overload_flags():
    """Every cap must be back at its production default (off/unlimited)
    after each test — a leaked cap would shed unrelated suites."""
    saved = get_flags(_FLAGS)
    yield
    set_flags(saved)


class _SlowPredictor:
    """Stand-in Predictor: holds the in-flight slot for ``delay`` seconds
    (InferenceServer.add_model accepts any object with run/specs)."""

    input_specs = [{"shape": [None], "dtype": "float32"}]
    output_specs = [{"shape": [None], "dtype": "float32"}]

    def __init__(self, delay: float = 0.05):
        self.delay = delay

    def run(self, x):
        time.sleep(self.delay)
        return np.asarray(x)


class _Echo(FrameService):
    def _dispatch(self, sock, op, header, payload):
        send_frame(sock, 0, {"echo": header.get("x")})
        return True


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_shed_under_load_then_all_recover():
    """Acceptance scenario: cap=1, a simultaneous burst of 8 infers —
    some are shed with code 2, every client succeeds after retry, and
    both sides of the shed show up in monitor stats."""
    srv = io.InferenceServer()
    srv.add_model("slow", _SlowPredictor(0.05))
    srv.start()
    set_flags({"wire_max_inflight": 1, "wire_backoff_max_s": 0.2})
    monitor.reset_stats("wire/")
    x = np.ones((4,), np.float32)
    results, errors = [], []
    gate = threading.Barrier(8)

    def worker():
        c = io.InferenceClient(srv.endpoint, timeout=10.0, retries=32)
        try:
            gate.wait()
            (y,) = c.infer("slow", x)
            results.append(y)
        except Exception as e:              # noqa: BLE001 - collected
            errors.append(e)
        finally:
            c.close()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, f"shed requests must succeed on retry: {errors[:2]}"
    assert len(results) == 8
    assert all(np.allclose(y, x) for y in results)
    assert monitor.get_stat("wire/shed") >= 1, "cap=1 + burst must shed"
    assert monitor.get_stat("wire/shed_server") >= 1
    srv.stop()


class _ShedOnce(FrameService):
    """Replies code-2 to the first request, then serves normally — the
    deterministic unit of the client's shed-retry contract."""

    def __init__(self):
        self.seen = 0
        super().__init__()

    def _dispatch(self, sock, op, header, payload):
        self.seen += 1
        if self.seen == 1:
            send_frame(sock, CODE_SHED,
                       {"error": "overloaded", "retry_after_s": 0.01})
        else:
            send_frame(sock, 0, {"ok": True})
        return True


class _ShedAlways(FrameService):
    def _dispatch(self, sock, op, header, payload):
        send_frame(sock, CODE_SHED,
                   {"error": "overloaded", "retry_after_s": 0.0})
        return True


def test_shed_retried_even_for_non_idempotent_ops():
    """A shed request never executed, so it must be retried even for an
    op outside the idempotent set — without burning conn-retry stats."""
    srv = _ShedOnce().start()
    monitor.reset_stats("wire/")
    c = FrameClient(srv.endpoint, {"push": 1}, service="test", timeout=5.0,
                    retries=2)                  # "push" NOT idempotent
    h, _ = c._request("push", {})
    assert h["ok"] is True
    assert monitor.get_stat("wire/shed") == 1
    assert monitor.get_stat("wire/retries") == 0, "shed != conn retry"
    c.close()
    srv.stop()


def test_shed_budget_exhaustion_surfaces_error():
    srv = _ShedAlways().start()
    c = FrameClient(srv.endpoint, {"push": 1}, service="test", timeout=5.0,
                    retries=1)
    with pytest.raises(RuntimeError, match="shed .* after 2 attempt"):
        c._request("push", {})
    c.close()
    srv.stop()


def test_connection_cap_sheds_excess_connection():
    srv = _Echo().start()
    c1 = FrameClient(srv.endpoint, {"e": 1}, timeout=5.0)
    assert c1._request("e", {"x": 1})[0]["echo"] == 1   # conn 1 admitted
    set_flags({"wire_max_conns": 1})
    monitor.reset_stats("wire/")
    c2 = FrameClient(srv.endpoint, {"e": 1}, timeout=5.0, retries=0)
    with pytest.raises(RuntimeError, match="shed"):
        c2._request("e", {"x": 2})
    assert monitor.get_stat("wire/shed_conns") >= 1
    # the incumbent connection is unaffected
    assert c1._request("e", {"x": 3})[0]["echo"] == 3
    c1.close()
    c2.close()
    srv.stop()


# ---------------------------------------------------------------------------
# universal health op
# ---------------------------------------------------------------------------

def _build_step():
    def step_fn(feats, labels):
        return 0.0, feats

    def eval_fn(feats, labels):
        return 0.0

    return step_fn, eval_fn


def test_health_served_by_every_service(tmp_path):
    services = {
        "InferenceServer": io.InferenceServer(),
        "ParameterServer": ParameterServer(),
        "HeterWorker": HeterWorker(_build_step),
        "FSService": io.FSService(str(tmp_path / "root")),
    }
    for name, srv in services.items():
        srv.start()
        # ops table is empty: health is universal, outside every op table
        with FrameClient(srv.endpoint, {}, service="probe",
                         timeout=5.0) as probe:
            h = probe.health()
        assert h["status"] == "ok"
        assert h["service"] == name
        assert h["inflight"] == 0 and h["conns"] >= 1
        assert h["uptime_s"] >= 0.0
        assert isinstance(h["stats"], dict)
        srv.stop()


def test_health_via_service_clients(tmp_path):
    ps = ParameterServer().start()
    pc = PSClient(ps.endpoint, timeout=5.0)
    assert pc.health()["service"] == "ParameterServer"
    pc.close()
    ps.stop()

    fssrv = io.FSService(str(tmp_path / "r")).start()
    wfs = io.WireFS(fssrv.endpoint, timeout=5.0)
    assert wfs.health()["status"] == "ok"
    wfs.close()
    fssrv.stop()


def test_health_never_shed_under_full_load():
    """The probe must answer while the admission cap is saturated."""
    srv = io.InferenceServer()
    srv.add_model("slow", _SlowPredictor(0.5))
    srv.start()
    set_flags({"wire_max_inflight": 1})
    c = io.InferenceClient(srv.endpoint, timeout=10.0, retries=0)
    t = threading.Thread(
        target=lambda: c.infer("slow", np.ones((2,), np.float32)))
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and srv.health()["inflight"] < 1:
            time.sleep(0.01)
        assert srv.health()["inflight"] == 1
        with FrameClient(srv.endpoint, {}, timeout=5.0) as probe:
            h = probe.health()          # not shed despite the full cap
        assert h["inflight"] == 1 and h["max_inflight"] == 1
    finally:
        t.join(timeout=10)
        c.close()
        srv.stop()


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------

def test_drain_completes_inflight_before_sever():
    """Acceptance scenario: drain() lets the in-flight infer finish (and
    deliver its response) before the socket is severed."""
    srv = io.InferenceServer()
    srv.add_model("slow", _SlowPredictor(0.4))
    srv.start()
    c = io.InferenceClient(srv.endpoint, timeout=10.0, retries=0)
    x = np.arange(3, dtype=np.float32)
    out = {}

    def worker():
        out["y"] = c.infer("slow", x)[0]

    t = threading.Thread(target=worker)
    t.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and srv.health()["inflight"] < 1:
        time.sleep(0.01)
    assert srv.health()["inflight"] == 1, "infer must be in flight"

    clean = srv.drain(5.0)
    t.join(timeout=10)
    assert clean is True
    assert "y" in out and np.allclose(out["y"], x)
    # drained service is gone: new connections are refused
    with pytest.raises(OSError):
        io.InferenceClient(srv.endpoint, timeout=1.0, retries=0)
    c.close()


def test_health_reports_draining_and_new_requests_shed():
    srv = io.InferenceServer()
    srv.add_model("slow", _SlowPredictor(0.6))
    srv.start()
    c = io.InferenceClient(srv.endpoint, timeout=10.0, retries=0)
    probe = FrameClient(srv.endpoint, {"infer": 1}, service="probe",
                        timeout=5.0, retries=0)
    # warm the probe connection: a conn still in the accept backlog when
    # drain closes the listener is reset (= shed, nothing executed); a
    # served one survives until the final sever — the persistent-probe
    # pattern a load balancer uses
    assert probe.health()["status"] == "ok"
    t = threading.Thread(
        target=lambda: c.infer("slow", np.ones((2,), np.float32)))
    t.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and srv.health()["inflight"] < 1:
        time.sleep(0.01)
    d = threading.Thread(target=srv.drain, args=(5.0,))
    d.start()
    saw_draining = saw_shed = False
    while d.is_alive():
        try:
            h = probe.health()
            saw_draining |= h["status"] == "draining"
            if not saw_shed:
                try:
                    probe._request(
                        "infer", {"model": "slow", "inputs": [], "nbytes": 0})
                except RuntimeError as e:
                    saw_shed = "shed" in str(e)
        except (ConnectionError, OSError):
            break                      # drain finished and severed us
        time.sleep(0.02)
    d.join(timeout=10)
    t.join(timeout=10)
    assert saw_draining, "health must report draining during the drain"
    assert saw_shed, "new requests during drain must be shed (code 2)"
    probe.close()
    c.close()


def test_preemption_handler_drains_hosted_services():
    """SIGTERM on a serving process: the handler drains the service — the
    in-flight request completes, then the listener goes away."""
    srv = io.InferenceServer()
    srv.add_model("slow", _SlowPredictor(0.3))
    srv.start()
    host, port = srv.host, srv.port
    c = io.InferenceClient(srv.endpoint, timeout=10.0, retries=0)
    out = {}

    def worker():
        out["y"] = c.infer("slow", np.ones((2,), np.float32))[0]

    with io.PreemptionHandler(services=[srv], drain_s=5.0) as h:
        t = threading.Thread(target=worker)
        t.start()
        deadline = time.monotonic() + 5.0
        while (time.monotonic() < deadline
               and srv.health()["inflight"] < 1):
            time.sleep(0.01)
        os.kill(os.getpid(), signal.SIGTERM)
        t.join(timeout=10)
    assert h.installed and h.preempted
    assert "y" in out, "in-flight request survived the SIGTERM drain"
    deadline = time.monotonic() + 5.0
    gone = False
    while time.monotonic() < deadline and not gone:
        try:
            socket.create_connection((host, port), timeout=0.2).close()
            time.sleep(0.05)
        except OSError:
            gone = True
    assert gone, "drained service must stop listening"
    c.close()


# ---------------------------------------------------------------------------
# satellites: barrier flag, idle reap, stop() race
# ---------------------------------------------------------------------------

def test_ps_barrier_timeout_flag():
    set_flags({"ps_barrier_timeout_s": 0.2})
    monitor.reset_stats("ps/")
    ps = ParameterServer().start()
    c = PSClient(ps.endpoint, timeout=5.0)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="barrier timed out"):
        c.barrier(world=2)              # alone at a world-2 rendezvous
    assert time.monotonic() - t0 < 5.0, "flag bounded the wait"
    assert monitor.get_stat("ps/barrier_timeouts") == 1
    c.close()
    ps.stop()


def test_idle_connection_reaped():
    set_flags({"wire_server_idle_s": 0.3})
    monitor.reset_stats("wire/")
    srv = _Echo().start()
    s = socket.create_connection((srv.host, srv.port))
    s.settimeout(5.0)
    t0 = time.monotonic()
    assert s.recv(1) == b"", "silent connection must be closed by server"
    assert time.monotonic() - t0 < 4.0
    assert monitor.get_stat("wire/idle_closed") == 1
    s.close()
    srv.stop()


def test_late_connection_during_stop_is_closed_immediately():
    """The stop()/handler race: a connection that lands while stop() is
    severing must be closed by the handler, not serve forever."""
    srv = _Echo().start()
    with srv._conns_lock:
        srv._stopping = True            # simulate the severing window
    s = socket.create_connection((srv.host, srv.port))
    s.settimeout(5.0)
    assert s.recv(1) == b"", "late connection must be refused service"
    with srv._conns_lock:
        assert not srv._conns, "late connection must not be registered"
    s.close()
    srv.stop()
