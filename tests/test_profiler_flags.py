"""check_nan_inf / benchmark flag consumers + profiler timeline capture.

Reference behaviors: FLAGS_check_nan_inf per-op sweep
(``framework/details/nan_inf_utils_detail.cc:301``), FLAGS_benchmark
per-op sync (``framework/operator.cc:1123``), EnableProfiler/RecordEvent
(``platform/profiler.h:127,209``) + timeline export (``tools/timeline.py``).
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer as optim, profiler
from paddle_tpu.core import flags as flags_mod
from paddle_tpu.parallel import mesh as M


def _mlp_step(loss_fn=None):
    paddle_tpu.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    if loss_fn is None:
        def loss_fn(m, batch, training=True):
            return jnp.mean((m(batch["x"]) - batch["y"]) ** 2)
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(0.1), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
    batch = {"x": jnp.ones((4, 4)), "y": jnp.ones((4, 1))}
    return step, state, step.shard_batch(batch)


def test_check_nan_inf_raises_on_nonfinite():
    def bad_loss(m, batch, training=True):
        pred = m(batch["x"])
        # 0 * inf = nan enters the loss at step >= 1 via the updated params
        return jnp.mean((pred - batch["y"]) ** 2) + jnp.log(
            jnp.sum(pred) - jnp.sum(pred) - 1.0)  # log(-1) = nan

    paddle_tpu.set_flags({"check_nan_inf": True})
    try:
        step, state, batch = _mlp_step(bad_loss)
        with pytest.raises(FloatingPointError, match="check_nan_inf"):
            step(state, batch, jax.random.PRNGKey(0))
    finally:
        paddle_tpu.set_flags({"check_nan_inf": False})


def test_check_nan_inf_quiet_when_finite():
    paddle_tpu.set_flags({"check_nan_inf": True})
    try:
        step, state, batch = _mlp_step()
        state, metrics = step(state, batch, jax.random.PRNGKey(0))
        assert bool(metrics["check/grads_finite"])
        assert bool(metrics["check/params_finite"])
    finally:
        paddle_tpu.set_flags({"check_nan_inf": False})


def test_check_nan_inf_off_means_no_sweep():
    step, state, batch = _mlp_step()
    _, metrics = step(state, batch, jax.random.PRNGKey(0))
    assert not any(k.startswith("check/") for k in metrics)


def test_benchmark_flag_sync_path():
    paddle_tpu.set_flags({"benchmark": True})
    try:
        step, state, batch = _mlp_step()
        state, metrics = step(state, batch, jax.random.PRNGKey(0))
        assert np.isfinite(float(metrics["loss"]))
    finally:
        paddle_tpu.set_flags({"benchmark": False})


def test_profiler_captures_timeline(tmp_path):
    logdir = str(tmp_path / "prof")
    with profiler.profiler(logdir):
        f = jax.jit(lambda x: jnp.sin(x) @ x.T)
        jax.block_until_ready(f(jnp.ones((64, 64))))
    # a TensorBoard xplane artifact must exist (the timeline file)
    captured = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                         recursive=True)
    assert captured, f"no xplane capture under {logdir}"


def test_record_event_inside_and_outside_jit():
    with profiler.RecordEvent("host_span"):
        pass

    @profiler.record_function("fn_span")
    def g(x):
        with profiler.RecordEvent("inner"):
            return x * 2

    out = jax.jit(g)(jnp.ones(3))
    np.testing.assert_allclose(np.asarray(out), 2 * np.ones(3))
    # named_scope must land in the compiled HLO metadata
    hlo = jax.jit(g).lower(jnp.ones(3)).as_text(debug_info=True)
    assert "fn_span" in hlo and "inner" in hlo


def test_named_scopes_in_train_step_hlo():
    """Phase annotations must appear in the compiled train step."""
    paddle_tpu.seed(0)
    model = nn.Linear(4, 1)
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])

    def loss_fn(m, batch, training=True):
        return jnp.mean((m(batch["x"]) - batch["y"]) ** 2)

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(0.1), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = {"x": jnp.ones((2, 4)), "y": jnp.ones((2, 1))}
        lowered = jax.jit(step._step_fn).lower(
            state, batch, jax.random.PRNGKey(0)).as_text(debug_info=True)
    assert "forward_backward" in lowered
    assert "optimizer_update" in lowered


# ---------------------------------------------------------------------------
# stat registry / monitors (reference platform/monitor.h StatRegistry)
# ---------------------------------------------------------------------------

def test_stat_registry_counters():
    from paddle_tpu.core import monitor

    monitor.reset_stats("t/")
    monitor.stat_add("t/x", 3)
    monitor.stat_add("t/x", 2)
    monitor.stat_set("t/y", 7.5)
    assert monitor.get_stat("t/x") == 5
    exported = monitor.export_stats()
    assert exported["t/y"] == 7.5
    monitor.reset_stats("t/")
    assert monitor.get_stat("t/x") == 0


def test_train_step_increments_fleet_steps():
    from paddle_tpu.core import monitor

    monitor.reset_stats("fleet/")
    step, state, batch = _mlp_step()
    for i in range(3):
        state, _ = step(state, batch, jax.random.PRNGKey(i))
    assert monitor.get_stat("fleet/steps") == 3


def test_step_timer_and_host_monitors():
    import time as _time

    from paddle_tpu.core import monitor

    monitor.reset_stats("bench/")
    t = monitor.StepTimer("bench", window=4)
    for _ in range(5):
        t.tick(tokens=128)
        _time.sleep(0.01)
    assert monitor.get_stat("bench/steps") == 5
    assert monitor.get_stat("bench/steps_per_sec") > 0
    assert monitor.get_stat("bench/tokens_per_sec") > 0
    assert monitor.host_rss_bytes() > 10 * 1024 * 1024
    mem = monitor.device_memory_stats()
    assert isinstance(mem, dict)
