"""Page-table-aware Pallas decode kernel (ops/pallas/paged_decode_attention.py).

OpTest discipline, same contract as ``test_decode_attention.py`` but
with the page indirection inside the index maps: in interpret mode the
kernel must reproduce ``models.generation.paged_gather`` + masked
attention bit-for-bit per slot, honor the physical page permutation
(same logical sequence, different page placement → identical output),
bound reads to the filled prefix, fold int8 pool scales exactly, and
survive ``jax.vmap`` over slots. This is the hardware-independent
result; the TPU timing run is the stated caveat in the module doc.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models.generation import paged_gather
from paddle_tpu.ops.pallas import _support
from paddle_tpu.ops.pallas import paged_decode_attention as pdk


def _mk(B=2, Hq=4, Hkv=2, P=8, M=4, D=64, L=2, N=16, quant=False,
        dtype=jnp.float32, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(B, 1, Hq, D), dtype)
    kn = jnp.asarray(rs.randn(B, Hkv, 1, D), dtype)
    vn = jnp.asarray(rs.randn(B, Hkv, 1, D), dtype)
    if quant:
        pool = (
            jnp.asarray(rs.randint(-127, 128, (N + 1, L, Hkv, P, D)),
                        jnp.int8),
            jnp.asarray(rs.randint(-127, 128, (N + 1, L, Hkv, P, D)),
                        jnp.int8),
            jnp.asarray(rs.rand(N + 1, L, Hkv, P) * 0.05 + 0.001,
                        jnp.float32),
            jnp.asarray(rs.rand(N + 1, L, Hkv, P) * 0.05 + 0.001,
                        jnp.float32),
        )
    else:
        pool = (jnp.asarray(rs.randn(N + 1, L, Hkv, P, D), dtype),
                jnp.asarray(rs.randn(N + 1, L, Hkv, P, D), dtype))
    # distinct live pages per slot, never the null page 0
    ids = rs.permutation(np.arange(1, N + 1))[: B * M]
    table = jnp.asarray(ids.reshape(B, M).astype(np.int32))
    return q, kn, vn, pool, table


def _via_paged_gather(q, kn, vn, pool, table, layer, idx, scale):
    """Independent reference built on the REAL ``paged_gather`` (the
    copy the kernel deletes): per slot, materialize the view, one-layer
    masked attention in the fallback's dtype discipline."""
    B, _, Hq, D = q.shape
    Hkv = kn.shape[1]
    G = Hq // Hkv
    M = table.shape[1]
    P = pool[0].shape[3]
    idx = np.broadcast_to(np.asarray(idx), (B,))
    outs = []
    for b in range(B):
        view = paged_gather(pool, table[b])       # [L, 1, Hkv, M*P, ...]
        if len(pool) == 4:
            k_c = (view[0][layer, 0].astype(q.dtype)
                   * view[2][layer, 0][..., None])
            v_c = (view[1][layer, 0].astype(q.dtype)
                   * view[3][layer, 0][..., None])
        else:
            k_c, v_c = view[0][layer, 0], view[1][layer, 0]
        qh = q[b, 0].reshape(Hkv, G, D)
        s_c = jnp.einsum("hgd,hsd->hgs", qh, k_c) * scale
        mask = jnp.arange(M * P) < idx[b]
        s_c = jnp.where(mask[None, None, :], s_c, pdk.NEG_INF)
        s_n = jnp.sum(qh * kn[b], axis=-1, keepdims=True) * scale
        s_all = jnp.concatenate([s_c, s_n], -1).astype(jnp.float32)
        p = jax.nn.softmax(s_all, -1).astype(q.dtype)
        o = (jnp.einsum("hgs,hsd->hgd", p[..., :-1], v_c)
             + p[..., -1:] * vn[b])
        outs.append(o.reshape(Hq, D))
    return jnp.stack(outs).reshape(B, 1, Hq, D)


@pytest.mark.parametrize("quant", [False, True])
@pytest.mark.parametrize("idx", [1, 7, 17, 32])
def test_kernel_matches_paged_gather(quant, idx):
    q, kn, vn, pool, table = _mk(quant=quant)
    want = _via_paged_gather(q, kn, vn, pool, table, 1, idx, 0.125)
    with _support.force_dispatch():
        assert pdk.supported(q, pool, table)
        got = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                         jnp.int32(1), jnp.int32(idx),
                                         scale=0.125)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # the fallback arm is the same math
    ref = pdk.paged_reference(q, kn, vn, pool, table, 1, jnp.int32(idx),
                              scale=0.125)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("quant", [False, True])
def test_kernel_selects_layer(quant):
    """sp_ref[b, 0] must pick layer l's plane out of the pool stack."""
    q, kn, vn, pool, table = _mk(L=3, quant=quant, seed=7)
    for l in range(3):
        with _support.force_dispatch():
            got = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                             jnp.int32(l), jnp.int32(20),
                                             scale=0.125)
        want = _via_paged_gather(q, kn, vn, pool, table, l, 20, 0.125)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5, err_msg=f"l={l}")


def test_page_indirection_is_honored():
    """The same logical sequence under two different physical page
    placements must produce identical output — the proof that the index
    map reads the table rather than assuming contiguity."""
    q, kn, vn, pool, table = _mk(B=1, seed=3)
    perm = np.array([3, 1, 0, 2])                 # logical -> new slot order
    kp, vp = np.asarray(pool[0]).copy(), np.asarray(pool[1]).copy()
    old = np.asarray(table[0])
    new_ids = old[perm]                           # reuse the same pages...
    kp2, vp2 = kp.copy(), vp.copy()
    for lg in range(len(perm)):                   # ...but relocate content
        kp2[new_ids[lg]] = kp[old[lg]]
        vp2[new_ids[lg]] = vp[old[lg]]
    table2 = jnp.asarray(new_ids[None].astype(np.int32))
    with _support.force_dispatch():
        a = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                       jnp.int32(0), jnp.int32(25),
                                       scale=0.125)
        b = pdk.paged_decode_attention(
            q, kn, vn, (jnp.asarray(kp2), jnp.asarray(vp2)), table2,
            jnp.int32(0), jnp.int32(25), scale=0.125)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_kernel_ignores_stale_and_unmapped():
    """Positions >= index and the null page must not contribute:
    poisoning them with huge values changes nothing."""
    q, kn, vn, pool, table = _mk(seed=1)
    idx = 19                                       # mid page 3 of 4
    kp, vp = np.asarray(pool[0]).copy(), np.asarray(pool[1]).copy()
    P = kp.shape[3]
    for b in range(table.shape[0]):
        row = np.asarray(table[b])
        kp[row[idx // P], :, :, idx % P:] = 1e4    # stale tail of the page
        vp[row[idx // P], :, :, idx % P:] = -1e4
        kp[row[idx // P + 1:]] = 1e4               # wholly unfilled pages
        vp[row[idx // P + 1:]] = -1e4
    kp[0], vp[0] = 1e4, -1e4                       # the null page
    poisoned = (jnp.asarray(kp), jnp.asarray(vp))
    with _support.force_dispatch():
        a = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                       jnp.int32(0), jnp.int32(idx),
                                       scale=0.125)
        b = pdk.paged_decode_attention(q, kn, vn, poisoned, table,
                                       jnp.int32(0), jnp.int32(idx),
                                       scale=0.125)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_per_slot_index_vector():
    """index may be [B] — each slot masks at its own fill position."""
    q, kn, vn, pool, table = _mk(seed=4)
    idxv = jnp.asarray([3, 30], jnp.int32)
    with _support.force_dispatch():
        got = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                         jnp.int32(0), idxv, scale=0.125)
    want = _via_paged_gather(q, kn, vn, pool, table, 0,
                             np.asarray(idxv), 0.125)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gqa_group_mapping():
    """Hq=8, Hkv=2 (G=4): each q head reads ITS kv head's pages — the
    block-diagonal mask at page granularity."""
    q, kn, vn, pool, table = _mk(Hq=8, Hkv=2, seed=5)
    with _support.force_dispatch():
        got = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                         jnp.int32(1), jnp.int32(28),
                                         scale=0.125)
    want = _via_paged_gather(q, kn, vn, pool, table, 1, 28, 0.125)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "quant", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_kernel_under_vmap_matches_per_slot(quant):
    """The engine's fused decode vmaps over the slot axis, so the
    kernel must survive jax's pallas batching rule: vmapped calls equal
    the per-slot calls exactly (pool closed over, tables/indices
    mapped)."""
    SLOTS = 3
    _, _, _, pool, _ = _mk(B=1, quant=quant, seed=20)
    qs, kns, vns, tabs = [], [], [], []
    idxs = [2, 15, 31]
    for s in range(SLOTS):
        q, kn, vn, _, table = _mk(B=1, quant=quant, seed=30 + s)
        qs.append(q), kns.append(kn), vns.append(vn), tabs.append(table)
    qv, knv, vnv = jnp.stack(qs), jnp.stack(kns), jnp.stack(vns)
    tabv = jnp.stack(tabs)
    idxv = jnp.asarray(idxs, jnp.int32)

    def one(q, kn, vn, tab, i):
        assert pdk.supported(q, pool, tab)     # gate holds under tracer
        return pdk.paged_decode_attention(q, kn, vn, pool, tab,
                                          jnp.int32(1), i, scale=0.125)

    with _support.force_dispatch():
        got = jax.jit(jax.vmap(one, in_axes=(0, 0, 0, 0, 0)))(
            qv, knv, vnv, tabv, idxv)
        want = jnp.stack([
            pdk.paged_decode_attention(qs[s], kns[s], vns[s], pool,
                                       tabs[s], jnp.int32(1),
                                       jnp.int32(idxs[s]), scale=0.125)
            for s in range(SLOTS)])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for s in range(SLOTS):
        np.testing.assert_allclose(
            np.asarray(got[s]),
            np.asarray(_via_paged_gather(qs[s], kns[s], vns[s], pool,
                                         tabs[s], 1, idxs[s], 0.125)),
            rtol=2e-5, atol=2e-5, err_msg=f"slot {s}")


def test_supported_gates():
    q, _, _, pool, table = _mk()
    with _support.force_dispatch():
        assert pdk.supported(q, pool, table)
        # prefill chunk (T > 1) is not this kernel's job
        assert not pdk.supported(jnp.zeros((2, 4, 4, 64)), pool, table)
        # head_dim off the MXU grid
        assert not pdk.supported(
            jnp.zeros((2, 1, 4, 32)),
            (jnp.zeros((17, 2, 2, 8, 32)),) * 2, table)
        # page size not sublane-aligned
        assert not pdk.supported(
            jnp.zeros((2, 1, 4, 64)),
            (jnp.zeros((17, 2, 2, 6, 64)),) * 2, table)
        # table batch mismatch
        assert not pdk.supported(q, pool, table[:1])
        # int8 leaves without the 4-leaf scale layout
        assert not pdk.supported(
            q, (jnp.zeros((17, 2, 2, 8, 64), jnp.int8),) * 2, table)
    # no dispatch context off-TPU → fallback
    if not _support.on_tpu():
        assert not pdk.supported(q, pool, table)


def test_fallback_arm_dispatch(monkeypatch):
    """Off-TPU with no force_dispatch the public entry must take the
    einsum fallback (raw_call untouched); under force_dispatch it must
    route through the pallas_call."""
    q, kn, vn, pool, table = _mk(seed=6)
    calls = {}
    orig = pdk.raw_call

    def spy(*a, **kw):
        calls["n"] = calls.get("n", 0) + 1
        return orig(*a, **kw)

    monkeypatch.setattr(pdk, "raw_call", spy)
    out_f = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                       jnp.int32(0), jnp.int32(10),
                                       scale=0.125)
    if not _support.on_tpu():
        assert calls.get("n", 0) == 0          # fallback arm
    with _support.force_dispatch():
        out_k = pdk.paged_decode_attention(q, kn, vn, pool, table,
                                           jnp.int32(0), jnp.int32(10),
                                           scale=0.125)
    assert calls.get("n", 0) >= 1              # kernel arm engaged
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_f),
                               rtol=2e-5, atol=2e-5)
