"""End-to-end generation resilience: lossless stream resumption across
replica death, engine self-healing (trap → rebuild → re-admit), crash
quarantine, the spawn circuit breaker, and the typed poll-TTL expiry.

The load-bearing property is the resumption determinism contract: a
greedy stream whose replica dies mid-decode, resumed on a survivor by
replaying prompt + delivered tokens as a prefill-from-prefix, is
byte-identical to an uninterrupted run — replica loss becomes invisible
to the caller instead of a GenerationFailed.
"""

import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core import fault, trace
from paddle_tpu.core.flags import flag, get_flags, set_flags
from paddle_tpu.core.monitor import get_stat
from paddle_tpu.io.serving import InferenceClient, InferenceServer
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import advance_key, generate
from paddle_tpu.serving import (
    GenerationEngine, GenerationExpired, GenerationFailed, ReplicaSpawner,
    RequestQuarantined, RoutedClient, ServingController,
    StreamResumeExhausted,
)
from paddle_tpu.serving.engine import RESET_MARKER

pytestmark = pytest.mark.resilience

VOCAB = 96


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


# -- tentpole: lossless stream resumption -----------------------------------

def test_resume_after_replica_kill_greedy_identical(model):
    """Kill the replica holding a live greedy stream: with a resume
    budget the routed stream replays prompt + delivered tokens onto the
    survivor and completes byte-identical to an uninterrupted solo
    generate() — zero GenerationFailed surfaced to the caller."""
    servers, engines = [], []
    for _ in range(2):
        eng = GenerationEngine(model, slots=2, max_len=32,
                               step_wait_s=0.03)
        srv = InferenceServer().start()
        srv.add_generator("llm", eng)
        servers.append(srv)
        engines.append(eng)
    router = RoutedClient([s.endpoint for s in servers],
                          probe_interval_s=0)
    try:
        rs = np.random.RandomState(31)
        prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 10))[0, 5:]
        resumes0 = get_stat("serving/router/stream_resumes")

        sess = router.session("victim-stream")
        it = sess.generate("llm", prompt, 10, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it), next(it)]            # stream is live
        pinned = sess.endpoint
        victim = next(s for s in servers if s.endpoint == pinned)
        victim.stop()                          # SIGKILL-equivalent sever
        toks += list(it)                       # resumes on the survivor

        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        assert get_stat("serving/router/stream_resumes") == resumes0 + 1
        survivor = next(e for s, e in zip(servers, engines)
                        if s.endpoint != pinned)
        assert _wait(lambda: survivor.stats()["active"] == 0)
    finally:
        router.close()
        for s in servers:
            s.stop()


@pytest.mark.obs
def test_failover_stream_is_one_trace_across_replicas(model):
    """A traced stream that fails over keeps ONE trace id: the victim's
    admission and the survivor's completion land under the same stream
    trace id (what obs_dump merges into a single cross-replica
    timeline), joined by the router's gen/stream_resume marker."""
    saved = get_flags(["trace", "trace_buffer"])
    set_flags({"trace_buffer": 4096, "trace": True})
    trace.clear()
    servers, engines = [], []
    try:
        for _ in range(2):
            eng = GenerationEngine(model, slots=2, max_len=32,
                                   step_wait_s=0.03)
            srv = InferenceServer().start()
            srv.add_generator("llm", eng)
            servers.append(srv)
            engines.append(eng)
        router = RoutedClient([s.endpoint for s in servers],
                              probe_interval_s=0)
        try:
            rs = np.random.RandomState(43)
            prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
            ref = np.asarray(generate(model, prompt[None], 10))[0, 5:]
            sess = router.session("traced-victim")
            it = sess.generate("llm", prompt, 10, poll_wait_s=0.05,
                               resume_budget=2)
            toks = [next(it), next(it)]
            pinned = sess.endpoint
            victim = next(s for s in servers if s.endpoint == pinned)
            victim.stop()
            toks += list(it)
            np.testing.assert_array_equal(np.asarray(toks, np.int32),
                                          ref)

            spans = trace.get_spans()
            # stream-lifecycle spans: per-generation events (they carry
            # the gen id) plus the router's resume marker — NOT the
            # engine-wide gen/decode_step spans, which mint their own
            # trace ids
            stream_ids = {sp["trace_id"] for sp in spans
                          if sp["name"].startswith("gen/")
                          and ("gen" in (sp.get("attrs") or {})
                               or sp["name"] == "gen/stream_resume")}
            assert len(stream_ids) == 1    # whole life under ONE id
            sid, = stream_ids
            mine = [sp for sp in spans if sp["trace_id"] == sid]
            # both replicas admitted the stream: the in-proc servers
            # share one process tracer, so the engine loop thread id is
            # what tells the two replicas' spans apart
            admits = [sp for sp in mine if sp["name"] == "gen/admitted"]
            assert len(admits) == 2
            assert len({sp["tid"] for sp in admits}) == 2
            names = {sp["name"] for sp in mine}
            assert "gen/stream_resume" in names
            assert any((sp.get("attrs") or {}).get("reason")
                       == "complete" for sp in mine
                       if sp["name"] == "gen/retire")
        finally:
            router.close()
    finally:
        for s in servers:
            s.stop()
        set_flags(saved)
        trace.clear()


def test_resume_budget_exhaustion_surfaces_typed(model):
    """When every resume attempt fails (no replica left), the stream
    gives up with the typed StreamResumeExhausted — which still IS a
    GenerationFailed for existing handlers — after exactly budget+1
    attempts."""
    eng = GenerationEngine(model, slots=1, max_len=32, step_wait_s=0.03)
    srv = InferenceServer().start()
    srv.add_generator("llm", eng)
    router = RoutedClient([srv.endpoint], probe_interval_s=0)
    try:
        rs = np.random.RandomState(32)
        prompt = rs.randint(0, VOCAB, (4,)).astype(np.int32)
        ex0 = get_stat("serving/router/resume_exhausted")
        it = router.session("doomed").generate(
            "llm", prompt, 12, poll_wait_s=0.05, resume_budget=1)
        next(it)
        srv.stop()
        with pytest.raises(StreamResumeExhausted) as ei:
            list(it)
        assert isinstance(ei.value, GenerationFailed)
        assert ei.value.attempts == 2          # budget 1 + the last try
        assert get_stat("serving/router/resume_exhausted") == ex0 + 1
    finally:
        router.close()
        srv.stop()


def test_sampled_resume_replays_rng_position(model):
    """A sampled stream resumed as prefill-from-prefix with
    rng_skip=len(delivered) continues the exact per-(prompt, seed) key
    schedule: the resumed tail equals the uninterrupted stream's."""
    with GenerationEngine(model, slots=2, max_len=32) as eng:
        rs = np.random.RandomState(33)
        prompt = rs.randint(0, VOCAB, (6,)).astype(np.int32)
        kw = dict(temperature=0.8, top_k=7, top_p=0.9, seed=42)
        full, err = _drain(eng, eng.start(prompt, 6, **kw))
        assert err is None and len(full) == 6
        # resume after 3 delivered tokens: replay prompt+delivered,
        # fast-forward the key schedule by 3 splits
        replay = np.concatenate([prompt,
                                 np.asarray(full[:3], np.int32)])
        tail, err = _drain(eng, eng.start(replay, 3, rng_skip=3, **kw))
        assert err is None
        assert tail == full[3:]


def test_advance_key_matches_engine_schedule():
    """advance_key(key, n) is exactly n split-and-keep-first steps (the
    engine's per-token schedule)."""
    import jax

    key = jax.random.PRNGKey(42)
    manual = key
    for _ in range(5):
        manual = jax.random.split(manual)[0]
    np.testing.assert_array_equal(np.asarray(advance_key(key, 5)),
                                  np.asarray(manual))
    np.testing.assert_array_equal(np.asarray(advance_key(key, 0)),
                                  np.asarray(key))


# -- engine self-healing ----------------------------------------------------

def test_engine_rebuild_readmits(model):
    """A decode-loop trap with rebuilds enabled fails the active
    generations loudly (resumable 'engine reset:' error), rebuilds the
    device state, and re-admits new work — no terminal broken state."""
    with GenerationEngine(model, slots=2, max_len=32,
                          rebuilds=2) as eng:
        rs = np.random.RandomState(34)
        prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 4))[0, 5:]
        with fault.inject_faults({"engine.decode_step": (1.0, 1)}):
            toks, err = _drain(eng, eng.start(prompt, 4))
            assert err is not None and RESET_MARKER in err
        st = eng.stats()
        assert st["broken"] is None and st["rebuilds"] == 1
        assert st["active"] == 0
        # re-admitted work is byte-identical on the rebuilt state
        toks, err = _drain(eng, eng.start(prompt, 4))
        assert err is None
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)


def test_rebuilds_off_keeps_terminal_break(model):
    """Default gen_engine_rebuilds=0: the first trap still bricks the
    engine (the pre-resilience contract, unchanged)."""
    assert int(flag("gen_engine_rebuilds")) == 0
    with GenerationEngine(model, slots=1, max_len=32) as eng:
        rs = np.random.RandomState(35)
        prompt = rs.randint(0, VOCAB, (4,)).astype(np.int32)
        with fault.inject_faults({"engine.decode_step": (1.0, 1)}):
            toks, err = _drain(eng, eng.start(prompt, 4))
            assert err is not None
        assert _wait(lambda: eng.stats()["broken"] is not None)
        with pytest.raises(RuntimeError, match="broken"):
            eng.start(prompt, 2)


def test_quarantine_after_n_traps(model):
    """A request whose prefill traps gen_quarantine_after times is
    rejected at start with the typed RequestQuarantined; other requests
    are untouched."""
    with GenerationEngine(model, slots=2, max_len=32, rebuilds=4,
                          quarantine_after=1) as eng:
        rs = np.random.RandomState(36)
        poison = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        other = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        q0 = get_stat("gen/quarantined")
        with fault.inject_faults({"engine.prefill": (1.0, 1)}):
            toks, err = _drain(eng, eng.start(poison, 4))
            assert err is not None and RESET_MARKER in err
        assert get_stat("gen/quarantined") == q0 + 1
        # same (prompt, sampling params) fingerprint: typed rejection
        with pytest.raises(RequestQuarantined) as ei:
            eng.start(poison, 4)
        assert ei.value.fingerprint
        assert eng.stats()["quarantined"] == 1
        # an innocent request (different fingerprint) runs fine
        toks, err = _drain(eng, eng.start(other, 3))
        assert err is None and len(toks) == 3


def test_quarantined_start_surfaces_typed_over_wire(model):
    """The quarantine rejection crosses the wire typed (marker →
    RequestQuarantined), so a routed client can give up instead of
    walking the poison request across the fleet."""
    eng = GenerationEngine(model, slots=1, max_len=32, rebuilds=4,
                           quarantine_after=1)
    srv = InferenceServer().start()
    srv.add_generator("llm", eng)
    client = InferenceClient(srv.endpoint)
    try:
        rs = np.random.RandomState(37)
        poison = rs.randint(0, VOCAB, (4,)).astype(np.int32)
        with fault.inject_faults({"engine.prefill": (1.0, 1)}):
            toks, err = _drain(eng, eng.start(poison, 3))
            assert err is not None
        with pytest.raises(RequestQuarantined):
            client.generate_start("llm", poison, 3)
    finally:
        client.close()
        srv.stop()


def test_fused_decode_trap_is_suspect_needs_two_hits(model):
    """Satellite: a fused-decode trap implicates EVERY stepped
    generation — co-tenant-ambiguous attribution. One shared trap must
    not quarantine anyone (a poison request would take its innocent
    co-tenants down with it, even at quarantine_after=1); a second
    independent hit on the same fingerprint convicts. Prefill traps
    (exact) keep their configured threshold of 1 — see
    test_quarantine_after_n_traps."""
    with GenerationEngine(model, slots=2, max_len=32, rebuilds=8,
                          quarantine_after=1, step_wait_s=0.03) as eng:
        rs = np.random.RandomState(61)
        a = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        b = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        s0 = get_stat("gen/suspect_traps")
        for hit in (1, 2):
            # both streams must ride the SAME fused step when the trap
            # fires, or attribution degenerates to exact-by-pigeonhole
            g1, g2 = eng.start(a, 12), eng.start(b, 12)
            assert _wait(lambda: (len(eng.poll(g1)["tokens"]) > 0
                                  and len(eng.poll(g2)["tokens"]) > 0
                                  and not eng.poll(g1)["done"]
                                  and not eng.poll(g2)["done"]),
                         timeout=10.0)
            with fault.inject_faults({"engine.decode_step": (1.0, 1)}):
                _, err1 = _drain(eng, g1)
                _, err2 = _drain(eng, g2)
            assert err1 is not None and err2 is not None
            if hit == 1:
                # one ambiguous hit: suspects, not convicts — the next
                # round's eng.start(a/b) below must be admissible
                assert eng.stats()["quarantined"] == 0
        assert get_stat("gen/suspect_traps") >= s0 + 4
        # two independent ambiguous hits: now both are convicted
        with pytest.raises(RequestQuarantined):
            eng.start(a, 4)
        with pytest.raises(RequestQuarantined):
            eng.start(b, 4)
        assert eng.stats()["quarantined"] == 2


def test_watchdog_fails_stuck_generations(model):
    """A wedged decode loop (heartbeat older than gen_watchdog_s with
    active work) gets its generations failed loudly with the resumable
    reset marker, and new starts shed retryably while stuck."""
    with GenerationEngine(model, slots=1, max_len=32, rebuilds=2,
                          watchdog_s=5.0) as eng:
        rs = np.random.RandomState(38)
        prompt = rs.randint(0, VOCAB, (4,)).astype(np.int32)
        # warm the compiled paths under the generous deadline (XLA
        # compile IS a legitimate long step), then tighten it
        toks, err = _drain(eng, eng.start(prompt, 2))
        assert err is None
        eng._watchdog_s = 0.3
        # wedge the loop: monkeypatch the step to sleep well past the
        # watchdog (the loop thread blocks inside the "compiled call")
        real_step = eng._step

        def stuck_step(*a, **k):
            time.sleep(3.0)
            return real_step(*a, **k)

        eng._step = stuck_step
        stuck0 = get_stat("gen/stuck")
        gid = eng.start(prompt, 8)
        assert _wait(lambda: eng.poll(gid)["done"], timeout=5.0)
        doc = eng.poll(gid)
        assert doc["error"] is not None and "stuck" in doc["error"]
        assert RESET_MARKER in doc["error"]
        assert get_stat("gen/stuck") == stuck0 + 1
        eng._step = real_step
        # the loop rebuilds once the wedged call returns; re-admit works
        assert _wait(lambda: not eng.stats()["stuck"]
                     and eng.stats()["rebuilds"] >= 1, timeout=5.0)
        toks, err = _drain(eng, eng.start(prompt, 2))
        assert err is None and len(toks) == 2


# -- poll-TTL expiry + shed jitter ------------------------------------------

def test_poll_ttl_expiry_is_typed(model):
    """A poll landing after the TTL reap gets the typed
    GenerationExpired (still a KeyError for old handlers) — engine-level
    and across the wire — instead of the ambiguous unknown-id error."""
    eng = GenerationEngine(model, slots=1, max_len=32, ttl_s=0.3,
                           step_wait_s=0.05)
    srv = InferenceServer().start()
    srv.add_generator("llm", eng)
    client = InferenceClient(srv.endpoint)
    try:
        rs = np.random.RandomState(39)
        prompt = rs.randint(0, VOCAB, (4,)).astype(np.int32)
        gid = eng.start(prompt, 25)
        assert _wait(lambda: eng.stats()["generations"] == 0,
                     timeout=3.0)          # TTL reaped (no polls)
        with pytest.raises(GenerationExpired):
            eng.poll(gid)
        assert isinstance(GenerationExpired("x"), KeyError)
        with pytest.raises(GenerationExpired):
            client.generate_poll("llm", gid)
        # an id never seen here stays a plain unknown-id error
        with pytest.raises(RuntimeError, match="unknown generation"):
            client.generate_poll("llm", "deadbeef")
    finally:
        client.close()
        srv.stop()


def test_poll_refreshing_ttl_survives_reap_race(model):
    """A generation whose client IS polling never expires: the reap
    re-checks the TTL under the lock, so a poll that lands while retire
    walks its candidates keeps the stream alive."""
    with GenerationEngine(model, slots=1, max_len=32, ttl_s=0.4,
                          step_wait_s=0.02) as eng:
        rs = np.random.RandomState(40)
        prompt = rs.randint(0, VOCAB, (4,)).astype(np.int32)
        gid = eng.start(prompt, 20)
        toks, err = _drain(eng, gid, wait_s=0.1)   # poll faster than TTL
        assert err is None and len(toks) == 20


def test_shed_retry_after_carries_jitter(model):
    """Shed responses de-synchronize their retry hints: repeated sheds
    return varied retry_after_s within the jitter envelope."""
    with GenerationEngine(model, slots=1, max_len=32, queue_max=1,
                          step_wait_s=0.05) as eng:
        rs = np.random.RandomState(41)
        prompts = [rs.randint(0, VOCAB, (4,)).astype(np.int32)
                   for _ in range(3)]
        gids = [eng.start(p, 25) for p in prompts[:2]]  # 1 runs + 1 queued
        hints = []
        for _ in range(6):
            try:
                eng.start(prompts[2], 25)
                pytest.fail("expected EngineOverloaded")
            except Exception as e:
                hints.append(e.retry_after_s)
        assert len(set(hints)) > 1
        assert all(0.125 <= h <= 0.375 for h in hints)
        for g in gids:
            eng.cancel(g)


# -- deep health ------------------------------------------------------------

def test_deep_health_canary_distinguishes_engine_liveness(model):
    """health(deep=True) runs a one-token canary decode per generator:
    a wedged/broken engine reports ok=False while the wire-level status
    stays 'ok' — 'port open' and 'device healthy' are now separable."""
    eng = GenerationEngine(model, slots=2, max_len=32)
    srv = InferenceServer().start()
    srv.add_generator("llm", eng)
    client = InferenceClient(srv.endpoint)
    try:
        h = client.health(deep=True)
        probe = h["generators"]["llm"]["engine"]
        assert probe["ok"] and probe["latency_s"] > 0
        # shallow health never pays for a canary
        assert "engine" not in client.health()["generators"]["llm"]
        # brick the engine: the wire stays up, the deep probe notices
        with eng._cond:
            eng._broken = "induced for test"
        h = client.health(deep=True)
        assert h["status"] == "ok"                 # port open...
        assert not h["generators"]["llm"]["engine"]["ok"]   # device not
        with eng._cond:
            eng._broken = None
    finally:
        client.close()
        srv.stop()


# -- spawn circuit breaker --------------------------------------------------

class _FlakySpawner(ReplicaSpawner):
    """Spawner whose artifact is poisoned until told otherwise."""

    def __init__(self):
        self.calls = 0
        self.fail = True
        self.servers = []

    def spawn(self) -> str:
        self.calls += 1
        if self.fail:
            raise RuntimeError("poisoned artifact: replica crashed")
        srv = InferenceServer().start()
        self.servers.append(srv)
        return srv.endpoint

    def stop(self, endpoint: str, drain_s: float = 0.0) -> None:
        for srv in self.servers:
            if srv.endpoint == endpoint:
                srv.stop()

    def close(self):
        for srv in self.servers:
            srv.stop()


def test_spawn_breaker_opens_and_half_opens():
    """Consecutive spawn failures open the breaker (spawner NOT called,
    'spawn_breaker' decision recorded); after the backoff one half-open
    trial runs, and a success closes the breaker."""
    sp = _FlakySpawner()
    ctl = ServingController(sp, interval_s=0, min_replicas=0,
                            max_replicas=3, spawn_breaker=2,
                            spawn_backoff_s=0.2, cooldown_s=0)
    try:
        assert ctl._scale_up("t", {}).action == "spawn_failed"
        d = ctl._scale_up("t", {})
        assert d.action == "spawn_failed" and "OPEN" in d.reason
        assert sp.calls == 2
        # breaker open: the spawner is not even called
        d = ctl._scale_up("t", {})
        assert d.action == "spawn_breaker"
        assert sp.calls == 2
        time.sleep(0.25)                       # backoff elapses
        sp.fail = False                        # artifact fixed
        d = ctl._scale_up("t", {})             # half-open trial
        assert d.action == "scale_up" and sp.calls == 3
        assert ctl._spawn_fails == 0           # breaker closed
        actions = [x["action"] for x in ctl.decisions()]
        assert "spawn_breaker" in actions
    finally:
        ctl.close()
        sp.close()


def test_spawn_breaker_off_by_default():
    """control_spawn_breaker=0 (default): every attempt calls the
    spawner — the pre-resilience hot-loop behavior is opt-out only."""
    assert int(flag("control_spawn_breaker")) == 0
    sp = _FlakySpawner()
    ctl = ServingController(sp, interval_s=0, min_replicas=0,
                            max_replicas=3, cooldown_s=0)
    try:
        for _ in range(4):
            assert ctl._scale_up("t", {}).action == "spawn_failed"
        assert sp.calls == 4
    finally:
        ctl.close()


# -- defaults stay inert ----------------------------------------------------

def test_resilience_defaults_off(model):
    """Every new knob reads zero by default: no watchdog thread, no
    rebuilds, no quarantine books consulted, no resume wrapper — the
    unflagged path is the PR-7 behavior byte-identically."""
    for name in ("gen_resume_budget", "gen_quarantine_after",
                 "gen_engine_rebuilds", "control_spawn_breaker"):
        assert int(flag(name)) == 0, name
    assert float(flag("gen_watchdog_s")) == 0.0
    with GenerationEngine(model, slots=1, max_len=32) as eng:
        assert eng._watchdog is None
        assert eng._rebuild_max == 0 and eng._quarantine_after == 0
    srv = InferenceServer().start()
    srv.add_generator("llm", GenerationEngine(model, slots=1,
                                              max_len=32))
    router = RoutedClient([srv.endpoint], probe_interval_s=0)
    try:
        rs = np.random.RandomState(42)
        prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 4))[0, 5:]
        r0 = get_stat("serving/router/stream_resumes")
        toks = list(router.generate("llm", prompt, 4))
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        assert get_stat("serving/router/stream_resumes") == r0
    finally:
        router.close()
        srv.stop()


def test_poisoned_stream_quarantined_after_failover_hop(model):
    """Satellite (PR 8 NOTE): a resumed stream's replay prompt grew by
    the delivered tokens, so it used to hash a FRESH crash fingerprint
    on every hop — a poisoned stream could walk the fleet forever, one
    quarantine book at a time. The router now carries the ORIGINAL
    fingerprint through the resume path (header ``fp``), so the
    survivor that traps on the replay quarantines the original stream
    identity and the next resume attempt is rejected typed."""
    from paddle_tpu.serving.engine import stream_fingerprint

    servers, engines = [], []
    for _ in range(2):
        eng = GenerationEngine(model, slots=2, max_len=32,
                               step_wait_s=0.03, rebuilds=4,
                               quarantine_after=1)
        srv = InferenceServer().start()
        srv.add_generator("llm", eng)
        servers.append(srv)
        engines.append(eng)
    router = RoutedClient([s.endpoint for s in servers],
                          probe_interval_s=0)
    try:
        rs = np.random.RandomState(51)
        prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        fp = stream_fingerprint(prompt)

        sess = router.session("poison-stream")
        it = sess.generate("llm", prompt, 10, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it)]                      # live on the pinned replica
        pinned = sess.endpoint
        victim = next(s for s in servers if s.endpoint == pinned)
        survivor = next(e for s, e in zip(servers, engines)
                        if s.endpoint != pinned)
        victim.stop()                          # hop 1: replica death
        # the resumed replay traps on the survivor: without the fp
        # carry it would quarantine hash(prompt + delivered) and the
        # NEXT resume would walk the poison right back in
        with fault.inject_faults({"engine.decode_step": (1.0, 1)}):
            with pytest.raises(RequestQuarantined):
                toks += list(it)
        assert fp in survivor._quarantined     # the ORIGINAL identity
        assert survivor.stats()["quarantined"] == 1
        # the poison is now rejected under its original prompt too
        with pytest.raises(RequestQuarantined):
            survivor.start(prompt, 4)
    finally:
        router.close()
        for s in servers:
            s.stop()
