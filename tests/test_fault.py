"""Fault-tolerance layer: wire deadlines/retry/reconnect, deterministic
fault injection, checkpoint integrity + rollback, guarded training,
preemption-safe epoch loops. All CPU-only and tier-1 fast."""

import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import io, nn
from paddle_tpu.core import fault, monitor
from paddle_tpu.core.wire import FrameClient, FrameService, send_frame

pytestmark = pytest.mark.fault


@pytest.fixture(autouse=True)
def _injection_off():
    """Injection must be hard-off around every test (the production
    default) — a leaked config would poison unrelated suites."""
    fault.reset()
    yield
    fault.reset()


# ---------------------------------------------------------------------------
# fault injection registry
# ---------------------------------------------------------------------------

def _fire_pattern(seed, n=32):
    fault.configure({"x": 0.5}, seed=seed)
    out = []
    for _ in range(n):
        try:
            fault.inject("x")
            out.append(0)
        except fault.InjectedFault:
            out.append(1)
    return out


def test_injection_deterministic_per_seed():
    a, b = _fire_pattern(7), _fire_pattern(7)
    assert a == b, "same seed must reproduce the same fire pattern"
    assert 0 < sum(a) < len(a)
    assert _fire_pattern(8) != a


def test_injection_cap_stats_and_default_off():
    monitor.reset_stats("fault/")
    fault.configure("y=1.0@2", seed=0)   # flag-style spec string
    fires = 0
    for _ in range(5):
        try:
            fault.inject("y")
        except fault.InjectedFault:
            fires += 1
    assert fires == 2, "@2 caps total fires"
    assert monitor.get_stat("fault/injected/y") == 2
    assert fault.site_counts()["y"] == (5, 2)
    fault.inject("unlisted.site")        # non-spec sites never fire
    fault.reset()
    assert not fault.enabled()
    fault.inject("y")                    # off == plain no-op


# ---------------------------------------------------------------------------
# wire: deadlines, retry, reconnect, context manager
# ---------------------------------------------------------------------------

class _Echo(FrameService):
    def _dispatch(self, sock, op, header, payload):
        send_frame(sock, 0, {"echo": header.get("x")})
        return True


class _Blackhole(FrameService):
    """Accepts requests and never replies — the dead-peer hang the old
    client waited on forever."""

    def _dispatch(self, sock, op, header, payload):
        time.sleep(2.0)
        return True


def test_request_deadline_and_retry_budget():
    srv = _Blackhole().start()
    monitor.reset_stats("wire/")
    c = FrameClient(srv.endpoint, {"ping": 1}, service="test",
                    timeout=0.2, retries=1, idempotent=("ping",))
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="after 2 attempt"):
        c._request("ping", {})
    assert time.monotonic() - t0 < 2.0, "deadline bounded the hang"
    assert monitor.get_stat("wire/timeouts") >= 2
    assert monitor.get_stat("wire/retries") == 1
    c.close()
    c.close()                            # double close is safe
    with pytest.raises(ConnectionError, match="closed"):
        c._request("ping", {})
    srv.stop()


def test_frame_client_context_manager():
    srv = _Echo().start()
    with FrameClient(srv.endpoint, {"e": 1}, timeout=5.0) as c:
        h, _ = c._request("e", {"x": 5})
        assert h["echo"] == 5
    with pytest.raises(ConnectionError, match="closed"):
        c._request("e", {})
    srv.stop()


def test_injected_wire_fault_recovered_by_retry():
    srv = _Echo().start()
    monitor.reset_stats("wire/")
    monitor.reset_stats("fault/")
    c = FrameClient(srv.endpoint, {"e": 1}, timeout=5.0, retries=2,
                    idempotent=("e",))
    with fault.inject_faults({"wire.send": (1.0, 2)}, seed=1):
        h, _ = c._request("e", {"x": 1})
    assert h["echo"] == 1
    assert monitor.get_stat("fault/injected/wire.send") == 2
    assert monitor.get_stat("wire/retries") == 2
    assert monitor.get_stat("wire/reconnects") >= 1
    c.close()
    srv.stop()


def test_non_idempotent_op_fails_fast():
    srv = _Echo().start()
    monitor.reset_stats("wire/")
    c = FrameClient(srv.endpoint, {"e": 1}, timeout=5.0, retries=3)
    with fault.inject_faults({"wire.send": (1.0, 1)}, seed=1):
        with pytest.raises(ConnectionError, match="after 1 attempt"):
            c._request("e", {"x": 1})    # not in the idempotent set
    assert monitor.get_stat("wire/retries") == 0
    c.close()
    srv.stop()


def test_inference_client_survives_server_restart(tmp_path):
    """The chaos scenario: kill the serving process mid-session, bring
    it back on the same port — the client's next request reconnects and
    succeeds instead of hanging or dying."""
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = str(tmp_path / "mlp")
    io.save_inference_model(path, net, [np.zeros((2, 4), np.float32)])

    srv = io.InferenceServer({"m": path}).start()
    port = srv.port
    client = io.InferenceClient(srv.endpoint, timeout=10.0)
    x = np.ones((2, 4), np.float32)
    (y1,) = client.infer("m", x)
    srv.stop()                                   # kill

    monitor.reset_stats("wire/")
    srv2 = io.InferenceServer({"m": path}, port=port).start()  # restart
    (y2,) = client.infer("m", x)                 # same client object
    np.testing.assert_allclose(y2, y1)
    assert monitor.get_stat("wire/retries") >= 1
    assert monitor.get_stat("wire/reconnects") >= 1
    client.stop_server()
    client.stop_server()                         # safe to call twice
    client.close()
    srv2.stop()


def test_wirefs_and_ps_clients_take_timeouts(tmp_path):
    from paddle_tpu.distributed.ps import ParameterServer, PSClient

    fssrv = io.FSService(str(tmp_path / "root")).start()
    wfs = io.WireFS(fssrv.endpoint, timeout=5.0)
    wfs.mkdirs("a")
    assert wfs.is_dir("a")
    with fault.inject_faults({"fs.upload": 1.0}):
        with pytest.raises(fault.InjectedFault):
            wfs.upload(__file__, "a/f")
    wfs.upload(__file__, "a/f")                  # off again: works
    assert wfs.is_file("a/f")
    wfs.close()
    fssrv.stop()

    ps = ParameterServer().start()
    c = PSClient(ps.endpoint, timeout=5.0)
    c.create_table("t", 4)
    rows = c.pull("t", np.arange(3))
    assert rows.shape == (3, 4)
    c.stop_servers()
    c.close()


# ---------------------------------------------------------------------------
# checkpoint integrity + rollback
# ---------------------------------------------------------------------------

def _tpl(v=0.0, step=0):
    return {"w": jnp.full((8, 8), float(v)), "step": jnp.asarray(int(step))}


def _corrupt_tree(path):
    """Bit-flip + truncate every substantial file under a step dir."""
    for root, _, files in os.walk(path):
        for name in files:
            p = os.path.join(root, name)
            size = os.path.getsize(p)
            if size < 8:
                continue
            with open(p, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
                f.truncate(max(size // 2, 8))


def test_corrupt_latest_step_falls_back(tmp_path):
    d = str(tmp_path / "ck")
    for s in (1, 2, 3):
        io.save_checkpoint(_tpl(s, s), d, step=s)
    io.checkpoint.wait_until_finished(d)
    assert io.latest_step(d) == 3

    _corrupt_tree(os.path.join(d, "3"))
    monitor.reset_stats("ckpt/")
    restored, used = io.load_checkpoint(_tpl(), d, return_step=True)
    assert used == 2
    assert float(restored["w"][0, 0]) == 2.0 and int(restored["step"]) == 2
    assert monitor.get_stat("ckpt/rollbacks") >= 1
    assert monitor.get_stat("ckpt/corrupt_steps") >= 1
    # strict mode surfaces the corruption instead
    with pytest.raises(Exception):
        io.load_checkpoint(_tpl(), d, step=3, fallback=False)


def test_bitflip_caught_by_manifest_checksum(tmp_path):
    """A single flipped byte that still *restores* cleanly must be caught
    by the manifest crc32, not returned as silently wrong weights."""
    d = str(tmp_path / "ck")
    for s in (1, 2):
        io.save_checkpoint(_tpl(s, s), d, step=s)
    io.checkpoint.wait_until_finished(d)
    # flip one payload byte in the largest file of step 2 (no truncation)
    biggest, bsize = None, -1
    for root, _, files in os.walk(os.path.join(d, "2")):
        for name in files:
            p = os.path.join(root, name)
            if os.path.getsize(p) > bsize:
                biggest, bsize = p, os.path.getsize(p)
    with open(biggest, "r+b") as f:
        f.seek(bsize // 2)
        b = f.read(1)
        f.seek(bsize // 2)
        f.write(bytes([b[0] ^ 0x01]))
    restored, used = io.load_checkpoint(_tpl(), d, return_step=True)
    assert used == 1 and float(restored["w"][0, 0]) == 1.0


def test_epoch_range_injected_save_crash_then_resume(tmp_path):
    """Acceptance scenario: a TrainEpochRange run crashes inside a
    checkpoint save (injected ``ckpt.save`` fault). The orbax step may
    exist on disk but carries no manifest — the relaunch must resume
    from the previous verifiable step, not crash, not trust it."""
    d = str(tmp_path / "run")
    monitor.reset_stats("ckpt/")
    monitor.reset_stats("fault/")
    r = io.TrainEpochRange(6, d, state=_tpl(-1, -1))
    seen = []
    with pytest.raises(fault.InjectedFault):
        for epoch in r:
            seen.append(epoch)
            r.state = _tpl(epoch, epoch)
            if epoch == 2:   # next epoch-end save will blow up
                fault.configure({"ckpt.save": 1.0}, seed=0)
    assert seen == [0, 1, 2]
    assert monitor.get_stat("fault/injected/ckpt.save") == 1
    fault.reset()
    io.checkpoint.wait_until_finished(d)   # let step 2's async data land

    r2 = io.TrainEpochRange(6, d, state=_tpl())
    assert r2.resumed
    assert r2.start_epoch == 2, "resumes AFTER the last verifiable step"
    assert int(r2.state["step"]) == 1
    assert io.verify_step(d, 1)
    assert not io.verify_step(d, 2)


def test_train_guard_nan_rollback_on_mlp(tmp_path):
    """Loss-spike sentinel on a tiny MLP: two poisoned epochs produce
    non-finite losses; the guard blocks checkpointing the poisoned state
    and rolls back to the last good step, and training continues."""
    d = str(tmp_path / "guard")
    paddle_tpu.seed(3)
    model = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 1))
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, 6).astype(np.float32))
    y = jnp.asarray(rs.randn(16, 1).astype(np.float32))

    def loss_fn(m, xb, yb):
        return jnp.mean((m(xb) - yb) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    monitor.reset_stats("ckpt/")
    monitor.reset_stats("train/")

    r = io.TrainEpochRange(8, d, state=model)
    guard = io.TrainGuard(r, patience=2, max_rollbacks=1)
    bad = {4, 5}
    losses = {}
    for epoch in r:
        xb = x * jnp.nan if epoch in bad else x
        loss, g = grad_fn(r.state, xb, y)
        new_m = jax.tree_util.tree_map(lambda p, gg: p - 0.05 * gg,
                                       r.state, g)
        r.state = guard.observe(new_m, loss)
        losses[epoch] = float(loss)

    assert guard.rollbacks == 1
    assert all(np.isnan(losses[e]) for e in bad)
    assert all(np.isfinite(losses[e]) for e in losses if e not in bad)
    # the post-rollback weights are finite (poison did not survive)
    assert all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree_util.tree_leaves(r.state))
    assert monitor.get_stat("train/steps_skipped_nonfinite") == 2
    assert monitor.get_stat("train/guard_rollbacks") == 1
    assert monitor.get_stat("ckpt/rollbacks") >= 1
    assert monitor.get_stat("ckpt/saves_skipped_unhealthy") >= 1


def test_train_guard_rollback_budget_exhausted(tmp_path):
    d = str(tmp_path / "budget")
    r = io.TrainEpochRange(10, d, state=_tpl())
    guard = io.TrainGuard(r, patience=1, max_rollbacks=0)
    with pytest.raises(io.RollbackBudgetExceeded):
        for epoch in r:
            r.state = guard.observe(_tpl(epoch, epoch), float("nan"))


def test_preemption_sigterm_saves_and_exits(tmp_path):
    """SIGTERM mid-epoch: the loop finishes the epoch, persists it (even
    off the save interval), flushes the async save, and exits; a
    relaunch resumes exactly there."""
    d = str(tmp_path / "pre")
    monitor.reset_stats("train/")
    r = io.TrainEpochRange(50, d, state=_tpl(), save_interval=10)
    seen = []
    with io.PreemptionHandler(r) as h:
        for epoch in r:
            seen.append(epoch)
            r.state = _tpl(epoch, epoch)
            if epoch == 3:
                os.kill(os.getpid(), signal.SIGTERM)
    assert h.installed and h.preempted and r.stopped
    assert seen == [0, 1, 2, 3], "stopped after the preempted epoch"
    assert io.latest_step(d) == 3
    assert monitor.get_stat("train/preemptions") == 1
    assert monitor.get_stat("train/preempted_exits") == 1

    r2 = io.TrainEpochRange(50, d, state=_tpl())
    assert r2.start_epoch == 4 and int(r2.state["step"]) == 3


# ---------------------------------------------------------------------------
# monitor satellites
# ---------------------------------------------------------------------------

def test_step_timer_windowed_tokens_per_sec():
    monitor.reset_stats("tt/")
    t = monitor.StepTimer("tt", window=8)
    for tok in (100, 200, 300):
        t.tick(tokens=tok)
    sps = monitor.get_stat("tt/steps_per_sec")
    tps = monitor.get_stat("tt/tokens_per_sec")
    assert sps > 0 and tps > 0
    # dt cancels in the ratio: windowed mean of the ticks the interval
    # spans = (200+300)/2, NOT the old last-tick value 300
    assert tps / sps == pytest.approx((200 + 300) / 2)
    assert monitor.get_stat("tt/tokens") == 600


def test_host_rss_current_vs_peak():
    cur, peak = monitor.host_rss_bytes(), monitor.host_peak_rss_bytes()
    assert isinstance(cur, int) and isinstance(peak, int)
    assert cur > 0 and peak > 0
    # current RSS can't meaningfully exceed the lifetime peak
    assert cur <= peak * 1.05
