"""Extended nn surface: activations, pads, pools (1D/3D/adaptive),
conv3d/transposes, dropout variants, pixel shuffle, LRN, spectral norm,
CTC/margin/hsigmoid losses, SimpleRNN/BiRNN — OpTest-style golden checks
against numpy/torch-documented formulas."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def test_extended_activations_golden():
    x = jnp.asarray(np.linspace(-3, 3, 13).astype(np.float32))
    xn = np.asarray(x)
    np.testing.assert_allclose(F.hardshrink(x, 0.5),
                               np.where(np.abs(xn) > 0.5, xn, 0), rtol=1e-6)
    np.testing.assert_allclose(F.hardtanh(x), np.clip(xn, -1, 1), rtol=1e-6)
    np.testing.assert_allclose(F.softsign(x), xn / (1 + np.abs(xn)),
                               rtol=1e-6)
    np.testing.assert_allclose(F.tanhshrink(x), xn - np.tanh(xn), rtol=1e-5)
    np.testing.assert_allclose(F.thresholded_relu(x, 1.0),
                               np.where(xn > 1, xn, 0), rtol=1e-6)
    np.testing.assert_allclose(F.softshrink(x, 0.5),
                               np.sign(xn) * np.maximum(np.abs(xn) - .5, 0),
                               rtol=1e-6)
    np.testing.assert_allclose(F.log_sigmoid(x),
                               -np.log1p(np.exp(-xn)), rtol=1e-5)
    # selu fixed point: mean/var preserving constants
    np.testing.assert_allclose(float(F.selu(jnp.asarray(0.0))), 0.0,
                               atol=1e-7)
    assert abs(float(F.selu(jnp.asarray(-1e9))) + 1.7581) < 1e-3


def test_maxout_and_prelu():
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(1, 6, 2))
    y = F.maxout(x, groups=3, axis=1)
    assert y.shape == (1, 2, 2)
    layer = nn.PReLU(num_parameters=4, init=0.1)
    x2 = jnp.asarray(np.array([[-1.0, 2.0, -3.0, 4.0]], np.float32))
    out = layer(x2)
    np.testing.assert_allclose(np.asarray(out), [[-0.1, 2.0, -0.3, 4.0]],
                               rtol=1e-6)
    g = jax.grad(lambda m: jnp.sum(m(x2)))(layer)
    np.testing.assert_allclose(np.asarray(g.weight), [-1, 0, -3, 0],
                               rtol=1e-6)


def test_pads_and_pixel_shuffle():
    x = jnp.asarray(np.arange(8, dtype=np.float32).reshape(1, 1, 2, 4))
    y = nn.Pad2D((1, 2, 0, 1), value=9.0)(x)
    assert y.shape == (1, 1, 3, 7)
    assert float(y[0, 0, 0, 0]) == 9.0
    y2 = nn.Pad1D(2, mode="reflect")(x.reshape(1, 2, 4))
    assert y2.shape == (1, 2, 8)

    ps = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 2, 2))
    out = F.pixel_shuffle(ps, 2)
    assert out.shape == (1, 1, 4, 4)
    # upper-left 2x2 block interleaves channels 0..3 at (0,0)
    np.testing.assert_allclose(np.asarray(out[0, 0, :2, :2]),
                               [[0, 4], [8, 12]])


def test_pool_1d_3d_and_adaptive():
    x1 = jnp.asarray(np.arange(8, dtype=np.float32).reshape(1, 1, 8))
    np.testing.assert_allclose(np.asarray(F.max_pool1d(x1, 2))[0, 0],
                               [1, 3, 5, 7])
    np.testing.assert_allclose(np.asarray(F.avg_pool1d(x1, 2))[0, 0],
                               [0.5, 2.5, 4.5, 6.5])
    np.testing.assert_allclose(
        np.asarray(F.adaptive_avg_pool1d(x1, 2))[0, 0], [1.5, 5.5])
    np.testing.assert_allclose(
        np.asarray(F.adaptive_max_pool1d(x1, 2))[0, 0], [3, 7])

    x3 = jnp.asarray(np.random.RandomState(0).rand(1, 2, 4, 4, 4)
                     .astype(np.float32))
    out = F.max_pool3d(x3, 2)
    assert out.shape == (1, 2, 2, 2, 2)
    np.testing.assert_allclose(
        float(out[0, 0, 0, 0, 0]),
        np.asarray(x3)[0, 0, :2, :2, :2].max(), rtol=1e-6)
    avg = F.adaptive_avg_pool3d(x3, 1)
    np.testing.assert_allclose(np.asarray(avg)[0, :, 0, 0, 0],
                               np.asarray(x3).mean(axis=(2, 3, 4))[0],
                               rtol=1e-5)


def test_conv3d_matches_naive():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(1, 2, 4, 4, 4).astype(np.float32))
    layer = nn.Conv3D(2, 3, 2)
    out = layer(x)
    assert out.shape == (1, 3, 3, 3, 3)
    w = np.asarray(layer.weight)
    ref = np.zeros((3, 3, 3, 3))
    xn = np.asarray(x)[0]
    for o in range(3):
        for d in range(3):
            for i in range(3):
                for j in range(3):
                    patch = xn[:, d:d + 2, i:i + 2, j:j + 2]
                    ref[o, d, i, j] = (patch * w[o]).sum()
    np.testing.assert_allclose(np.asarray(out)[0], ref, rtol=1e-4,
                               atol=1e-5)


def test_conv1d_transpose_is_conv_input_grad():
    """Defining property: conv_transpose(x; w) equals the vjp of the
    forward conv (same stride/padding) applied to x."""
    x = jnp.asarray(np.random.RandomState(0).randn(1, 3, 8)
                    .astype(np.float32))
    deconv = nn.Conv1DTranspose(3, 2, 3, stride=2, padding=1, bias=False)
    y = deconv(x)
    # (L-1)*s - 2p + k = 7*2 - 2 + 3 = 15
    assert y.shape == (1, 2, 15)

    # forward conv [1,2,15] -> [1,3,8]: deconv.weight [in=3, out=2, k]
    # read as conv1d's [O=3, I=2, K]
    _, vjp = jax.vjp(
        lambda v: F.conv1d(v, deconv.weight, stride=2, padding=1),
        jnp.zeros((1, 2, 15)))
    (grad_in,) = vjp(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(grad_in),
                               rtol=1e-4, atol=1e-5)


def test_dropout_variants():
    key = jax.random.PRNGKey(0)
    x = jnp.ones((4, 8, 5, 5))
    y = F.dropout2d(x, 0.5, training=True, key=key)
    # whole channels are dropped: each [h, w] map is constant
    yn = np.asarray(y)
    assert ((yn == 0).all(axis=(2, 3)) | (yn == 2.0).all(axis=(2, 3))).all()
    y3 = F.dropout3d(jnp.ones((2, 4, 3, 3, 3)), 0.5, training=True, key=key)
    assert y3.shape == (2, 4, 3, 3, 3)
    ya = F.alpha_dropout(jnp.asarray(np.random.RandomState(0)
                                     .randn(10000).astype(np.float32)),
                         0.3, training=True, key=key)
    # mean/std approximately preserved (the point of alpha dropout)
    assert abs(float(jnp.mean(ya))) < 0.1
    assert 0.8 < float(jnp.std(ya)) < 1.25
    assert not np.allclose(np.asarray(ya), 0)


def test_local_response_norm_golden():
    x = jnp.asarray(np.random.RandomState(0).rand(1, 6, 2, 2)
                    .astype(np.float32))
    y = F.local_response_norm(x, size=3, alpha=1.0, beta=0.5, k=1.0)
    xn = np.asarray(x)
    sq = xn ** 2
    ref = np.zeros_like(xn)
    for c in range(6):
        lo, hi = max(0, c - 1), min(6, c + 2)
        denom = 1.0 + sq[:, lo:hi].sum(axis=1)
        ref[:, c] = xn[:, c] / np.sqrt(denom)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5)


def test_spectral_norm_unit_sigma():
    paddle_tpu.seed(0)
    sn = nn.SpectralNorm((8, 4), n_power_iterations=20)
    w = jnp.asarray(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    w_sn = sn(w)
    sigma = np.linalg.svd(np.asarray(w_sn), compute_uv=False)[0]
    np.testing.assert_allclose(sigma, 1.0, rtol=1e-3)


def test_ctc_loss_collapses():
    """CTC of a sequence that strongly predicts the label path is small;
    a contradictory one is large."""
    B, T, V, L = 2, 6, 5, 2
    labels = jnp.asarray([[1, 2], [3, 4]])
    good = np.full((B, T, V), -10.0, np.float32)
    # frames spell: 1 1 2 2 blank blank
    for b, (a, c) in enumerate([[1, 2], [3, 4]]):
        good[b, :2, a] = 0
        good[b, 2:4, c] = 0
        good[b, 4:, 0] = 0
    good = jax.nn.log_softmax(jnp.asarray(good), -1)
    il = jnp.asarray([T, T])
    ll = jnp.asarray([L, L])
    loss_good = F.ctc_loss(good, labels, il, ll, reduction="none")
    bad = jax.nn.log_softmax(jnp.zeros((B, T, V)), -1)
    loss_bad = F.ctc_loss(bad, labels, il, ll, reduction="none")
    assert (np.asarray(loss_good) < np.asarray(loss_bad)).all()


def test_margin_ranking_loss():
    a = jnp.asarray([1.0, 2.0])
    b = jnp.asarray([2.0, 1.0])
    lab = jnp.asarray([1.0, 1.0])   # wants a > b
    loss = F.margin_ranking_loss(a, b, lab, margin=0.5, reduction="none")
    np.testing.assert_allclose(np.asarray(loss), [1.5, 0.0], rtol=1e-6)


def test_hsigmoid_loss_trains_classifier():
    """HSigmoid must be minimizable toward the true classes and beat an
    untrained baseline by a wide margin."""
    paddle_tpu.seed(0)
    n_cls, dim = 8, 16
    layer = nn.HSigmoidLoss(dim, n_cls)
    rs = np.random.RandomState(0)
    protos = rs.randn(n_cls, dim).astype(np.float32) * 2
    labels = rs.randint(0, n_cls, (64,))
    x = jnp.asarray(protos[labels] + 0.1 * rs.randn(64, dim)
                    .astype(np.float32))
    y = jnp.asarray(labels)

    def loss_fn(m):
        return m(x, y)

    l0 = float(loss_fn(layer))
    for _ in range(60):
        g = jax.grad(loss_fn)(layer)
        layer = jax.tree_util.tree_map(lambda p, gg: p - 0.3 * gg, layer, g)
    l1 = float(loss_fn(layer))
    assert l1 < l0 * 0.3, (l0, l1)


def test_simple_rnn_and_birnn():
    paddle_tpu.seed(1)
    rnn = nn.SimpleRNN(4, 8, num_layers=2)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 4)
                    .astype(np.float32))
    out, states = rnn(x)
    assert out.shape == (2, 5, 8)

    bi = nn.BiRNN(nn.SimpleRNNCell(4, 8), nn.SimpleRNNCell(4, 8))
    out2, (st_f, st_b) = bi(x)
    assert out2.shape == (2, 5, 16)
    # backward half at t=0 equals a forward pass over the reversed seq at
    # its last step feature — sanity: not equal to forward half
    assert not np.allclose(np.asarray(out2[..., :8]),
                           np.asarray(out2[..., 8:]))


def test_bilinear_and_distances():
    paddle_tpu.seed(2)
    bl = nn.Bilinear(3, 4, 2)
    x1 = jnp.asarray(np.random.RandomState(0).randn(5, 3).astype(np.float32))
    x2 = jnp.asarray(np.random.RandomState(1).randn(5, 4).astype(np.float32))
    out = bl(x1, x2)
    assert out.shape == (5, 2)
    ref = np.einsum("bi,oij,bj->bo", np.asarray(x1), np.asarray(bl.weight),
                    np.asarray(x2)) + np.asarray(bl.bias)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)

    d = nn.PairwiseDistance()(x1, x1 + 1.0)
    np.testing.assert_allclose(np.asarray(d), np.sqrt(3 * (1 + 1e-6) ** 2)
                               * np.ones(5), rtol=1e-4)


def test_upsample_and_rowconv():
    x = jnp.asarray(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    up = nn.UpsamplingNearest2D(scale_factor=2)(x)
    assert up.shape == (1, 1, 4, 4)
    # nearest with integer scale replicates each pixel into a 2x2 block
    np.testing.assert_allclose(np.asarray(up[0, 0]),
                               np.kron(np.asarray(x[0, 0]), np.ones((2, 2))))

    paddle_tpu.seed(3)
    rc = nn.RowConv(4, future_context_size=2)
    seq = jnp.asarray(np.random.RandomState(0).randn(1, 6, 4)
                      .astype(np.float32))
    out = rc(seq)
    assert out.shape == (1, 6, 4)
    # golden at t=3: sum_i w[i] * x[t+i]
    w = np.asarray(rc.weight)
    xn = np.asarray(seq)[0]
    ref = sum(w[i] * xn[3 + i] for i in range(3))
    np.testing.assert_allclose(np.asarray(out[0, 3]), ref, rtol=1e-5)


def test_affine_grid_and_grid_sample_identity():
    """Identity theta must reproduce the input exactly (bilinear,
    align_corners) — the spatial-transformer sanity check."""
    x = jnp.asarray(np.random.RandomState(0).rand(2, 3, 5, 7)
                    .astype(np.float32))
    theta = jnp.broadcast_to(
        jnp.asarray([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]), (2, 2, 3))
    grid = F.affine_grid(theta, (2, 3, 5, 7))
    assert grid.shape == (2, 5, 7, 2)
    out = F.grid_sample(x, grid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)
    # nearest mode identity too
    out_n = F.grid_sample(x, grid, mode="nearest")
    np.testing.assert_allclose(np.asarray(out_n), np.asarray(x), atol=1e-5)


def test_grid_sample_translation_zero_pad():
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    # shift right by one pixel (normalized step = 2/(W-1))
    theta = jnp.asarray([[[1.0, 0.0, -2.0 / 3.0], [0.0, 1.0, 0.0]]])
    out = F.grid_sample(x, F.affine_grid(theta, (1, 1, 4, 4)))
    ref = np.zeros((4, 4), np.float32)
    ref[:, 1:] = np.asarray(x)[0, 0, :, :-1]
    np.testing.assert_allclose(np.asarray(out[0, 0]), ref, atol=1e-5)


def test_loss_zoo_golden():
    p = jnp.asarray([0.9, 0.1])
    y = jnp.asarray([1.0, 0.0])
    np.testing.assert_allclose(
        np.asarray(F.log_loss(p, y)),
        [-np.log(0.9), -np.log(0.9)], rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(F.square_error_cost(p, y)), [0.01, 0.01], rtol=1e-4)

    # dice: perfect prediction → ~0 loss; disjoint → ~1
    pred = jnp.asarray([[1.0, 0.0, 1.0, 0.0]])
    assert float(F.dice_loss(pred, pred)) < 1e-4
    assert float(F.dice_loss(pred, 1.0 - pred)) > 0.99

    # focal loss: well-classified examples are strongly down-weighted
    logit = jnp.asarray([5.0, -5.0])
    label = jnp.asarray([1.0, 0.0])
    easy = float(F.sigmoid_focal_loss(logit, label))
    hard = float(F.sigmoid_focal_loss(-logit, label))
    assert easy < hard / 1000

    # npair: matching pairs beat shuffled pairs
    rs = np.random.RandomState(0)
    a = jnp.asarray(rs.randn(8, 4).astype(np.float32))
    labels = jnp.arange(8)
    good = float(F.npair_loss(a, a, labels, l2_reg=0.0))
    bad = float(F.npair_loss(a, -a, labels, l2_reg=0.0))
    assert good < bad


def test_diag_embed_and_instance_norm():
    v = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    d = F.diag_embed(v)
    assert d.shape == (2, 2, 2)
    np.testing.assert_allclose(np.asarray(d[0]), [[1, 0], [0, 2]])
    off = F.diag_embed(jnp.asarray([1.0, 2.0]), offset=1)
    assert off.shape == (3, 3) and float(off[0, 1]) == 1.0

    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 4, 4)
                    .astype(np.float32))
    y = F.instance_norm(x)
    m = np.asarray(y).mean(axis=(2, 3))
    np.testing.assert_allclose(m, np.zeros_like(m), atol=1e-5)


def test_functional_conv_transposes_match_layers():
    paddle_tpu.seed(0)
    deconv = nn.Conv2DTranspose(3, 2, 3, stride=2, padding=1, bias=False)
    x = jnp.asarray(np.random.RandomState(0).randn(1, 3, 5, 5)
                    .astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(F.conv2d_transpose(x, deconv.weight, stride=2,
                                      padding=1)),
        np.asarray(deconv(x)), rtol=1e-5)


def test_nce_minimizable():
    """NCE loss must be reducible by gradient descent on the features —
    the sampled-softmax training property (reference nce_op)."""
    rs = np.random.RandomState(0)
    V, D, B = 50, 8, 16
    weight = jnp.asarray(rs.randn(V, D).astype(np.float32) * 0.5)
    labels = jnp.asarray(rs.randint(0, V, (B,)))
    x = jnp.asarray(rs.randn(B, D).astype(np.float32))
    key = jax.random.PRNGKey(0)

    def loss_fn(x):
        return F.nce(x, labels, weight, num_total_classes=V, key=key)

    l0 = float(loss_fn(x))
    step = jax.jit(lambda x: x - 0.5 * jax.grad(loss_fn)(x))
    for _ in range(150):
        x = step(x)
    l1 = float(loss_fn(x))
    # floor is nonzero (noise-id collisions with labels are irreducible)
    assert l1 < l0 * 0.45, (l0, l1)


def test_data_norm_from_accumulators():
    x = jnp.asarray([[2.0, 4.0]])
    bs = jnp.asarray(10.0)
    bsum = jnp.asarray([20.0, 40.0])      # mean = [2, 4]
    bsq = jnp.asarray([50.0, 170.0])      # var = 5-4=1, 17-16=1
    y = F.data_norm(x, bs, bsum, bsq, epsilon=0.0)
    np.testing.assert_allclose(np.asarray(y), [[0.0, 0.0]], atol=1e-5)


def test_fd_gradients_new_ops():
    """Finite-difference gradient checks (OpTest check_grad pattern) for
    the round-2 op additions."""
    from op_test import check_grad

    rs = np.random.RandomState(0)

    # focal loss wrt logits
    logit = rs.randn(6).astype(np.float64)
    label = (rs.rand(6) > 0.5).astype(np.float64)
    check_grad(lambda lg: F.sigmoid_focal_loss(lg, jnp.asarray(label),
                                               reduction="sum"), [logit])

    # dice loss wrt probabilities (kept away from 0/1 corners)
    pred = (0.2 + 0.6 * rs.rand(2, 8)).astype(np.float64)
    lab = (rs.rand(2, 8) > 0.5).astype(np.float64)
    check_grad(lambda p: F.dice_loss(p, jnp.asarray(lab)), [pred])

    # hsigmoid wrt features and node weights
    x = rs.randn(4, 6).astype(np.float64)
    w = rs.randn(7, 6).astype(np.float64)
    y = rs.randint(0, 8, (4,))
    check_grad(lambda xx, ww: F.hsigmoid_loss(
        xx, jnp.asarray(y), ww, num_classes=8, reduction="sum"),
        [x, w], wrt=(0, 1))

    # grid_sample wrt both input and grid: bilinear grads are piecewise —
    # FD must not straddle a lattice point, so pick unnormalized coords
    # with fractional parts well inside (0, 1) and map back to [-1, 1]
    img = rs.randn(1, 2, 5, 5).astype(np.float64)
    frac_coords = np.array([0.4, 1.6, 2.5, 3.4, 1.35, 2.65, 0.55, 3.45,
                            1.5])[:9].reshape(3, 3)
    gx = (frac_coords / 4.0) * 2.0 - 1.0            # W=5 → denom 4
    gy = (frac_coords.T / 4.0) * 2.0 - 1.0
    grid = np.stack([gx, gy], axis=-1)[None].astype(np.float64)
    check_grad(lambda im, g: F.grid_sample(im, g), [img, grid],
               wrt=(0, 1))

    # selu / softshrink elementwise (away from kinks)
    x1 = (rs.randn(16) + np.sign(rs.randn(16)) * 0.6).astype(np.float64)
    check_grad(F.selu, [x1])
    check_grad(lambda v: F.softshrink(v, 0.3), [x1])

    # margin ranking
    a = rs.randn(5).astype(np.float64)
    b = rs.randn(5).astype(np.float64) + 3.0  # away from the hinge kink
    lab2 = np.ones(5)
    check_grad(lambda u, v: F.margin_ranking_loss(
        u, v, jnp.asarray(lab2), margin=0.1, reduction="sum"),
        [a, b], wrt=(0, 1))
