"""Layer & functional op tests, OpTest-style (golden + numeric grad)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.core import rng
from paddle_tpu.nn import functional as F

from op_test import check_grad, check_output


def test_linear_matches_numpy():
    layer = nn.Linear(6, 3)
    x = np.random.randn(5, 6).astype(np.float32)
    y = layer(jnp.asarray(x))
    ref = x @ np.asarray(layer.weight) + np.asarray(layer.bias)
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


def test_layer_norm_golden_and_grad():
    x = np.random.randn(4, 8).astype(np.float32)
    w = np.random.randn(8).astype(np.float32)
    b = np.random.randn(8).astype(np.float32)

    def ref(x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (np.asarray(x) - mu) / np.sqrt(var + 1e-5) * w + b

    check_output(lambda x, w, b: F.layer_norm(x, w, b), ref,
                 [jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)],
                 rtol=1e-4, atol=1e-5)
    check_grad(lambda x, w, b: F.layer_norm(x, w, b),
               [x, w, b], wrt=(0, 1, 2))


def test_rms_norm_grad():
    x = np.random.randn(3, 16).astype(np.float32)
    w = np.random.randn(16).astype(np.float32)
    check_grad(lambda x, w: F.rms_norm(x, w), [x, w], wrt=(0, 1))


def test_softmax_cross_entropy_golden():
    logits = np.random.randn(6, 10).astype(np.float32)
    labels = np.random.randint(0, 10, (6,))

    def ref(lg, lb):
        e = np.exp(lg - lg.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        return -np.log(p[np.arange(6), np.asarray(lb)])

    check_output(lambda lg, lb: F.softmax_with_cross_entropy(lg, lb), ref,
                 [jnp.asarray(logits), jnp.asarray(labels)],
                 rtol=1e-5, atol=1e-6)
    check_grad(lambda lg: F.softmax_with_cross_entropy(
        lg, jnp.asarray(labels)), [logits])


def test_cross_entropy_ignore_index():
    logits = jnp.asarray(np.random.randn(4, 5).astype(np.float32))
    labels = jnp.asarray([1, -100, 3, -100])
    loss = F.cross_entropy(logits, labels, ignore_index=-100)
    # mean over the 2 valid entries only
    per = F.softmax_with_cross_entropy(logits, labels, ignore_index=-100)
    assert float(per[1]) == 0.0
    np.testing.assert_allclose(float(loss),
                               float((per[0] + per[2]) / 2), rtol=1e-6)


def test_dropout_needs_key_and_scales():
    x = jnp.ones((100, 100))
    with pytest.raises(ValueError):
        F.dropout(x, 0.5, training=True)
    with rng.stream(jax.random.PRNGKey(0)):
        y = F.dropout(x, 0.5, training=True)
    keep_frac = float(jnp.mean((y > 0).astype(jnp.float32)))
    assert 0.45 < keep_frac < 0.55
    # inverted dropout preserves expectation
    assert 0.9 < float(jnp.mean(y)) < 1.1
    # eval mode = identity
    np.testing.assert_allclose(F.dropout(x, 0.5, training=False), x)


def test_attention_causal_masks_future():
    B, T, H, D = 2, 6, 2, 8
    q = jnp.asarray(np.random.randn(B, T, H, D).astype(np.float32))
    k, v = q, q
    out = F.scaled_dot_product_attention(q, k, v, causal=True,
                                         use_pallas="never")
    # position 0 attends only to itself -> output = v[0]
    np.testing.assert_allclose(out[:, 0], v[:, 0], rtol=1e-5, atol=1e-5)


def test_attention_gqa_equals_repeated_kv():
    B, T, D = 2, 4, 8
    q = jnp.asarray(np.random.randn(B, T, 4, D).astype(np.float32))
    k = jnp.asarray(np.random.randn(B, T, 2, D).astype(np.float32))
    v = jnp.asarray(np.random.randn(B, T, 2, D).astype(np.float32))
    out = F.scaled_dot_product_attention(q, k, v, causal=True,
                                         use_pallas="never")
    k2 = jnp.repeat(k, 2, axis=2)
    v2 = jnp.repeat(v, 2, axis=2)
    ref = F.scaled_dot_product_attention(q, k2, v2, causal=True,
                                         use_pallas="never")
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_rope_preserves_norm_and_relative():
    B, T, H, D = 1, 8, 2, 16
    x = jnp.asarray(np.random.randn(B, T, H, D).astype(np.float32))
    cos, sin = F.rotary_embedding(jnp.arange(T), D)
    y = F.apply_rotary(x, cos, sin)
    np.testing.assert_allclose(jnp.linalg.norm(y, axis=-1),
                               jnp.linalg.norm(x, axis=-1),
                               rtol=1e-5, atol=1e-5)


def test_mha_cache_matches_full():
    attn = nn.MultiHeadAttention(16, 4, use_rope=True)
    x = jnp.asarray(np.random.randn(2, 5, 16).astype(np.float32))
    full = attn(x, causal=True)
    cache = attn.init_cache(2)
    outs = []
    for t in range(5):
        o, cache = attn(x[:, t:t + 1], causal=False, cache=cache)
        outs.append(o)
    np.testing.assert_allclose(full, jnp.concatenate(outs, axis=1),
                               rtol=1e-4, atol=1e-4)


def test_batchnorm_state_tape():
    bn = nn.BatchNorm2D(3, momentum=0.5)
    x = jnp.asarray(np.random.randn(4, 3, 2, 2).astype(np.float32) * 2 + 1)
    with nn.state_tape() as tape:
        y = bn(x, training=True)
    assert len(tape) == 1
    bn2 = nn.merge_state(bn, tape)
    # running mean moved toward batch mean
    batch_mean = np.asarray(x).mean(axis=(0, 2, 3))
    np.testing.assert_allclose(bn2.running_mean, 0.5 * batch_mean, rtol=1e-4)
    # training output is standardized
    np.testing.assert_allclose(np.asarray(y).mean(axis=(0, 2, 3)),
                               np.zeros(3), atol=1e-5)


def test_conv2d_matches_naive():
    conv = nn.Conv2D(2, 3, 3, padding=1)
    x = jnp.asarray(np.random.randn(1, 2, 5, 5).astype(np.float32))
    y = conv(x)
    assert y.shape == (1, 3, 5, 5)
    # compare against explicit im2col computation at one position
    w = np.asarray(conv.weight)
    xp = np.pad(np.asarray(x), ((0, 0), (0, 0), (1, 1), (1, 1)))
    patch = xp[0, :, 2:5, 2:5]
    expect = (w * patch[None]).sum(axis=(1, 2, 3)) + np.asarray(conv.bias)
    np.testing.assert_allclose(y[0, :, 2, 2], expect, rtol=1e-4, atol=1e-4)


def test_lstm_shapes_and_grad_flow():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = jnp.asarray(np.random.randn(3, 7, 4).astype(np.float32))
    out, states = lstm(x)
    assert out.shape == (3, 7, 8)
    assert len(states) == 2

    def loss(m):
        y, _ = m(x)
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(lstm)
    gn = float(jnp.sqrt(sum(jnp.sum(l ** 2)
                            for l in jax.tree_util.tree_leaves(g))))
    assert gn > 0


def test_transformer_encoder_forward():
    enc = nn.TransformerEncoder(
        lambda: nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0), 2)
    x = jnp.asarray(np.random.randn(2, 5, 16).astype(np.float32))
    y = enc(x)
    assert y.shape == (2, 5, 16)


def test_sequential_threads_training_flag():
    model = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5), nn.Linear(4, 2))
    x = jnp.ones((2, 4))
    # eval works without key
    y = model(x, training=False)
    assert y.shape == (2, 2)
    with rng.stream(jax.random.PRNGKey(0)):
        y2 = model(x, training=True)
    assert y2.shape == (2, 2)


def test_conv2d_transpose_output_size():
    # classic 2x upsampler: k=4, s=2, p=1 -> H_out = 2*H_in
    deconv = nn.Conv2DTranspose(3, 5, 4, stride=2, padding=1)
    x = jnp.ones((1, 3, 8, 8), jnp.float32)
    y = deconv(x)
    assert y.shape == (1, 5, 16, 16)
    # adjoint property: <conv(a), b> == <a, conv_T(b)>. conv maps 5ch->3ch,
    # its transpose maps 3ch->5ch; layouts [O=3,I=5,kh,kw] vs [in=3,out=5,..]
    # line up directly.
    conv = nn.Conv2D(5, 3, 4, stride=2, padding=1, bias=False)
    deconv2 = nn.Conv2DTranspose(3, 5, 4, stride=2, padding=1, bias=False)
    deconv2 = deconv2.replace(weight=conv.weight)
    a = jnp.asarray(np.random.randn(1, 5, 16, 16).astype(np.float32))
    b = jnp.asarray(np.random.randn(1, 3, 8, 8).astype(np.float32))
    lhs = jnp.sum(conv(a) * b)
    rhs = jnp.sum(a * deconv2(b))
    np.testing.assert_allclose(float(lhs), float(rhs), rtol=1e-4)


def test_avg_pool_exclusive_padding():
    x = jnp.ones((1, 1, 4, 4))
    # exclusive (reference default): padded borders still average to 1
    y = F.avg_pool2d(x, 3, stride=1, padding=1)
    np.testing.assert_allclose(y, jnp.ones_like(y), rtol=1e-6)
    # inclusive: corner window has 4 real cells / 9
    y2 = F.avg_pool2d(x, 3, stride=1, padding=1, exclusive=False)
    np.testing.assert_allclose(float(y2[0, 0, 0, 0]), 4 / 9, rtol=1e-6)


def test_group_norm_bias_without_weight():
    x = jnp.asarray(np.random.randn(2, 4, 3, 3).astype(np.float32))
    b = jnp.asarray(np.arange(4, dtype=np.float32))
    y = F.group_norm(x, 2, weight=None, bias=b)
    y0 = F.group_norm(x, 2, weight=None, bias=None)
    np.testing.assert_allclose(y, y0 + b.reshape(1, 4, 1, 1), rtol=1e-5)


def test_embedding_padding_idx_zero_forward_and_grad():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = jnp.asarray([0, 3, 0, 7])
    out = emb(ids)
    np.testing.assert_allclose(np.asarray(out[0]), np.zeros(4), atol=0)
    np.testing.assert_allclose(np.asarray(out[2]), np.zeros(4), atol=0)

    def loss_fn(m):
        return jnp.sum(m(ids) ** 2)

    grads = jax.grad(loss_fn)(emb)
    g = np.asarray(grads.weight)
    # padding row gradient must stay exactly zero (reference semantics)
    np.testing.assert_allclose(g[0], np.zeros(4), atol=0)
    assert np.abs(g[3]).sum() > 0


def test_cross_entropy_soft_label_weight():
    logits = jnp.asarray(np.random.RandomState(0).randn(5, 4).astype(np.float32))
    hard = np.random.RandomState(1).randint(0, 4, (5,))
    soft = np.eye(4, dtype=np.float32)[hard]
    w = jnp.asarray([0.5, 2.0, 1.0, 0.25])
    # one-hot soft labels with weights must match the hard-label weighted path
    got = F.cross_entropy(logits, jnp.asarray(soft), soft_label=True,
                          weight=w, reduction="mean")
    want = F.cross_entropy(logits, jnp.asarray(hard), soft_label=False,
                           weight=w, reduction="mean")
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    for red in ("sum", "none"):
        got = F.cross_entropy(logits, jnp.asarray(soft), soft_label=True,
                              weight=w, reduction=red)
        want = F.cross_entropy(logits, jnp.asarray(hard), soft_label=False,
                               weight=w, reduction=red)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)
