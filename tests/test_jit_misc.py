"""paddle.jit shim, regularizers, FLOPs counter, MobileNetV1."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import jit, nn, regularizer
from paddle_tpu import optimizer as optim


def test_to_static_compiles_and_runs():
    calls = []

    @jit.to_static
    def f(x):
        calls.append(1)
        return x * 2 + 1

    y = f(jnp.ones(4))
    np.testing.assert_allclose(np.asarray(y), 3 * np.ones(4))
    f(jnp.ones(4))
    assert len(calls) == 1  # traced once: it IS compiled


def test_to_static_input_spec_pretraces():
    @jit.to_static(input_spec=[jit.InputSpec([2, 3], "float32")])
    def g(x):
        return x.sum(axis=1)

    out = g(jnp.ones((2, 3)))
    assert out.shape == (2,)
    with pytest.raises(ValueError, match="dynamic dims"):
        jit.InputSpec([None, 3])


def test_jit_save_load_roundtrip(tmp_path):
    def f(x):
        return jnp.tanh(x) @ jnp.ones((4, 2))

    spec = [jit.InputSpec([3, 4], "float32")]
    jit.save(f, str(tmp_path / "fn"), spec)
    pred = jit.load(str(tmp_path / "fn"))
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(pred.run(x)),
                               np.asarray(f(jnp.asarray(x))), rtol=1e-6)


def test_l2_decay_matches_float_weight_decay():
    paddle_tpu.seed(0)
    g = jnp.asarray([1.0, -1.0])
    p = jnp.asarray([2.0, 3.0])
    o1 = optim.Momentum(0.1, weight_decay=0.01)
    o2 = optim.Momentum(0.1, weight_decay=regularizer.L2Decay(0.01))
    u1, _ = o1.update(g, o1.init(p), p)
    u2, _ = o2.update(g, o2.init(p), p)
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), rtol=1e-6)


def test_l1_decay_adds_sign_term():
    g = jnp.zeros(3)
    p = jnp.asarray([2.0, -3.0, 0.0])
    o = optim.SGD(1.0, weight_decay=regularizer.L1Decay(0.5))
    u, _ = o.update(g, o.init(p), p)
    np.testing.assert_allclose(np.asarray(u), [-0.5, 0.5, 0.0], rtol=1e-6)


def test_flops_counter_linear():
    from paddle_tpu.hapi import flops

    layer = nn.Linear(64, 32, bias=False)
    n = flops(layer, jnp.ones((8, 64)))
    # 2 * B * I * O multiply-adds
    expected = 2 * 8 * 64 * 32
    assert 0.5 * expected <= n <= 2 * expected, (n, expected)


def test_mobilenet_v1_forward():
    from paddle_tpu.vision.models import MobileNetV1

    paddle_tpu.seed(0)
    m = MobileNetV1(num_classes=10, scale=0.25)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 32, 32)
                    .astype(np.float32))
    out = m(x)
    assert out.shape == (2, 10)
    assert np.isfinite(np.asarray(out)).all()


def test_summary_counts_params_and_buffers():
    import paddle_tpu as P

    paddle_tpu.seed(0)
    m = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.Linear(8, 2))
    out = P.summary(m)
    assert "Total params: 90" in out
    assert "trainable 74" in out and "buffers 16" in out
