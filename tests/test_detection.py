"""Detection op family + PP-YOLOE model tests.

Golden outputs against independent numpy ports of the reference kernels
(``paddle/fluid/operators/detection/*``) via the OpTest pattern
(``tests/op_test.py``), FD gradients for the differentiable ops, and a
train-to-falling-loss smoke for the PP-YOLOE-class model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.vision import ops as V
from tests.op_test import check_grad, check_output


def np_iou(a, b, normalized=True):
    off = 0.0 if normalized else 1.0
    out = np.zeros((a.shape[0], b.shape[0]), np.float64)
    for i in range(a.shape[0]):
        for j in range(b.shape[0]):
            iw = min(a[i, 2], b[j, 2]) - max(a[i, 0], b[j, 0]) + off
            ih = min(a[i, 3], b[j, 3]) - max(a[i, 1], b[j, 1]) + off
            inter = max(iw, 0.0) * max(ih, 0.0)
            aa = max(a[i, 2] - a[i, 0] + off, 0) * \
                max(a[i, 3] - a[i, 1] + off, 0)
            ab = max(b[j, 2] - b[j, 0] + off, 0) * \
                max(b[j, 3] - b[j, 1] + off, 0)
            u = aa + ab - inter
            out[i, j] = inter / u if u > 0 else 0.0
    return out


def test_box_iou_golden():
    rs = np.random.RandomState(0)
    a = np.sort(rs.rand(5, 4).astype(np.float32) * 50, axis=-1)[:, [0, 1, 2, 3]]
    a = np.stack([a[:, 0], a[:, 1], a[:, 2], a[:, 3]], -1)
    b = np.sort(rs.rand(7, 4).astype(np.float32) * 50, axis=-1)
    for norm in (True, False):
        got = V.box_iou_xyxy(jnp.asarray(a), jnp.asarray(b), normalized=norm)
        np.testing.assert_allclose(np.asarray(got), np_iou(a, b, norm),
                                   rtol=1e-5, atol=1e-6)


def test_yolo_box_golden():
    """Against a direct numpy port of GetYoloBox/CalcDetectionBox
    (reference detection/yolo_box_op.h)."""
    rs = np.random.RandomState(1)
    N, A, C, H, W = 2, 2, 3, 4, 5
    anchors = [10, 13, 16, 30]
    down = 32
    x = rs.randn(N, A * (5 + C), H, W).astype(np.float32)
    img = np.array([[320, 480], [256, 256]], np.int32)
    conf_t = 0.3

    boxes, scores = V.yolo_box(jnp.asarray(x), jnp.asarray(img), anchors, C,
                               conf_t, down, clip_bbox=True, scale_x_y=1.2)

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    xr = x.reshape(N, A, 5 + C, H, W)
    ref_boxes = np.zeros((N, H * W * A, 4), np.float64)
    ref_scores = np.zeros((N, H * W * A, C), np.float64)
    bias = -0.5 * (1.2 - 1.0)
    for n in range(N):
        ih, iw = img[n]
        for a in range(A):
            for i in range(H):
                for j in range(W):
                    conf = sig(xr[n, a, 4, i, j])
                    idx = (i * W + j) * A + a
                    if conf < conf_t:
                        continue
                    cx = (j + sig(xr[n, a, 0, i, j]) * 1.2 + bias) * iw / W
                    cy = (i + sig(xr[n, a, 1, i, j]) * 1.2 + bias) * ih / H
                    bw = np.exp(xr[n, a, 2, i, j]) * anchors[2 * a] * iw \
                        / (down * W)
                    bh = np.exp(xr[n, a, 3, i, j]) * anchors[2 * a + 1] \
                        * ih / (down * H)
                    b = [cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2]
                    b[0] = max(b[0], 0)
                    b[1] = max(b[1], 0)
                    b[2] = min(b[2], iw - 1)
                    b[3] = min(b[3], ih - 1)
                    ref_boxes[n, idx] = b
                    ref_scores[n, idx] = conf * sig(xr[n, a, 5:, i, j])
    np.testing.assert_allclose(np.asarray(boxes), ref_boxes, rtol=1e-4,
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(scores), ref_scores, rtol=1e-4,
                               atol=1e-5)


def test_box_coder_roundtrip_and_golden():
    rs = np.random.RandomState(2)
    priors = np.abs(rs.rand(6, 4).astype(np.float32))
    priors[:, 2:] = priors[:, :2] + 0.5 + priors[:, 2:]
    targets = np.abs(rs.rand(6, 4).astype(np.float32))
    targets[:, 2:] = targets[:, :2] + 0.3 + targets[:, 2:]
    var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)

    enc = V.box_coder(jnp.asarray(priors), jnp.asarray(var),
                      jnp.asarray(targets), "encode_center_size")
    # decode the diagonal back: each target encoded against its own prior
    diag = jnp.stack([enc[i, i] for i in range(6)])
    dec = V.box_coder(jnp.asarray(priors), jnp.asarray(var), diag[:, None, :]
                      .repeat(6, 1), "decode_center_size")
    rec = np.stack([np.asarray(dec)[i, i] for i in range(6)])
    np.testing.assert_allclose(rec, targets, rtol=1e-4, atol=1e-4)


def test_bipartite_match_golden():
    """Reference bipartite_match_op.cc greedy global-argmax semantics."""
    sim = np.array([
        [0.8, 0.1, 0.3],
        [0.7, 0.9, 0.2],
    ], np.float32)
    idx, dist = V.bipartite_match(jnp.asarray(sim))
    # best global: (1,1)=0.9 -> then (0,0)=0.8; col 2 unmatched
    np.testing.assert_array_equal(np.asarray(idx), [0, 1, -1])
    np.testing.assert_allclose(np.asarray(dist), [0.8, 0.9, 0.0], rtol=1e-6)


def np_greedy_nms(boxes, scores, thr, top_k):
    order = np.argsort(-scores)
    keep = []
    for i in order:
        if scores[i] <= 0:
            continue
        ok = True
        for j in keep:
            if np_iou(boxes[i:i + 1], boxes[j:j + 1])[0, 0] > thr:
                ok = False
                break
        if ok:
            keep.append(i)
            if len(keep) >= top_k:
                break
    return keep


def test_multiclass_nms_matches_numpy_reference():
    rs = np.random.RandomState(3)
    M, C = 40, 3
    ctr = rs.rand(M, 2) * 80
    wh = rs.rand(M, 2) * 20 + 4
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], -1).astype(np.float32)
    scores = rs.rand(C, M).astype(np.float32)
    scores[scores < 0.2] = 0.0

    out, nvalid = V.multiclass_nms(jnp.asarray(boxes), jnp.asarray(scores),
                                   score_threshold=0.3, nms_top_k=20,
                                   keep_top_k=10, nms_threshold=0.45)
    out = np.asarray(out)
    # numpy reference: per-class greedy NMS then global top-k by score
    cand = []
    for c in range(C):
        s = scores[c].copy()
        s[s < 0.3] = 0.0
        for i in np_greedy_nms(boxes, s, 0.45, 20):
            cand.append((c, s[i], *boxes[i]))
    cand.sort(key=lambda t: -t[1])
    cand = cand[:10]
    assert int(nvalid) == len(cand)
    got_valid = out[out[:, 0] >= 0]
    np.testing.assert_allclose(
        got_valid[:, 1], [t[1] for t in cand], rtol=1e-5)
    np.testing.assert_array_equal(
        got_valid[:, 0].astype(int), [t[0] for t in cand])
    np.testing.assert_allclose(got_valid[:, 2:],
                               np.asarray([t[2:] for t in cand]), rtol=1e-5)


def test_matrix_nms_decay_semantics():
    """Two heavily-overlapping boxes + one far box: the overlapped
    lower-scored box is decayed by (1-iou)/(1-0), the far box untouched
    (reference matrix_nms_op.cc NMSMatrix)."""
    boxes = np.array([[0, 0, 10, 10], [1, 0, 11, 10], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([[0.9, 0.8, 0.7]], np.float32)
    out, nvalid = V.matrix_nms(jnp.asarray(boxes), jnp.asarray(scores),
                               score_threshold=0.1, post_threshold=0.0,
                               nms_top_k=3, keep_top_k=3)
    out = np.asarray(out)
    iou = np_iou(boxes[:1], boxes[1:2])[0, 0]
    assert int(nvalid) == 3
    np.testing.assert_allclose(
        sorted(out[:, 1], reverse=True),
        sorted([0.9, 0.8 * (1 - iou), 0.7], reverse=True), rtol=1e-5)


def test_roi_align_golden_and_grad():
    """Constant feature map → every bin equals the constant; plus FD
    gradient through the bilinear sampling."""
    feat = np.full((1, 2, 8, 8), 3.5, np.float32)
    rois = np.array([[1.0, 1.0, 6.0, 6.0]], np.float32)
    bidx = np.array([0], np.int32)
    out = V.roi_align(jnp.asarray(feat), jnp.asarray(rois),
                      jnp.asarray(bidx), 4, spatial_scale=1.0,
                      sampling_ratio=2)
    assert out.shape == (1, 2, 4, 4)
    np.testing.assert_allclose(np.asarray(out), 3.5, rtol=1e-6)

    rs = np.random.RandomState(4)
    feat = rs.randn(1, 2, 8, 8).astype(np.float32)
    with jax.enable_x64(True):
        check_grad(
            lambda f: V.roi_align(f, jnp.asarray(rois, jnp.float64),
                                  jnp.asarray(bidx), 3, sampling_ratio=2),
            [jnp.asarray(feat, jnp.float64)], wrt=(0,))


def test_anchor_generator_and_prior_box_shapes():
    anchors, var = V.anchor_generator((4, 6), [32, 64], [0.5, 1.0, 2.0],
                                      (16, 16))
    assert anchors.shape == (4, 6, 6, 4) and var.shape == anchors.shape
    # center of cell (0,0) is offset*stride
    ctr = np.asarray((anchors[0, 0, 0, :2] + anchors[0, 0, 0, 2:]) / 2)
    np.testing.assert_allclose(ctr, [8.0, 8.0], atol=1e-5)

    boxes, pvar = V.prior_box((3, 3), (300, 300), min_sizes=[30.0],
                              max_sizes=[60.0], aspect_ratios=[2.0])
    assert boxes.shape[-1] == 4 and boxes.shape[:2] == (3, 3)
    # priors: min, sqrt ratios (2, 1/2), sqrt(min*max) → 4 per cell
    assert boxes.shape[2] == 4


def test_distance_bbox_roundtrip():
    rs = np.random.RandomState(5)
    pts = rs.rand(10, 2).astype(np.float32) * 100
    dist = np.abs(rs.rand(10, 4)).astype(np.float32) * 20
    boxes = V.distance2bbox(jnp.asarray(pts), jnp.asarray(dist))
    back = V.bbox2distance(jnp.asarray(pts), boxes)
    np.testing.assert_allclose(np.asarray(back), dist, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# PP-YOLOE model
# ---------------------------------------------------------------------------

def _toy_batch(rs, n=2, size=64, n_cls=4, n_gt=3):
    imgs = rs.randn(n, 3, size, size).astype(np.float32) * 0.1
    gt_boxes = np.zeros((n, n_gt, 4), np.float32)
    gt_labels = np.full((n, n_gt), -1, np.int32)
    for i in range(n):
        k = rs.randint(1, n_gt + 1)
        for g in range(k):
            cx, cy = rs.rand(2) * (size - 24) + 12
            w, h = rs.rand(2) * 20 + 10
            gt_boxes[i, g] = [max(cx - w, 0), max(cy - h, 0),
                              min(cx + w, size), min(cy + h, size)]
            gt_labels[i, g] = rs.randint(0, n_cls)
    return (jnp.asarray(imgs), jnp.asarray(gt_boxes),
            jnp.asarray(gt_labels))


def test_ppyoloe_trains_loss_falls():
    from paddle_tpu import optimizer as optim
    from paddle_tpu.nn.stateful import state_tape, merge_state
    from paddle_tpu.vision.models import ppyoloe_tiny

    paddle_tpu.seed(0)
    rs = np.random.RandomState(0)
    model = ppyoloe_tiny(num_classes=4)
    imgs, gtb, gtl = _toy_batch(rs)
    opt = optim.Momentum(5e-4, momentum=0.9)
    opt_state = opt.init(model)

    @jax.jit
    def step(model, opt_state):
        def lf(m):
            with state_tape() as tape:
                loss = m.loss(imgs, gtb, gtl, training=True)
            return loss, dict(tape)
        (loss, tape), grads = jax.value_and_grad(lf, has_aux=True)(model)
        model, opt_state = opt.apply_gradients(model, grads, opt_state)
        model = merge_state(model, tape)
        return model, opt_state, loss

    losses = []
    for _ in range(8):
        model, opt_state, loss = step(model, opt_state)
        losses.append(float(loss))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


def test_ppyoloe_predict_fixed_shape():
    from paddle_tpu.vision.models import ppyoloe_tiny

    paddle_tpu.seed(0)
    model = ppyoloe_tiny(num_classes=4)
    imgs = jnp.asarray(np.random.RandomState(1).randn(2, 3, 64, 64),
                       jnp.float32)
    out, nvalid = jax.jit(lambda m, x: m.predict(x))(model, imgs)
    assert out.shape == (2, model.config.keep_top_k, 6)
    assert nvalid.shape == (2,)
    out = np.asarray(out)
    valid_rows = out[out[:, :, 0].astype(int) >= 0]
    # scores in [0, 1], labels in range
    assert (valid_rows[:, 1] >= 0).all() and (valid_rows[:, 1] <= 1).all()
    assert (valid_rows[:, 0] < 4).all()


def test_detector_loss_scoped_amp_parity():
    """Under an ambient bf16 autocast the detector scopes itself: convs
    run bf16 but decode/TAL/losses are pinned fp32 (amp.suspend), so the
    loss stays within bf16-forward tolerance of the fp32 loss (r3's
    whole-model autocast was both 15x slower and numerically looser)."""
    import paddle_tpu
    from paddle_tpu import amp
    from paddle_tpu.vision.models import ppyoloe_tiny

    paddle_tpu.seed(0)
    det = ppyoloe_tiny(num_classes=8)
    rs = np.random.RandomState(3)
    imgs = jnp.asarray(rs.randn(2, 3, 64, 64).astype(np.float32) * 0.1)
    gtb = jnp.asarray(
        np.array([[[4, 4, 30, 30], [20, 10, 60, 50]],
                  [[8, 8, 40, 40], [0, 0, 0, 0]]], np.float32))
    gtl = jnp.asarray(np.array([[1, 3], [5, -1]], np.int32))

    ref = float(det.loss(imgs, gtb, gtl, training=False))
    with amp.auto_cast(enable=True, dtype="bfloat16"):
        got = float(det.loss(imgs, gtb, gtl, training=False))
    assert np.isfinite(got)
    np.testing.assert_allclose(got, ref, rtol=2e-2)
