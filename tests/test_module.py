"""Core module-system tests: pytree registration, specs, masks, surgery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.core.module import (
    apply_updates, count_params, named_parameters, partition_specs,
    trainable_mask, tree_at,
)


def make_mlp():
    return nn.Sequential(
        nn.Linear(4, 8, pspec=P(None, "tp")),
        nn.ReLU(),
        nn.Linear(8, 2),
    )


def test_module_is_pytree():
    m = make_mlp()
    leaves = jax.tree_util.tree_leaves(m)
    # 2 weights + 2 biases
    assert len(leaves) == 4
    # round trip
    flat, treedef = jax.tree_util.tree_flatten(m)
    m2 = jax.tree_util.tree_unflatten(treedef, flat)
    assert isinstance(m2, nn.Sequential)
    y1 = m(jnp.ones((3, 4)))
    y2 = m2(jnp.ones((3, 4)))
    np.testing.assert_allclose(y1, y2)


def test_named_parameters_paths():
    m = make_mlp()
    names = dict(named_parameters(m)).keys()
    assert "layers.0.weight" in names
    assert "layers.2.bias" in names
    assert count_params(m) == 4 * 8 + 8 + 8 * 2 + 2


def test_jit_and_grad_through_module():
    m = make_mlp()
    x = jnp.ones((3, 4))

    @jax.jit
    def loss_fn(model, x):
        return jnp.sum(model(x) ** 2)

    g = jax.grad(loss_fn)(m, x)
    assert isinstance(g, nn.Sequential)
    assert g.layers[0].weight.shape == (4, 8)
    # static fields preserved in grad pytree
    assert g.layers[0].in_features == 4


def test_partition_specs():
    m = make_mlp()
    specs = partition_specs(m)
    assert specs.layers[0].weight == P(None, "tp")
    assert specs.layers[0].bias == P("tp")
    assert specs.layers[2].weight == P()


def test_trainable_mask_batchnorm():
    bn = nn.BatchNorm2D(3)
    mask = trainable_mask(bn)
    assert mask.weight is True
    assert mask.running_mean is False
    assert mask.running_var is False


def test_tree_at_surgery():
    m = make_mlp()
    new_w = jnp.zeros((4, 8))
    m2 = tree_at(lambda t: t.layers[0].weight, m, new_w)
    assert float(jnp.sum(jnp.abs(m2.layers[0].weight))) == 0.0
    # original untouched
    assert float(jnp.sum(jnp.abs(m.layers[0].weight))) > 0.0


def test_apply_updates_dtype_preserved():
    m = nn.Linear(2, 2, dtype=jnp.bfloat16)
    upd = jax.tree_util.tree_map(lambda p: jnp.ones_like(p, jnp.float32), m)
    m2 = apply_updates(m, upd)
    assert m2.weight.dtype == jnp.bfloat16


def test_static_list_rejected():
    class Bad(nn.Module):
        def __init__(self):
            self.config = [1, 2, 3]  # list static -> error

    with pytest.raises(TypeError):
        jax.tree_util.tree_leaves(Bad())


def test_strategy_roundtrip(tmp_path):
    s = paddle_tpu.DistributedStrategy()
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 4
    s.amp.enable = True
    p = tmp_path / "strategy.json"
    s.save(str(p))
    s2 = paddle_tpu.DistributedStrategy.load(str(p))
    assert s2.sharding.stage == 3
    assert s2.amp.enable is True
    assert s2.parallel_degrees()["fsdp"] == 4
