"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's multi-process-on-localhost emulation strategy
(reference ``tests/unittests/test_dist_base.py:642``) but device-faking via
XLA is stronger: all sharding/collective paths compile and execute in one
process (SURVEY.md §4 'Mocks/fakes').
"""

import os

# Must be set before jax initializes its backends. Note: in this environment
# the axon TPU plugin wins over the JAX_PLATFORMS *env var*, so the config
# update below (which does take effect) is the authoritative switch.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# NOTE: x64 is NOT enabled globally — the finite-difference gradient checks
# in op_test.py scope it with `jax.enable_x64()`. (Global x64 triggers an
# XLA CPU compiler abort in grad-of-shard_map-ring-attention graphs.)
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]
