"""paddle.distribution parity: Uniform / Normal / Categorical
(reference python/paddle/distribution.py) — analytic quantities checked
exactly, samplers checked statistically, log_prob FD-checked via grads.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distribution import Categorical, Normal, Uniform
from op_test import check_grad


def test_uniform_moments_and_support():
    u = Uniform(2.0, 6.0)
    s = np.asarray(u.sample((20000,), key=jax.random.PRNGKey(0)))
    assert s.min() >= 2.0 and s.max() < 6.0
    np.testing.assert_allclose(s.mean(), 4.0, atol=0.05)
    np.testing.assert_allclose(float(u.entropy()), np.log(4.0), rtol=1e-6)
    np.testing.assert_allclose(float(u.probs(3.0)), 0.25, rtol=1e-6)
    assert float(u.log_prob(7.0)) == -np.inf


def test_normal_logprob_entropy_kl():
    n = Normal(1.0, 2.0)
    # log N(x; 1, 2) at x=3: -(2^2)/(2*4) - log 2 - 0.5 log 2pi
    want = -0.5 - np.log(2.0) - 0.5 * np.log(2 * np.pi)
    np.testing.assert_allclose(float(n.log_prob(3.0)), want, rtol=1e-6)
    np.testing.assert_allclose(float(n.entropy()),
                               0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0),
                               rtol=1e-6)
    # KL(N0||N1) closed form vs Monte Carlo
    a, b = Normal(0.0, 1.0), Normal(1.0, 2.0)
    kl = float(a.kl_divergence(b))
    s = a.sample((200000,), key=jax.random.PRNGKey(1))
    mc = float(jnp.mean(a.log_prob(s) - b.log_prob(s)))
    np.testing.assert_allclose(kl, mc, atol=0.01)
    assert float(a.kl_divergence(a)) == 0.0


def test_normal_sample_statistics_and_grad():
    n = Normal(jnp.asarray([0.0, 5.0]), jnp.asarray([1.0, 0.5]))
    s = np.asarray(n.sample((50000,), key=jax.random.PRNGKey(2)))
    np.testing.assert_allclose(s.mean(0), [0.0, 5.0], atol=0.05)
    np.testing.assert_allclose(s.std(0), [1.0, 0.5], atol=0.05)
    # log_prob differentiable wrt parameters (FD)
    check_grad(
        lambda loc, scale: Normal(loc, scale).log_prob(jnp.asarray(0.7)),
        [np.array(0.3), np.array(1.3)], wrt=(0, 1))


def test_categorical_all():
    logits = jnp.log(jnp.asarray([[0.2, 0.3, 0.5], [0.6, 0.3, 0.1]]))
    c = Categorical(logits)
    np.testing.assert_allclose(
        np.asarray(c.probs(jnp.asarray([2, 0]))), [0.5, 0.6], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(c.log_prob(jnp.asarray([1, 1]))), np.log([0.3, 0.3]),
        rtol=1e-6)
    want_ent = [-(0.2 * np.log(0.2) + 0.3 * np.log(0.3)
                  + 0.5 * np.log(0.5)),
                -(0.6 * np.log(0.6) + 0.3 * np.log(0.3)
                  + 0.1 * np.log(0.1))]
    np.testing.assert_allclose(np.asarray(c.entropy()), want_ent,
                               rtol=1e-6)
    other = Categorical(jnp.zeros((2, 3)))
    kl = np.asarray(c.kl_divergence(other))
    p = np.asarray([[0.2, 0.3, 0.5], [0.6, 0.3, 0.1]])
    want_kl = (p * (np.log(p) - np.log(1 / 3))).sum(-1)
    np.testing.assert_allclose(kl, want_kl, rtol=1e-5)
    # empirical frequencies match probs
    s = np.asarray(c.sample((8000,), key=jax.random.PRNGKey(3)))
    freq0 = np.bincount(s[:, 0], minlength=3) / 8000
    np.testing.assert_allclose(freq0, [0.2, 0.3, 0.5], atol=0.02)


def test_categorical_masked_actions_finite():
    """-inf logits (action masking): entropy/KL stay finite, masked
    classes never sampled."""
    c = Categorical(jnp.asarray([0.0, -jnp.inf, 0.0]))
    np.testing.assert_allclose(float(c.entropy()), np.log(2.0), rtol=1e-6)
    other = Categorical(jnp.zeros((3,)))
    assert np.isfinite(float(c.kl_divergence(other)))
    s = np.asarray(c.sample((2000,), key=jax.random.PRNGKey(5)))
    assert not (s == 1).any()


def test_distribution_methods_jit():
    @jax.jit
    def f(loc):
        n = Normal(loc, 1.0)
        return n.entropy() + n.log_prob(0.0)

    assert np.isfinite(float(f(jnp.asarray(0.5))))
