"""SLO-aware, tenant-fair scheduler (``FLAGS_gen_sched``, hard-off).

The load-bearing contracts: with the flag off the engine holds no
scheduler and the default loop is byte-identical with zero hot-path
flag reads (spy-pinned); with it on, weighted-fair queueing converges
per-tenant admission shares to the configured quotas, interactive never
queues behind batch (priority-inversion regression), and a preempted
stream parks via the prompt-fold + ``rng_skip`` replay contract and
resumes byte-identically — greedy and sampled — through the ordinary
re-admission path.
"""

import time

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.serving.scheduler as sched_mod
from paddle_tpu.core import monitor
from paddle_tpu.core.flags import flag
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.serving import GenerationEngine
from paddle_tpu.serving.ledger import RequestLedger
from paddle_tpu.serving.metrics import MetricsHub
from paddle_tpu.serving.scheduler import (BATCH, BEST_EFFORT, INTERACTIVE,
                                          GenScheduler, classify)

pytestmark = [pytest.mark.gen, pytest.mark.sched]

VOCAB = 96
SAMPLE_KW = dict(temperature=0.8, top_k=7, top_p=0.9, seed=42)


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


def _sampled_ref(model, prompt, n):
    import jax
    return np.asarray(generate(
        model, prompt[None], n, temperature=SAMPLE_KW["temperature"],
        top_k=SAMPLE_KW["top_k"], top_p=SAMPLE_KW["top_p"],
        key=jax.random.PRNGKey(SAMPLE_KW["seed"])))[0, prompt.size:]


def _mk_sched(monkeypatch, **overrides):
    """A GenScheduler whose construction-time flag reads see
    ``overrides`` (name -> value) instead of the registry defaults."""
    real = sched_mod.flag
    monkeypatch.setattr(
        sched_mod, "flag",
        lambda name: overrides[name] if name in overrides else real(name))
    return GenScheduler()


class _FakeGen:
    """Just the attributes the scheduler reads/writes."""

    def __init__(self, tenant, pclass, prompt_len=8, new=8):
        self.prompt = np.zeros(prompt_len, np.int32)
        self.max_new_tokens = new
        self.tenant = tenant
        self.pclass = pclass
        self.created = time.monotonic()
        self.sched_seq = 0
        self.sched_vft = 0.0
        self.sched_ts = 0.0


def _cum_hist(values):
    h = monitor._Histogram()
    for v in values:
        h.observe(v)
    return h.summary(raw=True)


def _doc(hists):
    return {"status": "ok", "inflight": 0, "generators": {}, "stats": {},
            "histograms": {n: _cum_hist(v) for n, v in hists.items()}}


# -- classification ---------------------------------------------------------

def test_classify_aliases_and_default():
    assert classify("interactive") == INTERACTIVE
    assert classify("rt") == INTERACTIVE
    assert classify(" Realtime ") == INTERACTIVE
    assert classify(0) == INTERACTIVE
    assert classify("batch") == BATCH
    assert classify("best-effort") == BEST_EFFORT
    assert classify("be") == BEST_EFFORT
    assert classify(2) == BEST_EFFORT
    # absent / unknown traffic is batch, never dropped
    assert classify(None) == BATCH
    assert classify("???") == BATCH


# -- weighted-fair queueing -------------------------------------------------

def test_wfq_admission_converges_to_quota_shares(monkeypatch):
    """Saturating 2-tenant load, quotas 3:1, identical costs: the
    admission order the virtual-finish tags induce gives alice ~3 slots
    for every bob slot — regardless of (alternating) arrival order."""
    sched = _mk_sched(monkeypatch,
                      gen_sched_quotas="alice=3,bob=1")
    gens = []
    for _ in range(20):                      # saturating backlog
        for tenant in ("alice", "bob"):
            g = _FakeGen(tenant, BATCH)
            sched.on_enqueue(g)
            gens.append(g)
    served, first16 = [], []
    while gens:
        gens.sort(key=sched.order_key)
        head = gens.pop(0)
        sched.note_admitted(head, now=time.monotonic())
        served.append(head.tenant)
    first16 = served[:16]
    assert first16.count("alice") >= 11      # ~12 expected at 3:1
    assert first16.count("bob") >= 3         # throttled, never starved
    snap = sched.snapshot()
    assert snap["admitted"][BATCH] == 40
    assert snap["virtual_time"] > 0.0


def test_wfq_tags_are_backlog_local_not_global():
    """A tenant arriving late starts at CURRENT virtual time, not at
    zero — it cannot claim the whole engine to 'catch up'."""
    sched = GenScheduler()
    old = [_FakeGen("early", BATCH) for _ in range(4)]
    for g in old:
        sched.on_enqueue(g)
    for g in old[:2]:
        sched.note_admitted(g)
    late = _FakeGen("late", BATCH)
    sched.on_enqueue(late)
    # late's finish tag sits at/after the already-served frontier
    assert late.sched_vft >= min(g.sched_vft for g in old)


def test_quota_throttle_scales_weight_down_not_to_zero(monkeypatch):
    """A tenant holding chip-seconds far past its quota share gets its
    WFQ weight divided by the (capped) overuse ratio — later finish
    tags — but still makes progress."""
    class _Book:
        @staticmethod
        def snapshot():
            return {"hog": {"chip_seconds": 90.0},
                    "meek": {"chip_seconds": 10.0}}

    sched = _mk_sched(monkeypatch, gen_sched_quotas="hog=1,meek=3")
    sched.attach_book(_Book())
    hog, meek = _FakeGen("hog", BATCH), _FakeGen("meek", BATCH)
    sched.on_enqueue(hog)
    sched.on_enqueue(meek)
    assert hog.sched_vft > meek.sched_vft    # throttled behind meek
    assert np.isfinite(hog.sched_vft)        # but never starved
    assert sched.snapshot()["quota_throttles"] >= 1


# -- priority classes / inversion regression --------------------------------

def test_priority_inversion_interactive_sorts_ahead_of_backlog():
    """An interactive arrival behind a deep batch/best-effort backlog
    sorts strictly first — class rank dominates every fair-queue tag."""
    from collections import deque
    sched = GenScheduler()
    q = deque()
    for _ in range(10):
        g = _FakeGen("bulk", BATCH)
        sched.on_enqueue(g)
        q.append(g)
    be = _FakeGen("scav", BEST_EFFORT)
    sched.on_enqueue(be)
    q.append(be)
    it = _FakeGen("live", INTERACTIVE)
    sched.on_enqueue(it)
    q.append(it)                             # arrives LAST
    plan = sched.plan(q, [_FakeGen("busy", BATCH)])   # no free slot
    assert q[0] is it
    assert q[-1] is be                       # best-effort drains last
    assert plan.spec_budget == 0             # speculation shed for TTFT
    assert plan.prefill_chunk is not None    # chunk clamp while hot
    assert plan.kv_scale < 1.0


def test_plan_preempts_only_lower_class_occupants():
    from collections import deque
    sched = GenScheduler()
    it = _FakeGen("live", INTERACTIVE)
    sched.on_enqueue(it)
    q = deque([it])
    # occupied by batch -> preempt; occupied by interactive -> never
    assert sched.plan(q, [_FakeGen("bulk", BATCH)]).preempt is True
    assert sched.plan(q, [_FakeGen("live2", INTERACTIVE)]).preempt is False
    assert sched.plan(q, [None]).preempt is False   # free slot: admit
    # nothing interactive waiting: no preemption at all
    q2 = deque([_FakeGen("bulk", BATCH)])
    sched.on_enqueue(q2[0])
    assert sched.plan(q2, [_FakeGen("x", BEST_EFFORT)]).preempt is False


def test_choose_victims_strictly_lower_class_most_recent_first():
    sched = GenScheduler()
    b1, b2 = _FakeGen("t", BATCH), _FakeGen("t", BATCH)
    be = _FakeGen("t", BEST_EFFORT)
    it = _FakeGen("t", INTERACTIVE)
    b1.sched_ts, b2.sched_ts, be.sched_ts, it.sched_ts = 1.0, 3.0, 2.0, 4.0
    cands = [(0, b1), (1, b2), (2, be), (3, it)]
    # an interactive claimant never evicts a peer interactive
    v = sched.choose_victims(cands, INTERACTIVE, 2)
    assert [g for _s, g in v] == [b2, be]    # most recent eligible first
    # batch claims only best-effort
    v = sched.choose_victims(cands, BATCH, 5)
    assert [g for _s, g in v] == [be]
    assert sched.choose_victims(cands, BEST_EFFORT, 1) == []


# -- the one shed brain -----------------------------------------------------

def test_shed_start_class_aware_caps():
    sched = GenScheduler()                   # headroom default: 2
    qm = 4
    assert sched.shed_start(BATCH, 3, qm) is False
    assert sched.shed_start(BATCH, 4, qm) is True
    # interactive rides the headroom past the cap
    assert sched.shed_start(INTERACTIVE, 4, qm) is False
    assert sched.shed_start(INTERACTIVE, 5, qm) is False
    assert sched.shed_start(INTERACTIVE, 6, qm) is True
    # best-effort sheds at half the cap
    assert sched.shed_start(BEST_EFFORT, 1, qm) is False
    assert sched.shed_start(BEST_EFFORT, 2, qm) is True
    # unlimited queue stays unlimited for every class
    for c in (INTERACTIVE, BATCH, BEST_EFFORT):
        assert sched.shed_start(c, 10_000, 0) is False
    sheds = sched.snapshot()["sheds"]
    assert sheds[BATCH] == 1 and sheds[INTERACTIVE] == 1
    assert sheds[BEST_EFFORT] == 1


def test_wire_gate_admits_interactive_within_headroom_only():
    sched = GenScheduler()                   # headroom default: 2
    assert sched.wire_gate({"pc": "interactive"}, 4, 4) is True
    assert sched.wire_gate({"pc": "interactive"}, 5, 4) is True
    assert sched.wire_gate({"pc": "interactive"}, 6, 4) is False
    assert sched.wire_gate({"pc": "batch"}, 4, 4) is False
    assert sched.wire_gate({}, 4, 4) is False
    assert sched.wire_gate(None, 4, 4) is False
    assert sched.snapshot()["sheds"][BATCH] >= 3


# -- SLO burn plumbing ------------------------------------------------------

def test_burn_rates_per_tenant_dimension_reads_the_split_series():
    """``burn_rates(..., tenant=)`` narrows to the ``<name>/<tn>``
    histogram the engine observes next to the fleet-wide one — a hot
    tenant's burn is visible even while the fleet looks healthy."""
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    hub.ingest({"ep": _doc({"gen/ttft_s": [0.01] * 5,
                            "gen/ttft_s/hot": [0.01]})})
    hub.ingest({"ep": _doc({"gen/ttft_s": [0.01] * 10,
                            "gen/ttft_s/hot": [0.01] + [2.0] * 5})})
    assert hub.burn_rates("gen/ttft_s", 0.5, 0.1) == (0.0, 0.0)
    fast, slow = hub.burn_rates("gen/ttft_s", 0.5, 0.1, tenant="hot")
    assert fast == pytest.approx(10.0) and slow == pytest.approx(10.0)
    # an unknown tenant has no series: no traffic burns no budget
    assert hub.burn_rates("gen/ttft_s", 0.5, 0.1, tenant="cold") == \
        (0.0, 0.0)


def test_infer_bypass_fires_on_per_tenant_burn():
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    hub.ingest({"ep": _doc({"gen/ttft_s": [0.01] * 5,
                            "gen/ttft_s/hot": [0.01]})})
    hub.ingest({"ep": _doc({"gen/ttft_s": [0.01] * 10,
                            "gen/ttft_s/hot": [0.01] + [2.0] * 5})})
    sched = GenScheduler()
    assert sched.infer_bypass("hot") is False   # no hub: never bypass
    sched.attach_hub(hub, slo_s=0.5, budget=0.1)
    assert sched.infer_bypass("hot") is True
    assert sched.infer_bypass(None) is False    # fleet-wide is clean
    assert sched.infer_bypass("cold") is False


# -- live queue-wait booking (satellite: ledger) ----------------------------

class _LedgerGen:
    """Just the attributes RequestLedger reads."""

    def __init__(self, created):
        self.gen_id, self.tenant = "g1", "t"
        self.created = created
        self.admitted_ts = self.first_tok_ts = self.done_ts = 0.0
        self.prompt = np.zeros(4, np.int32)
        self.tokens = [1, 2]
        self.chip_s = 0.0
        self.rng_skip = 0
        self.spec_proposed = self.spec_accepted = 0
        self.queue_booked = 0.0


def test_book_admission_books_live_delta_finalize_stays_exact():
    """Queue wait lands in the tenant book AT admission; a park +
    re-admission books only the delta; finalize books the remainder so
    the total equals the authoritative admit_wait_s exactly."""
    led = RequestLedger()
    t0 = time.monotonic()
    gen = _LedgerGen(t0)
    led.book_admission(gen, now=t0 + 1.0)
    assert led.book.snapshot()["t"]["queue_wait_s"] == pytest.approx(1.0)
    # parked, re-queued, re-admitted 2s later: only the delta books
    led.book_admission(gen, now=t0 + 3.0)
    assert led.book.snapshot()["t"]["queue_wait_s"] == pytest.approx(3.0)
    gen.admitted_ts = t0 + 3.0
    gen.first_tok_ts = t0 + 3.5
    gen.done_ts = t0 + 4.0
    rec = led.finalize(gen, "ok", now=t0 + 4.0)
    assert rec["phases"]["admit_wait_s"] == pytest.approx(3.0)
    # finalize's remainder is ~0: the live bookings already covered it
    assert led.book.snapshot()["t"]["queue_wait_s"] == pytest.approx(3.0)


# -- preempt / park / resume byte-identity ----------------------------------

def _run_preempt(model, eng, sample=False):
    """Saturate the 1-slot engine with a batch stream, preempt it with
    an interactive arrival, return (interactive_toks, batch_toks)."""
    kw = dict(SAMPLE_KW) if sample else {}
    p_batch = np.arange(1, 9, dtype=np.int32)
    p_inter = np.arange(10, 14, dtype=np.int32)
    gb = eng.start(p_batch, 16, tenant="bulk", priority="batch", **kw)
    # wait for the batch stream to be decoding (>=1 token emitted) so
    # the interactive arrival finds the slot occupied mid-stream
    doc = eng.poll(gb, start=0, wait_s=5.0)
    assert doc["tokens"], "batch stream never started decoding"
    gi = eng.start(p_inter, 6, tenant="live", priority="interactive", **kw)
    ti, ei = _drain(eng, gi, wait_s=0.2)
    tb, eb = _drain(eng, gb, wait_s=0.2)
    assert ei is None and eb is None
    return (np.asarray(ti, np.int32), np.asarray(tb, np.int32),
            p_inter, p_batch)


def test_preempt_park_resume_greedy_byte_identity(model):
    """Interactive preempts the only slot; the parked batch stream
    resumes through ordinary re-admission and BOTH streams match solo
    ``generate()`` byte-for-byte."""
    ref_b = np.asarray(generate(model, np.arange(1, 9, dtype=np.int32)[None],
                                16))[0, 8:]
    ref_i = np.asarray(generate(model, np.arange(10, 14,
                                                 dtype=np.int32)[None],
                                6))[0, 4:]
    with GenerationEngine(model, slots=1, max_len=64, paged=True,
                          page_tokens=8, pages=24, prefill_chunk=8,
                          step_wait_s=0.02, sched=True,
                          ledger=True) as eng:
        ti, tb, _pi, _pb = _run_preempt(model, eng)
        np.testing.assert_array_equal(ti, ref_i)
        np.testing.assert_array_equal(tb, ref_b)
        st = eng.stats()
        assert st["sched"]["preemptions"] >= 1
        assert st["sched"]["admitted"][INTERACTIVE] == 1
        # initial admission + at least one re-admission after the park
        assert st["sched"]["admitted"][BATCH] >= 2
        # every page not free is held by the prefix cache — none leaked
        assert st["pages"] - st["pages_free"] <= st["prefix_entries"]
        # live queue-wait attribution reached the tenant book
        tenants = eng.stats()["tenants"]
        assert "live" in tenants and "bulk" in tenants


def test_preempt_park_resume_sampled_byte_identity(model):
    """Same preemption, sampled decoding: the fold advances
    ``rng_skip`` by the folded tokens, so the resumed stream replays
    the per-token sampling-key schedule exactly."""
    ref_b = _sampled_ref(model, np.arange(1, 9, dtype=np.int32), 16)
    ref_i = _sampled_ref(model, np.arange(10, 14, dtype=np.int32), 6)
    with GenerationEngine(model, slots=1, max_len=64, paged=True,
                          page_tokens=8, pages=24, prefill_chunk=8,
                          step_wait_s=0.02, sched=True) as eng:
        ti, tb, _pi, _pb = _run_preempt(model, eng, sample=True)
        np.testing.assert_array_equal(ti, ref_i)
        np.testing.assert_array_equal(tb, ref_b)
        assert eng.stats()["sched"]["preemptions"] >= 1


# -- hard-off defaults ------------------------------------------------------

def test_defaults_off_no_scheduler_no_hot_path_flag_reads(model,
                                                          monkeypatch):
    """gen_sched defaults off: the engine builds NO scheduler, stats
    ship no "sched" block, the flag is read at construction only, and
    the default loop's tokens are byte-identical — a priority= hint is
    recorded-but-inert."""
    assert flag("gen_sched") is False
    import paddle_tpu.serving.engine as engine_mod

    reads: list[str] = []
    real_flag = engine_mod.flag

    def spy(name):
        reads.append(name)
        return real_flag(name)

    monkeypatch.setattr(engine_mod, "flag", spy)
    rs = np.random.RandomState(5)
    prompt = rs.randint(0, VOCAB, (6,)).astype(np.int32)
    ref = np.asarray(generate(model, prompt[None], 6))[0, 6:]
    with GenerationEngine(model, slots=2, max_len=32, paged=True,
                          page_tokens=8) as eng:
        assert "gen_sched" in reads
        assert eng._sched is None and eng._plan is None
        assert eng.sched is None
        assert "sched" not in eng.stats()
        reads.clear()
        toks, err = _drain(eng, eng.start(prompt, 6,
                                          priority="interactive"))
        assert err is None
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        assert not [r for r in reads if r.startswith("gen_sched")]
