"""hapi Model + DataLoader integration: the reference's book-test
equivalent — train a classifier end to end, evaluate, predict, checkpoint."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import metric, nn
from paddle_tpu import optimizer as optim
from paddle_tpu.data import (
    BatchSampler, DataLoader, DistributedBatchSampler, TensorDataset,
    random_split,
)
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.models.mlp import MLP
from paddle_tpu.vision.datasets import RandomImageDataset
from paddle_tpu.vision.models import LeNet


def test_dataloader_batching_and_workers():
    ds = TensorDataset(np.arange(10, dtype=np.float32).reshape(10, 1),
                       np.arange(10))
    dl = DataLoader(ds, batch_size=3, drop_last=False)
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == (3, 1)
    assert batches[-1][0].shape == (1, 1)
    # threaded prefetch gives identical content
    dl2 = DataLoader(ds, batch_size=3, num_workers=2)
    for (a, _), (b, _) in zip(batches, dl2):
        np.testing.assert_allclose(a, b)


def test_dataloader_shuffle_reshuffles_per_epoch():
    ds = TensorDataset(np.arange(32, dtype=np.float32))
    dl = DataLoader(ds, batch_size=32, shuffle=True)
    e1 = next(iter(dl))
    e2 = next(iter(dl))
    assert not np.allclose(e1, e2)
    assert sorted(e1.tolist()) == sorted(e2.tolist())


def test_distributed_batch_sampler_partitions():
    ds = TensorDataset(np.arange(16))
    seen = []
    for rank in range(4):
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                    rank=rank)
        for batch in s:
            seen.extend(batch)
    assert sorted(seen) == list(range(16))


def test_random_split_disjoint():
    ds = TensorDataset(np.arange(20))
    a, b = random_split(ds, [15, 5])
    assert len(a) == 15 and len(b) == 5
    assert set(a.indices).isdisjoint(b.indices)


def test_hapi_fit_evaluate_predict(tmp_path):
    paddle_tpu.seed(0)
    train = RandomImageDataset(128, (784,), num_classes=4, seed=0)
    val = RandomImageDataset(64, (784,), num_classes=4, seed=0)
    model = Model(MLP([784, 64, 4]))
    model.prepare(optimizer=optim.Adam(1e-2),
                  loss=nn.CrossEntropyLoss(),
                  metrics=[metric.Accuracy()])
    loader = DataLoader(train, batch_size=32, shuffle=True)
    val_loader = DataLoader(val, batch_size=32)
    history = model.fit(loader, val_loader, epochs=3, verbose=0)
    assert history[-1]["eval_acc" + "uracy"] > 0.9
    assert history[-1]["loss"] <= history[0]["loss"]
    preds = model.predict(val_loader)
    assert preds.shape == (64, 4)
    # save / load round trip
    model.save(str(tmp_path / "mlp"))
    m2 = Model(MLP([784, 64, 4]))
    m2.prepare(optimizer=optim.Adam(1e-2), loss=nn.CrossEntropyLoss())
    m2.load(str(tmp_path / "mlp"))
    p2 = m2.predict(val_loader)
    np.testing.assert_allclose(preds, p2, rtol=1e-5, atol=1e-5)


def test_hapi_lenet_with_batchnorm_free_path():
    paddle_tpu.seed(0)
    ds = RandomImageDataset(64, (1, 28, 28), num_classes=4, seed=1)
    model = Model(LeNet(num_classes=4))
    model.prepare(optimizer=optim.Adam(1e-2), loss=nn.CrossEntropyLoss())
    history = model.fit(DataLoader(ds, batch_size=16), epochs=2, verbose=0)
    assert history[-1]["loss"] < history[0]["loss"]


def test_hapi_resnet_updates_bn_stats():
    paddle_tpu.seed(0)
    from paddle_tpu.vision.models import resnet18

    ds = RandomImageDataset(16, (3, 32, 32), num_classes=2, seed=2)
    net = resnet18(num_classes=2)
    model = Model(net)
    model.prepare(optimizer=optim.SGD(1e-2), loss=nn.CrossEntropyLoss())
    before = np.asarray(net.bn1.running_mean).copy()
    model.fit(DataLoader(ds, batch_size=8), epochs=1, verbose=0)
    after = np.asarray(model.network_live.bn1.running_mean)
    assert not np.allclose(before, after), "BN stats did not update"


def test_early_stopping():
    stopper = EarlyStopping(monitor="loss", patience=1)
    stopper.on_epoch_end(0, {"loss": 1.0})
    stopper.on_epoch_end(1, {"loss": 1.5})
    stopper.on_epoch_end(2, {"loss": 1.6})
    assert stopper.stopped


def test_mamba_tiny_trains():
    paddle_tpu.seed(0)
    import jax
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    cfg = MambaConfig.tiny()
    m = MambaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 32))
                      .astype(np.int32))
    loss0 = float(m.loss(ids, ids, training=False))

    @jax.jit
    def step(m):
        g = jax.grad(lambda mm: mm.loss(ids, ids, training=False))(m)
        return jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg, m, g)

    for _ in range(10):
        m = step(m)
    loss1 = float(m.loss(ids, ids, training=False))
    assert loss1 < loss0


def test_selective_scan_matches_sequential():
    from paddle_tpu.models.mamba import selective_scan
    import jax

    rs = np.random.RandomState(0)
    B, T, Ei, N = 2, 6, 4, 3
    u = jnp.asarray(rs.randn(B, T, Ei).astype(np.float32))
    delta = jnp.asarray(np.abs(rs.randn(B, T, Ei)).astype(np.float32))
    A = -jnp.asarray(np.abs(rs.randn(Ei, N)).astype(np.float32))
    Bc = jnp.asarray(rs.randn(B, T, N).astype(np.float32))
    C = jnp.asarray(rs.randn(B, T, N).astype(np.float32))
    D = jnp.asarray(rs.randn(Ei).astype(np.float32))
    y = selective_scan(u, delta, A, Bc, C, D)

    # sequential reference
    h = np.zeros((B, Ei, N), np.float32)
    ys = []
    for t in range(T):
        dA = np.exp(np.asarray(delta[:, t])[..., None] * np.asarray(A))
        dBu = (np.asarray(delta[:, t]) * np.asarray(u[:, t]))[..., None] \
            * np.asarray(Bc[:, t])[:, None, :]
        h = dA * h + dBu
        ys.append(np.einsum("bin,bn->bi", h, np.asarray(C[:, t])))
    ref = np.stack(ys, 1) + np.asarray(u) * np.asarray(D)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_vision_model_shapes():
    paddle_tpu.seed(0)
    from paddle_tpu.vision.models import MobileNetV2, ViT, vgg11

    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 32, 32)
                    .astype(np.float32))
    vit = ViT(image_size=32, patch_size=8, dim=32, depth=2, heads=2,
              mlp_dim=64, num_classes=5)
    assert vit(x).shape == (2, 5)


def test_distributed_batch_sampler_tiny_dataset_even_shards():
    # dataset smaller than the replica count: every rank must still see the
    # same number of samples (tiled padding), or multi-host training hangs.
    ds = TensorDataset(np.arange(3))
    counts = []
    for rank in range(8):
        s = DistributedBatchSampler(ds, batch_size=1, num_replicas=8,
                                    rank=rank)
        counts.append(sum(len(b) for b in s))
    assert len(set(counts)) == 1 and counts[0] == 1


class _SlowDataset(TensorDataset):
    """10ms per item — models IO/decode latency (sleep releases the GIL,
    like real file reads)."""

    def __getitem__(self, i):
        import time
        time.sleep(0.01)
        return super().__getitem__(i)


def test_dataloader_workers_preserve_order():
    ds = TensorDataset(np.arange(64))
    dl = DataLoader(ds, batch_size=4, num_workers=4)
    got = np.concatenate([np.asarray(b) for b in dl])
    np.testing.assert_array_equal(got, np.arange(64))


def test_dataloader_workers_scale_throughput():
    import time
    ds = _SlowDataset(np.arange(64))

    def timed(workers):
        dl = DataLoader(ds, batch_size=4, num_workers=workers)
        t0 = time.perf_counter()
        n = sum(1 for _ in dl)
        assert n == 16
        return time.perf_counter() - t0

    serial = timed(0)
    parallel = timed(4)
    # 64 items * 10ms ≈ 0.64s serial; 4 workers should cut it >2x
    assert parallel < serial / 2, (serial, parallel)


def test_dataloader_process_workers():
    ds = TensorDataset(np.arange(32))
    dl = DataLoader(ds, batch_size=4, num_workers=2, worker_mode="process")
    got = np.concatenate([np.asarray(b) for b in dl])
    np.testing.assert_array_equal(got, np.arange(32))


def test_dataloader_worker_error_propagates():
    class Boom(TensorDataset):
        def __getitem__(self, i):
            if i == 7:
                raise RuntimeError("bad sample")
            return super().__getitem__(i)

    dl = DataLoader(Boom(np.arange(16)), batch_size=4, num_workers=2)
    with pytest.raises(RuntimeError, match="bad sample"):
        list(dl)


def test_hapi_eval_predict_sharded_on_mesh(devices8):
    """eval/predict inputs must carry the same dp batch sharding as the
    train step (VERDICT r1 weak #8: unsharded eval silently replicates)."""
    from paddle_tpu.parallel import mesh as M

    paddle_tpu.seed(0)
    mesh = M.create_mesh({"dp": 8})
    with M.MeshContext(mesh):
        model = Model(MLP([16, 32, 4]))
        model.prepare(optimizer=optim.Adam(1e-2),
                      loss=nn.CrossEntropyLoss())
        x = np.random.RandomState(0).randn(16, 16).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 4, (16,))
        model.train_batch(x, y)
        out, l = model.eval_batch(x, y)
        assert np.isfinite(l)
        sx, _ = model._shard_inputs(x, y)
        # input really sharded over dp, not replicated
        assert "dp" in str(sx.sharding.spec)
        assert len(sx.sharding.device_set) == 8
        preds = model.predict_batch(x)
        assert preds.shape == (16, 4)


def test_selective_scan_chunked_matches_full():
    """Chunked state-passing scan must be exact vs the one-shot scan,
    values and gradients (the memory-scaling path for long-context
    Mamba)."""
    import jax
    from paddle_tpu.models.mamba import selective_scan

    rs = np.random.RandomState(0)
    B, T, Ei, N = 2, 32, 4, 3
    u = jnp.asarray(rs.randn(B, T, Ei).astype(np.float32))
    delta = jnp.asarray(0.1 + np.abs(rs.randn(B, T, Ei)).astype(np.float32))
    A = jnp.asarray(-np.abs(rs.randn(Ei, N)).astype(np.float32))
    Bc = jnp.asarray(rs.randn(B, T, N).astype(np.float32))
    Cc = jnp.asarray(rs.randn(B, T, N).astype(np.float32))
    D = jnp.asarray(rs.randn(Ei).astype(np.float32))

    full = selective_scan(u, delta, A, Bc, Cc, D)
    for k in (4, 8, 16):
        chunked = selective_scan(u, delta, A, Bc, Cc, D, chunk_size=k)
        np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                                   rtol=2e-5, atol=2e-5)

    g_full = jax.grad(lambda uu: selective_scan(
        uu, delta, A, Bc, Cc, D).sum())(u)
    g_chunk = jax.grad(lambda uu: selective_scan(
        uu, delta, A, Bc, Cc, D, chunk_size=8).sum())(u)
    np.testing.assert_allclose(np.asarray(g_chunk), np.asarray(g_full),
                               rtol=2e-4, atol=2e-5)


def test_hapi_model_with_distributed_strategy(devices8):
    """hapi Model driving the fleet compiler with a real strategy
    (zero-2 over 8 devices) end to end: fit + evaluate + predict."""
    from paddle_tpu import DistributedStrategy
    from paddle_tpu.parallel import mesh as M

    paddle_tpu.seed(0)
    s = DistributedStrategy()
    s.sharding.enable = True
    s.sharding.stage = 2
    s.sharding.degree = 8
    with M.MeshContext(M.mesh_from_strategy(s)):
        train = RandomImageDataset(128, (784,), num_classes=4, seed=0)
        model = Model(MLP([784, 64, 4]), strategy=s)
        model.prepare(optimizer=optim.Adam(1e-2),
                      loss=nn.CrossEntropyLoss(),
                      metrics=[metric.Accuracy()])
        loader = DataLoader(train, batch_size=32, shuffle=True)
        history = model.fit(loader, epochs=2, verbose=0)
        # the toy task saturates within the first epoch (loss -> ~0), so
        # assert convergence itself rather than strict decrease
        assert all(np.isfinite(h["loss"]) for h in history)
        eval_logs = model.evaluate(DataLoader(train, batch_size=32),
                                   verbose=0)
        assert eval_logs["eval_accuracy"] > 0.95, eval_logs
        preds = model.predict(DataLoader(train, batch_size=32))
        assert preds.shape == (128, 4)
