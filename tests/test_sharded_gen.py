"""Sharded serving: tensor-parallel GenerationEngine byte-identity.

The load-bearing property is the same one every serving PR leans on,
now across device layouts: a generation through a mesh-backed engine —
params Megatron-split, KV cache/page pool sharded on the KV-head axis,
every compiled entry point carrying explicit in/out shardings — must be
BYTE-identical to the unsharded engine (and to solo ``generate``), for
greedy and sampled decode, contiguous and paged caches, speculation on
and off. That identity is what lets the router fail a stream over
between sharded and unsharded replicas with ``rng_skip`` resumption.

Runs on the conftest-forced 8-virtual-device CPU host
(``--xla_force_host_platform_device_count=8``): all sharding and
collective paths compile and execute for real in one process.
"""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import (
    POOL_KV_SPEC, STACKED_KV_SPEC, _draft_model_propose, generate,
    init_paged_cache, paged_gather, paged_scatter,
)
from paddle_tpu.serving import DeviceLayout, GenerationEngine

pytestmark = pytest.mark.sharded

VOCAB = 96


@pytest.fixture(scope="module")
def model():
    # 4 heads / 4 KV heads so tp=4 divides the head axes (the gen-suite
    # default of 2 KV heads only admits tp<=2)
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=4, num_kv_heads=4, max_seq_len=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft():
    paddle_tpu.seed(3)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=1,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _prompt(seed=0, n=9):
    return np.random.RandomState(seed).randint(0, VOCAB, (n,)).astype(
        np.int32)


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            assert doc["error"] is None, doc["error"]
            return toks


def _streams(engine, prompt):
    """One greedy + one sampled stream — the pair every identity
    assertion compares across layouts."""
    greedy = _drain(engine, engine.start(prompt, 10))
    sampled = _drain(engine, engine.start(prompt, 10, temperature=0.8,
                                          top_k=20, seed=3))
    return greedy, sampled


@pytest.fixture(scope="module")
def unsharded(model):
    """tp=0 reference streams + device block, per cache mode."""
    out = {}
    for paged in (False, True):
        with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                              paged=paged, page_tokens=8) as eng:
            out[paged] = (_streams(eng, _prompt()), eng.stats()["device"])
    return out


@pytest.mark.parametrize("tp", [1, 2, 4])
@pytest.mark.parametrize("paged", [False, True],
                         ids=["contiguous", "paged"])
def test_tp_byte_identity(model, unsharded, tp, paged):
    """Greedy AND sampled streams byte-identical to the unsharded
    engine at every tp degree, both cache modes — and the solo
    ``generate`` anchor holds transitively."""
    with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                          paged=paged, page_tokens=8, mesh_tp=tp) as eng:
        assert _streams(eng, _prompt()) == unsharded[paged][0]
    ref = np.asarray(generate(model, _prompt()[None], 10))[0, 9:]
    assert unsharded[paged][0][0] == [int(t) for t in ref]


@pytest.mark.parametrize("mode", ["ngram", "draft"])
def test_tp_spec_identity(model, draft, unsharded, mode):
    """Speculation composes with sharding unchanged: a tp=2 speculating
    engine (both drafters) emits the same streams as the plain tp=0
    engine — acceptance only changes step count, never tokens."""
    prompt = np.tile(_prompt(1, 4), 3)
    ref = None
    for tp in (0, 2):
        kw = {"draft_model": draft} if mode == "draft" else {}
        with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                              spec_k=4, spec_mode=mode, mesh_tp=tp,
                              **kw) as eng:
            got = _streams(eng, prompt)
        ref = got if ref is None else ref
        assert got == ref
    with GenerationEngine(model, slots=2, max_len=64,
                          queue_max=8) as plain:
        assert _streams(plain, prompt) == ref


def test_rng_skip_resumes_across_layouts(model):
    """The failover contract across layouts: a sampled stream started
    on a tp=2 engine resumes byte-identically on an UNSHARDED engine
    via prompt-replay + ``rng_skip`` (what RoutedClient does when a
    sharded replica dies mid-stream), and vice versa."""
    prompt = _prompt(2)
    kw = dict(temperature=0.9, top_k=24, seed=11)
    with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                          mesh_tp=2) as eng:
        full = _drain(eng, eng.start(prompt, 10, **kw))
    with GenerationEngine(model, slots=2, max_len=64, queue_max=8) as eng:
        resumed = _drain(eng, eng.start(
            np.concatenate([prompt, np.asarray(full[:4], np.int32)]),
            6, rng_skip=4, **kw))
    assert resumed == full[4:]


def test_paged_ops_under_named_sharding(model, devices8):
    """``paged_gather``/``paged_scatter`` bit-exact when the pool lives
    under ``NamedSharding`` on the KV-head axis (the engine's paged
    layout), vs the same ops on the unsharded pool."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    layout = DeviceLayout(2)
    proto = model.init_cache(1, 32)
    pool = init_paged_cache(proto, num_pages=6, page_tokens=8)
    table = jnp.asarray([3, 1, 5, 0], jnp.int32)
    chunk = tuple(
        jax.random.normal(jax.random.PRNGKey(i), c.shape[:3] + (8,)
                          + c.shape[4:], c.dtype)
        for i, c in enumerate(proto))
    ref_pool = paged_scatter(pool, table, chunk, 8, 8, length=5)
    ref_view = paged_gather(ref_pool, table)

    sh = NamedSharding(layout.mesh, POOL_KV_SPEC)
    spool = tuple(jax.device_put(p, sh) for p in pool)
    got_pool = paged_scatter(spool, table, chunk, 8, 8, length=5)
    got_view = paged_gather(got_pool, table)
    for r, g in zip(ref_pool + ref_view, got_pool + got_view):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_state_sharding_specs(model):
    """The layout's spec map matches the documented KV contract: the
    stacked contiguous leaf shards axis 3, the paged pool leaf axis 2,
    scalars replicate — and placed engine state reports per-device
    shards of 1/tp the KV bytes."""
    layout = DeviceLayout(2)
    assert STACKED_KV_SPEC[3] == "tp" and POOL_KV_SPEC[2] == "tp"
    with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                          mesh_tp=2) as eng:
        leaf = eng._state["cache"][0]
        assert leaf.sharding.spec == STACKED_KV_SPEC
        # Hkv axis actually split: each device holds half the heads
        shard = leaf.addressable_shards[0].data
        assert shard.shape[3] * 2 == leaf.shape[3]
        assert eng._state["tok"].sharding.is_fully_replicated
    with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                          paged=True, page_tokens=8, mesh_tp=2) as eng:
        leaf = eng._state["cache"][0]
        assert leaf.sharding.spec == POOL_KV_SPEC
        assert leaf.addressable_shards[0].data.shape[2] * 2 == \
            leaf.shape[2]
    assert layout.describe(1000)["kv_bytes_per_device"] == 500


def test_device_stats_block(model, unsharded):
    """stats()/health ship the topology: platform, device count, mesh
    axis sizes, and per-device KV bytes ~= 1/tp of the unsharded
    pool."""
    for paged in (False, True):
        ref = unsharded[paged][1]
        assert ref["devices"] == 1 and ref["mesh"] is None
        assert ref["kv_bytes_per_device"] == ref["kv_bytes"]
        with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                              paged=paged, page_tokens=8,
                              mesh_tp=2) as eng:
            dev = eng.stats()["device"]
        assert dev["platform"] == "cpu"
        assert dev["devices"] == 2 and dev["mesh"] == {"tp": 2}
        assert dev["kv_bytes"] == ref["kv_bytes"]
        assert dev["kv_bytes_per_device"] * 2 == ref["kv_bytes"]


def test_defaults_off_no_mesh_no_hot_path_flag_read(model, monkeypatch):
    """Hard-off discipline: the default engine builds NO mesh (layout is
    the identity), and ``gen_mesh_tp`` is never read on the decode hot
    path — only at construction."""
    import paddle_tpu.serving.engine as engine_mod

    reads: list[str] = []
    real_flag = engine_mod.flag

    def spy(name):
        reads.append(name)
        return real_flag(name)

    monkeypatch.setattr(engine_mod, "flag", spy)
    with GenerationEngine(model, slots=2, max_len=64,
                          queue_max=8) as eng:
        assert eng._layout.mesh is None and not eng._layout.sharded
        assert "gen_mesh_tp" in reads          # construction-time only
        reads.clear()
        _drain(eng, eng.start(_prompt(), 6))   # prefill + decode steps
        assert "gen_mesh_tp" not in reads


def test_draft_fn_constant_graph_and_bit_identity(model, draft):
    """Satellite: the draft lookahead's decode tail is a fori_loop —
    ONE traced body, so the jaxpr no longer grows with spec_k (the old
    unrolled build compiled K-1 forwards per bucket) — and its output
    is bit-identical to the eager reference drafter."""
    import jax
    import jax.numpy as jnp

    ctx = np.tile(_prompt(1, 4), 3)
    sizes = {}
    for K in (2, 8):
        with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                              spec_k=K, spec_mode="draft",
                              draft_model=draft) as eng:
            got = eng._draft_propose(ctx, K)
            ref = np.asarray(_draft_model_propose(draft, ctx, K))
            np.testing.assert_array_equal(got, ref[:K])
            # compile observability plumbing recorded the draft compile
            assert any(e == "draft" for e, _ in eng._compiled_seen)
            bucket = eng._bucket(ctx.size)
            fn = eng._build_draft_fn(bucket)
            jaxpr = jax.make_jaxpr(lambda p, t: fn(p, t))(
                jnp.zeros((bucket,), jnp.int32),
                jnp.asarray(ctx.size, jnp.int32))
            sizes[K] = len(jaxpr.jaxpr.eqns)
    assert sizes[2] == sizes[8], sizes


def test_mesh_tp_validates_head_divisibility(model):
    """tp must divide the head axes — caught loudly at construction,
    not as a silently pad-sharded cache."""
    paddle_tpu.seed(9)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=1,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    odd = LlamaForCausalLM(cfg)
    with pytest.raises(ValueError, match="num_kv_heads"):
        GenerationEngine(odd, slots=2, max_len=64, queue_max=8, mesh_tp=4)
