"""Fused LM-head ⊗ cross-entropy kernel (ops/pallas/linear_xent).

OpTest-style (reference ``tests/unittests/op_test.py:226``): outputs and
custom_vjp gradients of the Pallas kernels (interpret mode on CPU) vs a
dense jnp reference; the chunked pure-XLA variant against the same
reference; the F.linear_cross_entropy dispatch surface (padding,
ignore_index, reductions); and the restructured llama loss path
(full-T rows with left-shifted labels) vs the sliced dense formulation.
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.nn.functional as F

LX = importlib.import_module("paddle_tpu.ops.pallas.linear_xent")


def dense_ref(h, w, labels):
    """Per-row lse − selected-logit; out-of-range labels select 0."""
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32))
    lse = jax.nn.logsumexp(logits, axis=1)
    v = w.shape[1]
    safe = jnp.clip(labels, 0, v - 1)
    sel = jnp.take_along_axis(logits, safe[:, None].astype(jnp.int32),
                              axis=1)[:, 0]
    sel = jnp.where((labels >= 0) & (labels < v), sel, 0.0)
    return lse - sel


@pytest.mark.parametrize("n,e,v", [
    (24, 128, 384),     # n < row block (sublane-aligned)
    (256, 128, 256),    # exactly one row block
    (512, 256, 1280),   # multiple row and vocab blocks
])
def test_fused_matches_dense(n, e, v):
    rs = np.random.RandomState(0)
    h = jnp.asarray(rs.randn(n, e).astype(np.float32))
    w = jnp.asarray(0.1 * rs.randn(e, v).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, v, n).astype(np.int32))
    labels = labels.at[1].set(-100)   # ignore-style out-of-range row
    assert LX.supported(h, w, labels)

    out = LX.fused_linear_cross_entropy(h, w, labels)
    ref = dense_ref(h, w, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    mask = (labels >= 0).astype(jnp.float32)

    def loss_fused(h, w):
        per = LX.fused_linear_cross_entropy(h, w, labels)
        return jnp.sum(per * mask) / jnp.sum(mask)

    def loss_dense(h, w):
        return jnp.sum(dense_ref(h, w, labels) * mask) / jnp.sum(mask)

    gf = jax.grad(loss_fused, (0, 1))(h, w)
    gd = jax.grad(loss_dense, (0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gd[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gd[1]),
                               rtol=1e-4, atol=1e-5)


def test_chunked_matches_dense():
    rs = np.random.RandomState(1)
    n, e, v = 40, 64, 640
    h = jnp.asarray(rs.randn(n, e).astype(np.float32))
    w = jnp.asarray(0.1 * rs.randn(e, v).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, v, n).astype(np.int32))

    out = LX.chunked_linear_cross_entropy(h, w, labels, block_v=128)
    ref = dense_ref(h, w, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def loss_c(h, w):
        return jnp.mean(LX.chunked_linear_cross_entropy(h, w, labels,
                                                        block_v=128))

    def loss_d(h, w):
        return jnp.mean(dense_ref(h, w, labels))

    gc = jax.grad(loss_c, (0, 1))(h, w)
    gd = jax.grad(loss_d, (0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gc[0]), np.asarray(gd[0]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gc[1]), np.asarray(gd[1]),
                               rtol=1e-4, atol=1e-6)


def test_ignored_rows_have_zero_grad():
    rs = np.random.RandomState(2)
    n, e, v = 32, 128, 256
    h = jnp.asarray(rs.randn(n, e).astype(np.float32))
    w = jnp.asarray(0.1 * rs.randn(e, v).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, v, n).astype(np.int32))
    labels = labels.at[:8].set(-100)

    def loss(h):
        return F.linear_cross_entropy(h, w, labels, mode="fused")

    g = jax.grad(loss)(h)
    np.testing.assert_allclose(np.asarray(g[:8]), 0.0, atol=1e-12)
    assert float(jnp.max(jnp.abs(g[8:]))) > 0.0


def test_row_padding_path():
    """n = 44 is sublane-misaligned ((-44) % 8 == 4): the dispatch must
    pad rows, and gradients must flow correctly through the [:n] slice
    (padded rows are ignore-masked, so they contribute nothing)."""
    rs = np.random.RandomState(7)
    n, e, v = 44, 128, 256
    h = jnp.asarray(rs.randn(n, e).astype(np.float32))
    w = jnp.asarray(0.1 * rs.randn(e, v).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, v, n).astype(np.int32))

    def loss_f(h, w):
        return F.linear_cross_entropy(h, w, labels, mode="fused")

    def loss_d(h, w):
        return F.cross_entropy((h @ w).astype(jnp.float32), labels)

    np.testing.assert_allclose(float(loss_f(h, w)), float(loss_d(h, w)),
                               rtol=1e-5)
    gf = jax.grad(loss_f, (0, 1))(h, w)
    gd = jax.grad(loss_d, (0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gd[0]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gd[1]),
                               rtol=1e-4, atol=1e-6)


def test_unknown_mode_raises():
    h = jnp.zeros((8, 128), jnp.float32)
    w = jnp.zeros((128, 256), jnp.float32)
    lab = jnp.zeros((8,), jnp.int32)
    with pytest.raises(ValueError, match="unknown mode"):
        F.linear_cross_entropy(h, w, lab, mode="Fused")


@pytest.mark.parametrize("mode", ["fused", "chunked", "dense"])
def test_functional_modes_agree(mode):
    rs = np.random.RandomState(3)
    b, t, e, v = 2, 20, 128, 256
    h = jnp.asarray(rs.randn(b, t, e).astype(np.float32))
    w = jnp.asarray(0.1 * rs.randn(e, v).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, v, (b, t)).astype(np.int32))
    labels = labels.at[0, :3].set(-100)

    ref_logits = (h.reshape(-1, e) @ w).astype(jnp.float32)
    want = F.cross_entropy(ref_logits, labels.reshape(-1))
    got = F.linear_cross_entropy(h, w, labels, mode=mode)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    got_sum = F.linear_cross_entropy(h, w, labels, mode=mode,
                                     reduction="sum")
    want_sum = F.cross_entropy(ref_logits, labels.reshape(-1),
                               reduction="sum")
    np.testing.assert_allclose(float(got_sum), float(want_sum),
                               rtol=1e-5)

    got_none = F.linear_cross_entropy(h, w, labels, mode=mode,
                                      reduction="none")
    assert got_none.shape == labels.shape


def test_llama_loss_fused_path_matches_dense():
    """The restructured loss (full-T rows, left-shifted labels, final
    position ignore-masked) must equal the dense sliced formulation."""
    import dataclasses

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    import paddle_tpu
    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=256, hidden_size=64, num_layers=2)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(4)
    ids = jnp.asarray(rs.randint(0, 256, (2, 16)).astype(np.int32))

    dense = model.loss(ids, ids, training=False)
    model.config = dataclasses.replace(cfg, lm_head_mode="chunked")
    fused = model.loss(ids, ids, training=False)
    np.testing.assert_allclose(float(fused), float(dense), rtol=1e-5)


class TestPartitioned:
    """custom_partitioning dispatch on the virtual 8-device mesh: rows
    sharded over (dp, fsdp), vocab sharded Megatron-style over tp —
    numerics must match the unsharded dense reference, and the kernel
    (not the fallback) must have lowered when shapes align."""

    @pytest.fixture
    def mesh(self, devices8):
        from jax.sharding import Mesh
        return Mesh(np.array(devices8).reshape(2, 2, 2),
                    ("dp", "fsdp", "tp"))

    @pytest.mark.parametrize("aligned", [True, False])
    def test_vocab_sharded_matches_dense(self, mesh, aligned):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.ops.pallas import _partition, _support

        rs = np.random.RandomState(0)
        # aligned: local shards stay kernel-tileable; misaligned (e=120)
        # must take the jnp fallback with identical numerics
        n, e, v = (512, 128, 512) if aligned else (512, 120, 512)
        h = rs.randn(n, e).astype(np.float32)
        w = (0.1 * rs.randn(e, v)).astype(np.float32)
        labels = rs.randint(0, v, n).astype(np.int32)
        labels[:5] = -100

        hs = jax.device_put(jnp.asarray(h),
                            NamedSharding(mesh, P(("dp", "fsdp"), None)))
        ws = jax.device_put(jnp.asarray(w),
                            NamedSharding(mesh, P(None, "tp")))
        lab = jnp.asarray(labels)

        with _support.force_dispatch():
            _partition.reset_stats()

            def loss(h, w):
                per = LX.fused_linear_cross_entropy(h, w, lab,
                                                    partitioned=True)
                mask = (lab >= 0).astype(jnp.float32)
                return jnp.sum(per * mask) / jnp.sum(mask)

            val, (gh, gw) = jax.jit(
                jax.value_and_grad(loss, (0, 1)))(hs, ws)
            key = "kernel" if aligned else "fallback"
            assert _partition.stats[f"flce_fwd:{key}"] > 0
            assert _partition.stats[f"flce_dh:{key}"] > 0
            assert _partition.stats[f"flce_dw:{key}"] > 0

        mask = (jnp.asarray(labels) >= 0).astype(jnp.float32)

        def ref(h, w):
            per = dense_ref(h, w, jnp.asarray(labels))
            return jnp.sum(per * mask) / jnp.sum(mask)

        rval, (rgh, rgw) = jax.value_and_grad(ref, (0, 1))(
            jnp.asarray(h), jnp.asarray(w))
        np.testing.assert_allclose(float(val), float(rval), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(rgh),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rgw),
                                   rtol=1e-4, atol=1e-5)


def test_gpt_loss_fused_path_matches_dense():
    import dataclasses

    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    import paddle_tpu
    paddle_tpu.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32, dtype="float32",
                    remat=False)
    model = GPTForCausalLM(cfg)
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(0, 256, (2, 16)).astype(np.int32))
    dense = model.loss(ids, ids, training=False)
    model.config = dataclasses.replace(cfg, lm_head_mode="chunked")
    fused = model.loss(ids, ids, training=False)
    np.testing.assert_allclose(float(fused), float(dense), rtol=1e-5)


def test_mamba_tied_loss_fused_path_matches_dense():
    """Tied-embedding models route the fused path through the
    transposed table."""
    import dataclasses

    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    import paddle_tpu
    paddle_tpu.seed(0)
    cfg = MambaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      dtype="float32", scan_chunk_size=None)
    model = MambaForCausalLM(cfg)
    rs = np.random.RandomState(6)
    ids = jnp.asarray(rs.randint(0, 256, (2, 16)).astype(np.int32))
    dense = model.loss(ids, ids, training=False)
    model.config = dataclasses.replace(cfg, lm_head_mode="chunked")
    fused = model.loss(ids, ids, training=False)
    np.testing.assert_allclose(float(fused), float(dense), rtol=1e-5)


def test_supported_gates():
    h = jnp.zeros((24, 128), jnp.float32)
    w = jnp.zeros((128, 384), jnp.float32)
    lab = jnp.zeros((24,), jnp.int32)
    assert LX.supported(h, w, lab)
    # misaligned E
    assert not LX.supported(jnp.zeros((24, 100)), jnp.zeros((100, 384)), lab)
    # vocab with no 128-multiple divisor tile
    assert not LX.supported(h, jnp.zeros((128, 200)), lab)
    # row count not sublane-aligned
    assert not LX.supported(jnp.zeros((25, 128)), w,
                            jnp.zeros((25,), jnp.int32))
    # dtype mismatch
    assert not LX.supported(h.astype(jnp.bfloat16), w, lab)
