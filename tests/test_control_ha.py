"""Control-plane high availability: leased leadership over a shared
directory, the durable fleet-state journal, actuator epoch fencing, and
orphan-replica adoption at takeover.

The load-bearing properties: a standby claims the lease within one TTL
of the leader going silent and replays the journal to the EXACT managed
set; live orphans are adopted — routing membership restored around
running replicas, zero double-spawns; a deposed leader's queued
``spawn``/``stop`` carries a stale (holder, term), is rejected at the
actuator, and lands as a typed ``fenced`` decision, never executed; and
every HA flag is hard-off — the flag-default controller constructs no
lease, writes no journal byte, and reads no flag after construction.
"""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.core.flags import get_flags
from paddle_tpu.io import InferenceClient, InferenceServer, \
    save_inference_model
from paddle_tpu.serving import (
    FencedSpawner, FleetJournal, FleetState, InProcSpawner, LeaderLease,
    ServingController, StaleEpochError, control_dump,
)
from paddle_tpu.serving import control as control_mod
from paddle_tpu.serving import ha as ha_mod
from paddle_tpu.serving import router as router_mod

pytestmark = [pytest.mark.ha, pytest.mark.control]

TTL = 0.5


@pytest.fixture(scope="module")
def mlp_path(tmp_path_factory):
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = str(tmp_path_factory.mktemp("ha") / "mlp")
    save_inference_model(path, net, [np.zeros((2, 4), np.float32)],
                         dynamic_batch=True)
    return path


def _mlp_factory():
    return InferenceServer()


def _ctl(tmp, holder, **kw):
    kw.setdefault("min_replicas", 2)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("drain_s", 3.0)
    return ServingController(
        InProcSpawner(_mlp_factory), interval_s=0,
        ha_lease_dir=str(tmp), ha_lease_ttl_s=TTL, ha_holder=holder,
        **kw)


# ---------------------------------------------------------------------------
# LeaderLease
# ---------------------------------------------------------------------------

def test_lease_acquire_renew_and_peek(tmp_path):
    a = LeaderLease(str(tmp_path), ttl_s=TTL, holder="A")
    assert a.try_acquire()
    assert a.leading and a.term == 1 and a.is_current()
    doc = a.peek()
    assert doc["holder"] == "A" and doc["term"] == 1
    assert doc["expires"] > time.time()
    assert a.renew()                     # same term, refreshed deadline
    assert a.term == 1
    a.release()
    assert a.peek() is None and not a.leading
    a.close()


def test_lease_live_foreign_holder_blocks(tmp_path):
    a = LeaderLease(str(tmp_path), ttl_s=30.0, holder="A")
    b = LeaderLease(str(tmp_path), ttl_s=30.0, holder="B")
    assert a.try_acquire()
    assert not b.try_acquire()           # live foreign lease: hold
    assert not b.leading and b.term == 0
    assert a.is_current() and not b.is_current()
    a.close(), b.close()


def test_lease_expiry_takeover_bumps_term(tmp_path):
    a = LeaderLease(str(tmp_path), ttl_s=0.2, holder="A")
    b = LeaderLease(str(tmp_path), ttl_s=0.2, holder="B")
    assert a.try_acquire() and a.term == 1
    time.sleep(0.3)                      # A goes a TTL without renewal
    assert b.try_acquire()
    assert b.leading and b.term == 2     # term monotonically bumped
    # the deposed holder notices on its next probe — no write happens
    assert not a.renew() and not a.leading
    assert not a.is_current() and b.is_current()
    a.close(), b.close()


def test_lease_release_is_owner_guarded(tmp_path):
    """A standby's release must never delete the leader's lease."""
    a = LeaderLease(str(tmp_path), ttl_s=30.0, holder="A")
    b = LeaderLease(str(tmp_path), ttl_s=30.0, holder="B")
    assert a.try_acquire() and not b.try_acquire()
    b.release()
    assert a.is_current() and a.peek()["holder"] == "A"
    a.close(), b.close()


def test_lease_torn_file_is_reclaimable(tmp_path):
    """An unparseable lease file (torn write) reads as no lease and is
    simply re-claimed — never a crash, never a deadlock."""
    (tmp_path / ha_mod.LEASE_FILE).write_bytes(b'{"holder": "A", "te')
    a = LeaderLease(str(tmp_path), ttl_s=TTL, holder="B")
    assert a.peek() is None
    assert a.try_acquire() and a.term == 1
    a.close()


# ---------------------------------------------------------------------------
# FleetJournal
# ---------------------------------------------------------------------------

def test_journal_replay_reconstructs_exact_state(tmp_path):
    j = FleetJournal(str(tmp_path), compact_records=0)
    j.append("spawn_intent")
    j.append("spawn", ep="h:1", pid=11)
    j.append("spawn_intent")
    j.append("spawn", ep="h:2", pid=None)
    j.append("register_model", name="m", path="/p", warm=True)
    j.append("adopt", ep="h:3", pid=33)
    j.append("remove", ep="h:2")
    j.append("drain_begin", ep="h:1")
    j.append("spawn_intent")             # died inside the spawner
    j.append("future_op", ep="x")        # newer leader's record: skipped
    st = FleetJournal(str(tmp_path), compact_records=0).replay()
    assert st.managed == {"h:1": {"pid": 11}, "h:3": {"pid": 33}}
    assert st.registry == {"m": {"path": "/p", "warm": True}}
    assert st.draining == "h:1"          # unfinished drain survives
    assert st.lost_spawns == 1           # the unmatched intent
    j.close()


def test_journal_compaction_checkpoint_roundtrip(tmp_path):
    j = FleetJournal(str(tmp_path), compact_records=4)
    for i in range(4):
        j.append("spawn", ep=f"h:{i}", pid=i)
    assert j.should_compact()
    j.compact(j.replay())
    assert j.pending == 0 and not j.should_compact()
    # records fold on top of the checkpoint, not instead of it
    j.append("remove", ep="h:0")
    j.append("adopt", ep="h:9", pid=99)
    st = FleetJournal(str(tmp_path), compact_records=4).replay()
    assert set(st.managed) == {"h:1", "h:2", "h:3", "h:9"}
    assert st.managed["h:9"] == {"pid": 99}
    j.close()


def test_journal_torn_tail_breaks_clean(tmp_path):
    """The previous leader died mid-append: every record before the
    torn line replays, nothing after it exists."""
    j = FleetJournal(str(tmp_path), compact_records=0)
    j.append("spawn", ep="h:1", pid=1)
    j.append("spawn", ep="h:2", pid=2)
    with open(tmp_path / ha_mod.JOURNAL_FILE, "ab") as f:
        f.write(b'{"op": "remove", "ep": "h:1"')      # no newline, torn
    st = FleetJournal(str(tmp_path), compact_records=0).replay()
    assert set(st.managed) == {"h:1", "h:2"}
    j.close()


def test_fleet_state_dict_roundtrip():
    st = FleetState(managed={"h:1": {"pid": 7}},
                    registry={"m": {"path": "/p", "warm": False}},
                    draining="h:1", lost_spawns=2)
    assert FleetState.from_dict(
        json.loads(json.dumps(st.as_dict()))).as_dict() == st.as_dict()


# ---------------------------------------------------------------------------
# actuator fencing
# ---------------------------------------------------------------------------

class _RecordingSpawner:
    def __init__(self):
        self.calls = []

    def spawn(self):
        self.calls.append("spawn")
        return "h:1"

    def stop(self, endpoint, drain_s=0.0):
        self.calls.append(("stop", endpoint))

    def kill(self, endpoint):
        self.calls.append(("kill", endpoint))

    def adopt(self, endpoint, pid=None):
        self.calls.append(("adopt", endpoint))

    def pid_of(self, endpoint):
        return None


def test_fencing_rejects_stale_epoch_actions(tmp_path):
    """A deposed leader's queued spawn/stop/kill/adopt raises the typed
    StaleEpochError at the actuator and the inner spawner is NEVER
    called; the current leader's actions pass through untouched."""
    a = LeaderLease(str(tmp_path), ttl_s=0.2, holder="A")
    b = LeaderLease(str(tmp_path), ttl_s=0.2, holder="B")
    assert a.try_acquire()
    ra, rb = _RecordingSpawner(), _RecordingSpawner()
    fa, fb = FencedSpawner(ra, a), FencedSpawner(rb, b)
    assert fa.spawn() == "h:1"           # current leader: passes
    time.sleep(0.3)
    assert b.try_acquire()               # B deposes A at term 2
    for action in (fa.spawn, lambda: fa.stop("h:1"),
                   lambda: fa.kill("h:1"), lambda: fa.adopt("h:1")):
        with pytest.raises(StaleEpochError):
            action()
    assert ra.calls == ["spawn"]         # nothing executed post-depose
    fb.adopt("h:1")
    fb.stop("h:1")
    assert rb.calls == [("adopt", "h:1"), ("stop", "h:1")]
    assert fa.pid_of("h:1") is None      # reads are not fenced
    a.close(), b.close()


# ---------------------------------------------------------------------------
# controller end-to-end: standby hold, takeover adoption, fencing
# ---------------------------------------------------------------------------

def test_takeover_adopts_live_fleet_and_fences_zombie(mlp_path,
                                                      tmp_path):
    """The whole failover story in one fleet: leader bootstraps and
    registers a model; a standby holds; the leader goes silent; within
    one TTL the standby takes the lease at term+1, replays the journal,
    and ADOPTS the live replicas (same endpoints, zero double-spawns,
    registry intact); the zombie leader's next tick is a ``deposed``
    decision and its queued scale-up a ``fenced`` one — never executed,
    and its close() cannot stop the successor's fleet."""
    c1 = _ctl(tmp_path, "A")
    c2 = _ctl(tmp_path, "B")
    try:
        c1.start()
        c1.register_model("m", mlp_path, warm=True)
        assert c1.router.endpoints() == []   # HA: bootstrap waits for
        c1.tick()                            # leadership; tick leads
        assert c1.lease.leading and c1.lease.term == 1
        eps = set(c1.router.endpoints())
        assert len(eps) == 2

        c2.start()
        d = c2.tick()
        assert d.action == "hold" and "standby" in d.reason
        assert "'A'" in d.reason and not c2.router.endpoints()

        # the leader dies silently: no renewals; one TTL later the
        # standby's ordinary tick claims the lease and takes over
        time.sleep(TTL + 0.2)
        c2.tick()
        assert c2.lease.leading and c2.lease.term == 2
        assert set(c2.router.endpoints()) == eps     # EXACT managed set
        inner = c2._spawner.inner
        assert not inner.servers             # adopted, not respawned
        assert inner.adopted == eps
        adopts = [x for x in c2.decisions() if x["action"] == "adopt"]
        assert {x["endpoint"] for x in adopts} == eps
        # registry survived through the journal: warm pin and all
        spec = c2.registered_models()["m"]
        assert spec["warm"] and spec["path"] == mlp_path
        # adopted replicas serve — streams/requests untouched
        (y,) = c2.infer("m", np.ones((2, 4), np.float32))
        assert y.shape == (2, 3)

        # the zombie leader: deposed on its next tick, fenced at the
        # actuator on its queued scale-up — a typed decision, no spawn
        d = c1.tick()
        assert d.action == "deposed" and "'B'" in d.reason
        n_before = len(c1._spawner.inner.servers)
        d = c1._scale_up("zombie queued action", {})
        assert d.action == "fenced" and "epoch fence" in d.reason
        assert c1.decisions()[-1]["action"] == "fenced"
        assert len(c1._spawner.inner.servers) == n_before
        assert set(c2.router.endpoints()) == eps     # fleet untouched

        # deposed close must not stop the successor's replicas
        c1.close(stop_replicas=True)
        healths = c2.router.health()
        assert set(healths) == eps
        assert all(h.get("status") == "ok" for h in healths.values())
    finally:
        c1.close()
        c2.close()


def test_takeover_replaces_dead_and_surfaces_lost_spawns(mlp_path,
                                                         tmp_path):
    """Journaled replicas that prove dead at takeover are replaced (a
    ``replace`` decision plus a fresh spawn), and spawn intents that
    never reported an endpoint are surfaced, not silently forgotten."""
    j = FleetJournal(str(tmp_path), compact_records=0)
    j.append("spawn", ep="127.0.0.1:9", pid=None)     # nothing there
    j.append("spawn_intent")                          # died mid-spawn
    j.close()
    ctl = _ctl(tmp_path, "C", min_replicas=1)
    try:
        ctl.start()
        ctl.tick()
        acts = [d["action"] for d in ctl.decisions()]
        assert "replace" in acts and "scale_up" in acts
        assert "adopt" not in acts
        eps = ctl.router.endpoints()
        assert len(eps) == 1 and "127.0.0.1:9" not in eps
        # the takeover checkpoint reflects the repaired fleet
        st = FleetJournal(str(tmp_path), compact_records=0).replay()
        assert set(st.managed) == set(eps)
        assert st.lost_spawns == 0       # folded into the checkpoint
    finally:
        ctl.close()


def test_takeover_resumes_journaled_drain(mlp_path, tmp_path):
    """An unfinished sticky drain journaled by the previous leader is
    resumed by the new one: the victim is adopted, drained clean, and
    removed — then the fleet is bootstrapped back to min_replicas."""
    srv = InferenceServer({"m": mlp_path}).start()   # the orphan victim
    try:
        j = FleetJournal(str(tmp_path), compact_records=0)
        j.append("spawn", ep=srv.endpoint, pid=None)
        j.append("drain_begin", ep=srv.endpoint)
        j.close()
        ctl = _ctl(tmp_path, "D", min_replicas=1)
        try:
            ctl.start()
            ctl.tick()
            acts = [d["action"] for d in ctl.decisions()]
            assert "adopt" in acts and "drain_resume" in acts
            assert "scale_down" in acts
            eps = ctl.router.endpoints()
            assert srv.endpoint not in eps and len(eps) == 1
            assert ctl._draining is None
            st = FleetJournal(str(tmp_path), compact_records=0).replay()
            assert st.draining is None and set(st.managed) == set(eps)
        finally:
            ctl.close()
    finally:
        srv.stop()


def test_control_dump_over_wire(mlp_path, tmp_path):
    """The decision ring, managed set, registry, and leader/term are
    scrapeable over the ``control_dump`` frame op — decisions no longer
    die with the controller process."""
    ctl = _ctl(tmp_path, "E", min_replicas=1)
    try:
        ctl.start()
        ctl.register_model("m", mlp_path)
        ctl.tick()
        ep = ctl.serve()
        assert ctl.serve() == ep         # idempotent: one service
        doc = control_dump(ep)
        assert doc["leader"] == {"leading": True, "holder": "E",
                                 "term": 1}
        assert doc["managed"] == sorted(ctl.router.endpoints())
        assert doc["registry"]["m"]["path"] == mlp_path
        assert any(d["action"] == "scale_up" for d in doc["decisions"])
        # last=N truncates the ring server-side
        assert len(control_dump(ep, last=1)["decisions"]) == 1
    finally:
        ctl.close()


# ---------------------------------------------------------------------------
# defaults: hard-off, construction-only flag reads, jitter band
# ---------------------------------------------------------------------------

def test_ha_defaults_hard_off_and_construction_only(mlp_path,
                                                    monkeypatch):
    """Flag defaults: no lease, no journal, no fencing wrapper, no
    wire service — and NO flag (HA or otherwise) is read after
    construction: ticks, infer, and close run entirely on captured
    config."""
    assert get_flags(["control_ha_lease_dir", "control_ha_lease_ttl_s",
                      "control_ha_holder",
                      "control_ha_compact_records"]) == {
        "control_ha_lease_dir": "", "control_ha_lease_ttl_s": 3.0,
        "control_ha_holder": "", "control_ha_compact_records": 256}
    ctl = ServingController(InProcSpawner(_mlp_factory), interval_s=0,
                            min_replicas=1)
    try:
        assert ctl.lease is None and ctl._journal is None
        assert ctl._service is None
        assert isinstance(ctl._spawner, InProcSpawner)   # unwrapped
        ctl.start()
        ctl.register_model("m", mlp_path)

        def spy(name):
            raise AssertionError(
                f"flag({name!r}) read after construction")

        monkeypatch.setattr(control_mod, "flag", spy)
        monkeypatch.setattr(ha_mod, "flag", spy)
        for _ in range(3):
            ctl.tick()
        assert ctl.infer("m", np.ones((1, 4), np.float32))[0].shape \
            == (1, 3)
        assert "leader" not in ctl.control_dump()
        monkeypatch.undo()
    finally:
        ctl.close()


def test_tick_and_probe_jitter_band():
    """Controller tick and router probe cadences are jittered
    U[0.9, 1.1)x base — decorrelated fleets, same mean period."""
    for fn in (control_mod._jittered, router_mod._jittered):
        vals = [fn(2.0) for _ in range(400)]
        assert all(1.8 <= v < 2.2 for v in vals), (fn, min(vals),
                                                   max(vals))
        assert max(vals) - min(vals) > 0.1           # actually jitters
