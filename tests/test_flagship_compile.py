"""Flagship-scale compile checks — abstract AOT lowering.

The environment has one real chip, but the north-star configs are
multi-chip (Llama-2-7B sharded; 70B 4D-parallel). ``jax.eval_shape``
builds the full-size model abstractly (no weights materialized) and
``jax.jit(...).lower(...).compile()`` partitions + compiles the real
train step for the virtual mesh, with XLA's memory analysis giving
per-device footprints — the strongest no-hardware evidence that the
strategy compiler's output actually scales.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.parallel import mesh as M


def _compile_abstract(cfg, strategy, bs=8, seq=4096):
    """Lower + compile the train step over abstract full-size state;
    returns (compiled, params_B, mesh)."""
    mesh = M.mesh_from_strategy(strategy)

    def make_model():
        paddle_tpu.seed(0)
        return LlamaForCausalLM(cfg)

    abs_model = jax.eval_shape(make_model)
    params = sum(int(np.prod(l.shape)) for l in
                 jax.tree_util.tree_leaves(abs_model)
                 if hasattr(l, "shape"))
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            abs_model, optimizer=optim.AdamW(3e-4), strategy=strategy,
            mesh=mesh)
        abs_state = jax.eval_shape(step.init_state, abs_model)
        abs_batch = {
            "input_ids": jax.ShapeDtypeStruct((bs, seq), jnp.int32),
            "labels": jax.ShapeDtypeStruct((bs, seq), jnp.int32),
        }
        compiled = step.compile_abstract(abs_state, abs_batch)
    return compiled, params / 1e9, mesh


def test_llama2_7b_zero3_tp_compiles(devices8):
    """The 7B north-star config (zero3 x tp2 x dp2, seq 4096) compiles
    for an 8-device mesh; XLA's memory analysis confirms the state is
    genuinely sharded (per-device args ~ total/4, far below the 54GB a
    replicated 7B + fp32 moments would need)."""
    s = DistributedStrategy()
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 2
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    s.dp_degree = 2
    compiled, params_b, _ = _compile_abstract(LlamaConfig.llama2_7b(), s)
    assert 6.5 < params_b < 7.0, params_b
    ma = compiled.memory_analysis()
    # bf16 params + fp32 m/v (~10B/param total), sharded 4-way over
    # fsdp2 x tp2 (dp replicates) -> ~17GB/device, all donated
    args_gb = ma.argument_size_in_bytes / 1e9
    assert 12 < args_gb < 22, args_gb
    assert ma.alias_size_in_bytes / 1e9 > 12   # state donated, not copied
    assert ma.temp_size_in_bytes / 1e9 < 40    # remat keeps temps bounded


def test_llama2_70b_4d_compiles(devices8):
    """The 70B config compiles under zero3(4) x tp2 — the graph builds
    and partitions; the reported per-device footprint documents why a
    real run needs a pod slice (the same specs scale the denominator)."""
    s = DistributedStrategy()
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 4
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    s.dp_degree = 1
    compiled, params_b, _ = _compile_abstract(LlamaConfig.llama2_70b(), s)
    assert 65 < params_b < 72, params_b
    ma = compiled.memory_analysis()
    # 69B * ~10B/param / 8 shards ~= 86GB/device on this 8-device mesh
    assert 70 < ma.argument_size_in_bytes / 1e9 < 100


def test_mixtral_8x7b_ep_fsdp_compiles(devices8):
    """Mixtral-8x7B-scale MoE (46B total / ~13B active) compiles under
    ep4 x fsdp2 x dp1 with per-block remat: expert weights sharded over
    BOTH ep and fsdp (zero-3 inside each expert shard), the einsum
    dispatch's derived all_to_all partitioned by XLA. The memory
    analysis documents the per-device footprint a pod slice amortizes."""
    from paddle_tpu.models import MoEConfig, MoEForCausalLM

    cfg = MoEConfig(num_layers=32, remat=True,
                    remat_policy="nothing_saveable", max_seq_len=2048)
    s = DistributedStrategy()
    s.expert_parallel.enable = True
    s.expert_parallel.degree = 4
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 2
    s.dp_degree = 1
    mesh = M.mesh_from_strategy(s)

    def make_model():
        paddle_tpu.seed(0)
        return MoEForCausalLM(cfg)

    abs_model = jax.eval_shape(make_model)
    params = sum(int(np.prod(l.shape)) for l in
                 jax.tree_util.tree_leaves(abs_model)
                 if hasattr(l, "shape"))
    params_b = params / 1e9
    assert 43 < params_b < 48, params_b
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            abs_model, optimizer=optim.AdamW(3e-4), strategy=s, mesh=mesh)
        abs_state = jax.eval_shape(step.init_state, abs_model)
        abs_batch = {
            "input_ids": jax.ShapeDtypeStruct((8, 2048), jnp.int32),
            "labels": jax.ShapeDtypeStruct((8, 2048), jnp.int32),
        }
        compiled = step.compile_abstract(abs_state, abs_batch)
    ma = compiled.memory_analysis()
    args_gb = ma.argument_size_in_bytes / 1e9
    # ~10B/param AdamW state; experts (45B of 45.6B) sharded 8-way over
    # ep4 x fsdp2 -> ~57GB/device + unsharded-axis leftovers
    assert 40 < args_gb < 75, args_gb
    assert ma.alias_size_in_bytes / 1e9 > 40   # donated, not copied


def test_mixtral_8x7b_pp_ep_fsdp_compiles(devices8):
    """Mixtral at pod scale is pp x ep: the same 8x7B geometry compiles
    under pp2 x ep2 x fsdp2 (1F1B over the stacked MoE blocks — the
    memory-right schedule at this scale — with the expert all_to_all
    inside the pipeline shard_map) — the composition the r4 verdict
    flagged as inexpressible. Reference: section programs carry no
    model-class carve-outs (framework/section_worker.cc:44). GPipe
    compiles this config on TPU but trips the known XLA-CPU
    bf16-carry-in-vjp-in-scan-in-shard_map abort on the virtual mesh
    (tests/repros/, "Invalid binary instruction opcode copy"), so the
    CPU-mesh test pins 1f1b."""
    from paddle_tpu.models import MoEConfig, MoEForCausalLM

    cfg = MoEConfig(num_layers=32, remat=True,
                    remat_policy="nothing_saveable", max_seq_len=2048)
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = 4
    s.pipeline.schedule = "1f1b"
    s.expert_parallel.enable = True
    s.expert_parallel.degree = 2
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 2
    s.dp_degree = 1
    mesh = M.mesh_from_strategy(s)

    def make_model():
        paddle_tpu.seed(0)
        return MoEForCausalLM(cfg)

    abs_model = jax.eval_shape(make_model)
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            abs_model, optimizer=optim.AdamW(3e-4), strategy=s, mesh=mesh)
        abs_state = jax.eval_shape(step.init_state, abs_model)
        abs_batch = {
            "input_ids": jax.ShapeDtypeStruct((8, 2048), jnp.int32),
            "labels": jax.ShapeDtypeStruct((8, 2048), jnp.int32),
        }
        compiled = step.compile_abstract(abs_state, abs_batch)
    ma = compiled.memory_analysis()
    args_gb = ma.argument_size_in_bytes / 1e9
    # experts sharded pp2 (layer axis) x ep2 x fsdp2 = 8-way
    assert 40 < args_gb < 75, args_gb
    assert ma.alias_size_in_bytes / 1e9 > 40


def test_llama2_7b_long_context_ring_compiles(devices8):
    """The long-context north star at flagship scale: 7B with the
    sequence axis sharded 4-way (ring attention) at seq 32,768 compiles
    under sp4 x fsdp2. Ring attention's O(T/sp) per-device attention
    memory is what makes the config expressible at all — a dense
    [B, H, T, T] score tensor at this shape would be ~137 GB in bf16
    (~275 GB fp32), far past a single device."""
    s = DistributedStrategy()
    s.sequence_parallel.enable = True
    s.sequence_parallel.degree = 4
    s.sequence_parallel.mode = "ring"
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 2
    s.dp_degree = 1
    compiled, params_b, _ = _compile_abstract(
        LlamaConfig.llama2_7b(), s, bs=2, seq=32768)
    assert 6.5 < params_b < 7.0, params_b
    ma = compiled.memory_analysis()
    args_gb = ma.argument_size_in_bytes / 1e9
    # state sharded over fsdp2 only (sp shards activations, not params)
    assert 25 < args_gb < 45, args_gb
    assert ma.alias_size_in_bytes / 1e9 > 25   # donated
