"""Multi-chip Pallas dispatch: the custom_partitioning wrappers must run
the kernels per-shard under a multi-device mesh with numerics matching the
jnp reference — the analogue of the reference's fused CUDA kernels running
under the multi-device executor (``fused/multihead_matmul_op.cu`` per
device via ``framework/parallel_executor.cc:504``).

Everything runs interpreted on the virtual 8-device CPU mesh
(``_support.force_dispatch``), exactly the way the multichip dryrun
artifact exercises the path.
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu
from paddle_tpu.ops.pallas import _partition, _support
from paddle_tpu.ops.pallas import norm as NORM
from paddle_tpu.ops.pallas import softmax_xent as SX
from paddle_tpu.ops.pallas import rope as RP

FA = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")


@pytest.fixture
def mesh222(devices8):
    return Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "fsdp", "tp"))


def put(mesh, x, *spec):
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(*spec)))


def test_partitioned_rms_and_ln(mesh222):
    rs = np.random.RandomState(0)
    x = rs.randn(512, 256).astype(np.float32)
    w = np.abs(rs.randn(256)).astype(np.float32)
    b = rs.randn(256).astype(np.float32)
    xs = put(mesh222, x, ("dp", "fsdp"), None)
    ws = put(mesh222, w, None)
    bs = put(mesh222, b, None)

    with _support.force_dispatch():
        _partition.reset_stats()

        def loss_rms(x, w):
            return jnp.sum(NORM.rms_norm(x, w, partitioned=True) ** 2)

        val, (gx, gw) = jax.jit(
            jax.value_and_grad(loss_rms, argnums=(0, 1)))(xs, ws)

        def loss_ln(x, w, b):
            return jnp.sum(NORM.layer_norm(x, w, b, partitioned=True) ** 2)

        lval, lgs = jax.jit(
            jax.value_and_grad(loss_ln, argnums=(0, 1, 2)))(xs, ws, bs)
        assert _partition.stats["rms_fwd:kernel"] > 0
        assert _partition.stats["ln_bwd:kernel"] > 0

    def ref_rms(x, w):
        rstd = jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6)
        return jnp.sum((x * rstd * w) ** 2)

    rval, (rgx, rgw) = jax.value_and_grad(ref_rms, argnums=(0, 1))(
        jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(float(val), float(rval), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rgx),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rgw),
                               rtol=1e-3, atol=1e-3)

    def ref_ln(x, w, b):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
        return jnp.sum(((x - mu) * jax.lax.rsqrt(var + 1e-5) * w + b) ** 2)

    rlval, rlgs = jax.value_and_grad(ref_ln, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(float(lval), float(rlval), rtol=1e-5)
    for got, ref in zip(lgs, rlgs):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)


def test_partitioned_flash_attention_gqa_head_sharded(mesh222):
    """Batch over dp, heads over tp, GQA group preserved per shard."""
    rs = np.random.RandomState(1)
    B, T, Hq, Hkv, D = 4, 128, 8, 4, 64
    q = rs.randn(B, T, Hq, D).astype(np.float32)
    k = rs.randn(B, T, Hkv, D).astype(np.float32)
    v = rs.randn(B, T, Hkv, D).astype(np.float32)
    qs = put(mesh222, q, "dp", None, "tp", None)
    ks = put(mesh222, k, "dp", None, "tp", None)
    vs = put(mesh222, v, "dp", None, "tp", None)

    with _support.force_dispatch():
        _partition.reset_stats()

        def loss(q, k, v):
            o = FA.flash_attention(q, k, v, causal=True, partitioned=True)
            return jnp.sum(o ** 2)

        val, gs = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1, 2)))(qs, ks, vs)
        assert _partition.stats["flash_fwd:kernel"] > 0
        assert _partition.stats["flash_bwd:kernel"] > 0

    def ref(q, k, v):
        kk = jnp.repeat(k, Hq // Hkv, axis=2)
        vv = jnp.repeat(v, Hq // Hkv, axis=2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / np.sqrt(D)
        i = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        logits = jnp.where(j <= i, logits, -1e30)
        p = jax.nn.softmax(logits, -1)
        return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", p, vv) ** 2)

    rval, rgs = jax.value_and_grad(ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(float(val), float(rval), rtol=1e-4)
    for got, refg in zip(gs, rgs):
        np.testing.assert_allclose(np.asarray(got), np.asarray(refg),
                                   rtol=1e-3, atol=1e-3)


def test_partitioned_xent_vocab_sharded(mesh222):
    """Megatron-style: rows over dp, vocab over tp — local lse + LSE
    combine across the vocab axes."""
    rs = np.random.RandomState(2)
    N, V = 256, 512
    logits = rs.randn(N, V).astype(np.float32)
    labels = rs.randint(0, V, (N,)).astype(np.int32)
    ls = put(mesh222, logits, "dp", "tp")
    ys = put(mesh222, labels, "dp")

    with _support.force_dispatch():
        _partition.reset_stats()

        def loss(lg, lb):
            return jnp.sum(SX.softmax_cross_entropy(lg, lb, partitioned=True))

        val, g = jax.jit(jax.value_and_grad(loss))(ls, ys)
        assert _partition.stats["xent_lse:kernel"] > 0
        assert _partition.stats["xent_dx:kernel"] > 0

    def ref(lg, lb):
        lp = jax.nn.log_softmax(lg, -1)
        return -jnp.sum(jnp.take_along_axis(lp, lb[:, None], 1))

    rval, rg = jax.value_and_grad(ref)(jnp.asarray(logits),
                                       jnp.asarray(labels))
    np.testing.assert_allclose(float(val), float(rval), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                               rtol=1e-4, atol=1e-5)


def test_partitioned_rope_seq_sharded(mesh222):
    """Sequence sharded: the cos/sin tables shard with it so every shard
    rotates by its own absolute positions."""
    rs = np.random.RandomState(3)
    x = rs.randn(4, 256, 4, 64).astype(np.float32)
    ang = np.arange(256)[:, None] * (0.1 + np.arange(32)[None, :] / 32)
    cos = np.cos(ang).astype(np.float32)
    sin = np.sin(ang).astype(np.float32)
    xs = put(mesh222, x, "dp", "fsdp", None, None)
    cs = put(mesh222, cos, "fsdp", None)
    ss = put(mesh222, sin, "fsdp", None)

    with _support.force_dispatch():
        _partition.reset_stats()

        def loss(x, c, s):
            return jnp.sum(RP.apply_rotary(x, c, s, partitioned=True) ** 2)

        val, g = jax.jit(jax.value_and_grad(loss))(xs, cs, ss)
        assert _partition.stats["rope:kernel"] > 0

    def ref(x, c, s):
        x1, x2 = x[..., :32], x[..., 32:]
        c = c[None, :, None, :]
        s = s[None, :, None, :]
        return jnp.sum(
            jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1) ** 2)

    rval, rg = jax.value_and_grad(ref)(jnp.asarray(x), jnp.asarray(cos),
                                       jnp.asarray(sin))
    np.testing.assert_allclose(float(val), float(rval), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                               rtol=1e-4, atol=1e-4)


def test_partitioned_misaligned_shard_falls_back(mesh222):
    """A shard whose row count breaks kernel block alignment must take the
    per-shard jnp fallback and stay correct (not crash, not gather)."""
    rs = np.random.RandomState(4)
    # 8-way row sharding of 72 rows -> 9 rows/shard: not sublane-aligned
    x = rs.randn(72, 256).astype(np.float32)
    w = np.abs(rs.randn(256)).astype(np.float32)
    mesh = Mesh(np.array(mesh222.devices).reshape(8), ("dp",))
    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp", None)))
    ws = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P(None)))

    with _support.force_dispatch():
        _partition.reset_stats()
        y = jax.jit(lambda x, w: NORM.rms_norm(x, w, partitioned=True))(
            xs, ws)
        assert _partition.stats["rms_fwd:fallback"] > 0

    rstd = 1.0 / np.sqrt(np.mean(x * x, -1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(y), x * rstd * w,
                               rtol=1e-5, atol=1e-5)


def test_fleet_zero3_tp_kernels_match_jnp_losses(devices8):
    """VERDICT r2 'done when': under zero3×tp the Pallas kernel path must
    reproduce the jnp-path losses on the virtual mesh."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.core.strategy import DistributedStrategy
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel import mesh as M

    cfg = LlamaConfig.tiny(vocab_size=512, hidden_size=128, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=128)
    rs = np.random.RandomState(7)
    ids = rs.randint(0, 512, (8, 128)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}

    def run(kernels: bool):
        paddle_tpu.seed(42)
        s = DistributedStrategy()
        s.sharding.enable = True
        s.sharding.stage = 3
        s.sharding.degree = 2
        s.tensor_parallel.enable = True
        s.tensor_parallel.degree = 2
        model = LlamaForCausalLM(cfg)
        mesh = M.mesh_from_strategy(s)
        losses = []
        with M.MeshContext(mesh):
            opt = optim.AdamW(1e-2)
            step = dist.fleet.build_train_step(model, optimizer=opt,
                                               strategy=s, mesh=mesh)
            state = step.init_state(model)
            sbatch = step.shard_batch(batch)
            if kernels:
                with _support.force_dispatch():
                    _partition.reset_stats()
                    for i in range(3):
                        state, metrics = step(state, sbatch,
                                              jax.random.PRNGKey(i))
                        losses.append(float(metrics["loss"]))
                    assert _partition.stats["flash_fwd:kernel"] > 0, \
                        dict(_partition.stats)
            else:
                for i in range(3):
                    state, metrics = step(state, sbatch,
                                          jax.random.PRNGKey(i))
                    losses.append(float(metrics["loss"]))
        return losses

    l_kernel = run(True)
    l_jnp = run(False)
    np.testing.assert_allclose(l_kernel, l_jnp, rtol=5e-4, atol=5e-5)


def test_ulysses_uses_raw_kernel_inside_shard_map(devices8):
    """Inside the fully-manual Ulysses shard_map the dispatch gate goes
    'raw' — flash runs on local head-sharded shapes — and the result still
    matches dense attention."""
    from paddle_tpu.parallel.ring_attention import ulysses_self_attention
    import paddle_tpu.nn.functional as F

    mesh = Mesh(np.array(devices8).reshape(8), ("sp",))
    rs = np.random.RandomState(5)
    q = rs.randn(2, 1024, 8, 64).astype(np.float32)
    k = rs.randn(2, 1024, 8, 64).astype(np.float32)
    v = rs.randn(2, 1024, 8, 64).astype(np.float32)
    qj, kj, vj = map(jnp.asarray, (q, k, v))

    with _support.force_dispatch():
        out = jax.jit(lambda q, k, v: ulysses_self_attention(
            q, k, v, mesh, axis="sp", causal=True))(qj, kj, vj)

    ref = F.scaled_dot_product_attention(qj, kj, vj, causal=True,
                                         use_pallas="never")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
