"""Every example script must run end to end (tiny smoke settings)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(name, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name), *args],
        env=env, cwd=REPO, timeout=420, capture_output=True, text=True)


@pytest.mark.slow
@pytest.mark.parametrize("script,args", [
    ("train_llama.py", ("--smoke", "--steps", "4")),
    ("ps_recommender.py", ("--steps", "10")),
    ("qat_mnist_style.py", ("--steps", "10")),
    ("generate_text.py", ()),
    ("serve_model.py", ("--steps", "120")),
    ("long_context_sp.py", ("--steps", "4", "--seq", "256")),
    ("elastic_remote_ckpt.py", ("--epochs", "4", "--steps", "3")),
    ("dgc_dcn.py", ("--steps", "8")),
])
def test_example_runs(script, args):
    proc = run_example(script, *args)
    assert proc.returncode == 0, (script, proc.stdout[-1500:],
                                  proc.stderr[-1500:])
    assert proc.stdout.strip(), script
