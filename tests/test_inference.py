"""Inference layer (L8): export/Predictor + the generate decode loop.

Reference coverage model: C++ predictor tests per model
(``paddle/fluid/inference/tests/api/``) assert save→load→run parity;
here export→reload must be bit-identical on CPU, and the static-KV-cache
decode loop must reproduce full-recompute forward logits exactly.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
from paddle_tpu import io
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate, sample_logits


@pytest.fixture
def tiny_llama():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, num_layers=2,
                           num_heads=4, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def test_export_reload_bit_identical(tiny_llama, tmp_path):
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int32))
    path = str(tmp_path / "exported")
    io.save_inference_model(path, tiny_llama, [ids])

    pred = io.load_inference_model(path)
    got = pred.run(ids)
    want = jax.jit(lambda m, x: m(x))(tiny_llama, ids)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert pred.input_specs[0]["shape"] == [2, 16]
    assert pred.output_specs[0]["shape"] == [2, 16, 128]


def test_predictor_validates_inputs(tiny_llama, tmp_path):
    ids = jnp.zeros((2, 16), jnp.int32)
    path = str(tmp_path / "exported")
    io.save_inference_model(path, tiny_llama, [ids])
    pred = io.Predictor(path)
    with pytest.raises(ValueError, match="shape"):
        pred.run(jnp.zeros((2, 8), jnp.int32))
    with pytest.raises(ValueError, match="expected 1 inputs"):
        pred.run(ids, ids)
    with pytest.raises(ValueError, match="dtype"):
        pred.run(jnp.zeros((2, 16), jnp.float32))


def test_export_function_roundtrip(tmp_path):
    def fn(x, y):
        return jnp.sin(x) @ y

    x = jnp.asarray(np.random.RandomState(1).randn(4, 8).astype(np.float32))
    y = jnp.asarray(np.random.RandomState(2).randn(8, 2).astype(np.float32))
    p = str(tmp_path / "fn.stablehlo")
    io.export_function(fn, (x, y), p)
    from jax import export as jax_export
    with open(p, "rb") as f:
        rt = jax_export.deserialize(f.read())
    np.testing.assert_array_equal(np.asarray(rt.call(x, y)),
                                  np.asarray(fn(x, y)))


def test_cache_forward_matches_full_forward(tiny_llama):
    """Prefill + per-token decode through the static KV cache must equal
    the full recompute forward at every position."""
    model = tiny_llama
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(0, 128, (2, 12)).astype(np.int32))
    T = ids.shape[1]

    full_logits = model(ids)                       # [B, T, V]

    cache = model.init_cache(2, T)
    pre = 5
    logits_pre, cache = model.forward_with_cache(ids[:, :pre], cache, index=0)
    np.testing.assert_allclose(np.asarray(logits_pre),
                               np.asarray(full_logits[:, :pre]),
                               rtol=2e-5, atol=2e-5)
    for t in range(pre, T):
        logits_t, cache = model.forward_with_cache(
            ids[:, t:t + 1], cache, index=t)
        np.testing.assert_allclose(
            np.asarray(logits_t[:, 0]), np.asarray(full_logits[:, t]),
            rtol=2e-5, atol=2e-5,
            err_msg=f"decode step {t} diverged from full forward")


def test_generate_greedy_matches_naive_loop(tiny_llama):
    """generate() (fori_loop + static cache) vs the obvious slow loop that
    recomputes the full forward every step."""
    model = tiny_llama
    ids = jnp.asarray(
        np.random.RandomState(4).randint(0, 128, (2, 6)).astype(np.int32))
    n_new = 8

    out = generate(model, ids, n_new, temperature=0.0)

    naive = ids
    for _ in range(n_new):
        logits = model(naive)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        naive = jnp.concatenate([naive, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(naive))


def test_generate_zero_tokens_returns_prompt(tiny_llama):
    ids = jnp.asarray([[5, 67, 123]], jnp.int32)
    out = generate(tiny_llama, ids, 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))


def test_generate_eos_padding(tiny_llama):
    model = tiny_llama
    ids = jnp.asarray(
        np.random.RandomState(5).randint(0, 128, (1, 4)).astype(np.int32))
    # force every token to be "eos" by picking the greedy first token as eos
    first = int(jnp.argmax(model(ids)[:, -1], axis=-1)[0])
    out = generate(model, ids, 5, temperature=0.0, eos_token_id=first,
                   pad_token_id=99)
    out = np.asarray(out)
    assert out[0, 4] == first                  # eos emitted
    assert (out[0, 5:] == 99).all()            # then padding


def test_generate_jits(tiny_llama):
    model = tiny_llama
    ids = jnp.asarray(
        np.random.RandomState(6).randint(0, 128, (2, 6)).astype(np.int32))
    jitted = jax.jit(lambda m, x: generate(m, x, 4, temperature=0.0))
    out1 = jitted(model, ids)
    out2 = generate(model, ids, 4, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_sample_logits_top_k_top_p():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 10.0]])
    key = jax.random.PRNGKey(0)
    # top_k=1 → always argmax regardless of key
    for i in range(5):
        tok = sample_logits(logits, jax.random.PRNGKey(i), temperature=1.0,
                            top_k=1)
        assert int(tok[0]) == 4
    # top_p tiny → nucleus collapses to argmax
    for i in range(5):
        tok = sample_logits(logits, jax.random.PRNGKey(i), temperature=1.0,
                            top_p=0.1)
        assert int(tok[0]) == 4
    # greedy
    assert int(sample_logits(logits, None)[0]) == 4
    # plain sampling covers more than one token eventually
    seen = {int(sample_logits(logits * 0.0, jax.random.PRNGKey(i),
                              temperature=1.0)[0]) for i in range(32)}
    assert len(seen) > 1


def test_generate_sampling_reproducible(tiny_llama):
    model = tiny_llama
    ids = jnp.asarray(
        np.random.RandomState(8).randint(0, 128, (2, 5)).astype(np.int32))
    k = jax.random.PRNGKey(42)
    a = generate(model, ids, 6, temperature=0.8, top_k=10, key=k)
    b = generate(model, ids, 6, temperature=0.8, top_k=10, key=k)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 11)


def test_beam_search_beats_greedy_logprob():
    """Beam search must find sequences with total log-prob >= greedy's
    (the defining property), on a tiny trained-ish Llama."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import beam_search, generate

    paddle_tpu.seed(3)
    cfg = LlamaConfig.tiny(num_layers=2, vocab_size=64, max_seq_len=48)
    model = LlamaForCausalLM(cfg)
    prompt = jnp.asarray(np.random.RandomState(0).randint(
        0, 64, (2, 4)).astype(np.int32))

    greedy = generate(model, prompt, 8)
    beam = beam_search(model, prompt, 8, num_beams=4)
    assert beam.shape == greedy.shape == (2, 12)

    def seq_logprob(seq):
        logits = model(seq)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        tok_lp = jnp.take_along_axis(
            logp[:, :-1], seq[:, 1:, None], axis=-1)[..., 0]
        return jnp.sum(tok_lp[:, 3:], axis=1)  # generated part only

    g_lp = np.asarray(seq_logprob(greedy))
    b_lp = np.asarray(seq_logprob(beam))
    assert (b_lp >= g_lp - 1e-3).all(), (b_lp, g_lp)


def test_beam_search_eos_and_pad():
    """Beams that emit EOS stop scoring and pad; output stays rectangular."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import beam_search

    paddle_tpu.seed(4)
    cfg = LlamaConfig.tiny(num_layers=1, vocab_size=32, max_seq_len=32)
    model = LlamaForCausalLM(cfg)
    prompt = jnp.zeros((1, 2), jnp.int32)
    out = beam_search(model, prompt, 10, num_beams=3, eos_token_id=5,
                      pad_token_id=0)
    assert out.shape == (1, 12)
    row = np.asarray(out[0, 2:])
    if 5 in row:
        after = row[list(row).index(5) + 1:]
        assert (after == 0).all(), row


@pytest.mark.parametrize("family", ["gpt", "moe"])
def test_gpt_moe_cache_decode_matches_full_forward(family):
    """GPT and MoE decode through the shared static-KV-cache contract
    (r4): prefill logits and teacher-forced decode steps must match the
    full parallel forward, and generate() runs jitted."""
    import paddle_tpu
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM, MoEConfig,
                                   MoEForCausalLM)
    from paddle_tpu.models.generation import generate

    paddle_tpu.seed(0)
    if family == "gpt":
        m = GPTForCausalLM(GPTConfig.tiny(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=64, dropout=0.0))
    else:
        m = MoEForCausalLM(MoEConfig.tiny(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_layers=2, num_experts=4, max_seq_len=64))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 96, (2, 10)).astype(np.int32))
    ext = jnp.asarray(np.random.RandomState(1).randint(0, 96, (2, 3))
                      .astype(np.int32))
    allids = jnp.concatenate([ids, ext], axis=1)

    cache = m.init_cache(2, 20)
    pre, cache = m.forward_with_cache(ids, cache, 0)
    np.testing.assert_allclose(np.asarray(pre), np.asarray(m(ids)),
                               rtol=2e-4, atol=2e-5)
    full2 = np.asarray(m(allids))
    logits = []
    for t in range(3):
        lg, cache = m.forward_with_cache(allids[:, 10 + t:11 + t], cache,
                                         10 + t)
        logits.append(np.asarray(lg[:, 0]))
    np.testing.assert_allclose(np.stack(logits, 1), full2[:, 10:],
                               rtol=2e-3, atol=1e-4)
    out = np.asarray(jax.jit(lambda mm, i: generate(mm, i, 6))(m, ids))
    assert out.shape == (2, 16)
    assert (out[:, :10] == np.asarray(ids)).all()
    # beam search reorders cache leaves on axis 1 — the layout contract
    # every family's init_cache must satisfy
    from paddle_tpu.models.generation import beam_search
    bs_out = np.asarray(beam_search(m, ids, 4, num_beams=3))
    assert bs_out.shape == (2, 14)
    assert (bs_out[:, :10] == np.asarray(ids)).all()


def test_gpt_decode_beyond_max_seq_len_raises():
    """Learned positions cannot extrapolate: a decode length past
    max_seq_len must fail loudly, not silently clamp the pos gather."""
    import paddle_tpu
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle_tpu.seed(0)
    m = GPTForCausalLM(GPTConfig.tiny(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=16, dropout=0.0))
    with pytest.raises(ValueError, match="max_seq_len"):
        m.init_cache(2, 32)


def test_gpt_num_params_exact():
    """GPTConfig.num_params must equal the actual leaf count (the bench
    decode leg reports it)."""
    import paddle_tpu
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig.tiny(vocab_size=128, hidden_size=64, num_layers=3,
                         num_heads=4, max_seq_len=32)
    paddle_tpu.seed(0)
    m = GPTForCausalLM(cfg)
    actual = sum(int(np.prod(l.shape))
                 for l in jax.tree_util.tree_leaves(m)
                 if hasattr(l, "shape"))
    assert cfg.num_params() == actual, (cfg.num_params(), actual)


@pytest.mark.parametrize("family", ["llama", "gpt", "moe"])
def test_int8_kv_cache_decode_close_to_full(family):
    """Quantized KV cache (init_kv_cache(dtype=int8) via
    generate(cache_dtype=jnp.int8)): per-(position, head) absmax scales
    keep teacher-forced decode logits within a fraction of a percent of
    the full forward, and greedy generation matches the bf16-cache run
    on these shapes."""
    import paddle_tpu
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM, LlamaConfig,
                                   LlamaForCausalLM, MoEConfig,
                                   MoEForCausalLM)
    from paddle_tpu.models.generation import generate

    paddle_tpu.seed(0)
    if family == "llama":
        m = LlamaForCausalLM(LlamaConfig.tiny(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, max_seq_len=64))
    elif family == "gpt":
        m = GPTForCausalLM(GPTConfig.tiny(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=64, dropout=0.0))
    else:
        m = MoEForCausalLM(MoEConfig.tiny(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_layers=2, num_experts=4, max_seq_len=64))
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 96, (2, 10)).astype(np.int32))
    ext = jnp.asarray(np.random.RandomState(1).randint(0, 96, (2, 3))
                      .astype(np.int32))
    allids = jnp.concatenate([ids, ext], axis=1)
    full = np.asarray(m(allids))

    cache = m.init_cache(2, 16, dtype=jnp.int8)
    assert len(cache) == 4 and cache[0].dtype == jnp.int8
    pre, cache = m.forward_with_cache(ids, cache, 0)
    # prefill attends on the raw chunk — exact
    np.testing.assert_allclose(np.asarray(pre), full[:, :10], rtol=2e-4,
                               atol=2e-5)
    for t in range(3):
        lg, cache = m.forward_with_cache(allids[:, 10 + t:11 + t], cache,
                                         10 + t)
        rel = (np.linalg.norm(np.asarray(lg[:, 0]) - full[:, 10 + t])
               / np.linalg.norm(full[:, 10 + t]))
        assert rel < 0.02, (t, rel)

    g8 = np.asarray(generate(m, ids, 6, cache_dtype=jnp.int8))
    gf = np.asarray(generate(m, ids, 6))
    assert g8.shape == gf.shape == (2, 16)
    np.testing.assert_array_equal(g8, gf)


def test_mamba_ignores_int8_cache_dtype():
    """Mamba's recurrent state accumulates — cache_dtype=int8 falls back
    to the model float dtype instead of corrupting the state."""
    import paddle_tpu
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    paddle_tpu.seed(0)
    m = MambaForCausalLM(MambaConfig.tiny(vocab_size=64, hidden_size=32,
                                          num_layers=2, state_size=8))
    cache = m.init_cache(2, dtype=jnp.int8)
    assert jnp.issubdtype(jax.tree_util.tree_leaves(cache)[0].dtype,
                          jnp.floating)


def test_kv_cache_rejects_other_int_dtypes():
    import paddle_tpu
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle_tpu.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(vocab_size=64, hidden_size=32,
                                          num_layers=2, num_heads=4,
                                          num_kv_heads=2, max_seq_len=32))
    with pytest.raises(ValueError, match="int8"):
        m.init_cache(2, 16, dtype=jnp.int32)


def test_generate_under_tensor_parallel_sharding(devices8):
    """Serving runs TP-sharded: generate() on a Megatron-sharded model
    (weights placed by partition_specs over a tp2 mesh) must reproduce
    the single-device tokens exactly — with the bf16 AND the int8 KV
    cache. The partitioner derives the decode collectives from the
    weight shardings; no serving-specific code path exists."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    from paddle_tpu import partition_specs
    from paddle_tpu.parallel import mesh as M

    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=4, num_kv_heads=2, max_seq_len=64)
    m = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 96, (2, 8))
                      .astype(np.int32))
    ref = np.asarray(generate(m, ids, 8))

    mesh = M.create_mesh({"tp": 2, "dp": 1}, jax.devices()[:2])
    sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), partition_specs(m),
        is_leaf=lambda x: isinstance(x, P))
    m_sh = jax.device_put(m, sh)
    with M.MeshContext(mesh):
        out = np.asarray(jax.jit(
            lambda mm, i: generate(mm, i, 8))(m_sh, ids))
        out8 = np.asarray(jax.jit(
            lambda mm, i: generate(mm, i, 8,
                                   cache_dtype=jnp.int8))(m_sh, ids))
    np.testing.assert_array_equal(out, ref)
    np.testing.assert_array_equal(out8, ref)
