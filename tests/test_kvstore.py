"""Disaggregated serving: the tiered fleet-wide KV store.

The load-bearing properties: page frames are BIT-exact across the wire
(both cache layouts — f32/bf16 2-leaf and int8 4-leaf data+scale), the
radix chain key commits to the whole token prefix (full pages only —
the partial tail and the null page never enter the store), RAM-tier
eviction DEMOTES to the spill tier and refetches byte-identical, and a
second engine sharing the spill root serves a prefix computed elsewhere
as a KV fetch — not a prefill recompute — with streams byte-identical
to the cold path. Defaults are hard-off: the unflagged engine builds no
store and reads no ``gen_kv*`` flag on the hot path.
"""

import tempfile

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core.flags import flag, get_flags, set_flags
from paddle_tpu.core.monitor import get_stat
from paddle_tpu.io.serving import InferenceClient, InferenceServer
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import (
    deserialize_page, generate, init_paged_cache, serialize_page,
)
from paddle_tpu.serving import GenerationEngine, RoutedClient
from paddle_tpu.serving.kvstore import KVStore, page_chain_keys

pytestmark = pytest.mark.disagg

VOCAB = 96


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            assert doc["error"] is None, doc["error"]
            return toks


def _prompt(seed=0, n=16):
    return np.random.RandomState(seed).randint(0, VOCAB, (n,)).astype(
        np.int32)


# -- page frame serialization ----------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_page_frame_roundtrip_2leaf(model, dtype):
    """The float layouts' 2-leaf page frames decode bit-for-bit: same
    shapes, same dtypes, same bytes."""
    import jax.numpy as jnp

    proto = model.init_cache(1, 32, dtype=getattr(jnp, dtype))
    pool = init_paged_cache(proto, num_pages=2, page_tokens=8)
    rs = np.random.RandomState(3)
    leaves = [np.asarray(rs.rand(*leaf.shape[1:]), np.float32).astype(
        np.asarray(leaf).dtype) for leaf in pool]
    back = deserialize_page(serialize_page(leaves))
    assert len(back) == 2
    for a, b in zip(leaves, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_page_frame_roundtrip_int8_4leaf(model):
    """The int8 quantized layout — 4 leaves, the scale leaves one
    trailing dim shorter than their data leaves — serializes through
    the same frame format bit-exactly."""
    import jax.numpy as jnp

    proto = model.init_cache(1, 32, dtype=jnp.int8)
    pool = init_paged_cache(proto, num_pages=2, page_tokens=8)
    assert len(pool) == 4
    rs = np.random.RandomState(4)
    leaves = []
    for leaf in pool:
        shape, dt = leaf.shape[1:], np.asarray(leaf).dtype
        if dt == np.int8:
            leaves.append(rs.randint(-127, 128, shape).astype(np.int8))
        else:
            leaves.append(rs.rand(*shape).astype(dt))
    back = deserialize_page(serialize_page(leaves))
    assert len(back) == 4
    assert back[2].ndim == back[0].ndim - 1    # scale: one dim shorter
    for a, b in zip(leaves, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_page_frame_rejects_corruption():
    """Foreign magic, truncation, and trailing garbage all raise — a
    corrupt store entry must read as a MISS, never as silent wrong
    cache bytes."""
    frame = serialize_page([np.arange(8, dtype=np.float32)])
    with pytest.raises(ValueError):
        deserialize_page(b"NOTKV" + frame[5:])
    with pytest.raises(ValueError):
        deserialize_page(frame[:-3])
    with pytest.raises(ValueError):
        deserialize_page(frame + b"xx")


def test_page_chain_keys_full_pages_only():
    """Only FULL pages are keyed — the partial tail (and with it the
    null-page sink positions) never enters the store — and key[i]
    commits to the whole prefix through page i, so a shared prefix
    yields a shared key chain and a diverging one diverges."""
    toks = _prompt(5, 23)
    keys = page_chain_keys(toks, 8)
    assert len(keys) == 2                     # 23 tokens = 2 full pages
    assert page_chain_keys(toks[:7], 8) == []  # sub-page prompt: nothing
    # prefix property: a longer prompt's chain extends the shorter one's
    assert page_chain_keys(toks[:16], 8) == keys
    assert page_chain_keys(np.tile(toks, 2), 8)[:2] == keys
    # limit stops the chain early (the admission cap)
    assert page_chain_keys(np.tile(toks, 2), 8, limit=1) == keys[:1]
    # divergence anywhere re-keys everything after it
    other = toks.copy()
    other[2] += 1
    assert page_chain_keys(other, 8)[0] != keys[0]


# -- the tiered store ------------------------------------------------------

def test_store_put_get_probe(tmp_path):
    st = KVStore(pages=8, spill=str(tmp_path))
    assert st.get("missing") is None and st.misses == 1
    assert st.put("k1", b"frame-1")
    assert not st.put("k1", b"frame-1")       # content-addressed: no-op
    assert st.get("k1") == b"frame-1"
    st.put("k2", b"frame-2")
    # probe: longest unbroken prefix run of the chain
    assert st.probe(["k1", "k2", "k3"]) == 2
    assert st.probe(["k3", "k1"]) == 0        # stops at the first hole
    assert st.close() is None


def test_store_lru_demotes_to_spill_and_refetches(tmp_path):
    """RAM eviction is a DEMOTION: the bytes survive in the spill tier
    and a later get() promotes them back byte-identical."""
    st = KVStore(pages=2, spill=str(tmp_path))
    frames = {f"k{i}": bytes([i]) * 40 for i in range(4)}
    for k, f in frames.items():
        st.put(k, f)
    assert st.demotions == 2 and st.dropped == 0
    snap = st.snapshot()
    assert snap["ram_entries"] == 2
    for k, f in frames.items():               # every frame survives
        assert st.get(k) == f
    assert st.spill_hits >= 2                 # the demoted pair
    st.close()


def test_store_without_spill_drops():
    """No spill tier configured: eviction DROPS (counted) and the key
    reads as a miss — degraded, never wrong."""
    st = KVStore(pages=1)
    st.put("a", b"A")
    st.put("b", b"B")
    assert st.dropped == 1 and st.demotions == 0
    assert st.get("a") is None
    assert st.get("b") == b"B"
    assert st.snapshot()["spill"] is False


# -- hard-off defaults ------------------------------------------------------

def test_defaults_off_no_store_no_hot_path_flag_read(model, monkeypatch):
    """Hard-off discipline: gen_kv_store/gen_role default off/'both',
    the default engine builds NO store ('kv' absent from stats — the
    health doc is byte-identical to a store-less build), and no
    ``gen_kv*``/``gen_role`` flag is read on the serve hot path — only
    at construction."""
    assert flag("gen_kv_store") is False
    assert flag("gen_role") == "both"
    assert flag("gen_kv_spill_dir") == ""
    import paddle_tpu.serving.engine as engine_mod

    reads: list[str] = []
    real_flag = engine_mod.flag

    def spy(name):
        reads.append(name)
        return real_flag(name)

    monkeypatch.setattr(engine_mod, "flag", spy)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8) as eng:
        assert eng._kv is None and eng._role == "both"
        assert "gen_kv_store" in reads and "gen_role" in reads
        reads.clear()
        _drain(eng, eng.start(_prompt(), 6))
        assert not [r for r in reads
                    if r.startswith("gen_kv") or r == "gen_role"]
        assert "kv" not in eng.stats()


def test_store_requires_paged_cache(model):
    with pytest.raises(ValueError, match="paged"):
        GenerationEngine(model, slots=1, max_len=64,
                         kv_store=KVStore(pages=4))


# -- fleet-wide prefix reuse ------------------------------------------------

def test_cross_engine_shared_prefix_fetch(model, tmp_path):
    """A prefix prefilled on engine A is a KV FETCH on engine B (own
    store instance, shared spill root, cold prefix cache): B's stream
    is byte-identical to A's and to solo generate(), B fetched pages
    instead of recomputing them, and no page leaks."""
    prompt = _prompt(11, 16)                  # 2 full pages @ 8
    spill = str(tmp_path)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="both") as engA:
        outA = _drain(engA, engA.start(prompt, 6))
        kvA = engA.stats()["kv"]
        assert kvA["role"] == "both" and kvA["published"] == 2
    ref = np.asarray(generate(model, prompt[None], 6))[0, 16:]
    np.testing.assert_array_equal(np.asarray(outA, np.int32), ref)

    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="decode") as engB:
        outB = _drain(engB, engB.start(prompt, 6))
        assert outB == outA
        kvB = engB.stats()["kv"]
        # cap leaves the last prompt token to prefill: 1 of 2 pages
        # is fetchable, and it came from the store, not recompute
        assert kvB["fetched_pages"] == 1 and kvB["fetched_bytes"] > 0
        assert kvB["published"] == 0          # decode computed no
        assert get_stat("gen/kv_fetch_tokens_saved") >= 8
        g = engB.stats()
        assert g["pages_free"] + g["prefix_entries"] == g["pages"]


def test_prefix_eviction_demotes_to_store(model, tmp_path):
    """clear_prefix_cache (any eviction) with the store on demotes the
    victims' pages instead of dropping them."""
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=str(tmp_path)),
                          role="both") as eng:
        _drain(eng, eng.start(_prompt(13, 16), 4))
        assert eng.clear_prefix_cache() > 0
        kv = eng.stats()["kv"]
        assert kv["demoted"] > 0
        g = eng.stats()
        assert g["pages_free"] == g["pages"]


# -- KV-native failover -----------------------------------------------------

@pytest.mark.resilience
@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
def test_failover_resume_via_kv_fetch_zero_recompute(model, tmp_path,
                                                     sampled):
    """The tentpole acceptance: a stream resumed on a DIFFERENT decode
    replica (replay prompt+delivered, rng_skip=delivered) whose store
    holds the original prompt's pages completes byte-identical with
    ZERO recomputed prefill tokens — the page-aligned original prompt
    is covered entirely by KV fetch. Greedy and sampled (rng_skip
    composes with the fetch unchanged)."""
    kw = (dict(temperature=0.8, top_k=7, top_p=0.9, seed=42)
          if sampled else {})
    prompt = _prompt(17, 16)                  # page-aligned: 2 pages @ 8
    spill = str(tmp_path)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="both") as engA:
        full = _drain(engA, engA.start(prompt, 6, **kw))
        assert len(full) == 6

    # the survivor: fresh engine, cold radix cache, same spill root
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="decode") as engB:
        replay = np.concatenate([prompt, np.asarray(full[:3], np.int32)])
        tail = _drain(engB, engB.start(replay, 3, rng_skip=3, **kw))
        assert tail == full[3:]
        kv = engB.stats()["kv"]
        assert kv["prefill_recomputed"] == 0
        assert kv["fetched_pages"] == 2       # the whole original prompt
        g = engB.stats()
        assert g["pages_free"] + g["prefix_entries"] == g["pages"]


# -- wire ops & router locality --------------------------------------------

def test_kv_wire_ops(model, tmp_path):
    """kv_put/kv_get/kv_probe cross the wire: a store-backed replica
    answers from its engine's store, a store-less replica degrades to
    miss answers instead of erroring (mixed fleets probe cleanly)."""
    eng = GenerationEngine(model, slots=1, max_len=64, paged=True,
                           page_tokens=8,
                           kv_store=KVStore(pages=8, spill=str(tmp_path)))
    srv = InferenceServer().start()
    srv.add_generator("llm", eng)
    bare = InferenceServer().start()
    bare.add_generator("llm", GenerationEngine(model, slots=1,
                                               max_len=32))
    c = InferenceClient(srv.endpoint)
    c2 = InferenceClient(bare.endpoint)
    try:
        frame = serialize_page([np.arange(4, dtype=np.float32)])
        assert c.kv_put("wire-k1", frame) is True
        assert c.kv_put("wire-k1", frame) is False   # content-addressed
        assert c.kv_get("wire-k1") == frame
        assert c.kv_get("nope") is None
        assert c.kv_probe(["wire-k1", "nope"]) == 1
        # store-less replica: miss answers, not errors
        assert c2.kv_put("wire-k1", frame) is False
        assert c2.kv_get("wire-k1") is None
        assert c2.kv_probe(["wire-k1"]) == 0
    finally:
        c.close()
        c2.close()
        srv.stop()
        bare.stop()


def test_router_kv_locality_pins_longest_prefix(model, tmp_path):
    """With the store on, a session's first dispatch probes the fleet's
    stores and pins the replica holding the longest prefix chain — the
    request lands where its pages already are."""
    # router reads both at init: the locality gate and the fleet's page
    # size (the engines below are built with page_tokens=8 to match)
    saved = get_flags(["gen_kv_store", "gen_page_tokens"])
    set_flags({"gen_kv_store": True, "gen_page_tokens": 8})
    servers, engines = [], []
    try:
        for i in range(2):
            eng = GenerationEngine(
                model, slots=2, max_len=64, paged=True, page_tokens=8,
                kv_store=KVStore(pages=64,
                                 spill=str(tmp_path / f"r{i}")),
                role="both")
            srv = InferenceServer().start()
            srv.add_generator("llm", eng)
            servers.append(srv)
            engines.append(eng)
        prompt = _prompt(23, 16)
        # warm replica 1's store only (its private spill root)
        ref = _drain(engines[1], engines[1].start(prompt, 4))
        router = RoutedClient([s.endpoint for s in servers],
                              probe_interval_s=0)
        try:
            p0 = get_stat("serving/router/kv_placements")
            sess = router.session("locality-stream")
            toks = list(sess.generate("llm", prompt, 4,
                                      poll_wait_s=0.05))
            assert toks == ref
            assert sess.endpoint == servers[1].endpoint
            assert get_stat("serving/router/kv_placements") == p0 + 1
        finally:
            router.close()
    finally:
        set_flags(saved)
        for s in servers:
            s.stop()
