"""Disaggregated serving: the tiered fleet-wide KV store.

The load-bearing properties: page frames are BIT-exact across the wire
(both cache layouts — f32/bf16 2-leaf and int8 4-leaf data+scale), the
radix chain key commits to the whole token prefix (full pages only —
the partial tail and the null page never enter the store), RAM-tier
eviction DEMOTES to the spill tier and refetches byte-identical, and a
second engine sharing the spill root serves a prefix computed elsewhere
as a KV fetch — not a prefill recompute — with streams byte-identical
to the cold path. Defaults are hard-off: the unflagged engine builds no
store and reads no ``gen_kv*`` flag on the hot path.
"""

import shutil
import tempfile
import threading
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core import fault
from paddle_tpu.core.flags import flag, get_flags, set_flags
from paddle_tpu.core.monitor import get_stat
from paddle_tpu.io.serving import InferenceClient, InferenceServer
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import (
    deserialize_page, generate, init_paged_cache, serialize_page,
)
from paddle_tpu.serving import GenerationEngine, RoutedClient
from paddle_tpu.serving.kvstore import KVStore, page_chain_keys

pytestmark = pytest.mark.disagg

VOCAB = 96


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            assert doc["error"] is None, doc["error"]
            return toks


def _prompt(seed=0, n=16):
    return np.random.RandomState(seed).randint(0, VOCAB, (n,)).astype(
        np.int32)


# -- page frame serialization ----------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_page_frame_roundtrip_2leaf(model, dtype):
    """The float layouts' 2-leaf page frames decode bit-for-bit: same
    shapes, same dtypes, same bytes."""
    import jax.numpy as jnp

    proto = model.init_cache(1, 32, dtype=getattr(jnp, dtype))
    pool = init_paged_cache(proto, num_pages=2, page_tokens=8)
    rs = np.random.RandomState(3)
    leaves = [np.asarray(rs.rand(*leaf.shape[1:]), np.float32).astype(
        np.asarray(leaf).dtype) for leaf in pool]
    back = deserialize_page(serialize_page(leaves))
    assert len(back) == 2
    for a, b in zip(leaves, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_page_frame_roundtrip_int8_4leaf(model):
    """The int8 quantized layout — 4 leaves, the scale leaves one
    trailing dim shorter than their data leaves — serializes through
    the same frame format bit-exactly."""
    import jax.numpy as jnp

    proto = model.init_cache(1, 32, dtype=jnp.int8)
    pool = init_paged_cache(proto, num_pages=2, page_tokens=8)
    assert len(pool) == 4
    rs = np.random.RandomState(4)
    leaves = []
    for leaf in pool:
        shape, dt = leaf.shape[1:], np.asarray(leaf).dtype
        if dt == np.int8:
            leaves.append(rs.randint(-127, 128, shape).astype(np.int8))
        else:
            leaves.append(rs.rand(*shape).astype(dt))
    back = deserialize_page(serialize_page(leaves))
    assert len(back) == 4
    assert back[2].ndim == back[0].ndim - 1    # scale: one dim shorter
    for a, b in zip(leaves, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_page_frame_rejects_corruption():
    """Foreign magic, truncation, and trailing garbage all raise — a
    corrupt store entry must read as a MISS, never as silent wrong
    cache bytes."""
    frame = serialize_page([np.arange(8, dtype=np.float32)])
    with pytest.raises(ValueError):
        deserialize_page(b"NOTKV" + frame[5:])
    with pytest.raises(ValueError):
        deserialize_page(frame[:-3])
    with pytest.raises(ValueError):
        deserialize_page(frame + b"xx")


def test_page_chain_keys_full_pages_only():
    """Only FULL pages are keyed — the partial tail (and with it the
    null-page sink positions) never enters the store — and key[i]
    commits to the whole prefix through page i, so a shared prefix
    yields a shared key chain and a diverging one diverges."""
    toks = _prompt(5, 23)
    keys = page_chain_keys(toks, 8)
    assert len(keys) == 2                     # 23 tokens = 2 full pages
    assert page_chain_keys(toks[:7], 8) == []  # sub-page prompt: nothing
    # prefix property: a longer prompt's chain extends the shorter one's
    assert page_chain_keys(toks[:16], 8) == keys
    assert page_chain_keys(np.tile(toks, 2), 8)[:2] == keys
    # limit stops the chain early (the admission cap)
    assert page_chain_keys(np.tile(toks, 2), 8, limit=1) == keys[:1]
    # divergence anywhere re-keys everything after it
    other = toks.copy()
    other[2] += 1
    assert page_chain_keys(other, 8)[0] != keys[0]


# -- the tiered store ------------------------------------------------------

def test_store_put_get_probe(tmp_path):
    st = KVStore(pages=8, spill=str(tmp_path))
    assert st.get("missing") is None and st.misses == 1
    assert st.put("k1", b"frame-1")
    assert not st.put("k1", b"frame-1")       # content-addressed: no-op
    assert st.get("k1") == b"frame-1"
    st.put("k2", b"frame-2")
    # probe: longest unbroken prefix run of the chain
    assert st.probe(["k1", "k2", "k3"]) == 2
    assert st.probe(["k3", "k1"]) == 0        # stops at the first hole
    assert st.close() is None


def test_store_lru_demotes_to_spill_and_refetches(tmp_path):
    """RAM eviction is a DEMOTION: the bytes survive in the spill tier
    and a later get() promotes them back byte-identical."""
    st = KVStore(pages=2, spill=str(tmp_path))
    frames = {f"k{i}": bytes([i]) * 40 for i in range(4)}
    for k, f in frames.items():
        st.put(k, f)
    assert st.demotions == 2 and st.dropped == 0
    snap = st.snapshot()
    assert snap["ram_entries"] == 2
    for k, f in frames.items():               # every frame survives
        assert st.get(k) == f
    assert st.spill_hits >= 2                 # the demoted pair
    st.close()


def test_store_without_spill_drops():
    """No spill tier configured: eviction DROPS (counted) and the key
    reads as a miss — degraded, never wrong."""
    st = KVStore(pages=1)
    st.put("a", b"A")
    st.put("b", b"B")
    assert st.dropped == 1 and st.demotions == 0
    assert st.get("a") is None
    assert st.get("b") == b"B"
    assert st.snapshot()["spill"] is False


# -- hard-off defaults ------------------------------------------------------

def test_defaults_off_no_store_no_hot_path_flag_read(model, monkeypatch):
    """Hard-off discipline: gen_kv_store/gen_role default off/'both',
    the default engine builds NO store ('kv' absent from stats — the
    health doc is byte-identical to a store-less build), and no
    ``gen_kv*``/``gen_role`` flag is read on the serve hot path — only
    at construction."""
    assert flag("gen_kv_store") is False
    assert flag("gen_role") == "both"
    assert flag("gen_kv_spill_dir") == ""
    import paddle_tpu.serving.engine as engine_mod

    reads: list[str] = []
    real_flag = engine_mod.flag

    def spy(name):
        reads.append(name)
        return real_flag(name)

    monkeypatch.setattr(engine_mod, "flag", spy)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8) as eng:
        assert eng._kv is None and eng._role == "both"
        assert "gen_kv_store" in reads and "gen_role" in reads
        reads.clear()
        _drain(eng, eng.start(_prompt(), 6))
        assert not [r for r in reads
                    if r.startswith("gen_kv") or r == "gen_role"]
        assert "kv" not in eng.stats()


def test_store_requires_paged_cache(model):
    with pytest.raises(ValueError, match="paged"):
        GenerationEngine(model, slots=1, max_len=64,
                         kv_store=KVStore(pages=4))


# -- fleet-wide prefix reuse ------------------------------------------------

def test_cross_engine_shared_prefix_fetch(model, tmp_path):
    """A prefix prefilled on engine A is a KV FETCH on engine B (own
    store instance, shared spill root, cold prefix cache): B's stream
    is byte-identical to A's and to solo generate(), B fetched pages
    instead of recomputing them, and no page leaks."""
    prompt = _prompt(11, 16)                  # 2 full pages @ 8
    spill = str(tmp_path)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="both") as engA:
        outA = _drain(engA, engA.start(prompt, 6))
        kvA = engA.stats()["kv"]
        assert kvA["role"] == "both" and kvA["published"] == 2
    ref = np.asarray(generate(model, prompt[None], 6))[0, 16:]
    np.testing.assert_array_equal(np.asarray(outA, np.int32), ref)

    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="decode") as engB:
        outB = _drain(engB, engB.start(prompt, 6))
        assert outB == outA
        kvB = engB.stats()["kv"]
        # cap leaves the last prompt token to prefill: 1 of 2 pages
        # is fetchable, and it came from the store, not recompute
        assert kvB["fetched_pages"] == 1 and kvB["fetched_bytes"] > 0
        assert kvB["published"] == 0          # decode computed no
        assert get_stat("gen/kv_fetch_tokens_saved") >= 8
        g = engB.stats()
        assert g["pages_free"] + g["prefix_entries"] == g["pages"]


def test_prefix_eviction_demotes_to_store(model, tmp_path):
    """clear_prefix_cache (any eviction) with the store on demotes the
    victims' pages instead of dropping them."""
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=str(tmp_path)),
                          role="both") as eng:
        _drain(eng, eng.start(_prompt(13, 16), 4))
        assert eng.clear_prefix_cache() > 0
        kv = eng.stats()["kv"]
        assert kv["demoted"] > 0
        g = eng.stats()
        assert g["pages_free"] == g["pages"]


# -- KV-native failover -----------------------------------------------------

@pytest.mark.resilience
@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
def test_failover_resume_via_kv_fetch_zero_recompute(model, tmp_path,
                                                     sampled):
    """The tentpole acceptance: a stream resumed on a DIFFERENT decode
    replica (replay prompt+delivered, rng_skip=delivered) whose store
    holds the original prompt's pages completes byte-identical with
    ZERO recomputed prefill tokens — the page-aligned original prompt
    is covered entirely by KV fetch. Greedy and sampled (rng_skip
    composes with the fetch unchanged)."""
    kw = (dict(temperature=0.8, top_k=7, top_p=0.9, seed=42)
          if sampled else {})
    prompt = _prompt(17, 16)                  # page-aligned: 2 pages @ 8
    spill = str(tmp_path)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="both") as engA:
        full = _drain(engA, engA.start(prompt, 6, **kw))
        assert len(full) == 6

    # the survivor: fresh engine, cold radix cache, same spill root
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="decode") as engB:
        replay = np.concatenate([prompt, np.asarray(full[:3], np.int32)])
        tail = _drain(engB, engB.start(replay, 3, rng_skip=3, **kw))
        assert tail == full[3:]
        kv = engB.stats()["kv"]
        assert kv["prefill_recomputed"] == 0
        assert kv["fetched_pages"] == 2       # the whole original prompt
        g = engB.stats()
        assert g["pages_free"] + g["prefix_entries"] == g["pages"]


# -- wire ops & router locality --------------------------------------------

def test_kv_wire_ops(model, tmp_path):
    """kv_put/kv_get/kv_probe cross the wire: a store-backed replica
    answers from its engine's store, a store-less replica degrades to
    miss answers instead of erroring (mixed fleets probe cleanly)."""
    eng = GenerationEngine(model, slots=1, max_len=64, paged=True,
                           page_tokens=8,
                           kv_store=KVStore(pages=8, spill=str(tmp_path)))
    srv = InferenceServer().start()
    srv.add_generator("llm", eng)
    bare = InferenceServer().start()
    bare.add_generator("llm", GenerationEngine(model, slots=1,
                                               max_len=32))
    c = InferenceClient(srv.endpoint)
    c2 = InferenceClient(bare.endpoint)
    try:
        frame = serialize_page([np.arange(4, dtype=np.float32)])
        assert c.kv_put("wire-k1", frame) is True
        assert c.kv_put("wire-k1", frame) is False   # content-addressed
        assert c.kv_get("wire-k1") == frame
        assert c.kv_get("nope") is None
        assert c.kv_probe(["wire-k1", "nope"]) == 1
        # store-less replica: miss answers, not errors
        assert c2.kv_put("wire-k1", frame) is False
        assert c2.kv_get("wire-k1") is None
        assert c2.kv_probe(["wire-k1"]) == 0
    finally:
        c.close()
        c2.close()
        srv.stop()
        bare.stop()


def test_router_kv_locality_pins_longest_prefix(model, tmp_path):
    """With the store on, a session's first dispatch probes the fleet's
    stores and pins the replica holding the longest prefix chain — the
    request lands where its pages already are."""
    # router reads both at init: the locality gate and the fleet's page
    # size (the engines below are built with page_tokens=8 to match)
    saved = get_flags(["gen_kv_store", "gen_page_tokens"])
    set_flags({"gen_kv_store": True, "gen_page_tokens": 8})
    servers, engines = [], []
    try:
        for i in range(2):
            eng = GenerationEngine(
                model, slots=2, max_len=64, paged=True, page_tokens=8,
                kv_store=KVStore(pages=64,
                                 spill=str(tmp_path / f"r{i}")),
                role="both")
            srv = InferenceServer().start()
            srv.add_generator("llm", eng)
            servers.append(srv)
            engines.append(eng)
        prompt = _prompt(23, 16)
        # warm replica 1's store only (its private spill root)
        ref = _drain(engines[1], engines[1].start(prompt, 4))
        router = RoutedClient([s.endpoint for s in servers],
                              probe_interval_s=0)
        try:
            p0 = get_stat("serving/router/kv_placements")
            sess = router.session("locality-stream")
            toks = list(sess.generate("llm", prompt, 4,
                                      poll_wait_s=0.05))
            assert toks == ref
            assert sess.endpoint == servers[1].endpoint
            assert get_stat("serving/router/kv_placements") == p0 + 1
        finally:
            router.close()
    finally:
        set_flags(saved)
        for s in servers:
            s.stop()


def test_kv_place_never_pins_cordoned_holder(model, tmp_path):
    """Satellite: KV locality must never override liveness. A cordon
    landing DURING the (slow, networked) probe loop — after the healthy
    snapshot, before the pin — used to let _kv_place pin a replica the
    router had just taken out of rotation. The pin-time revalidation
    rejects it and the session falls back to a live replica; the stream
    still completes (cold, recomputed — degraded, never wrong)."""
    saved = get_flags(["gen_kv_store", "gen_page_tokens"])
    set_flags({"gen_kv_store": True, "gen_page_tokens": 8})
    servers, engines = [], []
    try:
        for i in range(2):
            eng = GenerationEngine(
                model, slots=2, max_len=64, paged=True, page_tokens=8,
                kv_store=KVStore(pages=64,
                                 spill=str(tmp_path / f"r{i}")),
                role="both")
            srv = InferenceServer().start()
            srv.add_generator("llm", eng)
            servers.append(srv)
            engines.append(eng)
        prompt = _prompt(29, 16)
        # warm replica 1 only: it is the longest-chain holder
        ref = _drain(engines[1], engines[1].start(prompt, 4))
        holder = servers[1].endpoint
        box = {}

        def factory(ep):
            c = InferenceClient(ep, retries=0)
            if ep == holder:
                real = c.kv_probe

                def probe(keys):
                    n = real(keys)
                    # the race: the drain cordons the holder while its
                    # winning probe answer is in flight
                    box["router"].cordon(holder)
                    return n

                c.kv_probe = probe
            return c

        router = RoutedClient([s.endpoint for s in servers],
                              probe_interval_s=0,
                              client_factory=factory)
        box["router"] = router
        try:
            r0 = get_stat("serving/router/kv_place_rejected")
            sess = router.session("cordoned-holder-stream")
            toks = list(sess.generate("llm", prompt, 4,
                                      poll_wait_s=0.05))
            assert toks == ref                # recomputed cold, not wrong
            assert sess.endpoint == servers[0].endpoint
            assert get_stat("serving/router/kv_place_rejected") == r0 + 1
        finally:
            router.close()
    finally:
        set_flags(saved)
        for s in servers:
            s.stop()


# -- failure-domain hardening ----------------------------------------------

def test_store_breaker_opens_half_opens_closes(tmp_path):
    """Spill-tier circuit breaker lifecycle: consecutive transfer
    failures open it (the store stops touching the tier and reports
    itself unplaceable), the backoff elapses into a half-open probe,
    and a successful probe closes it — all observable in the health
    snapshot."""
    st = KVStore(pages=8, spill=str(tmp_path), breaker=2,
                 breaker_backoff_s=0.05)
    st.put("warm", b"W" * 16)
    with fault.inject_faults({"kvstore.spill": 1.0}, seed=3):
        assert st.fetch("cold-1") == (None, True)
        assert st.fetch("cold-2") == (None, True)       # opens here
        h = st.snapshot()["health"]["spill"]
        assert h["opens"] == 1 and h["state"] in ("open", "half_open")
        assert st.snapshot()["degraded"] is True
        assert st.placeable is False
        # while open the tier is skipped, not retried: still degraded,
        # but no new spill-tier error is booked
        e0 = st.snapshot()["health"]["spill"]["errors"]
        assert st.fetch("cold-3") == (None, True)
        assert st.snapshot()["health"]["spill"]["errors"] == e0
    assert st.get("warm") == b"W" * 16       # RAM serves through it all
    time.sleep(0.12)                          # backoff elapses
    # half-open probe (injection gone): a clean answer closes
    assert st.get("cold-1") is None
    h = st.snapshot()["health"]["spill"]
    assert h["state"] == "closed"
    assert h["half_opens"] >= 1 and h["closes"] == 1
    assert st.placeable is True
    assert st.snapshot()["breaker_opens"] == 1
    st.close()


def test_store_broken_spill_demotes_to_drop_loudly(tmp_path):
    """A put against an OPEN spill breaker keeps the frame RAM-only;
    evicting such a frame cannot pretend the spill tier holds it — it
    drops, loudly (degraded_drops), instead of wedging eviction on the
    sick tier."""
    st = KVStore(pages=1, spill=str(tmp_path), breaker=1,
                 breaker_backoff_s=30.0)
    with fault.inject_faults({"kvstore.spill": 1.0}, seed=5):
        st.put("a", b"A" * 8)                # write-through fails: open
        assert st.snapshot()["health"]["spill"]["state"] == "open"
        st.put("b", b"B" * 8)                # evicts unspilled "a"
    snap = st.snapshot()
    assert snap["degraded_drops"] == 1 and snap["dropped"] == 1
    assert snap["demotions"] == 0
    assert st.get("b") == b"B" * 8           # RAM entry still serves
    st.close()


def test_store_fetch_deadline_abandons_slow_tier(tmp_path, monkeypatch):
    """gen_kv_fetch_timeout_s: a cold fetch outrunning its budget is
    abandoned — bounded latency, a degraded miss, and a tier failure
    booked against the wedged tier."""
    st = KVStore(pages=8, spill=str(tmp_path), fetch_timeout_s=0.1)
    st.put("warm", b"X" * 8)
    real = st._fs.download

    def slow(src, dst):
        time.sleep(0.6)
        return real(src, dst)

    monkeypatch.setattr(st._fs, "download", slow)
    t0 = time.monotonic()
    frame, degraded = st.fetch("cold")
    dt = time.monotonic() - t0
    assert frame is None and degraded is True
    assert dt < 0.45                          # bounded, not the 0.6s sleep
    assert st.timeouts == 1
    assert st.snapshot()["health"]["spill"]["errors"] >= 1
    assert st.get("warm") == b"X" * 8         # RAM unaffected
    st.close()


def test_store_hedged_fetch_peer_wins(tmp_path, monkeypatch):
    """gen_kv_hedge_ms: a spill read still pending after the hedge
    threshold races a peer replica; the peer's frame wins and the slow
    spill read is abandoned — correct bytes, bounded latency."""
    frames = {"hk": b"H" * 32}
    seeder = KVStore(pages=8, spill=str(tmp_path))
    seeder.put("hk", frames["hk"])
    seeder.close()
    st = KVStore(pages=8, spill=str(tmp_path), fetch_timeout_s=2.0,
                 hedge_ms=20.0, peers=(lambda k: frames.get(k),))
    real = st._fs.download

    def slow(src, dst):
        time.sleep(0.6)
        return real(src, dst)

    monkeypatch.setattr(st._fs, "download", slow)
    t0 = time.monotonic()
    frame, degraded = st.fetch("hk")
    assert frame == frames["hk"] and degraded is False
    assert time.monotonic() - t0 < 0.5        # won before the spill read
    snap = st.snapshot()
    assert snap["hedges"] == 1 and snap["hedge_wins"] == 1
    assert snap["peer_hits"] == 1
    st.close()


def test_store_peer_fallback_without_spill(tmp_path):
    """The peer tier also serves as the sequential fallback: no spill
    tier at all, a peer holding the frame answers the cold fetch (no
    hedge involved — there is nothing to race)."""
    frames = {"pk": b"P" * 24}
    st = KVStore(pages=8, peers=(lambda k: frames.get(k),))
    frame, degraded = st.fetch("pk")
    assert frame == frames["pk"] and degraded is False
    assert st.peer_hits == 1 and st.hedges == 0
    assert st.fetch("absent") == (None, False)    # clean miss: answered
    st.close()


def test_spill_dir_loss_degrades_to_recompute(model, tmp_path):
    """Satellite: the spill root vanishing mid-serving (volume loss) is
    TIER loss, not a clean miss — the fetch degrades to local prefill
    recompute with gen/kv_fetch_degraded booked, the stream stays
    byte-identical, and the pool returns to full."""
    prompt = _prompt(31, 16)
    spill = str(tmp_path / "kv")
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="both") as engA:
        outA = _drain(engA, engA.start(prompt, 6))
    ref = np.asarray(generate(model, prompt[None], 6))[0, 16:]
    np.testing.assert_array_equal(np.asarray(outA, np.int32), ref)

    store = KVStore(pages=64, spill=spill)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=store,
                          role="decode") as engB:
        shutil.rmtree(spill)                  # the tier vanishes
        d0 = get_stat("gen/kv_fetch_degraded")
        outB = _drain(engB, engB.start(prompt, 6))
        assert outB == outA                   # recomputed, byte-identical
        kv = engB.stats()["kv"]
        assert kv["fetch_degraded"] >= 1
        assert kv["fetched_pages"] == 0
        assert get_stat("gen/kv_fetch_degraded") >= d0 + 1
        g = engB.stats()
        assert g["pages_free"] + g["prefix_entries"] == g["pages"]


def test_corrupt_spill_frame_degrades_to_recompute(model, tmp_path):
    """Satellite: a truncated spill frame reads as a DEGRADED miss
    (gen/kv_corrupt + gen/kv_fetch_degraded) — recompute debt, zero
    wrong bytes, pool intact."""
    prompt = _prompt(37, 16)
    spill = str(tmp_path)
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="both") as engA:
        outA = _drain(engA, engA.start(prompt, 6))
    key = page_chain_keys(prompt, 8)[0]       # the page admission fetches
    path = tmp_path / f"{key}.kvpg"
    data = path.read_bytes()
    path.write_bytes(data[:len(data) // 2])   # truncate in place
    c0 = get_stat("gen/kv_corrupt")
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=KVStore(
                              pages=64, spill=spill),
                          role="decode") as engB:
        outB = _drain(engB, engB.start(prompt, 6))
        assert outB == outA
        kv = engB.stats()["kv"]
        assert kv["fetch_degraded"] >= 1 and kv["fetched_pages"] == 0
        assert get_stat("gen/kv_corrupt") >= c0 + 1
        g = engB.stats()
        assert g["pages_free"] + g["prefix_entries"] == g["pages"]


@pytest.mark.resilience
def test_watchdog_fails_stuck_admit_fetch_resumable(model, tmp_path):
    """Satellite: a wedged _kv_admit_fetch must trip gen_watchdog_s and
    fail the ADMITTING generation with the resumable reset marker (the
    stranded-gen contract) — it holds no slot yet, so the pre-hardening
    watchdog saw no busy work and the loop wedged silently. The engine
    then recovers for subsequent work."""
    from paddle_tpu.serving.engine import RESET_MARKER

    block = threading.Event()                 # armed after warm-up
    release = threading.Event()

    class _BlockingStore(KVStore):
        def fetch(self, key):
            if block.is_set():
                release.wait(8.0)             # a dead tier, no deadline
            return super().fetch(key)

    st = _BlockingStore(pages=16, spill=str(tmp_path))
    with GenerationEngine(model, slots=2, max_len=64, paged=True,
                          page_tokens=8, kv_store=st, role="decode",
                          watchdog_s=5.0, rebuilds=2) as eng:
        # warm the compiled paths under the generous deadline (XLA
        # compile IS a legitimate long step), then tighten it
        _drain(eng, eng.start(_prompt(47, 16), 4))
        eng._watchdog_s = 0.3
        block.set()
        gid = eng.start(_prompt(41, 16), 4)
        deadline = time.monotonic() + 6.0
        doc = eng.poll(gid, wait_s=0.2)
        while not doc["done"] and time.monotonic() < deadline:
            doc = eng.poll(gid, wait_s=0.2)
        assert doc["done"], "watchdog never fired: admission wedged"
        assert doc["error"] and RESET_MARKER in doc["error"]
        assert "admission kv fetch" in doc["error"]
        block.clear()
        release.set()
        # the loop unwinds the abandoned fetch and rebuilds; new starts
        # are shed (EngineOverloaded) until it does — retry briefly
        from paddle_tpu.serving.engine import EngineOverloaded
        deadline = time.monotonic() + 6.0
        while True:
            try:
                gid2 = eng.start(_prompt(43, 16), 4)
                break
            except EngineOverloaded:
                assert time.monotonic() < deadline, "engine never healed"
                time.sleep(0.1)
        out = _drain(eng, gid2)
        assert len(out) == 4                  # engine recovered


def test_kv_hardening_defaults_off(tmp_path, monkeypatch):
    """Hard-off discipline for the hardening flags: all zero/empty by
    default, and the defaults store runs THREAD-FREE — cold fetches are
    inline, no hedge or deadline machinery exists to pay for."""
    assert flag("gen_kv_fetch_timeout_s") == 0.0
    assert flag("gen_kv_admit_timeout_s") == 0.0
    assert flag("gen_kv_hedge_ms") == 0.0
    assert flag("gen_kv_breaker") == 0
    assert flag("gen_kv_peers") == ""
    assert flag("gen_kv_breaker_backoff_s") > 0
    st = KVStore(pages=4, spill=str(tmp_path))
    import paddle_tpu.serving.kvstore as kvstore_mod

    def no_thread(*a, **k):
        raise AssertionError("defaults path spawned a fetch thread")

    monkeypatch.setattr(kvstore_mod.threading, "Thread", no_thread)
    st.put("k", b"Z" * 8)
    assert st.get("k") == b"Z" * 8
    assert st.get("cold-miss") is None        # cold path: still inline
    h = st.snapshot()["health"]
    assert all(t["state"] == "closed" for t in h.values())
    st.close()
