"""Minimal repro: Shardy rejects a shard_map nested inside another
manual computation over a different axis.

This is the upstream limitation that shaped the pp∘sp design
(paddle_tpu.parallel.pipeline / pipeline_1f1b run manual over
{pp, sp} jointly, and ring/Ulysses attention uses the already-manual
axis instead of nesting a shard_map).

Observed on jax 0.9.0 (CPU, 4 virtual devices):

    ValueError: Cannot lower jaxpr with verifier errors:
      'sdy.manual_computation' op operates on axis "pp" which is
      already bound by a parent sdy.manual_computation op

The same program lowers fine under GSPMD
(jax_use_shardy_partitioner=False) — r3 shipped that as a scoped
fallback; r4 removed the nesting instead. Two other r3 gates no longer
reproduce on jax 0.9.0 and were retired outright:
- 1F1B∘AMP under Shardy ("Invalid binary instruction opcode copy");
- pp∘Ulysses ("Fatal Python error: Aborted" from a nested all_to_all
  inside the tick scan under grad) — with the joint-manual formulation
  the all_to_all is not nested and compiles under both partitioners.

Run: python tests/repros/shardy_nested_manual_sp.py
Exit status 0 means the upstream limitation still reproduces (or that
nesting now works — a message says which; if nesting works, the nested
formulation could simplify pipeline.py again).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402


def main():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "sp"))

    def inner(x):  # wants to run per-sp-shard inside the pp body
        return x + jax.lax.axis_index("sp")

    def pp_body(x):
        am = jax.sharding.get_abstract_mesh()
        nested = jax.shard_map(inner, mesh=am, axis_names={"sp"},
                               in_specs=P("sp"), out_specs=P("sp"),
                               check_vma=False)
        return nested(x) + jax.lax.axis_index("pp")

    f = jax.jit(jax.shard_map(pp_body, mesh=mesh, axis_names={"pp"},
                              in_specs=P("pp"), out_specs=P("pp"),
                              check_vma=False))
    x = jnp.zeros((4, 4), jnp.float32)
    try:
        f(x).block_until_ready()
    except ValueError as e:
        assert "already bound by a parent" in str(e), e
        print("reproduced: Shardy rejects the nested manual computation\n"
              f"  {type(e).__name__}: {str(e)[:160]}")
        return
    print("nesting now lowers under Shardy — the joint-manual pp∘sp "
          "formulation in parallel/pipeline*.py could be simplified back "
          "to nested shard_maps")


if __name__ == "__main__":
    main()
