"""Minimal repro: fp16_allreduce ∘ tensor-parallel via a PARTIAL-manual
shard_map (manual over the data axes, tp automatic) is blocked upstream.

This is why the strategy compiler rejects
``fp16_allreduce × {tp, pp, sp, zero-3}``
(``distributed/fleet/strategy_compiler.py``, the ``use_fp16_ar`` gate):
compressing the data-parallel gradient reduction requires a shard_map
manual over the batch axes (the wire-dtype psum must be explicit —
XLA's implicit backward reduction is always fp32), while the Megatron
matmuls need tp to stay an *automatic* axis inside that region. The
reference composes these freely because its fp16_allreduce pass
rewrites the c_allreduce ops in a static graph
(``python/paddle/distributed/fleet/meta_optimizers/
fp16_allreduce_optimizer.py``) — there is no manual/automatic axis
distinction to cross.

History of the failure mode:
- r4 (earlier jax): the partial-manual formulation hard-aborted XLA CPU
  ("Fatal Python error: Aborted" during compilation) — the original
  reason for the gate, then undistilled.
- jax 0.9.0 (current): the abort is gone; the program now fails EARLIER
  and more honestly, at trace time, in the sharding-in-types checker:

      jax._src.core.ShardingTypeError: Contracting dimensions are
      sharded and it is ambiguous how the output should be sharded.
      Please specify the output sharding via the `out_sharding`
      parameter. Got lhs_contracting_spec=('tp',) and
      rhs_contracting_spec=('tp',)

  i.e. inside a partially-manual region, an automatic-axis contraction
  no longer gets the GSPMD treatment (insert the tp psum); it demands a
  per-operation ``out_sharding`` annotation. Arbitrary model code (every
  ``jnp.dot`` in every layer) cannot carry that annotation, so the
  composition stays gated rather than half-supported.

Run: python tests/repros/fp16_ar_partial_manual_tp.py
Exit 0 either way; the message says whether the limitation still
reproduces. If it stops reproducing (jax starts inserting the tp
reduction automatically), the strategy-compiler gate can open for tp —
``tests/test_fleet.py::test_fp16_allreduce_tp_gate_cites_live_limitation`` will
flag it.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def reproduces() -> bool:
    """True if the partial-manual fp16-allreduce-with-tp program still
    fails to trace/compile."""
    from jax import shard_map

    mesh = jax.make_mesh((4, 2), ("dp", "tp"))
    rs = np.random.RandomState(0)
    w1 = jax.device_put(jnp.asarray(rs.randn(16, 32), jnp.float32),
                        NamedSharding(mesh, P(None, "tp")))
    w2 = jax.device_put(jnp.asarray(rs.randn(32, 16), jnp.float32),
                        NamedSharding(mesh, P("tp", None)))
    x = jax.device_put(jnp.asarray(rs.randn(8, 16), jnp.float32),
                       NamedSharding(mesh, P("dp", None)))

    def local(w1, w2, xb):
        def loss(ws):
            a, b = ws
            h = jnp.maximum(xb @ a, 0.0)   # [B/dp, F] sharded tp on F
            return jnp.mean((h @ b) ** 2)  # tp-contraction: needs psum

        l, g = jax.value_and_grad(loss)((w1, w2))
        n = jax.lax.psum(1, "dp")
        g = jax.tree_util.tree_map(
            lambda t: (jax.lax.psum(t.astype(jnp.bfloat16), "dp") / n
                       ).astype(t.dtype), g)
        return jax.lax.pmean(l, "dp"), g

    try:
        f = jax.jit(shard_map(
            local, mesh=mesh, axis_names={"dp"},
            in_specs=(P(None, None), P(None, None), P("dp", None)),
            out_specs=(P(), (P(None, None), P(None, None))),
            check_vma=False))
        jax.block_until_ready(f(w1, w2, x))
        return False
    except Exception as e:
        # only the DOCUMENTED failure counts as "still reproduces":
        # anything else (e.g. a renamed shard_map kwarg) must propagate,
        # or incidental API drift would mute this canary forever
        if (type(e).__name__ == "ShardingTypeError"
                or "out_sharding" in str(e)):
            print(f"  failed as expected: {type(e).__name__}: "
                  f"{str(e)[:200]}")
            return True
        raise


def main():
    if reproduces():
        print("REPRODUCES: partial-manual fp16-allreduce with automatic "
              "tp still fails — the strategy-compiler gate stands.")
    else:
        print("FIXED UPSTREAM: the composition now traces — revisit the "
              "fp16_allreduce tp gate in strategy_compiler.py "
              "(parity-test against the fp32 path, then open the gate).")


if __name__ == "__main__":
    main()
