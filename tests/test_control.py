"""Serving control plane: multi-model multiplexing, SLO-driven
autoscaling, sticky-drain scale-down — plus the wire/router primitives
it stands on (the ``unload_model`` op, per-model health stats, cordon)
and `RoutedClient` membership churn under live traffic.

The load-bearing properties: a clean scale-down loses ZERO in-flight
work (every session-pinned generation runs to completion on the replica
holding its KV state — no ``GenerationFailed``), and a replica serves
more registered models than its warm-tier capacity via LRU eviction.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.core import monitor
from paddle_tpu.core.flags import get_flags, set_flags
from paddle_tpu.io import (
    InferenceClient, InferenceServer, ModelBusyError, Predictor,
    save_inference_model,
)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.serving import (
    GenerationEngine, InProcSpawner, RoutedClient, ServingController,
)
from paddle_tpu.serving.metrics import hist_delta

pytestmark = pytest.mark.control

VOCAB = 96


@pytest.fixture(scope="module")
def mlp_path(tmp_path_factory):
    """A dynamic-batch MLP artifact shared by the fleet tests."""
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = str(tmp_path_factory.mktemp("ctl") / "mlp")
    save_inference_model(path, net, [np.zeros((2, 4), np.float32)],
                         dynamic_batch=True)
    return path


@pytest.fixture(scope="module")
def mlp_paths(tmp_path_factory):
    """Three distinct artifacts — the multi-model registry (distinct
    weights so responses identify which model answered)."""
    out = {}
    for i, name in enumerate(("a", "b", "c")):
        paddle_tpu.seed(i + 1)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        path = str(tmp_path_factory.mktemp("ctlm") / name)
        save_inference_model(path, net, [np.zeros((2, 4), np.float32)],
                             dynamic_batch=True)
        out[name] = path
    return out


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


# ---------------------------------------------------------------------------
# unload_model wire op + per-model health stats
# ---------------------------------------------------------------------------

def test_unload_model_roundtrip(mlp_path):
    srv = InferenceServer({"m": mlp_path}).start()
    try:
        with InferenceClient(srv.endpoint) as c:
            (y,) = c.infer("m", np.ones((2, 4), np.float32))
            assert y.shape == (2, 3)
            assert c.unload_model("m") is True
            assert c.unload_model("m") is False      # idempotent
            with pytest.raises(RuntimeError, match="no model"):
                c.infer("m", np.ones((2, 4), np.float32))
            c.load_model("m", mlp_path)              # reload works
            (y2,) = c.infer("m", np.ones((2, 4), np.float32))
            np.testing.assert_allclose(y2, y, rtol=1e-6)
    finally:
        srv.stop()


def test_unload_model_admin_gated(mlp_path):
    srv = InferenceServer({"m": mlp_path}, admin_ops=False).start()
    try:
        with InferenceClient(srv.endpoint) as c:
            with pytest.raises(RuntimeError, match="admin"):
                c.unload_model("m")
            # data plane unaffected
            assert c.infer("m", np.ones((1, 4), np.float32))[0].shape \
                == (1, 3)
    finally:
        srv.stop()


def test_unload_busy_in_batcher_fails_typed(mlp_path):
    """A model with requests inside the dynamic batcher refuses the
    unload with the typed ModelBusyError — clean and retryable, never a
    hang or a predictor yanked from a forming batch."""

    class _SlowDyn:
        supports_batching = True
        input_specs = [{"shape": [None, 4], "dtype": "float32"}]
        output_specs = [{"shape": [None, 3], "dtype": "float32"}]

        def run(self, x):
            time.sleep(0.5)
            return np.zeros((x.shape[0], 3), np.float32)

    set_flags({"serving_batch_max": 8, "serving_batch_timeout_s": 0.05,
               "serving_batch_min_queue": 0})
    srv = InferenceServer()
    srv.add_model("slow", _SlowDyn())
    srv.start()
    try:
        done = []

        def worker():
            with InferenceClient(srv.endpoint, timeout=15.0) as c:
                done.append(c.infer("slow", np.ones((1, 4), np.float32)))

        t = threading.Thread(target=worker)
        t.start()
        time.sleep(0.15)                 # request is inside the batcher
        with pytest.raises(ModelBusyError, match="batcher"):
            srv.unload_model("slow")
        with InferenceClient(srv.endpoint, timeout=15.0) as c:
            with pytest.raises(ModelBusyError):   # typed over the wire
                c.unload_model("slow")
        t.join(timeout=30)
        assert len(done) == 1            # the batched request survived
        assert srv.unload_model("slow") is True   # drained: unload ok
    finally:
        set_flags({"serving_batch_max": 0, "serving_batch_timeout_s": 0.005,
                   "serving_batch_min_queue": 2})
        srv.stop()


def test_health_ships_per_model_stats(mlp_path):
    srv = InferenceServer({"m": mlp_path}).start()
    try:
        with InferenceClient(srv.endpoint) as c:
            h0 = c.health()
            assert h0["models"]["m"]["infers"] == 0
            assert h0["models"]["m"]["resident_bytes"] > 0
            for _ in range(3):
                c.infer("m", np.ones((1, 4), np.float32))
            h1 = c.health()
            st = h1["models"]["m"]
            assert st["infers"] == 3
            assert st["last_used_ts"] >= h0["models"]["m"]["last_used_ts"]
            assert st["idle_s"] < 5.0
            # stats_prefix still filters the monitor-stats snapshot;
            # the models/generators decision inputs always ship
            h2 = c.health(stats_prefix="\x00none")
            assert h2["stats"] == {}
            assert h2["models"]["m"]["infers"] == 3
    finally:
        srv.stop()


def test_router_unload_broadcast(mlp_path):
    servers = [InferenceServer({"m": mlp_path}).start() for _ in range(2)]
    rc = RoutedClient([s.endpoint for s in servers], probe_interval_s=0,
                      timeout=10.0)
    try:
        out = rc.unload_model("m")
        assert out == {s.endpoint: True for s in servers}
        with pytest.raises(RuntimeError, match="no model"):
            rc.infer("m", np.ones((1, 4), np.float32))
        rc.load_model("m", mlp_path)     # broadcast reload
        assert rc.infer("m", np.ones((1, 4), np.float32))[0].shape \
            == (1, 3)
    finally:
        rc.close()
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------------
# cordon (the sticky-drain routing primitive)
# ---------------------------------------------------------------------------

def test_cordon_excludes_new_picks_keeps_member(mlp_path):
    servers = [InferenceServer({"m": mlp_path}).start() for _ in range(2)]
    rc = RoutedClient([s.endpoint for s in servers], probe_interval_s=0,
                      timeout=10.0)
    try:
        rc.cordon(servers[0].endpoint)
        m = {r["endpoint"]: r for r in rc.members()}
        assert m[servers[0].endpoint]["cordoned"]
        assert m[servers[0].endpoint]["healthy"]     # cordon != down
        for _ in range(6):
            rc.infer("m", np.ones((1, 4), np.float32))
        # all traffic went to the uncordoned replica
        h = rc.health()
        # per-model infer counters prove placement (replica-local state)
        assert h[servers[0].endpoint]["models"]["m"]["infers"] == 0
        assert h[servers[1].endpoint]["models"]["m"]["infers"] == 6
        rc.uncordon(servers[0].endpoint)
        assert not rc.members()[0]["cordoned"]
        rc.infer("m", np.ones((1, 4), np.float32))   # eligible again
    finally:
        rc.close()
        for s in servers:
            s.stop()


def test_cordon_lets_pinned_generation_finish(model):
    """Cordon the replica holding a live generation: the stream keeps
    polling the SAME replica to completion (byte-identical), while new
    sessions pin elsewhere — the router half of sticky drain."""
    servers = []
    for _ in range(2):
        srv = InferenceServer().start()
        srv.add_generator("llm", model, slots=2, max_len=32,
                          step_wait_s=0.02)
        servers.append(srv)
    rc = RoutedClient([s.endpoint for s in servers], probe_interval_s=0,
                      timeout=10.0)
    try:
        rs = np.random.RandomState(11)
        prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 12))[0, 5:]
        sess = rc.session("drain-me")
        it = sess.generate("llm", prompt, 12, poll_wait_s=0.05)
        toks = [next(it)]
        pinned = sess.endpoint
        rc.cordon(pinned)
        toks += list(it)                  # stream survives the cordon
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        other = next(s.endpoint for s in servers if s.endpoint != pinned)
        sess2 = rc.session("new-after-cordon")
        sess2.health()
        assert sess2.endpoint == other    # new pins avoid the cordoned
    finally:
        rc.close()
        for s in servers:
            s.stop()


def test_membership_churn_under_concurrent_traffic(model, mlp_path):
    """Satellite: add/remove/cordon endpoints while infer AND streaming
    generations are in flight — zero lost requests, streams
    byte-identical, membership lands where the churn put it."""
    servers = []
    for _ in range(3):
        srv = InferenceServer({"m": mlp_path}).start()
        srv.add_generator("llm", model, slots=2, max_len=32,
                          step_wait_s=0.01)
        servers.append(srv)
    rc = RoutedClient([s.endpoint for s in servers[:2]],
                      probe_interval_s=0, timeout=10.0)
    ref_pred = Predictor(mlp_path)
    rs = np.random.RandomState(12)
    prompts = [rs.randint(0, VOCAB, (4 + i,)).astype(np.int32)
               for i in range(2)]
    refs = [np.asarray(generate(model, p[None], 10))[0, p.size:]
            for p in prompts]
    stop_at = time.perf_counter() + 2.0
    infer_results: dict = {}
    streams: dict = {}
    errors: list = []

    def infer_worker(i):
        try:
            j = 0
            while time.perf_counter() < stop_at:
                x = np.full((1, 4), float(i * 100 + j), np.float32)
                infer_results[(i, j)] = (x, rc.infer("m", x)[0])
                j += 1
                time.sleep(0.005)
        except Exception as e:
            errors.append(f"infer{i}: {type(e).__name__}: {e}")

    def stream_worker(i):
        try:
            sess = rc.session(f"churn-{i}")
            streams[i] = list(sess.generate("llm", prompts[i], 10,
                                            poll_wait_s=0.05))
        except Exception as e:
            errors.append(f"stream{i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=infer_worker, args=(i,))
               for i in range(3)]
    threads += [threading.Thread(target=stream_worker, args=(i,))
                for i in range(2)]
    for t in threads:
        t.start()
    # churn while traffic flows: grow, cordon/uncordon the one member
    # guaranteed stream-free (just added), then remove and re-add it
    time.sleep(0.2)
    rc.add_endpoint(servers[2].endpoint)
    time.sleep(0.2)
    rc.cordon(servers[2].endpoint)
    time.sleep(0.2)
    rc.uncordon(servers[2].endpoint)
    time.sleep(0.2)
    rc.remove_endpoint(servers[2].endpoint)
    time.sleep(0.2)
    rc.add_endpoint(servers[2].endpoint)
    for t in threads:
        t.join(timeout=60)
    try:
        assert not errors, errors
        assert len(infer_results) >= 20
        for (i, j), (x, y) in infer_results.items():
            np.testing.assert_allclose(y, np.asarray(ref_pred.run(x)),
                                       rtol=1e-5, atol=1e-6)
        for i in range(2):
            np.testing.assert_array_equal(
                np.asarray(streams[i], np.int32), refs[i])
        assert len(rc.endpoints()) == 3
    finally:
        rc.close()
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------------
# engine: undelivered (the drain-wait signal)
# ---------------------------------------------------------------------------

def test_engine_undelivered_tracks_final_poll(model):
    with GenerationEngine(model, slots=2, max_len=32) as eng:
        gid = eng.start(np.arange(1, 6, dtype=np.int32), 3)
        deadline = time.monotonic() + 10
        while not eng.poll(gid, start=0, wait_s=0.2)["done"]:
            assert time.monotonic() < deadline
        # done AND the done-carrying poll answered -> delivered
        assert eng.stats()["undelivered"] == 0
        gid2 = eng.start(np.arange(1, 6, dtype=np.int32), 3)
        deadline = time.monotonic() + 10
        while eng.stats()["active"] > 0 or eng.stats()["queued"] > 0:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        # finished computing, but no poll told the client: undelivered
        assert eng.stats()["undelivered"] == 1
        eng.poll(gid2, start=0, wait_s=0.2)
        assert eng.stats()["undelivered"] == 0


# ---------------------------------------------------------------------------
# ServingController
# ---------------------------------------------------------------------------

def _mlp_factory():
    return InferenceServer()


def test_controller_defaults_are_inert(mlp_path):
    """Flag defaults: autoscaling and eviction both off — ticks hold, no
    replica or model ever touched. (The data path reads no control_*
    flag at all; this pins the controller itself.)"""
    f = get_flags(["control_max_replicas", "control_warm_models"])
    assert f == {"control_max_replicas": 0, "control_warm_models": 0}
    ctl = ServingController(InProcSpawner(_mlp_factory), interval_s=0,
                            min_replicas=1)
    try:
        ctl.start()
        ctl.register_model("m", mlp_path)
        assert ctl.infer("m", np.ones((1, 4), np.float32))[0].shape \
            == (1, 3)
        for _ in range(8):
            d = ctl.tick()
        assert d.action == "hold" and "disabled" in d.reason
        assert len(ctl.router.endpoints()) == 1
        # no scale/evict decisions beyond the bootstrap + fault-in
        actions = {x["action"] for x in ctl.decisions()}
        assert actions <= {"scale_up", "fault_in"}   # bootstrap only
        assert sum(1 for x in ctl.decisions()
                   if x["action"] == "scale_up") == 1
    finally:
        ctl.close()


def test_controller_multiplexes_more_models_than_warm_tier(mlp_paths):
    """Warm capacity 1, three registered models: every model stays
    servable (cold ones fault in), residency never exceeds the cap
    after reconcile, and the LRU is the one evicted."""
    ctl = ServingController(InProcSpawner(_mlp_factory), interval_s=0,
                            min_replicas=1, warm_models=1)
    refs = {n: Predictor(p) for n, p in mlp_paths.items()}
    try:
        ctl.start()
        for n, p in mlp_paths.items():
            ctl.register_model(n, p)
        x = np.ones((1, 4), np.float32)
        for rnd in range(2):             # every model twice: re-fault-in
            for n in mlp_paths:
                np.testing.assert_allclose(
                    ctl.infer(n, x)[0], np.asarray(refs[n].run(x)),
                    rtol=1e-5, atol=1e-6)
        ctl.tick()
        for doc in ctl.router.health().values():
            assert len(doc["models"]) <= 1, doc["models"]
        evicts = [d for d in ctl.decisions() if d["action"] == "evict"]
        assert len(evicts) >= 3
        assert all("LRU" in d["reason"] for d in evicts)
    finally:
        ctl.close()


def test_controller_warm_pinned_model_survives_eviction(mlp_paths):
    ctl = ServingController(InProcSpawner(_mlp_factory), interval_s=0,
                            min_replicas=1, warm_models=1)
    try:
        ctl.start()
        ctl.register_model("a", mlp_paths["a"], warm=True)
        ctl.register_model("b", mlp_paths["b"])
        x = np.ones((1, 4), np.float32)
        ctl.infer("a", x)
        ctl.infer("b", x)                # over capacity: 2 resident > 1
        ctl.tick()
        for doc in ctl.router.health().values():
            assert "a" in doc["models"]  # pinned: never the LRU victim
    finally:
        ctl.close()


def _engine_factory(model, slots=1, step_wait_s=0.03):
    def factory():
        srv = InferenceServer().start()
        srv.add_generator("llm", model, slots=slots, max_len=32,
                          step_wait_s=step_wait_s)
        return srv
    return factory


def test_controller_scales_up_on_queue_pressure(model):
    """Sustained generation queueing (demand > slots) breaches for
    breach_ticks consecutive ticks -> one scale-up, with the queue
    signal named in the decision."""
    spawner = InProcSpawner(_engine_factory(model))
    ctl = ServingController(spawner, interval_s=0, min_replicas=1,
                            max_replicas=3, breach_ticks=2,
                            cooldown_s=0.0, queue_high=1.0)
    try:
        ctl.start()
        rs = np.random.RandomState(13)
        prompts = [rs.randint(0, VOCAB, (4,)).astype(np.int32)
                   for _ in range(3)]
        sessions = [ctl.router.session(f"load-{i}") for i in range(3)]
        its = [s.generate("llm", p, 20, poll_wait_s=0.02)
               for s, p in zip(sessions, prompts)]
        next(its[0])                      # slots=1: 2 of 3 queue behind
        d1 = ctl.tick()
        assert d1.action == "hold"        # hysteresis: 1 breach < 2
        assert d1.signals["queued"] >= 1
        d2 = ctl.tick()
        assert d2.action == "scale_up", (d2.action, d2.reason)
        assert "queued generations" in d2.reason
        assert len(ctl.router.endpoints()) == 2
        for it in its:                    # everything still completes
            list(it)
    finally:
        ctl.close()


def test_controller_cooldown_holds_second_scale_up(model):
    spawner = InProcSpawner(_engine_factory(model))
    ctl = ServingController(spawner, interval_s=0, min_replicas=1,
                            max_replicas=4, breach_ticks=1,
                            cooldown_s=60.0, queue_high=1.0)
    try:
        ctl.start()
        rs = np.random.RandomState(14)
        its = [ctl.router.session(f"cool-{i}").generate(
                   "llm", rs.randint(0, VOCAB, (4,)).astype(np.int32),
                   20, poll_wait_s=0.02) for i in range(3)]
        next(its[0])
        d1 = ctl.tick()
        assert d1.action == "scale_up"
        d2 = ctl.tick()                   # pressure persists; cooldown
        assert d2.action == "hold" and "cooldown" in d2.reason
        assert len(ctl.router.endpoints()) == 2     # no flap
        for it in its:
            list(it)
    finally:
        ctl.close()


def test_controller_sticky_drain_scale_down_is_lossless(model):
    """The tentpole acceptance: a scale-down victim with a LIVE pinned
    generation drains — the stream finishes byte-identical on the
    victim, no GenerationFailed, and only then is the replica stopped
    and removed."""
    monitor.reset_stats("control/")
    spawner = InProcSpawner(_engine_factory(model, slots=2))
    ctl = ServingController(spawner, interval_s=0, min_replicas=1,
                            max_replicas=2, drain_s=20.0)
    try:
        ctl.start()
        ctl.scale_to(2, reason="test setup")
        assert len(ctl.router.endpoints()) == 2
        rs = np.random.RandomState(15)
        prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 15))[0, 5:]
        sess = ctl.router.session("pinned-on-victim")
        it = sess.generate("llm", prompt, 15, poll_wait_s=0.05)
        toks = [next(it)]
        victim = sess.endpoint
        got: dict = {}

        def drain():
            got["d"] = ctl.scale_down(victim=victim, reason="test drain")

        t = threading.Thread(target=drain)
        t.start()
        toks += list(it)                  # streams THROUGH the drain
        t.join(timeout=60)
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        d = got["d"]
        assert d.action == "scale_down" and d.endpoint == victim
        assert d.clean, d.reason          # inside the deadline, unforced
        assert monitor.get_stat("control/drain_forced") == 0
        assert victim not in ctl.router.endpoints()
        assert len(ctl.router.endpoints()) == 1
        assert victim not in spawner.servers        # actually stopped
        # the survivor still serves new generations
        toks2 = list(ctl.router.session("after").generate(
            "llm", prompt, 15, poll_wait_s=0.05))
        np.testing.assert_array_equal(np.asarray(toks2, np.int32), ref)
    finally:
        ctl.close()


def test_controller_scale_down_to_idle_fleet(model):
    """The automatic path: sustained idleness scales the fleet back to
    min_replicas (idle_ticks hysteresis), decision explains it."""
    spawner = InProcSpawner(_engine_factory(model))
    ctl = ServingController(spawner, interval_s=0, min_replicas=1,
                            max_replicas=3, idle_ticks=3, cooldown_s=0.0,
                            drain_s=10.0)
    try:
        ctl.start()
        ctl.scale_to(2, reason="test setup")
        d = None
        for _ in range(3):               # idle_ticks=3: fires on the 3rd
            d = ctl.tick()
        assert d.action == "scale_down", (d.action, d.reason)
        assert "idle" in d.reason and d.clean
        assert len(ctl.router.endpoints()) == 1
    finally:
        ctl.close()


def test_controller_replaces_dead_replica(mlp_path):
    spawner = InProcSpawner(_mlp_factory)
    ctl = ServingController(spawner, interval_s=0, min_replicas=2,
                            breach_ticks=1)
    try:
        ctl.start()
        ctl.register_model("m", mlp_path, warm=True)
        eps = ctl.router.endpoints()
        spawner.kill(eps[0])              # crash, no drain
        ctl.tick()                        # breach_ticks=1: replace now
        new_eps = ctl.router.endpoints()
        assert len(new_eps) == 2 and eps[0] not in new_eps
        replaced = [d for d in ctl.decisions()
                    if d["action"] == "replace"]
        assert replaced and "unreachable" in replaced[0]["reason"]
        # the substitute preloaded the warm model and serves it
        assert ctl.router.infer(
            "m", np.ones((1, 4), np.float32))[0].shape == (1, 3)
    finally:
        ctl.close()


def test_controller_spawn_preloads_registry(mlp_paths):
    ctl = ServingController(InProcSpawner(_mlp_factory), interval_s=0,
                            min_replicas=1)
    try:
        for n, p in mlp_paths.items():   # registry BEFORE any spawn
            ctl.register_model(n, p)
        ctl.start()
        ctl.scale_to(2, reason="grow")
        healths = ctl.router.health()
        assert len(healths) == 2
        for doc in healths.values():
            # warm_models=0 (no cap): every registered model preloads
            assert set(doc["models"]) == set(mlp_paths)
    finally:
        ctl.close()


def test_decisions_are_explainable():
    d = hist_delta(None, {"buckets": [1, 2], "count": 3, "sum": 1.0})
    assert d is None                      # no baseline yet
    assert hist_delta({"buckets": [1, 0]},
                      {"buckets": [1, 0], "count": 1}) is None  # empty
    d = hist_delta(
        {"buckets": [1, 2], "count": 3, "sum": 1.0},
        {"buckets": [2, 5], "count": 7, "sum": 4.0, "min": 0.1,
         "max": 0.9})
    assert d["buckets"] == [1, 3] and d["count"] == 4
    assert abs(d["sum"] - 3.0) < 1e-9


def _cum_hist(values):
    """A cumulative raw histogram snapshot, as ``health`` would ship."""
    h = monitor._Histogram()
    for v in values:
        h.observe(v)
    return h.summary(raw=True)


def test_controller_burn_rate_pressure_signals():
    """TTFT pressure is the multi-window burn rate, not a raw p99
    breach: the first scrape is a baseline (burn 0), a violating window
    trips BOTH windows past the threshold, and the resulting decision
    carries the burn evidence in its signals."""
    ctl = ServingController(InProcSpawner(_mlp_factory), interval_s=0,
                            max_replicas=1, breach_ticks=1,
                            cooldown_s=0.0, target_ttft_s=0.5,
                            slo_budget=0.1, burn_fast_ticks=2,
                            burn_slow_ticks=4, burn_threshold=1.0)
    try:
        def doc(values):
            return {"ep": {"status": "ok", "inflight": 0,
                           "generators": {}, "stats": {},
                           "histograms": {"gen/ttft_s":
                                          _cum_hist(values)}}}
        fast = [0.01] * 5
        s1 = ctl._signals(doc(fast))
        assert s1["ttft_burn_fast"] == 0.0      # baseline tick: no delta
        assert not ctl._pressure(s1)
        # window 2: five observations at 1.0s — 100% violating, budget
        # 0.1 -> burn 10x on both windows (one delta tick feeds both)
        s2 = ctl._signals(doc(fast + [2.0] * 5))
        assert s2["ttft_burn_fast"] == pytest.approx(10.0)
        assert s2["ttft_burn_slow"] == pytest.approx(10.0)
        assert s2["ttft_p99_s"] is not None and s2["ttft_p99_s"] > 0.5
        reasons = ctl._pressure(s2)
        assert any("burn rate" in r for r in reasons), reasons
        d = ctl._decide(s2)                     # at max_replicas: holds,
        assert d.action == "hold"               # but evidence is logged
        assert d.signals["ttft_burn_fast"] == pytest.approx(10.0)
        assert d.signals["ttft_burn_slow"] == pytest.approx(10.0)
        # two clean ticks push the violation out of the fast window: the
        # slow window still remembers it, but the PAGE condition needs
        # both — acute pressure released, no flapping on stale history
        s3 = ctl._signals(doc(fast + [2.0] * 5 + [0.01] * 20))
        s4 = ctl._signals(doc(fast + [2.0] * 5 + [0.01] * 40))
        assert s4["ttft_burn_fast"] == 0.0      # fast window is clean
        assert s4["ttft_burn_slow"] > 1.0       # slow window remembers
        assert not ctl._pressure(s4)
        assert s3["ttft_burn_fast"] < 10.0
    finally:
        ctl.close()


def test_controller_decision_log_schema(model):
    spawner = InProcSpawner(_engine_factory(model))
    ctl = ServingController(spawner, interval_s=0, min_replicas=1,
                            max_replicas=2, breach_ticks=1,
                            cooldown_s=0.0, drain_s=10.0)
    try:
        ctl.start()
        ctl.scale_to(2, reason="grow")
        ctl.scale_down(reason="shrink")
        docs = ctl.decisions()
        assert docs, "decisions must be recorded"
        for doc in docs:
            assert set(doc) == {"action", "reason", "endpoint", "clean",
                                "ts", "signals"}
            assert doc["reason"]
        acts = [d["action"] for d in docs]
        assert "scale_up" in acts and "scale_down" in acts
    finally:
        ctl.close()
