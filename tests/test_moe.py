"""MoE + expert parallelism tests: routing math, capacity semantics,
identical-expert parity vs dense, aux loss, EP-sharded training parity.
(New capability — no reference analogue; SURVEY.md §2.3.8.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.models import MoEConfig, MoEForCausalLM
from paddle_tpu.nn.moe import MoEMLP, top_k_routing
from paddle_tpu.parallel import mesh as M


def test_routing_top1_dispatches_every_token():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(16, 4).astype(np.float32))
    dispatch, combine, aux = top_k_routing(logits, k=1, capacity=16)
    # each token lands in exactly one (expert, slot)
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(1, 2))),
                               np.ones(16))
    # combine weight equals the token's top softmax prob
    probs = np.asarray(jax.nn.softmax(logits, -1))
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(1, 2))),
                               probs.max(-1), rtol=1e-6)
    # slots within an expert are used at most once
    per_slot = np.asarray(dispatch.sum(axis=0))
    assert (per_slot <= 1.0 + 1e-6).all()


def test_routing_capacity_drops_overflow():
    # all tokens prefer expert 0; capacity 2 keeps the first two
    logits = jnp.asarray(np.tile([10.0, 0.0, 0.0], (8, 1)))
    dispatch, combine, _ = top_k_routing(logits, k=1, capacity=2)
    kept = np.asarray(dispatch.sum(axis=(1, 2)))
    np.testing.assert_array_equal(kept, [1, 1, 0, 0, 0, 0, 0, 0])


def test_routing_top2_uses_two_experts():
    logits = jnp.asarray(np.random.RandomState(1).randn(8, 4)
                         .astype(np.float32))
    dispatch, _, _ = top_k_routing(logits, k=2, capacity=8)
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(1, 2))),
                               2 * np.ones(8))
    # the two picks are different experts
    per_expert = np.asarray(dispatch.sum(axis=2))  # [N, E]
    assert (per_expert <= 1.0 + 1e-6).all()


def test_aux_loss_balanced_vs_collapsed():
    rs = np.random.RandomState(2)
    balanced = jnp.asarray(rs.randn(256, 4).astype(np.float32))
    _, _, aux_b = top_k_routing(balanced, k=1, capacity=256)
    collapsed = jnp.asarray(
        np.tile([5.0, 0, 0, 0], (256, 1)).astype(np.float32))
    _, _, aux_c = top_k_routing(collapsed, k=1, capacity=256)
    assert float(aux_b) < 1.5
    assert float(aux_c) > 3.0   # E=4 at full collapse


def test_moe_identical_experts_matches_dense():
    """Zero router (uniform gates, argmax→expert 0) + identical expert
    weights: MoE top-1 output must equal (1/E) * dense SwiGLU MLP."""
    paddle_tpu.seed(5)
    H, I_, E = 16, 32, 4
    moe = MoEMLP(H, I_, E, top_k=1, capacity_factor=float(E))
    w_g = np.asarray(moe.w_gate[0])
    moe = moe.replace(
        router=jnp.zeros((H, E)),
        w_gate=jnp.broadcast_to(moe.w_gate[0], moe.w_gate.shape),
        w_up=jnp.broadcast_to(moe.w_up[0], moe.w_up.shape),
        w_down=jnp.broadcast_to(moe.w_down[0], moe.w_down.shape))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, H)
                    .astype(np.float32))
    out, aux = moe(x)

    from paddle_tpu.nn import functional as F
    dense = F.swiglu(x @ moe.w_up[0], x @ jnp.asarray(w_g)) @ moe.w_down[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense) / E,
                               rtol=2e-4, atol=1e-6)


def test_moe_model_trains():
    paddle_tpu.seed(0)
    cfg = MoEConfig.tiny()
    model = MoEForCausalLM(cfg)
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (4, 16))
                      .astype(np.int32))
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.AdamW(1e-2), mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"input_ids": ids, "labels": ids})
        losses = []
        for i in range(8):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_moe_expert_parallel_matches_single(devices8):
    """ep=4 × dp=2 must reproduce the dp-only losses (same seed), with
    expert weights actually sharded over ep."""
    def run(strategy):
        paddle_tpu.seed(9)
        cfg = MoEConfig.tiny(num_experts=4)
        model = MoEForCausalLM(cfg)
        mesh = M.mesh_from_strategy(strategy)
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 16))
                          .astype(np.int32))
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-2), strategy=strategy,
                mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"input_ids": ids, "labels": ids})
            losses = []
            for i in range(4):
                state, metrics = step(state, batch, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        return losses, state

    s_ep = DistributedStrategy()
    s_ep.expert_parallel.enable = True
    s_ep.expert_parallel.degree = 4
    ep_losses, ep_state = run(s_ep)

    w = ep_state.model.blocks.block.moe.w_gate
    assert "ep" in str(w.sharding.spec), w.sharding.spec
    # stacked blocks: leading layer axis, then the expert axis
    assert w.sharding.spec[1] == "ep"

    dp_losses, _ = run(DistributedStrategy())
    np.testing.assert_allclose(ep_losses, dp_losses, rtol=2e-4)


def test_moe_ep_fsdp_hybrid(devices8):
    """ep=2 x fsdp=2 x dp=2: expert weights sharded over BOTH ep and fsdp
    (ZeRO-3 inside each expert shard); loss parity with dp-only."""
    def run(strategy):
        paddle_tpu.seed(11)
        cfg = MoEConfig.tiny(num_experts=2)
        model = MoEForCausalLM(cfg)
        mesh = M.mesh_from_strategy(strategy)
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 16))
                          .astype(np.int32))
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-2), strategy=strategy,
                mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"input_ids": ids, "labels": ids})
            losses = []
            for i in range(3):
                state, metrics = step(state, batch, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        return losses, state

    s = DistributedStrategy()
    s.expert_parallel.enable = True
    s.expert_parallel.degree = 2
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 2
    hybrid_losses, st = run(s)
    w = st.model.blocks.block.moe.w_gate
    assert w.sharding.spec[1] == "ep" and "fsdp" in str(w.sharding.spec)
    ref_losses, _ = run(DistributedStrategy())
    np.testing.assert_allclose(hybrid_losses, ref_losses, rtol=2e-4)


def test_moe_dispatch_modes_match():
    """gather (index) dispatch must reproduce the einsum (one-hot)
    dispatch exactly — same routing core, same capacity/drop semantics —
    for outputs AND gradients, including with overflow drops."""
    paddle_tpu.seed(7)
    H, I_, E = 16, 32, 4
    # capacity_factor 0.6 forces real drops at top-2
    kw = dict(top_k=2, capacity_factor=0.6)
    moe_e = MoEMLP(H, I_, E, dispatch_mode="einsum", **kw)
    moe_g = moe_e.replace(dispatch_mode="gather")

    x = jnp.asarray(np.random.RandomState(3).randn(2, 24, H)
                    .astype(np.float32))

    def loss(m, x):
        out, aux = m(x)
        return jnp.sum(out ** 2) + aux, out

    (l_e, out_e), g_e = jax.value_and_grad(loss, argnums=(0, 1),
                                           has_aux=True)(moe_e, x)
    (l_g, out_g), g_g = jax.value_and_grad(loss, argnums=(0, 1),
                                           has_aux=True)(moe_g, x)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_e),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(l_g), float(l_e), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_e), jax.tree.leaves(g_g)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-6)


def test_moe_auto_mode_resolution():
    """auto → gather off-mesh / on an ep-less mesh; einsum when the mesh
    has a real ep axis."""
    moe = MoEMLP(8, 16, 2)
    assert moe.dispatch_mode == "auto"
    assert moe._resolved_mode() == "gather"
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    with M.MeshContext(mesh):
        assert moe._resolved_mode() == "gather"


def test_moe_auto_mode_picks_einsum_under_ep(devices8):
    from paddle_tpu.core.strategy import DistributedStrategy as DS
    s = DS()
    s.expert_parallel.enable = True
    s.expert_parallel.degree = 4
    mesh = M.mesh_from_strategy(s)
    moe = MoEMLP(8, 16, 4)
    with M.MeshContext(mesh):
        assert moe._resolved_mode() == "einsum"


def test_moe_remat_matches_no_remat():
    """Per-block remat (python-loop checkpoint) is a pure memory/FLOPs
    trade: losses must match the non-remat forward exactly."""
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 256, (4, 16)).astype(np.int32))

    def losses(remat):
        paddle_tpu.seed(3)
        cfg = MoEConfig.tiny(remat=remat)
        model = MoEForCausalLM(cfg)
        mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-2), mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"input_ids": ids, "labels": ids})
            out = []
            for i in range(3):
                state, m = step(state, batch, jax.random.PRNGKey(i))
                out.append(float(m["loss"]))
        return out

    np.testing.assert_allclose(losses(True), losses(False), rtol=1e-6)


def test_moe_gather_grouped_ample_capacity_matches_gather(devices8):
    """With ample capacity (no drops anywhere) grouped per-shard quotas
    and the global-capacity gather mode route identically — outputs
    must agree exactly on a dp4 mesh (G=4 groups)."""
    paddle_tpu.seed(13)
    H, I_, E = 16, 32, 4
    kw = dict(top_k=2, capacity_factor=float(E))   # no drops possible
    moe_g = MoEMLP(H, I_, E, dispatch_mode="gather", **kw)
    moe_gg = moe_g.replace(dispatch_mode="gather_grouped")
    x = jnp.asarray(np.random.RandomState(5).randn(8, 8, H)
                    .astype(np.float32))
    mesh = M.create_mesh({"dp": 4}, devices=jax.devices()[:4])
    with M.MeshContext(mesh):
        assert moe_gg._groups(8 * 8) == 4
        out_g, aux_g = moe_g(x)
        out_gg, aux_gg = moe_gg(x)
    np.testing.assert_allclose(np.asarray(out_gg), np.asarray(out_g),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_gg), float(aux_g), rtol=1e-5)


def test_moe_gather_grouped_ep_trains_and_matches(devices8):
    """gather_grouped under a REAL ep mesh: ep4 x dp2 training losses
    match the dp-only run (ample capacity), expert weights sharded."""
    def run(strategy, mode):
        paddle_tpu.seed(9)
        cfg = MoEConfig.tiny(num_experts=4, capacity_factor=4.0,
                             dispatch_mode=mode)
        model = MoEForCausalLM(cfg)
        mesh = M.mesh_from_strategy(strategy)
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 16))
                          .astype(np.int32))
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-2), strategy=strategy,
                mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"input_ids": ids, "labels": ids})
            losses = []
            for i in range(4):
                state, metrics = step(state, batch, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        return losses, state

    s_ep = DistributedStrategy()
    s_ep.expert_parallel.enable = True
    s_ep.expert_parallel.degree = 4
    s_ep.dp_degree = 2
    ep_losses, ep_state = run(s_ep, "gather_grouped")
    w = ep_state.model.blocks.block.moe.w_gate
    assert w.sharding.spec[1] == "ep", w.sharding.spec

    dp_losses, _ = run(DistributedStrategy(), "gather")
    np.testing.assert_allclose(ep_losses, dp_losses, rtol=2e-4)


def test_moe_gather_grouped_fsdp_batch_axes(devices8):
    """The group axis must follow ALL batch axes (dp·fsdp), not just dp:
    on a dp2 x fsdp2 mesh _groups is 4 and outputs still match the
    global gather mode under ample capacity."""
    paddle_tpu.seed(17)
    H, I_, E = 16, 32, 4
    kw = dict(top_k=2, capacity_factor=float(E))
    moe_g = MoEMLP(H, I_, E, dispatch_mode="gather", **kw)
    moe_gg = moe_g.replace(dispatch_mode="gather_grouped")
    x = jnp.asarray(np.random.RandomState(8).randn(8, 8, H)
                    .astype(np.float32))
    mesh = M.create_mesh({"dp": 2, "fsdp": 2}, devices=jax.devices()[:4])
    with M.MeshContext(mesh):
        assert moe_gg._groups(8 * 8) == 4
        out_g, _ = moe_g(x)
        out_gg, _ = moe_gg(x)
    np.testing.assert_allclose(np.asarray(out_gg), np.asarray(out_g),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# MoE × pipeline parallelism (verdict r4 #2): MoE blocks are
# scan-stacked like every other family, so both pipeline schedules apply
# — the aux loss rides the per-layer tape (nn.stateful.record_aux),
# which GPipe transports differentiably and 1F1B cotangent-seeds.
# Reference: arbitrary section programs with no model-class carve-outs
# (framework/section_worker.cc:44).
# ---------------------------------------------------------------------------

def _pp_moe_run(strategy, cfg, n=3, lr=1e-2, opt=None, seed=11):
    paddle_tpu.seed(seed)
    model = MoEForCausalLM(cfg)
    mesh = M.mesh_from_strategy(strategy)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 16))
                      .astype(np.int32))
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=opt or optim.AdamW(lr), strategy=strategy,
            mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"input_ids": ids, "labels": ids})
        losses = []
        for i in range(n):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    return losses, state


def _pp_ep_strategy(schedule="gpipe", microbatches=4, fsdp=0, ep=2):
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = microbatches
    s.pipeline.schedule = schedule
    s.expert_parallel.enable = True
    s.expert_parallel.degree = ep
    if fsdp:
        s.sharding.enable = True
        s.sharding.stage = 3
        s.sharding.degree = fsdp
    return s


def test_moe_gpipe_pp_ep_fsdp_matches_dp(devices8):
    """pp2×ep2×fsdp2 GPipe must reproduce the dp losses. aux weight 0 +
    generous capacity isolate schedule parity from the (documented)
    per-microbatch aux/capacity semantics; the expert all_to_all runs
    INSIDE the pipeline shard_map (ep stays an automatic axis of the
    partial-manual region)."""
    cfg = MoEConfig.tiny(num_experts=4, aux_loss_weight=0.0,
                         capacity_factor=4.0)
    pp_losses, pp_state = _pp_moe_run(
        _pp_ep_strategy("gpipe", fsdp=2), cfg)
    w = pp_state.model.blocks.block.moe.w_gate
    spec = w.sharding.spec
    assert spec[0] == "pp" and spec[1] == "ep", spec
    dp_losses, _ = _pp_moe_run(DistributedStrategy(), cfg)
    np.testing.assert_allclose(pp_losses, dp_losses, rtol=2e-4)


def test_moe_1f1b_pp_ep_matches_gpipe_with_aux(devices8):
    """1F1B pp2×ep2 with the aux loss ON must match GPipe (same
    microbatching → identical aux semantics): the schedule adds the
    taped aux to its loss and seeds its cotangent in the manual
    backward."""
    cfg = MoEConfig.tiny(num_experts=4, aux_loss_weight=0.05,
                         capacity_factor=4.0)
    g_losses, _ = _pp_moe_run(_pp_ep_strategy("gpipe"), cfg, n=4)
    f_losses, _ = _pp_moe_run(_pp_ep_strategy("1f1b"), cfg, n=4)
    np.testing.assert_allclose(f_losses, g_losses, rtol=3e-4)
    # and the aux is genuinely included: a run with weight 0 differs
    cfg0 = MoEConfig.tiny(num_experts=4, aux_loss_weight=0.0,
                          capacity_factor=4.0)
    f0_losses, _ = _pp_moe_run(_pp_ep_strategy("1f1b"), cfg0, n=4)
    assert abs(f_losses[0] - f0_losses[0]) > 1e-4


def test_moe_1f1b_aux_gradients_match_reference(devices8):
    """Gradient-level check of the 1F1B aux cotangent seeding: one SGD
    step under pp2×ep2 must move the parameters exactly like jax.grad
    of the microbatched reference loss (mean over microbatch chunks of
    ce + taped aux). The router only receives gradient THROUGH the aux
    term's tape cotangent on tiny balanced data where ce barely moves
    it, so a mismatch here means dropped/mis-scaled seeds."""
    cfg = MoEConfig.tiny(num_experts=4, aux_loss_weight=0.1,
                         capacity_factor=4.0)
    M_mb = 4
    lr = 0.5
    losses, state = _pp_moe_run(
        _pp_ep_strategy("1f1b", microbatches=M_mb), cfg, n=1, lr=lr,
        opt=optim.SGD(lr), seed=23)
    stepped = jax.device_get(state.model)

    paddle_tpu.seed(23)
    ref_model = MoEForCausalLM(cfg)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 16))
                      .astype(np.int32))

    def ref_loss(m):
        total = 0.0
        for c in range(M_mb):
            chunk = ids[c * 2:(c + 1) * 2]
            total = total + m.loss(chunk, chunk, training=True)
        return total / M_mb

    grads = jax.grad(ref_loss)(ref_model)
    ref_stepped = jax.tree_util.tree_map(
        lambda p, g: p - lr * g.astype(p.dtype), ref_model, grads)

    got = np.asarray(stepped.blocks.block.moe.router, np.float32)
    want = np.asarray(ref_stepped.blocks.block.moe.router, np.float32)
    # router moved at all (aux gradient flowed) ...
    orig = np.asarray(ref_model.blocks.block.moe.router, np.float32)
    assert np.abs(want - orig).max() > 1e-6
    # ... and the pipeline's step matches the reference step
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-6)
    gw = np.asarray(stepped.blocks.block.moe.w_gate, np.float32)
    ww = np.asarray(ref_stepped.blocks.block.moe.w_gate, np.float32)
    np.testing.assert_allclose(gw, ww, rtol=2e-3, atol=2e-6)
