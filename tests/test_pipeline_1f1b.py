"""1F1B pipeline schedule: parity vs GPipe/DP + the memory bound.

Reference scheduling machinery: ``framework/section_worker.cc:44``. The
1F1B schedule is a pure re-ordering of the same math, so its losses and
gradients must match GPipe and plain DP bit-for-tolerance; its defining
property — peak live stage inputs bounded by the stage count, not the
microbatch count — is asserted via the ring-buffer size and compiled
memory analysis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.parallel import mesh as M
from paddle_tpu.parallel.pipeline_1f1b import ring_buffer_slots


def make_batch(bs=8, seq=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, vocab, (bs, seq)).astype(np.int32)
    return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}


def run_steps(strategy, n=6, cfg=None, lr=1e-2):
    paddle_tpu.seed(42)
    cfg = cfg or LlamaConfig.tiny(num_layers=4)
    model = LlamaForCausalLM(cfg)
    mesh = M.mesh_from_strategy(strategy)
    with M.MeshContext(mesh):
        opt = optim.AdamW(lr, grad_clip=optim.ClipGradByGlobalNorm(1.0))
        step = dist.fleet.build_train_step(model, optimizer=opt,
                                           strategy=strategy, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch(make_batch())
        losses = []
        for i in range(n):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    return losses, state, step


def _pp_strategy(schedule, microbatches=4, tp=1):
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = microbatches
    s.pipeline.schedule = schedule
    if tp > 1:
        s.tensor_parallel.enable = True
        s.tensor_parallel.degree = tp
    return s


def test_1f1b_matches_dp_losses(devices8):
    l_dp, _, _ = run_steps(DistributedStrategy())
    l_1f1b, state, _ = run_steps(_pp_strategy("1f1b"))
    np.testing.assert_allclose(l_dp, l_1f1b, rtol=2e-4, atol=2e-5)
    # layer dim actually sharded over pp
    wq = state.model.blocks.block.attn.wq.weight
    assert wq.sharding.spec[0] == "pp"


def test_1f1b_matches_gpipe_losses(devices8):
    l_g, _, _ = run_steps(_pp_strategy("gpipe"))
    l_1, _, _ = run_steps(_pp_strategy("1f1b"))
    np.testing.assert_allclose(l_g, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_composes_with_tp(devices8):
    l_dp, _, _ = run_steps(DistributedStrategy())
    l_1, _, _ = run_steps(_pp_strategy("1f1b", tp=2))
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_many_microbatches(devices8):
    """M >> S — the regime where the memory bound matters."""
    l_dp, _, _ = run_steps(DistributedStrategy())
    l_1, _, _ = run_steps(_pp_strategy("1f1b", microbatches=8))
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_uneven_ignore_index_matches_dp(devices8):
    """ignore_index tokens concentrated in some microbatches: the global
    valid-count normalization must keep parity with the DP mean loss."""
    batch = make_batch()
    labels = np.array(batch["labels"])  # np.asarray view of a jax array is read-only
    labels[:2, :] = -100          # microbatch 0 (M=4 → mb size 2) all pad
    labels[2, 1:14] = -100        # microbatch 1 nearly all pad
    batch = {"input_ids": batch["input_ids"],
             "labels": jnp.asarray(labels)}

    def run(strategy):
        paddle_tpu.seed(42)
        cfg = LlamaConfig.tiny(num_layers=4)
        model = LlamaForCausalLM(cfg)
        mesh = M.mesh_from_strategy(strategy)
        with M.MeshContext(mesh):
            opt = optim.AdamW(1e-2)
            step = dist.fleet.build_train_step(model, optimizer=opt,
                                               strategy=strategy, mesh=mesh)
            state = step.init_state(model)
            b = step.shard_batch(batch)
            losses = []
            for i in range(4):
                state, metrics = step(state, b, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        return losses

    l_dp = run(DistributedStrategy())
    l_1f1b = run(_pp_strategy("1f1b"))
    np.testing.assert_allclose(l_dp, l_1f1b, rtol=2e-4, atol=2e-5)


def test_ring_buffer_bound_independent_of_microbatches():
    """The 1F1B point: saved stage inputs bounded by stages, not M."""
    assert ring_buffer_slots(num_stages=2, num_microbatches=64) == 3
    assert ring_buffer_slots(num_stages=4, num_microbatches=256) == 7
    # degenerate: fewer microbatches than the window
    assert ring_buffer_slots(num_stages=4, num_microbatches=2) == 2


def test_1f1b_peak_memory_below_gpipe(devices8):
    """Compiled peak temp memory of the 1F1B step must undercut GPipe
    once M is large (GPipe saves O(M) stage inputs for the backward)."""
    cfg = LlamaConfig.tiny(num_layers=4)

    def compile_step(schedule):
        paddle_tpu.seed(0)
        model = LlamaForCausalLM(cfg)
        s = _pp_strategy(schedule, microbatches=8)
        mesh = M.mesh_from_strategy(s)
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-2), strategy=s, mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch(make_batch(bs=16, seq=32))
            specs = step._state_specs_fn(state)
            from jax.sharding import NamedSharding
            shardings = jax.tree_util.tree_map(
                lambda sp: NamedSharding(mesh, sp), specs,
                is_leaf=lambda x: isinstance(
                    x, jax.sharding.PartitionSpec))
            lowered = jax.jit(
                step._step_fn,
                in_shardings=(shardings, None, None)).lower(
                state, batch, jax.random.PRNGKey(0))
            compiled = lowered.compile()
            mem = compiled.memory_analysis()
        return getattr(mem, "temp_size_in_bytes", None)

    t_1f1b = compile_step("1f1b")
    t_gpipe = compile_step("gpipe")
    if t_1f1b is None or t_gpipe is None or t_gpipe == 0:
        pytest.skip("memory_analysis not available on this backend")
    assert t_1f1b < t_gpipe, (t_1f1b, t_gpipe)


def test_unknown_schedule_rejected(devices8):
    s = _pp_strategy("interleaved")
    with pytest.raises(ValueError, match="schedule"):
        run_steps(s, n=1)


def test_1f1b_tied_embeddings_matches_dp(devices8):
    """Tied lm head: the head carries the embedding table; its gradient
    must hop back into the embedding gradient (assemble sums it)."""
    cfg = LlamaConfig.tiny(num_layers=4, tie_embeddings=True)
    l_dp, _, _ = run_steps(DistributedStrategy(), cfg=cfg)
    l_1, _, _ = run_steps(_pp_strategy("1f1b"), cfg=cfg)
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_amp_bf16_matches_dp_amp(devices8):
    """AMP autocast composes with 1F1B: the model is cast to bf16, grads
    land on the fp32 masters (fp32 accumulators inside the schedule), and
    losses must track the plain DP AMP path within bf16 tolerance.

    (The comparison baseline is DP+amp, not GPipe+amp: jax.grad of the
    GPipe scan in bf16 trips an XLA *CPU* emitter crash — the minimal
    vjp-in-scan-in-shard_map bf16 pattern compiles fine on the TPU
    backend.)"""
    s_dp = DistributedStrategy()
    s_dp.amp.enable = True
    s_dp.amp.dtype = "bfloat16"
    s_pp = _pp_strategy("1f1b")
    s_pp.amp.enable = True
    s_pp.amp.dtype = "bfloat16"

    l_dp, _, _ = run_steps(s_dp)
    l_1, _, _ = run_steps(s_pp)
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-2, atol=2e-2)
    # and training still converges
    assert l_1[-1] < l_1[0]


def test_1f1b_fp16_dynamic_loss_scaling(devices8):
    """fp16 + dynamic scaler: the scale multiplies the backward seed and
    unscale restores the gradients — losses stay finite and fall."""
    s = _pp_strategy("1f1b")
    s.amp.enable = True
    s.amp.dtype = "float16"
    losses, state, _ = run_steps(s, n=4)
    assert np.isfinite(losses).all(), losses
    assert float(state.scaler.loss_scaling) > 0
    assert losses[-1] < losses[0]


def test_1f1b_dropout_replay(devices8):
    """Dropout inside pipelined blocks: (a) deterministic per key, (b)
    key-sensitive, (c) gradients consistent with finite differences —
    which holds ONLY if the backward recompute replays the forward's
    masks (SectionWorker semantics)."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.parallel import pipeline_1f1b
    from paddle_tpu.parallel.pipeline import pipeline_blocks

    paddle_tpu.seed(7)
    cfg = GPTConfig.tiny(num_layers=4, dropout=0.3)
    model = GPTForCausalLM(cfg)
    s = _pp_strategy("1f1b")
    mesh = M.mesh_from_strategy(s)
    model = model.replace(blocks=pipeline_blocks(model.blocks, 2, 4,
                                                 mesh=mesh))
    batch = make_batch()

    with M.MeshContext(mesh):
        run = jax.jit(lambda m, k: pipeline_1f1b.loss_and_grads(
            m, batch, mesh, key=k))
        k0 = jax.random.PRNGKey(0)
        loss_a, grads_a = run(model, k0)
        loss_b, _ = run(model, k0)
        loss_c, _ = run(model, jax.random.PRNGKey(1))
        assert float(loss_a) == float(loss_b)          # deterministic
        assert float(loss_a) != float(loss_c)          # dropout active

        # directional FD along the gradient (same key → deterministic
        # loss surface; the directional signal eps·|g|² is far above f32
        # loss resolution, unlike a single-scalar probe)
        eps = 1e-3

        def loss_at(sign):
            m2 = jax.tree_util.tree_map(
                lambda p, g: p + sign * eps * g.astype(p.dtype)
                if hasattr(p, "dtype")
                and jnp.issubdtype(p.dtype, jnp.floating) else p,
                model, grads_a)
            l, _ = run(m2, k0)
            return float(l)

        fd = (loss_at(+1.0) - loss_at(-1.0)) / (2 * eps)
        gsq = float(sum(
            jnp.sum(jnp.square(g)) for g in
            jax.tree_util.tree_leaves(grads_a)
            if hasattr(g, "dtype") and jnp.issubdtype(g.dtype,
                                                      jnp.floating)))
        assert abs(fd - gsq) / (abs(gsq) + 1e-6) < 2e-2, (fd, gsq)
