"""1F1B pipeline schedule: parity vs GPipe/DP + the memory bound.

Reference scheduling machinery: ``framework/section_worker.cc:44``. The
1F1B schedule is a pure re-ordering of the same math, so its losses and
gradients must match GPipe and plain DP bit-for-tolerance; its defining
property — peak live stage inputs bounded by the stage count, not the
microbatch count — is asserted via the ring-buffer size and compiled
memory analysis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.parallel import mesh as M
from paddle_tpu.parallel.pipeline_1f1b import ring_buffer_slots


def make_batch(bs=8, seq=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, vocab, (bs, seq)).astype(np.int32)
    return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}


def run_steps(strategy, n=6, cfg=None, lr=1e-2):
    paddle_tpu.seed(42)
    cfg = cfg or LlamaConfig.tiny(num_layers=4)
    model = LlamaForCausalLM(cfg)
    mesh = M.mesh_from_strategy(strategy)
    with M.MeshContext(mesh):
        opt = optim.AdamW(lr, grad_clip=optim.ClipGradByGlobalNorm(1.0))
        step = dist.fleet.build_train_step(model, optimizer=opt,
                                           strategy=strategy, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch(make_batch())
        losses = []
        for i in range(n):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    return losses, state, step


def _pp_strategy(schedule, microbatches=4, tp=1):
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = microbatches
    s.pipeline.schedule = schedule
    if tp > 1:
        s.tensor_parallel.enable = True
        s.tensor_parallel.degree = tp
    return s


def test_1f1b_matches_dp_losses(devices8):
    l_dp, _, _ = run_steps(DistributedStrategy())
    l_1f1b, state, _ = run_steps(_pp_strategy("1f1b"))
    np.testing.assert_allclose(l_dp, l_1f1b, rtol=2e-4, atol=2e-5)
    # layer dim actually sharded over pp
    wq = state.model.blocks.block.attn.wq.weight
    assert wq.sharding.spec[0] == "pp"


def test_1f1b_matches_gpipe_losses(devices8):
    l_g, _, _ = run_steps(_pp_strategy("gpipe"))
    l_1, _, _ = run_steps(_pp_strategy("1f1b"))
    np.testing.assert_allclose(l_g, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_composes_with_tp(devices8):
    l_dp, _, _ = run_steps(DistributedStrategy())
    l_1, _, _ = run_steps(_pp_strategy("1f1b", tp=2))
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_many_microbatches(devices8):
    """M >> S — the regime where the memory bound matters."""
    l_dp, _, _ = run_steps(DistributedStrategy())
    l_1, _, _ = run_steps(_pp_strategy("1f1b", microbatches=8))
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_uneven_ignore_index_matches_dp(devices8):
    """ignore_index tokens concentrated in some microbatches: the global
    valid-count normalization must keep parity with the DP mean loss."""
    batch = make_batch()
    labels = np.array(batch["labels"])  # np.asarray view of a jax array is read-only
    labels[:2, :] = -100          # microbatch 0 (M=4 → mb size 2) all pad
    labels[2, 1:14] = -100        # microbatch 1 nearly all pad
    batch = {"input_ids": batch["input_ids"],
             "labels": jnp.asarray(labels)}

    def run(strategy):
        paddle_tpu.seed(42)
        cfg = LlamaConfig.tiny(num_layers=4)
        model = LlamaForCausalLM(cfg)
        mesh = M.mesh_from_strategy(strategy)
        with M.MeshContext(mesh):
            opt = optim.AdamW(1e-2)
            step = dist.fleet.build_train_step(model, optimizer=opt,
                                               strategy=strategy, mesh=mesh)
            state = step.init_state(model)
            b = step.shard_batch(batch)
            losses = []
            for i in range(4):
                state, metrics = step(state, b, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        return losses

    l_dp = run(DistributedStrategy())
    l_1f1b = run(_pp_strategy("1f1b"))
    np.testing.assert_allclose(l_dp, l_1f1b, rtol=2e-4, atol=2e-5)


def test_ring_buffer_bound_independent_of_microbatches():
    """The 1F1B point: saved stage inputs bounded by stages, not M."""
    assert ring_buffer_slots(num_stages=2, num_microbatches=64) == 3
    assert ring_buffer_slots(num_stages=4, num_microbatches=256) == 7
    # degenerate: fewer microbatches than the window
    assert ring_buffer_slots(num_stages=4, num_microbatches=2) == 2


def test_1f1b_peak_memory_below_gpipe(devices8):
    """Compiled peak temp memory of the 1F1B step must undercut GPipe
    once M is large (GPipe saves O(M) stage inputs for the backward)."""
    cfg = LlamaConfig.tiny(num_layers=4)

    def compile_step(schedule):
        paddle_tpu.seed(0)
        model = LlamaForCausalLM(cfg)
        s = _pp_strategy(schedule, microbatches=8)
        mesh = M.mesh_from_strategy(s)
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-2), strategy=s, mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch(make_batch(bs=16, seq=32))
            specs = step._state_specs_fn(state)
            from jax.sharding import NamedSharding
            shardings = jax.tree_util.tree_map(
                lambda sp: NamedSharding(mesh, sp), specs,
                is_leaf=lambda x: isinstance(
                    x, jax.sharding.PartitionSpec))
            lowered = jax.jit(
                step._step_fn,
                in_shardings=(shardings, None, None)).lower(
                state, batch, jax.random.PRNGKey(0))
            compiled = lowered.compile()
            mem = compiled.memory_analysis()
        return getattr(mem, "temp_size_in_bytes", None)

    t_1f1b = compile_step("1f1b")
    t_gpipe = compile_step("gpipe")
    if t_1f1b is None or t_gpipe is None or t_gpipe == 0:
        pytest.skip("memory_analysis not available on this backend")
    assert t_1f1b < t_gpipe, (t_1f1b, t_gpipe)


def test_unknown_schedule_rejected(devices8):
    s = _pp_strategy("interleaved")
    with pytest.raises(ValueError, match="schedule"):
        run_steps(s, n=1)


def test_1f1b_tied_embeddings_matches_dp(devices8):
    """Tied lm head: the head carries the embedding table; its gradient
    must hop back into the embedding gradient (assemble sums it)."""
    cfg = LlamaConfig.tiny(num_layers=4, tie_embeddings=True)
    l_dp, _, _ = run_steps(DistributedStrategy(), cfg=cfg)
    l_1, _, _ = run_steps(_pp_strategy("1f1b"), cfg=cfg)
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-4, atol=2e-5)


def test_1f1b_amp_bf16_matches_dp_amp(devices8):
    """AMP autocast composes with 1F1B: the model is cast to bf16, grads
    land on the fp32 masters (fp32 accumulators inside the schedule), and
    losses must track the plain DP AMP path within bf16 tolerance.

    (The comparison baseline is DP+amp, not GPipe+amp: jax.grad of the
    GPipe scan in bf16 trips an XLA *CPU* emitter crash — the minimal
    vjp-in-scan-in-shard_map bf16 pattern compiles fine on the TPU
    backend.)"""
    s_dp = DistributedStrategy()
    s_dp.amp.enable = True
    s_dp.amp.dtype = "bfloat16"
    s_pp = _pp_strategy("1f1b")
    s_pp.amp.enable = True
    s_pp.amp.dtype = "bfloat16"

    l_dp, _, _ = run_steps(s_dp)
    l_1, _, _ = run_steps(s_pp)
    np.testing.assert_allclose(l_dp, l_1, rtol=2e-2, atol=2e-2)
    # and training still converges
    assert l_1[-1] < l_1[0]


def test_1f1b_fp16_dynamic_loss_scaling(devices8):
    """fp16 + dynamic scaler: the scale multiplies the backward seed and
    unscale restores the gradients — losses stay finite and fall."""
    s = _pp_strategy("1f1b")
    s.amp.enable = True
    s.amp.dtype = "float16"
    losses, state, _ = run_steps(s, n=4)
    assert np.isfinite(losses).all(), losses
    assert float(state.scaler.loss_scaling) > 0
    assert losses[-1] < losses[0]


def test_1f1b_dropout_replay(devices8):
    """Dropout inside pipelined blocks: (a) deterministic per key, (b)
    key-sensitive, (c) gradients consistent with finite differences —
    which holds ONLY if the backward recompute replays the forward's
    masks (SectionWorker semantics)."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.parallel import pipeline_1f1b
    from paddle_tpu.parallel.pipeline import pipeline_blocks

    paddle_tpu.seed(7)
    cfg = GPTConfig.tiny(num_layers=4, dropout=0.3)
    model = GPTForCausalLM(cfg)
    s = _pp_strategy("1f1b")
    mesh = M.mesh_from_strategy(s)
    model = model.replace(blocks=pipeline_blocks(model.blocks, 2, 4,
                                                 mesh=mesh))
    batch = make_batch()

    with M.MeshContext(mesh):
        run = jax.jit(lambda m, k: pipeline_1f1b.loss_and_grads(
            m, batch, mesh, key=k))
        k0 = jax.random.PRNGKey(0)
        loss_a, grads_a, _ = run(model, k0)
        loss_b, _, _ = run(model, k0)
        loss_c, _, _ = run(model, jax.random.PRNGKey(1))
        assert float(loss_a) == float(loss_b)          # deterministic
        assert float(loss_a) != float(loss_c)          # dropout active

        # directional FD along the gradient (same key → deterministic
        # loss surface; the directional signal eps·|g|² is far above f32
        # loss resolution, unlike a single-scalar probe)
        eps = 1e-3

        def loss_at(sign):
            m2 = jax.tree_util.tree_map(
                lambda p, g: p + sign * eps * g.astype(p.dtype)
                if hasattr(p, "dtype")
                and jnp.issubdtype(p.dtype, jnp.floating) else p,
                model, grads_a)
            l, _, _ = run(m2, k0)
            return float(l)

        fd = (loss_at(+1.0) - loss_at(-1.0)) / (2 * eps)
        gsq = float(sum(
            jnp.sum(jnp.square(g)) for g in
            jax.tree_util.tree_leaves(grads_a)
            if hasattr(g, "dtype") and jnp.issubdtype(g.dtype,
                                                      jnp.floating)))
        assert abs(fd - gsq) / (abs(gsq) + 1e-6) < 2e-2, (fd, gsq)


# ---------------------------------------------------------------------------
# r4 generality: custom head loss + stateful (BatchNorm) blocks
# ---------------------------------------------------------------------------

def _smoothed_loss_fns(eps=0.1, vocab=256):
    """The same label-smoothed CE expressed both ways: as a generic
    (model, batch) loss for DP/GPipe, and as a per-microbatch 1F1B head
    loss (labels arrive pre-shifted there)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.parallel import pipeline_1f1b as P1

    def smooth_ce(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        nll = -jnp.take_along_axis(logp, safe[..., None], -1)[..., 0]
        uni = -jnp.mean(logp, axis=-1)
        per = (1 - eps) * nll + eps * uni
        return jnp.where(valid, per, 0.0)

    def generic(m, batch, training=True):
        logits = m(batch["input_ids"], training=training)
        labels = batch["labels"]
        lab = jnp.concatenate(
            [labels[:, 1:], jnp.full((labels.shape[0], 1), -100,
                                     labels.dtype)], axis=1)
        per = smooth_ce(logits, lab)
        return jnp.sum(per) / jnp.maximum(
            jnp.sum((lab != -100).astype(jnp.float32)), 1.0)

    @P1.head_loss
    def head(head_p, h, labels):
        norm, out = head_p
        logits = out(norm(h)).astype(jnp.float32)
        return jnp.sum(smooth_ce(logits, labels))

    return generic, head


def test_1f1b_custom_head_loss_matches_dp(devices8):
    """A user loss (label-smoothed CE) threads into the 1F1B last stage
    via the head_loss marker and matches the same loss computed
    generically under DP — the reference's arbitrary-section-program
    capability (section_worker.cc:44)."""
    generic, head = _smoothed_loss_fns()

    def run(strategy, loss_fn):
        paddle_tpu.seed(42)
        model = LlamaForCausalLM(LlamaConfig.tiny(num_layers=4))
        mesh = M.mesh_from_strategy(strategy)
        with M.MeshContext(mesh):
            step = dist.fleet.build_train_step(
                model, optimizer=optim.AdamW(1e-2), loss_fn=loss_fn,
                strategy=strategy, mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch(make_batch())
            losses = []
            for i in range(4):
                state, metrics = step(state, batch, jax.random.PRNGKey(i))
                losses.append(float(metrics["loss"]))
        return losses

    l_dp = run(DistributedStrategy(), generic)
    l_1f = run(_pp_strategy("1f1b"), head)
    np.testing.assert_allclose(l_dp, l_1f, rtol=2e-4, atol=2e-5)


def test_1f1b_generic_loss_fn_still_rejected(devices8):
    s = _pp_strategy("1f1b")
    mesh = M.mesh_from_strategy(s)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_layers=4))
    with M.MeshContext(mesh):
        with pytest.raises(ValueError, match="head_loss"):
            dist.fleet.build_train_step(
                model, optimizer=optim.SGD(1e-2),
                loss_fn=lambda m, b, training=True: 0.0,
                strategy=s, mesh=mesh)


class _BNBlock(paddle_tpu.nn.Module):
    """Residual Linear+BatchNorm block (stateful: running stats)."""

    def __init__(self, e, key=None):
        from paddle_tpu import nn
        from paddle_tpu.core import rng as _rng
        k1, _ = _rng.split_key(key)
        self.fc = nn.Linear(e, e, key=k1)
        self.bn = nn.BatchNorm1D(e, data_format="NHWC", momentum=0.8)

    def __call__(self, x, training: bool = False):
        return x + jax.nn.relu(self.bn(self.fc(x), training=training))


class _BNToyLM(paddle_tpu.nn.Module):
    """Pipeline-decomposable toy LM with stateful blocks."""

    def __init__(self, vocab=64, e=32, n_layers=4, key=None):
        from paddle_tpu import nn
        from paddle_tpu.core import rng as _rng
        keys = _rng.split_key(key, 2 + n_layers)
        self.embed = nn.Embedding(vocab, e, key=keys[0])
        from paddle_tpu.nn.scan import ScannedBlocks
        self.blocks = ScannedBlocks(
            lambda i: _BNBlock(e, key=keys[2 + i]), n_layers)
        self.head = nn.Linear(e, vocab, key=keys[1])
        self.vocab = vocab

    def loss(self, input_ids, labels, training: bool = True):
        import paddle_tpu.nn.functional as F
        x = self.embed(input_ids)
        x = self.blocks(x, training=training)
        logits = self.head(x).astype(jnp.float32)
        lab = jnp.concatenate(
            [labels[:, 1:], jnp.full((labels.shape[0], 1), -100,
                                     labels.dtype)], axis=1)
        return F.cross_entropy(logits, lab)

    def pipeline_parts(self):
        import paddle_tpu.nn.functional as F

        def head_loss_sum(head, h, labels):
            return F.cross_entropy(head(h).astype(jnp.float32), labels,
                                   reduction="sum")

        from paddle_tpu.parallel.pipeline_1f1b import default_loss_denom
        model = self

        def assemble(dembed, dblocks, dhead):
            g = jax.tree_util.tree_map(jnp.zeros_like, model)
            return g.replace(embed=dembed, head=dhead,
                             blocks=g.blocks.replace(block=dblocks))

        return (self.embed, self.blocks, self.head, head_loss_sum,
                default_loss_denom, assemble)


_STATEFUL_RUNS: dict = {}


def _run_stateful(schedule):
    """Train the BN toy 3 steps under one executor; cached per schedule
    so the parametrized checks and the cross-executor comparison don't
    re-run the compile+train work."""
    if schedule in _STATEFUL_RUNS:
        return _STATEFUL_RUNS[schedule]
    paddle_tpu.seed(7)
    model = _BNToyLM()
    if schedule == "dp":
        s = DistributedStrategy()
    else:
        s = _pp_strategy(schedule, microbatches=1)
    mesh = M.mesh_from_strategy(s)
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(1e-2), strategy=s, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch(make_batch(vocab=64))
        rm0 = np.asarray(state.model.blocks.block.bn.running_mean)
        losses = []
        for i in range(3):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    rm = np.asarray(state.model.blocks.block.bn.running_mean)
    rv = np.asarray(state.model.blocks.block.bn.running_var)
    _STATEFUL_RUNS[schedule] = (losses, rm0, rm, rv)
    return _STATEFUL_RUNS[schedule]


@pytest.mark.parametrize("schedule", ["dp", "gpipe", "1f1b"])
def test_stateful_blocks_update_running_stats(devices8, schedule):
    """BatchNorm inside (scanned / GPipe'd / 1F1B'd) blocks: running
    stats must update through the executor's tape path."""
    _, rm0, rm, rv = _run_stateful(schedule)
    assert rm.shape[0] == 4          # stacked per layer
    assert np.all(np.isfinite(rm)) and np.all(np.isfinite(rv))
    assert np.abs(rm - rm0).max() > 1e-6, "stats never updated"


def test_stateful_blocks_match_across_executors(devices8):
    """With M=1 microbatch all three executors see the full batch, so
    losses AND merged running stats must agree exactly (per-microbatch
    statistics only differ for M>1 — standard microbatch-BN
    semantics)."""
    dp = _run_stateful("dp")
    for sched in ("gpipe", "1f1b"):
        other = _run_stateful(sched)
        np.testing.assert_allclose(dp[0], other[0], rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(dp[2], other[2], rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(dp[3], other[3], rtol=1e-4, atol=1e-5)
