"""OPS_AUDIT.md stays complete: every enumerated reference op classifies,
and a sample of 'implemented' claims point at real attributes."""

import importlib
import os
import re
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
TOOLS = os.path.join(HERE, "..", "tools")
sys.path.insert(0, TOOLS)


def test_every_reference_op_is_classified():
    gen = importlib.import_module("gen_ops_audit")
    ops = open(os.path.join(TOOLS, "ref_ops.txt")).read().split()
    assert len(ops) > 450
    unmapped = [op for op in ops if gen.classify(op) is None]
    assert not unmapped, unmapped


@pytest.mark.parametrize("api", [
    ("paddle_tpu.ops.extras", "temporal_shift"),
    ("paddle_tpu.ops.extras", "gather_tree"),
    ("paddle_tpu.ops.extras", "max_unpool2d"),
    ("paddle_tpu.vision.ops", "generate_proposals"),
    ("paddle_tpu.vision.ops", "target_assign"),
    ("paddle_tpu.ops.sequence", "segment_mean"),
    ("paddle_tpu.nn.rnn", "LSTM"),
    ("paddle_tpu.nn.functional", "interpolate"),
    ("paddle_tpu.nn.functional", "row_conv"),
    ("paddle_tpu.metric", "Auc"),
])
def test_sampled_implemented_claims_exist(api):
    mod, name = api
    assert hasattr(importlib.import_module(mod), name), api
