"""Checkpoint tests: state dicts and orbax sharded save/restore."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import io


def test_state_dict_roundtrip(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "model")
    io.save_state_dict(m, path)

    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = jnp.ones((2, 4))
    assert not np.allclose(m(x), m2(x))
    m2 = io.load_state_dict(m2, path)
    np.testing.assert_allclose(m(x), m2(x), rtol=1e-6)


def test_state_dict_strict_mismatch(tmp_path):
    m = nn.Linear(4, 8)
    path = str(tmp_path / "model")
    io.save_state_dict(m, path)
    wrong = nn.Linear(4, 9)
    with pytest.raises(ValueError):
        io.load_state_dict(wrong, path)


def test_orbax_checkpoint_roundtrip(tmp_path):
    m = nn.Linear(4, 4)
    from paddle_tpu import optimizer as opt

    o = opt.Adam(1e-3)
    state = o.init(m)
    tree = {"model": m, "opt": state, "step": jnp.asarray(7)}
    d = str(tmp_path / "ckpt")
    io.save_checkpoint(tree, d, step=7)
    io.checkpoint.wait_until_finished(d)
    restored = io.load_checkpoint(tree, d)
    assert int(restored["step"]) == 7
    np.testing.assert_allclose(restored["model"].weight, m.weight)
