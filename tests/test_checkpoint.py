"""Checkpoint tests: state dicts and orbax sharded save/restore."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import io


def test_state_dict_roundtrip(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "model")
    io.save_state_dict(m, path)

    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = jnp.ones((2, 4))
    assert not np.allclose(m(x), m2(x))
    m2 = io.load_state_dict(m2, path)
    np.testing.assert_allclose(m(x), m2(x), rtol=1e-6)


def test_state_dict_strict_mismatch(tmp_path):
    m = nn.Linear(4, 8)
    path = str(tmp_path / "model")
    io.save_state_dict(m, path)
    wrong = nn.Linear(4, 9)
    with pytest.raises(ValueError):
        io.load_state_dict(wrong, path)


def test_orbax_checkpoint_roundtrip(tmp_path):
    m = nn.Linear(4, 4)
    from paddle_tpu import optimizer as opt

    o = opt.Adam(1e-3)
    state = o.init(m)
    tree = {"model": m, "opt": state, "step": jnp.asarray(7)}
    d = str(tmp_path / "ckpt")
    io.save_checkpoint(tree, d, step=7)
    io.checkpoint.wait_until_finished(d)
    restored = io.load_checkpoint(tree, d)
    assert int(restored["step"]) == 7
    np.testing.assert_allclose(restored["model"].weight, m.weight)


def _toy_training(tmp_path, n_epochs, crash_after=None, ckdir=None):
    """One optimizer step per epoch on fixed data; returns loss curve.
    With crash_after=k, stops after k epochs without a clean shutdown
    (the kill); a later call with the same ckdir resumes."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.parallel import mesh as M

    paddle_tpu.seed(11)
    model = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 1))
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 6).astype(np.float32))
    y = jnp.asarray(rs.randn(8, 1).astype(np.float32))

    def loss_fn(m, batch, training=True):
        return jnp.mean((m(batch["x"]) - batch["y"]) ** 2)

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.Adam(1e-2), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"x": x, "y": y})

        r = io.TrainEpochRange(n_epochs, str(ckdir), state=state)
        state = r.state
        losses = {}
        for epoch in r:
            state, metrics = step(state, batch, jax.random.PRNGKey(epoch))
            losses[epoch] = float(metrics["loss"])
            r.state = state
            if crash_after is not None and epoch + 1 >= crash_after:
                r.flush()   # async save durability; the "kill" is that we
                break       # never run the remaining epochs
        r.flush()
        return losses, r


def test_auto_checkpoint_kill_and_resume(tmp_path):
    """Interrupted-then-resumed training must reproduce the uninterrupted
    loss curve exactly (auto_checkpoint.py:71 train_epoch_range contract)."""
    ref, _ = _toy_training(tmp_path, 6, ckdir=tmp_path / "ref")
    assert sorted(ref) == list(range(6))

    part1, r1 = _toy_training(tmp_path, 6, crash_after=3,
                              ckdir=tmp_path / "killed")
    assert sorted(part1) == [0, 1, 2]
    assert not r1.resumed

    # the break escapes the generator before epoch 2's post-yield save, so
    # resume restores end-of-epoch-1 state and recomputes epoch 2 — real
    # kill semantics (at most the unsaved epoch is redone)
    part2, r2 = _toy_training(tmp_path, 6, ckdir=tmp_path / "killed")
    assert r2.resumed and sorted(part2) == [2, 3, 4, 5]

    merged = {**part1, **part2}
    np.testing.assert_allclose([merged[e] for e in range(6)],
                               [ref[e] for e in range(6)], rtol=1e-6)


def test_auto_checkpoint_fresh_run_no_resume(tmp_path):
    losses, r = _toy_training(tmp_path, 2, ckdir=tmp_path / "fresh")
    assert not r.resumed
    assert sorted(losses) == [0, 1]


def test_encrypted_state_dict_roundtrip(tmp_path):
    """AES-GCM encrypted save/load (reference aes_cipher.cc role):
    round-trips with the right key, fails loudly with the wrong key or a
    tampered file."""
    paddle_tpu.seed(5)
    model = nn.Linear(4, 3)
    path = str(tmp_path / "model.enc")
    io.save_state_dict_encrypted(model, path, key="hunter2")

    blank = nn.Linear(4, 3)
    restored = io.load_state_dict_encrypted(blank, path, key="hunter2")
    np.testing.assert_array_equal(np.asarray(restored.weight),
                                  np.asarray(model.weight))

    with pytest.raises(Exception):
        io.load_state_dict_encrypted(blank, path, key="wrong")

    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(Exception):
        io.load_state_dict_encrypted(blank, path, key="hunter2")

    kb = io.generate_key()
    io.save_state_dict_encrypted(model, path, key=kb)
    r2 = io.load_state_dict_encrypted(blank, path, key=kb)
    np.testing.assert_array_equal(np.asarray(r2.weight),
                                  np.asarray(model.weight))


def test_auto_checkpoint_resume_on_different_topology(tmp_path):
    """Resume a dp-only run as zero2-sharded (different mesh layout): the
    orbax restore reshapes shards onto the new topology and the loss
    curve continues exactly — the elastic-resume property the reference's
    per-rank scope dumps cannot offer."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.core.strategy import DistributedStrategy
    from paddle_tpu.parallel import mesh as M

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, 6).astype(np.float32))
    y = jnp.asarray(rs.randn(16, 1).astype(np.float32))

    def loss_fn(m, batch, training=True):
        return jnp.mean((m(batch["x"]) - batch["y"]) ** 2)

    def build(strategy):
        paddle_tpu.seed(21)
        model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 1))
        mesh = M.mesh_from_strategy(strategy)
        ctx = M.MeshContext(mesh)
        ctx.__enter__()
        step = dist.fleet.build_train_step(
            model, optimizer=optim.Adam(1e-2), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"x": x, "y": y})
        return step, state, batch, ctx

    ckdir = str(tmp_path / "topo")

    # phase 1: pure dp over 8 devices, run 3 epochs, save
    s1 = DistributedStrategy()
    step, state, batch, ctx = build(s1)
    r = io.TrainEpochRange(6, ckdir, state=state)
    losses = {}
    for epoch in r:
        state, metrics = step(state, batch, jax.random.PRNGKey(epoch))
        losses[epoch] = float(metrics["loss"])
        r.state = state
        if epoch == 2:
            break
    r.flush()
    ctx.__exit__(None, None, None)

    # phase 2: SAME job resumed as zero-2 over (dp=4, fsdp=2)
    s2 = DistributedStrategy()
    s2.sharding.enable = True
    s2.sharding.stage = 2
    s2.sharding.degree = 2
    step2, state2, batch2, ctx2 = build(s2)
    r2 = io.TrainEpochRange(6, ckdir, state=state2)
    assert r2.resumed
    state2 = r2.state
    for epoch in r2:
        state2, metrics = step2(state2, batch2, jax.random.PRNGKey(epoch))
        losses[epoch] = float(metrics["loss"])
        r2.state = state2
    r2.flush()
    ctx2.__exit__(None, None, None)

    # reference: one uninterrupted dp run
    s3 = DistributedStrategy()
    step3, state3, batch3, ctx3 = build(s3)
    ref = []
    for epoch in range(6):
        state3, metrics = step3(state3, batch3, jax.random.PRNGKey(epoch))
        ref.append(float(metrics["loss"]))
    ctx3.__exit__(None, None, None)

    got = [losses[e] for e in range(6)]
    np.testing.assert_allclose(got, ref, rtol=1e-5)
