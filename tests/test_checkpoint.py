"""Checkpoint tests: state dicts and orbax sharded save/restore."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu import io


def test_state_dict_roundtrip(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = str(tmp_path / "model")
    io.save_state_dict(m, path)

    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = jnp.ones((2, 4))
    assert not np.allclose(m(x), m2(x))
    m2 = io.load_state_dict(m2, path)
    np.testing.assert_allclose(m(x), m2(x), rtol=1e-6)


def test_state_dict_strict_mismatch(tmp_path):
    m = nn.Linear(4, 8)
    path = str(tmp_path / "model")
    io.save_state_dict(m, path)
    wrong = nn.Linear(4, 9)
    with pytest.raises(ValueError):
        io.load_state_dict(wrong, path)


def test_orbax_checkpoint_roundtrip(tmp_path):
    m = nn.Linear(4, 4)
    from paddle_tpu import optimizer as opt

    o = opt.Adam(1e-3)
    state = o.init(m)
    tree = {"model": m, "opt": state, "step": jnp.asarray(7)}
    d = str(tmp_path / "ckpt")
    io.save_checkpoint(tree, d, step=7)
    io.checkpoint.wait_until_finished(d)
    restored = io.load_checkpoint(tree, d)
    assert int(restored["step"]) == 7
    np.testing.assert_allclose(restored["model"].weight, m.weight)


def _toy_training(tmp_path, n_epochs, crash_after=None, ckdir=None):
    """One optimizer step per epoch on fixed data; returns loss curve.
    With crash_after=k, stops after k epochs without a clean shutdown
    (the kill); a later call with the same ckdir resumes."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.parallel import mesh as M

    paddle_tpu.seed(11)
    model = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 1))
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 6).astype(np.float32))
    y = jnp.asarray(rs.randn(8, 1).astype(np.float32))

    def loss_fn(m, batch, training=True):
        return jnp.mean((m(batch["x"]) - batch["y"]) ** 2)

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.Adam(1e-2), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"x": x, "y": y})

        r = io.TrainEpochRange(n_epochs, str(ckdir), state=state)
        state = r.state
        losses = {}
        for epoch in r:
            state, metrics = step(state, batch, jax.random.PRNGKey(epoch))
            losses[epoch] = float(metrics["loss"])
            r.state = state
            if crash_after is not None and epoch + 1 >= crash_after:
                r.flush()   # async save durability; the "kill" is that we
                break       # never run the remaining epochs
        r.flush()
        return losses, r


def test_auto_checkpoint_kill_and_resume(tmp_path):
    """Interrupted-then-resumed training must reproduce the uninterrupted
    loss curve exactly (auto_checkpoint.py:71 train_epoch_range contract)."""
    ref, _ = _toy_training(tmp_path, 6, ckdir=tmp_path / "ref")
    assert sorted(ref) == list(range(6))

    part1, r1 = _toy_training(tmp_path, 6, crash_after=3,
                              ckdir=tmp_path / "killed")
    assert sorted(part1) == [0, 1, 2]
    assert not r1.resumed

    # the break escapes the generator before epoch 2's post-yield save, so
    # resume restores end-of-epoch-1 state and recomputes epoch 2 — real
    # kill semantics (at most the unsaved epoch is redone)
    part2, r2 = _toy_training(tmp_path, 6, ckdir=tmp_path / "killed")
    assert r2.resumed and sorted(part2) == [2, 3, 4, 5]

    merged = {**part1, **part2}
    np.testing.assert_allclose([merged[e] for e in range(6)],
                               [ref[e] for e in range(6)], rtol=1e-6)


def test_auto_checkpoint_fresh_run_no_resume(tmp_path):
    losses, r = _toy_training(tmp_path, 2, ckdir=tmp_path / "fresh")
    assert not r.resumed
    assert sorted(losses) == [0, 1]


def test_encrypted_state_dict_roundtrip(tmp_path):
    """AES-GCM encrypted save/load (reference aes_cipher.cc role):
    round-trips with the right key, fails loudly with the wrong key or a
    tampered file."""
    paddle_tpu.seed(5)
    model = nn.Linear(4, 3)
    path = str(tmp_path / "model.enc")
    io.save_state_dict_encrypted(model, path, key="hunter2")

    blank = nn.Linear(4, 3)
    restored = io.load_state_dict_encrypted(blank, path, key="hunter2")
    np.testing.assert_array_equal(np.asarray(restored.weight),
                                  np.asarray(model.weight))

    with pytest.raises(Exception):
        io.load_state_dict_encrypted(blank, path, key="wrong")

    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(Exception):
        io.load_state_dict_encrypted(blank, path, key="hunter2")

    kb = io.generate_key()
    io.save_state_dict_encrypted(model, path, key=kb)
    r2 = io.load_state_dict_encrypted(blank, path, key=kb)
    np.testing.assert_array_equal(np.asarray(r2.weight),
                                  np.asarray(model.weight))


def _run_elastic_resume(ckdir, build, strategy1, strategy2, *, n_epochs,
                        break_epoch, rtol, check_restored=None):
    """Shared elastic-resume harness: phase 1 trains under ``strategy1``
    and is killed by breaking *inside* ``break_epoch``'s iteration —
    before that epoch's post-yield save — so the checkpoint on disk is
    ``break_epoch - 1``'s and the resumed phase re-trains ``break_epoch``
    (requires ``break_epoch >= 1``). Phase 2 resumes the SAME job under
    ``strategy2`` (resharded restore); the merged loss curve must match
    one uninterrupted ``strategy1`` run. Mesh contexts are closed on
    every path so a failing phase can't leak a global mesh into later
    tests."""
    assert break_epoch >= 1, "no checkpoint exists before epoch 0's save"
    losses = {}
    step, state, batch, ctx = build(strategy1)
    try:
        r = io.TrainEpochRange(n_epochs, ckdir, state=state)
        for epoch in r:
            state, metrics = step(state, batch, jax.random.PRNGKey(epoch))
            losses[epoch] = float(metrics["loss"])
            r.state = state
            if epoch == break_epoch:
                break
        r.flush()
    finally:
        ctx.__exit__(None, None, None)

    step2, state2, batch2, ctx2 = build(strategy2)
    try:
        r2 = io.TrainEpochRange(n_epochs, ckdir, state=state2)
        assert r2.resumed
        state2 = r2.state
        for epoch in r2:
            state2, metrics = step2(state2, batch2,
                                    jax.random.PRNGKey(epoch))
            losses[epoch] = float(metrics["loss"])
            r2.state = state2
        r2.flush()
        if check_restored is not None:
            check_restored(state2)
    finally:
        ctx2.__exit__(None, None, None)

    step3, state3, batch3, ctx3 = build(strategy1)
    try:
        ref = []
        for epoch in range(n_epochs):
            state3, metrics = step3(state3, batch3,
                                    jax.random.PRNGKey(epoch))
            ref.append(float(metrics["loss"]))
    finally:
        ctx3.__exit__(None, None, None)
    np.testing.assert_allclose([losses[e] for e in range(n_epochs)], ref,
                               rtol=rtol)


def test_auto_checkpoint_resume_on_different_topology(tmp_path):
    """Resume a dp-only run as zero2-sharded (different mesh layout): the
    orbax restore reshapes shards onto the new topology and the loss
    curve continues exactly — the elastic-resume property the reference's
    per-rank scope dumps cannot offer."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.core.strategy import DistributedStrategy
    from paddle_tpu.parallel import mesh as M

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, 6).astype(np.float32))
    y = jnp.asarray(rs.randn(16, 1).astype(np.float32))

    def loss_fn(m, batch, training=True):
        return jnp.mean((m(batch["x"]) - batch["y"]) ** 2)

    def build(strategy):
        paddle_tpu.seed(21)
        model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 1))
        mesh = M.mesh_from_strategy(strategy)
        ctx = M.MeshContext(mesh)
        ctx.__enter__()
        try:
            step = dist.fleet.build_train_step(
                model, optimizer=optim.Adam(1e-2), loss_fn=loss_fn,
                mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"x": x, "y": y})
        except BaseException:
            ctx.__exit__(None, None, None)
            raise
        return step, state, batch, ctx

    s2 = DistributedStrategy()
    s2.sharding.enable = True
    s2.sharding.stage = 2
    s2.sharding.degree = 2
    _run_elastic_resume(str(tmp_path / "topo"), build,
                        DistributedStrategy(), s2, n_epochs=6,
                        break_epoch=2, rtol=1e-5)


def test_auto_checkpoint_resume_into_tp_sharded_llama(tmp_path):
    """Elastic resume with a genuinely resharded parameter layout: a
    dp-only tiny-Llama run is resumed as zero3 x tp2 — Megatron-split
    weights (fsdp AND tp axes in the pspecs) restored from replicated
    shards. The loss curve must continue exactly as an uninterrupted dp
    run."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.core.strategy import DistributedStrategy
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.parallel import mesh as M

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(0, 256, (8, 16)).astype(np.int32))

    def build(strategy):
        paddle_tpu.seed(31)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        mesh = M.mesh_from_strategy(strategy)
        ctx = M.MeshContext(mesh)
        ctx.__enter__()
        try:
            step = dist.fleet.build_train_step(
                model, optimizer=optim.Adam(1e-3), strategy=strategy,
                mesh=mesh)
            state = step.init_state(model)
            batch = step.shard_batch({"input_ids": ids, "labels": ids})
        except BaseException:
            ctx.__exit__(None, None, None)
            raise
        return step, state, batch, ctx

    def check_restored(state2):
        # the restored params really are Megatron-split on the new mesh:
        # wq's spec must carry BOTH the fsdp and the tp axis
        spec = state2.model.blocks.block.attn.wq.weight.sharding.spec
        axes = {ax for part in spec if part
                for ax in (part if isinstance(part, tuple) else (part,))}
        assert {"tp", "fsdp"} <= axes, axes

    s2 = DistributedStrategy()
    s2.sharding.enable = True
    s2.sharding.stage = 3
    s2.sharding.degree = 2
    s2.tensor_parallel.enable = True
    s2.tensor_parallel.degree = 2
    _run_elastic_resume(str(tmp_path / "llama_topo"), build,
                        DistributedStrategy(), s2, n_epochs=5,
                        break_epoch=1, rtol=2e-4,
                        check_restored=check_restored)
