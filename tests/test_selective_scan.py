"""Pallas selective-scan kernel (Mamba recurrence): numerics vs the XLA
formulation in ``models/mamba.py`` (the spec), finite-difference gradient
checks in interpret mode (the OpTest pattern,
reference ``tests/unittests/op_test.py:1324``), and the partitioned
multi-chip path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import importlib

from paddle_tpu.models.mamba import selective_scan as ref_scan
from paddle_tpu.ops.pallas import _partition, _support

SS = importlib.import_module("paddle_tpu.ops.pallas.selective_scan")


def make_inputs(Bsz=2, T=32, Ei=128, N=8, seed=0):
    rs = np.random.RandomState(seed)
    u = rs.randn(Bsz, T, Ei).astype(np.float32)
    delta = (np.abs(rs.randn(Bsz, T, Ei)) * 0.1).astype(np.float32)
    A = -np.abs(rs.randn(Ei, N)).astype(np.float32)
    B = rs.randn(Bsz, T, N).astype(np.float32)
    C = rs.randn(Bsz, T, N).astype(np.float32)
    D = rs.randn(Ei).astype(np.float32)
    return tuple(map(jnp.asarray, (u, delta, A, B, C, D)))


def test_forward_matches_reference():
    args = make_inputs()
    assert SS.supported(*args, chunk=8)
    with _support.force_interpret():
        y = SS.selective_scan(*args, chunk=8)
    yr = ref_scan(*args, chunk_size=8)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)


def test_single_chunk_and_multi_chunk_agree():
    args = make_inputs(T=16)
    with _support.force_interpret():
        y1 = SS.selective_scan(*args, chunk=16)   # one chunk
        y2 = SS.selective_scan(*args, chunk=8)    # two chunks + carry
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_reference():
    """All six input gradients against jax.grad of the XLA spec. Ei=256
    (two lane blocks) so cross-channel-block reductions of dB/dC are
    exercised — Ei=128 hides an overwrite across the channel grid dim."""
    args = make_inputs(Ei=256)

    def loss_k(*a):
        return jnp.sum(SS.selective_scan(*a, chunk=8) ** 2)

    def loss_r(*a):
        return jnp.sum(ref_scan(*a, chunk_size=8) ** 2)

    with _support.force_interpret():
        gk = jax.grad(loss_k, argnums=tuple(range(6)))(*args)
    gr = jax.grad(loss_r, argnums=tuple(range(6)))(*args)
    for name, a, b in zip("u delta A B C D".split(), gk, gr):
        scale = float(jnp.max(jnp.abs(b))) + 1e-8
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 1e-4, (name, err)


def test_finite_difference_gradients():
    """Direct FD check of the custom VJP (scoped x64 would change the
    kernel dtype gate, so FD runs in f32 with loose tolerance on a tiny
    problem)."""
    args = make_inputs(Bsz=1, T=8, Ei=128, N=8)

    def loss(*a):
        return jnp.sum(SS.selective_scan(*a, chunk=8) ** 2)

    with _support.force_interpret():
        grads = jax.grad(loss, argnums=(2, 5))(*args)  # A and D
        eps = 1e-2
        for argnum, g in zip((2, 5), grads):
            x = np.asarray(args[argnum])
            g = np.asarray(g)
            # probe where the gradient is largest so f32 FD can resolve it
            idx = np.unravel_index(np.argmax(np.abs(g)), g.shape)
            fd_vals = []
            for sign in (+1, -1):
                xp = x.copy()
                xp[idx] += sign * eps
                pert = list(args)
                pert[argnum] = jnp.asarray(xp)
                fd_vals.append(float(loss(*pert)))
            fd = (fd_vals[0] - fd_vals[1]) / (2 * eps)  # central difference
            an = float(g[idx])
            assert abs(fd - an) / (abs(an) + 1e-6) < 5e-2, (argnum, fd, an)


def test_mamba_block_dispatches_kernel(monkeypatch):
    """The model integration: MambaBlock must route through the kernel
    when the gate is open and reproduce the XLA-path output."""
    from paddle_tpu.models.mamba import MambaConfig, MambaForCausalLM
    import paddle_tpu

    cfg = MambaConfig.tiny(hidden_size=64, state_size=8, num_layers=2,
                           scan_chunk_size=8)
    paddle_tpu.seed(0)
    model = MambaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 16)),
                      jnp.int32)
    ref = model(ids)
    with _support.force_dispatch():
        _partition.reset_stats()
        out = model(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_partitioned_selective_scan(devices8):
    """Batch over dp and channels over tp: the custom_partitioning path
    must match the reference with grads."""
    mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
    args = make_inputs(Bsz=4, T=16, Ei=256, N=8)
    u = jax.device_put(args[0], NamedSharding(mesh, P("dp", None, "tp")))
    rest = args[1:]

    def loss_k(u, *a):
        return jnp.sum(SS.selective_scan(u, *a, chunk=8,
                                         partitioned=True) ** 2)

    grad_args = tuple(range(6))  # incl. dB/dC: channel-sharded partials
    with _support.force_dispatch():
        _partition.reset_stats()
        val, gs = jax.jit(jax.value_and_grad(
            loss_k, argnums=grad_args))(u, *rest)
        assert _partition.stats["selective_scan_fwd:kernel"] > 0
        assert _partition.stats["selective_scan_bwd:kernel"] > 0

    def loss_r(u, *a):
        return jnp.sum(ref_scan(u, *a, chunk_size=8) ** 2)

    rval, rgs = jax.value_and_grad(loss_r, argnums=grad_args)(*args)
    np.testing.assert_allclose(float(val), float(rval), rtol=1e-4)
    for name, got, ref in zip("u delta A B C D".split(), gs, rgs):
        scale = float(jnp.max(jnp.abs(ref))) + 1e-8
        err = float(jnp.max(jnp.abs(got - ref))) / scale
        assert err < 1e-3, (name, err)


def test_mamba_stateful_decode_matches_parallel_scan():
    """The recurrent O(1)-per-token decode path (init_cache /
    forward_with_cache) must reproduce the parallel-scan forward:
    prefill logits, teacher-forced stepwise logits, and the
    prefill→step state handoff all match."""
    import paddle_tpu
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    paddle_tpu.seed(0)
    cfg = MambaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           state_size=8)
    m = MambaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 96, (2, 12))
                      .astype(np.int32))
    full = np.asarray(m(ids))

    pre, cache_p = m.forward_with_cache(ids, m.init_cache(2))
    np.testing.assert_allclose(np.asarray(pre), full, rtol=2e-4,
                               atol=1e-5)

    cache = m.init_cache(2)
    steps = []
    for t in range(ids.shape[1]):
        lg, cache = m.forward_with_cache(ids[:, t:t + 1], cache)
        steps.append(np.asarray(lg[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full, rtol=2e-3,
                               atol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(cache_p),
                    jax.tree_util.tree_leaves(cache)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=1e-4)


def test_mamba_generate_runs_jitted():
    import paddle_tpu
    from paddle_tpu.models import MambaConfig, MambaForCausalLM
    from paddle_tpu.models.generation import generate

    paddle_tpu.seed(1)
    cfg = MambaConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                           state_size=8)
    m = MambaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 6))
                      .astype(np.int32))
    out = np.asarray(jax.jit(lambda mm, i: generate(mm, i, 8))(m, ids))
    assert out.shape == (2, 14)
    assert (out[:, :6] == np.asarray(ids)).all()


def test_mamba_prefill_short_prompt_pads_conv_tail():
    """Prompt shorter than the conv kernel: the conv tail zero-pads and
    continued stepping still matches the full parallel forward."""
    import paddle_tpu
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    paddle_tpu.seed(2)
    cfg = MambaConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                           state_size=8, conv_kernel=4)
    m = MambaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 64, (1, 5))
                      .astype(np.int32))
    # prefill only the first 2 tokens (< K-1), then step the rest
    _, cache = m.forward_with_cache(ids[:, :2], m.init_cache(1))
    outs = []
    for t in range(2, 5):
        lg, cache = m.forward_with_cache(ids[:, t:t + 1], cache)
        outs.append(np.asarray(lg[:, 0]))
    full = np.asarray(m(ids))
    np.testing.assert_allclose(np.stack(outs, axis=1), full[:, 2:],
                               rtol=2e-3, atol=1e-4)


def test_mamba_chunked_prefill_continuation_exact():
    """Warm-cache multi-token prefill (the Llama-contract pattern of
    appending T>1 chunks) must be exact: prefilling a prompt in two
    chunks equals one-shot prefill — logits AND carried state."""
    import paddle_tpu
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    paddle_tpu.seed(3)
    # scan_chunk_size=4 with T=16/9/7 chunks: the 16-token one-shot
    # prefill AND the 9/7 split both exercise selective_scan's CHUNKED
    # branch with initial_state/return_state (chunked when divisible,
    # unchunked otherwise) against each other
    cfg = MambaConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                           state_size=8, conv_kernel=4,
                           scan_chunk_size=4)
    m = MambaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 64, (2, 16))
                      .astype(np.int32))
    one_lg, one_cache = m.forward_with_cache(ids, m.init_cache(2))

    lg_a, cache = m.forward_with_cache(ids[:, :7], m.init_cache(2))
    lg_b, cache = m.forward_with_cache(ids[:, 7:], cache)
    np.testing.assert_allclose(
        np.concatenate([np.asarray(lg_a), np.asarray(lg_b)], axis=1),
        np.asarray(one_lg), rtol=2e-3, atol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(cache),
                    jax.tree_util.tree_leaves(one_cache)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=1e-4)


def test_mamba_decode_conv_kernel_one():
    """conv_kernel=1 (no temporal conv): the carried tail is an empty
    [B, 0, Ei] slice — a -(K-1) slice bug would silently return the
    whole sequence and corrupt every subsequent step."""
    import paddle_tpu
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    paddle_tpu.seed(4)
    cfg = MambaConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                           state_size=8, conv_kernel=1)
    m = MambaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(4).randint(0, 64, (2, 6))
                      .astype(np.int32))
    full = np.asarray(m(ids))
    _, cache = m.forward_with_cache(ids[:, :4], m.init_cache(2))
    assert jax.tree_util.tree_leaves(cache)[0].shape[2] == 0
    outs = []
    for t in range(4, 6):
        lg, cache = m.forward_with_cache(ids[:, t:t + 1], cache)
        outs.append(np.asarray(lg[:, 0]))
    np.testing.assert_allclose(np.stack(outs, axis=1), full[:, 4:],
                               rtol=2e-3, atol=1e-4)
