"""Pluggable checkpoint filesystems (io/fs.py).

Reference parity target: ``python/paddle/distributed/fleet/utils/fs.py``
(FS/LocalFS/HDFSClient surface) + the HDFS-staged elastic resume of
``fluid/incubate/checkpoint/auto_checkpoint.py:71``. The remote backend
under test is the real ``ptfs://`` TCP service (core/wire framing), so
the off-node story — save on one "node", resume on another with an empty
local cache — runs end-to-end in-process.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.io import fs as fs_mod


@pytest.fixture
def remote(tmp_path):
    """A running FSService rooted in a temp dir + its ptfs:// URL."""
    srv = fs_mod.FSService(str(tmp_path / "storage")).start()
    try:
        yield srv, f"ptfs://{srv.endpoint}"
    finally:
        srv.stop()


def test_local_fs_surface(tmp_path):
    fs = fs_mod.LocalFS()
    d = tmp_path / "a" / "b"
    fs.mkdirs(str(d))
    assert fs.is_dir(str(d)) and fs.is_exist(str(d))
    f = d / "x.txt"
    f.write_bytes(b"hi")
    assert fs.is_file(str(f))
    dirs, files = fs.ls_dir(str(d))
    assert files == ["x.txt"] and dirs == []
    fs.mv(str(f), str(d / "y.txt"))
    assert fs.is_file(str(d / "y.txt")) and not fs.is_exist(str(f))
    fs.touch(str(d / "z"))
    assert fs.is_file(str(d / "z"))
    fs.delete(str(d))
    assert not fs.is_exist(str(d))
    assert fs.need_upload_download() is False


def test_wire_fs_round_trip(remote, tmp_path):
    srv, url = remote
    fs = fs_mod.fs_for_path(url)
    assert isinstance(fs, fs_mod.WireFS)
    assert fs.need_upload_download() is True

    # file upload/download
    src = tmp_path / "local.bin"
    src.write_bytes(os.urandom(4096))
    fs.upload(str(src), f"{url}/dir1/remote.bin")
    assert fs.is_file(f"{url}/dir1/remote.bin")
    back = tmp_path / "back.bin"
    fs.download(f"{url}/dir1/remote.bin", str(back))
    assert back.read_bytes() == src.read_bytes()

    # directory tree upload/download
    tree = tmp_path / "tree"
    (tree / "sub").mkdir(parents=True)
    (tree / "a.txt").write_bytes(b"a")
    (tree / "sub" / "b.txt").write_bytes(b"b")
    fs.upload(str(tree), f"{url}/tree")
    dirs, files = fs.ls_dir(f"{url}/tree")
    assert dirs == ["sub"] and files == ["a.txt"]
    out = tmp_path / "out"
    fs.download(f"{url}/tree", str(out))
    assert (out / "sub" / "b.txt").read_bytes() == b"b"

    # mv / delete / touch
    fs.mv(f"{url}/tree/a.txt", f"{url}/tree/c.txt")
    assert fs.is_file(f"{url}/tree/c.txt")
    fs.touch(f"{url}/marker")
    assert fs.is_exist(f"{url}/marker")
    fs.delete(f"{url}/tree")
    assert not fs.is_exist(f"{url}/tree")
    fs.close()


def test_fs_service_rejects_escape(remote):
    srv, url = remote
    fs = fs_mod.fs_for_path(url)
    with pytest.raises(RuntimeError, match="escapes"):
        fs.ls_dir(f"{url}/../outside")
    fs.close()


def test_unknown_scheme_raises():
    with pytest.raises(ValueError, match="no filesystem registered"):
        fs_mod.fs_for_path("hdfs://nn:9000/x")
    assert isinstance(fs_mod.fs_for_path("/plain/local"), fs_mod.LocalFS)


def test_state_dict_remote_round_trip(remote, tmp_path):
    from paddle_tpu import nn
    from paddle_tpu.io import checkpoint as ckpt
    import paddle_tpu

    srv, url = remote
    paddle_tpu.seed(0)
    net = nn.Linear(4, 3)
    ckpt.save_state_dict(net, f"{url}/weights")
    net2 = nn.Linear(4, 3)
    net2 = ckpt.load_state_dict(net2, f"{url}/weights")
    np.testing.assert_array_equal(np.asarray(net.weight),
                                  np.asarray(net2.weight))


def test_auto_checkpoint_remote_resume_fresh_node(remote, tmp_path,
                                                  monkeypatch):
    """The elastic story: train + save through ptfs://, 'lose the node'
    (wipe the staging cache), relaunch — TrainEpochRange must pull the
    latest complete remote step and fast-forward past finished epochs."""
    from paddle_tpu.io import checkpoint as ckpt
    from paddle_tpu.io.auto_checkpoint import TrainEpochRange

    srv, base_url = remote
    url = f"{base_url}/job42"
    cache1 = tmp_path / "node1_cache"
    cache2 = tmp_path / "node2_cache"

    def stager_at(cache):
        # the supported per-node override + process-restart simulation
        ckpt.reset_remote_cache()
        monkeypatch.setenv("PADDLE_CKPT_CACHE_ROOT", str(cache))

    stager_at(cache1)
    state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.int32(0)}
    # the "crashing" run completes epochs 0..1 of the 4-epoch job (a
    # break would skip the post-yield save — like dying mid-epoch, which
    # correctly resumes from the previous completed epoch)
    r = TrainEpochRange(2, url, state=state, save_interval=1)
    assert not r.resumed
    seen = []
    for epoch in r:
        r.state = {"w": r.state["w"] + 1.0,
                   "step": jnp.int32(epoch + 1)}
        seen.append(epoch)
    r.flush()
    assert seen == [0, 1]

    # node loss: brand-new staging cache on the relaunched trainer
    stager_at(cache2)
    state0 = {"w": jnp.zeros((2, 3)), "step": jnp.int32(0)}
    r2 = TrainEpochRange(4, url, state=state0, save_interval=1)
    assert r2.resumed and r2.start_epoch == 2
    np.testing.assert_allclose(np.asarray(r2.state["w"]),
                               np.arange(6.0).reshape(2, 3) + 2.0)
    remaining = list(r2)
    assert remaining == [2, 3]


def test_wire_fs_chunked_transfer(remote, tmp_path, monkeypatch):
    """Files larger than one chunk stream in bounded frames both ways
    (no full-file buffering on either side)."""
    srv, url = remote
    monkeypatch.setattr(fs_mod, "CHUNK_BYTES", 1024)
    fs = fs_mod.fs_for_path(url)
    payload = os.urandom(1024 * 7 + 333)   # 8 chunks, ragged tail
    src = tmp_path / "big.bin"
    src.write_bytes(payload)
    fs.upload(str(src), f"{url}/big.bin")
    out = tmp_path / "big_back.bin"
    fs.download(f"{url}/big.bin", str(out))
    assert out.read_bytes() == payload
    fs.close()


def test_incomplete_remote_step_not_resumable(remote, tmp_path):
    """A step dir without its .complete marker (writer died mid-upload)
    must be excluded from resume and refused by explicit fetch."""
    srv, url = remote
    stage = fs_mod.RemoteCheckpointDir(f"{url}/jobX",
                                       cache_root=str(tmp_path / "c"))
    local = tmp_path / "step0"
    local.mkdir()
    (local / "data.bin").write_bytes(b"partial")
    stage.fs.upload(str(local), stage._remote(0))   # no marker
    assert stage.remote_steps() == []
    assert stage.pull_latest() is None
    with pytest.raises(FileNotFoundError, match="complete"):
        stage.fetch(0)


def test_stale_cache_detected_and_redownloaded(remote, tmp_path):
    """Same URL, new run (operator wiped the remote and re-saved): a
    node with the OLD run's staging cache must re-download, not silently
    resume obsolete weights — the upload token in the .complete marker
    is the version identity."""
    srv, url = remote
    cache = str(tmp_path / "cache")
    stage = fs_mod.RemoteCheckpointDir(f"{url}/runX", cache_root=cache)

    def save_step(value):
        local = os.path.join(stage.local_dir, "0")
        fs_mod.LocalFS().delete(local)
        os.makedirs(local)
        with open(os.path.join(local, "w.bin"), "wb") as f:
            f.write(bytes([value]) * 8)
        stage.push(0)

    save_step(1)
    # second "run" at the same URL from another node: wipe remote, save new
    stage2 = fs_mod.RemoteCheckpointDir(f"{url}/runX",
                                        cache_root=str(tmp_path / "c2"))
    stage2.fs.delete(stage2._remote(0))
    stage2.fs.delete(stage2._marker_remote(0))
    local2 = os.path.join(stage2.local_dir, "0")
    os.makedirs(local2)
    with open(os.path.join(local2, "w.bin"), "wb") as f:
        f.write(bytes([5]) * 8)
    stage2.push(0)

    # original node still has value-1 cached; fetch must resync to 5
    stage.fetch(0)
    with open(os.path.join(stage.local_dir, "0", "w.bin"), "rb") as f:
        assert f.read() == bytes([5]) * 8
