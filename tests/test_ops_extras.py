"""Contrib op tail (ops/extras.py) + two-stage detector ops
(vision/ops.py r5 additions) — the implemented rows of OPS_AUDIT.md.

OpTest discipline (reference ``tests/unittests/op_test.py``): each op
checked against an obvious numpy reference on small shapes.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops import extras as E
from paddle_tpu.vision import ops as V


def test_shuffle_channel():
    x = jnp.arange(2 * 6 * 2 * 2, dtype=jnp.float32).reshape(2, 6, 2, 2)
    y = E.shuffle_channel(x, groups=3)
    # group-transpose: channel order [0,2,4,1,3,5]
    np.testing.assert_array_equal(np.asarray(y[:, 0]), np.asarray(x[:, 0]))
    np.testing.assert_array_equal(np.asarray(y[:, 1]), np.asarray(x[:, 2]))
    np.testing.assert_array_equal(np.asarray(y[:, 3]), np.asarray(x[:, 1]))


def test_temporal_shift_matches_manual():
    rs = np.random.RandomState(0)
    x = rs.randn(4, 8, 2, 2).astype(np.float32)       # N=2, T=2
    y = np.asarray(E.temporal_shift(jnp.asarray(x), seg_num=2))
    x5 = x.reshape(2, 2, 8, 2, 2)
    want = np.zeros_like(x5)
    want[:, 0, :2] = x5[:, 1, :2]                     # back shift
    want[:, 1, 2:4] = x5[:, 0, 2:4]                   # forward shift
    want[:, :, 4:] = x5[:, :, 4:]
    np.testing.assert_allclose(y, want.reshape(4, 8, 2, 2))


def test_space_to_depth_matches_reference_layout():
    """Reference channel layout is BLOCK-major (space_to_depth_op.h:47:
    out channel k = (bi*b + bj)*C + c), not pixel_shuffle's C-major."""
    rs = np.random.RandomState(1)
    x = rs.randn(2, 3, 4, 6).astype(np.float32)
    y = np.asarray(E.space_to_depth(jnp.asarray(x), 2))
    assert y.shape == (2, 12, 2, 3)
    for bi in range(2):
        for bj in range(2):
            for c in range(3):
                np.testing.assert_array_equal(
                    y[:, (bi * 2 + bj) * 3 + c],
                    x[:, c, bi::2, bj::2])


def test_multiplex():
    a = jnp.asarray([[1.0, 1], [2, 2], [3, 3]])
    b = jnp.asarray([[10.0, 10], [20, 20], [30, 30]])
    out = E.multiplex([a, b], jnp.asarray([1, 0, 1]))
    np.testing.assert_array_equal(np.asarray(out),
                                  [[10, 10], [2, 2], [30, 30]])


def test_partial_concat_and_sum_reference_example():
    a = jnp.asarray([[1.0, 2], [3, 4]])
    b = jnp.asarray([[5.0, 6], [7, 8]])
    out = E.partial_concat([a, b], start_index=1, length=1)
    np.testing.assert_array_equal(np.asarray(out), [[2, 6], [4, 8]])
    s = E.partial_sum([a, b], start_index=1, length=1)
    np.testing.assert_array_equal(np.asarray(s), [[8.0], [12.0]])


def test_cvm_both_modes():
    x = jnp.asarray([[3.0, 1.0, 0.5, 0.6]])
    y = np.asarray(E.cvm(x, use_cvm=True))
    np.testing.assert_allclose(
        y[0, :2], [np.log(4.0), np.log(2.0) - np.log(4.0)], rtol=1e-6)
    np.testing.assert_allclose(y[0, 2:], [0.5, 0.6])
    y2 = E.cvm(x, use_cvm=False)
    assert y2.shape == (1, 2)


def test_gather_tree_backtrace():
    # T=3, B=1, K=2; parents select which beam each id came from
    ids = jnp.asarray([[[1, 2]], [[3, 4]], [[5, 6]]])
    parents = jnp.asarray([[[0, 0]], [[0, 0]], [[1, 0]]])
    out = np.asarray(E.gather_tree(ids, parents))
    # beam 0 at t=2 came from beam 1 at t=1 (parent=1) which came from
    # beam 0 at t=0
    np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])
    np.testing.assert_array_equal(out[:, 0, 1], [1, 3, 6])


def test_fsp_matrix_shape_and_value():
    x = jnp.ones((2, 3, 4, 4))
    y = jnp.full((2, 5, 4, 4), 2.0)
    m = np.asarray(E.fsp_matrix(x, y))
    assert m.shape == (2, 3, 5)
    np.testing.assert_allclose(m, 2.0)


def test_conv_shift_circular():
    x = jnp.asarray([[1.0, 2, 3, 4]])
    y = jnp.asarray([[0.0, 1, 0]])        # identity kernel
    np.testing.assert_allclose(np.asarray(E.conv_shift(x, y)),
                               [[1, 2, 3, 4]])
    shift = jnp.asarray([[1.0, 0, 0]])    # pick left neighbour
    np.testing.assert_allclose(np.asarray(E.conv_shift(x, shift)),
                               [[4, 1, 2, 3]])


def test_batch_fc():
    rs = np.random.RandomState(2)
    x = rs.randn(3, 4, 5).astype(np.float32)
    w = rs.randn(3, 5, 2).astype(np.float32)
    b = rs.randn(3, 2).astype(np.float32)
    out = np.asarray(E.batch_fc(jnp.asarray(x), jnp.asarray(w),
                                jnp.asarray(b)))
    want = np.einsum("sni,sio->sno", x, w) + b[:, None]
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_max_pool_with_index_and_unpool_roundtrip():
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(2, 3, 4, 4).astype(np.float32))
    out, idx = E.max_pool2d_with_index(x, 2, 2)
    assert out.shape == (2, 3, 2, 2) and idx.dtype == jnp.int32
    # indices point at the argmax positions in the flat 4x4 map
    flat = np.asarray(x).reshape(2, 3, 16)
    got = np.take_along_axis(flat, np.asarray(idx).reshape(2, 3, 4), -1)
    np.testing.assert_allclose(got, np.asarray(out).reshape(2, 3, 4))
    up = E.max_unpool2d(out, idx, (4, 4))
    assert up.shape == x.shape
    np.testing.assert_allclose(np.asarray(up).sum(),
                               np.asarray(out).sum(), rtol=1e-6)


def test_spatial_pyramid_pool_sizes():
    x = jnp.ones((2, 3, 8, 8))
    y = E.spatial_pyramid_pool(x, pyramid_height=3)
    assert y.shape == (2, 3 * (1 + 4 + 16))
    np.testing.assert_allclose(np.asarray(y), 1.0)


def test_losses_basic_values():
    np.testing.assert_allclose(
        np.asarray(E.hinge_loss(jnp.asarray([0.5, -2.0]),
                                jnp.asarray([1.0, 0.0]))),
        [0.5, 0.0])
    # rank loss at o=0, P=0.5: log(2)
    np.testing.assert_allclose(
        float(E.rank_loss(0.5, 1.0, 1.0)), np.log(2.0), rtol=1e-6)
    h = np.asarray(E.huber_loss(jnp.asarray([0.5, 3.0]),
                                jnp.asarray([0.0, 0.0]), delta=1.0))
    np.testing.assert_allclose(h, [0.125, 2.5])
    mh = np.asarray(E.modified_huber_loss(jnp.asarray([0.5, -2.0]),
                                          jnp.asarray([1.0, 1.0])))
    np.testing.assert_allclose(mh, [0.25, 8.0])
    np.testing.assert_allclose(
        float(E.squared_l2_distance(jnp.ones((1, 4)),
                                    jnp.zeros((1, 4)))[0]), 4.0)
    assert float(E.squared_l2_norm(jnp.asarray([3.0, 4.0]))) == 25.0
    assert float(E.l1_norm(jnp.asarray([-3.0, 4.0]))) == 7.0


def test_bpr_loss_prefers_ranked_positive():
    x_good = jnp.asarray([[5.0, 0.0, 0.0]])
    x_bad = jnp.asarray([[0.0, 5.0, 5.0]])
    lab = jnp.asarray([0])
    assert float(E.bpr_loss(x_good, lab)[0]) < float(E.bpr_loss(x_bad,
                                                                lab)[0])


def test_center_loss_update_moves_centers_toward_features():
    feats = jnp.asarray([[1.0, 1.0], [3.0, 3.0]])
    labels = jnp.asarray([0, 0])
    centers = jnp.zeros((3, 2))
    loss, new_c = E.center_loss(feats, labels, centers, alpha=1.0)
    assert loss.shape == (2,)
    # center 0 moves toward the mean of its features; others untouched
    assert float(new_c[0, 0]) > 0.0
    np.testing.assert_allclose(np.asarray(new_c[1:]), 0.0)


def test_teacher_student_sigmoid_loss_label_encoding():
    x = jnp.asarray([0.3, 0.3, 0.3, 0.3])
    # -2: clk=0 no teacher; -1: clk=1 no teacher; 0.7: clk=0 z'=0.7;
    # 1.7: clk=1 z'=0.7
    lab = jnp.asarray([-2.0, -1.0, 0.7, 1.7])
    out = np.asarray(E.teacher_student_sigmoid_loss(x, lab))

    def xent(x, z):
        return max(x, 0) - x * z + np.log1p(np.exp(-abs(x)))

    np.testing.assert_allclose(out[0], xent(0.3, 0.0), rtol=1e-6)
    np.testing.assert_allclose(out[1], xent(0.3, 1.0), rtol=1e-6)
    np.testing.assert_allclose(out[2], xent(0.3, 0.0) + xent(0.3, 0.7),
                               rtol=1e-6)
    np.testing.assert_allclose(out[3], xent(0.3, 1.0) + xent(0.3, 0.7),
                               rtol=1e-6)


def test_add_position_encoding_alpha_beta():
    x = jnp.zeros((1, 4, 8))
    y = np.asarray(E.add_position_encoding(x, alpha=2.0, beta=1.0))
    # position 0: sin terms 0, cos terms 1
    np.testing.assert_allclose(y[0, 0, :4], 0.0, atol=1e-6)
    np.testing.assert_allclose(y[0, 0, 4:], 1.0, atol=1e-6)


# -- two-stage detector ops -------------------------------------------------

def test_generate_proposals_picks_high_score_nonoverlapping():
    H = W = 4
    A = 2
    # anchors: two sizes per cell
    anchors = np.zeros((H, W, A, 4), np.float32)
    for i in range(H):
        for j in range(W):
            for a in range(A):
                size = 8.0 * (a + 1)
                cx, cy = j * 8.0 + 4, i * 8.0 + 4
                anchors[i, j, a] = [cx - size / 2, cy - size / 2,
                                    cx + size / 2, cy + size / 2]
    var = np.ones((H, W, A, 4), np.float32)
    scores = np.full((A, H, W), -5.0, np.float32)
    scores[0, 0, 0] = 5.0
    scores[0, 3, 3] = 4.0
    deltas = np.zeros((A * 4, H, W), np.float32)
    rois, s, valid = V.generate_proposals(
        jnp.asarray(scores), jnp.asarray(deltas), (32.0, 32.0),
        jnp.asarray(anchors), jnp.asarray(var),
        pre_nms_top_n=16, post_nms_top_n=4, nms_thresh=0.5, min_size=2.0)
    s = np.asarray(s)
    assert bool(np.asarray(valid)[0]) and s[0] == 5.0 and s[1] == 4.0
    # the two kept proposals are the two distinct high-score cells
    r = np.asarray(rois)
    assert r[0][0] < 8 and r[1][2] > 24


def test_distribute_and_collect_fpn_proposals():
    rois = jnp.asarray([[0, 0, 10, 10],       # small -> low level
                        [0, 0, 200, 200]], jnp.float32)
    lvl, order = V.distribute_fpn_proposals(rois, 2, 5, 4, 224.0)
    lv = np.asarray(lvl)
    assert lv[0] < lv[1]
    out_r, out_s = V.collect_fpn_proposals(
        [rois[:1], rois[1:]], [jnp.asarray([0.3]), jnp.asarray([0.9])],
        post_nms_top_n=2)
    np.testing.assert_allclose(np.asarray(out_s), [0.9, 0.3])


def test_target_assign():
    x = jnp.asarray([[1.0, 2], [3, 4], [5, 6]])
    mi = jnp.asarray([2, -1, 0, 1])
    out, w = V.target_assign(x, mi, mismatch_value=-9.0)
    np.testing.assert_array_equal(np.asarray(out),
                                  [[5, 6], [-9, -9], [1, 2], [3, 4]])
    np.testing.assert_array_equal(np.asarray(w), [1, 0, 1, 1])


def test_density_prior_box_shapes_and_bounds():
    boxes = V.density_prior_box((2, 2), (32, 32), densities=[2],
                                fixed_sizes=[8.0], fixed_ratios=[1.0])
    assert boxes.shape == (2, 2, 4, 4)       # 2x2 density grid = 4 priors
    b = np.asarray(boxes)
    assert (b >= 0).all() and (b <= 1).all()
    assert (b[..., 2] > b[..., 0]).all()


def test_generate_proposals_all_negative_scores_still_returns_topk():
    """RPN scores are raw logits: a background-only image (all scores
    negative) must still return the best post_nms_top_n boxes, not an
    empty set (review r5 finding)."""
    H = W = 2
    A = 1
    anchors = np.zeros((H, W, A, 4), np.float32)
    for i in range(H):
        for j in range(W):
            anchors[i, j, 0] = [j * 16.0, i * 16.0, j * 16.0 + 12,
                                i * 16.0 + 12]
    var = np.ones((H, W, A, 4), np.float32)
    scores = np.full((A, H, W), -3.0, np.float32)
    scores[0, 1, 1] = -1.0
    deltas = np.zeros((A * 4, H, W), np.float32)
    rois, s, valid = V.generate_proposals(
        jnp.asarray(scores), jnp.asarray(deltas), (32.0, 32.0),
        jnp.asarray(anchors), jnp.asarray(var),
        pre_nms_top_n=4, post_nms_top_n=2, nms_thresh=0.7, min_size=1.0)
    v = np.asarray(valid)
    assert v[0] and v[1]
    assert np.asarray(s)[0] == -1.0


def test_add_position_encoding_odd_embedding():
    y = E.add_position_encoding(jnp.zeros((1, 3, 5)))
    assert y.shape == (1, 3, 5)
    assert np.isfinite(np.asarray(y)).all()


def test_teacher_student_no_teacher_click_boundary():
    """label in [-1, 0) means clicked-no-teacher (z=1); label < -1
    means not-clicked-no-teacher (z=0) — the reference threshold is
    -1.0 (review r5 finding)."""
    x = jnp.asarray([2.0, 2.0])
    out = np.asarray(E.teacher_student_sigmoid_loss(
        x, jnp.asarray([-1.2, -0.8])))

    def xent(x, z):
        return max(x, 0) - x * z + np.log1p(np.exp(-abs(x)))

    np.testing.assert_allclose(out[0], xent(2.0, 0.0), rtol=1e-6)
    np.testing.assert_allclose(out[1], xent(2.0, 1.0), rtol=1e-6)
