"""Performance attribution (``FLAGS_gen_ledger``): the per-request
latency ledger, the engine goodput taxonomy, and per-tenant books.

The two load-bearing properties pinned here:

- **Partition invariant** — a finalized record's phase durations
  (admit_wait → prefill → decode → deliver) sum EXACTLY to its
  end-to-end latency, because boundaries telescope with clamping rather
  than being independent timers; likewise the goodput buckets account
  100% of the loop wall clock.
- **Hard-off discipline** — with the flag off (the default) the engine
  builds no books, reads no ledger flag on the decode hot path, ships
  no extra stats keys, and produces byte-identical token streams.
"""

import time
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core.flags import get_flags, set_flags
from paddle_tpu.io.serving import InferenceClient, InferenceServer
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.serving import GenerationEngine
from paddle_tpu.serving.ledger import (
    DEFAULT_TENANT, GOODPUT_BUCKETS, GOODPUT_USEFUL, PHASES, GoodputMeter,
    RequestLedger, TenantBook,
)

pytestmark = pytest.mark.gen

VOCAB = 96


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _prompt(seed=3, n=5):
    rs = np.random.RandomState(seed)
    return rs.randint(0, VOCAB, (n,)).astype(np.int32)


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


# ---------------------------------------------------------------- units

def _fake_gen(created, admitted, first_tok, done, *, tenant=None,
              tokens=6, rng_skip=0, spec=(0, 0)):
    return SimpleNamespace(
        gen_id="g0", tenant=tenant, created=created, admitted_ts=admitted,
        first_tok_ts=first_tok, done_ts=done,
        prompt=np.zeros((5,), np.int32), tokens=list(range(tokens)),
        chip_s=0.25, rng_skip=rng_skip,
        spec_proposed=spec[0], spec_accepted=spec[1])


def test_finalize_phases_partition_e2e_exactly():
    """The invariant: telescoping clamped boundaries make the four
    phase durations sum to ``e2e_s`` with no float drift beyond
    associativity (< 1e-9 for sub-minute requests)."""
    led = RequestLedger()
    t0 = time.monotonic()
    rec = led.finalize(_fake_gen(t0, t0 + 0.010, t0 + 0.030, t0 + 0.090,
                                 tenant="acme"), "complete",
                       now=t0 + 0.100)
    assert tuple(rec["phases"]) == PHASES
    assert abs(sum(rec["phases"].values()) - rec["e2e_s"]) < 1e-9
    assert rec["phases"]["admit_wait_s"] == pytest.approx(0.010)
    assert rec["phases"]["prefill_s"] == pytest.approx(0.020)
    assert rec["phases"]["decode_s"] == pytest.approx(0.060)
    assert rec["phases"]["deliver_s"] == pytest.approx(0.010)
    assert rec["outcome"] == "complete" and rec["tenant"] == "acme"


def test_finalize_missing_stamps_collapse_and_clamp():
    """Stamps that never ran (0.0) collapse to the end boundary, and
    out-of-order stamps clamp monotone — phases stay non-negative and
    the partition still holds."""
    led = RequestLedger()
    t0 = time.monotonic()
    # never admitted (queue death): everything is admit_wait
    rec = led.finalize(_fake_gen(t0, 0.0, 0.0, 0.0), "expired",
                       now=t0 + 0.050)
    assert rec["phases"]["admit_wait_s"] == pytest.approx(0.050)
    assert sum(abs(v) for v in rec["phases"].values()) == pytest.approx(
        rec["e2e_s"])
    # clock jitter: done stamped BEFORE first token still telescopes
    rec2 = led.finalize(_fake_gen(t0, t0 + 0.010, t0 + 0.040, t0 + 0.020),
                        "complete", now=t0 + 0.060)
    assert all(v >= 0.0 for v in rec2["phases"].values())
    assert abs(sum(rec2["phases"].values()) - rec2["e2e_s"]) < 1e-9


def test_finalize_resume_and_spec_subblocks():
    led = RequestLedger(records=2)
    t0 = time.monotonic()
    rec = led.finalize(_fake_gen(t0, t0, t0, t0, rng_skip=4, spec=(9, 5)),
                       "complete", now=t0 + 0.01)
    assert rec["resume"] == {"rng_skip": 4}
    assert rec["spec"] == {"proposed": 9, "accepted": 5}
    # ring buffer: maxlen trims oldest, records(limit) trims newest-last
    for _ in range(3):
        led.finalize(_fake_gen(t0, t0, t0, t0), "complete", now=t0 + 0.01)
    assert len(led) == 2
    assert len(led.records(1)) == 1 and "resume" not in led.records()[-1]


def test_tenant_book_default_key_and_accumulation():
    book = TenantBook()
    book.add(None, tokens=3, requests=1)
    book.add("", tokens=2, requests=1)            # falsy → default key
    book.add("acme", tokens=5, chip_s=0.5, queue_wait_s=0.1, requests=1)
    book.add("acme", tokens=5, chip_s=0.5, requests=1)
    snap = book.snapshot()
    assert snap[DEFAULT_TENANT]["tokens"] == 5
    assert snap["acme"] == {"tokens": 10, "chip_seconds": 1.0,
                            "queue_wait_s": pytest.approx(0.1),
                            "requests": 2}


def test_goodput_meter_sums_to_one_and_classifies():
    """Every loop second lands in exactly one of the seven buckets and
    the fractions sum to 1.0 by construction (tick sweeps the un-noted
    remainder into the hint bucket)."""
    meter = GoodputMeter()
    time.sleep(0.010)                             # real elapsed wall clock
    meter.note("prefill", 0.001)
    meter.note("decode", 0.003)
    meter.note("decode", -1.0)                    # ignored, not negative
    meter.tick()                                  # remainder → host_gather
    time.sleep(0.005)
    meter.note("admission_idle", 0.001)
    meter.tick(hint="watchdog_stuck")
    snap = meter.snapshot()
    assert set(snap["buckets"]) == set(GOODPUT_BUCKETS)
    assert snap["ticks"] == 2 and snap["total_s"] > 0.0
    assert sum(snap["fractions"].values()) == pytest.approx(1.0)
    assert snap["buckets"]["host_gather"] > 0.0
    assert snap["buckets"]["watchdog_stuck"] > 0.0
    useful = sum(snap["buckets"][b] for b in GOODPUT_USEFUL)
    assert snap["goodput"] == pytest.approx(useful / snap["total_s"])


# --------------------------------------------------------- engine books

def test_engine_ledger_records_partition_and_streams_identically(model):
    """Ledger on vs off: token streams are byte-identical, and every
    finalized record obeys the partition invariant with real engine
    timestamps."""
    prompt = _prompt(11)
    ref = np.asarray(generate(model, prompt[None], 10))[0, 5:]
    with GenerationEngine(model, slots=2, max_len=32, queue_max=4,
                          ledger=True) as eng:
        toks, err = _drain(eng, eng.start(prompt, 10, tenant="acme"))
        assert err is None and np.array_equal(np.asarray(toks, np.int32),
                                              ref)
        dump = eng.ledger_dump()
    assert [r["outcome"] for r in dump["records"]] == ["complete"]
    rec = dump["records"][0]
    assert tuple(rec["phases"]) == PHASES
    assert abs(sum(rec["phases"].values()) - rec["e2e_s"]) < 1e-9
    assert rec["tokens"] == 10 and rec["prompt_len"] == 5
    assert rec["tenant"] == "acme" and rec["chip_s"] > 0.0
    # decode dominates a 10-token greedy run; delivery was prompt
    assert rec["phases"]["decode_s"] > 0.0


def test_engine_goodput_and_tenant_blocks_in_stats(model):
    with GenerationEngine(model, slots=2, max_len=32, queue_max=4,
                          ledger=True) as eng:
        _drain(eng, eng.start(_prompt(12), 8))            # untenanted
        _drain(eng, eng.start(_prompt(13), 8, tenant="acme"))
        st = eng.stats()
        dump = eng.ledger_dump(limit=1)
    gp = st["goodput"]
    assert set(gp["buckets"]) == set(GOODPUT_BUCKETS)
    assert gp["ticks"] > 0 and gp["total_s"] > 0.0
    assert sum(gp["fractions"].values()) == pytest.approx(1.0)
    assert gp["buckets"]["decode"] > 0.0 and 0.0 < gp["goodput"] <= 1.0
    tens = st["tenants"]
    assert tens["acme"]["tokens"] == 8 and tens["acme"]["requests"] == 1
    assert tens[DEFAULT_TENANT]["tokens"] == 8
    assert tens["acme"]["chip_seconds"] > 0.0
    assert len(dump["records"]) == 1                      # limit honoured


def test_engine_ledger_cancel_outcome(model):
    with GenerationEngine(model, slots=1, max_len=48, queue_max=4,
                          step_wait_s=0.05, ledger=True) as eng:
        gid = eng.start(_prompt(14), 30)
        eng.poll(gid, wait_s=1.0)                 # at least one token out
        assert eng.cancel(gid)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            recs = eng.ledger_dump()["records"]
            if recs:
                break
            time.sleep(0.02)
    assert recs and recs[-1]["outcome"] == "cancelled"
    assert abs(sum(recs[-1]["phases"].values()) - recs[-1]["e2e_s"]) < 1e-9


def test_defaults_off_no_books_no_hot_path_flag_read(model, monkeypatch):
    """Hard-off discipline: the default engine holds no ledger and no
    meter, ships no goodput/tenants stats keys, returns None from
    ledger_dump, and never reads a ledger flag on the decode hot path —
    construction only (the FLAGS_trace pattern)."""
    import paddle_tpu.serving.engine as engine_mod

    assert not get_flags(["gen_ledger"])["gen_ledger"]
    reads: list[str] = []
    real_flag = engine_mod.flag

    def spy(name):
        reads.append(name)
        return real_flag(name)

    monkeypatch.setattr(engine_mod, "flag", spy)
    with GenerationEngine(model, slots=2, max_len=32, queue_max=4) as eng:
        assert eng._ledger is None and eng._goodput is None
        assert "gen_ledger" in reads               # construction-time only
        reads.clear()
        toks, err = _drain(eng, eng.start(_prompt(11), 10, tenant="acme"))
        assert err is None and len(toks) == 10
        assert not any(n.startswith("gen_ledger") for n in reads)
        st = eng.stats()
        assert "goodput" not in st and "tenants" not in st
        assert eng.ledger_dump() is None


# ----------------------------------------------------------------- wire

def test_ledger_dump_wire_roundtrip_and_infer_attribution(model, tmp_path):
    """The ``ledger_dump`` op ships engine records + tenant books +
    goodput over the wire, and the server's infer path books the ``tn``
    header into its own tenant book."""
    import paddle_tpu.io as io
    from paddle_tpu import nn

    saved = get_flags(["gen_ledger"])
    set_flags({"gen_ledger": True})       # server reads it at construction
    try:
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        mpath = str(tmp_path / "mlp")
        io.save_inference_model(mpath, net,
                                [np.zeros((2, 4), np.float32)])
        srv = InferenceServer({"m": mpath}).start()
        try:
            with GenerationEngine(model, slots=2, max_len=32,
                                  queue_max=4) as eng:   # flag-driven on
                srv.add_generator("llm", eng)
                with InferenceClient(srv.endpoint) as client:
                    toks = list(client.generate(
                        "llm", _prompt(21), 8, poll_wait_s=0.2,
                        tenant="acme"))
                    assert len(toks) == 8
                    client.infer("m", np.ones((2, 4), np.float32),
                                 tenant="acme")
                    client.infer("m", np.ones((2, 4), np.float32))
                    dump = client.ledger_dump()
                    one = client.ledger_dump(limit=1)
        finally:
            srv.stop()
    finally:
        set_flags(saved)
    eng_dump = dump["generators"]["llm"]
    assert [r["tenant"] for r in eng_dump["records"]] == ["acme"]
    rec = eng_dump["records"][0]
    assert abs(sum(rec["phases"].values()) - rec["e2e_s"]) < 1e-6
    assert eng_dump["tenants"]["acme"]["tokens"] == 8
    assert sum(eng_dump["goodput"]["fractions"].values()) == \
        pytest.approx(1.0)
    # infer-side book: the "tn" header lands per tenant, untagged
    # traffic books under the default key so fleet totals still add up
    inf = dump["infer_tenants"]
    assert inf["acme"]["requests"] == 1 and inf["acme"]["chip_seconds"] > 0
    assert inf[DEFAULT_TENANT]["requests"] == 1
    assert len(one["generators"]["llm"]["records"]) == 1
