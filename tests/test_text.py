"""Text dataset tests against miniature fixtures in the real on-disk
formats (aclImdb tar, PTB lines, UCI whitespace table, WMT parallel
files, MovieLens ::-separated, CoNLL prop spans)."""

import io
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu.text import (
    Conll05st, Imdb, Imikolov, MovieLens, RandomTextDataset, UCIHousing,
    Vocab, WMT14, simple_tokenize,
)


# ---------------------------------------------------------------------------
# vocab
# ---------------------------------------------------------------------------

def test_vocab_build_and_roundtrip():
    corpus = [["the", "cat", "sat"], ["the", "dog"], ["the", "cat"]]
    v = Vocab.build(corpus, min_freq=2, unk_token="<unk>")
    assert v["the"] != v["cat"]
    assert "dog" not in v                       # freq 1 < min_freq
    assert v["dog"] == v["<unk>"]
    ids = v.encode(["the", "cat", "zzz"])
    assert v.decode(ids)[:2] == ["the", "cat"]


def test_vocab_cutoff_and_determinism():
    corpus = [["a"] * 5 + ["b"] * 3 + ["c"]]
    v = Vocab.build(corpus, cutoff=2, unk_token="<unk>")
    assert "a" in v and "b" in v and "c" not in v
    v2 = Vocab.build(corpus, cutoff=2, unk_token="<unk>")
    assert v.itos == v2.itos


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _add_text(tf, name, text):
    data = text.encode()
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture
def imdb_tar(tmp_path):
    path = tmp_path / "aclImdb.tar.gz"
    with tarfile.open(path, "w:gz") as tf:
        docs = {
            "aclImdb/train/pos/0.txt": "a great movie great fun",
            "aclImdb/train/pos/1.txt": "great acting and great story",
            "aclImdb/train/neg/0.txt": "terrible movie boring plot",
            "aclImdb/train/neg/1.txt": "boring and terrible",
            "aclImdb/test/pos/0.txt": "great story",
            "aclImdb/test/neg/0.txt": "boring movie",
        }
        for name, text in docs.items():
            _add_text(tf, name, text)
    return str(path)


def test_imdb(imdb_tar):
    train = Imdb(imdb_tar, mode="train", cutoff=1)
    assert len(train) == 4
    ids, label = train[0]
    assert ids.dtype == np.int64 and label in (0, 1)
    # pos docs labeled 0 (reference convention), neg 1
    labels = sorted(int(train[i][1]) for i in range(4))
    assert labels == [0, 0, 1, 1]
    test = Imdb(imdb_tar, mode="test", cutoff=1)
    assert len(test) == 2
    # dict built on train in both modes: same vocab size
    assert len(test.word_idx) == len(train.word_idx)


def test_imikolov(tmp_path):
    f = tmp_path / "ptb.train.txt"
    f.write_text("the cat sat on the mat\nthe dog sat\n")
    ds = Imikolov(str(f), data_type="NGRAM", window_size=3, min_word_freq=1)
    first = ds[0]
    assert first.shape == (3,)
    assert ds.word_idx.decode([int(first[0])]) == ["<s>"]
    seq = Imikolov(str(f), data_type="SEQ", window_size=-1, min_word_freq=1)
    src, trg = seq[0]
    np.testing.assert_array_equal(src[1:], trg[:-1])
    assert len(seq) == 2


def test_uci_housing(tmp_path):
    rs = np.random.RandomState(0)
    table = rs.rand(50, 14) * 10
    f = tmp_path / "housing.data"
    f.write_text("\n".join(" ".join(f"{v:.4f}" for v in row)
                           for row in table))
    train = UCIHousing(str(f), mode="train")
    test = UCIHousing(str(f), mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)
    # normalized features are centered-ish
    allx = np.stack([train[i][0] for i in range(len(train))])
    assert np.abs(allx.mean(axis=0)).max() < 0.6


def test_wmt14(tmp_path):
    path = tmp_path / "wmt14.tar.gz"
    with tarfile.open(path, "w:gz") as tf:
        _add_text(tf, "wmt14/train/train.src", "le chat\nle chien\n")
        _add_text(tf, "wmt14/train/train.trg", "the cat\nthe dog\n")
        _add_text(tf, "wmt14/src.dict", "le\nchat\nchien\n")
        _add_text(tf, "wmt14/trg.dict", "the\ncat\ndog\n")
    ds = WMT14(str(path), mode="train")
    assert len(ds) == 2
    src, trg_in, trg_out = ds[0]
    assert ds.src_vocab.decode(src.tolist()) == ["le", "chat"]
    assert ds.trg_vocab.decode([int(trg_in[0])]) == ["<s>"]
    assert ds.trg_vocab.decode([int(trg_out[-1])]) == ["<e>"]
    np.testing.assert_array_equal(trg_in[1:], trg_out[:-1])


def test_movielens(tmp_path):
    d = tmp_path / "ml"
    d.mkdir()
    (d / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Comedy\n"
        "2::Heat (1995)::Action|Crime\n")
    (d / "users.dat").write_text(
        "1::M::25::4::90210\n2::F::35::7::10001\n")
    (d / "ratings.dat").write_text(
        "1::1::5::964982703\n1::2::3::964982931\n"
        "2::1::4::964982224\n2::2::2::964981247\n")
    ds = MovieLens(str(d), mode="train", test_ratio=0.25, rand_seed=0)
    ds_test = MovieLens(str(d), mode="test", test_ratio=0.25, rand_seed=0)
    assert len(ds) + len(ds_test) == 4
    uid, gender, age, job, mid, cats, title, rating = ds[0]
    assert gender in (0, 1) and 1 <= rating <= 5
    assert cats.dtype == np.int64 and title.dtype == np.int64


def test_conll05(tmp_path):
    words = tmp_path / "words.txt"
    props = tmp_path / "props.txt"
    words.write_text("The\ncat\nsat\n\nDogs\nbark\n\n")
    props.write_text(
        "-\t(A0*\n-\t*)\nsat\t(V*)\n\n-\t(A0*)\nbark\t(V*)\n\n")
    ds = Conll05st(str(words), str(props))
    assert len(ds) == 2
    word_ids, pred_idx, label_ids = ds[0]
    assert word_ids.shape == (3,) and label_ids.shape == (3,)
    assert int(pred_idx) == 2
    tags = [ds.label_vocab.itos[i] for i in label_ids]
    assert tags == ["B-A0", "I-A0", "B-V"]


def test_random_text_dataset_with_loader():
    from paddle_tpu.data import DataLoader

    ds = RandomTextDataset(num_samples=32, seq_len=16, vocab_size=50)
    dl = DataLoader(ds, batch_size=8, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0].shape == (8, 16)
    assert (batches[0] < 50).all()


def test_wmt16(tmp_path):
    from paddle_tpu.text import WMT16

    path = tmp_path / "wmt16.tar.gz"
    with tarfile.open(path, "w:gz") as tf:
        # tab-separated EN\tDE parallel lines (reference wmt16 layout)
        _add_text(tf, "wmt16/train",
                  "the cat\tdie katze\nthe dog\tder hund\n")
        _add_text(tf, "wmt16/val", "a cat\teine katze\n")
        _add_text(tf, "wmt16/en.dict", "<s>\n<e>\n<unk>\nthe\ncat\ndog\na\n")
        _add_text(tf, "wmt16/de.dict",
                  "<s>\n<e>\n<unk>\ndie\nkatze\nder\nhund\neine\n")
    ds = WMT16(str(path), mode="train")
    assert len(ds) == 2
    src, trg_in, trg_out = ds[0]
    # source wrapped in <s>...<e> (wmt16 semantics, unlike wmt14)
    assert ds.src_vocab.decode(src.tolist()) == ["<s>", "the", "cat", "<e>"]
    assert ds.trg_vocab.decode([int(trg_in[0])]) == ["<s>"]
    assert ds.trg_vocab.decode([int(trg_out[-1])]) == ["<e>"]
    np.testing.assert_array_equal(trg_in[1:], trg_out[:-1])

    # de -> en direction swaps the columns
    ds_de = WMT16(str(path), mode="train", lang="de")
    src_de, _, trg_out_de = ds_de[0]
    assert ds_de.src_vocab.decode(src_de.tolist()) == [
        "<s>", "die", "katze", "<e>"]

    # val split + built-from-train dictionaries when the tar ships none
    path2 = tmp_path / "wmt16_nodict.tar.gz"
    with tarfile.open(path2, "w:gz") as tf:
        _add_text(tf, "wmt16/train",
                  "the cat\tdie katze\nthe dog\tder hund\n")
        _add_text(tf, "wmt16/val", "the cat\tdie katze\n")
    ds_val = WMT16(str(path2), mode="val")
    assert len(ds_val) == 1
    src, _, _ = ds_val[0]
    assert ds_val.src_vocab.decode(src.tolist()) == [
        "<s>", "the", "cat", "<e>"]
