"""Parallelism tests: mesh construction, collectives in shard_map, ZeRO
spec derivation — all on the virtual 8-device CPU mesh (SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.parallel import collective as C
from paddle_tpu.parallel import mesh as M
from paddle_tpu.parallel.sharding import (
    add_fsdp_axis, opt_state_specs, param_specs_for_stage, strip_axis,
)


def test_create_mesh_from_strategy(devices8):
    s = DistributedStrategy()
    s.sharding.enable = True
    s.sharding.degree = 2
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    mesh = M.mesh_from_strategy(s)
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == 2  # leftover folded into dp
    assert mesh.shape["pp"] == 1
    assert mesh.axis_names == M.AXIS_ORDER


def test_create_mesh_indivisible_raises(devices8):
    with pytest.raises(ValueError):
        M.create_mesh({"tp": 3})


def _mesh2d(devices8):
    return Mesh(np.array(devices8).reshape(2, 4), ("dp", "tp"))


def test_collectives_in_shard_map(devices8):
    mesh = _mesh2d(devices8)
    x = jnp.arange(8.0)

    def body(x):  # x: [1] shard on dp axis? use tp axis of size 4
        s = C.all_reduce(x, axis="tp")
        g = C.all_gather(x, axis="tp")
        rs = C.reduce_scatter(g, axis="tp")
        b = C.broadcast(x, src=2, axis="tp")
        return s, g, rs, b

    f = shard_map(body, mesh=mesh, in_specs=P(("dp", "tp")),
                  out_specs=(P(("dp", "tp")), P("dp"),
                             P(("dp", "tp")), P(("dp", "tp"))),
                  check_vma=False)
    s, g, rs, b = f(x)
    # all_reduce over tp groups: ranks 0-3 sum to 6, ranks 4-7 sum to 22
    np.testing.assert_allclose(s[:4], [6, 6, 6, 6])
    np.testing.assert_allclose(s[4:], [22, 22, 22, 22])
    # gather: every tp rank holds its group's full vector (replicated over
    # tp, so the global view stacks one copy per dp group)
    np.testing.assert_allclose(g[:4], [0, 1, 2, 3])
    np.testing.assert_allclose(g[4:], [4, 5, 6, 7])
    # reduce_scatter of the gathered (each rank holds its group's [a..d]):
    # sum over 4 identical copies then scatter -> rank i gets 4*chunk_i
    np.testing.assert_allclose(rs[:4], [0, 4, 8, 12])
    np.testing.assert_allclose(rs[4:], [16, 20, 24, 28])
    # broadcast from tp-rank 2
    np.testing.assert_allclose(b[:4], [2, 2, 2, 2])
    np.testing.assert_allclose(b[4:], [6, 6, 6, 6])


def test_all_to_all_ulysses_swap(devices8):
    mesh = Mesh(np.array(devices8[:4]).reshape(4), ("sp",))
    # [seq=4, heads=4]: seq sharded; all_to_all -> heads sharded
    x = jnp.arange(16.0).reshape(4, 4)

    def body(x):  # local [1, 4]
        return C.all_to_all(x, axis="sp", split_axis=1, concat_axis=0)

    f = shard_map(body, mesh=mesh, in_specs=P("sp", None),
                  out_specs=P(None, "sp"))
    y = f(x)
    # transpose of blocks: y[:, j] on rank j holds column-block j of all seq
    np.testing.assert_allclose(y, x)  # with 1-wide blocks this is identity


def test_send_next_ring(devices8):
    mesh = Mesh(np.array(devices8[:4]).reshape(4), ("pp",))
    x = jnp.arange(4.0)

    f = shard_map(lambda v: C.send_next(v, axis="pp"), mesh=mesh,
                  in_specs=P("pp"), out_specs=P("pp"))
    y = f(x)
    np.testing.assert_allclose(y, [3, 0, 1, 2])  # rank i receives from i-1


def test_strip_and_add_fsdp_axis(devices8):
    assert strip_axis(P("fsdp", "tp"), "fsdp") == P(None, "tp")
    assert strip_axis(P(("dp", "fsdp"), None), "fsdp") == P("dp", None)
    mesh = M.create_mesh({"fsdp": 2, "tp": 2, "dp": 2})
    # adds to first divisible unsharded dim
    assert add_fsdp_axis(P(None, "tp"), (8, 4), mesh) == P("fsdp", "tp")
    # respects existing shard
    assert add_fsdp_axis(P("fsdp", None), (8, 4), mesh) == P("fsdp", None)
    # indivisible: replicated
    assert add_fsdp_axis(P(None,), (7,), mesh) == P(None)


def test_param_and_opt_specs_stages(devices8):
    from paddle_tpu import optimizer as opt

    mesh = M.create_mesh({"fsdp": 2, "tp": 2, "dp": 2})
    model = nn.Sequential(
        nn.Linear(8, 16, pspec=P("fsdp", "tp")),
        nn.Linear(16, 8, pspec=P("tp", "fsdp")),
    )
    # stage 2: params replicated over fsdp (tp kept)
    specs2 = param_specs_for_stage(model, mesh, stage=2)
    assert specs2.layers[0].weight == P(None, "tp")
    # stage 3: params keep fsdp
    specs3 = param_specs_for_stage(model, mesh, stage=3)
    assert specs3.layers[0].weight == P("fsdp", "tp")

    o = opt.Adam(1e-3)
    state = o.init(model)
    ospecs = opt_state_specs(state, specs2, model, mesh, stage=2)
    # moments get the fsdp shard stage>=1; counters stay replicated
    adam_state = ospecs[0]
    assert adam_state.mu.layers[0].weight == P("fsdp", "tp")
    assert adam_state.count == P()


def test_all_reduce_prod_signs_and_zeros(devices8):
    mesh = Mesh(np.array(devices8[:4]).reshape(4), ("g",))
    x = jnp.asarray([-2.0, 3.0, -1.0, 4.0])
    f = shard_map(lambda v: C.all_reduce(v, op=C.ReduceOp.PROD, axis="g"),
                  mesh=mesh, in_specs=P("g"), out_specs=P("g"),
                  check_vma=False)
    np.testing.assert_allclose(f(x), jnp.full(4, 24.0), rtol=1e-5)
    x0 = jnp.asarray([-2.0, 0.0, 5.0, 4.0])
    np.testing.assert_allclose(f(x0), jnp.zeros(4), atol=1e-7)


def test_opt_state_specs_single_param_model(devices8):
    """A one-leaf model: Adam's scalar count has the same treedef as the
    params, so structure matching alone would misclassify it and assign a
    rank-2 spec to a rank-0 leaf (advisor finding)."""
    from paddle_tpu import optimizer as opt

    class OneParam(nn.Module):
        def __init__(self):
            self.w = jnp.zeros((8, 8))
            self._pspecs = (("w", P("fsdp", "tp")),)

        def __call__(self, x):
            return x @ self.w

    mesh = M.create_mesh({"fsdp": 2, "tp": 2, "dp": 2})
    model = OneParam()
    specs = param_specs_for_stage(model, mesh, stage=3)
    o = opt.Adam(1e-3)
    state = o.init(model)
    ospecs = opt_state_specs(state, specs, model, mesh, stage=3)
    adam_state = ospecs[0]
    assert adam_state.count == P()
    assert adam_state.mu.w == P("fsdp", "tp")


def test_create_hybrid_mesh_single_slice_fallback(devices8):
    """Single-process: dcn degrees fold into the flat mesh so launch
    scripts work unchanged on one host."""
    mesh = M.create_hybrid_mesh({"tp": 2, "fsdp": 2}, {"dp": 2})
    assert mesh.shape["tp"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["dp"] == 2
    assert mesh.size == 8
    # no dcn axes at all → plain create_mesh
    mesh2 = M.create_hybrid_mesh({"tp": 2})
    assert mesh2.shape["tp"] == 2 and mesh2.size == 8
