"""Sequence/ragged op family (reference
``paddle/fluid/operators/sequence_ops/*``): golden outputs vs per-sequence
numpy loops, FD gradients for the pooling family (the OpTest pattern),
and the ragged DataLoader collate path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import sequence as S
from tests.op_test import check_grad


def ragged(rs, B=4, T=10, E=3):
    lengths = rs.randint(1, T + 1, (B,)).astype(np.int32)
    x = rs.randn(B, T, E).astype(np.float32)
    for i, n in enumerate(lengths):
        x[i, n:] = 0.0
    return jnp.asarray(x), jnp.asarray(lengths)


def test_sequence_mask():
    m = S.sequence_mask(jnp.asarray([0, 2, 4]), 4)
    np.testing.assert_array_equal(
        np.asarray(m), [[0, 0, 0, 0], [1, 1, 0, 0], [1, 1, 1, 1]])


def test_pad_unpad_roundtrip():
    rs = np.random.RandomState(0)
    lengths = np.array([3, 1, 4], np.int32)
    flat = rs.randn(int(lengths.sum()), 2).astype(np.float32)
    padded = S.sequence_pad(jnp.asarray(flat), jnp.asarray(lengths), 5,
                            pad_value=-1.0)
    assert padded.shape == (3, 5, 2)
    # valid rows match the packed input, padding is the pad value
    off = 0
    for i, n in enumerate(lengths):
        np.testing.assert_allclose(np.asarray(padded)[i, :n],
                                   flat[off:off + n])
        np.testing.assert_allclose(np.asarray(padded)[i, n:], -1.0)
        off += n
    fl, valid, packed = S.sequence_unpad(padded, jnp.asarray(lengths))
    got = np.zeros_like(flat)
    got[np.asarray(packed)[np.asarray(valid)]] = \
        np.asarray(fl)[np.asarray(valid)]
    np.testing.assert_allclose(got, flat)


@pytest.mark.parametrize("pool", ["sum", "mean", "sqrt", "max", "min",
                                  "first", "last"])
def test_sequence_pool_golden(pool):
    rs = np.random.RandomState(1)
    x, lengths = ragged(rs)
    got = np.asarray(S.sequence_pool(x, lengths, pool))
    xn, ln = np.asarray(x), np.asarray(lengths)
    for i, n in enumerate(ln):
        seq = xn[i, :n]
        ref = {"sum": seq.sum(0), "mean": seq.mean(0),
               "sqrt": seq.sum(0) / np.sqrt(n), "max": seq.max(0),
               "min": seq.min(0), "first": seq[0], "last": seq[n - 1]}[pool]
        np.testing.assert_allclose(got[i], ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("pool", ["sum", "mean", "sqrt", "max"])
def test_sequence_pool_fd_grad(pool):
    rs = np.random.RandomState(2)
    lengths = jnp.asarray([2, 3], jnp.int32)
    x = jnp.asarray(rs.randn(2, 4, 3).astype(np.float32))
    with jax.enable_x64(True):
        check_grad(
            lambda x: S.sequence_pool(x, lengths, pool),
            [jnp.asarray(np.asarray(x), jnp.float64)], wrt=(0,))


def test_segment_reductions_golden_and_grad():
    rs = np.random.RandomState(3)
    data = rs.randn(10, 4).astype(np.float32)
    seg = np.array([0, 0, 1, 1, 1, 3, 3, 0, 2, 2], np.int32)
    for name, fn, ref in [
        ("sum", S.segment_sum, lambda d, m: d[m].sum(0)),
        ("mean", S.segment_mean, lambda d, m: d[m].mean(0) if m.any()
         else np.zeros(4)),
        ("max", S.segment_max, lambda d, m: d[m].max(0) if m.any()
         else None),
    ]:
        got = np.asarray(fn(jnp.asarray(data), jnp.asarray(seg), 4))
        for s in range(4):
            m = seg == s
            expect = ref(data, m)
            if expect is None:
                continue
            np.testing.assert_allclose(got[s], expect, rtol=1e-5,
                                       atol=1e-6, err_msg=name)
    with jax.enable_x64(True):
        check_grad(
            lambda d: S.segment_sum(d, jnp.asarray(seg), 4),
            [jnp.asarray(data, jnp.float64)], wrt=(0,))
        check_grad(
            lambda d: S.segment_mean(d, jnp.asarray(seg), 4),
            [jnp.asarray(data, jnp.float64)], wrt=(0,))


def test_sequence_softmax_masks_padding():
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(3, 6).astype(np.float32))
    lengths = jnp.asarray([6, 2, 4], jnp.int32)
    p = np.asarray(S.sequence_softmax(x, lengths))
    for i, n in enumerate([6, 2, 4]):
        np.testing.assert_allclose(p[i, :n].sum(), 1.0, rtol=1e-5)
        np.testing.assert_allclose(p[i, n:], 0.0)


def test_sequence_reverse_golden():
    x = jnp.asarray(np.arange(12).reshape(2, 6).astype(np.float32))
    lengths = jnp.asarray([4, 6], jnp.int32)
    got = np.asarray(S.sequence_reverse(x, lengths))
    np.testing.assert_allclose(got[0], [3, 2, 1, 0, 4, 5])
    np.testing.assert_allclose(got[1], [11, 10, 9, 8, 7, 6])


def test_sequence_concat_golden():
    a = jnp.asarray(np.arange(6).reshape(2, 3).astype(np.float32))
    b = jnp.asarray((10 + np.arange(4)).reshape(2, 2).astype(np.float32))
    out, nl = S.sequence_concat(a, jnp.asarray([2, 3]), b,
                                jnp.asarray([1, 2]))
    np.testing.assert_array_equal(np.asarray(nl), [3, 5])
    np.testing.assert_allclose(np.asarray(out)[0], [0, 1, 10, 0, 0])
    np.testing.assert_allclose(np.asarray(out)[1], [3, 4, 5, 12, 13])


def test_sequence_conv_matches_loop():
    """Window projection vs an explicit per-position numpy loop
    (reference sequence_conv_op.h im2col semantics)."""
    rs = np.random.RandomState(5)
    B, T, E, O, ctx = 2, 6, 3, 4, 3
    x, lengths = ragged(rs, B=B, T=T, E=E)
    w = rs.randn(ctx * E, O).astype(np.float32)
    got = np.asarray(S.sequence_conv(x, lengths, jnp.asarray(w),
                                     context_start=-1, context_length=ctx))
    xn, ln = np.asarray(x), np.asarray(lengths)
    for i in range(B):
        for t in range(T):
            if t >= ln[i]:
                np.testing.assert_allclose(got[i, t], 0.0)
                continue
            cols = []
            for j in range(ctx):
                p = t + (-1 + j)
                cols.append(xn[i, p] if 0 <= p < ln[i]
                            else np.zeros(E, np.float32))
            ref = np.concatenate(cols) @ w
            np.testing.assert_allclose(got[i, t], ref, rtol=1e-5,
                                       atol=1e-5)


def test_sequence_enumerate_and_erase():
    ids = jnp.asarray([[1, 2, 3, 4, 0, 0], [5, 2, 5, 2, 5, 9]], jnp.int32)
    lengths = jnp.asarray([4, 6], jnp.int32)
    win = np.asarray(S.sequence_enumerate(ids, lengths, 2, pad_value=0))
    np.testing.assert_array_equal(win[0, :4],
                                  [[1, 2], [2, 3], [3, 4], [4, 0]])
    out, nl = S.sequence_erase(ids, lengths, jnp.asarray([2, 9]))
    np.testing.assert_array_equal(np.asarray(nl), [3, 3])
    np.testing.assert_array_equal(np.asarray(out)[0], [1, 3, 4, 0, 0, 0])
    np.testing.assert_array_equal(np.asarray(out)[1], [5, 5, 5, 0, 0, 0])
    # nothing erased + full length: compaction must not clobber the tail
    out2, nl2 = S.sequence_erase(ids, lengths, jnp.asarray([77]))
    np.testing.assert_array_equal(np.asarray(out2)[1], [5, 2, 5, 2, 5, 9])
    np.testing.assert_array_equal(np.asarray(nl2), [4, 6])


def test_ragged_collate_dataloader_path():
    """Variable-length dataset → DataLoader with ragged_collate yields
    bucketed (padded, lengths) batches; a pooled classifier consumes them
    with paddle_tpu.ops.sequence — the Imdb/Conll feed shape."""
    from paddle_tpu.data import DataLoader, ragged_collate
    from paddle_tpu.data.dataset import Dataset

    rs = np.random.RandomState(6)

    class VarLen(Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            n = 3 + (i * 7) % 50
            return (rs.randint(1, 100, (n,)).astype(np.int64),
                    np.int64(i % 2))

    dl = DataLoader(VarLen(), batch_size=4,
                    collate_fn=ragged_collate(bucket=16))
    shapes = set()
    for (ids, lengths), labels in dl:
        assert ids.shape[0] == 4 and lengths.shape == (4,)
        assert ids.shape[1] % 16 == 0
        shapes.add(ids.shape[1])
        assert labels.shape == (4,)
        # padding correct: everything beyond each length is 0
        for i in range(4):
            assert (ids[i, lengths[i]:] == 0).all()
        # consume on-device: masked mean pooling
        emb = jnp.take(jnp.ones((100, 8)), jnp.asarray(ids), axis=0)
        pooled = S.sequence_pool(emb, jnp.asarray(lengths), "mean")
        assert np.isfinite(np.asarray(pooled)).all()
    # bucketing bounds the distinct compile shapes
    assert len(shapes) <= 4


# ---------------------------------------------------------------------------
# sequence labeling: CRF / edit distance / ctc_align / im2sequence
# ---------------------------------------------------------------------------

def _brute_crf(emission, transition, lengths):
    """Enumerate all label sequences: exact log-partition and best path."""
    import itertools

    start, stop, trans = transition[0], transition[1], transition[2:]
    B, T, D = emission.shape
    log_zs, best_paths, best_scores = [], [], []
    for b in range(B):
        L = int(lengths[b])
        scores = {}
        for seq in itertools.product(range(D), repeat=L):
            s = start[seq[0]] + emission[b, 0, seq[0]]
            for t in range(1, L):
                s += trans[seq[t - 1], seq[t]] + emission[b, t, seq[t]]
            s += stop[seq[-1]]
            scores[seq] = s
        vals = np.array(list(scores.values()))
        m = vals.max()
        log_zs.append(m + np.log(np.exp(vals - m).sum()))
        best = max(scores, key=scores.get)
        best_paths.append(list(best) + [0] * (T - L))
        best_scores.append(scores[best])
    return np.array(log_zs), np.array(best_paths)


def test_linear_chain_crf_matches_enumeration():
    rs = np.random.RandomState(0)
    B, T, D = 3, 4, 3
    emission = rs.randn(B, T, D).astype(np.float64)
    transition = rs.randn(D + 2, D).astype(np.float64)
    labels = rs.randint(0, D, (B, T))
    lengths = np.array([4, 2, 3])
    log_z, _ = _brute_crf(emission, transition, lengths)
    nll = np.asarray(S.linear_chain_crf(
        jnp.asarray(emission), jnp.asarray(transition),
        jnp.asarray(labels), jnp.asarray(lengths)))
    # manual gold scores
    start, stop, trans = transition[0], transition[1], transition[2:]
    for b in range(B):
        L = int(lengths[b])
        g = start[labels[b, 0]] + emission[b, 0, labels[b, 0]]
        for t in range(1, L):
            g += trans[labels[b, t - 1], labels[b, t]]
            g += emission[b, t, labels[b, t]]
        g += stop[labels[b, L - 1]]
        np.testing.assert_allclose(nll[b], log_z[b] - g, rtol=1e-5)


def test_linear_chain_crf_grads():
    rs = np.random.RandomState(1)
    emission = rs.randn(2, 3, 3)
    transition = rs.randn(5, 3)
    labels = jnp.asarray(rs.randint(0, 3, (2, 3)))
    lengths = jnp.asarray(np.array([3, 2]))
    check_grad(
        lambda e, tr: S.linear_chain_crf(e, tr, labels, lengths),
        [emission, transition], wrt=(0, 1))


def test_crf_decoding_matches_enumeration():
    rs = np.random.RandomState(2)
    B, T, D = 3, 5, 3
    emission = rs.randn(B, T, D).astype(np.float64)
    transition = rs.randn(D + 2, D).astype(np.float64)
    lengths = np.array([5, 3, 1])
    _, best = _brute_crf(emission, transition, lengths)
    path = np.asarray(S.crf_decoding(
        jnp.asarray(emission), jnp.asarray(transition),
        jnp.asarray(lengths)))
    np.testing.assert_array_equal(path, best)
    # label mode: per-position correctness indicator
    ind = np.asarray(S.crf_decoding(
        jnp.asarray(emission), jnp.asarray(transition),
        jnp.asarray(lengths), labels=jnp.asarray(best)))
    expect = (np.arange(T)[None] < lengths[:, None]).astype(np.int64)
    np.testing.assert_array_equal(ind, expect)


def test_edit_distance_golden():
    def py_ed(a, b):
        dp = np.zeros((len(a) + 1, len(b) + 1))
        dp[:, 0] = np.arange(len(a) + 1)
        dp[0, :] = np.arange(len(b) + 1)
        for i in range(1, len(a) + 1):
            for j in range(1, len(b) + 1):
                dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                               dp[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
        return dp[-1, -1]

    rs = np.random.RandomState(3)
    B, Th, Tr = 4, 6, 5
    hyp = rs.randint(0, 4, (B, Th))
    ref = rs.randint(0, 4, (B, Tr))
    hl = np.array([6, 4, 2, 0])
    rl = np.array([5, 5, 0, 3])
    got = np.asarray(S.edit_distance(
        jnp.asarray(hyp), jnp.asarray(hl), jnp.asarray(ref),
        jnp.asarray(rl)))
    want = [py_ed(list(hyp[b, :hl[b]]), list(ref[b, :rl[b]]))
            for b in range(B)]
    np.testing.assert_allclose(got, want)
    norm = np.asarray(S.edit_distance(
        jnp.asarray(hyp), jnp.asarray(hl), jnp.asarray(ref),
        jnp.asarray(rl), normalized=True))
    np.testing.assert_allclose(norm, np.array(want) / np.maximum(rl, 1))


def test_ctc_align_golden():
    ids = jnp.asarray(np.array([[1, 1, 0, 2, 2, 0, 3],
                                [0, 0, 4, 4, 4, 5, 0]]))
    lengths = jnp.asarray(np.array([7, 6]))
    out, new_len = S.ctc_align(ids, lengths, blank=0)
    np.testing.assert_array_equal(np.asarray(new_len), [3, 2])
    np.testing.assert_array_equal(np.asarray(out)[0, :3], [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(out)[1, :2], [4, 5])
    assert np.asarray(out)[0, 3:].sum() == 0


def test_im2sequence_matches_unfold():
    from paddle_tpu.nn import functional as F

    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(2, 3, 6, 6).astype(np.float32))
    seq = S.im2sequence(x, 2, stride=2)
    assert seq.shape == (2, 9, 12)        # 3x3 positions, 3*2*2 features
    cols = F.unfold(x, 2, stride=2)
    np.testing.assert_allclose(np.asarray(seq),
                               np.asarray(cols).transpose(0, 2, 1))
