"""Launcher tests: real subprocesses, real coordination service — the test
class the reference runs via ``tests/unittests/test_dist_base.py:642``
(_run_cluster vs _run_local within tolerance) and the one that catches
bootstrap bugs a faked in-process device mesh cannot (VERDICT round 1)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_toy_train.py")


def run_launcher(nproc, tmp_path, mode="train", timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["TOY_OUT"] = str(tmp_path)
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc", str(nproc), "--log_dir", str(tmp_path / "logs"),
         WORKER, mode],
        env=env, cwd=REPO, timeout=timeout, capture_output=True, text=True)
    return proc, time.time() - t0


def read_losses(tmp_path, rank):
    with open(tmp_path / f"losses.{rank}.json") as f:
        return json.load(f)


@pytest.mark.slow
def test_launch_2proc_matches_local(tmp_path):
    """2-process DP losses must equal the single-process run (the
    TestDistBase check_with_place comparison, over a real coordination
    service + Gloo CPU collectives instead of faked devices)."""
    proc, _ = run_launcher(2, tmp_path)
    assert proc.returncode == 0, (proc.stdout, proc.stderr,
                                  _logs(tmp_path))
    l0 = read_losses(tmp_path, 0)
    l1 = read_losses(tmp_path, 1)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)  # replicated loss

    local = tmp_path / "local"
    local.mkdir()
    proc, _ = run_launcher(1, local)
    assert proc.returncode == 0, (proc.stdout, proc.stderr, _logs(local))
    lref = read_losses(local, 0)
    np.testing.assert_allclose(l0, lref, rtol=1e-4)
    assert l0[-1] < l0[0]


@pytest.mark.slow
def test_launch_tears_down_pod_on_failure(tmp_path):
    """Rank 1 exits 3; rank 0 sleeps forever. The launcher must kill the
    pod and propagate the failing code well before rank-0's sleep ends
    (reference distributed/utils.py:484 watch_local_trainers)."""
    proc, dt = run_launcher(2, tmp_path, mode="crash", timeout=120)
    assert proc.returncode == 3, (proc.returncode, proc.stdout, proc.stderr)
    assert dt < 100, f"teardown took {dt:.0f}s — watch loop not working"


def _logs(tmp_path):
    out = {}
    logdir = tmp_path / "logs"
    if logdir.exists():
        for p in logdir.iterdir():
            out[p.name] = p.read_text()[-2000:]
    return out


@pytest.mark.slow
def test_launch_ps_mode_2proc(tmp_path):
    """rank 0 hosts the PS service; both ranks train disjoint sparse rows
    through it (the reference's PS-mode distributed test shape)."""
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["TOY_OUT"] = str(tmp_path)
    env["PS_PORT"] = str(port)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc", "2", "--log_dir", str(tmp_path / "logs"),
         os.path.join(REPO, "tests", "dist_ps_train.py")],
        env=env, cwd=REPO, timeout=240, capture_output=True, text=True)
    assert proc.returncode == 0, (proc.stdout, proc.stderr, _logs(tmp_path))
    for rank in range(2):
        with open(tmp_path / f"ps_losses.{rank}.json") as f:
            losses = json.load(f)
        assert losses[-1] < losses[0] * 0.1, (rank, losses[:3], losses[-3:])


@pytest.mark.slow
def test_spawn_runs_collective(tmp_path):
    """distributed.spawn: 2 module-level workers psum over the
    coordination service (reference spawn.py semantics)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import os; os.environ['JAX_PLATFORMS']='cpu'; "
         "os.environ.pop('XLA_FLAGS', None); "
         "import sys; sys.path.insert(0, 'tests'); "
         "from paddle_tpu.distributed.launch import spawn; "
         "from dist_toy_train import spawn_worker; "
         f"spawn(spawn_worker, args=({str(tmp_path)!r},), nprocs=2)"],
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                 "PYTHONPATH", "")},
        cwd=REPO, timeout=240, capture_output=True, text=True)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    vals = [float(open(tmp_path / f"spawn.{r}.txt").read())
            for r in range(2)]
    assert vals == [3.0, 3.0], vals  # 1 + 2 on both ranks
