"""Sequence parallelism tests: ring attention and Ulysses must equal dense
attention exactly; fleet sp strategy must reproduce DP losses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu
from paddle_tpu.nn import functional as F
from paddle_tpu.parallel.ring_attention import (
    ring_self_attention, ulysses_self_attention,
)


def _sp_mesh(devices8, n=4):
    return Mesh(np.array(devices8[:n]).reshape(n), ("sp",))


def _qkv(B=2, T=16, H=4, Hkv=4, D=8, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, T, Hkv, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, T, Hkv, D).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(devices8, causal):
    mesh = _sp_mesh(devices8)
    q, k, v = _qkv()
    ref = F.scaled_dot_product_attention(q, k, v, causal=causal,
                                         use_pallas="never")
    out = ring_self_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_ring_attention_gqa(devices8):
    mesh = _sp_mesh(devices8)
    q, k, v = _qkv(H=4, Hkv=2)
    ref = F.scaled_dot_product_attention(q, k, v, causal=True,
                                         use_pallas="never")
    out = ring_self_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match(devices8):
    mesh = _sp_mesh(devices8)
    q, k, v = _qkv(T=8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh=mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(F.scaled_dot_product_attention(
            q, k, v, causal=True, use_pallas="never") ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(devices8, causal):
    mesh = _sp_mesh(devices8)
    q, k, v = _qkv()  # H=4 divisible by sp=4
    ref = F.scaled_dot_product_attention(q, k, v, causal=causal,
                                         use_pallas="never")
    out = ulysses_self_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_fleet_seq_parallel_matches_dp(devices8, mode):
    from test_fleet import run_steps
    from paddle_tpu.core.strategy import DistributedStrategy
    from paddle_tpu.models import LlamaConfig

    cfg = LlamaConfig.tiny()
    s1 = DistributedStrategy()
    s2 = DistributedStrategy()
    s2.sequence_parallel.enable = True
    s2.sequence_parallel.degree = 2
    s2.sequence_parallel.mode = mode
    l1, _, _ = run_steps(s1, cfg=cfg)
    l2, state2, _ = run_steps(s2, cfg=cfg)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-5)
    assert state2.model.blocks.block.attn.seq_mode == mode


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_fleet_pp_seq_parallel_matches_dp(devices8, mode, schedule):
    """pp∘sp composition matrix under the default (Shardy) partitioner:
    the pipeline shard_maps run manual over {pp, sp} and ring/Ulysses
    rides the already-manual sp axis — r3's scoped-GSPMD fallback and
    the pp∘Ulysses gate are retired. Multi-step loss parity vs pure DP
    exercises the grad psums (block grads partial over sequence shards),
    the RoPE global-position offset, and the schedule's centrally
    shifted labels at shard boundaries."""
    from test_fleet import run_steps
    from paddle_tpu.core.strategy import DistributedStrategy
    from paddle_tpu.models import LlamaConfig

    cfg = LlamaConfig.tiny()
    s1 = DistributedStrategy()
    s2 = DistributedStrategy()
    s2.pipeline.enable = True
    s2.pipeline.degree = 2
    s2.pipeline.num_microbatches = 2
    s2.pipeline.schedule = schedule
    s2.sequence_parallel.enable = True
    s2.sequence_parallel.degree = 2
    s2.sequence_parallel.mode = mode
    l1, _, _ = run_steps(s1, cfg=cfg)
    l2, _, _ = run_steps(s2, cfg=cfg)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-5)
