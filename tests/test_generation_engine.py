"""Continuous-batching generation engine: slot scheduling, streaming
wire ops, session-sticky routing, and the early-exit decode loop.

The load-bearing property is determinism: a greedy generation through
the slot engine — admitted into a shared batched KV cache, stepped
alongside arbitrary co-tenants, prefetched through a right-padded
bucket — must be byte-identical to a solo
``models.generation.generate`` call.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core.flags import flag, set_flags
from paddle_tpu.core.monitor import get_stat
from paddle_tpu.core.wire import WireShedError
from paddle_tpu.io.serving import InferenceClient, InferenceServer
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.serving import (
    EngineOverloaded, GenerationEngine, GenerationFailed, RoutedClient,
)

pytestmark = pytest.mark.gen

VOCAB = 96


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def engine(model):
    with GenerationEngine(model, slots=3, max_len=32, queue_max=4,
                          ttl_s=10.0) as eng:
        yield eng


@pytest.fixture(scope="module")
def server(model, engine):
    srv = InferenceServer().start()
    srv.add_generator("llm", engine)   # pre-built engine: no recompile
    client = InferenceClient(srv.endpoint)
    yield srv, client
    client.close()
    srv.stop()


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


def _wait_active(engine, pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred(engine.stats()):
            return True
        time.sleep(0.02)
    return False


def test_interleaved_matches_solo_generate(model, engine):
    """8 concurrent greedy generations through 3 slots (queueing forces
    admits/retires mid-flight) are byte-identical to solo generate()."""
    rs = np.random.RandomState(1)
    prompts = rs.randint(0, VOCAB, (8, 6)).astype(np.int32)
    ref = np.asarray(generate(model, prompts, 5))[:, 6:]

    out = {}

    def worker(i):
        gid = None
        while gid is None:
            try:
                gid = engine.start(prompts[i], 5)
            except EngineOverloaded as e:
                time.sleep(e.retry_after_s)
        out[i] = _drain(engine, gid)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for i in range(8):
        toks, err = out[i]
        assert err is None
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref[i],
                                      err_msg=f"request {i}")
    st = engine.stats()
    assert st["active"] == 0 and st["queued"] == 0


def test_variable_lengths_and_late_admit(model, engine):
    """Different prompt lengths (different prefill buckets) and a late
    admit into a freed slot still match solo generate exactly."""
    rs = np.random.RandomState(2)
    prompts = [rs.randint(0, VOCAB, (n,)).astype(np.int32)
               for n in (3, 9, 5)]
    gids = [engine.start(p, 4) for p in prompts]
    outs = [_drain(engine, g) for g in gids]
    for p, (toks, err) in zip(prompts, outs):
        assert err is None
        ref = np.asarray(generate(model, p[None], 4))[0, len(p):]
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)


def test_eos_retires_slot_early(model, engine):
    """A request whose eos fires mid-stream stops there (stream ends
    with eos) and frees its slot without running to max_new_tokens."""
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, VOCAB, (6,)).astype(np.int32)
    ref = np.asarray(generate(model, prompt[None], 6))[0, 6:]
    eos = int(ref[2])                        # finish after 3 tokens
    gid = engine.start(prompt, 6, eos_token_id=eos)
    toks, err = _drain(engine, gid)
    assert err is None
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref[:3])
    assert engine.stats()["active"] == 0


def test_cancel_frees_slot_others_uninterrupted(model, engine):
    rs = np.random.RandomState(4)
    p_a = rs.randint(0, VOCAB, (5,)).astype(np.int32)
    p_b = rs.randint(0, VOCAB, (5,)).astype(np.int32)
    ref_b = np.asarray(generate(model, p_b[None], 10))[0, 5:]
    ev0 = get_stat("gen/evictions")
    engine.step_wait_s = 0.02     # pace the loop so "mid-flight" exists
    try:
        gid_a = engine.start(p_a, 20)
        gid_b = engine.start(p_b, 10)
        # let both stream a little, then cancel A mid-flight
        while len(engine.poll(gid_a, wait_s=0.5)["tokens"]) < 2:
            pass
        assert engine.cancel(gid_a)
        toks_b, err_b = _drain(engine, gid_b)
    finally:
        engine.step_wait_s = 0.0
    assert err_b is None
    np.testing.assert_array_equal(np.asarray(toks_b, np.int32), ref_b)
    doc = engine.poll(gid_a) if gid_a in engine._gens else None
    assert doc is None                      # cancelled gens are dropped
    assert get_stat("gen/evictions") == ev0 + 1
    assert _wait_active(engine, lambda s: s["active"] == 0)


def test_full_engine_sheds_start(model, engine):
    """slots busy + queue at queue_max -> EngineOverloaded (retryable),
    and capacity returns once generations are cancelled."""
    rs = np.random.RandomState(5)
    prompts = [rs.randint(0, VOCAB, (4,)).astype(np.int32)
               for _ in range(7)]
    engine.step_wait_s = 0.03     # keep slots visibly busy
    try:
        gids = [engine.start(p, 25) for p in prompts]  # 3 run + 4 queue
        assert _wait_active(engine, lambda s: s["active"] == 3
                            and s["queued"] >= 4)
        with pytest.raises(EngineOverloaded) as ei:
            engine.start(prompts[0], 25)
        assert ei.value.retry_after_s > 0
        for g in gids:
            engine.cancel(g)
    finally:
        engine.step_wait_s = 0.0
    assert _wait_active(engine, lambda s: s["active"] == 0
                        and s["queued"] == 0)
    gid = engine.start(prompts[0], 2)               # works again
    toks, err = _drain(engine, gid)
    assert err is None and len(toks) == 2


def test_poll_ttl_reaps_disconnected_client(model, engine):
    """A generation whose client stops polling is evicted after the TTL
    and its slot reclaimed — the disconnect story."""
    old = engine._ttl_s
    engine._ttl_s = 0.3
    engine.step_wait_s = 0.05     # generation outlives the TTL window
    try:
        rs = np.random.RandomState(6)
        gid = engine.start(rs.randint(0, VOCAB, (4,)).astype(np.int32),
                           25)
        assert _wait_active(engine, lambda s: s["active"] == 1)
        ev0 = get_stat("gen/evictions")
        # no polls -> TTL expires -> slot freed, gen forgotten
        assert _wait_active(engine, lambda s: s["active"] == 0
                            and s["generations"] == 0, timeout=3.0)
        assert get_stat("gen/evictions") >= ev0 + 1
        with pytest.raises(KeyError):
            engine.poll(gid)
    finally:
        engine._ttl_s = old
        engine.step_wait_s = 0.0


def test_sampled_generation_is_per_request_deterministic(model, engine):
    """Sampling params are per-slot traced state: the same (prompt,
    seed) yields the same stream regardless of co-tenants."""
    rs = np.random.RandomState(7)
    prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
    runs = []
    for _ in range(2):
        gid = engine.start(prompt, 6, temperature=0.8, top_k=7,
                           top_p=0.9, seed=42)
        toks, err = _drain(engine, gid)
        assert err is None
        runs.append(toks)
    assert runs[0] == runs[1]
    assert all(0 <= t < VOCAB for t in runs[0])


def test_engine_requires_slots_flag(model):
    """FLAGS_gen_slots=0 (default) keeps generation serving off: no
    engine, no background thread, the serving path untouched."""
    assert int(flag("gen_slots")) == 0
    with pytest.raises(ValueError, match="gen_slots"):
        GenerationEngine(model)
    with pytest.raises(ValueError, match="gen_slots"):
        InferenceServer().add_generator("llm", model)
    set_flags({"gen_slots": 2})
    try:
        eng = GenerationEngine(model, max_len=32)
        assert eng.slots == 2
        eng.close()
    finally:
        set_flags({"gen_slots": 0})


def test_start_validates_capacity(model, engine):
    with pytest.raises(ValueError, match="capacity"):
        engine.start(np.arange(10, dtype=np.int32), 30)   # 40 > 32
    with pytest.raises(ValueError, match="empty"):
        engine.start(np.zeros((0,), np.int32), 4)


def test_wire_stream_and_health(model, engine, server):
    """Client streaming iterator over the wire matches solo generate;
    health reports slot occupancy; breaking the stream cancels
    server-side so the slot frees immediately."""
    srv, client = server
    rs = np.random.RandomState(8)
    prompt = rs.randint(0, VOCAB, (6,)).astype(np.int32)
    ref = np.asarray(generate(model, prompt[None], 5))[0, 6:]
    toks = list(client.generate("llm", prompt, 5))
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)

    h = client.health()
    assert h["generators"]["llm"]["slots"] == 3

    it = client.generate("llm", prompt, 25)
    assert next(it) == int(ref[0])
    it.close()                              # break mid-stream -> cancel
    assert _wait_active(engine, lambda s: s["active"] == 0)


def test_wire_full_engine_sheds_with_retry_hint(model, engine, server):
    """A full engine sheds generate_start with CODE_SHED +
    retry_after_s — the typed, retryable WireShedError a no-retry
    client surfaces (never an opaque error; the start never ran) — and
    capacity returns once generations are cancelled."""
    srv, client = server
    rs = np.random.RandomState(9)
    prompts = [rs.randint(0, VOCAB, (4,)).astype(np.int32)
               for _ in range(7)]
    engine.step_wait_s = 0.03
    try:
        gids = [engine.start(p, 25) for p in prompts]
        assert _wait_active(engine, lambda s: s["active"] == 3
                            and s["queued"] >= 4)
        c0 = InferenceClient(srv.endpoint, retries=0)
        try:
            with pytest.raises(WireShedError, match="engine full"):
                c0.generate_start("llm", prompts[0], 25)
        finally:
            c0.close()
        for g in gids:
            engine.cancel(g)
        assert _wait_active(engine, lambda s: s["active"] == 0)
    finally:
        engine.step_wait_s = 0.0
    toks = list(client.generate("llm", prompts[0], 2))
    assert len(toks) == 2                   # capacity returned


def test_wire_unknown_generator_and_generation(server):
    srv, client = server
    with pytest.raises(RuntimeError, match="no generator"):
        client.generate_start("nope", [1, 2, 3], 4)
    with pytest.raises(RuntimeError, match="unknown generation"):
        client.generate_poll("llm", "deadbeef")


# -- paged KV cache + prefix sharing + chunked prefill ----------------------

@pytest.fixture(scope="module")
def paged_engine(model):
    """Paged mode with deliberately awkward geometry: 8-token pages,
    3-token prefill chunks (page- and chunk-misaligned prompts), pool
    sized to the contiguous equivalent."""
    with GenerationEngine(model, slots=3, max_len=32, queue_max=32,
                          ttl_s=10.0, paged=True, page_tokens=8,
                          prefill_chunk=3) as eng:
        yield eng


def test_paged_interleaved_matches_solo_generate(model, paged_engine):
    """8 concurrent greedy generations through 3 paged slots — admits,
    retires, page reuse, and chunked prefill all mid-flight — are
    byte-identical to solo generate()."""
    rs = np.random.RandomState(21)
    prompts = rs.randint(0, VOCAB, (8, 6)).astype(np.int32)
    ref = np.asarray(generate(model, prompts, 5))[:, 6:]
    out = {}

    def worker(i):
        gid = None
        while gid is None:
            try:
                gid = paged_engine.start(prompts[i], 5)
            except EngineOverloaded as e:
                time.sleep(e.retry_after_s)
        out[i] = _drain(paged_engine, gid)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for i in range(8):
        toks, err = out[i]
        assert err is None
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref[i],
                                      err_msg=f"request {i}")
    st = paged_engine.stats()
    assert st["active"] == 0 and st["queued"] == 0
    # every non-shared page came back (6+5 = 11 tokens < 1 full page of
    # prompt -> nothing prefix-cacheable here)
    assert st["pages_free"] == st["pages"]


def test_paged_prefix_sharing_matches_solo(model, paged_engine):
    """Generations sharing a 17-token prompt prefix (2 full 8-token
    pages) map their early pages to the same physical pages: prefill
    runs once per unique prefix, and each stream is still
    byte-identical to its solo generate()."""
    from paddle_tpu.core.monitor import get_stat

    rs = np.random.RandomState(22)
    prefix = rs.randint(0, VOCAB, (17,)).astype(np.int32)
    hits0 = get_stat("gen/prefix_hits")
    saved0 = get_stat("gen/prefix_tokens_saved")
    for t in range(3):
        tail = rs.randint(0, VOCAB, (3,)).astype(np.int32)
        p = np.concatenate([prefix, tail])
        ref = np.asarray(generate(model, p[None], 4))[0, len(p):]
        gid = paged_engine.start(p, 4)
        toks, err = _drain(paged_engine, gid)
        assert err is None
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref,
                                      err_msg=f"stream {t}")
    # streams 2 and 3 each matched the 2 cached prefix pages
    assert get_stat("gen/prefix_hits") == hits0 + 2
    assert get_stat("gen/prefix_tokens_saved") == saved0 + 2 * 2 * 8
    st = paged_engine.stats()
    assert st["prefix_entries"] >= 2
    # cached pages are the only ones still held
    assert st["pages_free"] == st["pages"] - st["prefix_entries"]
    paged_engine.clear_prefix_cache()
    assert paged_engine.stats()["pages_free"] == st["pages"]


def test_paged_long_prompt_chunked_prefill_matches_solo(model,
                                                        paged_engine):
    """A prompt spanning many 3-token chunks and several pages prefills
    in slices and still matches solo generate() exactly; the chunk
    histogram proves the slicing actually happened."""
    from paddle_tpu.core.monitor import get_histogram

    rs = np.random.RandomState(23)
    p = rs.randint(0, VOCAB, (26,)).astype(np.int32)
    ref = np.asarray(generate(model, p[None], 5))[0, 26:]
    h0 = (get_histogram("gen/prefill_chunk_s") or {}).get("count", 0)
    gid = paged_engine.start(p, 5)
    toks, err = _drain(paged_engine, gid)
    assert err is None
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
    h1 = get_histogram("gen/prefill_chunk_s")["count"]
    assert h1 - h0 >= 9                     # ceil(26 / 3) chunks


def test_paged_sampled_deterministic_per_seed(model, paged_engine):
    rs = np.random.RandomState(24)
    prompt = rs.randint(0, VOCAB, (9,)).astype(np.int32)
    runs = []
    for _ in range(2):
        gid = paged_engine.start(prompt, 6, temperature=0.8, top_k=7,
                                 top_p=0.9, seed=42)
        toks, err = _drain(paged_engine, gid)
        assert err is None
        runs.append(toks)
    assert runs[0] == runs[1]
    assert all(0 <= t < VOCAB for t in runs[0])


def test_paged_defaults_off_keeps_contiguous_layout(model):
    """FLAGS_gen_paged=0 (default) leaves the PR-5 contiguous engine in
    place: per-slot [slots, L, 1, Hkv, S, D] cache, no pool, no page
    tables."""
    assert not flag("gen_paged")
    with GenerationEngine(model, slots=2, max_len=32) as eng:
        assert not eng._paged
        assert eng._pool is None and eng._pt is None
        leaf = eng._state["cache"][0]
        assert leaf.shape[0] == 2 and leaf.shape[4] == 32
        assert not eng.stats()["paged"]
    set_flags({"gen_paged": True})
    try:
        with GenerationEngine(model, slots=2, max_len=32) as eng:
            assert eng._paged and eng.stats()["paged"]
            # default pool = slots x ceil(max_len / page_tokens)
            assert eng.stats()["pages"] == 2 * -(-32 // int(
                flag("gen_page_tokens")))
    finally:
        set_flags({"gen_paged": False})


def test_paged_wire_stream_and_health(model, paged_engine):
    """The wire path is mode-agnostic: streaming over a paged engine
    matches solo generate, and health ships page-pool occupancy."""
    srv = InferenceServer().start()
    srv.add_generator("pllm", paged_engine)
    client = InferenceClient(srv.endpoint)
    try:
        rs = np.random.RandomState(25)
        prompt = rs.randint(0, VOCAB, (7,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 5))[0, 7:]
        toks = list(client.generate("pllm", prompt, 5))
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        g = client.health()["generators"]["pllm"]
        assert g["paged"] and g["pages"] > 0
        assert g["pages_free"] + g["prefix_entries"] >= g["pages"] - 1
    finally:
        client.close()
        # the engine is module-scoped: detach it before stopping so the
        # server does not close it for later tests
        with srv._lock:
            srv._generators.clear()
        srv.stop()


# -- session-sticky routing -------------------------------------------------

def test_session_sticky_pick_and_repick_on_loss():
    """Same session id -> same replica while membership holds; member
    loss re-picks only when no generation is in flight."""
    servers = [InferenceServer().start() for _ in range(3)]
    router = RoutedClient([s.endpoint for s in servers],
                          probe_interval_s=0)
    try:
        s1 = router.session("sess-abc")
        s2 = router.session("sess-abc")
        assert s1.health()["status"] == "ok"
        assert s2.health()["status"] == "ok"
        assert s1.endpoint == s2.endpoint      # deterministic pin
        pinned = s1.endpoint
        for _ in range(3):
            s1.health()
            assert s1.endpoint == pinned       # sticky across ops

        router.remove_endpoint(pinned)
        s1.health()                            # member loss -> re-pick
        assert s1.endpoint is not None and s1.endpoint != pinned

        # an in-flight generation must NOT re-pick silently
        s3 = router.session("sess-xyz")
        s3.health()
        s3._active = 1
        router.remove_endpoint(s3.endpoint)
        with pytest.raises(GenerationFailed) as ei:
            s3.health()
        assert ei.value.endpoint not in router.endpoints()
    finally:
        router.close()
        for s in servers:
            s.stop()


@pytest.mark.slow
def test_session_generate_no_silent_failover(model):
    """Kill the replica holding a generation mid-stream: the session
    surfaces GenerationFailed naming the replica (never silently
    reroutes the poll), and a restart on the survivor succeeds."""
    paddle_tpu.seed(7)
    servers = []
    for _ in range(2):
        srv = InferenceServer().start()
        srv.add_generator("llm", model, slots=2, max_len=32)
        servers.append(srv)
    router = RoutedClient([s.endpoint for s in servers],
                          probe_interval_s=0)
    try:
        rs = np.random.RandomState(10)
        prompt = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 4))[0, 5:]
        sess = router.session("victim")
        it = sess.generate("llm", prompt, 25, poll_wait_s=0.05)
        next(it)
        pinned = sess.endpoint
        victim = next(s for s in servers if s.endpoint == pinned)
        victim.stop()
        with pytest.raises(GenerationFailed) as ei:
            list(it)
        assert ei.value.endpoint == pinned

        sess2 = router.session("survivor-run")
        toks = list(sess2.generate("llm", prompt, 4))
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        assert sess2.endpoint != pinned
    finally:
        router.close()
        for s in servers:
            s.stop()


# -- generate(): while_loop early exit --------------------------------------

def _fori_reference(model, input_ids, max_new_tokens, *, temperature=0.0,
                    eos_token_id=None, pad_token_id=0, key=None):
    """The pre-while_loop decode loop (fixed trip count), kept here as
    the regression reference for the early-exit rewrite."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.generation import sample_logits

    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, T0 = input_ids.shape
    S = T0 + int(max_new_tokens)
    cache = model.init_cache(B, S, dtype=None)
    logits, cache = model.forward_with_cache(input_ids, cache, index=0)
    seq = jnp.concatenate(
        [input_ids, jnp.full((B, max_new_tokens), pad_token_id,
                             jnp.int32)], axis=1)
    if key is None:
        key = jax.random.PRNGKey(0)

    def pick(logits, key):
        return sample_logits(logits, None if temperature == 0.0 else key,
                             temperature=temperature)

    key, sub = jax.random.split(key)
    next_tok = pick(logits[:, -1], sub)
    finished = jnp.zeros((B,), bool)
    if eos_token_id is not None:
        finished = next_tok == eos_token_id
    seq = jax.lax.dynamic_update_slice(seq, next_tok[:, None], (0, T0))

    def body(i, state):
        seq, cache, prev_tok, finished, key = state
        logits, cache = model.forward_with_cache(
            prev_tok[:, None], cache, index=T0 + i - 1)
        key, sub = jax.random.split(key)
        tok = pick(logits[:, -1], sub)
        if eos_token_id is not None:
            tok = jnp.where(finished, pad_token_id, tok)
            finished = finished | (tok == eos_token_id)
        seq = jax.lax.dynamic_update_slice(seq, tok[:, None], (0, T0 + i))
        return seq, cache, tok, finished, key

    if max_new_tokens > 1:
        seq, *_ = jax.lax.fori_loop(1, max_new_tokens, body,
                                    (seq, cache, next_tok, finished, key))
    return seq


def test_generate_while_matches_fori_reference(model):
    """The while_loop rewrite is output-identical to the old fixed-trip
    fori_loop — with an eos that fires early, and without one."""
    import jax

    rs = np.random.RandomState(11)
    prompt = rs.randint(0, VOCAB, (2, 5)).astype(np.int32)
    # greedy, eos chosen so one row finishes early
    base = np.asarray(generate(model, prompt, 8))
    eos = int(base[0, 5 + 2])
    got = np.asarray(generate(model, prompt, 8, eos_token_id=eos))
    want = np.asarray(_fori_reference(model, prompt, 8,
                                      eos_token_id=eos))
    np.testing.assert_array_equal(got, want)
    # sampled, no eos: full trip count, same key schedule
    key = jax.random.PRNGKey(3)
    got = np.asarray(generate(model, prompt, 6, temperature=0.7,
                              key=key))
    want = np.asarray(_fori_reference(model, prompt, 6, temperature=0.7,
                                      key=key))
    np.testing.assert_array_equal(got, want)


def test_generate_while_exits_early():
    """The loop really stops once every row finished: a callback-counting
    fake model sees ~2 forward calls, not max_new_tokens."""
    import jax
    import jax.numpy as jnp

    EOS, V = 3, 8
    calls = []

    class FakeModel:
        def init_cache(self, B, S, dtype=None):
            return (jnp.zeros((1, B, 1, S, 1), jnp.float32),) * 2

        def forward_with_cache(self, ids, cache, index):
            B, T = ids.shape

            def emit(ids_np):
                calls.append(1)
                logits = np.zeros((B, T, V), np.float32)
                logits[:, :, EOS] = 1.0           # always pick EOS
                return logits

            logits = jax.pure_callback(
                emit, jax.ShapeDtypeStruct((B, T, V), jnp.float32), ids)
            return logits, cache

    out = generate(FakeModel(), np.ones((2, 3), np.int32), 10,
                   eos_token_id=EOS)
    assert out.shape == (2, 13)
    # prefill picks EOS for every row -> finished before the loop; the
    # old fori_loop would have called forward 10 times regardless
    assert sum(calls) <= 2, f"loop did not exit early: {sum(calls)} calls"
    assert int(out[0, 3]) == EOS and int(out[0, 4]) == 0
