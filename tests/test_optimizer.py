"""Optimizer tests: convergence on a quadratic, reference formulas, clips,
schedules, loss scaling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import amp, nn
from paddle_tpu import optimizer as opt
from paddle_tpu.core.module import apply_updates
from paddle_tpu.optimizer import lr as lr_mod
from paddle_tpu.optimizer import transform as T


def quadratic_converges(optimizer, steps=120, tol=1e-2):
    params = {"w": jnp.asarray([3.0, -2.0, 1.5])}
    state = optimizer.init(params)

    @jax.jit
    def step(params, state):
        grads = jax.grad(lambda p: jnp.sum(p["w"] ** 2))(params)
        return optimizer.apply_gradients(params, grads, state)

    for _ in range(steps):
        params, state = step(params, state)
    return float(jnp.max(jnp.abs(params["w"]))) < tol


@pytest.mark.parametrize("factory,steps,tol", [
    (lambda: opt.SGD(0.1), 120, 1e-2),
    (lambda: opt.Momentum(0.05, momentum=0.9), 120, 1e-2),
    (lambda: opt.Adam(0.1), 120, 1e-2),
    (lambda: opt.AdamW(0.1, weight_decay=0.0), 120, 1e-2),
    (lambda: opt.Adamax(0.1), 120, 1e-2),
    (lambda: opt.Adagrad(0.5), 120, 1e-2),
    (lambda: opt.RMSProp(0.05), 120, 1e-2),
    # adadelta's eps floor makes it dither near the optimum: coarse tol
    (lambda: opt.Adadelta(2.0), 1500, 1e-1),
    (lambda: opt.Lamb(0.05, lamb_weight_decay=0.0), 300, 1e-2),
    # lars trust ratio is coeff*|w|/|g|: tiny by design, scale lr/coeff up
    (lambda: opt.LarsMomentum(1.0, lars_coeff=0.1, lars_weight_decay=0.0),
     300, 1e-2),
])
def test_optimizer_converges(factory, steps, tol):
    assert quadratic_converges(factory(), steps=steps, tol=tol)


def test_adam_matches_reference_formula():
    """First Adam step must equal -lr * g / (sqrt(g^2) + eps) with bias
    correction (reference adam_op.h update rule)."""
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    g = jnp.asarray([0.5, -1.0])
    p = {"w": jnp.asarray([1.0, 1.0])}
    o = opt.Adam(lr, beta1=b1, beta2=b2, epsilon=eps)
    state = o.init(p)
    updates, _ = o.update({"w": g}, state, p)
    mhat = g  # m/(1-b1) after 1 step = g
    vhat = g ** 2
    expect = -lr * mhat / (jnp.sqrt(vhat) + eps)
    np.testing.assert_allclose(updates["w"], expect, rtol=1e-5)


def test_adamw_decoupled_decay():
    wd, lr = 0.1, 0.01
    p = {"w": jnp.asarray([2.0])}
    o = opt.AdamW(lr, weight_decay=wd)
    state = o.init(p)
    g = {"w": jnp.asarray([0.0])}  # zero grad: update is pure decay
    p2, _ = o.apply_gradients(p, g, state)
    np.testing.assert_allclose(p2["w"], p["w"] * (1 - lr * wd), rtol=1e-6)


def test_clip_by_global_norm():
    clip = T.clip_by_global_norm(1.0)
    g = {"a": jnp.asarray([3.0, 4.0])}  # norm 5
    out, _ = clip.update(g, (), None)
    np.testing.assert_allclose(
        float(jnp.sqrt(jnp.sum(out["a"] ** 2))), 1.0, rtol=1e-5)
    # under the limit: untouched
    g2 = {"a": jnp.asarray([0.3, 0.4])}
    out2, _ = clip.update(g2, (), None)
    np.testing.assert_allclose(out2["a"], g2["a"], rtol=1e-6)


def test_optimizer_with_paddle_style_clip():
    o = opt.SGD(0.1, grad_clip=opt.ClipGradByGlobalNorm(0.5))
    assert quadratic_converges(o, steps=400)


def test_lr_schedules():
    warm = lr_mod.LinearWarmup(0.1, warmup_steps=10)
    assert float(warm(0)) == 0.0
    np.testing.assert_allclose(float(warm(5)), 0.05, rtol=1e-5)
    np.testing.assert_allclose(float(warm(20)), 0.1, rtol=1e-5)

    cos = lr_mod.CosineAnnealingDecay(1.0, t_max=100)
    np.testing.assert_allclose(float(cos(0)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(cos(100)), 0.0, atol=1e-6)

    wc = lr_mod.warmup_cosine(3e-4, 10, 110)
    np.testing.assert_allclose(float(wc(10)), 3e-4, rtol=1e-4)

    piece = lr_mod.PiecewiseDecay([10, 20], [1.0, 0.5, 0.1])
    assert float(piece(5)) == 1.0
    assert float(piece(15)) == 0.5
    assert float(piece(25)) == pytest.approx(0.1)


def test_schedule_traces_into_jit():
    sched = lr_mod.warmup_cosine(0.1, 5, 50)
    o = opt.Adam(sched)
    p = {"w": jnp.ones(3)}
    state = o.init(p)

    @jax.jit
    def step(p, s):
        g = jax.grad(lambda q: jnp.sum(q["w"] ** 2))(p)
        return o.apply_gradients(p, g, s)

    for _ in range(3):
        p, state = step(p, state)  # one compile, schedule inside


def test_grad_scaler_dynamics():
    scaler = amp.GradScaler(init_loss_scaling=8.0, incr_every_n_steps=2,
                            incr_ratio=2.0, decr_ratio=0.5)
    s = scaler.init()
    loss = jnp.asarray(2.0)
    assert float(scaler.scale(loss, s)) == 16.0
    grads = {"w": jnp.asarray([8.0])}
    un, finite = scaler.unscale(grads, s)
    assert bool(finite)
    np.testing.assert_allclose(un["w"], [1.0])
    # two good steps -> scale doubles
    s = scaler.update(s, jnp.asarray(False))
    s = scaler.update(s, jnp.asarray(False))
    assert float(s.loss_scaling) == 16.0
    # inf -> halves
    s = scaler.update(s, jnp.asarray(True))
    assert float(s.loss_scaling) == 8.0
    # non-finite grads detected
    bad = {"w": jnp.asarray([jnp.inf])}
    _, finite = scaler.unscale(bad, s)
    assert not bool(finite)


def test_apply_if_finite_skips_bad_update():
    inner = T.scale(1.0)
    tx = T.apply_if_finite(inner)
    s = tx.init({"w": jnp.ones(2)})
    good, s = tx.update({"w": jnp.ones(2)}, s, None)
    np.testing.assert_allclose(good["w"], [1.0, 1.0])
    bad, s = tx.update({"w": jnp.asarray([jnp.nan, 1.0])}, s, None)
    np.testing.assert_allclose(bad["w"], [0.0, 0.0])
    assert int(s.notfinite_count) == 1


def test_amp_cast_model_and_master_weights():
    m = nn.Linear(4, 4)
    low = amp.cast_model(m, jnp.bfloat16)
    assert low.weight.dtype == jnp.bfloat16
    back = amp.master_weights(low)
    assert back.weight.dtype == jnp.float32


def test_centered_rmsprop_differs_and_converges():
    o1 = opt.RMSProp(0.05, centered=True)
    o2 = opt.RMSProp(0.05, centered=False)
    p = {"w": jnp.asarray([1.0])}
    g = {"w": jnp.asarray([0.5])}
    u1, _ = o1.update(g, o1.init(p), p)
    u2, _ = o2.update(g, o2.init(p), p)
    assert abs(float(u1["w"][0]) - float(u2["w"][0])) > 1e-8
    assert quadratic_converges(opt.RMSProp(0.05, centered=True))


def test_scaler_decr_threshold():
    scaler = amp.GradScaler(init_loss_scaling=8.0, decr_every_n_nan_or_inf=2,
                            decr_ratio=0.5)
    s = scaler.init()
    s = scaler.update(s, jnp.asarray(True))   # 1 bad step: no change yet
    assert float(s.loss_scaling) == 8.0
    s = scaler.update(s, jnp.asarray(True))   # 2nd consecutive: halve
    assert float(s.loss_scaling) == 4.0
    s = scaler.update(s, jnp.asarray(False))  # resets bad counter
    s = scaler.update(s, jnp.asarray(True))
    assert float(s.loss_scaling) == 4.0


def test_clip_by_value_asymmetric():
    clip = opt.ClipGradByValue(max=1.0, min=0.0).transform()
    g = {"a": jnp.asarray([-2.0, 0.5, 3.0])}
    out, _ = clip.update(g, (), None)
    np.testing.assert_allclose(out["a"], [0.0, 0.5, 1.0])


def test_adam_l2_decay_enters_moments():
    # with L2 decay, a zero gradient still produces a decay-driven update
    # whose magnitude is shaped by adam's normalization (≈ lr at step 1)
    o = opt.Adam(0.01, weight_decay=0.1)
    p = {"w": jnp.asarray([2.0])}
    state = o.init(p)
    updates, _ = o.update({"w": jnp.asarray([0.0])}, state, p)
    # decayed grad = 0.2 -> normalized by sqrt(v̂)=0.2 -> update ≈ -lr
    np.testing.assert_allclose(updates["w"], [-0.01], rtol=1e-4)


def test_ftrl_matches_numpy_reference():
    """FTRL-proximal vs a direct numpy transcription of ftrl_op.h."""
    from paddle_tpu import optimizer as optim

    lr, l1, l2 = 0.05, 0.01, 0.1
    opt = optim.Ftrl(lr, l1=l1, l2=l2)
    p = jnp.asarray(np.array([0.5, -0.3, 0.1], np.float32))
    state = opt.init(p)
    rs = np.random.RandomState(0)

    p_np = np.array(p, np.float64)
    n_np = np.zeros(3)
    z_np = np.zeros(3)
    for _ in range(5):
        g = rs.randn(3).astype(np.float32)
        updates, state = opt.update(jnp.asarray(g), state, p)
        p = p + updates

        g64 = g.astype(np.float64)
        new_n = n_np + g64 * g64
        sigma = (np.sqrt(new_n) - np.sqrt(n_np)) / lr
        z_np = z_np + g64 - sigma * p_np
        n_np = new_n
        denom = np.sqrt(n_np) / lr + 2 * l2
        p_np = np.where(np.abs(z_np) > l1,
                        (l1 * np.sign(z_np) - z_np) / denom, 0.0)
    np.testing.assert_allclose(np.asarray(p), p_np, rtol=1e-4, atol=1e-6)


def test_dpsgd_clips_and_noises():
    from paddle_tpu import optimizer as optim

    opt = optim.Dpsgd(0.1, clip=1.0, batch_size=4, sigma=0.5, seed=1)
    p = jnp.zeros(1000)
    state = opt.init(p)
    g = jnp.full(1000, 100.0)  # huge grad: must be clipped to norm 1
    updates, state = opt.update(g, state, p)
    u = np.asarray(updates) / -0.1  # undo lr scale
    # clipped grad norm ~1 plus noise of std clip*sigma/bs = 0.125
    assert np.linalg.norm(u) < 1.0 + 0.125 * np.sqrt(1000) * 3
    # noise present: updates not all equal
    assert np.std(u) > 0.01
    # deterministic across same seed
    opt2 = optim.Dpsgd(0.1, clip=1.0, batch_size=4, sigma=0.5, seed=1)
    u2, _ = opt2.update(g, opt2.init(p), p)
    np.testing.assert_array_equal(np.asarray(updates), np.asarray(u2))


def test_ema_tracks_and_applies():
    from paddle_tpu import nn
    from paddle_tpu import optimizer as optim

    paddle_tpu.seed(0)
    model = nn.Linear(4, 2)
    ema = optim.ExponentialMovingAverage(0.9)
    st = ema.init(model)
    m2 = model.replace(weight=model.weight + 1.0)
    for _ in range(50):
        st = ema.update(st, m2)
    applied = ema.apply(st, m2)
    # after many updates the EMA converges to the new weights
    np.testing.assert_allclose(np.asarray(applied.weight),
                               np.asarray(m2.weight), atol=0.05)
    assert applied.weight.dtype == m2.weight.dtype


def test_reduce_on_plateau_logic():
    from paddle_tpu.optimizer.lr import ReduceOnPlateau

    s = ReduceOnPlateau(1.0, mode="min", factor=0.5, patience=2,
                        threshold=0.0)
    assert not s.step(10.0)
    assert not s.step(9.0)            # improving
    assert not s.step(9.5)            # bad 1
    assert not s.step(9.5)            # bad 2
    assert s.step(9.5)                # bad 3 > patience → reduce
    assert s.get_lr() == 0.5
    # min_lr floor
    s2 = ReduceOnPlateau(1e-4, factor=0.1, patience=0, min_lr=5e-5,
                         threshold=0.0)
    s2.step(1.0)
    assert s2.step(2.0)
    assert s2.get_lr() == 5e-5
    assert not s2.step(3.0)           # already at floor: no change


def test_ftrl_dpsgd_train_quadratic():
    """Both optimizers reduce a simple quadratic."""
    from paddle_tpu import optimizer as optim

    for opt in (optim.Ftrl(0.5), optim.Dpsgd(0.05, clip=5.0, sigma=0.1)):
        p = jnp.asarray(np.array([2.0, -3.0], np.float32))
        state = opt.init(p)
        for _ in range(60):
            g = 2 * p
            updates, state = opt.update(g, state, p)
            p = p + updates
        assert float(jnp.sum(p ** 2)) < 1.0, type(opt).__name__
