"""Serving at scale: cross-request dynamic batching + replica routing.

Server half (``paddle_tpu/serving/batcher.py`` behind
``io.InferenceServer``): coalescing, timeout flush, bucket-padding
correctness vs unbatched outputs, defaults-off identity. Client half
(``paddle_tpu/serving/router.py``): least-inflight pick, failover on a
replica kill, shed-driven rebalance, live endpoint add/remove.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.core import monitor
from paddle_tpu.core.flags import set_flags
from paddle_tpu.io import (
    InferenceClient, InferenceServer, Predictor, save_inference_model,
)
from paddle_tpu.serving import DynamicBatcher, RoutedClient

pytestmark = pytest.mark.serving


@pytest.fixture(scope="module")
def dyn_mlp(tmp_path_factory):
    """A dynamic-batch MLP artifact (symbolic leading dim)."""
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = str(tmp_path_factory.mktemp("srvb") / "mlp")
    save_inference_model(path, net, [np.zeros((2, 4), np.float32)],
                         dynamic_batch=True)
    return path


@pytest.fixture
def batching_flags():
    """Enable batching for a test; always restore the hard-off default.
    ``min_queue=0`` by default so the coalescing-semantics tests see
    every request enter the queue; the watermark tests opt back in."""
    def enable(batch_max=16, timeout_s=0.05, min_queue=0):
        set_flags({"serving_batch_max": batch_max,
                   "serving_batch_timeout_s": timeout_s,
                   "serving_batch_min_queue": min_queue})
    yield enable
    set_flags({"serving_batch_max": 0, "serving_batch_timeout_s": 0.005,
               "serving_batch_min_queue": 2})


def _concurrent(n, fn):
    gate = threading.Barrier(n)
    errs = []

    def run(i):
        try:
            gate.wait()
            fn(i)
        except Exception as e:  # pragma: no cover - failure reporting
            errs.append((i, e))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs, errs
    return errs


class _CountingPredictor:
    """Delegates to a real dynamic Predictor but counts run() calls."""

    supports_batching = True

    def __init__(self, path):
        self._pred = Predictor(path)
        self.calls = 0
        self.batch_sizes = []

    @property
    def input_specs(self):
        return self._pred.input_specs

    @property
    def output_specs(self):
        return self._pred.output_specs

    def run(self, *inputs):
        self.calls += 1
        self.batch_sizes.append(int(inputs[0].shape[0]))
        return self._pred.run(*inputs)


# ---------------------------------------------------------------------------
# dynamic-batch export
# ---------------------------------------------------------------------------

def test_dynamic_batch_export_any_batch_size(dyn_mlp):
    pred = Predictor(dyn_mlp)
    assert pred.supports_batching
    assert pred.input_specs[0]["shape"] == [None, 4]
    assert pred.output_specs[0]["shape"] == [None, 3]
    rs = np.random.RandomState(0)
    x7 = rs.randn(7, 4).astype(np.float32)
    y7 = np.asarray(pred.run(x7))
    assert y7.shape == (7, 3)
    # row-independent: per-row results match a per-row run
    for i in (0, 3, 6):
        np.testing.assert_allclose(
            np.asarray(pred.run(x7[i:i + 1]))[0], y7[i], rtol=1e-5,
            atol=1e-6)
    # trailing dims still validated
    with pytest.raises(ValueError, match="shape"):
        pred.run(np.zeros((2, 5), np.float32))


def test_static_export_unchanged(dyn_mlp, tmp_path):
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = str(tmp_path / "static")
    save_inference_model(path, net, [np.zeros((2, 4), np.float32)])
    pred = Predictor(path)
    assert not pred.supports_batching
    assert pred.input_specs[0]["shape"] == [2, 4]
    with pytest.raises(ValueError, match="shape"):
        pred.run(np.zeros((3, 4), np.float32))


# ---------------------------------------------------------------------------
# batcher
# ---------------------------------------------------------------------------

def test_batcher_coalesces_concurrent_requests(dyn_mlp, batching_flags):
    batching_flags(batch_max=16, timeout_s=0.05)
    counting = _CountingPredictor(dyn_mlp)
    srv = InferenceServer()
    srv.add_model("m", counting)
    srv.start()
    ref = Predictor(dyn_mlp)
    results = {}
    try:
        def worker(i):
            with InferenceClient(srv.endpoint) as c:
                x = np.full((1, 4), float(i), np.float32)
                results[i] = c.infer("m", x)[0]

        _concurrent(8, worker)
    finally:
        srv.stop()
    # 8 concurrent single-row requests ran as FEWER predictor calls...
    assert counting.calls < 8, counting.batch_sizes
    assert sum(counting.batch_sizes) >= 8   # padding only adds rows
    # ...and every caller got ITS rows back
    for i, y in results.items():
        np.testing.assert_allclose(
            y, np.asarray(ref.run(np.full((1, 4), float(i), np.float32))),
            rtol=1e-5, atol=1e-6)


def test_batch_timeout_flushes_partial_batch(dyn_mlp, batching_flags):
    batching_flags(batch_max=64, timeout_s=0.02)
    srv = InferenceServer({"m": dyn_mlp}).start()
    try:
        with InferenceClient(srv.endpoint) as c:
            t0 = time.perf_counter()
            (y,) = c.infer("m", np.ones((2, 4), np.float32))
            dt = time.perf_counter() - t0
        assert y.shape == (2, 3)
        # flushed by the window, not stuck waiting for 64 rows
        assert dt < 5.0
    finally:
        srv.stop()


def test_bucket_padding_correctness_vs_unbatched(dyn_mlp, batching_flags):
    """Mixed-size concurrent requests (1+2+3+5 = 11 rows -> padded
    bucket) return exactly what per-request unbatched runs return."""
    batching_flags(batch_max=16, timeout_s=0.05)
    monitor.reset_stats("serving/")
    srv = InferenceServer({"m": dyn_mlp}).start()
    ref = Predictor(dyn_mlp)
    rs = np.random.RandomState(1)
    rows = [1, 2, 3, 5]
    xs = {i: rs.randn(r, 4).astype(np.float32)
          for i, r in enumerate(rows)}
    results = {}
    try:
        def worker(i):
            with InferenceClient(srv.endpoint) as c:
                results[i] = c.infer("m", xs[i])[0]

        _concurrent(len(rows), worker)
    finally:
        srv.stop()
    for i, x in xs.items():
        assert results[i].shape == (rows[i], 3)
        np.testing.assert_allclose(results[i], np.asarray(ref.run(x)),
                                   rtol=1e-5, atol=1e-6)
    assert monitor.get_stat("serving/batches") >= 1
    assert monitor.get_stat("serving/batched_requests") == len(rows)


def test_batcher_bad_request_fails_alone(dyn_mlp, batching_flags):
    """A malformed request is rejected before enqueueing; a co-batched
    good request still succeeds."""
    batching_flags(batch_max=16, timeout_s=0.05)
    srv = InferenceServer({"m": dyn_mlp}).start()
    good = {}
    try:
        gate = threading.Barrier(2)
        bad_err = []

        def good_worker():
            with InferenceClient(srv.endpoint) as c:
                gate.wait()
                good["y"] = c.infer("m", np.ones((1, 4), np.float32))[0]

        def bad_worker():
            with InferenceClient(srv.endpoint) as c:
                gate.wait()
                try:
                    c.infer("m", np.ones((1, 5), np.float32))  # bad dim
                except RuntimeError as e:
                    bad_err.append(e)

        ts = [threading.Thread(target=good_worker),
              threading.Thread(target=bad_worker)]
        [t.start() for t in ts]
        [t.join() for t in ts]
    finally:
        srv.stop()
    assert good["y"].shape == (1, 3)
    assert bad_err and "shape" in str(bad_err[0])


def test_min_queue_bypasses_idle_traffic(dyn_mlp, batching_flags):
    """Below the load watermark a request skips the coalescing window
    entirely (the conc-1 regression fix): sequential requests with
    batching ON never form a batch, never wait, and return the same
    results."""
    batching_flags(batch_max=16, timeout_s=0.05, min_queue=2)
    monitor.reset_stats("serving/")
    srv = InferenceServer({"m": dyn_mlp}).start()
    ref = Predictor(dyn_mlp)
    rs = np.random.RandomState(3)
    try:
        with InferenceClient(srv.endpoint) as c:
            c.infer("m", np.zeros((1, 4), np.float32))   # compile warmup
            monitor.reset_stats("serving/")
            t0 = time.perf_counter()
            for _ in range(6):
                x = rs.randn(1, 4).astype(np.float32)
                np.testing.assert_allclose(
                    c.infer("m", x)[0], np.asarray(ref.run(x)),
                    rtol=1e-5, atol=1e-6)
            dt = time.perf_counter() - t0
    finally:
        srv.stop()
    assert monitor.get_stat("serving/batch_bypass") == 6
    assert monitor.get_stat("serving/batches") == 0
    # six requests, zero 50 ms windows paid (the coalescing path would
    # have cost >= 6 x 50 ms deterministically)
    assert dt < 6 * 0.05


def test_min_queue_keeps_burst_coalescing(dyn_mlp, batching_flags):
    """The watermark only exempts idle traffic: a concurrent burst still
    coalesces (at most the first arrival bypasses)."""
    batching_flags(batch_max=16, timeout_s=0.05, min_queue=2)
    monitor.reset_stats("serving/")
    counting = _CountingPredictor(dyn_mlp)
    srv = InferenceServer()
    srv.add_model("m", counting)
    srv.start()
    try:
        def worker(i):
            with InferenceClient(srv.endpoint) as c:
                c.infer("m", np.full((1, 4), float(i), np.float32))

        _concurrent(8, worker)
    finally:
        srv.stop()
    bypassed = monitor.get_stat("serving/batch_bypass")
    batched = monitor.get_stat("serving/batched_requests")
    assert bypassed + batched == 8
    assert monitor.get_stat("serving/batches") >= 1
    assert batched >= 2, (bypassed, batched)
    assert counting.calls < 8, counting.batch_sizes


def test_min_queue_zero_restores_unconditional_coalescing(
        dyn_mlp, batching_flags):
    batching_flags(batch_max=16, timeout_s=0.01, min_queue=0)
    monitor.reset_stats("serving/")
    srv = InferenceServer({"m": dyn_mlp}).start()
    try:
        with InferenceClient(srv.endpoint) as c:
            c.infer("m", np.ones((1, 4), np.float32))
    finally:
        srv.stop()
    assert monitor.get_stat("serving/batch_bypass") == 0
    assert monitor.get_stat("serving/batches") == 1   # solo flush


def test_batching_defaults_off_is_inert(dyn_mlp):
    """With FLAGS_serving_batch_max unset the batcher never engages,
    even for a dynamic-batch model under concurrency."""
    monitor.reset_stats("serving/")
    srv = InferenceServer({"m": dyn_mlp}).start()
    try:
        def worker(i):
            with InferenceClient(srv.endpoint) as c:
                c.infer("m", np.full((1, 4), float(i), np.float32))

        _concurrent(6, worker)
    finally:
        srv.stop()
    assert monitor.get_stat("serving/batches") == 0
    assert monitor.get_stat("serving/batched_requests") == 0


def test_fixed_shape_model_passes_through(dyn_mlp, batching_flags,
                                          tmp_path):
    """Batching on, but a fixed-shape artifact: requests take the
    ordinary path (no coalescing, correct results)."""
    batching_flags(batch_max=16, timeout_s=0.01)
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = str(tmp_path / "static")
    save_inference_model(path, net, [np.zeros((2, 4), np.float32)])
    monitor.reset_stats("serving/")
    srv = InferenceServer({"m": path}).start()
    try:
        def worker(i):
            with InferenceClient(srv.endpoint) as c:
                (y,) = c.infer("m", np.ones((2, 4), np.float32))
                assert y.shape == (2, 3)

        _concurrent(4, worker)
    finally:
        srv.stop()
    assert monitor.get_stat("serving/batches") == 0


def test_batcher_direct_api(dyn_mlp, batching_flags):
    """DynamicBatcher used directly (no wire): validation + solo run."""
    batching_flags(batch_max=8, timeout_s=0.001)
    b = DynamicBatcher()
    pred = Predictor(dyn_mlp)
    assert DynamicBatcher.can_batch(pred)
    outs = b.submit("m", pred, [np.ones((3, 4), np.float32)])
    assert outs[0].shape == (3, 3)
    with pytest.raises(ValueError, match="dtype"):
        b.submit("m", pred, [np.ones((3, 4), np.float64)])
    with pytest.raises(ValueError, match="shape"):
        b.submit("m", pred, [np.ones((3, 7), np.float32)])


# ---------------------------------------------------------------------------
# load_model validation (registration-time, not first-infer)
# ---------------------------------------------------------------------------

def test_load_model_validates_at_registration(dyn_mlp, tmp_path):
    srv = InferenceServer({"m": dyn_mlp}).start()
    try:
        with InferenceClient(srv.endpoint) as c:
            with pytest.raises(RuntimeError,
                               match="not an inference-model"):
                c.load_model("ghost", str(tmp_path / "nope"))
            # a directory that exists but holds garbage fails too
            bad = tmp_path / "garbage"
            bad.mkdir()
            (bad / "model.stablehlo").write_bytes(b"not a model")
            (bad / "meta.json").write_text("{}")
            with pytest.raises(RuntimeError, match="failed to load"):
                c.load_model("ghost", str(bad))
            assert "ghost" not in c.list_models()
            # server kept serving and valid loads still work
            c.load_model("m2", dyn_mlp)
            (y,) = c.infer("m2", np.ones((2, 4), np.float32))
            assert y.shape == (2, 3)
    finally:
        srv.stop()


def test_server_ctor_validates_path(tmp_path):
    with pytest.raises(ValueError, match="not an inference-model"):
        InferenceServer({"m": str(tmp_path / "missing")})


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

def test_router_least_inflight_and_kill_failover(dyn_mlp):
    """A replica killed mid-traffic: every request still completes (the
    failover path re-issues idempotent infers on the survivors) and the
    dead replica is marked down by the error, not just by probing."""
    servers = [InferenceServer({"m": dyn_mlp}).start() for _ in range(3)]
    monitor.reset_stats("serving/router/")
    # probing effectively off: the kill must be discovered by traffic
    rc = RoutedClient([s.endpoint for s in servers],
                      probe_interval_s=30.0, timeout=10.0)
    results = {}
    try:
        # stop() blocks ~0.5s in the accept-loop shutdown before it
        # severs live connections, so kill in the background and keep
        # traffic flowing past the sever
        stop_at = time.perf_counter() + 1.6
        killer = threading.Timer(0.1, servers[1].stop)
        killer.start()

        def worker(i):
            j = 0
            while time.perf_counter() < stop_at:
                x = np.full((1, 4), float(i * 100 + j), np.float32)
                results[(i, j)] = rc.infer("m", x)[0]
                j += 1
                time.sleep(0.005)

        _concurrent(4, worker)
        killer.join()
        assert len(results) >= 8            # traffic actually flowed
        ref = Predictor(dyn_mlp)
        for (i, j), y in results.items():   # zero lost/garbled requests
            x = np.full((1, 4), float(i * 100 + j), np.float32)
            np.testing.assert_allclose(y, np.asarray(ref.run(x)),
                                       rtol=1e-5, atol=1e-6)
        assert monitor.get_stat("serving/router/failovers") >= 1
        m = {r["endpoint"]: r["healthy"] for r in rc.members()}
        assert not m[servers[1].endpoint], m
        assert m[servers[0].endpoint] and m[servers[2].endpoint], m
    finally:
        rc.close()
        for s in servers:
            s.stop()


def test_router_shed_reroutes_without_marking_down(dyn_mlp):
    """A replica whose admission control sheds (inflight cap busy with a
    direct long request) reroutes to the other replica; the shed replica
    stays a member."""

    class _SlowPredictor:
        input_specs = output_specs = []
        supports_batching = False

        def run(self, x):
            time.sleep(0.4)
            return np.asarray(x)

    slow = InferenceServer({"m": dyn_mlp})
    slow.add_model("slow", _SlowPredictor())
    slow.start()
    fast = InferenceServer({"m": dyn_mlp}).start()
    monitor.reset_stats("serving/router/")
    set_flags({"wire_max_inflight": 1})
    # probing disabled: membership must not flap from the cap itself
    rc = RoutedClient([slow.endpoint, fast.endpoint],
                      probe_interval_s=0, timeout=10.0)
    try:
        # occupy the slow replica's single slot out-of-band
        occupier = InferenceClient(slow.endpoint, timeout=10.0, retries=0)
        t = threading.Thread(
            target=lambda: occupier.infer("slow",
                                          np.ones((4,), np.float32)))
        t.start()
        time.sleep(0.1)                     # slot taken
        # router's first pick is the slow replica (round-robin over an
        # all-zero-inflight tie includes it within two requests)
        for _ in range(2):
            (y,) = rc.infer("m", np.ones((1, 4), np.float32))
            assert y.shape == (1, 3)
        t.join()
        occupier.close()
        assert monitor.get_stat("serving/router/shed_rerouted") >= 1
        assert monitor.get_stat("serving/router/marked_down") == 0
        assert all(r["healthy"] for r in rc.members())
    finally:
        set_flags({"wire_max_inflight": 0})
        rc.close()
        slow.stop()
        fast.stop()


def test_router_endpoint_add_remove(dyn_mlp):
    s1 = InferenceServer({"m": dyn_mlp}).start()
    s2 = InferenceServer({"m": dyn_mlp}).start()
    rc = RoutedClient([s1.endpoint], probe_interval_s=0, timeout=10.0)
    x = np.ones((1, 4), np.float32)
    try:
        assert rc.infer("m", x)[0].shape == (1, 3)
        rc.add_endpoint(s2.endpoint)
        assert len(rc.endpoints()) == 2
        rc.remove_endpoint(s1.endpoint)
        assert rc.endpoints() == [s2.endpoint]
        s1.stop()                            # only s2 remains
        for _ in range(3):
            assert rc.infer("m", x)[0].shape == (1, 3)
    finally:
        rc.close()
        s2.stop()


def test_router_probe_recovers_replica(dyn_mlp):
    s1 = InferenceServer({"m": dyn_mlp}).start()
    port = s1.port
    rc = RoutedClient([s1.endpoint], probe_interval_s=0, timeout=5.0)
    x = np.ones((1, 4), np.float32)
    try:
        assert rc.infer("m", x)[0].shape == (1, 3)
        s1.stop()
        with pytest.raises((ConnectionError, OSError)):
            rc.infer("m", x)
        assert not rc.members()[0]["healthy"]
        # restart on the same port; an explicit probe round resurrects
        s1b = InferenceServer({"m": dyn_mlp}, port=port).start()
        rc.probe()
        assert rc.members()[0]["healthy"]
        assert rc.infer("m", x)[0].shape == (1, 3)
        s1b.stop()
    finally:
        rc.close()


def test_router_health_and_client_inflight(dyn_mlp):
    s1 = InferenceServer({"m": dyn_mlp}).start()
    rc = RoutedClient([s1.endpoint], probe_interval_s=0, timeout=5.0)
    try:
        h = rc.health()
        assert h[s1.endpoint]["status"] == "ok"
        # FrameClient-level inflight counters (the routing signal)
        c = InferenceClient(s1.endpoint)
        assert c.inflight == 0
        c.infer("m", np.ones((1, 4), np.float32))
        assert c.inflight == 0 and c.inflight_by_op() == {}
        c.close()
    finally:
        rc.close()
        s1.stop()
