"""DGC (deep gradient compression) tests: dense-parity at sparsity 0,
momentum-correction equivalence vs a Momentum-DP baseline, rampup
executable schedule, error feedback under real sparsity, composition
gates. (Reference: ``fluid/optimizer.py:1183`` DGCMomentumOptimizer +
``framework/details/sparse_all_reduce_op_handle.cc``; the reference's
own DGC tests compare against momentum training, ``test_dgc_op.py`` /
``test_dgc_optimizer.py`` style.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as optim
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.parallel import mesh as M


def make_batch(bs=8, seq=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, vocab, (bs, seq)).astype(np.int32)
    return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}


def fresh_model(cfg):
    paddle_tpu.seed(7)
    return LlamaForCausalLM(cfg)


def dgc_strategy(**kw):
    s = DistributedStrategy()
    s.dgc.enable = True
    for k, v in kw.items():
        setattr(s.dgc, k, v)
    return s


def run(strategy, optimizer_fn, n=4, cfg=None):
    cfg = cfg or LlamaConfig.tiny()
    batch = make_batch()
    mesh = M.mesh_from_strategy(DistributedStrategy())
    with M.MeshContext(mesh):
        model = fresh_model(cfg)
        step = dist.fleet.build_train_step(
            model, optimizer=optimizer_fn(), strategy=strategy, mesh=mesh)
        state = step.init_state(model)
        data = step.shard_batch(batch)
        out = []
        for i in range(n):
            state, m = step(state, data, jax.random.PRNGKey(i))
            out.append(dict(m, loss=float(m["loss"])))
    return out, state


def test_dgc_sparsity0_matches_dense_dp(devices8):
    """momentum=0 + sparsity=0 selects every coordinate each step: the
    sparse exchange degenerates to the dense mean-allreduce, so losses
    must match plain DP-SGD (the TestDistBase-style parity check)."""
    dp, _ = run(DistributedStrategy(), lambda: optim.SGD(1e-2))
    dgc, _ = run(dgc_strategy(momentum=0.0, sparsity=(0.0,)),
                 lambda: optim.SGD(1e-2))
    np.testing.assert_allclose([m["loss"] for m in dgc],
                               [m["loss"] for m in dp], rtol=2e-5)


def test_dgc_momentum_matches_momentum_dp(devices8):
    """DGC owns the momentum (the DGCMomentumOptimizer contract: pair
    with plain-SGD outer). In the dense phase each worker's corrected
    accumulator is averaged, and by linearity
    mean_w(m*u_w + g_w) = m*mean(u) + mean(g) — exactly the Momentum
    optimizer run on the averaged gradient. Compare against DP with the
    Momentum optimizer over the whole warmup."""
    dp, _ = run(DistributedStrategy(),
                lambda: optim.Momentum(1e-2, momentum=0.9), n=5)
    # rampup_begin_step=100: every step stays in the dense warmup phase
    dgc, _ = run(dgc_strategy(momentum=0.9, rampup_begin_step=100),
                 lambda: optim.SGD(1e-2), n=5)
    np.testing.assert_allclose([m["loss"] for m in dgc],
                               [m["loss"] for m in dp], rtol=2e-5)

    # sub-threshold leaves keep momentum through the SPARSE phase too:
    # an impossible threshold sends every leaf down the corrected dense
    # path even though compression is active
    dgc2, _ = run(dgc_strategy(momentum=0.9, sparsity=(0.9,),
                               dense_size_threshold=1 << 30),
                  lambda: optim.SGD(1e-2), n=5)
    np.testing.assert_allclose([m["loss"] for m in dgc2],
                               [m["loss"] for m in dp], rtol=2e-5)


def test_dgc_sparse_trains_and_ramps(devices8):
    """Real sparsity: dense warmup steps, then the ramp, then the final
    sparsity; loss decreases through compressed training and the
    dgc_sparsity metric exposes the executable schedule."""
    out, state = run(
        dgc_strategy(momentum=0.9, sparsity=(0.75, 0.9375, 0.99),
                     rampup_begin_step=2, rampup_step=3,
                     dense_size_threshold=64),
        lambda: optim.SGD(5e-2), n=8)
    sp = [round(float(m["dgc_sparsity"]), 4) for m in out]
    assert sp == [0.0, 0.0, 0.75, 0.9375, 0.99, 0.99, 0.99, 0.99], sp
    losses = [m["loss"] for m in out]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    # error-feedback residuals hold the unsent mass: nonzero after
    # compressed steps for at least one compressed leaf
    v_leaves = [np.asarray(l) for l in
                jax.tree_util.tree_leaves(state.merge_grads["v"])
                if l.size]
    assert any(np.abs(v).max() > 0 for v in v_leaves)


def test_dgc_error_feedback_delivers_all_coordinates(devices8):
    """With 99% sparsity every step sends only ~1% of coordinates; the
    error-feedback invariant is that NO gradient mass is lost — every
    coordinate of a dense-gradient leaf is either already delivered
    (parameter moved) or still held in the u/v accumulators."""
    cfg = LlamaConfig.tiny()
    out, state = run(
        dgc_strategy(momentum=0.0, sparsity=(0.99,),
                     dense_size_threshold=1 << 30),  # nothing compresses
        lambda: optim.SGD(1e-2), n=2, cfg=cfg)
    # the 1<<30 threshold makes EVERY leaf ride the dense path — so this
    # config must also exactly match dense DP (threshold gate works)
    dp, _ = run(DistributedStrategy(), lambda: optim.SGD(1e-2), n=2,
                cfg=cfg)
    np.testing.assert_allclose([m["loss"] for m in out],
                               [m["loss"] for m in dp], rtol=2e-5)

    out2, state2 = run(
        dgc_strategy(momentum=0.9, sparsity=(0.99,),
                     dense_size_threshold=64),
        lambda: optim.SGD(1e-2), n=30, cfg=cfg)
    losses = [m["loss"] for m in out2]
    assert all(np.isfinite(losses))
    # 99% of coordinates are withheld per step, but error feedback must
    # still deliver steady progress on a fixed batch
    assert losses[-1] < losses[0] - 0.3, (losses[0], losses[-1])

    # the invariant itself, on the dense-gradient mlp/attention weights
    # (embedding rows of absent tokens legitimately have zero mass):
    # delivered ∪ held-in-u ∪ held-in-v covers every coordinate
    init = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
            jax.tree_util.tree_flatten_with_path(fresh_model(cfg))[0]}
    final = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
             jax.tree_util.tree_flatten_with_path(state2.model)[0]}
    res_u = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
             jax.tree_util.tree_flatten_with_path(
                 state2.merge_grads["u"])[0]}
    res_v = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
             jax.tree_util.tree_flatten_with_path(
                 state2.merge_grads["v"])[0]}
    checked = 0
    for name, w0 in init.items():
        if not (".mlp." in name or ".attn.w" in name):
            continue
        if res_v[name].size == 0:  # not compressed (below threshold)
            continue
        delivered = final[name] != w0
        held = ((np.abs(res_u[name]).sum(axis=0) > 0)
                | (np.abs(res_v[name]).sum(axis=0) > 0))
        coverage = (delivered | held).mean()
        assert coverage > 0.999, (name, coverage)
        checked += 1
    assert checked >= 4, checked


def test_dgc_local_grad_clip_runs(devices8):
    out, _ = run(dgc_strategy(momentum=0.9, sparsity=(0.9,),
                              local_grad_clip=1.0),
                 lambda: optim.SGD(1e-2), n=3)
    assert all(np.isfinite(m["loss"]) for m in out)


def test_dgc_composition_gates(devices8):
    mesh = M.mesh_from_strategy(DistributedStrategy())
    model = fresh_model(LlamaConfig.tiny())

    s = dgc_strategy()
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    with pytest.raises(ValueError, match="data-parallel"):
        dist.fleet.build_train_step(model, optimizer=optim.SGD(1e-2),
                                    strategy=s, mesh=M.mesh_from_strategy(s))

    s = dgc_strategy()
    s.amp.enable = True
    with pytest.raises(ValueError, match="amp"):
        dist.fleet.build_train_step(model, optimizer=optim.SGD(1e-2),
                                    strategy=s, mesh=mesh)

    s = dgc_strategy()
    s.localsgd.enable = True
    with pytest.raises(ValueError, match="mutually exclusive"):
        dist.fleet.build_train_step(model, optimizer=optim.SGD(1e-2),
                                    strategy=s, mesh=mesh)


def test_dgc_config_json_roundtrip():
    s = dgc_strategy(momentum=0.7, sparsity=(0.75, 0.999),
                     rampup_begin_step=10, rampup_step=20)
    s2 = DistributedStrategy.from_json(s.to_json())
    assert s2.dgc.enable
    assert s2.dgc.momentum == 0.7
    assert tuple(s2.dgc.sparsity) == (0.75, 0.999)
    assert s2.dgc.rampup_begin_step == 10
    assert s2.dgc.rampup_step == 20
