"""Decode hot-loop overhaul: device-resident page tables
(``FLAGS_gen_device_pt``) and async double-buffered dispatch
(``FLAGS_gen_async_depth``).

The load-bearing contract is the same byte-identity the engine has
always promised, now under lookahead: dispatching step ``i+1`` before
step ``i``'s token readback must not change a single token of any
stream — greedy or sampled, paged or contiguous, device-resident table
or host upload — because the autoregressive chain feeds itself on
device and the host bookkeeping only ever runs against tokens that HAVE
been read back. Cancel/TTL/failover land at most ``depth`` steps late,
which is safe (post-EOS steps write pads to pages the dying generation
still owns) and must leave the pool exactly full.
"""

import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core.flags import flag
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.serving import GenerationEngine

pytestmark = [pytest.mark.gen, pytest.mark.hotloop]

VOCAB = 96
SAMPLE_KW = dict(temperature=0.8, top_k=7, top_p=0.9, seed=42)


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


def _wait(engine, pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred(engine.stats()):
            return True
        time.sleep(0.02)
    return False


def _sampled_ref(model, prompt, n):
    import jax
    return np.asarray(generate(
        model, prompt[None], n, temperature=SAMPLE_KW["temperature"],
        top_k=SAMPLE_KW["top_k"], top_p=SAMPLE_KW["top_p"],
        key=jax.random.PRNGKey(SAMPLE_KW["seed"])))[0, prompt.size:]


# -- byte identity across the whole flag grid -------------------------------

def test_byte_identity_grid_matches_solo_generate(model):
    """{paged, contiguous} x {greedy, sampled} x async_depth {0,1,2} x
    device_pt {off,on}: every engine config reproduces solo
    ``generate()`` byte-for-byte — lookahead and the device-resident
    table change WHERE work happens, never a token."""
    rs = np.random.RandomState(1)
    prompts = rs.randint(0, VOCAB, (4, 6)).astype(np.int32)
    greedy_ref = np.asarray(generate(model, prompts, 5))[:, 6:]
    s_prompt = rs.randint(0, VOCAB, (6,)).astype(np.int32)
    sampled_ref = _sampled_ref(model, s_prompt, 6)

    configs = [(paged, pt, depth)
               for paged in (False, True)
               for pt in ((False, True) if paged else (False,))
               for depth in (0, 1, 2)]
    for paged, pt, depth in configs:
        tag = f"paged={paged} device_pt={pt} depth={depth}"
        kw = dict(paged=paged, device_pt=pt, async_depth=depth)
        if paged:
            kw.update(page_tokens=8, pages=24)
        with GenerationEngine(model, slots=2, max_len=32, queue_max=8,
                              **kw) as eng:
            st = eng.stats()
            assert st["async_depth"] == depth and st["device_pt"] == (
                paged and pt), tag
            gids = [eng.start(p, 5) for p in prompts]
            for i, g in enumerate(gids):
                toks, err = _drain(eng, g)
                assert err is None, tag
                np.testing.assert_array_equal(
                    np.asarray(toks, np.int32), greedy_ref[i],
                    err_msg=tag)
            toks, err = _drain(eng, eng.start(s_prompt, 6, **SAMPLE_KW))
            assert err is None, tag
            np.testing.assert_array_equal(
                np.asarray(toks, np.int32), sampled_ref, err_msg=tag)
            # the trailing lagged step (pad writes only) drains on the
            # next idle loop pass
            assert _wait(eng, lambda s: s["pending_steps"] == 0), tag


# -- cancel / TTL under lookahead -------------------------------------------

def test_cancel_and_ttl_under_lookahead_return_pool_to_full(model):
    """Cancel and TTL-reap land at most ``depth`` steps late under
    async dispatch; the lagged steps write only pads into pages the
    dying generation still owns, every page comes back to the pool, and
    a dropped generation never delivers another token."""
    rs = np.random.RandomState(2)
    p_a = rs.randint(0, VOCAB, (5,)).astype(np.int32)
    p_b = rs.randint(0, VOCAB, (5,)).astype(np.int32)
    ref_b = np.asarray(generate(model, p_b[None], 8))[0, 5:]
    with GenerationEngine(model, slots=2, max_len=32, queue_max=4,
                          paged=True, page_tokens=8, pages=12,
                          prefix_cache=False, device_pt=True,
                          async_depth=2) as eng:
        full = eng.stats()["pages_free"]
        eng.step_wait_s = 0.02        # pace so "mid-flight" exists
        try:
            gid_a = eng.start(p_a, 20)
            gid_b = eng.start(p_b, 8)
            while len(eng.poll(gid_a, wait_s=0.5)["tokens"]) < 2:
                pass
            assert eng.cancel(gid_a)
            toks_b, err_b = _drain(eng, gid_b)
        finally:
            eng.step_wait_s = 0.0
        assert err_b is None
        np.testing.assert_array_equal(np.asarray(toks_b, np.int32), ref_b)
        assert gid_a not in eng._gens           # no stale delivery
        assert _wait(eng, lambda s: s["active"] == 0
                     and s["pages_free"] == full), eng.stats()

        # TTL reap mid-flight under the same lookahead
        eng._ttl_s = 0.3
        eng.step_wait_s = 0.05
        try:
            gid = eng.start(p_a, 25)
            assert _wait(eng, lambda s: s["active"] == 1)
            assert _wait(eng, lambda s: s["active"] == 0
                         and s["generations"] == 0, timeout=3.0)
        finally:
            eng._ttl_s = 10.0
            eng.step_wait_s = 0.0
        with pytest.raises(KeyError):
            eng.poll(gid)
        assert _wait(eng, lambda s: s["pages_free"] == full), eng.stats()


# -- failover resume from a lagged stream -----------------------------------

def test_failover_resume_from_lagged_async_stream(model):
    """A sampled stream served by an async_depth=2 engine dies
    mid-flight (cancel stands in for SIGKILL); the delivered prefix —
    which by construction lags device progress by up to ``depth``
    steps — resumes on a plain synchronous engine via prompt-replay +
    ``rng_skip`` and lands on the exact solo-generate tail."""
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, VOCAB, (6,)).astype(np.int32)
    ref = _sampled_ref(model, prompt, 8)
    with GenerationEngine(model, slots=2, max_len=32, paged=True,
                          page_tokens=8, pages=16, device_pt=True,
                          async_depth=2) as eng:
        eng.step_wait_s = 0.02
        try:
            gid = eng.start(prompt, 8, **SAMPLE_KW)
            while len(eng.poll(gid, wait_s=0.5)["tokens"]) < 3:
                pass
            delivered = eng.poll(gid)["tokens"]
            eng.cancel(gid)
        finally:
            eng.step_wait_s = 0.0
    k = len(delivered)
    assert 3 <= k <= 8
    np.testing.assert_array_equal(np.asarray(delivered, np.int32),
                                  ref[:k])
    with GenerationEngine(model, slots=2, max_len=32) as survivor:
        tail, err = _drain(survivor, survivor.start(
            np.concatenate([prompt, np.asarray(delivered, np.int32)]),
            8 - k, rng_skip=k, **SAMPLE_KW))
    assert err is None
    np.testing.assert_array_equal(np.asarray(tail, np.int32), ref[k:])


# -- goodput accounting at the new readback site ----------------------------

def test_goodput_host_gather_measured_under_async(model):
    """With lookahead on, the blocking ``np.asarray`` moves from the
    dispatch site into ``_finish_step`` — the meter must still see it:
    host_gather > 0 and the bucket fractions still sum to 1.0."""
    rs = np.random.RandomState(4)
    with GenerationEngine(model, slots=2, max_len=32, ledger=True,
                          async_depth=1) as eng:
        toks, err = _drain(eng, eng.start(
            rs.randint(0, VOCAB, (5,)).astype(np.int32), 8))
        assert err is None and len(toks) == 8
        gp = eng.stats()["goodput"]
    assert gp["buckets"]["host_gather"] > 0.0
    assert gp["buckets"]["decode"] > 0.0
    assert sum(gp["fractions"].values()) == pytest.approx(1.0)


# -- hard-off defaults ------------------------------------------------------

def test_defaults_off_no_hot_path_flag_reads(model, monkeypatch):
    """gen_device_pt/gen_async_depth default off, the default engine
    runs the synchronous loop with the host page table (stats prove
    it), and neither flag is read on the serve hot path — construction
    only."""
    assert flag("gen_device_pt") is False
    assert flag("gen_async_depth") == 0
    import paddle_tpu.serving.engine as engine_mod

    reads: list[str] = []
    real_flag = engine_mod.flag

    def spy(name):
        reads.append(name)
        return real_flag(name)

    monkeypatch.setattr(engine_mod, "flag", spy)
    rs = np.random.RandomState(5)
    with GenerationEngine(model, slots=2, max_len=32, paged=True,
                          page_tokens=8) as eng:
        assert "gen_device_pt" in reads and "gen_async_depth" in reads
        st = eng.stats()
        assert st["device_pt"] is False and st["async_depth"] == 0
        assert st["pending_steps"] == 0
        assert eng._pt_dev is None
        reads.clear()
        toks, err = _drain(eng, eng.start(
            rs.randint(0, VOCAB, (5,)).astype(np.int32), 6))
        assert err is None and len(toks) == 6
        assert not [r for r in reads
                    if r in ("gen_device_pt", "gen_async_depth")]
