"""FD gradient sweeps over the conv / pool / rnn / interpolate surface.

The reference FD-checks essentially every op via OpTest.check_grad
(``python/paddle/fluid/tests/unittests/op_test.py:1324``); this file
closes the highest-risk families that previously had no FD case. All
shapes are tiny (FD is O(n) evaluations) and run in scoped x64 via
``op_test.check_grad``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from op_test import check_grad


def _r(*shape, seed=0, scale=1.0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float64) * scale


# ---------------------------------------------------------------------------
# Convolutions (reference operators/conv_op.*, conv_transpose_op.*)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stride,padding,dilation", [
    (1, 0, 1), (2, 1, 1), (1, 1, 2)])
def test_conv2d_grads(stride, padding, dilation):
    x, w, b = _r(1, 2, 5, 5), _r(3, 2, 3, 3, seed=1), _r(3, seed=2)
    check_grad(lambda x, w, b: F.conv2d(x, w, b, stride=stride,
                                        padding=padding, dilation=dilation),
               [x, w, b], wrt=(0, 1, 2))


def test_conv2d_grouped_grads():
    x, w = _r(1, 4, 4, 4), _r(4, 2, 3, 3, seed=1)
    check_grad(lambda x, w: F.conv2d(x, w, padding=1, groups=2),
               [x, w], wrt=(0, 1))


def test_conv1d_grads():
    x, w, b = _r(2, 2, 6), _r(3, 2, 3, seed=1), _r(3, seed=2)
    check_grad(lambda x, w, b: F.conv1d(x, w, b, stride=2, padding=1),
               [x, w, b], wrt=(0, 1, 2))


def test_conv3d_grads():
    x, w = _r(1, 2, 3, 4, 4), _r(2, 2, 2, 2, 2, seed=1)
    check_grad(lambda x, w: F.conv3d(x, w, padding=1), [x, w], wrt=(0, 1))


@pytest.mark.parametrize("stride,padding", [(1, 0), (2, 1)])
def test_conv2d_transpose_grads(stride, padding):
    x, w = _r(1, 3, 4, 4), _r(3, 2, 3, 3, seed=1)  # weight [in, out, kh, kw]
    check_grad(lambda x, w: F.conv2d_transpose(x, w, stride=stride,
                                               padding=padding),
               [x, w], wrt=(0, 1))


def test_conv1d_transpose_grads():
    x, w = _r(1, 2, 5), _r(2, 3, 3, seed=1)
    check_grad(lambda x, w: F.conv1d_transpose(x, w, stride=2, padding=1),
               [x, w], wrt=(0, 1))


def test_conv3d_transpose_grads():
    x, w = _r(1, 2, 2, 3, 3), _r(2, 2, 2, 2, 2, seed=1)
    check_grad(lambda x, w: F.conv3d_transpose(x, w, stride=1),
               [x, w], wrt=(0, 1))


# ---------------------------------------------------------------------------
# Pooling (reference operators/pool_op.*). Max pools get a random input
# with distinct values so the argmax is FD-stable.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pool,shape,kw", [
    (F.max_pool1d, (1, 2, 6), dict(kernel_size=2)),
    (F.avg_pool1d, (1, 2, 6), dict(kernel_size=2)),
    (F.max_pool2d, (1, 2, 4, 4), dict(kernel_size=2)),
    (F.avg_pool2d, (1, 2, 4, 4), dict(kernel_size=2)),
    (F.avg_pool2d, (1, 2, 4, 4), dict(kernel_size=3, stride=1, padding=1)),
    (F.avg_pool2d, (1, 2, 4, 4), dict(kernel_size=3, stride=1, padding=1,
                                      exclusive=False)),
    (F.max_pool3d, (1, 1, 4, 4, 4), dict(kernel_size=2)),
    (F.avg_pool3d, (1, 1, 4, 4, 4), dict(kernel_size=2)),
])
def test_pool_grads(pool, shape, kw):
    x = _r(*shape) + np.arange(np.prod(shape)).reshape(shape) * 1e-3
    check_grad(lambda x: pool(x, **kw), [x])


@pytest.mark.parametrize("pool,shape,out", [
    (F.adaptive_avg_pool1d, (1, 2, 6), 3),
    (F.adaptive_avg_pool2d, (1, 2, 6, 4), (3, 2)),
    (F.adaptive_avg_pool3d, (1, 1, 4, 4, 4), 2),
    (F.adaptive_max_pool1d, (1, 2, 6), 3),
    (F.adaptive_max_pool2d, (1, 2, 6, 4), (3, 2)),
    (F.adaptive_max_pool3d, (1, 1, 4, 4, 4), 2),
])
def test_adaptive_pool_grads(pool, shape, out):
    x = _r(*shape) + np.arange(np.prod(shape)).reshape(shape) * 1e-3
    check_grad(lambda x: pool(x, out), [x])


# ---------------------------------------------------------------------------
# Interpolate (reference operators/interpolate_op.*): bilinear/bicubic are
# linear in the input, nearest routes gradients to source pixels.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["nearest", "bilinear", "bicubic"])
@pytest.mark.parametrize("size", [(6, 8), (2, 3)])
def test_interpolate_grads(mode, size):
    x = _r(1, 2, 4, 4)
    check_grad(lambda x: F.interpolate(x, size=size, mode=mode), [x])


def test_upsample_scale_factor_grad():
    x = _r(1, 2, 3, 3)
    check_grad(lambda x: F.interpolate(x, scale_factor=2, mode="bilinear"),
               [x])


# ---------------------------------------------------------------------------
# RNN cells (reference operators/math/lstm_compute.*, gru_compute.*):
# gradients w.r.t. input and carried state through the gate math. Weight
# gradients are matmul gradients (covered by the linear FD cases); the
# cell-specific risk is the gate arithmetic, which x/h grads exercise
# end-to-end.
# ---------------------------------------------------------------------------

def _cell(cls, in_size=3, hidden=4):
    paddle_tpu.seed(5)
    return cls(in_size, hidden)


def test_simple_rnn_cell_grads():
    cell = _cell(nn.SimpleRNNCell)
    x, h = _r(2, 3), _r(2, 4, seed=1)
    check_grad(lambda x, h: cell(x, h)[0], [x, h], wrt=(0, 1))


def test_lstm_cell_grads():
    cell = _cell(nn.LSTMCell)
    x, h, c = _r(2, 3), _r(2, 4, seed=1), _r(2, 4, seed=2)
    check_grad(lambda x, h, c: cell(x, (h, c))[0], [x, h, c], wrt=(0, 1, 2))
    # cell state path (additive memory) separately
    check_grad(lambda c: cell(jnp.asarray(x), (jnp.asarray(h), c))[1][1], [c])


def test_gru_cell_grads():
    cell = _cell(nn.GRUCell)
    x, h = _r(2, 3), _r(2, 4, seed=1)
    check_grad(lambda x, h: cell(x, h)[0], [x, h], wrt=(0, 1))


def test_lstm_layer_over_time_grads():
    """Full LSTM over a short sequence: BPTT through the lax.scan."""
    paddle_tpu.seed(6)
    lstm = nn.LSTM(3, 4, num_layers=1)
    x = _r(2, 3, 3)  # [B, T, C]
    check_grad(lambda x: lstm(x)[0], [x], rtol=1e-2)


def test_gru_layer_over_time_grads():
    paddle_tpu.seed(7)
    gru = nn.GRU(3, 4, num_layers=1)
    x = _r(2, 3, 3)
    check_grad(lambda x: gru(x)[0], [x], rtol=1e-2)


# ---------------------------------------------------------------------------
# Cells under weight perturbation: one FD case where the *parameters* are
# the differentiated leaves, via functional substitution into the module.
# ---------------------------------------------------------------------------

def test_lstm_cell_weight_grads():
    cell = _cell(nn.LSTMCell)
    x, h, c = _r(2, 3), _r(2, 4, seed=1), _r(2, 4, seed=2)

    def fn(wih, whh, bias):
        gates = jnp.asarray(x) @ wih + jnp.asarray(h) @ whh + bias
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c_new = F.sigmoid(f) * jnp.asarray(c) + F.sigmoid(i) * jnp.tanh(g)
        return F.sigmoid(o) * jnp.tanh(c_new)

    wih = np.asarray(cell.weight_ih, np.float64)
    whh = np.asarray(cell.weight_hh, np.float64)
    bias = np.asarray(cell.bias, np.float64) + _r(16, seed=3) * 0.1
    # the substituted math must match the module bit-for-bit first
    got = cell(jnp.asarray(x, jnp.float32), (jnp.asarray(h, jnp.float32),
                                             jnp.asarray(c, jnp.float32)))[0]
    want = fn(jnp.asarray(wih, jnp.float32), jnp.asarray(whh, jnp.float32),
              jnp.asarray(cell.bias))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5,
                               atol=2e-5)
    check_grad(fn, [wih, whh, bias], wrt=(0, 1, 2))
