"""paddle.device + paddle.batch/reader surface parity."""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import device
from paddle_tpu.data import batch, chain, shuffle


def test_device_queries():
    assert device.device_count() >= 1
    d = device.get_device()
    platform, idx = d.rsplit(":", 1)
    assert platform in ("cpu", "tpu") and idx.isdigit()
    assert not device.is_compiled_with_cuda()
    assert not device.is_compiled_with_xpu()
    assert all(":" in s for s in device.get_all_devices())


def test_set_device_roundtrip():
    platform = device.get_device().rsplit(":", 1)[0]
    dev = device.set_device(f"{platform}:0")
    assert dev.id == 0
    assert device.get_device() == f"{platform}:0"
    with pytest.raises(ValueError, match="TPU-native"):
        device.set_device("gpu:0")
    with pytest.raises(ValueError, match="device"):
        device.set_device(f"{platform}:999")
    with pytest.raises(ValueError, match="device"):
        device.set_device(f"{platform}:-1")    # negative index rejected
    with pytest.raises(ValueError, match="backend not available"):
        device.set_device("xpu")


def test_batch_reader():
    r = batch(lambda: iter(range(10)), 4)
    assert [list(b) for b in r()] == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    r2 = batch(lambda: iter(range(10)), 4, drop_last=True)
    assert [list(b) for b in r2()] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert paddle_tpu.batch is batch
    with pytest.raises(ValueError, match="batch_size"):
        batch(lambda: iter(()), 0)


def test_shuffle_and_chain_readers():
    base = lambda: iter(range(20))
    out = list(shuffle(base, buf_size=8, seed=3)())
    assert sorted(out) == list(range(20)) and out != list(range(20))
    # deterministic under the same seed
    assert out == list(shuffle(base, buf_size=8, seed=3)())
    both = list(chain(lambda: iter([1, 2]), lambda: iter([3]))())
    assert both == [1, 2, 3]
    with pytest.raises(ValueError, match="buf_size"):
        shuffle(base, buf_size=0)


def test_run_check_and_deprecated():
    from paddle_tpu import utils

    assert utils.run_check(verbose=False)

    @utils.deprecated(since="0.3", update_to="new_fn", reason="renamed")
    def old_fn(x):
        return x + 1

    with pytest.warns(DeprecationWarning, match="old_fn.*renamed.*new_fn"):
        assert old_fn(1) == 2
    assert "[deprecated]" in old_fn.__doc__
