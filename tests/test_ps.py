"""Parameter-server stack tests: native table math, TCP service parity,
sync/async/geo communicator semantics, and an end-to-end sparse
recommender model trained through the jitted TPU step.

Reference test analogues: ``operators/distributed/communicator_test.cc``,
``tests/unittests/test_dist_base.py`` PS modes, and the sparse-embedding
workloads (``parallel_dygraph_sparse_embedding.py``).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.ps import (
    Communicator, InProcClient, NativeSparseTable, ParameterServer,
    PSClient, SparseEmbeddingHelper,
)


# ---------------------------------------------------------------------------
# native table
# ---------------------------------------------------------------------------

def test_table_deterministic_init_and_bounds():
    t1 = NativeSparseTable(8, seed=7, init_scale=0.25)
    t2 = NativeSparseTable(8, seed=7, init_scale=0.25)
    ids = np.array([1, 999999999, -5, 0])
    np.testing.assert_array_equal(t1.pull(ids), t2.pull(ids))
    assert (np.abs(t1.pull(ids)) <= 0.25).all()
    t3 = NativeSparseTable(8, seed=8, init_scale=0.25)
    assert not np.allclose(t3.pull(ids), t1.pull(ids))


def test_table_sgd_update_merges_duplicates():
    t = NativeSparseTable(4, optimizer="sgd", lr=0.5, seed=0)
    ids = np.array([3, 3, 9])
    before = t.pull(np.array([3, 9]))
    g = np.array([[1, 0, 0, 0], [0, 1, 0, 0], [1, 1, 1, 1]], np.float32)
    t.push_grad(ids, g)
    after = t.pull(np.array([3, 9]))
    np.testing.assert_allclose(after[0], before[0] - 0.5 * np.array(
        [1, 1, 0, 0], np.float32), rtol=1e-6)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * np.ones(4),
                               rtol=1e-6)


def test_table_adagrad_matches_numpy():
    t = NativeSparseTable(3, optimizer="adagrad", lr=0.1, seed=1)
    ids = np.array([42])
    p = t.pull(ids)[0].astype(np.float64)
    G = np.zeros(3)
    rs = np.random.RandomState(0)
    for _ in range(5):
        g = rs.randn(1, 3).astype(np.float32)
        t.push_grad(ids, g)
        G += g[0].astype(np.float64) ** 2
        p -= 0.1 * g[0] / (np.sqrt(G) + 1e-6)
    np.testing.assert_allclose(t.pull(ids)[0], p, rtol=1e-5)


def test_table_adam_matches_numpy():
    t = NativeSparseTable(3, optimizer="adam", lr=0.01, seed=1)
    ids = np.array([7])
    p = t.pull(ids)[0].astype(np.float64)
    m = np.zeros(3)
    v = np.zeros(3)
    rs = np.random.RandomState(3)
    for step in range(1, 6):
        g = rs.randn(1, 3).astype(np.float32)
        t.push_grad(ids, g)
        m = 0.9 * m + 0.1 * g[0]
        v = 0.999 * v + 0.001 * g[0] ** 2
        mhat = m / (1 - 0.9 ** step)
        vhat = v / (1 - 0.999 ** step)
        p -= 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(t.pull(ids)[0], p, rtol=1e-4, atol=1e-6)


def test_table_save_load_roundtrip(tmp_path):
    t = NativeSparseTable(5, optimizer="adagrad", lr=0.1, seed=2)
    ids = np.arange(100)
    t.push_grad(ids, np.ones((100, 5), np.float32))
    t.save(str(tmp_path / "tbl.bin"))
    t2 = NativeSparseTable(5, optimizer="adagrad", lr=0.1, seed=2)
    t2.load(str(tmp_path / "tbl.bin"))
    assert len(t2) == 100
    np.testing.assert_array_equal(t2.pull(ids), t.pull(ids))
    # optimizer slots restored too: next identical update stays identical
    t.push_grad(ids[:1], np.ones((1, 5), np.float32))
    t2.push_grad(ids[:1], np.ones((1, 5), np.float32))
    np.testing.assert_array_equal(t2.pull(ids[:1]), t.pull(ids[:1]))


# ---------------------------------------------------------------------------
# TCP service
# ---------------------------------------------------------------------------

def test_tcp_server_matches_inproc():
    server = ParameterServer().start()
    try:
        tcp = PSClient(server.endpoint)
        ref = InProcClient()
        for c in (tcp, ref):
            c.create_table("emb", 6, optimizer="sgd", lr=0.2, seed=5)
        ids = np.array([10, 20, 30, 10])
        np.testing.assert_array_equal(tcp.pull("emb", ids),
                                      ref.pull("emb", ids))
        g = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        tcp.push_grad("emb", ids, g)
        ref.push_grad("emb", ids, g)
        np.testing.assert_allclose(tcp.pull("emb", ids),
                                   ref.pull("emb", ids), rtol=1e-6)
        assert tcp.size("emb") == 3
        np.testing.assert_array_equal(tcp.keys("emb"),
                                      np.array([10, 20, 30]))
        tcp.close()
    finally:
        server.stop()


def test_tcp_multi_server_sharding():
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    try:
        c = PSClient([s1.endpoint, s2.endpoint])
        c.create_table("emb", 4, optimizer="sgd", lr=0.5, seed=9)
        ref = InProcClient()
        ref.create_table("emb", 4, optimizer="sgd", lr=0.5, seed=9)
        ids = np.arange(1, 21)
        np.testing.assert_array_equal(c.pull("emb", ids),
                                      ref.pull("emb", ids))
        g = np.random.RandomState(1).randn(20, 4).astype(np.float32)
        c.push_grad("emb", ids, g)
        ref.push_grad("emb", ids, g)
        np.testing.assert_allclose(c.pull("emb", ids), ref.pull("emb", ids),
                                   rtol=1e-6)
        assert c.size("emb") == 20
        c.close()
    finally:
        s1.stop()
        s2.stop()


def test_server_error_reporting():
    server = ParameterServer().start()
    try:
        c = PSClient(server.endpoint)
        with pytest.raises(RuntimeError, match="no table"):
            c.pull("nope", np.array([1]))
        c.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# communicator modes
# ---------------------------------------------------------------------------

def _drive(comm, steps=6):
    losses = []
    ids = np.array([1, 2, 3])
    target = np.full((3, 4), 0.5, np.float32)
    for _ in range(steps):
        rows = comm.pull("emb", ids)
        grad = 2 * (rows - target)       # d/drow ||row - t||^2
        losses.append(float(((rows - target) ** 2).sum()))
        comm.push_grad("emb", ids, grad)
    comm.flush()
    return losses


def test_communicator_sync_converges():
    client = InProcClient()
    comm = Communicator(client, "sync")
    comm.create_table("emb", 4, optimizer="sgd", lr=0.1, seed=3)
    losses = _drive(comm)
    assert losses[-1] < losses[0] * 0.2


def test_communicator_async_applies_eventually():
    """Async pushes land via the background sender: the post-flush state
    must reflect the training (loss measured during the loop may race —
    Hogwild staleness is the contract, not per-step freshness)."""
    client = InProcClient()
    comm = Communicator(client, "async")
    # lr small enough that even fully-stale gradient application (all 10
    # pulls racing ahead of the sender) still moves monotonically toward
    # the target instead of overshooting
    comm.create_table("emb", 4, optimizer="sgd", lr=0.02, seed=3)
    losses = _drive(comm, steps=10)
    comm.stop()
    ids = np.array([1, 2, 3])
    target = np.full((3, 4), 0.5, np.float32)
    final = float(((comm.pull("emb", ids) - target) ** 2).sum())
    assert final < losses[0] * 0.5, (final, losses[0])


def test_heartbeat_detects_dead_worker():
    """A worker that stops beating is flagged lost within the heartbeat
    window (heart_beat_monitor.cc:LostWorkerMonitor); live and COMPLETED
    workers are never flagged, and a returning beat resurrects."""
    import time

    lost_events = []
    server = ParameterServer(heartbeat_interval=0.6,
                             on_lost=lost_events.append).start()
    try:
        c = PSClient(server.endpoint)
        c.heartbeat(0)            # worker 0: will keep beating
        c.heartbeat(1)            # worker 1: dies after registration
        c.heartbeat(2)            # worker 2: completes cleanly
        c.heartbeat(2, status="completed")
        deadline = time.time() + 5
        while time.time() < deadline and 1 not in c.lost_workers():
            c.heartbeat(0)
            time.sleep(0.1)
        lost = c.lost_workers()
        assert 1 in lost, lost
        assert 0 not in lost and 2 not in lost, lost
        assert lost_events == [1]
        c.heartbeat(1)            # worker 1 comes back
        assert 1 not in c.lost_workers()
    finally:
        server.stop()


def test_communicator_background_heartbeat():
    """An async communicator with heartbeat_secs beats without any push
    traffic; after stop() the worker is COMPLETED (exempt from staleness),
    while a silently-killed worker is flagged."""
    import time

    server = ParameterServer(heartbeat_interval=0.6).start()
    try:
        live = Communicator(PSClient(server.endpoint), "async",
                            worker_id=7, heartbeat_secs=0.15)
        dead = Communicator(PSClient(server.endpoint), "async",
                            worker_id=8, heartbeat_secs=0.15)
        probe = PSClient(server.endpoint)
        # simulate a crash: stop the beat thread without the completed beat
        dead._hb_stop.set()
        dead._hb_thread.join()
        deadline = time.time() + 5
        while time.time() < deadline and 8 not in probe.lost_workers():
            time.sleep(0.1)
        assert 8 in probe.lost_workers()
        assert 7 not in probe.lost_workers()
        live.stop()   # clean shutdown -> completed
        time.sleep(1.0)
        status = server.monitor.status()
        assert status["workers"]["7"] == "completed"
        assert 7 not in status["lost"]
    finally:
        server.stop()


def test_communicator_geo_delta_sync():
    """Two geo workers on disjoint ids: local training + delta push must
    land both workers' progress on the server (geo-SGD semantics)."""
    server_tables = InProcClient()
    w1 = Communicator(server_tables, "geo", geo_k=4)
    w1.create_table("emb", 4, optimizer="sgd", lr=0.1, seed=3)
    w2 = Communicator(server_tables, "geo", geo_k=4)
    w2._specs["emb"] = w1._specs["emb"]
    w2._local["emb"] = NativeSparseTable(**w1._specs["emb"])
    w2._snapshot["emb"] = {}
    w2._touched["emb"] = set()

    ids1, ids2 = np.array([1, 2]), np.array([10, 20])
    target = np.zeros((2, 4), np.float32)
    for _ in range(8):
        for w, ids in ((w1, ids1), (w2, ids2)):
            rows = w.pull("emb", ids)
            w.push_grad("emb", ids, 2 * (rows - target))
    w1.flush()
    w2.flush()
    # server rows moved toward 0 for BOTH workers' ids
    init = NativeSparseTable(4, optimizer="sgd", lr=0.1, seed=3)
    for ids in (ids1, ids2):
        now = server_tables.pull("emb", ids)
        before = init.pull(ids)
        assert (np.abs(now) < np.abs(before)).mean() > 0.9, (now, before)


# ---------------------------------------------------------------------------
# end-to-end: sparse recommender through the jitted TPU step
# ---------------------------------------------------------------------------

def test_sparse_embedding_model_trains():
    """CTR-style toy: sparse id -> embedding (PS table) -> dense MLP (jit).
    The dense params train on-device; embedding rows train server-side
    via pushed gradients. Loss must drop substantially."""
    import paddle_tpu
    from paddle_tpu import nn

    paddle_tpu.seed(0)
    comm = Communicator(InProcClient(), "sync")
    helper = SparseEmbeddingHelper(comm, "user_emb", 8, optimizer="adagrad",
                                   lr=0.5, init_scale=0.1, seed=1)

    mlp = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))

    rs = np.random.RandomState(0)
    n_users = 50
    labels_by_user = (rs.rand(n_users) > 0.5).astype(np.float32)

    @jax.jit
    def step(m, rows, inverse, y):
        def loss_fn(m, rows):
            emb = rows[inverse]                      # [B, dim]
            logit = m(emb)[:, 0]
            return jnp.mean(
                jnp.maximum(logit, 0) - logit * y
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))  # stable BCE
        (loss), (gm, grows) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            m, rows)
        new_m = jax.tree_util.tree_map(lambda p, g: p - 0.2 * g, m, gm)
        return loss, new_m, grows

    losses = []
    for it in range(60):
        ids = rs.randint(0, n_users, (32,))
        y = jnp.asarray(labels_by_user[ids])
        rows, inverse, uniq = helper.lookup(ids)
        loss, mlp, grows = step(mlp, rows, inverse, y)
        helper.apply_grads(uniq, grows)
        losses.append(float(loss))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6, (
        losses[:5], losses[-5:])


# ---------------------------------------------------------------------------
# native multi-slot data feed
# ---------------------------------------------------------------------------

def _write_multislot(path, n=10):
    """MultiSlot wire format: per slot '<num> <v>*num' (data_feed.cc
    ParseOneInstance)."""
    rs = np.random.RandomState(0)
    lines = []
    for i in range(n):
        ids = rs.randint(0, 1000, rs.randint(1, 5))
        dense = rs.rand(3)
        label = [i % 2]
        lines.append(" ".join(
            [str(len(ids))] + [str(x) for x in ids]
            + ["3"] + [f"{x:.6f}" for x in dense]
            + ["1"] + [str(x) for x in label]))
    path.write_text("\n".join(lines) + "\n")
    return lines


def test_native_feed_parse_and_batch(tmp_path):
    from paddle_tpu.native.data_feed import NativeDataFeed

    f = tmp_path / "part-0"
    lines = _write_multislot(f, n=10)
    feed = NativeDataFeed({"ids": "int64", "dense": "float",
                           "label": "int64"})
    assert feed.load_file(str(f)) == 10
    assert len(feed) == 10

    batches = list(feed.batches(4))
    assert len(batches) == 3  # 4 + 4 + 2
    b0 = batches[0]
    ids_vals, ids_off = b0["ids"]
    assert ids_off[0] == 0 and ids_off[-1] == len(ids_vals)
    assert b0["dense"].shape == (4, 3)          # fixed-width → dense
    # first record round-trips exactly
    first = lines[0].split()
    n0 = int(first[0])
    np.testing.assert_array_equal(ids_vals[:n0],
                                  [int(x) for x in first[1:1 + n0]])
    lab_vals, lab_off = b0["label"]
    np.testing.assert_array_equal(np.diff(lab_off), np.ones(4))


def test_native_feed_shuffle_and_parse_error(tmp_path):
    from paddle_tpu.native.data_feed import NativeDataFeed

    f = tmp_path / "part-0"
    _write_multislot(f, n=8)
    feed = NativeDataFeed({"ids": "int64", "dense": "float",
                           "label": "int64"})
    feed.load_file(str(f))
    before = [b["label"][0].copy() for b in feed.batches(8)]
    feed.global_shuffle(seed=1)
    after = [b["label"][0].copy() for b in feed.batches(8)]
    assert sorted(before[0].tolist()) == sorted(after[0].tolist())
    assert not np.array_equal(before[0], after[0])

    bad = tmp_path / "bad"
    bad.write_text("2 1\n")  # claims 2 ids, gives 1 → malformed next slot
    feed2 = NativeDataFeed({"ids": "int64", "dense": "float",
                            "label": "int64"})
    with pytest.raises(ValueError, match="line 1"):
        feed2.load_file(str(bad))


def test_native_feed_throughput_vs_python(tmp_path):
    """The native parse must beat a straightforward Python parser by a
    wide margin (it is the reason this component is C++)."""
    import time

    f = tmp_path / "big"
    rs = np.random.RandomState(0)
    n = 20000
    rows = []
    for _ in range(n):
        k = rs.randint(1, 8)
        rows.append(" ".join([str(k)] + [str(x) for x in
                                         rs.randint(0, 10**6, k)]))
    f.write_text("\n".join(rows) + "\n")

    t0 = time.perf_counter()
    from paddle_tpu.native.data_feed import NativeDataFeed
    feed = NativeDataFeed({"ids": "int64"})
    feed.load_file(str(f))
    native_t = time.perf_counter() - t0
    assert len(feed) == n

    t0 = time.perf_counter()
    parsed = []
    with open(f) as fh:
        for line in fh:
            parts = line.split()
            k = int(parts[0])
            parsed.append(np.array([int(x) for x in parts[1:1 + k]],
                                   np.int64))
    python_t = time.perf_counter() - t0
    assert native_t < python_t, (native_t, python_t)


# ---------------------------------------------------------------------------
# fleet distributed metrics (reference fleet/metrics/metric.py)
# ---------------------------------------------------------------------------

def test_fleet_metrics_single_process():
    from paddle_tpu.distributed.fleet import metrics as fm

    assert fm.sum(np.asarray([1.0, 2.0])).tolist() == [1.0, 2.0]
    assert fm.acc(np.asarray(8), np.asarray(10)) == 0.8
    # perfect separation → auc 1; random → 0.5-ish
    pos = np.zeros(10); pos[9] = 100     # all positives score high
    neg = np.zeros(10); neg[0] = 100     # all negatives score low
    assert fm.auc(pos, neg) > 0.99
    uniform = np.ones(10)
    assert abs(fm.auc(uniform, uniform) - 0.5) < 1e-6


def test_native_cc_unit_tests(tmp_path):
    """Build and run the C++-level unit tests (the reference's colocated
    *_test.cc pattern): table math, shard-lock concurrency, feed CSR."""
    import subprocess
    import sys

    from paddle_tpu.native.build import _SRC_DIR

    exe = str(tmp_path / "native_test")
    srcs = [os.path.join(_SRC_DIR, s) for s in
            ("sparse_table.cc", "data_feed.cc", "native_test.cc")]
    subprocess.run(["g++", "-O1", "-std=c++17", "-pthread", "-o", exe,
                    *srcs], check=True, capture_output=True, text=True)
    out = subprocess.run([exe, str(tmp_path)], check=True,
                         capture_output=True, text=True, timeout=120)
    assert "ALL NATIVE TESTS PASSED" in out.stdout, out.stdout
