"""Parameter-server stack tests: native table math, TCP service parity,
sync/async/geo communicator semantics, and an end-to-end sparse
recommender model trained through the jitted TPU step.

Reference test analogues: ``operators/distributed/communicator_test.cc``,
``tests/unittests/test_dist_base.py`` PS modes, and the sparse-embedding
workloads (``parallel_dygraph_sparse_embedding.py``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.ps import (
    Communicator, InProcClient, NativeSparseTable, ParameterServer,
    PSClient, SparseEmbeddingHelper,
)


# ---------------------------------------------------------------------------
# native table
# ---------------------------------------------------------------------------

def test_table_deterministic_init_and_bounds():
    t1 = NativeSparseTable(8, seed=7, init_scale=0.25)
    t2 = NativeSparseTable(8, seed=7, init_scale=0.25)
    ids = np.array([1, 999999999, -5, 0])
    np.testing.assert_array_equal(t1.pull(ids), t2.pull(ids))
    assert (np.abs(t1.pull(ids)) <= 0.25).all()
    t3 = NativeSparseTable(8, seed=8, init_scale=0.25)
    assert not np.allclose(t3.pull(ids), t1.pull(ids))


def test_table_sgd_update_merges_duplicates():
    t = NativeSparseTable(4, optimizer="sgd", lr=0.5, seed=0)
    ids = np.array([3, 3, 9])
    before = t.pull(np.array([3, 9]))
    g = np.array([[1, 0, 0, 0], [0, 1, 0, 0], [1, 1, 1, 1]], np.float32)
    t.push_grad(ids, g)
    after = t.pull(np.array([3, 9]))
    np.testing.assert_allclose(after[0], before[0] - 0.5 * np.array(
        [1, 1, 0, 0], np.float32), rtol=1e-6)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * np.ones(4),
                               rtol=1e-6)


def test_table_adagrad_matches_numpy():
    t = NativeSparseTable(3, optimizer="adagrad", lr=0.1, seed=1)
    ids = np.array([42])
    p = t.pull(ids)[0].astype(np.float64)
    G = np.zeros(3)
    rs = np.random.RandomState(0)
    for _ in range(5):
        g = rs.randn(1, 3).astype(np.float32)
        t.push_grad(ids, g)
        G += g[0].astype(np.float64) ** 2
        p -= 0.1 * g[0] / (np.sqrt(G) + 1e-6)
    np.testing.assert_allclose(t.pull(ids)[0], p, rtol=1e-5)


def test_table_adam_matches_numpy():
    t = NativeSparseTable(3, optimizer="adam", lr=0.01, seed=1)
    ids = np.array([7])
    p = t.pull(ids)[0].astype(np.float64)
    m = np.zeros(3)
    v = np.zeros(3)
    rs = np.random.RandomState(3)
    for step in range(1, 6):
        g = rs.randn(1, 3).astype(np.float32)
        t.push_grad(ids, g)
        m = 0.9 * m + 0.1 * g[0]
        v = 0.999 * v + 0.001 * g[0] ** 2
        mhat = m / (1 - 0.9 ** step)
        vhat = v / (1 - 0.999 ** step)
        p -= 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(t.pull(ids)[0], p, rtol=1e-4, atol=1e-6)


def test_table_save_load_roundtrip(tmp_path):
    t = NativeSparseTable(5, optimizer="adagrad", lr=0.1, seed=2)
    ids = np.arange(100)
    t.push_grad(ids, np.ones((100, 5), np.float32))
    t.save(str(tmp_path / "tbl.bin"))
    t2 = NativeSparseTable(5, optimizer="adagrad", lr=0.1, seed=2)
    t2.load(str(tmp_path / "tbl.bin"))
    assert len(t2) == 100
    np.testing.assert_array_equal(t2.pull(ids), t.pull(ids))
    # optimizer slots restored too: next identical update stays identical
    t.push_grad(ids[:1], np.ones((1, 5), np.float32))
    t2.push_grad(ids[:1], np.ones((1, 5), np.float32))
    np.testing.assert_array_equal(t2.pull(ids[:1]), t.pull(ids[:1]))


# ---------------------------------------------------------------------------
# TCP service
# ---------------------------------------------------------------------------

def test_tcp_server_matches_inproc():
    server = ParameterServer().start()
    try:
        tcp = PSClient(server.endpoint)
        ref = InProcClient()
        for c in (tcp, ref):
            c.create_table("emb", 6, optimizer="sgd", lr=0.2, seed=5)
        ids = np.array([10, 20, 30, 10])
        np.testing.assert_array_equal(tcp.pull("emb", ids),
                                      ref.pull("emb", ids))
        g = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        tcp.push_grad("emb", ids, g)
        ref.push_grad("emb", ids, g)
        np.testing.assert_allclose(tcp.pull("emb", ids),
                                   ref.pull("emb", ids), rtol=1e-6)
        assert tcp.size("emb") == 3
        np.testing.assert_array_equal(tcp.keys("emb"),
                                      np.array([10, 20, 30]))
        tcp.close()
    finally:
        server.stop()


def test_tcp_multi_server_sharding():
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    try:
        c = PSClient([s1.endpoint, s2.endpoint])
        c.create_table("emb", 4, optimizer="sgd", lr=0.5, seed=9)
        ref = InProcClient()
        ref.create_table("emb", 4, optimizer="sgd", lr=0.5, seed=9)
        ids = np.arange(1, 21)
        np.testing.assert_array_equal(c.pull("emb", ids),
                                      ref.pull("emb", ids))
        g = np.random.RandomState(1).randn(20, 4).astype(np.float32)
        c.push_grad("emb", ids, g)
        ref.push_grad("emb", ids, g)
        np.testing.assert_allclose(c.pull("emb", ids), ref.pull("emb", ids),
                                   rtol=1e-6)
        assert c.size("emb") == 20
        c.close()
    finally:
        s1.stop()
        s2.stop()


def test_server_error_reporting():
    server = ParameterServer().start()
    try:
        c = PSClient(server.endpoint)
        with pytest.raises(RuntimeError, match="no table"):
            c.pull("nope", np.array([1]))
        c.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# communicator modes
# ---------------------------------------------------------------------------

def _drive(comm, steps=6):
    losses = []
    ids = np.array([1, 2, 3])
    target = np.full((3, 4), 0.5, np.float32)
    for _ in range(steps):
        rows = comm.pull("emb", ids)
        grad = 2 * (rows - target)       # d/drow ||row - t||^2
        losses.append(float(((rows - target) ** 2).sum()))
        comm.push_grad("emb", ids, grad)
    comm.flush()
    return losses


def test_communicator_sync_converges():
    client = InProcClient()
    comm = Communicator(client, "sync")
    comm.create_table("emb", 4, optimizer="sgd", lr=0.1, seed=3)
    losses = _drive(comm)
    assert losses[-1] < losses[0] * 0.2


def test_communicator_async_applies_eventually():
    """Async pushes land via the background sender: the post-flush state
    must reflect the training (loss measured during the loop may race —
    Hogwild staleness is the contract, not per-step freshness)."""
    client = InProcClient()
    comm = Communicator(client, "async")
    # lr small enough that even fully-stale gradient application (all 10
    # pulls racing ahead of the sender) still moves monotonically toward
    # the target instead of overshooting
    comm.create_table("emb", 4, optimizer="sgd", lr=0.02, seed=3)
    losses = _drive(comm, steps=10)
    comm.stop()
    ids = np.array([1, 2, 3])
    target = np.full((3, 4), 0.5, np.float32)
    final = float(((comm.pull("emb", ids) - target) ** 2).sum())
    assert final < losses[0] * 0.5, (final, losses[0])


def test_communicator_geo_delta_sync():
    """Two geo workers on disjoint ids: local training + delta push must
    land both workers' progress on the server (geo-SGD semantics)."""
    server_tables = InProcClient()
    w1 = Communicator(server_tables, "geo", geo_k=4)
    w1.create_table("emb", 4, optimizer="sgd", lr=0.1, seed=3)
    w2 = Communicator(server_tables, "geo", geo_k=4)
    w2._specs["emb"] = w1._specs["emb"]
    w2._local["emb"] = NativeSparseTable(**w1._specs["emb"])
    w2._snapshot["emb"] = {}
    w2._touched["emb"] = set()

    ids1, ids2 = np.array([1, 2]), np.array([10, 20])
    target = np.zeros((2, 4), np.float32)
    for _ in range(8):
        for w, ids in ((w1, ids1), (w2, ids2)):
            rows = w.pull("emb", ids)
            w.push_grad("emb", ids, 2 * (rows - target))
    w1.flush()
    w2.flush()
    # server rows moved toward 0 for BOTH workers' ids
    init = NativeSparseTable(4, optimizer="sgd", lr=0.1, seed=3)
    for ids in (ids1, ids2):
        now = server_tables.pull("emb", ids)
        before = init.pull(ids)
        assert (np.abs(now) < np.abs(before)).mean() > 0.9, (now, before)


# ---------------------------------------------------------------------------
# end-to-end: sparse recommender through the jitted TPU step
# ---------------------------------------------------------------------------

def test_sparse_embedding_model_trains():
    """CTR-style toy: sparse id -> embedding (PS table) -> dense MLP (jit).
    The dense params train on-device; embedding rows train server-side
    via pushed gradients. Loss must drop substantially."""
    import paddle_tpu
    from paddle_tpu import nn

    paddle_tpu.seed(0)
    comm = Communicator(InProcClient(), "sync")
    helper = SparseEmbeddingHelper(comm, "user_emb", 8, optimizer="adagrad",
                                   lr=0.5, init_scale=0.1, seed=1)

    mlp = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))

    rs = np.random.RandomState(0)
    n_users = 50
    labels_by_user = (rs.rand(n_users) > 0.5).astype(np.float32)

    @jax.jit
    def step(m, rows, inverse, y):
        def loss_fn(m, rows):
            emb = rows[inverse]                      # [B, dim]
            logit = m(emb)[:, 0]
            return jnp.mean(
                jnp.maximum(logit, 0) - logit * y
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))  # stable BCE
        (loss), (gm, grows) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            m, rows)
        new_m = jax.tree_util.tree_map(lambda p, g: p - 0.2 * g, m, gm)
        return loss, new_m, grows

    losses = []
    for it in range(60):
        ids = rs.randint(0, n_users, (32,))
        y = jnp.asarray(labels_by_user[ids])
        rows, inverse, uniq = helper.lookup(ids)
        loss, mlp, grows = step(mlp, rows, inverse, y)
        helper.apply_grads(uniq, grows)
        losses.append(float(loss))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6, (
        losses[:5], losses[-5:])
