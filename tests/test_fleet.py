"""Fleet strategy compiler integration tests: tiny Llama/GPT trained under
composed strategies on the 8-device CPU mesh — the analogue of the
reference's TestDistBase loss-vs-local comparison
(``tests/unittests/test_dist_base.py:1119``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.core.strategy import DistributedStrategy
from paddle_tpu.models import GPTConfig, GPTForCausalLM, LlamaConfig, LlamaForCausalLM
from paddle_tpu.parallel import mesh as M


def make_batch(bs=8, seq=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, vocab, (bs, seq)).astype(np.int32)
    return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}


def run_steps(strategy, n=6, model_cls=LlamaForCausalLM, cfg=None, lr=1e-2):
    paddle_tpu.seed(42)
    cfg = cfg or LlamaConfig.tiny()
    model = model_cls(cfg)
    mesh = M.mesh_from_strategy(strategy)
    with M.MeshContext(mesh):
        opt = optim.AdamW(lr, grad_clip=optim.ClipGradByGlobalNorm(1.0))
        step = dist.fleet.build_train_step(model, optimizer=opt,
                                           strategy=strategy, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch(make_batch())
        losses = []
        for i in range(n):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    return losses, state, step


def test_fleet_dp_only(devices8):
    s = DistributedStrategy()  # 8-way dp inferred
    losses, state, _ = run_steps(s)
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 6


def test_fleet_zero3_tp_hybrid(devices8):
    s = DistributedStrategy()
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 2
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    losses, state, step = run_steps(s)
    assert losses[-1] < losses[0], losses
    # parameters actually sharded: wq spec has fsdp AND tp
    wq = state.model.blocks.block.attn.wq.weight
    assert wq.sharding.spec == P(None, "fsdp", "tp")


def test_fleet_hybrid_matches_dp_losses(devices8):
    """Same seed => sharded/TP run must reproduce pure-DP losses (the
    TestDistBase check_with_place tolerance comparison)."""
    s1 = DistributedStrategy()
    s2 = DistributedStrategy()
    s2.sharding.enable = True
    s2.sharding.stage = 3
    s2.sharding.degree = 2
    s2.tensor_parallel.enable = True
    s2.tensor_parallel.degree = 2
    l1, _, _ = run_steps(s1)
    l2, _, _ = run_steps(s2)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-5)


def test_fleet_gradient_merge(devices8):
    s = DistributedStrategy()
    s.gradient_merge.enable = True
    s.gradient_merge.k_steps = 2
    losses, state, step = run_steps(s, n=4)
    # params must only move on steps 2 and 4; after step1 the model equals
    # init. We can't see intermediates here, so check the accumulator is
    # zeroed after an apply step (step 4 = 2nd apply).
    acc_norm = float(sum(jnp.sum(jnp.abs(l)) for l in
                         jax.tree_util.tree_leaves(state.merge_grads)))
    assert acc_norm == 0.0
    assert losses[-1] < losses[0]


def test_amp_cast_model_keeps_norms_fp32():
    """keep_norms_fp32 (keep_batch_norm_fp32 analogue): norm subtrees —
    params AND running stats — stay fp32 while everything else casts."""
    import jax.numpy as jnp

    from paddle_tpu import amp

    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(8, 8), nn.LayerNorm(8),
                        nn.BatchNorm1D(8), nn.Linear(8, 2))
    cast = amp.cast_model(net, jnp.bfloat16, keep_norms_fp32=True)
    assert cast.layers[0].weight.dtype == jnp.bfloat16
    assert cast.layers[3].weight.dtype == jnp.bfloat16
    assert cast.layers[1].weight.dtype == jnp.float32      # LayerNorm
    assert cast.layers[2].weight.dtype == jnp.float32      # BatchNorm
    assert cast.layers[2].running_mean.dtype == jnp.float32
    # decorate defaults to keeping norms fp32 (reference O2 decorator)
    dec = amp.decorate(net, dtype="bfloat16")
    assert dec.layers[1].weight.dtype == jnp.float32
    # plain cast_model still casts everything (master-weights path)
    allc = amp.cast_model(net, jnp.bfloat16)
    assert allc.layers[1].weight.dtype == jnp.bfloat16

    # user subclasses of norm layers keep the protection (isinstance)
    class MyNorm(nn.LayerNorm):
        pass

    sub = nn.Sequential(nn.Linear(4, 4), MyNorm(4))
    csub = amp.cast_model(sub, jnp.bfloat16, keep_norms_fp32=True)
    assert csub.layers[1].weight.dtype == jnp.float32


def test_fleet_amp_bf16(devices8):
    s = DistributedStrategy()
    s.amp.enable = True
    s.amp.dtype = "bfloat16"
    losses, _, _ = run_steps(s)
    assert losses[-1] < losses[0]


def test_fleet_amp_fp16_scaler(devices8):
    s = DistributedStrategy()
    s.amp.enable = True
    s.amp.dtype = "float16"
    losses, state, _ = run_steps(s, n=4)
    # dynamic loss scaling active
    assert float(state.scaler.loss_scaling) > 0
    assert losses[-1] < losses[0]


def test_fleet_recompute_same_losses(devices8):
    s1 = DistributedStrategy()
    s2 = DistributedStrategy()
    s2.recompute.enable = True
    s2.recompute.policy = "nothing_saveable"
    l1, _, _ = run_steps(s1)
    l2, _, _ = run_steps(s2)
    # remat must not change numerics
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_fleet_gpt_model(devices8):
    s = DistributedStrategy()
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    losses, _, _ = run_steps(s, model_cls=GPTForCausalLM,
                             cfg=GPTConfig.tiny())
    assert losses[-1] < losses[0]


def test_localsgd_runs_via_fleet(devices8):
    s = DistributedStrategy()
    s.localsgd.enable = True
    s.localsgd.k_steps = 2
    losses, state, _ = run_steps(s, lr=1e-2)
    assert losses[-1] < losses[0], losses


def test_scanned_blocks_match_loop():
    """Scan-over-layers must equal an explicit python loop."""
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(num_layers=3)
    from paddle_tpu.models.llama import LlamaBlock
    from paddle_tpu.nn.scan import ScannedBlocks

    paddle_tpu.seed(7)
    blocks = [LlamaBlock(cfg) for _ in range(3)]
    paddle_tpu.seed(7)
    scanned = ScannedBlocks(lambda i: LlamaBlock(cfg), 3)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, cfg.hidden_size)
                    .astype(np.float32))
    y_loop = x
    for b in blocks:
        y_loop = b(y_loop)
    y_scan = scanned(x)
    np.testing.assert_allclose(y_loop, y_scan, rtol=2e-5, atol=2e-5)


def test_fleet_pipeline_matches_dp_losses(devices8):
    """GPipe over pp=2 (+tp=2, dp=2) must reproduce pure-DP losses: the
    pipeline is a pure re-scheduling of the same math."""
    s1 = DistributedStrategy()
    s2 = DistributedStrategy()
    s2.pipeline.enable = True
    s2.pipeline.degree = 2
    s2.pipeline.num_microbatches = 2
    s2.tensor_parallel.enable = True
    s2.tensor_parallel.degree = 2
    cfg = LlamaConfig.tiny(num_layers=4)
    l1, _, _ = run_steps(s1, cfg=cfg)
    l2, state2, _ = run_steps(s2, cfg=cfg)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-5)
    # layer dim actually sharded over pp
    wq = state2.model.blocks.block.attn.wq.weight
    assert wq.sharding.spec[0] == "pp"


def test_fleet_pipeline_with_zero3(devices8):
    """4D-style composition: pp=2 x fsdp=2 x tp=2 on 8 devices."""
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = 2
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 2
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    cfg = LlamaConfig.tiny(num_layers=4)
    losses, _, _ = run_steps(s, cfg=cfg)
    assert losses[-1] < losses[0], losses


def test_merge_accumulator_skips_overflow_step(devices8):
    """fp16 scaling + gradient merge: a NaN/overflow step must not poison
    the merge window."""
    import paddle_tpu.distributed.fleet.strategy_compiler as sc
    from paddle_tpu import optimizer as optim

    s = DistributedStrategy()
    s.amp.enable = True
    s.amp.dtype = "float16"
    s.gradient_merge.enable = True
    s.gradient_merge.k_steps = 2
    s.amp.init_loss_scaling = 2.0 ** 60  # guarantee overflow on step 1
    paddle_tpu.seed(1)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    mesh = M.mesh_from_strategy(s)
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.AdamW(1e-3), strategy=s, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch(make_batch())
        state, m1 = step(state, batch, jax.random.PRNGKey(0))
        assert not bool(m1["all_finite"])  # overflow detected
        acc_finite = all(bool(jnp.all(jnp.isfinite(l))) for l in
                         jax.tree_util.tree_leaves(state.merge_grads))
        assert acc_finite, "overflow grads leaked into merge accumulator"


def test_pipeline_dropout_per_layer(devices8):
    """Pipelined GPT with dropout: trains and stays finite (per-layer keys
    threaded through the tick/stage scans)."""
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = 2
    cfg = GPTConfig.tiny(num_layers=4, dropout=0.2)
    losses, _, _ = run_steps(s, model_cls=GPTForCausalLM, cfg=cfg)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0]


def test_localsgd_k1_matches_sync_dp(devices8):
    """LocalSGD with k_steps=1 + SGD is algebraically identical to
    synchronous DP-SGD: p - lr*mean_i(g_i). Bitwise-tolerance parity is the
    TestDistBase-style check for the LocalSGD strategy."""
    batch = make_batch()
    cfg = LlamaConfig.tiny()
    mesh = M.mesh_from_strategy(DistributedStrategy())

    def fresh_model():
        # init_state arrays may alias the model's and get donated, so each
        # run rebuilds from the same seed
        paddle_tpu.seed(7)
        return LlamaForCausalLM(cfg)

    with M.MeshContext(mesh):
        # plain DP
        model = fresh_model()
        s_dp = DistributedStrategy()
        step_dp = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(1e-2), strategy=s_dp, mesh=mesh)
        st_dp = step_dp.init_state(model)
        dp_losses = []
        for i in range(4):
            st_dp, m = step_dp(st_dp, step_dp.shard_batch(batch),
                               jax.random.PRNGKey(i))
            dp_losses.append(float(m["loss"]))

        # LocalSGD k=1
        model = fresh_model()
        s_l = DistributedStrategy()
        s_l.localsgd.enable = True
        s_l.localsgd.k_steps = 1
        step_l = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(1e-2), strategy=s_l, mesh=mesh)
        st_l = step_l.init_state(model)
        l_losses = []
        for i in range(4):
            st_l, m = step_l(st_l, step_l.shard_batch(batch),
                             jax.random.PRNGKey(i))
            l_losses.append(float(m["loss"]))

    np.testing.assert_allclose(l_losses, dp_losses, rtol=2e-4)


def test_localsgd_k3_diverges_then_syncs(devices8):
    """k_steps=3: replicas diverge on non-sync steps and become identical
    after each sync step; training still reduces the loss."""
    paddle_tpu.seed(3)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    s = DistributedStrategy()
    s.localsgd.enable = True
    s.localsgd.k_steps = 3
    mesh = M.mesh_from_strategy(DistributedStrategy())
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(5e-2), strategy=s, mesh=mesh)
        state = step.init_state(model)
        losses = []
        # one fixed global batch: replicas still diverge because each gets
        # a different slice of it
        for i in range(6):
            b = step.shard_batch(make_batch())
            state, m = step(state, b, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
            w = np.asarray(state.model.lm_head.weight)
            spread = np.abs(w - w[0:1]).max()
            if (i + 1) % 3 == 0:
                assert bool(m["synced"])
                assert spread < 1e-6, f"step {i}: replicas differ post-sync"
            else:
                assert not bool(m["synced"])
                assert spread > 1e-7, f"step {i}: replicas never diverged"
    assert losses[-1] < losses[0]


def test_adaptive_localsgd_interval_grows_on_plateau(devices8):
    """AdaptiveLocalSGD (AdaComm, localsgd_optimizer.py:194): with a
    decaying learning rate and a plateauing loss, the sync interval k must
    grow — k = ceil(sqrt(lr_0*loss/(lr_t*loss_0)*init_k)) rises as
    lr_t/lr_0 shrinks faster than loss/loss_0."""
    paddle_tpu.seed(11)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    s = DistributedStrategy()
    s.localsgd.enable = True
    s.localsgd.adaptive = True
    s.localsgd.init_k_steps = 1
    s.localsgd.max_k_steps = 8
    mesh = M.mesh_from_strategy(DistributedStrategy())
    # lr tiny (loss barely moves = plateau) and halving every step
    sched = optim.lr.ExponentialDecay(learning_rate=1e-5, gamma=0.5)
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(sched), strategy=s, mesh=mesh)
        state = step.init_state(model)
        for i in range(12):
            state, m = step(state, step.shard_batch(make_batch()),
                            jax.random.PRNGKey(i))
    assert step.k_steps > 1, (step.k_steps, step.sync_history)
    gaps = np.diff(list(step.sync_history))
    assert gaps[-1] > gaps[0], (list(step.sync_history), step.k_steps)
    assert step.k_steps <= 8  # clipped at max_k_steps


def test_adaptive_localsgd_schedule_survives_resume(devices8):
    """The AdaComm schedule scalars (k, last_sync, loss_0, lr_0) ride in
    TrainState.scaler, so a fresh wrapper (process restart / checkpoint
    restore) adopts the grown interval instead of re-baselining to
    sync-every-step — matching the reference's persistable k_steps/loss_0
    variables."""
    paddle_tpu.seed(13)
    cfg = LlamaConfig.tiny()
    s = DistributedStrategy()
    s.localsgd.enable = True
    s.localsgd.adaptive = True
    s.localsgd.max_k_steps = 8
    mesh = M.mesh_from_strategy(DistributedStrategy())
    sched = optim.lr.ExponentialDecay(learning_rate=1e-5, gamma=0.5)

    def build():
        model = LlamaForCausalLM(cfg)
        return dist.fleet.build_train_step(
            model, optimizer=optim.SGD(sched), strategy=s, mesh=mesh), model

    with M.MeshContext(mesh):
        step1, model = build()
        state = step1.init_state(model)
        for i in range(8):
            state, _ = step1(state, step1.shard_batch(make_batch()),
                             jax.random.PRNGKey(i))
        assert step1.k_steps > 1
        # "restart": new wrapper object, same (donation-surviving) state
        step2, _ = build()
        k_before = step1.k_steps
        state, m = step2(state, step2.shard_batch(make_batch()),
                         jax.random.PRNGKey(99))
        # the grown interval and cadence carried over exactly: same k, and
        # step 9 is within the interval of the last sync at step 7, so a
        # re-baselined wrapper (which would sync at its first step) fails
        assert step2.k_steps == k_before, (k_before, step2.k_steps)
        assert not bool(m["synced"])
        assert step2._host_step == 9
        # a pre-schedule-scalars state (scaler=()) upgrades in place
        legacy = state._replace(scaler=())
        st3, _ = step2(legacy, step2.shard_batch(make_batch()),
                       jax.random.PRNGKey(100))
        assert isinstance(st3.scaler, dict) and "k_steps" in st3.scaler


def test_adaptive_localsgd_constant_lr_stays_synced(devices8):
    """With a constant lr and a non-increasing loss the AdaComm rule keeps
    k at init_k (ratio <= 1): adaptive mode degenerates to sync-DP when
    there is nothing to save."""
    paddle_tpu.seed(12)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    s = DistributedStrategy()
    s.localsgd.enable = True
    s.localsgd.adaptive = True
    s.localsgd.init_k_steps = 1
    mesh = M.mesh_from_strategy(DistributedStrategy())
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(1e-2), strategy=s, mesh=mesh)
        state = step.init_state(model)
        for i in range(5):
            state, m = step(state, step.shard_batch(make_batch()),
                            jax.random.PRNGKey(i))
            assert bool(m["synced"])
    assert step.k_steps == 1
    assert list(step.sync_history) == [1, 2, 3, 4, 5]


def test_localsgd_rejects_hybrid(devices8):
    s = DistributedStrategy()
    s.localsgd.enable = True
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    mesh = M.mesh_from_strategy(s)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    with M.MeshContext(mesh):
        with pytest.raises(ValueError, match="data parallelism only"):
            dist.fleet.build_train_step(model, optimizer=optim.SGD(1e-2),
                                        strategy=s, mesh=mesh)


def test_fp16_allreduce_matches_fp32_reduction(devices8):
    """bf16-compressed gradient all-reduce tracks the uncompressed DP run
    within bf16 tolerance (fp16_allreduce_optimizer.py equivalence)."""
    s = DistributedStrategy()
    s.fp16_allreduce.enable = True
    losses, state, _ = run_steps(s, lr=1e-3)
    s0 = DistributedStrategy()
    ref_losses, _, _ = run_steps(s0, lr=1e-3)
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-2)
    assert losses[-1] < losses[0]


def test_fp16_allreduce_composes_with_zero2(devices8):
    """zero-1/2 compose with the compressed reduction (params stay
    replicated over the manual data axes; only optimizer state is
    sharded). tp stays gated: the correct partial-manual formulation
    aborts XLA CPU today and the all-manual one would silently
    replicate the Megatron shards (probed r4; see the strategy-compiler
    comment)."""
    ref, _, _ = run_steps(DistributedStrategy(), lr=1e-3)
    s = DistributedStrategy()
    s.sharding.enable = True
    s.sharding.stage = 2
    s.sharding.degree = 2
    s.fp16_allreduce.enable = True
    losses, _, _ = run_steps(s, lr=1e-3)
    np.testing.assert_allclose(losses, ref, rtol=2e-2)
    assert losses[-1] < losses[0]

    s_tp = DistributedStrategy()
    s_tp.tensor_parallel.enable = True
    s_tp.tensor_parallel.degree = 2
    s_tp.fp16_allreduce.enable = True
    mesh = M.mesh_from_strategy(s_tp)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_layers=4))
    with M.MeshContext(mesh):
        with pytest.raises(ValueError, match="incompatible"):
            dist.fleet.build_train_step(
                model, optimizer=optim.SGD(1e-2), strategy=s_tp, mesh=mesh)


def test_pipeline_composes_with_ring_attention(devices8):
    """pp=2 x sp=2 x dp=2: ring attention inside the pipeline's manual
    shard_map (the nested-manual composition that needs the abstract-mesh
    handling + GSPMD fallback). Losses must match plain DP."""
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = 2
    s.sequence_parallel.enable = True
    s.sequence_parallel.degree = 2
    s.sequence_parallel.mode = "ring"
    cfg = LlamaConfig.tiny(num_layers=4)
    losses, _, _ = run_steps(s, cfg=cfg)
    ref, _, _ = run_steps(DistributedStrategy(), cfg=cfg)
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=2e-5)


def test_pipeline_ulysses_accepted(devices8):
    """pp + Ulysses builds (r4): the joint-manual {pp, sp} formulation
    removed the nested all_to_all that aborted XLA, so the r3 gate is
    retired. Loss parity vs DP is covered by
    test_seq_parallel.py::test_fleet_pp_seq_parallel_matches_dp."""
    s = DistributedStrategy()
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.num_microbatches = 2
    s.sequence_parallel.enable = True
    s.sequence_parallel.degree = 2
    s.sequence_parallel.mode = "ulysses"
    mesh = M.mesh_from_strategy(s)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_layers=4))
    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(model, optimizer=optim.SGD(1e-2),
                                           strategy=s, mesh=mesh)
    assert step is not None


def test_ernie_pretraining_trains_hybrid(devices8):
    """ERNIE MLM+SOP under zero2 x tp: loss decreases; masked positions
    drive the loss (ignore_index elsewhere)."""
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining

    paddle_tpu.seed(0)
    cfg = ErnieConfig.tiny()
    model = ErnieForPretraining(cfg)
    s = DistributedStrategy()
    s.sharding.enable = True
    s.sharding.stage = 2
    s.sharding.degree = 2
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    mesh = M.mesh_from_strategy(s)

    rs = np.random.RandomState(0)
    ids = rs.randint(5, cfg.vocab_size, (8, 32)).astype(np.int32)
    labels = np.full_like(ids, -100)
    mask_pos = rs.rand(*ids.shape) < 0.15
    labels[mask_pos] = ids[mask_pos]
    masked = ids.copy()
    masked[mask_pos] = 3  # [MASK]
    sop = rs.randint(0, 2, (8,)).astype(np.int32)

    def loss_fn(m, batch, training=True):
        return m.loss(batch["input_ids"], batch["labels"],
                      sop_labels=batch["sop"], training=training)

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.AdamW(5e-3), loss_fn=loss_fn,
            strategy=s, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({
            "input_ids": jnp.asarray(masked),
            "labels": jnp.asarray(labels),
            "sop": jnp.asarray(sop)})
        losses = []
        for i in range(6):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    # attention-mask plumbing: padded positions don't crash/NaN
    m2 = state.model
    am = jnp.asarray((rs.rand(2, 32) > 0.3).astype(np.float32))
    out, pooled = m2.ernie(jnp.asarray(masked[:2]), attention_mask=am)
    assert np.isfinite(np.asarray(out)).all()


def test_strategy_json_roundtrip_all_configs():
    """Every strategy section (incl. the round-2 additions: fp16_allreduce,
    expert_parallel, localsgd) survives the JSON round trip — the
    reference's proto-serializable-config contract."""
    s = DistributedStrategy()
    s.amp.enable = True
    s.amp.dtype = "float16"
    s.recompute.enable = True
    s.gradient_merge.enable = True
    s.gradient_merge.k_steps = 4
    s.localsgd.enable = True
    s.localsgd.k_steps = 3
    s.fp16_allreduce.enable = True
    s.fp16_allreduce.dtype = "float16"
    s.sharding.enable = True
    s.sharding.stage = 3
    s.sharding.degree = 4
    s.pipeline.enable = True
    s.pipeline.degree = 2
    s.pipeline.schedule = "1f1b"
    s.tensor_parallel.enable = True
    s.tensor_parallel.degree = 2
    s.sequence_parallel.enable = True
    s.sequence_parallel.mode = "ulysses"
    s.expert_parallel.enable = True
    s.expert_parallel.degree = 8

    s2 = DistributedStrategy.from_json(s.to_json())
    assert s2.to_json() == s.to_json()
    assert s2.localsgd.k_steps == 3
    assert s2.fp16_allreduce.dtype == "float16"
    assert s2.expert_parallel.degree == 8
    assert s2.pipeline.schedule == "1f1b"
    assert s2.parallel_degrees() == s.parallel_degrees()


def test_fp16_allreduce_tp_gate_cites_live_limitation(devices8):
    """The fp16_allreduce × tp gate rests on a distilled, in-tree repro
    (tests/repros/fp16_ar_partial_manual_tp.py): partial-manual
    shard_map with an automatic tp axis rejects the Megatron
    contraction (ShardingTypeError on jax 0.9; a hard XLA-CPU abort
    before that). This test runs the repro — if jax starts accepting
    the composition, it FAILS to flag that the gate can open."""
    import importlib.util as _ilu
    import os

    path = os.path.join(os.path.dirname(__file__), "repros",
                        "fp16_ar_partial_manual_tp.py")
    spec = _ilu.spec_from_file_location("fp16_ar_repro", path)
    mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.reproduces(), (
        "upstream now accepts partial-manual fp16-allreduce with "
        "automatic tp — revisit the strategy_compiler gate "
        "(parity-test tp, then open it)")
