"""Quantization tests: fake-quant math + STE grads, QAT training,
PTQ calibration and int8 freeze.

Reference analogues: fake_quantize op tests
(``tests/unittests/test_fake_quantize_op.py``) and the slim QAT/PTQ pass
tests (``fluid/contrib/slim/tests``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn, quant
from paddle_tpu.quant import QuantConfig


def test_fake_quant_grid_and_error():
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    q, scale = quant.fake_quant_abs_max(x, bits=8)
    qmax = quant.quant_max(8)
    # values land on the quant grid
    grid = np.round(np.asarray(q) / float(scale) * qmax)
    np.testing.assert_allclose(np.asarray(q), grid * float(scale) / qmax,
                               atol=1e-6)
    # error bounded by half a step
    assert float(jnp.max(jnp.abs(q - x))) <= float(scale) / qmax / 2 + 1e-6


def test_fake_quant_ste_gradient():
    scale = jnp.asarray(1.0)
    g = jax.grad(lambda x: jnp.sum(quant.fake_quant(x, scale)))(
        jnp.asarray([0.3, -0.7, 1.5, -2.0]))
    np.testing.assert_array_equal(np.asarray(g), [1.0, 1.0, 0.0, 0.0])


def test_channel_wise_beats_per_tensor_on_skewed_weights():
    rs = np.random.RandomState(1)
    w = rs.randn(16, 8).astype(np.float32)
    w[:, 0] *= 100.0  # one loud channel ruins a per-tensor scale
    w = jnp.asarray(w)
    q_pc, _ = quant.fake_channel_wise_quant_abs_max(w, axis=1)
    q_pt, _ = quant.fake_quant_abs_max(w)
    err_pc = float(jnp.mean((q_pc - w)[:, 1:] ** 2))
    err_pt = float(jnp.mean((q_pt - w)[:, 1:] ** 2))
    assert err_pc < err_pt / 10


def test_quantize_model_swaps_layers():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    qm = quant.quantize_model(m)
    assert isinstance(qm.layers[0], quant.QuantedLinear)
    assert isinstance(qm.layers[1], nn.ReLU)
    assert isinstance(qm.layers[2], quant.QuantedLinear)
    # weights carried over
    np.testing.assert_array_equal(np.asarray(qm.layers[0].weight),
                                  np.asarray(m.layers[0].weight))


def test_qat_trains_and_tracks_act_scale():
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.parallel import mesh as M

    paddle_tpu.seed(0)
    model = quant.quantize_model(
        nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 1)))
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(32, 6).astype(np.float32) * 3.0)
    y = jnp.asarray((x[:, :1] > 0).astype(np.float32))

    def loss_fn(m, batch, training=True):
        return jnp.mean((m(batch["x"], training=training) - batch["y"]) ** 2)

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.Adam(1e-2), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"x": x, "y": y})
        losses = []
        for i in range(25):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # activation scale buffer was tracked through the state tape and is in
    # the ballpark of the input abs-max
    s = float(state.model.layers[0].act_scale)
    assert 1.0 < s < 30.0, s


def test_ptq_calibrate_and_int8_convert():
    paddle_tpu.seed(3)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    rs = np.random.RandomState(0)
    batches = [jnp.asarray(rs.randn(16, 8).astype(np.float32))
               for _ in range(8)]

    qmodel = quant.calibrate(model, batches)
    s = float(qmodel.layers[0].act_scale)
    ref_max = max(float(jnp.max(jnp.abs(b))) for b in batches)
    assert 0.2 * ref_max < s <= ref_max * 1.01, (s, ref_max)

    int8_model = quant.convert_to_int8(qmodel)
    assert isinstance(int8_model.layers[0], quant.Int8Linear)
    assert int8_model.layers[0].weight_q.dtype == jnp.int8

    x = batches[0]
    y_ref = model(x)
    y_q = jax.jit(lambda m, v: m(v))(int8_model, x)
    # int8 path tracks the float model within quantization noise
    rel = float(jnp.linalg.norm(y_q - y_ref) / jnp.linalg.norm(y_ref))
    assert rel < 0.1, rel

    sd = quant.int8_state_dict(int8_model)
    assert any(v.dtype == np.int8 for v in sd.values())


def test_int8_dot_general_runs_int32_accum():
    """The frozen path must issue an integer dot (MXU int8), not a float
    simulation."""
    lin = nn.Linear(16, 8)
    q = quant.convert_to_int8(quant.calibrate(
        lin, [jnp.ones((4, 16))], forward=lambda m, b: m(b, training=True)
        if hasattr(m, "act_scale") else m(b)))
    hlo = jax.jit(lambda m, x: m(x)).lower(
        q, jnp.ones((4, 16))).as_text()
    assert "i8" in hlo and "i32" in hlo, hlo[:500]
