"""Quantization tests: fake-quant math + STE grads, QAT training,
PTQ calibration and int8 freeze.

Reference analogues: fake_quantize op tests
(``tests/unittests/test_fake_quantize_op.py``) and the slim QAT/PTQ pass
tests (``fluid/contrib/slim/tests``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn, quant
from paddle_tpu.quant import QuantConfig, quantize_weights_int8


def test_fake_quant_grid_and_error():
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    q, scale = quant.fake_quant_abs_max(x, bits=8)
    qmax = quant.quant_max(8)
    # values land on the quant grid
    grid = np.round(np.asarray(q) / float(scale) * qmax)
    np.testing.assert_allclose(np.asarray(q), grid * float(scale) / qmax,
                               atol=1e-6)
    # error bounded by half a step
    assert float(jnp.max(jnp.abs(q - x))) <= float(scale) / qmax / 2 + 1e-6


def test_fake_quant_ste_gradient():
    scale = jnp.asarray(1.0)
    g = jax.grad(lambda x: jnp.sum(quant.fake_quant(x, scale)))(
        jnp.asarray([0.3, -0.7, 1.5, -2.0]))
    np.testing.assert_array_equal(np.asarray(g), [1.0, 1.0, 0.0, 0.0])


def test_channel_wise_beats_per_tensor_on_skewed_weights():
    rs = np.random.RandomState(1)
    w = rs.randn(16, 8).astype(np.float32)
    w[:, 0] *= 100.0  # one loud channel ruins a per-tensor scale
    w = jnp.asarray(w)
    q_pc, _ = quant.fake_channel_wise_quant_abs_max(w, axis=1)
    q_pt, _ = quant.fake_quant_abs_max(w)
    err_pc = float(jnp.mean((q_pc - w)[:, 1:] ** 2))
    err_pt = float(jnp.mean((q_pt - w)[:, 1:] ** 2))
    assert err_pc < err_pt / 10


def test_quantize_model_swaps_layers():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    qm = quant.quantize_model(m)
    assert isinstance(qm.layers[0], quant.QuantedLinear)
    assert isinstance(qm.layers[1], nn.ReLU)
    assert isinstance(qm.layers[2], quant.QuantedLinear)
    # weights carried over
    np.testing.assert_array_equal(np.asarray(qm.layers[0].weight),
                                  np.asarray(m.layers[0].weight))


def test_qat_trains_and_tracks_act_scale():
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as optim
    from paddle_tpu.parallel import mesh as M

    paddle_tpu.seed(0)
    model = quant.quantize_model(
        nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 1)))
    mesh = M.create_mesh({"dp": 1}, devices=jax.devices()[:1])
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(32, 6).astype(np.float32) * 3.0)
    y = jnp.asarray((x[:, :1] > 0).astype(np.float32))

    def loss_fn(m, batch, training=True):
        return jnp.mean((m(batch["x"], training=training) - batch["y"]) ** 2)

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.Adam(1e-2), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"x": x, "y": y})
        losses = []
        for i in range(25):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # activation scale buffer was tracked through the state tape and is in
    # the ballpark of the input abs-max
    s = float(state.model.layers[0].act_scale)
    assert 1.0 < s < 30.0, s


def test_ptq_calibrate_and_int8_convert():
    paddle_tpu.seed(3)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    rs = np.random.RandomState(0)
    batches = [jnp.asarray(rs.randn(16, 8).astype(np.float32))
               for _ in range(8)]

    qmodel = quant.calibrate(model, batches)
    s = float(qmodel.layers[0].act_scale)
    ref_max = max(float(jnp.max(jnp.abs(b))) for b in batches)
    assert 0.2 * ref_max < s <= ref_max * 1.01, (s, ref_max)

    int8_model = quant.convert_to_int8(qmodel)
    assert isinstance(int8_model.layers[0], quant.Int8Linear)
    assert int8_model.layers[0].weight_q.dtype == jnp.int8

    x = batches[0]
    y_ref = model(x)
    y_q = jax.jit(lambda m, v: m(v))(int8_model, x)
    # int8 path tracks the float model within quantization noise
    rel = float(jnp.linalg.norm(y_q - y_ref) / jnp.linalg.norm(y_ref))
    assert rel < 0.1, rel

    sd = quant.int8_state_dict(int8_model)
    assert any(v.dtype == np.int8 for v in sd.values())


def test_int8_dot_general_runs_int32_accum():
    """The frozen path must issue an integer dot (MXU int8), not a float
    simulation."""
    lin = nn.Linear(16, 8)
    q = quant.convert_to_int8(quant.calibrate(
        lin, [jnp.ones((4, 16))], forward=lambda m, b: m(b, training=True)
        if hasattr(m, "act_scale") else m(b)))
    hlo = jax.jit(lambda m, x: m(x)).lower(
        q, jnp.ones((4, 16))).as_text()
    assert "i8" in hlo and "i32" in hlo, hlo[:500]


def test_weight_only_int8_linear_accuracy_and_bound():
    """Per-channel weight-only int8: elementwise dequant error bounded
    by scale/2, output relative error small (no calibration needed)."""
    import paddle_tpu

    paddle_tpu.seed(0)
    lin = nn.Linear(64, 32)
    q = quant.quantize_weights_int8(lin)
    assert isinstance(q, quant.WeightOnlyInt8Linear)
    assert q.weight_q.dtype == jnp.int8
    deq = q.weight_q.astype(jnp.float32) * q.w_scale
    err = np.abs(np.asarray(deq) - np.asarray(lin.weight))
    bound = np.asarray(q.w_scale)[None, :] / 2 + 1e-7
    assert (err <= bound).all()
    x = jnp.asarray(np.random.RandomState(1).randn(4, 64)
                    .astype(np.float32))
    rel = (np.linalg.norm(np.asarray(q(x)) - np.asarray(lin(x)))
           / np.linalg.norm(np.asarray(lin(x))))
    assert rel < 0.02, rel


def test_weight_only_int8_scan_stacked_model_generates():
    """quantize_weights_int8 over a scan-stacked llama: every stacked
    leaf keeps its leading layer axis (the scan contract), logits stay
    close, and the jitted KV-cache generate runs on the quantized
    model."""
    import paddle_tpu
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate

    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=64, num_layers=2,
                           num_heads=4, num_kv_heads=4, max_seq_len=64)
    m = LlamaForCausalLM(cfg)
    qm = quantize_weights_int8(m)
    wq = qm.blocks.block.attn.wq.weight_q
    assert wq.shape[0] == 2 and wq.dtype == jnp.int8
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 8))
                      .astype(np.int32))
    lo, lq = m(ids), qm(ids)
    rel = (np.linalg.norm(np.asarray(lq - lo, dtype=np.float32))
           / np.linalg.norm(np.asarray(lo, dtype=np.float32)))
    assert rel < 0.05, rel
    out = np.asarray(jax.jit(lambda mm, i: generate(mm, i, 8))(qm, ids))
    assert out.shape == (2, 16)
    assert (out[:, :8] == np.asarray(ids)).all()


def test_weight_only_int8_preserves_tp_pspecs():
    from jax.sharding import PartitionSpec as P

    lin = nn.Linear(16, 8, pspec=P(None, "tp"))
    q = quant.quantize_weights_int8(lin)
    specs = dict(q._pspecs)
    assert specs["weight_q"] == P(None, "tp")
    assert specs["w_scale"] == P("tp")


def test_weight_only_int8_bf16_grid_and_weight_property():
    """bf16 model: quantization happens against the bf16-rounded scale,
    so dequant with the stored scale keeps the scale/2 bound; the
    .weight property serves consumers that read linear.weight (e.g.
    model.loss on a quantized causal LM)."""
    import paddle_tpu
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle_tpu.seed(1)
    lin = nn.Linear(32, 16, dtype=jnp.bfloat16)
    q = quant.quantize_weights_int8(lin)
    deq = np.asarray(q.weight_q.astype(jnp.float32)
                     * np.asarray(q.w_scale, dtype=np.float32)[None, :])
    err = np.abs(deq - np.asarray(lin.weight, dtype=np.float32))
    bound = np.asarray(q.w_scale, dtype=np.float32)[None, :] / 2 + 1e-7
    assert (err <= bound).all()
    assert q.weight.shape == (32, 16)

    cfg = LlamaConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=32)
    qm = quant.quantize_weights_int8(LlamaForCausalLM(cfg))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8))
                      .astype(np.int32))
    loss = qm.loss(ids, ids, training=False)
    assert np.isfinite(float(loss))


def test_weight_only_int8_honors_autocast():
    from paddle_tpu import amp

    lin = nn.Linear(16, 8, dtype=jnp.float32)
    q = quant.quantize_weights_int8(lin)
    x = jnp.ones((2, 16), jnp.float32)
    assert q(x).dtype == jnp.float32
    with amp.auto_cast(enable=True, dtype="bfloat16"):
        assert q(x).dtype == jnp.bfloat16


def test_weight_only_int8_moe_experts():
    """quantize_weights_int8 must quantize the raw MoE expert tensors
    ([E, in, out] with per-(expert, out-channel) scales), not just the
    nn.Linear attention/head projections — expert weights dominate an
    MoE decode step's reads. Logits stay close and the jitted generate
    runs on the quantized model."""
    import paddle_tpu
    from paddle_tpu.models import MoEConfig, MoEForCausalLM
    from paddle_tpu.models.generation import generate

    paddle_tpu.seed(0)
    cfg = MoEConfig.tiny(vocab_size=128, hidden_size=32,
                         intermediate_size=64, num_layers=2,
                         num_experts=4, max_seq_len=64)
    m = MoEForCausalLM(cfg)
    qm = quantize_weights_int8(m)
    moe = qm.blocks.block.moe
    assert moe.w_gate.dtype == jnp.int8
    assert moe.w_down.dtype == jnp.int8
    assert moe.w_gate_scale.shape == (2, 4, 64)   # [L, E, I]
    assert moe.w_down_scale.shape == (2, 4, 32)   # [L, E, H]
    # scales preserve the ep/tp sharding annotations
    from jax.sharding import PartitionSpec as P
    specs = dict(moe._pspecs)
    assert specs["w_gate_scale"] == P("ep", "tp")
    # experts must be excluded from training updates
    from paddle_tpu.core.module import trainable_mask
    import jax as _jax
    mask_moe = trainable_mask(qm).blocks.block.moe
    assert mask_moe.w_gate is False and mask_moe.w_down_scale is False

    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 8))
                      .astype(np.int32))
    lo, lq = m(ids), qm(ids)
    rel = (np.linalg.norm(np.asarray(lq - lo, dtype=np.float32))
           / np.linalg.norm(np.asarray(lo, dtype=np.float32)))
    assert rel < 0.05, rel
    out = np.asarray(jax.jit(lambda mm, i: generate(mm, i, 8))(qm, ids))
    assert out.shape == (2, 16)
