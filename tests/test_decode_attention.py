"""Pallas decode-attention kernel (ops/pallas/decode_attention.py).

OpTest discipline (reference
``python/paddle/fluid/tests/unittests/op_test.py:226``): the kernel must
reproduce the einsum fallback bit-for-bit in interpret mode (same dtype
path, same visibility set), select the right layer out of the stacked
buffers, bound its reads to the filled prefix, and fold the int8 scales
exactly.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models import _common
from paddle_tpu.ops.pallas import _support, decode_attention as dk


def _mk(B=2, Hq=8, Hkv=4, S=256, D=64, L=2, dtype=jnp.float32, quant=False,
        seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(B, 1, Hq, D), dtype)
    k_new = jnp.asarray(rs.randn(B, Hkv, 1, D), dtype)
    v_new = jnp.asarray(rs.randn(B, Hkv, 1, D), dtype)
    if quant:
        kc = jnp.asarray(rs.randint(-127, 128, (L, B, Hkv, S, D)), jnp.int8)
        vc = jnp.asarray(rs.randint(-127, 128, (L, B, Hkv, S, D)), jnp.int8)
        ks = jnp.asarray(rs.rand(L, B, Hkv, S) * 0.05 + 0.001, jnp.float32)
        vs = jnp.asarray(rs.rand(L, B, Hkv, S) * 0.05 + 0.001, jnp.float32)
        cache = (kc, vc, ks, vs)
    else:
        cache = (jnp.asarray(rs.randn(L, B, Hkv, S, D), dtype),
                 jnp.asarray(rs.randn(L, B, Hkv, S, D), dtype))
    return q, k_new, v_new, cache


def _fallback(q, k_new, v_new, cache, layer, idx):
    """The einsum path of models._common.cached_attention, decode branch
    (q [B,1,Hq,D], chunk already in buffer layout)."""
    B, T, Hq, D = q.shape
    Hkv = k_new.shape[1]
    G = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    sl = tuple(c[layer] for c in cache)
    if len(cache) == 4:
        k_c, v_c, k_s, v_s = sl
        kc = k_c.astype(q.dtype) * k_s.astype(q.dtype)[..., None]
        vc = v_c.astype(q.dtype) * v_s.astype(q.dtype)[..., None]
    else:
        kc, vc = sl
    S = kc.shape[2]
    qh = q.transpose(0, 2, 1, 3).reshape(B, Hkv, G, T, D)
    neg = jnp.finfo(jnp.float32).min
    s_c = jnp.einsum("bkgtd,bksd->bkgts", qh, kc) * scale
    s_c = jnp.where((jnp.arange(S) < idx)[None, None, None, None, :],
                    s_c.astype(jnp.float32), neg)
    s_n = (jnp.einsum("bkgtd,bkud->bkgtu", qh, k_new) * scale
           ).astype(jnp.float32)
    probs = jax.nn.softmax(jnp.concatenate([s_c, s_n], -1), axis=-1)
    p_c = probs[..., :S].astype(q.dtype)
    p_n = probs[..., S:].astype(q.dtype)
    out = (jnp.einsum("bkgts,bksd->bkgtd", p_c, vc)
           + jnp.einsum("bkgtu,bkud->bkgtd", p_n, v_new))
    return out.reshape(B, Hq, T, D).transpose(0, 2, 1, 3)


@pytest.mark.parametrize("quant", [False, True])
@pytest.mark.parametrize("idx", [1, 37, 128, 255])
def test_kernel_matches_fallback(quant, idx):
    q, kn, vn, cache = _mk(quant=quant)
    with _support.force_dispatch():
        assert dk.supported(q, cache)
        got = dk.decode_attention(q, kn, vn, cache, jnp.int32(0),
                                  jnp.int32(idx), scale=1.0 / 8.0)
    want = _fallback(q, kn, vn, cache, 0, idx)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("quant", [False, True])
def test_kernel_selects_layer(quant):
    """The scalar-prefetched layer id must pick layer l's buffers out of
    the stack — each layer's output must match that layer's fallback."""
    q, kn, vn, cache = _mk(L=3, quant=quant, seed=7)
    for l in range(3):
        with _support.force_dispatch():
            got = dk.decode_attention(q, kn, vn, cache, jnp.int32(l),
                                      jnp.int32(90), scale=0.125)
        want = _fallback(q, kn, vn, cache, l, 90)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5, err_msg=f"l={l}")


def test_kernel_gqa_group_mapping():
    """Hq=8, Hkv=2 (G=4): each q head must read ITS kv head's cache."""
    q, kn, vn, cache = _mk(Hq=8, Hkv=2, seed=3)
    with _support.force_dispatch():
        got = dk.decode_attention(q, kn, vn, cache, jnp.int32(1),
                                  jnp.int32(100), scale=0.125)
    want = _fallback(q, kn, vn, cache, 1, 100)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_kernel_ignores_stale_positions():
    """Positions >= index must not contribute: poisoning them with huge
    values changes nothing."""
    q, kn, vn, cache = _mk(seed=1)
    idx = 64
    k, v = np.asarray(cache[0]).copy(), np.asarray(cache[1]).copy()
    k[:, :, :, idx:] = 1e4
    v[:, :, :, idx:] = -1e4
    poisoned = (jnp.asarray(k), jnp.asarray(v))
    with _support.force_dispatch():
        a = dk.decode_attention(q, kn, vn, cache, jnp.int32(0),
                                jnp.int32(idx), scale=0.125)
        b = dk.decode_attention(q, kn, vn, poisoned, jnp.int32(0),
                                jnp.int32(idx), scale=0.125)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_supported_gates():
    q, _, _, cache = _mk()
    with _support.force_dispatch():
        assert dk.supported(q, cache)
        # prefill chunk (T > 1) is not the kernel's job
        assert not dk.supported(jnp.zeros((2, 4, 8, 64)), cache)
        # head_dim off the MXU grid
        assert not dk.supported(jnp.zeros((2, 1, 8, 32)), (
            jnp.zeros((2, 2, 4, 256, 32)),) * 2)
        # S not blockable
        assert not dk.supported(jnp.zeros((2, 1, 8, 64)), (
            jnp.zeros((2, 2, 4, 100, 64)),) * 2)
    # no dispatch context off-TPU → fallback (on a TPU host the bare
    # call legitimately dispatches)
    if not _support.on_tpu():
        assert not dk.supported(q, cache)


def test_cached_attention_dispatches_kernel(monkeypatch):
    """models._common.cached_attention must route supported decode
    shapes through the kernel (and produce the same payload/out as the
    fallback it replaces)."""
    rs = np.random.RandomState(5)
    B, Hq, Hkv, S, D, L = 2, 4, 4, 128, 64, 2
    q = jnp.asarray(rs.randn(B, 1, Hq, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, 1, Hkv, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, 1, Hkv, D), jnp.float32)
    cache = (jnp.asarray(rs.randn(L, B, Hkv, S, D), jnp.float32),
             jnp.asarray(rs.randn(L, B, Hkv, S, D), jnp.float32))
    calls = {}
    orig = dk.decode_attention

    def spy(*a, **kw):
        calls["hit"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(dk, "decode_attention", spy)
    with _support.force_dispatch():
        out_k, pay_k = _common.cached_attention(q, k, v, cache,
                                                jnp.int32(50), layer=1)
    assert calls.get("hit")
    out_f, pay_f = _common.cached_attention(q, k, v, cache, jnp.int32(50),
                                            layer=1)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_f),
                               rtol=2e-5, atol=2e-5)
    for a, b in zip(pay_k, pay_f):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "quant", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_kernel_under_vmap_matches_per_slot(quant):
    """The GenerationEngine's fused decode vmaps
    ``forward_with_cache`` over the slot axis, so on TPU the kernel is
    invoked under ``jax.vmap`` with per-slot caches and fill positions.
    jax's pallas batching rule must reproduce the per-slot calls (and
    the einsum fallback) exactly — the gap CHANGES r5 flagged as
    untested."""
    SLOTS = 3
    qs, kns, vns, caches, idxs = [], [], [], [], [1, 100, 255]
    for s in range(SLOTS):
        q, kn, vn, cache = _mk(B=1, quant=quant, seed=10 + s)
        qs.append(q), kns.append(kn), vns.append(vn), caches.append(cache)
    q = jnp.stack(qs)
    kn, vn = jnp.stack(kns), jnp.stack(vns)
    cache = tuple(jnp.stack([c[i] for c in caches])
                  for i in range(len(caches[0])))
    idx = jnp.asarray(idxs, jnp.int32)

    def one(q, kn, vn, cache, i):
        assert dk.supported(q, cache)      # gate holds under the tracer
        return dk.decode_attention(q, kn, vn, cache, jnp.int32(1), i,
                                   scale=0.125)

    with _support.force_dispatch():
        got = jax.jit(jax.vmap(one))(q, kn, vn, cache, idx)
        want = jnp.stack([
            dk.decode_attention(qs[s], kns[s], vns[s], caches[s],
                                jnp.int32(1), jnp.int32(idxs[s]),
                                scale=0.125)
            for s in range(SLOTS)])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for s in range(SLOTS):
        np.testing.assert_allclose(
            np.asarray(got[s]),
            np.asarray(_fallback(qs[s], kns[s], vns[s], caches[s], 1,
                                 idxs[s])),
            rtol=2e-5, atol=2e-5, err_msg=f"slot {s}")


def test_engine_fused_decode_dispatch_is_explicit(monkeypatch):
    """The engine's vmapped decode dispatches per backend and both arms
    are pinned: with the kernel set dispatching, the vmapped
    cached_attention routes through decode_attention; without it (plain
    CPU), supported() gates False under the same vmap and the einsum
    fallback produces matching numbers."""
    rs = np.random.RandomState(9)
    SLOTS, B, Hq, Hkv, S, D, L = 2, 1, 4, 4, 128, 64, 2
    q = jnp.asarray(rs.randn(SLOTS, B, 1, Hq, D), jnp.float32)
    k = jnp.asarray(rs.randn(SLOTS, B, 1, Hkv, D), jnp.float32)
    v = jnp.asarray(rs.randn(SLOTS, B, 1, Hkv, D), jnp.float32)
    cache = tuple(jnp.asarray(rs.randn(SLOTS, L, B, Hkv, S, D),
                              jnp.float32) for _ in range(2))
    idx = jnp.asarray([17, 90], jnp.int32)
    calls = {}
    orig = dk.decode_attention

    def spy(*a, **kw):
        calls["n"] = calls.get("n", 0) + 1
        return orig(*a, **kw)

    monkeypatch.setattr(dk, "decode_attention", spy)

    def one(q, k, v, cache, i):
        out, _ = _common.cached_attention(q, k, v, cache, i, layer=1)
        return out

    with _support.force_dispatch():
        kernel_out = jax.vmap(one)(q, k, v, cache, idx)
    assert calls.get("n", 0) >= 1          # kernel arm engaged
    calls.clear()
    fallback_out = jax.vmap(one)(q, k, v, cache, idx)   # plain CPU
    assert calls.get("n", 0) == 0          # fallback arm: gate said no
    np.testing.assert_allclose(np.asarray(kernel_out),
                               np.asarray(fallback_out),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("cache_dtype", [None, jnp.int8])
def test_partitioned_kernel_under_tp_mesh(devices8, cache_dtype):
    """TP-sharded serving keeps the kernel: under a tp2 mesh with
    force_dispatch, generate() routes decode steps through the
    custom_partitioning wrapper (per-shard kernels, stats prove it) and
    reproduces the single-device tokens exactly — bf16 and int8 cache
    layouts (scales shard with the heads). Shapes sized to the kernel
    gate (prompt 120 + 8 new = S 128, D=64)."""
    import paddle_tpu
    from jax.sharding import NamedSharding
    from paddle_tpu import partition_specs
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.parallel import mesh as M
    from paddle_tpu.ops.pallas import _partition

    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=256, num_layers=2,
                           num_heads=4, num_kv_heads=2, max_seq_len=128)
    m = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 96, (2, 120))
                      .astype(np.int32))
    ref = np.asarray(generate(m, ids, 8, cache_dtype=cache_dtype))

    mesh = M.create_mesh({"tp": 2, "dp": 1}, jax.devices()[:2])
    sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), partition_specs(m),
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    m_sh = jax.device_put(m, sh)
    with M.MeshContext(mesh):
        with _support.force_dispatch():
            _partition.reset_stats()
            out = np.asarray(jax.jit(
                lambda mm, i: generate(mm, i, 8,
                                       cache_dtype=cache_dtype))(m_sh, ids))
        hits = dict(_partition.stats)
    assert hits.get("decode_attn:kernel", 0) > 0, hits
    np.testing.assert_array_equal(out, ref)
