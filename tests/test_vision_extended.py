"""Extended vision transforms + datasets against synthetic fixtures in
the real wire formats (CIFAR pickled tar, class folders, VOC-style)."""

import io
import os
import pickle
import tarfile

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.nn.functional as F

from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.datasets import (
    Cifar10, Cifar100, DatasetFolder, FashionMNIST, ImageFolder,
)


def _img(h=8, w=8, c=3, seed=0):
    return np.random.RandomState(seed).randint(0, 255, (h, w, c)) \
        .astype(np.uint8)


def test_resize_bilinear_matches_torch_golden():
    """Bilinear host resize reproduces the cv2 INTER_LINEAR /
    align_corners=False half-pixel convention (the reference's
    functional_cv2.resize backend) — checked against torch interpolate
    with antialias=False, which implements the same sampling. (Not
    jax.image.resize: that one low-pass filters on downsample, which
    cv2's INTER_LINEAR does not.)"""
    torch = pytest.importorskip("torch")

    rs = np.random.RandomState(3)
    img = rs.rand(9, 13, 3).astype(np.float32)
    t = torch.from_numpy(img.transpose(2, 0, 1))[None]
    for size in [(4, 7), (18, 26), (9, 13), (5, 5)]:
        ours = T.resize(img, size, "bilinear")
        golden = torch.nn.functional.interpolate(
            t, size=size, mode="bilinear", align_corners=False,
            antialias=False)[0].numpy().transpose(1, 2, 0)
        np.testing.assert_allclose(ours, golden, rtol=1e-5, atol=1e-5)


def test_resize_int_is_shorter_edge():
    img = _img(16, 24)
    out = T.resize(img, 8)
    assert out.shape == (8, 12, 3)      # shorter edge 16 -> 8, aspect kept
    tall = T.resize(_img(24, 16), 8)
    assert tall.shape == (12, 8, 3)
    same = T.resize(_img(8, 12), 8)     # already at size: no-op
    assert same.shape == (8, 12, 3)


def test_resize_dtypes_and_modes():
    img = _img(8, 8)
    out = T.resize(img, (4, 4))
    assert out.dtype == np.uint8        # ints round-trip their dtype
    near = T.resize(img, (4, 4), "nearest")
    assert near.dtype == np.uint8
    # nearest picks exact source pixels
    assert set(near.ravel()) <= set(img.ravel())
    gray = T.resize(img[:, :, 0], (4, 4))
    assert gray.shape == (4, 4)         # 2D in, 2D out
    with pytest.raises(ValueError, match="interpolation"):
        T.resize(img, (4, 4), "lanczos")


def test_resize_class_chw_bilinear():
    chw = _img(10, 14).transpose(2, 0, 1).astype(np.float32)
    out = T.Resize((5, 7))(chw)
    assert out.shape == (3, 5, 7)
    golden = T.resize(chw.transpose(1, 2, 0), (5, 7)).transpose(2, 0, 1)
    np.testing.assert_allclose(out, golden)


def test_to_tensor_and_transpose():
    x = _img()
    t = T.ToTensor()(x)
    assert t.shape == (3, 8, 8) and t.dtype == np.float32
    assert 0 <= t.min() and t.max() <= 1.0
    tr = T.Transpose()(x)
    assert tr.shape == (3, 8, 8)


def test_pad_and_flips():
    x = _img()
    p = T.Pad((1, 2, 3, 4))(x)     # l, t, r, b
    assert p.shape == (8 + 2 + 4, 8 + 1 + 3, 3)
    np.random.seed(0)
    v = T.RandomVerticalFlip(prob=1.0)(x)
    np.testing.assert_array_equal(v, x[::-1])


def test_color_transforms_preserve_shape_dtype():
    x = _img()
    np.random.seed(1)
    for t in (T.BrightnessTransform(0.4), T.ContrastTransform(0.4),
              T.SaturationTransform(0.4), T.HueTransform(0.25),
              T.ColorJitter(0.2, 0.2, 0.2, 0.1)):
        y = t(x)
        assert y.shape == x.shape and y.dtype == np.uint8

    g = T.Grayscale(3)(x)
    assert g.shape == x.shape
    assert np.allclose(g[..., 0], g[..., 1])


def test_hue_zero_is_identity_and_rotation():
    x = _img()
    np.random.seed(0)
    y = T.HueTransform(0.0)(x)
    np.testing.assert_array_equal(np.asarray(y), x)
    np.random.seed(0)
    r = T.RandomRotation(30)(x)
    assert r.shape == x.shape


def test_random_resized_crop():
    np.random.seed(2)
    out = T.RandomResizedCrop(4)(_img(16, 16))
    assert out.shape == (4, 4, 3)


def _cifar_tar(path, prefix, label_key, n=20):
    rs = np.random.RandomState(0)
    batch = {b"data": rs.randint(0, 255, (n, 3072)).astype(np.uint8),
             label_key: rs.randint(0, 10, n).tolist()}
    blob = pickle.dumps(batch)
    with tarfile.open(path, "w:gz") as tf:
        info = tarfile.TarInfo(f"cifar/{prefix}_1" if "data" in prefix
                               else f"cifar/{prefix}")
        info.size = len(blob)
        tf.addfile(info, io.BytesIO(blob))
    return batch


def test_cifar10_parses_batches(tmp_path):
    p = tmp_path / "cifar10.tar.gz"
    batch = _cifar_tar(str(p), "data_batch", b"labels")
    ds = Cifar10(str(p), mode="train")
    assert len(ds) == 20
    img, label = ds[0]
    assert img.shape == (3, 32, 32)
    np.testing.assert_array_equal(
        img.astype(np.uint8).reshape(-1), batch[b"data"][0])
    assert int(label) == batch[b"labels"][0]


def test_cifar100_fine_labels(tmp_path):
    p = tmp_path / "cifar100.tar.gz"
    _cifar_tar(str(p), "train", b"fine_labels")
    ds = Cifar100(str(p), mode="train")
    assert len(ds) == 20
    _, label = ds[5]
    assert 0 <= int(label) < 10


def test_dataset_folder_npy_and_transform(tmp_path):
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            np.save(d / f"{i}.npy", _img(seed=i))
    ds = DatasetFolder(str(tmp_path), transform=T.ToTensor())
    assert len(ds) == 6
    img, label = ds[0]
    assert img.shape == (3, 8, 8)
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    labels = sorted(int(ds[i][1]) for i in range(6))
    assert labels == [0, 0, 0, 1, 1, 1]
    assert ImageFolder is DatasetFolder


def test_fashion_mnist_is_mnist_format(tmp_path):
    # FashionMNIST shares the idx loader; absent files raise cleanly
    with pytest.raises(FileNotFoundError):
        FashionMNIST(str(tmp_path))


# ---------------------------------------------------------------------------
# deformable conv (r4, reference deformable_conv_op.cu) + general
# adaptive pooling (reference pool_op.cc adaptive attr)
# ---------------------------------------------------------------------------

class TestDeformConv2d:
    def _data(self, B=2, C=4, Cout=6, H=7, W=9, dg=1, seed=0):
        rs = np.random.RandomState(seed)
        x = jnp.asarray(rs.randn(B, C, H, W).astype(np.float32))
        w = jnp.asarray(0.3 * rs.randn(Cout, C, 3, 3).astype(np.float32))
        b = jnp.asarray(rs.randn(Cout).astype(np.float32))
        off = jnp.asarray(
            0.7 * rs.randn(B, 2 * dg * 9, H, W).astype(np.float32))
        return x, w, b, off

    def test_zero_offset_equals_conv2d(self):
        from paddle_tpu.vision.ops import deform_conv2d

        x, w, b, _ = self._data()
        got = deform_conv2d(x, jnp.zeros((2, 18, 7, 9)), w, b,
                            stride=1, padding=1)
        want = F.conv2d(x, w, b, stride=1, padding=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_integer_offset_equals_shifted_conv_interior(self):
        from paddle_tpu.vision.ops import deform_conv2d

        x, w, _, _ = self._data()
        one = jnp.ones((2, 18, 7, 9), jnp.float32)
        got = deform_conv2d(x, one, w, None, stride=1, padding=1)
        x_s = jnp.pad(x, ((0, 0), (0, 0), (0, 1), (0, 1)))[:, :, 1:, 1:]
        want = F.conv2d(x_s, w, None, stride=1, padding=1)
        np.testing.assert_allclose(
            np.asarray(got[:, :, 2:-2, 2:-2]),
            np.asarray(want[:, :, 2:-2, 2:-2]), rtol=1e-5, atol=1e-5)

    def test_bilinear_linearity(self):
        """offset +0.5 must equal the mean of offsets 0 and +1 — the
        bilinear interpolation identity, everywhere incl. borders."""
        from paddle_tpu.vision.ops import deform_conv2d

        x, w, _, _ = self._data()
        z = jnp.zeros((2, 18, 7, 9), jnp.float32)
        half = z.at[:, 0::2].set(0.5)
        oney = z.at[:, 0::2].set(1.0)
        gh = deform_conv2d(x, half, w, None, 1, 1)
        g0 = deform_conv2d(x, z, w, None, 1, 1)
        g1 = deform_conv2d(x, oney, w, None, 1, 1)
        np.testing.assert_allclose(np.asarray(gh),
                                   np.asarray(0.5 * (g0 + g1)),
                                   rtol=1e-5, atol=1e-5)

    def test_mask_modulation_and_groups(self):
        from paddle_tpu.vision.ops import deform_conv2d

        x, w, b, off = self._data(dg=2)
        m = jnp.asarray(np.random.RandomState(1).rand(
            2, 2 * 9, 7, 9).astype(np.float32))
        out = deform_conv2d(x, off, w, b, 1, 1, deformable_groups=2,
                            mask=m)
        assert out.shape == (2, 6, 7, 9)
        assert np.all(np.isfinite(np.asarray(out)))
        # mask=0 kills everything but the bias
        out0 = deform_conv2d(x, off, w, b, 1, 1, deformable_groups=2,
                             mask=jnp.zeros_like(m))
        np.testing.assert_allclose(
            np.asarray(out0),
            np.broadcast_to(np.asarray(b).reshape(1, -1, 1, 1),
                            out0.shape), atol=1e-6)

    def test_fd_gradients(self):
        from paddle_tpu.vision.ops import deform_conv2d
        from tests.op_test import check_grad

        x, w, _, off = self._data(B=1, C=2, Cout=2, H=5, W=5)

        def fn(x, off, w):
            return deform_conv2d(x, off, w, None, stride=1, padding=1)

        check_grad(fn, [x, off, w], wrt=(0, 1, 2))

    def test_stride_padding_dilation(self):
        from paddle_tpu.vision.ops import deform_conv2d

        x, w, _, _ = self._data(H=9, W=9)
        z = jnp.zeros((2, 18, 5, 5), jnp.float32)   # Ho=Wo=5 @ stride 2
        got = deform_conv2d(x, z, w, None, stride=2, padding=1)
        want = F.conv2d(x, w, None, stride=2, padding=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestGeneralAdaptivePool:
    """Non-divisible output sizes (torch/paddle bin semantics:
    bin i = [floor(i·D/O), ceil((i+1)·D/O)))."""

    @staticmethod
    def _ref_pool1d(row, out, op):
        import math as _m

        vals = []
        d = len(row)
        for i in range(out):
            lo = (i * d) // out
            hi = _m.ceil((i + 1) * d / out)
            seg = row[lo:hi]
            vals.append(max(seg) if op == "max" else sum(seg) / len(seg))
        return np.array(vals, np.float32)

    @pytest.mark.parametrize("dim,out", [(10, 3), (7, 5), (5, 5), (9, 4)])
    @pytest.mark.parametrize("op", ["avg", "max"])
    def test_1d_matches_reference(self, dim, out, op):
        rs = np.random.RandomState(0)
        x = rs.randn(2, 3, dim).astype(np.float32)
        fn = (F.adaptive_avg_pool1d if op == "avg"
              else F.adaptive_max_pool1d)
        got = np.asarray(fn(jnp.asarray(x), out))
        want = np.stack([
            np.stack([self._ref_pool1d(list(x[b, c]), out, op)
                      for c in range(3)]) for b in range(2)])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_2d_non_divisible(self):
        rs = np.random.RandomState(1)
        x = rs.randn(2, 3, 7, 10).astype(np.float32)
        got = np.asarray(F.adaptive_avg_pool2d(jnp.asarray(x), (3, 4)))
        assert got.shape == (2, 3, 3, 4)
        # every output bin is a mean of its reference window
        want_00 = x[:, :, 0:3, 0:3].mean(axis=(2, 3))   # ceil(7/3)=3, ceil(10/4)=3
        np.testing.assert_allclose(got[:, :, 0, 0], want_00, rtol=1e-5)
        got_max = np.asarray(F.adaptive_max_pool2d(jnp.asarray(x), (3, 4)))
        np.testing.assert_allclose(
            got_max[:, :, 0, 0], x[:, :, 0:3, 0:3].max(axis=(2, 3)),
            rtol=1e-5)

    def test_nhwc_and_divisible_fast_path(self):
        rs = np.random.RandomState(2)
        x = rs.randn(2, 8, 6, 3).astype(np.float32)    # NHWC
        got = np.asarray(F.adaptive_avg_pool2d(jnp.asarray(x), (4, 3),
                                               data_format="NHWC"))
        assert got.shape == (2, 4, 3, 3)
        x2 = rs.randn(1, 2, 8, 8).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(F.adaptive_avg_pool2d(jnp.asarray(x2), (2, 2))),
            x2.reshape(1, 2, 2, 4, 2, 4).mean(axis=(3, 5)), rtol=1e-5)


def test_psroi_pool_matches_naive():
    """Golden check vs a direct python implementation of the reference
    semantics (psroi_pool_op.cc): rounded roi coords, floor/ceil bin
    rectangles, per-bin channel group c*ph*pw + i*pw + j, empty bin
    -> 0."""
    from paddle_tpu.vision.ops import psroi_pool

    rs = np.random.RandomState(0)
    C_out, ph, pw, H, W = 3, 2, 2, 9, 11
    feats = rs.randn(2, C_out * ph * pw, H, W).astype(np.float32)
    # half-integer coords exercise the C-round (half-away-from-zero)
    # semantics where numpy/python round-half-to-even would differ
    rois = np.array([[0.0, 0.0, 7.9, 5.2],
                     [2.5, 1.5, 9.5, 8.0],
                     [4.0, 4.0, 4.2, 4.2]], np.float32)
    bidx = np.array([0, 1, 0], np.int32)
    scale = 0.5

    out = np.asarray(psroi_pool(jnp.asarray(feats), jnp.asarray(rois),
                                jnp.asarray(bidx), C_out, (ph, pw),
                                spatial_scale=scale))

    def round_away(v):  # C round(): half away from zero
        return np.sign(v) * np.floor(np.abs(v) + 0.5)

    want = np.zeros((3, C_out, ph, pw), np.float32)
    for r in range(3):
        x1 = round_away(rois[r, 0]) * scale
        y1 = round_away(rois[r, 1]) * scale
        x2 = (round_away(rois[r, 2]) + 1.0) * scale
        y2 = (round_away(rois[r, 3]) + 1.0) * scale
        rh = max(y2 - y1, 0.1)
        rw = max(x2 - x1, 0.1)
        for c in range(C_out):
            for i in range(ph):
                for j in range(pw):
                    hs = int(np.clip(np.floor(i * rh / ph + y1), 0, H))
                    he = int(np.clip(np.ceil((i + 1) * rh / ph + y1), 0, H))
                    ws = int(np.clip(np.floor(j * rw / pw + x1), 0, W))
                    we = int(np.clip(np.ceil((j + 1) * rw / pw + x1), 0, W))
                    ch = c * ph * pw + i * pw + j
                    region = feats[bidx[r], ch, hs:he, ws:we]
                    want[r, c, i, j] = region.mean() if region.size else 0.0
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_prroi_pool_matches_dense_integration():
    """PrRoIPool computes the EXACT integral of the bilinear surface;
    a dense Riemann sum over the same surface must converge to it."""
    from paddle_tpu.vision.ops import prroi_pool

    rs = np.random.RandomState(1)
    C, H, W = 2, 8, 10
    feats = rs.randn(1, C, H, W).astype(np.float32)
    rois = np.array([[1.2, 0.7, 7.6, 5.9]], np.float32)
    bidx = np.array([0], np.int32)
    ph = pw = 2

    out = np.asarray(prroi_pool(jnp.asarray(feats), jnp.asarray(rois),
                                jnp.asarray(bidx), (ph, pw)))

    def bilinear(c, y, x):
        # zero-padded outside, hat-function form
        total = 0.0
        for h in range(max(0, int(np.floor(y))),
                       min(H, int(np.floor(y)) + 2)):
            for w in range(max(0, int(np.floor(x))),
                           min(W, int(np.floor(x)) + 2)):
                wy = max(0.0, 1.0 - abs(y - h))
                wx = max(0.0, 1.0 - abs(x - w))
                total += feats[0, c, h, w] * wy * wx
        return total

    n = 80
    x1, y1, x2, y2 = rois[0]
    bh, bw = (y2 - y1) / ph, (x2 - x1) / pw
    want = np.zeros((C, ph, pw), np.float32)
    for c in range(C):
        for i in range(ph):
            for j in range(pw):
                ys = y1 + i * bh + (np.arange(n) + 0.5) * bh / n
                xs = x1 + j * bw + (np.arange(n) + 0.5) * bw / n
                acc = sum(bilinear(c, y, x) for y in ys for x in xs)
                want[c, i, j] = acc / (n * n)
    np.testing.assert_allclose(out[0], want, rtol=2e-3, atol=2e-3)


def test_prroi_pool_roi_gradients_flow():
    """The PrRoI selling point: gradients w.r.t. the roi COORDINATES
    exist (exact integral, no sampling) — finite and nonzero."""
    from paddle_tpu.vision.ops import prroi_pool

    rs = np.random.RandomState(2)
    feats = jnp.asarray(rs.randn(1, 2, 8, 8).astype(np.float32))
    bidx = jnp.asarray([0], jnp.int32)

    def f(rois):
        return jnp.sum(prroi_pool(feats, rois, bidx, 2))

    import jax
    g = jax.grad(f)(jnp.asarray([[1.0, 1.0, 6.0, 6.0]], jnp.float32))
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).max()) > 0.0


def test_prroi_pool_degenerate_bin_is_zero():
    from paddle_tpu.vision.ops import prroi_pool

    feats = jnp.ones((1, 1, 6, 6), jnp.float32)
    rois = jnp.asarray([[2.0, 2.0, 2.0, 5.0]], jnp.float32)  # zero width
    out = prroi_pool(feats, rois, jnp.asarray([0], jnp.int32), 2)
    np.testing.assert_array_equal(np.asarray(out), 0.0)
