"""Paged KV cache: allocator/refcount/prefix-cache edge cases.

The invariant under test everywhere: the page pool leaks nothing. Every
path that abandons a generation — cancel mid-chunked-prefill, poll-TTL
expiry mid-prefill, sharers retiring in either order, prefix eviction
under pool pressure — must return the pool to exactly its prior
occupancy (plus any pages the prefix cache legitimately retains, which
``clear_prefix_cache`` then drains).
"""

import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core.monitor import get_stat
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import (
    generate, init_paged_cache, paged_gather, paged_scatter,
)
from paddle_tpu.serving import GenerationEngine
from paddle_tpu.serving.engine import _PagePool, _PrefixCache

pytestmark = pytest.mark.gen

VOCAB = 96


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(11)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _drain(engine, gid, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gid, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


# -- host-side allocator ----------------------------------------------------

def test_page_pool_alloc_release_refcount():
    pool = _PagePool(4)
    assert pool.free_count == 4
    a = pool.alloc(3)
    assert sorted(a) == [1, 2, 3] and pool.free_count == 1
    pool.retain(a[0])                      # a second holder
    pool.release(a[0])
    assert pool.free_count == 1            # still referenced
    pool.release(a[0])
    assert pool.free_count == 2            # now actually free
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.alloc(3)
    pool.release(a[1])
    pool.release(a[2])
    assert pool.free_count == 4
    with pytest.raises(AssertionError, match="underflow"):
        pool.release(a[1])


def test_prefix_cache_chain_match_and_leaf_eviction():
    P = 4
    pool = _PagePool(8)
    cache = _PrefixCache(P)
    prompt = np.arange(12, dtype=np.int32)          # 3 full pages
    pages = pool.alloc(3)
    cache.insert(prompt, pages, pool)               # cache holds +1 each
    assert len(cache) == 3
    for pid in pages:                               # gen retires
        pool.release(pid)
    assert pool.free_count == 5                     # cache keeps 3 alive

    # chain semantics: a prompt diverging inside page 2 matches 1 page
    div = prompt.copy()
    div[6] = 77
    m = cache.match(div, pool)
    assert len(m) == 1 and m[0] == pages[0]
    pool.release(m[0])
    # full prefix (longer prompt) matches all 3; a 12-token prompt is
    # capped at (12 - 1) // 4 = 2 so one token remains to prefill
    m = cache.match(np.arange(13, dtype=np.int32), pool)
    assert m == pages
    for pid in m:
        pool.release(pid)
    m = cache.match(prompt, pool)
    assert m == pages[:2]
    for pid in m:
        pool.release(pid)

    # eviction is leaf-first: evicting 1 must free the CHAIN TAIL (page
    # 3), never a parent another entry still chains through
    freed = cache.evict(1, pool)
    assert freed == 1 and len(cache) == 2
    assert pool.refcount(pages[2]) == 0
    assert pool.refcount(pages[0]) == 1 and pool.refcount(pages[1]) == 1
    # a retained page (live generation) is not evictable
    m = cache.match(prompt, pool)
    assert m == pages[:2]
    assert cache.evict(8, pool) == 0       # both held by the "gen"
    for pid in m:
        pool.release(pid)
    assert cache.evict(8, pool) == 2
    assert pool.free_count == 8 and len(cache) == 0


# -- gather/scatter cache contract ------------------------------------------

@pytest.mark.parametrize("quant", [False, True])
def test_paged_gather_scatter_roundtrip(quant):
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    L, Hkv, S, D, P = 2, 2, 32, 4, 8
    dtype = jnp.int8 if quant else jnp.float32
    from paddle_tpu.models._common import init_kv_cache
    proto = init_kv_cache(L, 1, S, Hkv, D, dtype)
    pool = init_paged_cache(proto, num_pages=6, page_tokens=P)
    assert pool[0].shape == (7, L, Hkv, P, D)       # +1 null page
    if quant:
        assert pool[2].shape == (7, L, Hkv, P)      # scales follow

    table = jnp.asarray([3, 1, 5, 2], jnp.int32)
    chunk = tuple(
        jnp.asarray((rs.randn(L, 1, Hkv, 11, *leaf.shape[4:]) * 10)
                    .astype(leaf.dtype))
        for leaf in pool)
    pool2 = paged_scatter(pool, table, chunk, index=5, page_tokens=P,
                          length=jnp.asarray(11, jnp.int32))
    view = paged_gather(pool2, table)
    for v, ch in zip(view, chunk):
        assert v.shape[3] == 4 * P
        np.testing.assert_array_equal(np.asarray(v[:, :, :, 5:16]),
                                      np.asarray(ch))
    # null page absorbed nothing mapped: pages NOT in the table stayed 0
    for pid in (4, 6):
        assert not np.asarray(pool2[0][pid]).any()


def test_paged_scatter_padding_goes_to_null_page():
    """Writes past the true length land on the reserved null page, so a
    right-padded chunk can never clobber a live page — even when the
    padded window runs past the table."""
    import jax.numpy as jnp

    from paddle_tpu.models._common import init_kv_cache
    P = 4
    proto = init_kv_cache(1, 1, 8, 1, 2, jnp.float32)
    pool = init_paged_cache(proto, num_pages=2, page_tokens=P)
    table = jnp.asarray([1, 2], jnp.int32)
    chunk = tuple(jnp.ones((1, 1, 1, 6, 2), jnp.float32) * 7
                  for _ in range(2))
    pool2 = paged_scatter(pool, table, chunk, index=3, page_tokens=P,
                          length=jnp.asarray(2, jnp.int32))
    k = np.asarray(pool2[0])
    assert (k[1, 0, 0, 3] == 7).all() and (k[2, 0, 0, 0] == 7).all()
    assert not k[2, 0, 0, 1:].any()         # padding went to page 0
    assert k[0].any()                       # ...the null page took it


# -- engine edge cases ------------------------------------------------------

def _paced_engine(model, **kw):
    """Small pages + tiny chunks + a paced loop so 'mid-prefill' is a
    real window; prefix cache off unless a test opts in, so pool
    accounting is exact."""
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("queue_max", 8)
    kw.setdefault("paged", True)
    kw.setdefault("page_tokens", 4)
    kw.setdefault("prefill_chunk", 2)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("step_wait_s", 0.03)
    return GenerationEngine(model, **kw)


def _start_pacer(engine, rs):
    """A long-running decode stream that keeps the loop iterating (and
    sleeping step_wait_s per iteration) so chunked prefill of a later
    admit is observably incremental."""
    return engine.start(rs.randint(0, VOCAB, (4,)).astype(np.int32), 50)


def test_cancel_mid_chunked_prefill_frees_all_pages(model):
    rs = np.random.RandomState(30)
    with _paced_engine(model) as eng:
        total = eng.stats()["pages"]
        pacer = _start_pacer(eng, rs)
        victim = eng.start(rs.randint(0, VOCAB, (40,)).astype(np.int32),
                           8)
        # wait until the victim is genuinely mid-prefill (>= 2 chunks
        # in, well short of its 40-token prompt), then cancel
        assert _wait(lambda: victim in eng._gens
                     and eng._gens[victim].prefill_pos >= 4)
        assert eng._gens[victim].prefill_pos < 40
        ev0 = get_stat("gen/evictions")
        assert eng.cancel(victim)
        assert get_stat("gen/evictions") == ev0 + 1
        # every page the victim reserved came back; only the pacer holds
        pacer_pages = -(-(4 + 50) // 4)
        assert _wait(lambda: eng.stats()["pages_free"]
                     == total - pacer_pages)
        eng.cancel(pacer)
        assert _wait(lambda: eng.stats()["pages_free"] == total)
        assert eng.stats()["active"] == 0


@pytest.mark.slow
def test_ttl_expiry_mid_chunked_prefill_frees_all_pages(model):
    rs = np.random.RandomState(31)
    with _paced_engine(model, ttl_s=0.35) as eng:
        total = eng.stats()["pages"]
        victim = eng.start(rs.randint(0, VOCAB, (48,)).astype(np.int32),
                           8)
        pacer = _start_pacer(eng, rs)

        def mid_prefill():
            if victim not in eng._gens:
                return False
            eng.poll(victim)      # keep it alive while prefill ramps
            return eng._gens[victim].prefill_pos >= 4

        assert _wait(mid_prefill)
        # never poll the victim again: the TTL must reap it mid-prefill
        ev0 = get_stat("gen/evictions")
        assert _wait(lambda: victim not in eng._gens, timeout=8.0)
        assert get_stat("gen/evictions") >= ev0 + 1
        pacer_gen = eng._gens.get(pacer)
        while pacer_gen is not None and not pacer_gen.done:
            eng.poll(pacer, wait_s=0.2)     # keep the pacer alive
            if eng.stats()["pages_free"] == total - -(-(4 + 50) // 4):
                break
        eng.cancel(pacer)
        assert _wait(lambda: eng.stats()["pages_free"] == total)


@pytest.mark.slow
def test_sharer_refcounts_either_retire_order(model):
    """Two generations sharing cached prefix pages retire in either
    order; the pages survive until the cache itself lets go."""
    rs = np.random.RandomState(32)
    prefix = rs.randint(0, VOCAB, (9,)).astype(np.int32)   # 2 full pages
    tails = [rs.randint(0, VOCAB, (2,)).astype(np.int32) for _ in range(2)]
    for first_retires in (0, 1):
        with GenerationEngine(model, slots=2, max_len=64, queue_max=8,
                              paged=True, page_tokens=4, prefill_chunk=3,
                              prefix_cache=True,
                              step_wait_s=0.02) as eng:
            total = eng.stats()["pages"]
            # seed the prefix cache (runs to completion, registers pages)
            seed_gid = eng.start(np.concatenate([prefix, tails[0]]), 2)
            toks, err = _drain(eng, seed_gid)
            assert err is None
            assert eng.stats()["prefix_entries"] == 2
            shared = [e.page for e in eng._prefix._entries.values()]

            # two sharers in flight: each holds +1 on both shared pages
            gids = [eng.start(np.concatenate([prefix, tails[i]]), 12)
                    for i in (0, 1)]
            assert _wait(lambda: all(
                eng._gens[g].slot is not None
                and not eng._gens[g].prefilling for g in gids))
            for pid in shared:
                assert eng._pool.refcount(pid) == 3    # cache + 2 gens

            eng.cancel(gids[first_retires])
            for pid in shared:
                assert eng._pool.refcount(pid) == 2
            toks, err = _drain(eng, gids[1 - first_retires])
            assert err is None
            # solo-generate byte-identity survived the sharer's exit
            p = np.concatenate([prefix, tails[1 - first_retires]])
            ref = np.asarray(generate(model, p[None], 12))[0, len(p):]
            np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
            for pid in shared:
                assert eng._pool.refcount(pid) == 1    # cache only
            assert eng.stats()["pages_free"] == total - 2
            assert eng.clear_prefix_cache() == 2
            assert eng.stats()["pages_free"] == total


@pytest.mark.slow
def test_prefix_eviction_under_pool_pressure(model):
    """A pool-starved admit LRU-evicts cached prefix pages instead of
    stalling forever — and sheds only when live generations truly hold
    the pool."""
    rs = np.random.RandomState(33)
    with GenerationEngine(model, slots=2, max_len=32, queue_max=2,
                          paged=True, page_tokens=4, pages=8,
                          prefix_cache=True) as eng:
        # fill the cache: prompt of 8 -> 2 registered pages
        a = rs.randint(0, VOCAB, (8,)).astype(np.int32)
        toks, err = _drain(eng, eng.start(a, 4))
        assert err is None
        assert eng.stats()["prefix_entries"] == 2
        assert eng.stats()["pages_free"] == 6
        ev0 = get_stat("gen/prefix_evictions")
        # a request needing 7 of 8 pages: must evict at least one
        # cached page to fit
        b = rs.randint(0, VOCAB, (20,)).astype(np.int32)
        ref = np.asarray(generate(model, b[None], 8))[0, 20:]
        toks, err = _drain(eng, eng.start(b, 8))
        assert err is None
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        assert get_stat("gen/prefix_evictions") >= ev0 + 1
        assert eng.stats()["prefix_entries"] >= 1   # b registered pages


def test_start_rejects_request_larger_than_pool(model):
    with GenerationEngine(model, slots=2, max_len=32, paged=True,
                          page_tokens=4, pages=4) as eng:
        with pytest.raises(ValueError, match="pages"):
            eng.start(np.arange(10, dtype=np.int32), 16)   # needs 7 > 4
        # a fitting request still works
        toks, err = _drain(eng, eng.start(np.arange(6, dtype=np.int32),
                                          2))
        assert err is None and len(toks) == 2


@pytest.mark.slow
def test_admission_stalls_then_resumes_when_pages_free(model):
    """When live generations hold the whole pool the queue head waits
    (head-of-line) and admits as soon as a retire returns pages."""
    rs = np.random.RandomState(34)
    with GenerationEngine(model, slots=4, max_len=32, queue_max=8,
                          paged=True, page_tokens=4, pages=6,
                          prefix_cache=False, step_wait_s=0.02) as eng:
        # compile the solo reference FIRST: anything slow between the
        # holder pinning the pool and the waiter enqueueing would let
        # the holder finish and deflate the test
        holder_p = rs.randint(0, VOCAB, (8,)).astype(np.int32)
        waiter_p = rs.randint(0, VOCAB, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, waiter_p[None], 3))[0, 5:]
        holder = eng.start(holder_p, 14)           # 22 tokens -> 6 pages
        assert _wait(lambda: eng.stats()["pages_free"] == 0)
        waiter = eng.start(waiter_p, 3)            # 2 pages: must wait
        time.sleep(0.15)
        st = eng.stats()
        assert st["queued"] == 1 and eng._gens[waiter].slot is None
        eng.cancel(holder)                         # pages return
        toks, err = _drain(eng, waiter)
        assert err is None
        np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
        assert _wait(lambda: eng.stats()["pages_free"] == 6)


@pytest.mark.slow
@pytest.mark.parametrize("cache_dtype", ["int8"])
def test_paged_int8_cache_matches_solo(model, cache_dtype):
    """The quantized cache layout rides the same pool/page-table path
    (4 leaves: int8 buffers + f32 scales) — paged int8 decode matches
    solo int8 generate token-for-token."""
    import jax.numpy as jnp

    rs = np.random.RandomState(35)
    with GenerationEngine(model, slots=2, max_len=32, paged=True,
                          page_tokens=8, prefill_chunk=5,
                          cache_dtype=jnp.int8) as eng:
        assert len(eng._state["cache"]) == 4
        for n in (5, 11):
            p = rs.randint(0, VOCAB, (n,)).astype(np.int32)
            ref = np.asarray(generate(model, p[None], 6,
                                      cache_dtype=jnp.int8))[0, n:]
            toks, err = _drain(eng, eng.start(p, 6))
            assert err is None
            np.testing.assert_array_equal(np.asarray(toks, np.int32), ref)
