"""Multi-process worker for launcher tests (the dist_mnist.py pattern:
reference ``tests/unittests/dist_mnist.py`` driven by test_dist_base.py).

Run under ``python -m paddle_tpu.distributed.launch --nproc N``; trains a
tiny model data-parallel across N *processes* (1 CPU device each) and
writes its loss curve to ``$TOY_OUT/losses.<rank>.json``.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly one CPU device per process

import jax

# the axon TPU plugin outranks the env var; the config update is the
# authoritative platform switch (see tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.parallel import mesh as M


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "train"
    env = dist.init_parallel_env()

    if mode == "crash":
        # rank-1 dies; rank 0 would run forever — the launcher must tear
        # it down (watch_local_trainers behavior)
        if env.rank == 1:
            # hard exit: sys.exit would block in jax's atexit distributed-
            # shutdown barrier waiting for rank 0 (which is asleep) — a
            # real trainer crash doesn't run atexit either
            os._exit(3)
        import time
        time.sleep(300)
        return

    paddle_tpu.seed(0)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    mesh = M.create_mesh({"dp": jax.device_count()})

    rs = np.random.RandomState(0)
    x = rs.randn(16, 8).astype(np.float32)
    w_true = rs.randn(8, 1).astype(np.float32)
    y = x @ w_true

    def loss_fn(m, batch, training=True):
        pred = m(batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    with M.MeshContext(mesh):
        step = dist.fleet.build_train_step(
            model, optimizer=optim.SGD(0.05), loss_fn=loss_fn, mesh=mesh)
        state = step.init_state(model)
        batch = step.shard_batch({"x": jnp.asarray(x), "y": jnp.asarray(y)})
        losses = []
        for i in range(8):
            state, metrics = step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(metrics["loss"]))

    out_dir = os.environ.get("TOY_OUT", ".")
    with open(os.path.join(out_dir, f"losses.{env.rank}.json"), "w") as f:
        json.dump(losses, f)


if __name__ == "__main__":
    main()


def spawn_worker(out_dir):
    """Module-level worker for distributed.spawn tests."""
    env = dist.init_parallel_env()
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    f = jax.jit(shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                          in_specs=P(), out_specs=P()))
    out = f(jnp.asarray([1.0 * (env.rank + 1)]))
    # replicated psum: every rank sees sum over ranks
    with open(os.path.join(out_dir, f"spawn.{env.rank}.txt"), "w") as fh:
        fh.write(str(float(np.asarray(jax.device_get(out))[0])))
