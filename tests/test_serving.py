"""Inference serving: exported StableHLO models behind the TCP service.

Reference role: the C-API/AnalysisPredictor serving layer
(``inference/api/analysis_predictor.h:82``).
"""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.io import (
    InferenceClient, InferenceServer, Predictor, save_inference_model,
)


@pytest.fixture(scope="module")
def saved_mlp(tmp_path_factory):
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = str(tmp_path_factory.mktemp("srv") / "mlp")
    example = np.zeros((2, 4), np.float32)
    save_inference_model(path, net, [example])
    return path, net


def test_serving_matches_local_predictor(saved_mlp):
    path, net = saved_mlp
    server = InferenceServer({"mlp": path}).start()
    client = InferenceClient(server.endpoint)
    try:
        x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        (remote,) = client.infer("mlp", x)
        local = np.asarray(Predictor(path).run(x))
        np.testing.assert_allclose(remote, local, rtol=1e-6)
        # and the artifact reproduces the live model
        np.testing.assert_allclose(remote, np.asarray(net(x)), rtol=1e-5,
                                   atol=1e-6)
    finally:
        client.stop_server()
        client.close()


def test_serving_list_load_and_errors(saved_mlp, tmp_path):
    path, _ = saved_mlp
    server = InferenceServer().start()
    client = InferenceClient(server.endpoint)
    try:
        assert client.list_models() == {}
        client.load_model("m2", path)          # hot-load over the wire
        models = client.list_models()
        assert models["m2"]["inputs"][0]["shape"] == [2, 4]
        x = np.zeros((2, 4), np.float32)
        (y,) = client.infer("m2", x)
        assert y.shape == (2, 3)
        with pytest.raises(RuntimeError, match="no model"):
            client.infer("nope", x)
        with pytest.raises(RuntimeError, match="shape"):
            client.infer("m2", np.zeros((3, 4), np.float32))
        with pytest.raises(RuntimeError, match="dtype"):
            client.infer("m2", np.zeros((2, 4), np.float64))
        # server kept serving through the errors
        (y2,) = client.infer("m2", x)
        np.testing.assert_allclose(y2, y)
    finally:
        client.stop_server()
        client.close()


def test_serving_llm_generate_endpoint(tmp_path):
    """LLM serving end to end: the compiled greedy-decode loop
    (lax.fori_loop + static KV cache) exports to StableHLO and serves
    behind the TCP service — remote generations match local ones."""
    import jax.numpy as jnp

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.io import save_inference_model

    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=128, num_layers=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    prompt = np.random.RandomState(0).randint(0, 128, (2, 8)) \
        .astype(np.int32)
    path = str(tmp_path / "llm")
    save_inference_model(path, model, [prompt],
                         forward=lambda m, ids: generate(m, ids, 16))

    server = InferenceServer({"llm": path}).start()
    client = InferenceClient(server.endpoint)
    try:
        (out,) = client.infer("llm", prompt)
        assert out.shape == (2, 24)
        ref = np.asarray(generate(model, jnp.asarray(prompt), 16))
        np.testing.assert_array_equal(out, ref)
    finally:
        client.stop_server()
        client.close()


def test_serving_admin_ops_gated(saved_mlp):
    """admin_ops=False: the data plane stays up, but hot-load and stop
    over the wire are refused — the non-loopback exposure posture."""
    path, _ = saved_mlp
    server = InferenceServer({"mlp": path}, admin_ops=False).start()
    client = InferenceClient(server.endpoint)
    try:
        x = np.zeros((2, 4), np.float32)
        (y,) = client.infer("mlp", x)
        assert y.shape == (2, 3)
        with pytest.raises(RuntimeError, match="admin op"):
            client.load_model("evil", "/etc")
        client.stop_server()            # refused server-side, swallowed
        (y2,) = client.infer("mlp", x)  # still serving
        np.testing.assert_allclose(y2, y)
    finally:
        server.stop()
        client.close()


def test_serving_concurrent_clients(saved_mlp):
    import threading

    path, _ = saved_mlp
    server = InferenceServer({"mlp": path}).start()
    results, errs = {}, []

    def worker(i):
        try:
            c = InferenceClient(server.endpoint)
            x = np.full((2, 4), float(i), np.float32)
            (y,) = c.infer("mlp", x)
            results[i] = y
            c.close()
        except Exception as e:   # pragma: no cover - failure reporting
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    server.stop()
    assert not errs and len(results) == 6
    ref = Predictor(path)
    for i, y in results.items():
        np.testing.assert_allclose(
            y, np.asarray(ref.run(np.full((2, 4), float(i), np.float32))),
            rtol=1e-6)


def test_serving_concurrent_generate_clients(tmp_path):
    """Concurrent clients against the LLM GENERATE endpoint (the r4
    concurrency test covered plain predictors only): six threads drive
    the compiled decode loop with distinct prompts; every client gets
    ITS prompt's greedy continuation, bit-equal to a local generate."""
    import threading

    import jax.numpy as jnp

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.io import save_inference_model

    paddle_tpu.seed(3)
    cfg = LlamaConfig.tiny(vocab_size=128, num_layers=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(1)
    proto = rs.randint(0, 128, (2, 8)).astype(np.int32)
    path = str(tmp_path / "llm")
    save_inference_model(path, model, [proto],
                         forward=lambda m, ids: generate(m, ids, 12))

    server = InferenceServer({"llm": path}).start()
    prompts = {i: rs.randint(0, 128, (2, 8)).astype(np.int32)
               for i in range(6)}
    results, errs = {}, []

    def worker(i):
        try:
            c = InferenceClient(server.endpoint)
            (out,) = c.infer("llm", prompts[i])
            results[i] = out
            c.close()
        except Exception as e:   # pragma: no cover - failure reporting
            errs.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in prompts]
    [t.start() for t in threads]
    [t.join() for t in threads]
    server.stop()
    assert not errs and len(results) == 6, errs
    for i, out in results.items():
        ref = np.asarray(generate(model, jnp.asarray(prompts[i]), 12))
        np.testing.assert_array_equal(out, ref, err_msg=f"client {i}")
