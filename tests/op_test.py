"""OpTest — golden-output + numeric-gradient checking harness.

Replicates the reference's single most important piece of test infra
(reference ``python/paddle/fluid/tests/unittests/op_test.py:226``:
``check_output`` at ``:1250``, ``check_grad`` at ``:1324``, finite
differences at ``:101``): every op/kernel is validated against a reference
implementation for outputs AND against central finite differences for
gradients. The TPU version checks a jax implementation against a numpy/jnp
reference and ``jax.grad`` against FD.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def check_output(fn: Callable, ref_fn: Callable, args: Sequence,
                 rtol: float = 1e-5, atol: float = 1e-6):
    """Compare fn(*args) (jitted) against ref_fn(*args) elementwise."""
    out = jax.jit(fn)(*args)
    ref = ref_fn(*args)
    out_leaves = jax.tree_util.tree_leaves(out)
    ref_leaves = jax.tree_util.tree_leaves(ref)
    assert len(out_leaves) == len(ref_leaves)
    for o, r in zip(out_leaves, ref_leaves):
        np.testing.assert_allclose(np.asarray(o, np.float64),
                                   np.asarray(r, np.float64),
                                   rtol=rtol, atol=atol)


def numeric_grad(fn: Callable, args: list, idx: int, eps: float = 1e-3):
    """Central finite differences of sum(fn(*args)) w.r.t. args[idx]
    (the reference's ``get_numeric_gradient``, op_test.py:101)."""
    x = np.asarray(args[idx], np.float64)
    grad = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)

    def eval_at(v, i):
        flat2 = flat.copy()
        flat2[i] = v
        args2 = list(args)
        args2[idx] = jnp.asarray(flat2.reshape(x.shape), args[idx].dtype)
        return float(jnp.sum(fn(*args2)))

    for i in range(flat.size):
        gflat[i] = (eval_at(flat[i] + eps, i) - eval_at(flat[i] - eps, i)) / (
            2 * eps)
    return grad


def check_grad(fn: Callable, args: Sequence, wrt: Sequence[int] = (0,),
               rtol: float = 5e-3, atol: float = 1e-4, eps: float = 1e-3):
    """Compare jax.grad of sum(fn) against finite differences. Runs in
    float64 (x64 scoped via jax.enable_x64) so FD noise stays below
    tolerance — the reference instead loosens per-op thresholds
    (op_test white_list/op_accuracy_white_list.py)."""
    with jax.enable_x64(True):
        args = [jnp.asarray(np.asarray(a), jnp.float64) if np.issubdtype(
            np.asarray(a).dtype, np.floating) else jnp.asarray(a)
            for a in args]

        for idx in wrt:
            analytic = jax.grad(lambda *a: jnp.sum(fn(*a)), argnums=idx)(*args)
            numeric = numeric_grad(fn, list(args), idx, eps)
            np.testing.assert_allclose(np.asarray(analytic, np.float64),
                                       numeric, rtol=rtol, atol=atol,
                                       err_msg=f"grad mismatch wrt arg {idx}")
