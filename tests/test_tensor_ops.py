"""paddle.tensor API semantics tests — the conventions that differ from
numpy (split sections, topk tuples, gather axis, scatter modes, norm
default, shard_index routing)."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as P


def test_split_sections_with_inference():
    x = jnp.arange(12).reshape(12, 1)
    a, b, c = P.split(x, [3, -1, 4], axis=0)
    assert a.shape[0] == 3 and b.shape[0] == 5 and c.shape[0] == 4
    parts = P.split(x, 3)
    assert len(parts) == 3 and parts[0].shape[0] == 4


def test_topk_and_sort_conventions():
    x = jnp.asarray([[3.0, 1.0, 2.0], [9.0, 7.0, 8.0]])
    vals, idx = P.topk(x, 2)
    np.testing.assert_array_equal(np.asarray(vals), [[3, 2], [9, 8]])
    np.testing.assert_array_equal(np.asarray(idx), [[0, 2], [0, 2]])
    vals_s, idx_s = P.topk(x, 2, largest=False)
    np.testing.assert_array_equal(np.asarray(vals_s), [[1, 2], [7, 8]])
    np.testing.assert_array_equal(np.asarray(P.sort(x, descending=True)),
                                  [[3, 2, 1], [9, 8, 7]])
    np.testing.assert_array_equal(
        np.asarray(P.argsort(x, descending=True)[0]), [0, 2, 1])
    # topk along a non-last axis
    v2, i2 = P.topk(x, 1, axis=0)
    np.testing.assert_array_equal(np.asarray(v2), [[9, 7, 8]])


def test_gather_scatter_semantics():
    x = jnp.asarray(np.arange(12.0).reshape(4, 3))
    np.testing.assert_array_equal(np.asarray(P.gather(x, jnp.asarray([2, 0]))),
                                  [[6, 7, 8], [0, 1, 2]])
    upd = jnp.ones((2, 3))
    over = P.scatter(x, jnp.asarray([0, 1]), upd, overwrite=True)
    np.testing.assert_array_equal(np.asarray(over[0]), [1, 1, 1])
    acc = P.scatter(x, jnp.asarray([0, 0]), upd, overwrite=False)
    np.testing.assert_array_equal(np.asarray(acc[0]), [2, 3, 4])
    nd = P.gather_nd(x, jnp.asarray([[0, 1], [3, 2]]))
    np.testing.assert_array_equal(np.asarray(nd), [1, 11])
    samp = P.index_sample(x, jnp.asarray([[0, 2], [1, 1], [2, 0], [0, 0]]))
    np.testing.assert_array_equal(np.asarray(samp[0]), [0, 2])


def test_norm_defaults_and_dist():
    x = jnp.asarray([[3.0, 4.0], [0.0, 0.0]])
    assert float(P.norm(x)) == 5.0                    # fro over all
    np.testing.assert_allclose(np.asarray(P.norm(x, p=2, axis=1)), [5, 0])
    assert float(P.dist(x, jnp.zeros_like(x), p=2)) == 5.0


def test_shard_index_routes_ps_rows():
    ids = jnp.asarray([0, 5, 10, 15])
    # 16 ids over 4 shards: shard size 4
    out = P.shard_index(ids, 16, 4, shard_id=1)
    np.testing.assert_array_equal(np.asarray(out), [-1, 1, -1, -1])


def test_unique_and_masked_select_eager():
    x = jnp.asarray([3, 1, 3, 2, 1])
    u, inv, counts = P.unique(x, return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(np.asarray(u), [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(counts), [2, 1, 2])
    np.testing.assert_array_equal(np.asarray(u[inv]), np.asarray(x))
    sel = P.masked_select(x, x > 1)
    np.testing.assert_array_equal(np.asarray(sel), [3, 3, 2])
    nz = P.nonzero(jnp.asarray([0, 3, 0, 4]))
    np.testing.assert_array_equal(np.asarray(nz), [[1], [3]])


def test_math_and_stat_conventions():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    assert float(P.std(x)) == pytest.approx(np.std(np.arange(1, 5),
                                                   ddof=1))
    assert float(P.var(x, unbiased=False)) == pytest.approx(1.25)
    np.testing.assert_allclose(
        np.asarray(P.matmul(x, x, transpose_y=True)),
        np.asarray(x) @ np.asarray(x).T)
    np.testing.assert_allclose(np.asarray(P.addmm(jnp.ones((2, 2)), x, x,
                                                  beta=2.0, alpha=1.0)),
                               2 + np.asarray(x) @ np.asarray(x))
    assert int(P.numel(x)) == 4
    np.testing.assert_array_equal(np.asarray(P.flatten(x)), [1, 2, 3, 4])
    h = P.histogram(jnp.asarray([0.0, 1.0, 1.0, 2.0]), bins=2)
    np.testing.assert_array_equal(np.asarray(h), [1, 3])
