"""Heterogeneous PS: CPU trainer + accelerator-side dense section.

Reference flow (``heterxpu_trainer.cc`` + ``heter_service.proto``): the
trainer runs IO/sparse ops and RPCs the dense program section to a heter
worker, which executes it on the accelerator and returns boundary
tensors. Here: sparse embeddings on PS tables (CPU RAM), dense MLP on the
HeterWorker; the trainer round-trips features → (loss, d_features) and
pushes the feature grads back into the sparse table.
"""

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (
    HeterClient, HeterWorker, InProcClient,
)

DIM = 8


def _dense_section():
    """Worker-side dense model: 2-layer MLP regression head over the
    embedding features, AdamW'd locally — the 'cached program section'."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "w1": jax.random.normal(k1, (DIM, 16)) * 0.3,
        "b1": jnp.zeros((16,)),
        "w2": jax.random.normal(k2, (16, 1)) * 0.3,
        "b2": jnp.zeros((1,)),
    }

    def loss_fn(p, feats, labels):
        h = jnp.tanh(feats @ p["w1"] + p["b1"])
        pred = (h @ p["w2"] + p["b2"])[:, 0]
        return jnp.mean((pred - labels) ** 2)

    @jax.jit
    def fwd_bwd(p, feats, labels):
        loss, (gp, gf) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            p, feats, labels)
        new_p = jax.tree_util.tree_map(lambda w, g: w - 0.05 * g, p, gp)
        return loss, gf, new_p

    state = {"p": params}

    def step_fn(feats, labels):
        import jax.numpy as jnp

        loss, gf, new_p = fwd_bwd(state["p"], jnp.asarray(feats),
                                  jnp.asarray(labels))
        state["p"] = new_p
        return float(loss), np.asarray(gf)

    def eval_fn(feats, labels):
        import jax.numpy as jnp

        return float(loss_fn(state["p"], jnp.asarray(feats),
                             jnp.asarray(labels)))

    return step_fn, eval_fn


def test_heter_worker_trains_sparse_dense():
    """End-to-end heter training: loss drops and the *sparse* rows (on the
    CPU-side PS table) move — proving gradients crossed the RPC boundary
    both ways."""
    worker = HeterWorker(_dense_section).start()
    ps = InProcClient()
    ps.create_table("emb", DIM, optimizer="sgd", lr=0.1, seed=1)
    client = HeterClient(worker.endpoint)
    try:
        rs = np.random.RandomState(0)
        ids_all = np.arange(32, dtype=np.int64)
        # ground truth depends on the id so the embedding must learn
        target = (ids_all % 4).astype(np.float32)
        before = ps.pull("emb", ids_all).copy()   # pre-training snapshot

        first = None
        for step in range(60):
            ids = rs.choice(ids_all, size=16, replace=False)
            feats = ps.pull("emb", ids)
            loss, dfeats = client.forward_backward(feats, target[ids])
            assert dfeats.shape == feats.shape
            ps.push_grad("emb", ids, dfeats)
            if first is None:
                first = loss
        final = client.eval_loss(ps.pull("emb", ids_all), target)
        assert final < first * 0.5, (first, final)
        moved = np.abs(ps.pull("emb", ids_all) - before).max()
        assert moved > 1e-3, "sparse rows never updated"
    finally:
        client.stop_worker()
        client.close()


def test_heter_worker_error_reporting_and_info():
    worker = HeterWorker(_dense_section).start()
    client = HeterClient(worker.endpoint)
    try:
        info = client.info()
        assert "devices" in info and len(info["devices"]) >= 1
        with pytest.raises(RuntimeError, match="heter forward_backward"):
            # wrong feature width -> worker reports, keeps serving
            client.forward_backward(np.zeros((4, DIM + 1), np.float32),
                                    np.zeros((4,), np.float32))
        loss = client.eval_loss(np.zeros((4, DIM), np.float32),
                                np.zeros((4,), np.float32))
        assert np.isfinite(loss)
    finally:
        client.stop_worker()
        client.close()
