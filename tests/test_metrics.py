"""MetricsHub: the windowed in-memory fleet TSDB the controller scrapes
into, and the SLO burn-rate math built on it.

The load-bearing properties: deltas are reset-aware (a bounced replica's
counters going backwards clamp to an empty window, never a negative
spike), windowed queries use however many ticks exist (sane answers from
tick 2), and the multi-window burn rate trips on an acute violation but
releases as soon as the fast window is clean — the slow window alone
never pages.
"""

import bisect

import pytest

from paddle_tpu.core import monitor
from paddle_tpu.core.monitor import hist_fraction_above
from paddle_tpu.serving.metrics import MetricsHub, hist_delta

pytestmark = [pytest.mark.obs, pytest.mark.control]


def _cum_hist(values):
    """Cumulative raw histogram snapshot (what ``health`` ships)."""
    h = monitor._Histogram()
    for v in values:
        h.observe(v)
    return h.summary(raw=True)


def _doc(ttft_values, stats=None):
    return {"status": "ok", "inflight": 0, "generators": {},
            "stats": dict(stats or {}),
            "histograms": {"gen/ttft_s": _cum_hist(ttft_values)}}


# ---------------------------------------------------------------------------
# hist_fraction_above (the burn numerator)
# ---------------------------------------------------------------------------

def test_hist_fraction_above_counts_violating_buckets():
    doc = _cum_hist([0.01] * 9 + [2.0])
    # 0.5 sits in an EMPTY bucket: interpolation has nothing to share
    # out, so the answer is exact either way
    assert hist_fraction_above(doc, 0.5) == pytest.approx(0.1)
    assert hist_fraction_above(doc, 0.5, conservative=True) == \
        pytest.approx(0.1)
    assert hist_fraction_above(doc, 1e-6) == pytest.approx(1.0)
    # threshold at a bucket's exact upper bound: that bucket's counts
    # are all <= threshold, nothing interpolates
    lo = max(b for b in monitor._BUCKET_BOUNDS if b < 2.0)
    assert hist_fraction_above(doc, lo) > 0.0
    assert hist_fraction_above(_cum_hist([lo]), lo) == 0.0


def test_hist_fraction_above_interpolates_boundary_bucket():
    """A threshold strictly inside a populated bucket: the old behavior
    read ALL of that bucket as below (under-counting by up to a whole
    ~2.15x bucket span); the default now spreads the bucket's counts
    uniformly and attributes the share above the threshold.
    ``conservative=True`` restores the floor."""
    doc = _cum_hist([0.5])           # lands in the bucket (0.464, 1.0]
    i = bisect.bisect_left(monitor._BUCKET_BOUNDS, 0.5)
    lo, hi = monitor._BUCKET_BOUNDS[i - 1], monitor._BUCKET_BOUNDS[i]
    assert lo < 0.5 < hi
    expect = (hi - 0.5) / (hi - lo)  # uniform-spread share above 0.5
    assert hist_fraction_above(doc, 0.5) == pytest.approx(expect)
    assert hist_fraction_above(doc, 0.5, conservative=True) == 0.0
    # a threshold a full bucket lower sees it as violating either way
    assert hist_fraction_above(doc, 0.05) == pytest.approx(1.0)
    assert hist_fraction_above(doc, 0.05, conservative=True) == \
        pytest.approx(1.0)
    # interpolation never exceeds the whole-bucket ceiling
    assert hist_fraction_above(doc, lo * 1.0001) <= 1.0


def test_hist_fraction_above_overflow_bucket_uses_observed_max():
    """Observations beyond the last bound land in the overflow bucket,
    whose upper edge is unknowable from bounds alone — interpolation
    uses the histogram's observed ``max`` instead."""
    top = monitor._BUCKET_BOUNDS[-1]
    doc = _cum_hist([top * 2.0, top * 4.0])
    assert doc["max"] == pytest.approx(top * 4.0)
    frac = hist_fraction_above(doc, top * 3.0)
    # uniform spread over (top, max]: share above 3*top out of (1..4]*top
    assert frac == pytest.approx((4.0 - 3.0) / (4.0 - 1.0))
    assert hist_fraction_above(doc, top * 3.0, conservative=True) == 0.0


def test_hist_fraction_above_empty_inputs():
    assert hist_fraction_above({}, 0.5) == 0.0
    assert hist_fraction_above({"count": 0, "buckets": []}, 0.5) == 0.0
    assert hist_fraction_above(None, 0.5) == 0.0


# ---------------------------------------------------------------------------
# per-tick deltas: baseline, clamping, reset-awareness
# ---------------------------------------------------------------------------

def test_hist_delta_reset_clamps_to_empty_window():
    """A replica restart sends counters BACKWARDS; the delta must read
    as an empty window, not a negative distribution."""
    big = _cum_hist([0.1] * 10)
    small = _cum_hist([0.1] * 3)     # "restarted" snapshot
    assert hist_delta(big, small) is None
    d = hist_delta(small, big)       # forward diff still works
    assert d is not None and d["count"] == 7


def test_stat_deltas_are_reset_aware():
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    hub.ingest({"ep": _doc([], stats={"gen/streams": 10.0})})
    hub.ingest({"ep": _doc([], stats={"gen/streams": 14.0})})
    assert hub.rate("gen/streams") > 0.0       # 4 events this window
    # restart: counter falls back to 1 — clamps to zero, no negatives
    hub.ingest({"ep": _doc([], stats={"gen/streams": 1.0})})
    hub.ingest({"ep": _doc([], stats={"gen/streams": 1.0})})
    assert hub.rate("gen/streams") == 0.0


def test_first_sight_is_a_baseline_not_a_delta():
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    hub.ingest({"ep": _doc([0.1] * 100, stats={"gen/streams": 100.0})})
    # a brand-new endpoint's lifetime totals must NOT count as one
    # tick's worth of traffic
    assert hub.window_histogram("gen/ttft_s") is None
    assert hub.rate("gen/streams") == 0.0


# ---------------------------------------------------------------------------
# burn-rate window math
# ---------------------------------------------------------------------------

def test_burn_rate_fast_window_trip():
    """An acute violation burns BOTH windows past threshold (the slow
    window contains the fast one), so the page condition trips."""
    hub = MetricsHub(fast_ticks=2, slow_ticks=6)
    hub.ingest({"ep": _doc([0.01] * 5)})             # baseline
    hub.ingest({"ep": _doc([0.01] * 5 + [2.0] * 5)})  # 100% violating
    fast, slow = hub.burn_rates("gen/ttft_s", 0.5, budget=0.1)
    assert fast == pytest.approx(10.0)
    assert slow == pytest.approx(10.0)


def test_burn_rate_slow_window_holds_memory_fast_releases():
    """Clean ticks push the violation out of the fast window while the
    slow window still remembers it — exactly the asymmetry that makes
    the dual-window condition flap-proof."""
    hub = MetricsHub(fast_ticks=2, slow_ticks=6)
    cum = [0.01] * 5
    hub.ingest({"ep": _doc(cum)})
    cum = cum + [2.0] * 5
    hub.ingest({"ep": _doc(cum)})
    for _ in range(2):                   # two clean ticks
        cum = cum + [0.01] * 20
        hub.ingest({"ep": _doc(cum)})
    fast, slow = hub.burn_rates("gen/ttft_s", 0.5, budget=0.1)
    assert fast == 0.0                   # fast window: clean ticks only
    assert 0.0 < slow < 10.0             # slow window: diluted memory


def test_burn_rate_no_traffic_burns_nothing():
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    assert hub.burn_rates("gen/ttft_s", 0.5, budget=0.1) == (0.0, 0.0)
    hub.ingest({"ep": _doc([0.01])})
    assert hub.burn_rates("gen/ttft_s", 0.5, budget=0.1) == (0.0, 0.0)
    # zero/negative budget can never page
    hub.ingest({"ep": _doc([0.01, 1.0, 1.0])})
    assert hub.burn_rates("gen/ttft_s", 0.5, budget=0.0) == (0.0, 0.0)


def test_window_histogram_merges_across_endpoints():
    hub = MetricsHub(fast_ticks=3, slow_ticks=6)
    hub.ingest({"a": _doc([0.01]), "b": _doc([0.2] * 3)})
    hub.ingest({"a": _doc([0.01] * 6), "b": _doc([0.2] * 3 + [0.4] * 5)})
    win = hub.window_histogram("gen/ttft_s")
    assert win is not None
    assert win["count"] == 10            # 5 new on a + 5 new on b


# ---------------------------------------------------------------------------
# membership churn
# ---------------------------------------------------------------------------

def test_unreachable_docs_are_skipped_and_endpoints_pruned():
    hub = MetricsHub(fast_ticks=2, slow_ticks=3)
    hub.ingest({"a": _doc([0.1]), "b": _doc([0.1])})
    hub.ingest({"a": _doc([0.1] * 2),
                "b": {"status": "unreachable", "error": "boom"}})
    assert set(hub.endpoints()) == {"a", "b"}
    # b misses a full slow window of ticks -> pruned, a keeps answering
    for i in range(3, 7):
        hub.ingest({"a": _doc([0.1] * i)})
    assert hub.endpoints() == ["a"]
    assert hub.window_histogram("gen/ttft_s") is not None
    snap = hub.snapshot()
    assert snap["tick"] == 6 and list(snap["endpoints"]) == ["a"]


def test_readopted_endpoint_rebaselines_after_slow_gap():
    """An endpoint absent a full slow window then re-added — the HA
    takeover adoption path, or an operator re-adding a bounced replica
    — must re-baseline. Its ingest refreshes ``last_tick`` BEFORE the
    prune sweep runs, so without the explicit re-baseline it would
    dodge its own prune and difference the whole gap's cumulative
    counters against the stale pre-gap snapshot: one giant bogus
    window delta."""
    hub = MetricsHub(fast_ticks=2, slow_ticks=3)
    hub.ingest({"ep": _doc([0.1], stats={"gen/streams": 10.0})})
    # gone for > slow_ticks while another member keeps the hub ticking
    for _ in range(4):
        hub.ingest({"other": _doc([])})
    # returns with a much larger lifetime total: first sight is a
    # baseline (no delta), not a 990-event window spike
    hub.ingest({"ep": _doc([0.1] * 100,
                           stats={"gen/streams": 1000.0})})
    assert hub.rate("gen/streams") == 0.0
    assert hub.window_histogram("gen/ttft_s") is None
    # deltas resume normally from the new baseline
    hub.ingest({"ep": _doc([0.1] * 104,
                           stats={"gen/streams": 1002.0})})
    assert hub.rate("gen/streams") > 0.0


def test_gauges_track_latest_per_model_engine_stats():
    hub = MetricsHub()
    doc = _doc([])
    doc["generators"] = {"llm": {"slots": 4, "active": 2, "queued": 1}}
    hub.ingest({"ep": doc})
    g = hub.gauges()
    assert g["ep"]["llm"]["active"] == 2


# ---------------------------------------------------------------------------
# ledger rollups (FLAGS_gen_ledger fleet views)
# ---------------------------------------------------------------------------

def _gp(prefill, decode, host, ticks=10):
    total = prefill + decode + host
    return {"total_s": total, "ticks": ticks,
            "buckets": {"prefill": prefill, "decode": decode,
                        "spec_verify": 0.0, "host_gather": host,
                        "admission_idle": 0.0, "recompile": 0.0,
                        "watchdog_stuck": 0.0},
            "goodput": (prefill + decode) / total if total else 0.0}


def test_fleet_goodput_sums_bucket_seconds_across_engines():
    """The fleet rollup weights each engine by the wall clock it
    accounted (bucket-second sums), not a naive fraction average."""
    hub = MetricsHub()
    assert hub.fleet_goodput() is None           # ledger off fleet-wide
    a = _doc([])
    a["generators"] = {"llm": {"goodput": _gp(1.0, 8.0, 1.0)}}
    b = _doc([])
    b["generators"] = {"llm": {"goodput": _gp(0.0, 1.0, 9.0, ticks=5)}}
    hub.ingest({"a": a, "b": b})
    gp = hub.fleet_goodput()
    assert gp["engines"] == 2 and gp["ticks"] == 15
    assert gp["total_s"] == pytest.approx(20.0)
    # (1+8 + 0+1) useful seconds out of 20 — NOT mean(0.9, 0.1)
    assert gp["goodput"] == pytest.approx(0.5)
    assert gp["fractions"]["decode"] == pytest.approx(9.0 / 20.0)
    assert sum(gp["fractions"].values()) == pytest.approx(1.0)


def test_tenants_rollup_sums_across_endpoints():
    hub = MetricsHub()
    assert hub.tenants() == {}
    a = _doc([])
    a["generators"] = {"llm": {"tenants": {
        "acme": {"tokens": 10, "chip_seconds": 1.0, "requests": 2}}}}
    b = _doc([])
    b["generators"] = {"llm": {"tenants": {
        "acme": {"tokens": 5, "chip_seconds": 0.5, "requests": 1},
        "-": {"tokens": 3, "chip_seconds": 0.1, "requests": 1}}}}
    hub.ingest({"a": a, "b": b})
    tens = hub.tenants()
    assert tens["acme"] == {"tokens": 15.0, "chip_seconds": 1.5,
                            "requests": 3.0}
    assert tens["-"]["tokens"] == 3.0


def test_phase_percentiles_merge_ledger_histograms():
    hub = MetricsHub(fast_ticks=2, slow_ticks=6)
    def doc(vals):
        d = _doc([])
        d["histograms"]["gen/phase/decode_s"] = _cum_hist(vals)
        d["histograms"]["gen/e2e_s"] = _cum_hist(vals)
        return d
    hub.ingest({"a": doc([0.1]), "b": doc([0.3] * 2)})
    hub.ingest({"a": doc([0.1] * 4), "b": doc([0.3] * 6)})
    pct = hub.phase_percentiles()
    # tick 1 is each endpoint's baseline; the window holds tick 2's
    # deltas: 3 new on a + 4 new on b
    assert pct["gen/phase/decode_s"]["count"] == 7
    assert pct["gen/e2e_s"]["p50"] > 0.0
    # phases never observed are omitted, not zero-filled
    assert "gen/phase/admit_wait_s" not in pct


def test_phase_percentiles_not_ready_is_typed():
    """Satellite: before any endpoint has TWO health ticks there is no
    histogram delta to merge — the old code silently returned {} and a
    report could not tell "warming up" from "ledger off".  The empty
    merge is now the typed (and still falsy, so ``if pct:`` callers are
    unchanged) PhasesNotReady carrying per-endpoint ticks_observed."""
    from paddle_tpu.serving.metrics import PhasesNotReady

    hub = MetricsHub(fast_ticks=2, slow_ticks=6)

    def doc(vals):
        d = _doc([])
        d["histograms"]["gen/phase/decode_s"] = _cum_hist(vals)
        return d

    # no ticks at all: typed, and nothing observed yet
    pct = hub.phase_percentiles()
    assert isinstance(pct, PhasesNotReady) and pct.not_ready
    assert not pct                       # falsy like the old {}
    assert pct.ticks_observed == {}

    # one tick each: baselines only, still not ready — and the result
    # names every endpoint stuck below two ticks
    hub.ingest({"a": doc([0.1]), "b": doc([0.3])})
    pct = hub.phase_percentiles()
    assert isinstance(pct, PhasesNotReady)
    assert pct.ticks_observed == {"a": 1, "b": 1}
    assert pct.waiting == ["a", "b"]
    assert hub.ticks_observed() == {"a": 1, "b": 1}

    # second tick: a real merge — a PLAIN dict again, shape unchanged
    hub.ingest({"a": doc([0.1] * 3), "b": doc([0.3] * 2)})
    pct = hub.phase_percentiles()
    assert not isinstance(pct, PhasesNotReady)
    assert pct["gen/phase/decode_s"]["count"] == 3


def test_fleet_kv_rollup_sums_engine_stores():
    """fleet_kv() sums every engine's ``kv`` gauge block and derives
    the fleet hit rate (spill_hits is a SUBSET of hits — not double
    counted); None when no engine reports a store."""
    hub = MetricsHub()
    assert hub.fleet_kv() is None
    a = _doc([])
    a["generators"] = {"llm": {"kv": {
        "role": "prefill", "hits": 0, "spill_hits": 0, "misses": 2,
        "puts": 4, "fetched_bytes": 0, "demotions": 1,
        "prefill_recomputed": 0}}}
    b = _doc([])
    b["generators"] = {"llm": {"kv": {
        "role": "decode", "hits": 6, "spill_hits": 4, "misses": 2,
        "puts": 0, "fetched_bytes": 4096, "demotions": 0,
        "prefill_recomputed": 8}}}
    hub.ingest({"a": a, "b": b})
    kv = hub.fleet_kv()
    assert kv["engines"] == 2
    assert kv["roles"] == {"prefill": 1, "decode": 1}
    assert kv["hit_rate"] == pytest.approx(6.0 / 10.0)
    assert kv["fetch_bytes"] == 4096.0
    assert kv["demotions"] == 1.0
    assert kv["prefill_recomputed"] == 8.0
    assert kv["counters"]["puts"] == 4.0
