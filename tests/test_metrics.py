"""MetricsHub: the windowed in-memory fleet TSDB the controller scrapes
into, and the SLO burn-rate math built on it.

The load-bearing properties: deltas are reset-aware (a bounced replica's
counters going backwards clamp to an empty window, never a negative
spike), windowed queries use however many ticks exist (sane answers from
tick 2), and the multi-window burn rate trips on an acute violation but
releases as soon as the fast window is clean — the slow window alone
never pages.
"""

import pytest

from paddle_tpu.core import monitor
from paddle_tpu.core.monitor import hist_fraction_above
from paddle_tpu.serving.metrics import MetricsHub, hist_delta

pytestmark = [pytest.mark.obs, pytest.mark.control]


def _cum_hist(values):
    """Cumulative raw histogram snapshot (what ``health`` ships)."""
    h = monitor._Histogram()
    for v in values:
        h.observe(v)
    return h.summary(raw=True)


def _doc(ttft_values, stats=None):
    return {"status": "ok", "inflight": 0, "generators": {},
            "stats": dict(stats or {}),
            "histograms": {"gen/ttft_s": _cum_hist(ttft_values)}}


# ---------------------------------------------------------------------------
# hist_fraction_above (the burn numerator)
# ---------------------------------------------------------------------------

def test_hist_fraction_above_counts_violating_buckets():
    doc = _cum_hist([0.01] * 9 + [2.0])
    assert hist_fraction_above(doc, 0.5) == pytest.approx(0.1)
    assert hist_fraction_above(doc, 2.0) == 0.0
    assert hist_fraction_above(doc, 1e-6) == pytest.approx(1.0)


def test_hist_fraction_above_boundary_bucket_counts_as_below():
    """A threshold strictly inside a bucket cannot tell how much of that
    bucket violates — the fraction under-counts (conservative: never
    pages on observations that might be fine)."""
    doc = _cum_hist([0.5])           # lands in the bucket containing 0.5
    # threshold inside/at the same bucket: its counts read as below
    assert hist_fraction_above(doc, 0.5) == 0.0
    # a threshold a full bucket lower sees it as violating
    assert hist_fraction_above(doc, 0.05) == pytest.approx(1.0)


def test_hist_fraction_above_empty_inputs():
    assert hist_fraction_above({}, 0.5) == 0.0
    assert hist_fraction_above({"count": 0, "buckets": []}, 0.5) == 0.0
    assert hist_fraction_above(None, 0.5) == 0.0


# ---------------------------------------------------------------------------
# per-tick deltas: baseline, clamping, reset-awareness
# ---------------------------------------------------------------------------

def test_hist_delta_reset_clamps_to_empty_window():
    """A replica restart sends counters BACKWARDS; the delta must read
    as an empty window, not a negative distribution."""
    big = _cum_hist([0.1] * 10)
    small = _cum_hist([0.1] * 3)     # "restarted" snapshot
    assert hist_delta(big, small) is None
    d = hist_delta(small, big)       # forward diff still works
    assert d is not None and d["count"] == 7


def test_stat_deltas_are_reset_aware():
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    hub.ingest({"ep": _doc([], stats={"gen/streams": 10.0})})
    hub.ingest({"ep": _doc([], stats={"gen/streams": 14.0})})
    assert hub.rate("gen/streams") > 0.0       # 4 events this window
    # restart: counter falls back to 1 — clamps to zero, no negatives
    hub.ingest({"ep": _doc([], stats={"gen/streams": 1.0})})
    hub.ingest({"ep": _doc([], stats={"gen/streams": 1.0})})
    assert hub.rate("gen/streams") == 0.0


def test_first_sight_is_a_baseline_not_a_delta():
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    hub.ingest({"ep": _doc([0.1] * 100, stats={"gen/streams": 100.0})})
    # a brand-new endpoint's lifetime totals must NOT count as one
    # tick's worth of traffic
    assert hub.window_histogram("gen/ttft_s") is None
    assert hub.rate("gen/streams") == 0.0


# ---------------------------------------------------------------------------
# burn-rate window math
# ---------------------------------------------------------------------------

def test_burn_rate_fast_window_trip():
    """An acute violation burns BOTH windows past threshold (the slow
    window contains the fast one), so the page condition trips."""
    hub = MetricsHub(fast_ticks=2, slow_ticks=6)
    hub.ingest({"ep": _doc([0.01] * 5)})             # baseline
    hub.ingest({"ep": _doc([0.01] * 5 + [2.0] * 5)})  # 100% violating
    fast, slow = hub.burn_rates("gen/ttft_s", 0.5, budget=0.1)
    assert fast == pytest.approx(10.0)
    assert slow == pytest.approx(10.0)


def test_burn_rate_slow_window_holds_memory_fast_releases():
    """Clean ticks push the violation out of the fast window while the
    slow window still remembers it — exactly the asymmetry that makes
    the dual-window condition flap-proof."""
    hub = MetricsHub(fast_ticks=2, slow_ticks=6)
    cum = [0.01] * 5
    hub.ingest({"ep": _doc(cum)})
    cum = cum + [2.0] * 5
    hub.ingest({"ep": _doc(cum)})
    for _ in range(2):                   # two clean ticks
        cum = cum + [0.01] * 20
        hub.ingest({"ep": _doc(cum)})
    fast, slow = hub.burn_rates("gen/ttft_s", 0.5, budget=0.1)
    assert fast == 0.0                   # fast window: clean ticks only
    assert 0.0 < slow < 10.0             # slow window: diluted memory


def test_burn_rate_no_traffic_burns_nothing():
    hub = MetricsHub(fast_ticks=2, slow_ticks=4)
    assert hub.burn_rates("gen/ttft_s", 0.5, budget=0.1) == (0.0, 0.0)
    hub.ingest({"ep": _doc([0.01])})
    assert hub.burn_rates("gen/ttft_s", 0.5, budget=0.1) == (0.0, 0.0)
    # zero/negative budget can never page
    hub.ingest({"ep": _doc([0.01, 1.0, 1.0])})
    assert hub.burn_rates("gen/ttft_s", 0.5, budget=0.0) == (0.0, 0.0)


def test_window_histogram_merges_across_endpoints():
    hub = MetricsHub(fast_ticks=3, slow_ticks=6)
    hub.ingest({"a": _doc([0.01]), "b": _doc([0.2] * 3)})
    hub.ingest({"a": _doc([0.01] * 6), "b": _doc([0.2] * 3 + [0.4] * 5)})
    win = hub.window_histogram("gen/ttft_s")
    assert win is not None
    assert win["count"] == 10            # 5 new on a + 5 new on b


# ---------------------------------------------------------------------------
# membership churn
# ---------------------------------------------------------------------------

def test_unreachable_docs_are_skipped_and_endpoints_pruned():
    hub = MetricsHub(fast_ticks=2, slow_ticks=3)
    hub.ingest({"a": _doc([0.1]), "b": _doc([0.1])})
    hub.ingest({"a": _doc([0.1] * 2),
                "b": {"status": "unreachable", "error": "boom"}})
    assert set(hub.endpoints()) == {"a", "b"}
    # b misses a full slow window of ticks -> pruned, a keeps answering
    for i in range(3, 7):
        hub.ingest({"a": _doc([0.1] * i)})
    assert hub.endpoints() == ["a"]
    assert hub.window_histogram("gen/ttft_s") is not None
    snap = hub.snapshot()
    assert snap["tick"] == 6 and list(snap["endpoints"]) == ["a"]


def test_gauges_track_latest_per_model_engine_stats():
    hub = MetricsHub()
    doc = _doc([])
    doc["generators"] = {"llm": {"slots": 4, "active": 2, "queued": 1}}
    hub.ingest({"ep": doc})
    g = hub.gauges()
    assert g["ep"]["llm"]["active"] == 2
