"""Speculative decoding: n-gram + draft-model lookahead with batched
verification.

The load-bearing property is the repo's universal acceptance criterion
applied to the hottest path: a GREEDY speculative decode — whatever the
drafter proposed and however many tokens each verify step accepted —
must be byte-identical to the non-speculative decode, both solo
(``speculative_generate`` vs ``generate``) and through the engine's
fused step (contiguous AND paged cache modes, under interleaving).
Sampled streams must stay deterministic per (prompt, seed): one key is
consumed per EMITTED token regardless of acceptance pattern, so
speculation on/off cannot change a sampled stream and PR 8's
``rng_skip`` resumption composes unchanged.
"""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core.monitor import get_histogram, get_stat
from paddle_tpu.io.serving import InferenceClient, InferenceServer
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.generation import (
    generate, ngram_propose, speculative_generate,
)
from paddle_tpu.serving import GenerationEngine

pytestmark = pytest.mark.spec

VOCAB = 96
MAX_NEW = 12


@pytest.fixture(scope="module")
def model():
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


@pytest.fixture(scope="module")
def draft_model():
    paddle_tpu.seed(3)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=1,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _prompts(n, seed=1, size=None):
    # fixed ``size`` keeps the eager solo path on ONE compiled cache
    # shape (S = prompt + max_new + k); varied sizes exercise the
    # engine's bucketing instead
    rs = np.random.RandomState(seed)
    return [rs.randint(1, VOCAB,
                       size=size or rs.randint(4, 10)).astype(np.int32)
            for _ in range(n)]


def _drain(engine, gen_id, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gen_id, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        assert doc["error"] is None, doc["error"]
        if doc["done"]:
            return toks


# -- drafter ----------------------------------------------------------------

def test_ngram_propose_suffix_match():
    # suffix [2, 3] last occurs at the start; continuation is [9, 5, 2]
    out = ngram_propose([1, 2, 3, 9, 5, 2, 3], 3)
    assert out.tolist() == [9, 5, 2]


def test_ngram_propose_prefers_longest_then_most_recent():
    # 3-gram suffix [1, 2, 3] matches at index 0 — wins over the later
    # 2-gram match of [2, 3]
    ctx = [1, 2, 3, 7, 8, 2, 3, 6, 1, 2, 3]
    assert ngram_propose(ctx, 2).tolist() == [7, 8]
    # most recent occurrence wins among equal-length matches
    ctx = [5, 9, 1, 5, 9, 2, 5, 9]
    assert ngram_propose(ctx, 1).tolist() == [2]


def test_ngram_propose_no_match_and_clamps():
    assert ngram_propose([1, 2, 3, 4, 5], 4).size == 0     # no repeat
    assert ngram_propose([7], 4).size == 0                 # too short
    assert ngram_propose([1, 2, 3], 0).size == 0           # k=0
    # draft truncated at the end of the context
    assert ngram_propose([4, 6, 4], 5).tolist() == [6, 4]


# -- solo speculative_generate ----------------------------------------------

def test_solo_greedy_byte_identity_ngram(model):
    p = _prompts(1, size=8)[0]
    ref = generate(model, p[None], MAX_NEW)
    for k in (1, 4):
        out = speculative_generate(model, p[None], MAX_NEW, spec_k=k)
        assert np.array_equal(ref, out), f"k={k} diverged"


def test_solo_greedy_byte_identity_draft(model, draft_model):
    p = _prompts(1, seed=2, size=8)[0]
    ref = generate(model, p[None], MAX_NEW)
    out = speculative_generate(model, p[None], MAX_NEW, spec_k=4,
                               draft_model=draft_model)
    assert np.array_equal(ref, out)


def test_solo_sampled_deterministic_spec_on_off(model):
    """One key per EMITTED token: the sampled stream is a function of
    (prompt, seed) alone — acceptance pattern, k, and drafter choice
    cannot perturb it."""
    p = _prompts(1, size=8)[0]
    ref = generate(model, p[None], MAX_NEW, temperature=0.8, top_k=20,
                   key=paddle_tpu.seed(11))
    out = speculative_generate(model, p[None], MAX_NEW, spec_k=4,
                               temperature=0.8, top_k=20,
                               key=paddle_tpu.seed(11))
    assert np.array_equal(ref, out), "sampled stream diverged"


def test_solo_eos_respected(model):
    """EOS emitted inside an accepted draft run truncates the output at
    exactly the same token as the non-speculative decode."""
    p = _prompts(1, seed=5, size=8)[0]
    ref = generate(model, p[None], MAX_NEW)
    eos = int(ref[0, p.size + MAX_NEW // 2])   # force a mid-stream EOS
    ref = generate(model, p[None], MAX_NEW, eos_token_id=eos)
    out = speculative_generate(model, p[None], MAX_NEW, spec_k=4,
                               eos_token_id=eos)
    assert np.array_equal(ref, out)


# -- engine: byte-identity under interleaving --------------------------------

@pytest.fixture(scope="module")
def refs6(model):
    # solo generate() runs eagerly — compute the 6 reference streams
    # ONCE and share them across the engine-identity tests (same seed-1
    # prompt list everywhere)
    prompts = _prompts(6)
    refs = [generate(model, p[None], MAX_NEW)[0, p.size:].tolist()
            for p in prompts]
    return prompts, refs


def _engine_matches_solo(model, refs, prompts, **kw):
    with GenerationEngine(model, **kw) as eng:
        gids = [eng.start(p, MAX_NEW) for p in prompts]
        outs = [_drain(eng, g) for g in gids]
        st = eng.stats()
    assert outs == refs
    return st


def test_engine_greedy_identity_contiguous(model, refs6):
    """6 greedy streams through 3 speculating slots (queueing forces
    admits/retires mid-flight; slots speculate and plain-step in the
    same compiled call as drafts come and go) — byte-identical to solo
    generate(). The same workload doubles as the contiguous rollback
    test: the random model rejects plenty of n-gram drafts (rejected
    positions sit past the decode index, masked by attention and
    overwritten by later steps), and identity holds anyway."""
    prompts, refs = refs6
    st = _engine_matches_solo(model, refs, prompts, slots=3, max_len=40,
                              queue_max=8, spec_k=4, spec_mode="ngram",
                              spec_shed_occupancy=1.0)
    assert st["spec"]["proposed"] > 0
    assert st["spec"]["rejected"] > 0
    assert st["spec"]["accepted"] == st["spec"]["proposed"] - \
        st["spec"]["rejected"]
    assert st["tokens_per_step"] > 0


def test_engine_greedy_identity_paged_and_rollback(model, refs6):
    """Paged identity under the same interleaving — plus the rollback
    pool invariant: rejected drafts are truncated to the null page, and
    after the streams retire and the prefix cache is dropped every page
    is back in the pool (rollback cannot leak or double-free a page)."""
    prompts, refs = refs6
    with GenerationEngine(model, slots=3, max_len=40, queue_max=8,
                          paged=True, page_tokens=8, spec_k=4,
                          spec_mode="ngram",
                          spec_shed_occupancy=1.0) as eng:
        outs = [_drain(eng, eng.start(p, MAX_NEW)) for p in prompts]
        st = eng.stats()
        assert st["spec"]["proposed"] > 0
        assert st["spec"]["rejected"] > 0
        assert outs == refs
        eng.clear_prefix_cache()
        st = eng.stats()
        assert st["pages_free"] == st["pages"]


def test_engine_greedy_identity_draft_mode(model, draft_model, refs6):
    prompts, refs = refs6[0][:3], refs6[1][:3]
    st = _engine_matches_solo(model, refs, prompts, slots=2, max_len=40,
                              queue_max=8, spec_k=4, spec_mode="draft",
                              draft_model=draft_model,
                              spec_shed_occupancy=1.0)
    assert st["spec"]["mode"] == "draft"
    assert st["spec"]["proposed"] > 0


def test_engine_sampled_deterministic_spec_on_off(model):
    p = _prompts(1)[0]
    with GenerationEngine(model, slots=2, max_len=40) as base:
        a = _drain(base, base.start(p, MAX_NEW, temperature=0.9, top_k=12,
                                    seed=5))
    with GenerationEngine(model, slots=2, max_len=40, spec_k=4,
                          spec_mode="ngram",
                          spec_shed_occupancy=1.0) as spec:
        b = _drain(spec, spec.start(p, MAX_NEW, temperature=0.9, top_k=12,
                                    seed=5))
    assert a == b


def test_engine_rng_skip_resume_interop(model):
    """PR 8's resume contract survives speculation: replaying the
    emitted prefix into the prompt with rng_skip=len(prefix) continues
    the sampled stream byte-identically on a SPECULATING engine."""
    p = _prompts(1, seed=9)[0]
    with GenerationEngine(model, slots=2, max_len=60, spec_k=4,
                          spec_mode="ngram",
                          spec_shed_occupancy=1.0) as eng:
        A = _drain(eng, eng.start(p, 16, temperature=0.9, top_k=12,
                                  seed=5))
        m = 6
        p2 = np.concatenate([p, np.asarray(A[:m], np.int32)])
        B = _drain(eng, eng.start(p2, 16 - m, temperature=0.9, top_k=12,
                                  seed=5, rng_skip=m))
    assert B == A[m:]


def test_spec_capacity_reserve(model):
    """Admission reserves spec_k scratch positions: a request that
    would let the fixed K+1 verify window clamp past max_len is
    rejected up front."""
    with GenerationEngine(model, slots=1, max_len=32, spec_k=4,
                          spec_mode="ngram") as eng:
        with pytest.raises(ValueError, match="spec_k"):
            eng.start(np.arange(1, 17, dtype=np.int32), 16)
        gid = eng.start(np.arange(1, 13, dtype=np.int32), 16)  # 12+16+4
        assert len(_drain(eng, gid)) == 16


# -- load-adaptive shedding -------------------------------------------------

def test_occupancy_shedding(model):
    """Above the occupancy threshold the engine sheds speculation
    entirely (batched decode already fills the device) — output stays
    byte-identical, zero drafts are proposed. Below it, speculation
    engages."""
    p = _prompts(1)[0]
    ref = generate(model, p[None], MAX_NEW)[0, p.size:].tolist()
    with GenerationEngine(model, slots=2, max_len=40, spec_k=4,
                          spec_mode="ngram",
                          spec_shed_occupancy=0.0) as shed:
        out = _drain(shed, shed.start(p, MAX_NEW))
        st = shed.stats()
        assert out == ref
        assert st["spec"]["proposed"] == 0          # always shed
        assert st["spec"]["verify_steps"] == 0
    with GenerationEngine(model, slots=2, max_len=40, spec_k=4,
                          spec_mode="ngram",
                          spec_shed_occupancy=1.0) as solo:
        out = _drain(solo, solo.start(p, MAX_NEW))
        assert out == ref
        assert solo.stats()["spec"]["proposed"] > 0  # engaged


# -- observability ----------------------------------------------------------

def test_spec_counters_and_histograms(model):
    p0 = get_stat("gen/spec_proposed")
    a0 = get_stat("gen/spec_accepted")
    r0 = get_stat("gen/spec_rejected")
    with GenerationEngine(model, slots=2, max_len=40, spec_k=4,
                          spec_mode="ngram",
                          spec_shed_occupancy=1.0) as eng:
        _drain(eng, eng.start(_prompts(1)[0], MAX_NEW))
        st = eng.stats()["spec"]
    assert get_stat("gen/spec_proposed") - p0 == st["proposed"] > 0
    assert get_stat("gen/spec_accepted") - a0 == st["accepted"]
    assert get_stat("gen/spec_rejected") - r0 == st["rejected"]
    assert 0.0 <= st["accept_rate"] <= 1.0
    assert get_histogram("gen/spec_accept_len") is not None
    assert get_histogram("gen/spec_verify_s") is not None


def test_health_ships_spec_stats(model):
    srv = InferenceServer().start()
    try:
        with GenerationEngine(model, slots=2, max_len=40, spec_k=4,
                              spec_mode="ngram",
                              spec_shed_occupancy=1.0) as eng:
            srv.add_generator("sllm", eng)
            client = InferenceClient(srv.endpoint)
            try:
                _drain_client = eng.start(_prompts(1)[0], MAX_NEW)
                _drain(eng, _drain_client)
                g = client.health()["generators"]["sllm"]
            finally:
                client.close()
        assert g["spec"]["k"] == 4
        assert g["spec"]["accept_rate"] >= 0.0
        assert g["tokens_per_step"] > 0
    finally:
        srv.stop()


# -- defaults-off -----------------------------------------------------------

def test_defaults_off(model):
    """With the gen_spec_* flags at their defaults the engine builds no
    spec step, reports no spec stats, and moves no spec counters — the
    decode path is the pre-speculation one."""
    from paddle_tpu.core.flags import get_flags
    f = get_flags(["gen_spec_k", "gen_spec_mode", "gen_spec_ngram",
                   "gen_spec_shed_occupancy"])
    assert f["gen_spec_k"] == 0
    p0 = get_stat("gen/spec_proposed")
    p = _prompts(1)[0]
    ref = generate(model, p[None], MAX_NEW)[0, p.size:].tolist()
    with GenerationEngine(model, slots=2, max_len=40) as eng:
        out = _drain(eng, eng.start(p, MAX_NEW))
        st = eng.stats()
    assert out == ref
    assert "spec" not in st
    assert st["tokens_per_step"] > 0       # backfilled on the plain path
    assert eng._spec_step is None
    assert get_stat("gen/spec_proposed") == p0


def test_spec_config_validation(model):
    with pytest.raises(ValueError, match="gen_spec_mode"):
        GenerationEngine(model, slots=1, max_len=32, spec_k=2,
                         spec_mode="bogus")
    with pytest.raises(ValueError, match="draft_model"):
        GenerationEngine(model, slots=1, max_len=32, spec_k=2,
                         spec_mode="draft")
