"""PS-mode multi-process worker for launcher tests: rank 0 hosts the
parameter server, all ranks train a sparse embedding against it (the
reference's ps-mode TestDistBase workload shape)."""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from paddle_tpu.distributed.ps import Communicator, ParameterServer, PSClient


def main():
    out_dir = os.environ.get("TOY_OUT", ".")
    rank = int(os.environ["PTPU_RANK"])
    world = int(os.environ["PTPU_NUM_PROCESSES"])
    port = int(os.environ["PS_PORT"])

    server = None
    if rank == 0:
        server = ParameterServer(port=port).start()
    # every rank (incl. 0, which also trains) connects to the service
    import time
    for _ in range(100):
        try:
            client = PSClient(f"127.0.0.1:{port}")
            break
        except OSError:
            time.sleep(0.1)
    comm = Communicator(client, "sync")
    comm.create_table("emb", 4, optimizer="sgd", lr=0.05, seed=1)

    ids = np.arange(rank * 4, rank * 4 + 4)      # disjoint rows per rank
    target = np.zeros((4, 4), np.float32)
    client.barrier(world)
    losses = []
    for _ in range(20):
        rows = comm.pull("emb", ids)
        losses.append(float(((rows - target) ** 2).sum()))
        comm.push_grad("emb", ids, 2 * (rows - target))
    client.barrier(world)

    with open(os.path.join(out_dir, f"ps_losses.{rank}.json"), "w") as f:
        json.dump(losses, f)
    client.barrier(world)
    if server is not None:
        server.stop()


if __name__ == "__main__":
    main()
