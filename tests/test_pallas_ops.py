"""OpTest-style checks for the Pallas kernel set (paddle_tpu.ops.pallas).

Strategy (reference ``tests/unittests/op_test.py:226`` pattern):
- outputs: kernel (interpret mode on CPU) vs the jnp reference
  implementation, elementwise;
- gradients: kernel's custom_vjp vs jax.grad of the jnp reference —
  the jnp references themselves are FD-checked (tests/test_nn.py via
  tests/op_test.py), so this chains to finite differences;
- plus one direct FD check on the cheapest kernel (rms_norm) to anchor
  the chain.
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.nn.functional as F
from tests import op_test

FA = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")
NORM = importlib.import_module("paddle_tpu.ops.pallas.norm")
SX = importlib.import_module("paddle_tpu.ops.pallas.softmax_xent")
ROPE = importlib.import_module("paddle_tpu.ops.pallas.rope")
AW = importlib.import_module("paddle_tpu.ops.pallas.adamw")


def ref_attention(q, k, v, causal):
    return F.scaled_dot_product_attention(q, k, v, causal=causal,
                                          use_pallas="never")


@pytest.mark.parametrize("B,T,Hq,Hkv,D,causal", [
    (2, 256, 4, 4, 64, True),
    (1, 256, 4, 2, 128, True),    # GQA
    (2, 128, 2, 2, 64, False),
    (1, 512, 2, 1, 64, True),     # MQA, multiple q/k blocks
])
def test_flash_attention_matches_dense(B, T, Hq, Hkv, D, causal):
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, T, Hq, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, T, Hkv, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, T, Hkv, D).astype(np.float32))
    assert FA.supported(q, k, v, causal=causal)

    out = FA.flash_attention(q, k, v, causal=causal)
    ref = ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    def loss_fa(q, k, v):
        return jnp.sum(jnp.sin(FA.flash_attention(q, k, v, causal=causal)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(ref_attention(q, k, v, causal)))

    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_flash_attention_decode_shape():
    """Tq < Tk (decode with cache): causal offset must align diagonals."""
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 128, 2, 64).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 256, 2, 64).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 256, 2, 64).astype(np.float32))
    assert FA.supported(q, k, v, causal=True)
    out = FA.flash_attention(q, k, v, causal=True)
    ref = ref_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_unsupported_falls_back():
    q = jnp.zeros((1, 100, 2, 64))   # 100 not divisible by block
    assert not FA.supported(q, q, q, causal=True)
    q = jnp.zeros((1, 128, 2, 48))   # head_dim 48
    assert not FA.supported(q, q, q, causal=True)


def test_sdpa_use_pallas_always():
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(1, 128, 2, 64).astype(np.float32))
    out = F.scaled_dot_product_attention(q, q, q, causal=True,
                                         use_pallas="always")
    ref = F.scaled_dot_product_attention(q, q, q, causal=True,
                                         use_pallas="never")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    bad = jnp.zeros((1, 100, 2, 64))
    with pytest.raises(RuntimeError, match="use_pallas"):
        F.scaled_dot_product_attention(bad, bad, bad, causal=True,
                                       use_pallas="always")


@pytest.mark.parametrize("dtype", [np.float32, "bfloat16"])
def test_rms_norm_kernel(dtype):
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(64, 256)).astype(dtype)
    w = jnp.asarray(rs.randn(256).astype(np.float32)).astype(dtype)
    assert NORM.supported(x, w)
    out = NORM.rms_norm(x, w)
    ref = F.rms_norm(x, w)
    tol = 1e-5 if dtype == np.float32 else 5e-2
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)
    if dtype != np.float32:
        return
    g1 = jax.grad(lambda x, w: jnp.sum(jnp.sin(NORM.rms_norm(x, w))),
                  argnums=(0, 1))(x, w)
    g2 = jax.grad(lambda x, w: jnp.sum(jnp.sin(F.rms_norm(x, w))),
                  argnums=(0, 1))(x, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_rms_norm_kernel_fd():
    """Direct finite-difference anchor on the kernel itself (f64 runs
    through the interpreter)."""
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(8, 128).astype(np.float32))
    w = jnp.asarray(rs.randn(128).astype(np.float32))
    op_test.check_grad(lambda x, w: NORM.rms_norm(x, w), [x, w],
                       wrt=(1,), rtol=1e-2, atol=1e-3)


def test_layer_norm_kernel():
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(64, 256).astype(np.float32))
    w = jnp.asarray(rs.randn(256).astype(np.float32))
    b = jnp.asarray(rs.randn(256).astype(np.float32))
    assert NORM.supported(x, w)
    np.testing.assert_allclose(
        np.asarray(NORM.layer_norm(x, w, b)),
        np.asarray(F.layer_norm(x, w, b)),  # on_tpu()=False → jnp path
        rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda *a: jnp.sum(jnp.sin(NORM.layer_norm(*a))),
                  argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(lambda *a: jnp.sum(jnp.sin(F.layer_norm(*a))),
                  argnums=(0, 1, 2))(x, w, b)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=2e-4)


def test_softmax_cross_entropy_kernel():
    rs = np.random.RandomState(6)
    logits = jnp.asarray(rs.randn(64, 512).astype(np.float32) * 3)
    labels = jnp.asarray(rs.randint(0, 512, (64,)).astype(np.int32))
    assert SX.supported(logits, labels)
    out = SX.softmax_cross_entropy(logits, labels)
    ref = F.softmax_with_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda l: jnp.mean(SX.softmax_cross_entropy(l, labels)))(
        logits)
    g2 = jax.grad(lambda l: jnp.mean(F.softmax_with_cross_entropy(
        l, labels)))(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)


def test_rope_kernel():
    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(2, 128, 4, 64).astype(np.float32))
    cos, sin = F.rotary_embedding(jnp.arange(128), 64)
    assert ROPE.supported(x, cos, sin)
    np.testing.assert_allclose(
        np.asarray(ROPE.apply_rotary(x, cos, sin)),
        np.asarray(F.apply_rotary(x, cos, sin)), rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda x: jnp.sum(jnp.sin(ROPE.apply_rotary(
        x, cos, sin))))(x)
    g2 = jax.grad(lambda x: jnp.sum(jnp.sin(F.apply_rotary(
        x, cos, sin))))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


def test_adamw_kernel_matches_optimizer_math():
    rs = np.random.RandomState(8)
    p = jnp.asarray(rs.randn(33, 7).astype(np.float32))  # padding path
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)
    g = jnp.asarray(rs.randn(33, 7).astype(np.float32))
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-8, 0.01
    p1, m1, v1 = p, m, v
    for step in (1, 2, 3):
        p1, m1, v1 = AW.adamw_update(p1, m1, v1, g, lr=lr, beta1=b1,
                                     beta2=b2, eps=eps, weight_decay=wd,
                                     step=step)
    # plain-jnp reference
    p2, m2, v2 = p, m, v
    for step in (1, 2, 3):
        m2 = b1 * m2 + (1 - b1) * g
        v2 = b2 * v2 + (1 - b2) * g * g
        mh = m2 / (1 - b1 ** step)
        vh = v2 / (1 - b2 ** step)
        p2 = p2 - lr * (mh / (jnp.sqrt(vh) + eps) + wd * p2)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                               rtol=1e-5, atol=1e-6)


def test_dispatch_wrappers_forced(monkeypatch):
    """Exercise the functional.py auto-dispatch wrappers on CPU by forcing
    the gate open (kernels run interpreted) — covers the reshape /
    ignore_index / fallback glue that on_tpu() normally hides from CI."""
    support = importlib.import_module("paddle_tpu.ops.pallas._support")
    monkeypatch.setattr(support, "dispatch_mode", lambda: "raw")
    rs = np.random.RandomState(11)

    # rms_norm + layer_norm via the wrapper (3D input → reshape round-trip)
    x = jnp.asarray(rs.randn(4, 16, 256).astype(np.float32))
    w = jnp.asarray(rs.randn(256).astype(np.float32))
    b = jnp.asarray(rs.randn(256).astype(np.float32))
    np.testing.assert_allclose(np.asarray(F.rms_norm(x, w)),
                               np.asarray(NORM.rms_norm(x, w)),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(F.layer_norm(x, w, b)),
                               np.asarray(NORM.layer_norm(x, w, b)),
                               rtol=1e-6, atol=1e-6)
    # broadcastable-but-not-(h,) bias must fall back, not crash
    bad_bias = jnp.zeros((1,), jnp.float32)
    out = F.layer_norm(x, w, bad_bias)
    assert out.shape == x.shape

    # softmax_with_cross_entropy wrapper: [B, T, V] + ignore_index masking
    logits = jnp.asarray(rs.randn(2, 64, 512).astype(np.float32))
    labels = rs.randint(0, 512, (2, 64)).astype(np.int32)
    labels[0, :5] = -100
    labels = jnp.asarray(labels)
    got = F.softmax_with_cross_entropy(logits, labels)
    monkeypatch.setattr(support, "dispatch_mode", lambda: "off")
    ref = F.softmax_with_cross_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert float(jnp.max(jnp.abs(got[0, :5]))) == 0.0

    # apply_rotary wrapper
    monkeypatch.setattr(support, "dispatch_mode", lambda: "raw")
    x4 = jnp.asarray(rs.randn(2, 128, 4, 64).astype(np.float32))
    cos, sin = F.rotary_embedding(jnp.arange(128), 64)
    got = F.apply_rotary(x4, cos, sin)
    monkeypatch.setattr(support, "dispatch_mode", lambda: "off")
    ref = F.apply_rotary(x4, cos, sin)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dispatch_mode_under_multidevice_mesh(devices8):
    """Under a >1-device mesh the kernel set dispatches through the
    custom_partitioning wrappers (mode 'partitioned'); single device goes
    straight to pallas ('raw'); off-TPU without the force flag stays on
    the jnp path ('off')."""
    from paddle_tpu.parallel import mesh as M
    support = importlib.import_module("paddle_tpu.ops.pallas._support")
    mesh = M.create_mesh({"dp": 8}, devices8)
    assert support.single_device()
    assert support.dispatch_mode() == "off"  # CPU, no force
    with support.force_dispatch():
        assert support.dispatch_mode() == "raw"
        with M.MeshContext(mesh):
            assert not support.single_device()
            assert support.dispatch_mode() == "partitioned"
        assert support.dispatch_mode() == "raw"
    assert support.dispatch_mode() == "off"


def test_flash_attention_in_jit_and_remat():
    """Kernel must compose with jit + jax.checkpoint (the train step)."""
    rs = np.random.RandomState(9)
    q = jnp.asarray(rs.randn(1, 128, 2, 64).astype(np.float32))

    @jax.jit
    def step(q):
        def f(q):
            return jnp.sum(FA.flash_attention(q, q, q, causal=True) ** 2)
        return jax.grad(jax.checkpoint(f))(q)

    g = step(q)
    ref = jax.grad(lambda q: jnp.sum(
        ref_attention(q, q, q, True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
