#!/usr/bin/env python
"""Fleet performance-attribution report from the request ledger.

Probes N serving endpoints, pulls their ``health`` snapshots and ledger
dumps (the ``ledger_dump`` wire op, served when ``FLAGS_gen_ledger`` is
on), and answers the three capacity questions in one document:

- **Where does the engine's wall clock go?** The goodput taxonomy —
  prefill / decode / spec_verify vs host_gather / admission_idle /
  recompile / watchdog_stuck — rolled up across every engine, with the
  headline ``goodput`` fraction (useful-token time / total).
- **Where does a request's latency go?** Per-phase decomposition
  (admit_wait → prefill → decode → deliver) of the finalized request
  records, plus the fleet-merged phase percentile histograms from
  health.
- **Who consumed the fleet?** Per-tenant tokens / chip-seconds /
  queue-wait, merged across engines and the infer-side book.

This is the live, fleet-wide successor to the reference's
``tools/timeline.py`` post-hoc profile merge: attribution is computed
from always-on counters scraped over the wire, no profile files.

Usage::

    python tools/perf_report.py HOST:PORT [HOST:PORT ...] \
        [--json] [--limit N] [--timeout S]

Human-readable by default; ``--json`` emits the raw report document.
Exits nonzero if every endpoint is unreachable, or none has the ledger
on. ``tools/bench_generation.py`` imports the rollup helpers to build
``BENCH_goodput.json`` from in-process engines.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.serving.ledger import (  # noqa: E402
    GOODPUT_BUCKETS, GOODPUT_USEFUL, PHASES,
)

#: health histograms the ledger observes; merged fleet-wide for the
#: latency-decomposition percentiles
PHASE_HISTOGRAMS = ("gen/e2e_s",) + tuple(f"gen/phase/{p}" for p in PHASES)

#: priority classes the scheduler books queue waits under
#: (``gen/sched/wait_s/<class>``, FLAGS_gen_sched)
SCHED_CLASSES = ("interactive", "batch", "best_effort")
SCHED_HISTOGRAMS = tuple(f"gen/sched/wait_s/{c}" for c in SCHED_CLASSES)


def goodput_rollup(docs: list[dict]) -> dict | None:
    """Merge engine ``goodput`` snapshots by summing per-bucket seconds
    (weighting each engine by the wall clock it accounted). None when
    the list is empty — the ledger is off everywhere."""
    docs = [d for d in docs if isinstance(d, dict)]
    if not docs:
        return None
    buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
    total, ticks = 0.0, 0
    for d in docs:
        total += float(d.get("total_s", 0.0))
        ticks += int(d.get("ticks", 0))
        for b, v in (d.get("buckets") or {}).items():
            buckets[b] = buckets.get(b, 0.0) + float(v)
    useful = sum(buckets[b] for b in GOODPUT_USEFUL)
    return {
        "engines": len(docs), "total_s": total, "ticks": ticks,
        "buckets": buckets,
        "fractions": {b: (v / total if total > 0 else 0.0)
                      for b, v in buckets.items()},
        "goodput": useful / total if total > 0 else 0.0,
    }


def phase_decomposition(records: list[dict]) -> dict | None:
    """Aggregate finalized request records: per-phase mean/total
    seconds, mean end-to-end latency, outcome counts, resume count.
    None without records."""
    records = [r for r in records if isinstance(r, dict)]
    if not records:
        return None
    n = len(records)
    phase_tot = {p: 0.0 for p in PHASES}
    e2e_tot = 0.0
    tokens = 0
    outcomes: dict[str, int] = {}
    resumed = 0
    for r in records:
        e2e_tot += float(r.get("e2e_s", 0.0))
        tokens += int(r.get("tokens", 0))
        for p in PHASES:
            phase_tot[p] += float((r.get("phases") or {}).get(p, 0.0))
        o = str(r.get("outcome", "?"))
        outcomes[o] = outcomes.get(o, 0) + 1
        if r.get("resume"):
            resumed += 1
    return {
        "requests": n, "tokens": tokens, "resumed": resumed,
        "outcomes": outcomes,
        "e2e_mean_s": e2e_tot / n,
        "phase_mean_s": {p: t / n for p, t in phase_tot.items()},
        "phase_total_s": phase_tot,
        # share of end-to-end latency per phase (phases partition e2e
        # by construction, so these fractions sum to ~1.0)
        "phase_share": {p: (t / e2e_tot if e2e_tot > 0 else 0.0)
                        for p, t in phase_tot.items()},
    }


def tenant_rollup(docs: list[dict]) -> dict[str, dict[str, float]]:
    """Sum per-tenant counter blocks (engine ledgers + the infer-side
    book) into one tenant → counters table."""
    out: dict[str, dict[str, float]] = {}
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        for tenant, counters in doc.items():
            if not isinstance(counters, dict):
                continue
            agg = out.setdefault(str(tenant), {})
            for k, v in counters.items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0.0) + float(v)
    return out


def kv_rollup(docs: list[dict]) -> dict | None:
    """Merge engine ``kv`` gauge blocks (the tiered KV store's counters,
    ``FLAGS_gen_kv_store``) into the fleet scoreboard: hit rate over all
    lookups (spill_hits is a SUBSET of hits), fetch/put bytes, demotions
    vs drops, recompute debt. None when no engine runs a store."""
    docs = [d for d in docs if isinstance(d, dict)]
    if not docs:
        return None
    counters: dict[str, float] = {}
    roles: dict[str, int] = {}
    for d in docs:
        role = d.get("role")
        if isinstance(role, str):
            roles[role] = roles.get(role, 0) + 1
        for k, v in d.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                counters[k] = counters.get(k, 0.0) + float(v)
    hits = counters.get("hits", 0.0)
    lookups = hits + counters.get("misses", 0.0)
    return {
        "engines": len(docs), "roles": roles,
        "hit_rate": hits / lookups if lookups > 0 else 0.0,
        "lookups": lookups,
        "spill_hits": counters.get("spill_hits", 0.0),
        "fetch_bytes": counters.get("fetch_bytes", 0.0),
        "put_bytes": counters.get("put_bytes", 0.0),
        "published": counters.get("published", 0.0),
        "fetched_pages": counters.get("fetched_pages", 0.0),
        "demotions": counters.get("demotions", 0.0),
        "dropped": counters.get("dropped", 0.0),
        "prefill_recomputed": counters.get("prefill_recomputed", 0.0),
    }


def emb_rollup(docs: list[dict]) -> dict | None:
    """Merge replica ``emb`` health blocks (the PS-backed sparse
    embedding serving tier, ``FLAGS_serving_emb``) into the fleet
    scoreboard: hot-row hit rate over all lookups, pulled rows/bytes
    off the PS fleet, stale serves (zero in a healthy fleet),
    rollovers, and each table's per-replica version spread — more than
    one version means a rollover is still propagating. None when no
    replica runs the tier."""
    docs = [d for d in docs if isinstance(d, dict)]
    if not docs:
        return None
    counters: dict[str, float] = {}
    versions: dict[str, set] = {}
    for d in docs:
        for k, v in d.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                counters[k] = counters.get(k, 0.0) + float(v)
        for name, t in (d.get("tables") or {}).items():
            if isinstance(t, dict) and "version" in t:
                versions.setdefault(str(name), set()).add(
                    int(t["version"]))
    hits = counters.get("hits", 0.0)
    lookups = hits + counters.get("misses", 0.0)
    return {
        "replicas": len(docs),
        "hit_rate": hits / lookups if lookups > 0 else 0.0,
        "lookups": lookups,
        "pulled_rows": counters.get("pulled_rows", 0.0),
        "pulled_bytes": counters.get("pulled_bytes", 0.0),
        "stale_serves": counters.get("stale_serves", 0.0),
        "rollovers": counters.get("rollovers", 0.0),
        "evictions": counters.get("evictions", 0.0),
        "versions": {n: sorted(vs) for n, vs in versions.items()},
    }


def sched_rollup(docs: list[dict],
                 wait_hists: dict[str, dict] | None = None) -> dict | None:
    """Merge engine ``sched`` policy blocks (the SLO-aware scheduler's
    counters, ``FLAGS_gen_sched``) into the fleet scoreboard:
    preemptions, quota throttles, per-class admissions and sheds, plus
    per-class queue-wait percentiles from the merged
    ``gen/sched/wait_s/<class>`` histograms. None when no engine runs
    the scheduler."""
    docs = [d for d in docs if isinstance(d, dict)]
    if not docs:
        return None
    admitted = {c: 0 for c in SCHED_CLASSES}
    sheds = {c: 0 for c in SCHED_CLASSES}
    preemptions = throttles = 0
    for d in docs:
        preemptions += int(d.get("preemptions", 0))
        throttles += int(d.get("quota_throttles", 0))
        for c in SCHED_CLASSES:
            admitted[c] += int((d.get("admitted") or {}).get(c, 0))
            sheds[c] += int((d.get("sheds") or {}).get(c, 0))
    out = {
        "engines": len(docs),
        "preemptions": preemptions,
        "quota_throttles": throttles,
        "admitted": admitted,
        "sheds": sheds,
    }
    waits = {}
    for c in SCHED_CLASSES:
        h = (wait_hists or {}).get(f"gen/sched/wait_s/{c}")
        if h and h.get("count"):
            waits[c] = {k: round(float(h[k]), 6)
                        for k in ("count", "p50", "p95", "p99")}
    if waits:
        out["wait_s"] = waits
    return out


def scrape(endpoint: str, *, limit: int | None,
           timeout: float) -> dict:
    """One endpoint → {endpoint, health, ledger}; raises on wire
    errors. ``ledger`` is None when FLAGS_gen_ledger is off there."""
    from paddle_tpu.io.serving import InferenceClient

    with InferenceClient(endpoint, timeout=timeout, retries=0) as client:
        health = client.health(histograms=True)
        dump = client.ledger_dump(limit)
    ledger_on = bool(dump.get("generators")) or (
        dump.get("infer_tenants") is not None)
    return {"endpoint": endpoint, "health": health,
            "ledger": dump if ledger_on else None}


def build_report(scrapes: list[dict], *,
                 failed: list[dict] = ()) -> dict:
    """The fleet attribution document from a scrape list."""
    from paddle_tpu.core.monitor import merge_histograms

    goodputs: list[dict] = []
    records: list[dict] = []
    tenant_docs: list[dict] = []
    kv_docs: list[dict] = []
    sched_docs: list[dict] = []
    emb_docs: list[dict] = []
    hists: dict[str, list[dict]] = {}
    per_endpoint = []
    for s in scrapes:
        dump = s.get("ledger") or {}
        eng_dumps = (dump.get("generators") or {}).values()
        for d in eng_dumps:
            goodputs.append(d.get("goodput"))
            records.extend(d.get("records") or ())
            tenant_docs.append(d.get("tenants"))
        if dump.get("infer_tenants"):
            tenant_docs.append(dump["infer_tenants"])
        for g in (s["health"].get("generators") or {}).values():
            if isinstance(g, dict) and isinstance(g.get("kv"), dict):
                kv_docs.append(g["kv"])
            if isinstance(g, dict) and isinstance(g.get("sched"), dict):
                sched_docs.append(g["sched"])
        if isinstance(s["health"].get("emb"), dict):
            emb_docs.append(s["health"]["emb"])
        for name in PHASE_HISTOGRAMS + SCHED_HISTOGRAMS:
            h = (s["health"].get("histograms") or {}).get(name)
            if h and h.get("buckets"):
                hists.setdefault(name, []).append(h)
        per_endpoint.append({
            "endpoint": s["endpoint"],
            "status": s["health"].get("status"),
            "ledger": s.get("ledger") is not None,
            "engines": sorted(dump.get("generators") or ()),
        })
    merged = {name: merge_histograms(docs)
              for name, docs in hists.items()}
    return {
        "ok": True,
        "endpoints": per_endpoint,
        "failed": list(failed),
        "goodput": goodput_rollup(goodputs),
        "phases": phase_decomposition(records),
        "phase_percentiles": {
            name: {k: round(float(h[k]), 6)
                   for k in ("count", "p50", "p95", "p99")}
            for name in sorted(merged)
            if name in PHASE_HISTOGRAMS
            for h in (merged[name],)},
        "tenants": tenant_rollup(tenant_docs),
        "kv": kv_rollup(kv_docs),
        "sched": sched_rollup(sched_docs, merged),
        "emb": emb_rollup(emb_docs),
    }


def render(report: dict) -> str:
    """Human-readable report text (the default CLI output)."""
    lines: list[str] = []
    eps = report.get("endpoints") or []
    on = sum(1 for e in eps if e.get("ledger"))
    lines.append(f"fleet: {len(eps)} endpoint(s), ledger on at {on}; "
                 f"{len(report.get('failed') or ())} unreachable")
    gp = report.get("goodput")
    if gp:
        lines.append("")
        lines.append(f"goodput {gp['goodput'] * 100:6.2f}%  "
                     f"({gp['engines']} engine(s), "
                     f"{gp['total_s']:.2f}s accounted, "
                     f"{gp['ticks']} loop ticks)")
        for b in GOODPUT_BUCKETS:
            frac = gp["fractions"].get(b, 0.0)
            bar = "#" * int(round(frac * 40))
            lines.append(f"  {b:<15} {frac * 100:6.2f}%  "
                         f"{gp['buckets'].get(b, 0.0):9.3f}s  {bar}")
    ph = report.get("phases")
    if ph:
        lines.append("")
        lines.append(f"requests {ph['requests']}  tokens {ph['tokens']}  "
                     f"resumed {ph['resumed']}  "
                     f"outcomes {json.dumps(ph['outcomes'])}")
        lines.append(f"  e2e mean {ph['e2e_mean_s'] * 1e3:9.2f} ms")
        for p in PHASES:
            lines.append(f"  {p:<14} {ph['phase_mean_s'][p] * 1e3:9.2f} ms "
                         f"mean  ({ph['phase_share'][p] * 100:5.1f}% of e2e)")
    pp = report.get("phase_percentiles")
    if pp:
        lines.append("")
        lines.append(f"{'histogram':<24} {'count':>7} {'p50':>10} "
                     f"{'p95':>10} {'p99':>10}")
        for name, h in pp.items():
            lines.append(f"{name:<24} {h['count']:>7} "
                         f"{h['p50'] * 1e3:>8.2f}ms {h['p95'] * 1e3:>8.2f}ms "
                         f"{h['p99'] * 1e3:>8.2f}ms")
    kv = report.get("kv")
    if kv:
        lines.append("")
        roles = " ".join(f"{r}={n}" for r, n in
                         sorted(kv["roles"].items())) or "-"
        lines.append(f"kv store: {kv['engines']} engine(s)  "
                     f"roles {roles}")
        lines.append(f"  fleet hit rate {kv['hit_rate'] * 100:6.2f}%  "
                     f"({int(kv['lookups'])} lookups, "
                     f"{int(kv['spill_hits'])} from spill)")
        lines.append(f"  fetched {int(kv['fetched_pages'])} page(s) / "
                     f"{int(kv['fetch_bytes'])} B   published "
                     f"{int(kv['published'])} / {int(kv['put_bytes'])} B")
        lines.append(f"  demotions {int(kv['demotions'])}  dropped "
                     f"{int(kv['dropped'])}  prefill recomputed "
                     f"{int(kv['prefill_recomputed'])} tok")
    emb = report.get("emb")
    if emb:
        lines.append("")
        spread = " ".join(
            f"{t}={'/'.join(map(str, vs))}"
            for t, vs in sorted(emb["versions"].items())) or "-"
        lines.append(f"emb serving: {emb['replicas']} replica(s)  "
                     f"table versions {spread}"
                     + ("  [rollover propagating]"
                        if any(len(v) > 1 for v in
                               emb["versions"].values()) else ""))
        lines.append(f"  hot-row hit rate {emb['hit_rate'] * 100:6.2f}%  "
                     f"({int(emb['lookups'])} lookups, "
                     f"{int(emb['evictions'])} evictions)")
        lines.append(f"  pulled {int(emb['pulled_rows'])} row(s) / "
                     f"{int(emb['pulled_bytes'])} B   rollovers "
                     f"{int(emb['rollovers'])}   stale serves "
                     f"{int(emb['stale_serves'])}")
    sc = report.get("sched")
    if sc:
        lines.append("")
        lines.append(f"scheduler: {sc['engines']} engine(s)  "
                     f"preemptions {sc['preemptions']}  "
                     f"quota throttles {sc['quota_throttles']}")
        waits = sc.get("wait_s") or {}
        for c in SCHED_CLASSES:
            adm, shd = sc["admitted"].get(c, 0), sc["sheds"].get(c, 0)
            w = waits.get(c)
            wtxt = (f"  wait p50 {w['p50'] * 1e3:8.2f}ms "
                    f"p95 {w['p95'] * 1e3:8.2f}ms "
                    f"p99 {w['p99'] * 1e3:8.2f}ms" if w else "")
            lines.append(f"  {c:<12} admitted {adm:>6}  shed {shd:>5}"
                         f"{wtxt}")
    tens = report.get("tenants")
    if tens:
        lines.append("")
        lines.append(f"{'tenant':<16} {'requests':>8} {'tokens':>8} "
                     f"{'chip_s':>9} {'queue_wait_s':>12}")
        for t in sorted(tens, key=lambda t: -tens[t].get("chip_seconds", 0)):
            c = tens[t]
            lines.append(f"{t:<16} {int(c.get('requests', 0)):>8} "
                         f"{int(c.get('tokens', 0)):>8} "
                         f"{c.get('chip_seconds', 0.0):>9.3f} "
                         f"{c.get('queue_wait_s', 0.0):>12.4f}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("endpoints", nargs="+", metavar="HOST:PORT")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report document instead of text")
    ap.add_argument("--limit", type=int, default=None,
                    help="max ledger records per engine (default: all "
                         "buffered)")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)

    scrapes, failed = [], []
    for ep in args.endpoints:
        try:
            scrapes.append(scrape(ep, limit=args.limit,
                                  timeout=args.timeout))
        except (ConnectionError, RuntimeError, OSError) as e:
            failed.append({"endpoint": ep,
                           "error": f"{type(e).__name__}: {e}"})
    if not scrapes:
        print(json.dumps({"ok": False, "failed": failed}, indent=2))
        return 1
    report = build_report(scrapes, failed=failed)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
        if failed:
            for f in failed:
                print(f"unreachable: {f['endpoint']}: {f['error']}")
    # a report with the ledger off everywhere answers nothing: fail so
    # scripts notice the flag is missing rather than reading zeros
    return 0 if any(s.get("ledger") for s in scrapes) else 1


if __name__ == "__main__":
    sys.exit(main())
