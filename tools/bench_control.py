#!/usr/bin/env python
"""Serving control-plane benchmark: SLO-driven autoscaling under a
ramped generation load, and warm/cold multi-model multiplexing.

Two scenarios, both CPU, both end-to-end over the real wire:

1. **autoscale**: a fleet starts at ONE replica (2 generation slots,
   paced decode). The load ramps from 2 concurrent token streams to
   ``HIGH_STREAMS`` in repeated waves. The static fleet stays at one
   replica; the controlled fleet runs a ``ServingController``
   (queue-pressure + TTFT signals, hysteresis + cooldown) that scales up
   to three. Measured: client-side TTFT (``generate()`` call → first
   token) per wave. The acceptance floor: in the LAST high-load wave
   (steady state after convergence) the autoscaled fleet meets the TTFT
   SLO that the static fleet violates, with >= 1 scale-up; when the
   ramp ends, the idle fleet scales back down through a sticky drain
   with a live pinned stream riding through it — zero lost tokens, zero
   GenerationFailed, drain clean.
2. **burn**: the same ramp against a fleet whose ONLY scale-up signal
   is the dual-window SLO burn rate (queue and occupancy pressure
   disabled) — every scale-up must cite the burn in its reason and
   record ``ttft_burn_fast``/``ttft_burn_slow`` evidence above the
   threshold in its decision signals.
3. **multiplex**: one replica, warm-tier capacity 2, FOUR registered
   models. Round-robin inference across all four: every model stays
   servable (cold faults ride ``load_model``; LRU eviction keeps
   residency <= 2), outputs exactly match per-model direct Predictor
   runs.

Writes ``BENCH_control.json`` (repo root by default) with per-wave TTFT
quantiles for both fleets, the controller's decision log (every scale
event explainable), and the multiplex residency trace.

Usage: ``JAX_PLATFORMS=cpu python tools/bench_control.py [-o OUT.json]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu                                      # noqa: E402
from paddle_tpu import io, nn                          # noqa: E402
from paddle_tpu.core import monitor                    # noqa: E402
from paddle_tpu.serving import (                       # noqa: E402
    InProcSpawner, RoutedClient, ServingController,
)

VOCAB = 96
SLOTS = 2               # generation slots per replica
STEP_WAIT_S = 0.02      # paced decode: queueing is deterministic on CPU
NEW_TOKENS = 16
HIGH_STREAMS = 6
WAVES_HIGH = 4
TTFT_SLO_S = 0.55       # what the autoscaled fleet must meet at steady
#                         state (static: ~2 full generations of queue
#                         wait at HIGH_STREAMS over one replica's slots)
BURN_TTFT_SLO_S = 0.3   # burn-only run: tight enough that the high
#                         waves' queue wait lands in violating buckets
MAX_REPLICAS = 3


def _model():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    return LlamaForCausalLM(cfg)


def _engine_factory(model):
    def factory():
        srv = io.InferenceServer().start()
        srv.add_generator("llm", model, slots=SLOTS, max_len=32,
                          step_wait_s=STEP_WAIT_S)
        # pre-warm the engine's compiles so a freshly spawned replica
        # joins at serving speed (real fleets ship warmed images too)
        eng = srv._generators["llm"]
        gid = eng.start(np.arange(1, 7, dtype=np.int32), 1)
        while not eng.poll(gid, start=0, wait_s=1.0)["done"]:
            pass
        return srv
    return factory


def _quantiles(vals: list[float]) -> dict:
    if not vals:
        return {"n": 0}
    v = sorted(vals)
    return {"n": len(v),
            "p50": round(v[len(v) // 2], 4),
            "p99": round(v[min(len(v) - 1, int(len(v) * 0.99))], 4),
            "max": round(v[-1], 4)}


def _wave(router: RoutedClient, prompts, n_streams: int,
          errors: list) -> list[float]:
    """One wave: n concurrent streams; returns each stream's TTFT."""
    ttfts = [None] * n_streams
    gate = threading.Barrier(n_streams)

    def worker(i):
        try:
            gate.wait()
            t0 = time.perf_counter()
            it = router.session(f"wave-{i}-{t0}").generate(
                "llm", prompts[i % len(prompts)], NEW_TOKENS,
                poll_wait_s=0.02)
            next(it)
            ttfts[i] = time.perf_counter() - t0
            list(it)                      # run to completion
        except Exception as e:
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return [t for t in ttfts if t is not None]


def run_fleet(model, controlled: bool, burn: bool = False) -> dict:
    """The ramp against a static 1-replica fleet, the controlled fleet,
    or (``burn=True``) a fleet whose ONLY scale-up signal is the
    multi-window SLO burn rate: queue + occupancy pressure are disabled
    so every scale-up is attributable to the MetricsHub burn math, and
    the decision log must carry the burn evidence. Returns per-wave
    TTFT quantiles + fleet events."""
    spawner = InProcSpawner(_engine_factory(model))
    kw: dict = {}
    if burn:
        # burn-only: queue_high=0 disables the queue signal, occupancy
        # can never reach 2.0, and the tight target puts the high-wave
        # queue wait squarely in the violating buckets
        kw = dict(queue_high=0.0, occupancy_high=2.0,
                  target_ttft_s=BURN_TTFT_SLO_S, slo_budget=0.1,
                  burn_fast_ticks=3, burn_slow_ticks=12,
                  burn_threshold=1.0)
    ctl = ServingController(
        spawner, interval_s=0.25 if controlled else 0,
        min_replicas=1, max_replicas=MAX_REPLICAS if controlled else 0,
        breach_ticks=1, idle_ticks=3, cooldown_s=1.0,
        **(kw or dict(queue_high=0.5, target_ttft_s=TTFT_SLO_S)),
        drain_s=20.0)
    ctl.start()
    errors: list = []
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, VOCAB, (6,)).astype(np.int32)
               for _ in range(4)]
    waves = []
    result: dict = {"mode": ("burn" if burn else
                             "controlled" if controlled else "static")}
    try:
        # low phase: 2 streams — no pressure, fleet must NOT grow
        waves.append(("low", _quantiles(
            _wave(ctl.router, prompts, 2, errors))))
        # high phase: repeated waves; the controller sees the queue
        # build and scales between waves
        for w in range(WAVES_HIGH):
            waves.append((f"high{w}", _quantiles(
                _wave(ctl.router, prompts, HIGH_STREAMS, errors))))
        result["replicas_at_peak"] = len(ctl.router.endpoints())

        if controlled:
            # ramp over: pin a LIVE stream, then let the idle fleet
            # scale down THROUGH it (the sticky-drain proof point)
            sess = ctl.router.session("drain-rider")
            it = sess.generate("llm", prompts[0], NEW_TOKENS,
                               poll_wait_s=0.05)
            toks = [next(it)]

            def rider():                  # keeps polling like a real
                toks.extend(it)           # client while drains happen

            t = threading.Thread(target=rider)
            t.start()
            deadline = time.monotonic() + 30
            while (len(ctl.router.endpoints()) > 1
                   and time.monotonic() < deadline):
                time.sleep(0.2)
            t.join(timeout=60)
            result["drain_rider_tokens"] = len(toks)
            result["replicas_after_idle"] = len(ctl.router.endpoints())
    finally:
        ctl.close()
    result["waves"] = dict(waves)
    result["errors"] = errors
    if controlled:
        decs = ctl.decisions()
        result["decisions"] = decs
        result["scale_ups"] = sum(d["action"] == "scale_up" for d in decs)
        result["scale_downs"] = sum(
            d["action"] == "scale_down" for d in decs)
        result["drains_clean"] = all(
            d["clean"] for d in decs if d["action"] == "scale_down")
    return result


def run_multiplex(tmp: str) -> dict:
    """Warm capacity 2, four models, one replica: all servable, correct,
    residency bounded."""
    paths, refs = {}, {}
    for i, name in enumerate("abcd"):
        paddle_tpu.seed(i + 1)
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 3))
        p = os.path.join(tmp, f"mux_{name}")
        io.save_inference_model(p, net, [np.zeros((2, 4), np.float32)],
                                dynamic_batch=True)
        paths[name] = p
        refs[name] = io.Predictor(p)
    monitor.reset_stats("control/")
    ctl = ServingController(InProcSpawner(io.InferenceServer),
                            interval_s=0, min_replicas=1, warm_models=2)
    resident_trace, bad = [], 0
    try:
        ctl.start()
        for n, p in paths.items():
            ctl.register_model(n, p)
        x = np.ones((1, 4), np.float32)
        rounds = 4
        for _ in range(rounds):
            for n in paths:
                y = ctl.infer(n, x)[0]
                if not np.allclose(y, np.asarray(refs[n].run(x)),
                                   rtol=1e-5, atol=1e-6):
                    bad += 1
            ctl.tick()
            doc = next(iter(ctl.router.health().values()))
            resident_trace.append(sorted(doc["models"]))
    finally:
        ctl.close()
    return {
        "models_registered": len(paths),
        "warm_capacity": 2,
        "rounds": rounds,
        "bad_results": bad,
        "resident_trace": resident_trace,
        "max_resident": max(len(r) for r in resident_trace),
        "evictions": int(monitor.get_stat("control/model_evictions")),
        "fault_ins": int(monitor.get_stat("control/model_faults")),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("-o", "--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_control.json"))
    args = ap.parse_args()

    model = _model()
    results: dict = {
        "config": {"slots_per_replica": SLOTS, "step_wait_s": STEP_WAIT_S,
                   "new_tokens": NEW_TOKENS, "high_streams": HIGH_STREAMS,
                   "waves_high": WAVES_HIGH, "ttft_slo_s": TTFT_SLO_S,
                   "burn_ttft_slo_s": BURN_TTFT_SLO_S,
                   "max_replicas": MAX_REPLICAS},
    }
    print("== static fleet (1 replica) ==")
    static = run_fleet(model, controlled=False)
    print(json.dumps(static["waves"], indent=2))
    print("== controlled fleet (autoscaling 1..3) ==")
    controlled = run_fleet(model, controlled=True)
    print(json.dumps(controlled["waves"], indent=2))
    results["static"] = static
    results["controlled"] = controlled

    last = f"high{WAVES_HIGH - 1}"
    static_p99 = static["waves"][last]["p99"]
    auto_p99 = controlled["waves"][last]["p99"]
    results["autoscale_parsed"] = {
        "metric": "steady-state TTFT p99 under the high-load ramp, "
                  "autoscaled vs static single replica",
        "static_p99_s": static_p99,
        "autoscaled_p99_s": auto_p99,
        "speedup": round(static_p99 / auto_p99, 2) if auto_p99 else None,
    }
    autoscale_ok = (
        auto_p99 <= TTFT_SLO_S < static_p99
        and controlled["scale_ups"] >= 1
        and controlled["scale_downs"] >= 1
        and controlled["drains_clean"]
        and controlled["replicas_after_idle"] == 1
        and controlled["drain_rider_tokens"] == NEW_TOKENS
        and not static["errors"] and not controlled["errors"])
    results["autoscale_ok"] = autoscale_ok

    print("== burn-rate-driven fleet (TTFT burn is the ONLY signal) ==")
    burn = run_fleet(model, controlled=True, burn=True)
    print(json.dumps(burn["waves"], indent=2))
    results["burn"] = burn
    burn_ups = [d for d in burn["decisions"]
                if d["action"] == "scale_up"
                and "burn rate" in d["reason"]]
    results["burn_parsed"] = {
        "metric": "scale-ups driven purely by the dual-window SLO burn "
                  "rate (queue/occupancy pressure disabled), with the "
                  "burn evidence recorded in each decision's signals",
        "burn_scale_ups": len(burn_ups),
        "evidence": [{"reason": d["reason"],
                      "ttft_burn_fast": d["signals"]["ttft_burn_fast"],
                      "ttft_burn_slow": d["signals"]["ttft_burn_slow"]}
                     for d in burn_ups],
    }
    burn_ok = (
        len(burn_ups) >= 1
        and all(d["signals"].get("ttft_burn_fast", 0.0) > 1.0
                and d["signals"].get("ttft_burn_slow", 0.0) > 1.0
                for d in burn_ups)
        and burn["replicas_at_peak"] >= 2
        and not burn["errors"])
    results["burn_ok"] = burn_ok

    print("== multiplex (4 models, warm capacity 2, 1 replica) ==")
    with tempfile.TemporaryDirectory(prefix="ptpu_bench_ctl_") as tmp:
        mux = run_multiplex(tmp)
    print(json.dumps({k: v for k, v in mux.items()
                      if k != "resident_trace"}, indent=2))
    results["multiplex"] = mux
    multiplex_ok = (mux["bad_results"] == 0 and mux["max_resident"] <= 2
                    and mux["evictions"] >= 2)
    results["multiplex_ok"] = multiplex_ok

    results["parsed"] = {
        "metric": "autoscaled steady-state TTFT p99 vs TTFT SLO "
                  "(static fleet violates it); N>warm-tier models "
                  "servable via LRU eviction",
        "value": results["autoscale_parsed"]["speedup"],
        "unit": "x",
    }
    results["ok"] = bool(autoscale_ok and burn_ok and multiplex_ok)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results["parsed"], indent=2))
    print(f"wrote {args.out}; ok={results['ok']}")
    return 0 if results["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
