#!/usr/bin/env python
"""Chaos harness: drive the fault-tolerance layer end to end with
injection enabled and assert the recovery stats.

Scenarios (all CPU-only, single process):

1. **serving-wire**: an InferenceClient keeps answering through injected
   ``wire.send`` faults (retry/reconnect) AND through a real
   kill-and-restart of the server on the same port.
2. **checkpoint**: a corrupted latest step (bit-flip + truncation) rolls
   back to the newest verifiable step on load.
3. **elastic-resume**: a TrainEpochRange run crashed by an injected
   ``ckpt.save`` fault resumes from the previous verifiable step.
4. **overload**: with ``wire_max_inflight=1`` a concurrent infer burst is
   shed with the retryable status code 2, every client succeeds after
   backoff, the health op answers throughout, and ``drain()`` finishes
   in-flight work before severing.
5. **obs**: with ``FLAGS_trace`` on, a wire exchange under fault
   injection + an admission-cap shed records spans for the round-trip,
   the retries, and the shed waits — one trace id joins client and
   server — and the Chrome export parses as valid JSON.
6. **serving-routed**: one of three replicas is killed under routed,
   dynamically-batched load — zero idempotent requests are lost (the
   router fails them over to the survivors), router membership converges
   to mark the dead replica unhealthy, and cross-request batching
   demonstrably coalesced (fewer batches than batched requests).
7. **gen-engine**: three token streams share a continuous-batching
   GenerationEngine; one client is killed mid-stream (socket dropped, no
   cancel) — the poll TTL reclaims its slot, the surviving streams
   finish byte-identical to solo ``generate()``, a new generation is
   admitted into the reclaimed slot, and the ``gen/*`` counters stay
   consistent.
8. **gen-paged**: the paged engine (``FLAGS_gen_paged`` geometry: small
   pages, chunked prefill, prefix cache) under a client kill
   mid-chunked-prefill — the TTL reaps the victim BEFORE its prefill
   completes, every reserved page returns to the pool (no leaks: after
   the survivors finish and the prefix cache drains, the pool is back
   to full), survivors stay byte-identical to solo ``generate()``, and
   a prefix-sharing readmit lands in the reclaimed pages.
9. **control-plane**: (a) a subprocess replica is SIGKILLed right after
   joining a controller-driven scale-up, under live routed traffic —
   zero idempotent requests are lost and the controller's reconcile
   replaces the dead replica (typed ``replace`` decision); (b) a
   scale-down victim carrying a LIVE session-pinned generation is
   sticky-drained — the stream finishes byte-identical to solo
   ``generate()`` on the cordoned replica, zero ``GenerationFailed``,
   the drain is clean (not deadline-forced), and only then does the
   replica stop.
10. **gen-resilience**: (a) the subprocess replica holding a LIVE
    greedy stream is SIGKILLed under routed load — with a resume
    budget the stream replays prompt + delivered tokens onto the
    survivor and completes byte-identical to an uninterrupted solo
    ``generate()``, zero ``GenerationFailed`` surfaces, and the
    survivor's page pool drains back to full (zero leaked pages);
    (b) a poison request that traps an engine is quarantined by crash
    fingerprint — the typed ``RequestQuarantined`` surfaces through
    the resuming client and the second replica never crashes.
11. **gen-spec**: the subprocess replica holding a LIVE *speculating*
    stream (paged engine, ``--gen-spec-k 4`` n-gram drafter) is
    SIGKILLed — the stream resumes on the (also speculating) survivor
    byte-identical to solo ``generate()`` (``stream_resumes>=1``), the
    survivor's page pool drains back to full despite speculative
    rollback traffic, and health ships the acceptance stats.
12. **gen-sharded**: the tp=2 MESH-SHARDED subprocess replica
    (``--mesh-tp 2``: params Megatron-split, KV pool sharded on the
    KV-head axis over 2 virtual devices) holding a live stream is
    SIGKILLed under routed load — the stream resumes byte-identical on
    an UNSHARDED survivor (cross-layout determinism: the wire carries
    tokens + RNG position, never device layout), and the sharded
    replica's health shipped the ``device`` block (mesh {'tp': 2},
    per-device KV bytes half the unsharded pool).
13. **obs-fleet**: a TRACED stream (``FLAGS_trace`` inherited by the
    subprocess replicas) is SIGKILLed mid-flight and resumes on the
    survivor under the SAME stream trace id — the victim's span buffer,
    scraped moments before the kill, merges with the survivor's
    (scraped after completion) into one Chrome trace whose
    cross-endpoint stream count is >= 1 and whose merged timeline ends
    in the survivor's ``gen/retire reason=complete``; meanwhile a
    MetricsHub fed from routed ``health`` keeps answering windowed
    queries through the membership churn and prunes the dead replica.
14. **gen-disagg**: two DECODE-tier subprocess replicas (``--role
    decode --kv-store``) share one spill root; the replica holding a
    live stream whose page-aligned prompt was prefilled-and-published
    is SIGKILLed — the stream resumes byte-identical on the other
    decode replica via KV FETCH (``fetched_pages>=1``) with ZERO
    recomputed prefill tokens (``prefill_recomputed==0``: failover
    upgraded from token replay to page transfer) and zero leaked pages
    on the survivor.
15. **kv-campaign**: a seeded RANDOMIZED campaign over the KV failure
    domain — each scenario draws a store topology (shared spill / one
    shared store / peer tier), a producer/consumer role pair, hardening
    flags (fetch deadline, hedge, breaker), and a 1-3-site fault spec
    from the KV path, then asserts the invariants that must hold no
    matter what the faults did: streams byte-identical to solo
    ``generate()``, zero leaked pages, and every fired fault visible in
    the degradation ledger (tier errors/timeouts, ``fetch_degraded``).
    Ends with a deterministic breaker open → half-open → closed
    lifecycle check and a no-hot-path-flag-reads defaults check.
    ``--campaign N [--seed S]`` runs an N-scenario campaign standalone
    (defaults checks + campaign only).
16. **sparse-serve**: a PS-backed sparse-serving replica is SIGKILLed
    mid-version-rollover under routed load (two ``--emb-ps`` subprocess
    replicas over one PS fleet; the trainer publishes v1 right before
    the kill) — zero requests dropped (idempotent infers fail over),
    zero responses mixing two versions' rows, the survivor converges
    to the published version on its health tick, zero stale serves.
17. **control-ha**: the ACTIVE controller of an HA pair dies silently
    mid-flight (its last acts: a journaled-but-unfinished sticky drain
    and a spawn intent that never reported an endpoint) while a
    subprocess replica holds a LIVE token stream — the standby holds
    while the lease is live, claims it within one TTL of the silence
    (term bumped), replays the journal to the EXACT managed set,
    ADOPTS the live orphans (zero double-spawns; the in-flight stream
    rides through the takeover byte-identical to solo ``generate()``),
    surfaces the lost spawn intent, resumes the journaled drain clean,
    and fences the zombie leader's queued scale-up as a typed
    ``fenced`` decision that never executes.

Also asserts the production posture: every fault/retry/overload flag
defaults to hard-off/zero-cost (including the ``gen_spec_*`` family:
speculation is opt-in; the unflagged decode path is byte-identical to
the pre-speculation build — and ``gen_mesh_tp``: no mesh is built by
default, the engine's device layout is the identity and every compiled
entry point is the plain single-device jit).

Usage: ``JAX_PLATFORMS=cpu python tools/chaos_check.py`` for the full
suite, ``... chaos_check.py NAME [NAME ...]`` (e.g. ``control-ha``)
for the named scenarios only (defaults checks always run), or
``... chaos_check.py --campaign N [--seed S]`` for an
N-scenario randomized KV campaign standalone. Exits nonzero
(with a JSON report on stdout) if any recovery path or stat fails — a
scenario that raises is recorded as a failed check, never a bare
traceback, so the harness is CI-runnable as-is.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np       # noqa: E402

import paddle_tpu                                        # noqa: E402
from paddle_tpu import io, nn                            # noqa: E402
from paddle_tpu.core import fault, monitor, trace        # noqa: E402
from paddle_tpu.core.flags import get_flags, set_flags   # noqa: E402

CHECKS: list[tuple[str, bool, str]] = []


def check(name: str, ok: bool, detail: str = "") -> None:
    CHECKS.append((name, bool(ok), detail))


def check_defaults_off() -> None:
    f = get_flags(["fault_inject", "fault_seed", "wire_retries",
                   "wire_timeout_s", "ckpt_manifest"])
    check("defaults/injection_off", f["fault_inject"] == ""
          and not fault.enabled(), str(f))
    t = get_flags(["trace", "log_json"])
    check("defaults/trace_off", not t["trace"] and not trace.enabled()
          and not t["log_json"], str(t))
    check("defaults/deadline_finite", f["wire_timeout_s"] > 0, str(f))
    o = get_flags(["wire_max_inflight", "wire_max_conns",
                   "wire_server_idle_s", "ps_barrier_timeout_s"])
    check("defaults/overload_caps_off", o["wire_max_inflight"] == 0
          and o["wire_max_conns"] == 0 and o["wire_server_idle_s"] == 0,
          str(o))
    check("defaults/barrier_timeout_finite",
          o["ps_barrier_timeout_s"] > 0, str(o))
    s = get_flags(["serving_batch_max", "serving_batch_timeout_s"])
    check("defaults/serving_batching_off", s["serving_batch_max"] == 0,
          str(s))
    g = get_flags(["gen_slots", "gen_poll_ttl_s"])
    check("defaults/gen_engine_off", g["gen_slots"] == 0
          and g["gen_poll_ttl_s"] > 0, str(g))
    p = get_flags(["gen_paged", "gen_pages", "gen_prefill_chunk",
                   "gen_page_tokens"])
    check("defaults/gen_paged_off", not p["gen_paged"]
          and p["gen_pages"] == 0 and p["gen_prefill_chunk"] == 0
          and p["gen_page_tokens"] > 0, str(p))
    mq = get_flags(["serving_batch_min_queue"])
    check("defaults/batch_watermark_sane",
          mq["serving_batch_min_queue"] >= 0, str(mq))
    cpl = get_flags(["control_max_replicas", "control_warm_models",
                     "control_interval_s", "control_cooldown_s",
                     "control_drain_s", "control_breach_ticks",
                     "control_idle_ticks"])
    check("defaults/control_plane_off",
          cpl["control_max_replicas"] == 0        # autoscaling off
          and cpl["control_warm_models"] == 0     # eviction off
          and cpl["control_drain_s"] > 0 and cpl["control_cooldown_s"] > 0
          and cpl["control_breach_ticks"] >= 1
          and cpl["control_idle_ticks"] >= cpl["control_breach_ticks"],
          str(cpl))
    rz = get_flags(["gen_resume_budget", "gen_quarantine_after",
                    "gen_engine_rebuilds", "gen_watchdog_s",
                    "control_spawn_breaker", "control_spawn_backoff_s"])
    check("defaults/gen_resilience_off",
          rz["gen_resume_budget"] == 0            # no stream resumption
          and rz["gen_quarantine_after"] == 0     # no quarantine books
          and rz["gen_engine_rebuilds"] == 0      # trap still breaks
          and rz["gen_watchdog_s"] == 0           # no watchdog thread
          and rz["control_spawn_breaker"] == 0    # spawner never skipped
          and rz["control_spawn_backoff_s"] > 0,  # sane base when opted in
          str(rz))
    sk = get_flags(["gen_spec_k", "gen_spec_mode", "gen_spec_ngram",
                    "gen_spec_shed_occupancy"])
    check("defaults/gen_spec_off",
          sk["gen_spec_k"] == 0                   # no speculation at all
          and sk["gen_spec_mode"] == "ngram"      # weight-free drafter
          and sk["gen_spec_ngram"] >= 1           # sane when opted in
          and 0.0 <= sk["gen_spec_shed_occupancy"] <= 1.0,
          str(sk))
    mt = get_flags(["gen_mesh_tp"])
    check("defaults/gen_mesh_off",
          mt["gen_mesh_tp"] == 0,                 # no mesh, identity
          str(mt))                                # layout, plain jit
    ob = get_flags(["trace_sample", "control_slo_budget",
                    "control_burn_fast_ticks", "control_burn_slow_ticks",
                    "control_burn_threshold"])
    check("defaults/obs_burn_off",
          ob["trace_sample"] == 0                 # no per-token spans
          and ob["control_slo_budget"] > 0        # sane when opted in
          and 1 <= ob["control_burn_fast_ticks"]
          <= ob["control_burn_slow_ticks"]
          and ob["control_burn_threshold"] > 0, str(ob))
    led = get_flags(["gen_ledger", "gen_ledger_records"])
    check("defaults/gen_ledger_off",
          not led["gen_ledger"]                   # no ledger, no meter
          and led["gen_ledger_records"] > 0,      # sane when opted in
          str(led))
    hl = get_flags(["gen_device_pt", "gen_async_depth"])
    check("defaults/gen_hotloop_off",
          not hl["gen_device_pt"]                 # host page table
          and hl["gen_async_depth"] == 0,         # synchronous loop
          str(hl))
    kvs = get_flags(["gen_kv_store", "gen_role", "gen_kv_store_pages",
                     "gen_kv_spill_dir"])
    check("defaults/gen_kvstore_off",
          not kvs["gen_kv_store"]                 # no store, no tiers
          and kvs["gen_role"] == "both"           # no role split
          and kvs["gen_kv_store_pages"] > 0       # sane when opted in
          and kvs["gen_kv_spill_dir"] == "",      # no spill tier
          str(kvs))
    kvh = get_flags(["gen_kv_fetch_timeout_s", "gen_kv_admit_timeout_s",
                     "gen_kv_hedge_ms", "gen_kv_breaker",
                     "gen_kv_breaker_backoff_s", "gen_kv_peers"])
    check("defaults/gen_kv_hardening_off",
          kvh["gen_kv_fetch_timeout_s"] == 0.0    # unbounded, inline
          and kvh["gen_kv_admit_timeout_s"] == 0.0
          and kvh["gen_kv_hedge_ms"] == 0.0       # no hedging
          and kvh["gen_kv_breaker"] == 0          # no breakers
          and kvh["gen_kv_breaker_backoff_s"] > 0  # sane when opted in
          and kvh["gen_kv_peers"] == "",          # no peer tier
          str(kvh))
    # behavior at defaults: the store is THREAD-FREE — hedge/deadline
    # machinery must not exist to pay for, cold fetches are inline
    import threading as _threading

    from paddle_tpu.serving.kvstore import KVStore as _KVStore

    with tempfile.TemporaryDirectory(prefix="ptpu_kvdef_") as d:
        st = _KVStore(pages=4, spill=d)
        spawned = []
        real_thread = _threading.Thread

        def _spy_thread(*a, **k):
            spawned.append(k.get("name", "?"))
            return real_thread(*a, **k)

        _threading.Thread = _spy_thread
        try:
            st.put("k", b"x" * 8)
            got = st.get("k")
            miss = st.get("nope")
        finally:
            _threading.Thread = real_thread
            st.close()
        check("defaults/gen_kv_hardening_threadfree",
              not spawned and got == b"x" * 8 and miss is None,
              f"spawned={spawned}")

    haf = get_flags(["control_ha_lease_dir", "control_ha_lease_ttl_s",
                     "control_ha_holder", "control_ha_compact_records"])
    # behavior at defaults: the flag-default controller constructs NO
    # lease, NO journal, NO fencing wrapper, NO wire service, spawns no
    # thread, and writes no HA file — the pre-HA controller, byte for
    # byte (the HA flags are read once, at construction)
    from paddle_tpu.serving import InProcSpawner as _IPS
    from paddle_tpu.serving import ServingController as _SC
    from paddle_tpu.serving.ha import FencedSpawner as _FS

    spawned = []
    real_thread = _threading.Thread

    def _spy_thread(*a, **k):
        spawned.append(k.get("name", "?"))
        return real_thread(*a, **k)

    from paddle_tpu.serving import RoutedClient as _RC

    # probe-less router: its health-probe thread is default serving
    # behavior, not HA's — the spy must only see what HA would add
    router = _RC(probe_interval_s=0)
    _threading.Thread = _spy_thread
    try:
        ctl = _SC(_IPS(io.InferenceServer), router=router,
                  interval_s=0, min_replicas=0)
        ctl.start()
        for _ in range(3):
            d = ctl.tick()
        dump = ctl.control_dump()
        ctl.close()
    finally:
        _threading.Thread = real_thread
        router.close()
    check("defaults/control_ha_off",
          haf["control_ha_lease_dir"] == ""
          and haf["control_ha_holder"] == ""
          and haf["control_ha_lease_ttl_s"] == 3.0    # sane opt-in TTL
          and haf["control_ha_compact_records"] == 256
          and ctl._lease is None and ctl._journal is None
          and ctl._service is None
          and not isinstance(ctl._spawner, _FS)       # unwrapped
          and d.action == "hold" and "leader" not in dump
          and not spawned,
          f"flags={haf} spawned={spawned}")
    sc = get_flags(["gen_sched", "gen_sched_w_interactive",
                    "gen_sched_w_batch", "gen_sched_w_best_effort",
                    "gen_sched_quotas", "gen_sched_chunk",
                    "gen_sched_headroom"])
    check("defaults/gen_sched_off",
          not sc["gen_sched"]                     # no scheduler object
          and sc["gen_sched_quotas"] == ""        # no quota map
          # sane class-weight ordering when opted in
          and sc["gen_sched_w_interactive"] >= sc["gen_sched_w_batch"]
          >= sc["gen_sched_w_best_effort"] > 0
          and sc["gen_sched_chunk"] > 0
          and sc["gen_sched_headroom"] >= 0,
          str(sc))
    se = get_flags(["serving_emb", "serving_emb_cache_rows",
                    "serving_emb_ttl_s"])
    # behavior at defaults: attach_embeddings is a None no-op — the
    # server constructs NO tier, polls no versions, ships no "emb"
    # health block (the flag is read once, at server construction)
    _srv = io.InferenceServer({})
    check("defaults/serving_emb_off",
          not se["serving_emb"]
          and se["serving_emb_cache_rows"] > 0    # sane when opted in
          and se["serving_emb_ttl_s"] == 0.0      # no TTL by default
          and _srv.attach_embeddings(None) is None
          and _srv._emb_tier is None,
          str(se))


def scenario_serving_wire(tmp: str) -> None:
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = os.path.join(tmp, "mlp")
    io.save_inference_model(path, net, [np.zeros((2, 4), np.float32)])

    srv = io.InferenceServer({"m": path}).start()
    port = srv.port
    client = io.InferenceClient(srv.endpoint, timeout=10.0)
    x = np.ones((2, 4), np.float32)
    monitor.reset_stats("wire/")
    monitor.reset_stats("fault/")

    # injected send faults ride the retry path transparently
    with fault.inject_faults({"wire.send": (1.0, 2)}, seed=7):
        (y1,) = client.infer("m", x)
    check("wire/injected_faults_fired",
          monitor.get_stat("fault/injected/wire.send") == 2)
    check("wire/retries_recovered", monitor.get_stat("wire/retries") >= 2)

    # real kill + restart on the same port
    srv.stop()
    srv2 = io.InferenceServer({"m": path}, port=port).start()
    (y2,) = client.infer("m", x)
    check("wire/survives_restart", np.allclose(y1, y2))
    check("wire/reconnects", monitor.get_stat("wire/reconnects") >= 1)
    client.stop_server()
    client.close()
    srv2.stop()


def _tpl(v=0.0, step=0):
    return {"w": jnp.full((8, 8), float(v)), "step": jnp.asarray(int(step))}


def scenario_checkpoint(tmp: str) -> None:
    d = os.path.join(tmp, "ck")
    for s in (1, 2, 3):
        io.save_checkpoint(_tpl(s, s), d, step=s)
    io.checkpoint.wait_until_finished(d)
    # corrupt the latest step: flip + truncate every substantial file
    for root, _, files in os.walk(os.path.join(d, "3")):
        for name in files:
            p = os.path.join(root, name)
            size = os.path.getsize(p)
            if size < 8:
                continue
            with open(p, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
                f.truncate(max(size // 2, 8))
    monitor.reset_stats("ckpt/")
    restored, used = io.load_checkpoint(_tpl(), d, return_step=True)
    check("ckpt/fell_back_to_good_step",
          used == 2 and float(restored["w"][0, 0]) == 2.0)
    check("ckpt/rollbacks_stat", monitor.get_stat("ckpt/rollbacks") >= 1)
    check("ckpt/corrupt_steps_stat",
          monitor.get_stat("ckpt/corrupt_steps") >= 1)


def scenario_elastic_resume(tmp: str) -> None:
    d = os.path.join(tmp, "run")
    monitor.reset_stats("fault/")
    r = io.TrainEpochRange(6, d, state=_tpl(-1, -1))
    crashed = False
    try:
        for epoch in r:
            r.state = _tpl(epoch, epoch)
            if epoch == 2:
                fault.configure({"ckpt.save": 1.0}, seed=0)
    except fault.InjectedFault:
        crashed = True
    finally:
        fault.reset()
    io.checkpoint.wait_until_finished(d)
    r2 = io.TrainEpochRange(6, d, state=_tpl())
    check("resume/crashed_as_injected", crashed
          and monitor.get_stat("fault/injected/ckpt.save") == 1)
    check("resume/rolled_to_verifiable",
          r2.resumed and r2.start_epoch == 2
          and int(r2.state["step"]) == 1)


def scenario_overload(tmp: str) -> None:
    import threading
    import time

    class _SlowPredictor:
        input_specs = output_specs = []

        def run(self, x):
            time.sleep(0.05)
            return np.asarray(x)

    srv = io.InferenceServer()
    srv.add_model("slow", _SlowPredictor())
    srv.start()
    monitor.reset_stats("wire/")
    set_flags({"wire_max_inflight": 1, "wire_backoff_max_s": 0.2})
    try:
        x = np.ones((4,), np.float32)
        results, errors = [], []
        gate = threading.Barrier(6)

        def worker():
            c = io.InferenceClient(srv.endpoint, timeout=10.0, retries=32)
            try:
                gate.wait()
                results.append(c.infer("slow", x)[0])
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")
            finally:
                c.close()

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        check("overload/all_recovered_after_shed",
              not errors and len(results) == 6, repr(errors[:2]))
        check("overload/shed_fired", monitor.get_stat("wire/shed") >= 1
              and monitor.get_stat("wire/shed_server") >= 1)
        h = srv.health()
        check("overload/health_op", h["status"] == "ok"
              and h["inflight"] == 0 and h["max_inflight"] == 1, str(h))
    finally:
        set_flags({"wire_max_inflight": 0, "wire_backoff_max_s": 2.0})
    check("overload/drain_clean", srv.drain(5.0) is True)


def scenario_obs(tmp: str) -> None:
    import threading
    import time

    class _SlowPredictor:
        input_specs = output_specs = []

        def run(self, x):
            time.sleep(0.03)
            return np.asarray(x)

    srv = io.InferenceServer()
    srv.add_model("slow", _SlowPredictor())
    srv.start()
    set_flags({"trace": True, "wire_backoff_max_s": 0.2})
    monitor.reset_stats("wire/")
    trace.clear()
    try:
        x = np.ones((4,), np.float32)
        client = io.InferenceClient(srv.endpoint, timeout=10.0, retries=32)

        # 1. retries under fault injection leave wire/retry_wait spans
        with fault.inject_faults({"wire.send": (1.0, 2)}, seed=7):
            client.infer("slow", x)

        # 2. an admission-cap burst leaves wire/shed_wait spans
        set_flags({"wire_max_inflight": 1})
        gate = threading.Barrier(3)
        errors = []

        def worker():
            c = io.InferenceClient(srv.endpoint, timeout=10.0, retries=32)
            try:
                gate.wait()
                c.infer("slow", x)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")
            finally:
                c.close()

        threads = [threading.Thread(target=worker) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        set_flags({"wire_max_inflight": 0})

        spans = trace.get_spans()
        names = [s["name"] for s in spans]
        check("obs/burst_recovered", not errors, repr(errors[:2]))
        check("obs/retry_spans_recorded",
              names.count("wire/retry_wait") >= 2, str(names))
        check("obs/shed_spans_recorded", "wire/shed_wait" in names,
              str(names))
        clients = [s for s in spans if s["name"] == "wire/serving.infer"]
        servers = [s for s in spans
                   if s["name"] == "wire/InferenceServer.infer"]
        joined = {s["trace_id"] for s in clients} & {
            s["trace_id"] for s in servers}
        check("obs/cross_wire_trace_joined", len(joined) >= 1,
              f"{len(clients)} client / {len(servers)} server spans")
        check("obs/predict_spans_nested",
              any(s["name"] == "serving/predict" for s in spans))

        out = os.path.join(tmp, "chaos_trace.json")
        trace.export_chrome(out)
        with open(out) as f:
            doc = json.load(f)
        check("obs/chrome_export_parses",
              len(doc["traceEvents"]) >= len(spans))
        prom = monitor.export_prometheus("wire/")
        check("obs/prometheus_quantiles",
              'quantile="0.99"' in prom and "wire_op_latency_s" in prom)
        client.stop_server()
        client.close()
    finally:
        set_flags({"trace": False, "wire_max_inflight": 0,
                   "wire_backoff_max_s": 2.0})
        srv.stop()


def scenario_serving_routed(tmp: str) -> None:
    """Replica kill under routed + dynamically-batched load: all
    idempotent requests complete via failover, membership converges."""
    import threading
    import time

    from paddle_tpu.serving import RoutedClient

    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = os.path.join(tmp, "dyn_mlp")
    io.save_inference_model(path, net, [np.zeros((2, 4), np.float32)],
                            dynamic_batch=True)
    servers = [io.InferenceServer({"m": path}).start() for _ in range(3)]
    monitor.reset_stats("serving/")
    set_flags({"serving_batch_max": 8, "serving_batch_timeout_s": 0.002})
    rc = RoutedClient([s.endpoint for s in servers],
                      probe_interval_s=0.25, timeout=10.0)
    results: dict = {}
    errors: list = []
    try:
        # stop() spends ~0.5s shutting the accept loop down before it
        # severs live conns — keep traffic flowing well past the sever
        stop_at = time.perf_counter() + 1.8
        killer = threading.Timer(0.1, servers[1].stop)
        killer.start()
        gate = threading.Barrier(6)

        def worker(i):
            try:
                gate.wait()
                j = 0
                while time.perf_counter() < stop_at:
                    x = np.full((1, 4), float(i * 1000 + j), np.float32)
                    results[(i, j)] = (float(x[0, 0]), rc.infer("m", x)[0])
                    j += 1
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        killer.join()
        ref = io.Predictor(path)
        bad = sum(
            not np.allclose(
                y, np.asarray(ref.run(np.full((1, 4), v, np.float32))),
                rtol=1e-5, atol=1e-6)
            for v, y in results.values())
        check("routed/zero_lost_requests",
              not errors and len(results) > 10 and bad == 0,
              f"errors={errors[:2]} n={len(results)} bad={bad}")
        check("routed/failover_fired",
              monitor.get_stat("serving/router/failovers") >= 1)
        check("routed/batching_coalesced",
              0 < monitor.get_stat("serving/batches")
              < monitor.get_stat("serving/batched_requests"),
              str(monitor.export_stats("serving/")))
        # membership convergence (probe- or traffic-driven)
        deadline = time.time() + 5.0
        members = rc.members()
        while time.time() < deadline:
            members = rc.members()
            health = {m["endpoint"]: m["healthy"] for m in members}
            if (not health[servers[1].endpoint]
                    and health[servers[0].endpoint]
                    and health[servers[2].endpoint]):
                break
            time.sleep(0.05)
        health = {m["endpoint"]: m["healthy"] for m in members}
        check("routed/membership_converged",
              not health[servers[1].endpoint]
              and health[servers[0].endpoint]
              and health[servers[2].endpoint], str(members))
    finally:
        set_flags({"serving_batch_max": 0,
                   "serving_batch_timeout_s": 0.005})
        rc.close()
        for s in servers:
            s.stop()


def scenario_gen_engine(tmp: str) -> None:
    """Client killed mid-stream under the continuous-batching engine:
    its slot is TTL-reclaimed, surviving streams are byte-identical to
    solo generate(), and a new generation lands in the freed slot."""
    import threading
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import GenerationEngine

    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    monitor.reset_stats("gen/")
    # pace the loop so "mid-stream" is a real window, and shorten the
    # poll TTL so the dropped client's slot reclaims within the check
    engine = GenerationEngine(model, slots=3, max_len=32, queue_max=4,
                              ttl_s=0.6, step_wait_s=0.02)
    srv = io.InferenceServer().start()
    srv.add_generator("llm", engine)
    rs = np.random.RandomState(3)
    prompts = rs.randint(0, 96, (3, 6)).astype(np.int32)
    refs = np.asarray(generate(model, jnp.asarray(prompts), 12))[:, 6:]
    survivors: dict = {}
    errors: list = []
    try:
        victim = io.InferenceClient(srv.endpoint)
        vic_id = victim.generate_start("llm", prompts[0], 12)
        victim.generate_poll("llm", vic_id, wait_s=0.1)

        def worker(i):
            try:
                c = io.InferenceClient(srv.endpoint)
                survivors[i] = list(c.generate("llm", prompts[i], 12))
                c.close()
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in (1, 2)]
        for t in threads:
            t.start()
        # kill the victim's connection mid-stream: no cancel, no close
        # handshake — only the poll TTL can reclaim its slot
        victim.close()
        for t in threads:
            t.join(timeout=30)
        check("gen/survivors_byte_identical",
              not errors and len(survivors) == 2
              and all(np.array_equal(np.asarray(survivors[i], np.int32),
                                     refs[i]) for i in (1, 2)),
              f"errors={errors[:2]}")

        deadline = time.time() + 5.0
        st = engine.stats()
        while time.time() < deadline:
            st = engine.stats()
            if st["active"] == 0 and st["generations"] == 0:
                break
            time.sleep(0.05)
        check("gen/victim_slot_reclaimed",
              st["active"] == 0 and st["generations"] == 0
              and monitor.get_stat("gen/evictions") >= 1, str(st))

        # freed capacity admits new work; counters stay consistent
        c = io.InferenceClient(srv.endpoint)
        toks = list(c.generate("llm", prompts[0], 12))
        check("gen/readmit_after_reclaim",
              np.array_equal(np.asarray(toks, np.int32), refs[0]))
        h = c.health()
        c.close()
        emitted = sum(len(v) for v in survivors.values()) + len(toks)
        check("gen/counters_consistent",
              monitor.get_stat("gen/tokens") >= emitted
              and h["generators"]["llm"]["active"] == 0,
              f"tokens={monitor.get_stat('gen/tokens')} "
              f"emitted>={emitted} health={h.get('generators')}")
    finally:
        srv.stop()     # closes the engine too


def scenario_gen_paged(tmp: str) -> None:
    """Client killed mid-CHUNKED-PREFILL under the paged engine: the
    poll TTL reaps it before its prefill completes, all its reserved
    pages return to the pool, survivors are byte-identical to solo
    generate(), and a shared-prefix readmit reuses the cached pages."""
    import threading
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import GenerationEngine

    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    monitor.reset_stats("gen/")
    # 4-token pages + 1-token chunks + a paced loop: the victim's
    # 56-token prompt spans dozens of loop iterations, so a 0.45s TTL
    # fires while it is demonstrably mid-prefill
    engine = GenerationEngine(model, slots=3, max_len=64, queue_max=8,
                              ttl_s=0.45, step_wait_s=0.02, paged=True,
                              page_tokens=4, prefill_chunk=1,
                              prefix_cache=True)
    srv = io.InferenceServer().start()
    srv.add_generator("pllm", engine)
    total = engine.stats()["pages"]
    rs = np.random.RandomState(5)
    # warm the prefill-chunk + decode compiles so the TTL races real
    # scheduling, not XLA compilation, then drain the prefix cache
    wid = engine.start(rs.randint(0, 96, (5,)).astype(np.int32), 2)
    n = 0
    while True:
        doc = engine.poll(wid, start=n, wait_s=1.0)
        n += len(doc["tokens"])
        if doc["done"]:
            break
    engine.clear_prefix_cache()
    shared_prefix = rs.randint(0, 96, (9,)).astype(np.int32)
    tails = rs.randint(0, 96, (2, 3)).astype(np.int32)
    prompts = [np.concatenate([shared_prefix, t]) for t in tails]
    refs = [np.asarray(generate(model, p[None], 20))[0, len(p):]
            for p in prompts]
    victim_prompt = rs.randint(0, 96, (56,)).astype(np.int32)
    survivors: dict = {}
    errors: list = []
    try:
        # survivors first: their decode steps pace the loop
        def worker(i):
            try:
                c = io.InferenceClient(srv.endpoint)
                survivors[i] = list(c.generate("pllm", prompts[i], 20))
                c.close()
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in (0, 1)]
        for t in threads:
            t.start()
        victim = io.InferenceClient(srv.endpoint)
        vic_id = victim.generate_start("pllm", victim_prompt, 6)
        # drop the socket with no cancel: only the TTL can reap it
        victim.close()
        # watch the victim's chunked prefill advance until the reap
        # pops it from the engine; the last observation tells whether
        # the TTL really fired mid-prefill
        deadline = time.time() + 10.0
        last_pos, completed_prefill = 0, False
        while time.time() < deadline:
            with engine._cond:
                g = engine._gens.get(vic_id)
                if g is None:
                    break                    # reaped (and purged)
                if g.slot is not None and not g.prefilling:
                    completed_prefill = True
                    break                    # outlived the TTL: invalid
                last_pos = max(last_pos, g.prefill_pos)
            time.sleep(0.01)
        check("gen_paged/reaped_mid_prefill",
              not completed_prefill and g is None
              and 0 < last_pos < victim_prompt.size,
              f"last_pos={last_pos} completed={completed_prefill}")
        for t in threads:
            t.join(timeout=30)
        check("gen_paged/survivors_byte_identical",
              not errors and len(survivors) == 2
              and all(np.array_equal(np.asarray(survivors[i], np.int32),
                                     refs[i]) for i in (0, 1)),
              f"errors={errors[:2]}")
        check("gen_paged/eviction_counted",
              monitor.get_stat("gen/evictions") >= 1)

        # shared-prefix readmit into the reclaimed pages: prompts share
        # a 9-token prefix -> 2 cached 4-token pages
        c = io.InferenceClient(srv.endpoint)
        toks = list(c.generate("pllm", prompts[0], 20))
        c.close()
        check("gen_paged/readmit_after_reclaim",
              np.array_equal(np.asarray(toks, np.int32), refs[0]))
        check("gen_paged/prefix_shared",
              monitor.get_stat("gen/prefix_hits") >= 1
              and monitor.get_stat("gen/prefix_tokens_saved") >= 8,
              str(monitor.export_stats("gen/")))

        # no leaks: once the prefix cache drains, the pool is FULL
        deadline = time.time() + 5.0
        st = engine.stats()
        while time.time() < deadline:
            engine.clear_prefix_cache()
            st = engine.stats()
            if st["pages_free"] == total and st["active"] == 0:
                break
            time.sleep(0.05)
        check("gen_paged/pool_returns_to_full",
              st["pages_free"] == total and st["active"] == 0
              and st["prefix_entries"] == 0, f"{st} total={total}")
    finally:
        srv.stop()     # closes the engine too


def scenario_control_plane(tmp: str) -> None:
    """(a) SIGKILL a subprocess replica right after a controller
    scale-up, under routed traffic: zero lost requests, reconcile
    replaces it. (b) Sticky-drain a scale-down victim with a LIVE
    pinned generation: byte-identical stream, zero GenerationFailed,
    clean (unforced) drain."""
    import threading
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import (
        InProcSpawner, ServingController, SubprocessSpawner,
    )

    # -- (a) replica killed mid-scale-up (subprocess, real SIGKILL) -----
    paddle_tpu.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    path = os.path.join(tmp, "ctl_mlp")
    io.save_inference_model(path, net, [np.zeros((2, 4), np.float32)],
                            dynamic_batch=True)
    ref = io.Predictor(path)
    monitor.reset_stats("control/")
    spawner = SubprocessSpawner({"m": path})
    ctl = ServingController(spawner, interval_s=0, min_replicas=1,
                            max_replicas=3, breach_ticks=1,
                            cooldown_s=0.0)
    results: dict = {}
    errors: list = []
    try:
        ctl.start()
        stop_at = time.perf_counter() + 3.0

        def worker(i):
            try:
                j = 0
                while time.perf_counter() < stop_at:
                    x = np.full((1, 4), float(i * 1000 + j), np.float32)
                    results[(i, j)] = (float(x[0, 0]),
                                       ctl.router.infer("m", x)[0])
                    j += 1
                    time.sleep(0.005)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        before = set(ctl.router.endpoints())
        ctl.scale_to(2, reason="chaos scale-up")
        joined = next(iter(set(ctl.router.endpoints()) - before))
        spawner.kill(joined)              # SIGKILL the fresh replica
        time.sleep(0.3)
        ctl.tick()                        # reconcile: replace the corpse
        for t in threads:
            t.join(timeout=60)
        bad = sum(
            not np.allclose(
                y, np.asarray(ref.run(np.full((1, 4), v, np.float32))),
                rtol=1e-5, atol=1e-6)
            for v, y in results.values())
        check("control/zero_lost_through_kill",
              not errors and len(results) > 20 and bad == 0,
              f"errors={errors[:2]} n={len(results)} bad={bad}")
        eps = ctl.router.endpoints()
        check("control/dead_replica_replaced",
              len(eps) == 2 and joined not in eps, str(eps))
        acts = [d["action"] for d in ctl.decisions()]
        check("control/replace_decision_logged",
              "replace" in acts and "scale_up" in acts, str(acts))
    finally:
        ctl.close()

    # -- (b) sticky-drain scale-down with a live pinned generation ------
    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    def factory():
        srv = io.InferenceServer().start()
        srv.add_generator("llm", model, slots=2, max_len=32,
                          step_wait_s=0.02)
        return srv

    inproc = InProcSpawner(factory)
    ctl2 = ServingController(inproc, interval_s=0, min_replicas=1,
                             max_replicas=2, drain_s=20.0)
    try:
        ctl2.start()
        ctl2.scale_to(2, reason="chaos setup")
        rs = np.random.RandomState(9)
        prompt = rs.randint(0, 96, (5,)).astype(np.int32)
        refs = np.asarray(generate(model, prompt[None], 14))[0, 5:]
        sess = ctl2.router.session("chaos-pinned")
        it = sess.generate("llm", prompt, 14, poll_wait_s=0.05)
        toks = [next(it)]
        victim = sess.endpoint
        drained: dict = {}

        def drain():
            drained["d"] = ctl2.scale_down(victim=victim,
                                           reason="chaos drain")

        t = threading.Thread(target=drain)
        t.start()
        stream_err = None
        try:
            toks += list(it)              # rides through the drain
        except Exception as e:
            stream_err = f"{type(e).__name__}: {e}"
        t.join(timeout=60)
        d = drained.get("d")
        check("control/sticky_stream_byte_identical",
              stream_err is None
              and np.array_equal(np.asarray(toks, np.int32), refs),
              f"err={stream_err} toks={len(toks)}")
        check("control/drain_clean_and_victim_stopped",
              d is not None and d.action == "scale_down" and d.clean
              and victim not in ctl2.router.endpoints()
              and victim not in inproc.servers
              and monitor.get_stat("control/drain_forced") == 0,
              f"decision={d.as_dict() if d else None}")
        # the survivor still serves; fleet is one replica
        toks2 = list(ctl2.router.session("after-drain").generate(
            "llm", prompt, 14, poll_wait_s=0.05))
        check("control/survivor_serves_after_drain",
              len(ctl2.router.endpoints()) == 1
              and np.array_equal(np.asarray(toks2, np.int32), refs))
    finally:
        ctl2.close()


def scenario_control_ha(tmp: str) -> None:
    """The active controller of an HA pair dies silently (SIGKILL
    emulated in-process: it never ticks, renews, or closes again) with
    a live token stream on a subprocess replica, an unfinished
    journaled drain, and a spawn intent that never reported an
    endpoint. Asserts: standby holds while the lease is live; takeover
    within one TTL at term+1; journal replay reconstructs the EXACT
    managed set; live orphans adopted (zero double-spawns, the stream
    byte-identical to solo ``generate()`` across the takeover); the
    lost spawn surfaced; the drain resumed clean; the zombie's queued
    scale-up fenced at the actuator as a typed decision."""
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import ServingController, SubprocessSpawner

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)          # == every replica's weights
    monitor.reset_stats("control/")
    ha_root = os.path.join(tmp, "ha_root")
    ttl = 1.0
    # the rider stream deliberately goes unpolled across the whole
    # takeover (standby wait + adoption + a fresh subprocess spawn);
    # keep the replicas' poll TTL above that so "client paused" is not
    # mistaken for "client gone"
    os.environ["FLAGS_gen_poll_ttl_s"] = "300"
    gen_args = ("--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
                "--gen-max-len", "32", "--gen-step-wait-s", "0.05")

    def _ctl(holder):
        return ServingController(
            SubprocessSpawner(extra_args=gen_args), interval_s=0,
            min_replicas=2, max_replicas=4, drain_s=20.0,
            ha_lease_dir=ha_root, ha_lease_ttl_s=ttl, ha_holder=holder)

    c1, c2 = _ctl("primary"), _ctl("standby")
    try:
        c1.start()
        c1.tick()                          # claims term 1, bootstraps 2
        live = set(c1.router.endpoints())
        check("control/ha_leader_bootstrapped",
              c1.lease.leading and c1.lease.term == 1 and len(live) == 2,
              f"term={c1.lease.term} eps={sorted(live)}")

        rs = np.random.RandomState(61)
        prompt = rs.randint(0, 96, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 14))[0, 5:]
        sess = c1.router.session("ha-rider")
        it = sess.generate("llm", prompt, 14, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it), next(it)]        # the stream is live
        victim = next(ep for ep in live if ep != sess.endpoint)

        c1.tick()                          # one last renewal, then the
        #                                    leader dies silently. Its
        # final journaled acts: a drain begun but not finished, and a
        # spawn intent whose endpoint no one will ever learn
        c1._journal_rec("drain_begin", ep=victim)
        c1._journal_rec("spawn_intent")

        c2.start()
        d = c2.tick()
        check("control/ha_standby_holds_while_leader_live",
              d.action == "hold" and "standby" in d.reason
              and not c2.router.endpoints(), d.reason)

        time.sleep(ttl + 0.2)              # one TTL of silence
        t0 = time.monotonic()
        c2.tick()                          # claim + replay + adopt
        took = time.monotonic() - t0
        adopted = {x["endpoint"] for x in c2.decisions()
                   if x["action"] == "adopt"}
        check("control/ha_takeover_replays_exact_managed_set",
              c2.lease.leading and c2.lease.term == 2
              and adopted == live, f"term={c2.lease.term} "
              f"adopted={sorted(adopted)} expected={sorted(live)} "
              f"takeover_s={took:.2f}")
        # zero double-spawns: every live orphan was ADOPTED, never
        # respawned — the only process c2 started is the post-drain
        # bootstrap replacement, a fresh endpoint outside the old fleet
        check("control/ha_zero_double_spawns",
              set(c2._spawner.inner.procs).isdisjoint(live)
              and len(c2._spawner.inner.procs) == 1
              and sess.endpoint in c2._spawner.inner.adopted_pids
              and monitor.get_stat("control/ha_adopted") == 2,
              f"procs={list(c2._spawner.inner.procs)} "
              f"adopted={list(c2._spawner.inner.adopted_pids)}")
        acts = [x["action"] for x in c2.decisions()]
        check("control/ha_drain_resumed_clean",
              "drain_resume" in acts
              and any(x["action"] == "scale_down" and x.get("clean")
                      and x["endpoint"] == victim
                      for x in c2.decisions())
              and victim not in c2.router.endpoints()
              and monitor.get_stat("control/drain_forced") == 0,
              str(acts))
        check("control/ha_lost_spawn_surfaced",
              monitor.get_stat("control/ha_lost_spawns") == 1,
              str(monitor.get_stat("control/ha_lost_spawns")))

        err = None
        try:
            toks += list(it)               # rides through the takeover
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("control/ha_stream_byte_identical_across_takeover",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref),
              f"err={err} toks={len(toks)}")

        # the zombie: next tick deposes it; its queued scale-up is
        # fenced at the actuator — typed decision, never executed
        d = c1.tick()
        n_before = len(c1._spawner.inner.procs)
        f = c1._scale_up("zombie queued scale-up", {})
        check("control/ha_zombie_deposed_and_fenced",
              d.action == "deposed" and f.action == "fenced"
              and len(c1._spawner.inner.procs) == n_before
              and c1.decisions()[-1]["action"] == "fenced",
              f"tick={d.action} scale_up={f.action}")

        # durable truth: a fresh replay names exactly the live fleet
        from paddle_tpu.serving import FleetJournal
        st = FleetJournal(ha_root, compact_records=0).replay()
        check("control/ha_journal_names_live_fleet",
              set(st.managed) == set(c2.router.endpoints())
              and st.draining is None, str(st.as_dict()))
    finally:
        os.environ.pop("FLAGS_gen_poll_ttl_s", None)
        c1.close(stop_replicas=False)      # the corpse: fleet is c2's
        c2.close()
        for sp in (c1._spawner.inner, c2._spawner.inner):
            for ep in list(sp.procs):
                sp.kill(ep)


def scenario_gen_resilience(tmp: str) -> None:
    """(a) SIGKILL the subprocess replica holding a live greedy stream:
    with a resume budget the routed stream completes byte-identical on
    the survivor — zero GenerationFailed, zero leaked pages. (b) A
    poison request that traps an engine is quarantined typed; the
    second replica never crashes."""
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import (
        GenerationEngine, RequestQuarantined, RoutedClient,
        SubprocessSpawner,
    )

    # local reference weights: same seed + config as the --gen replicas
    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    # -- (a) SIGKILL under a live stream; resume on the survivor --------
    monitor.reset_stats("serving/router/")
    spawner = SubprocessSpawner(extra_args=(
        "--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
        "--gen-max-len", "32", "--gen-step-wait-s", "0.05",
        "--gen-paged", "--gen-page-tokens", "8"))
    eps = [spawner.spawn() for _ in range(2)]
    router = RoutedClient(eps, probe_interval_s=0)
    try:
        rs = np.random.RandomState(51)
        prompt = rs.randint(0, 96, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 12))[0, 5:]
        sess = router.session("kill-victim")
        it = sess.generate("llm", prompt, 12, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it), next(it)]          # the stream is live
        victim = sess.endpoint
        rider = router.session("rider")      # concurrent routed load
        it2 = rider.generate("llm", prompt, 12, poll_wait_s=0.05,
                             resume_budget=2)
        toks2 = [next(it2)]
        spawner.kill(victim)                 # real SIGKILL, no goodbye
        err = None
        try:
            toks += list(it)                 # resumes on the survivor
            toks2 += list(it2)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("genres/stream_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref)
              and np.array_equal(np.asarray(toks2, np.int32), ref),
              f"err={err} toks={len(toks)}/{len(toks2)}")
        check("genres/resume_counted_no_failure_surfaced",
              err is None
              and monitor.get_stat("serving/router/stream_resumes") >= 1
              and monitor.get_stat("serving/router/resume_exhausted")
              == 0,
              str(monitor.export_stats("serving/router/")))
        survivor = next(ep for ep in eps if ep != victim)
        g = {}
        with io.InferenceClient(survivor, timeout=5.0) as c:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                g = c.health()["generators"]["llm"]
                if (g.get("active") == 0 and g.get("pages_free", 0)
                        + g.get("prefix_entries", 0) == g.get("pages")):
                    break
                time.sleep(0.1)
        check("genres/zero_leaked_pages_on_survivor",
              g.get("pages_free", -1) + g.get("prefix_entries", 0)
              == g.get("pages"), str(g))
    finally:
        router.close()
        for ep in list(spawner.procs):
            spawner.kill(ep)

    # -- (b) quarantined poison never crashes a second replica ----------
    servers, engines = [], []
    for _ in range(2):
        eng = GenerationEngine(model, slots=1, max_len=32, rebuilds=4,
                               quarantine_after=1)
        srv = io.InferenceServer().start()
        srv.add_generator("llm", eng)
        servers.append(srv)
        engines.append(eng)
    router2 = RoutedClient([s.endpoint for s in servers],
                           probe_interval_s=0)
    try:
        rs = np.random.RandomState(52)
        poison = rs.randint(0, 96, (4,)).astype(np.int32)
        clean = rs.randint(0, 96, (4,)).astype(np.int32)
        qerr, other = None, None
        with fault.inject_faults({"engine.prefill": (1.0, 1)}):
            try:
                list(router2.session("poison").generate(
                    "llm", poison, 4, poll_wait_s=0.05, resume_budget=3))
            except RequestQuarantined as e:
                qerr = e
            except Exception as e:
                other = f"{type(e).__name__}: {e}"
        check("genres/quarantine_typed_giveup",
              qerr is not None and other is None,
              f"quarantined={qerr} other={other}")
        check("genres/second_replica_never_crashed",
              sum(e.stats()["rebuilds"] for e in engines) == 1
              and all(e.stats()["broken"] is None for e in engines),
              str([e.stats() for e in engines]))
        ref = np.asarray(generate(model, clean[None], 3))[0, 4:]
        toks = list(router2.generate("llm", clean, 3))
        check("genres/fleet_serves_after_quarantine",
              np.array_equal(np.asarray(toks, np.int32), ref),
              str(toks))
    finally:
        router2.close()
        for s in servers:
            s.stop()


def scenario_gen_spec(tmp: str) -> None:
    """SIGKILL a subprocess replica mid-stream while the stream is
    SPECULATING (paged engine, n-gram drafter): the routed resume
    replays the delivered prefix on the survivor — itself speculating —
    byte-identical, with ``stream_resumes>=1`` and zero leaked pages.
    Speculative rollback state is per-slot device state the resume
    never sees: the wire contract (delivered tokens + rng_skip) is
    unchanged, which is exactly what this scenario pins down."""
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    monitor.reset_stats("serving/router/")
    spawner = SubprocessSpawner(extra_args=(
        "--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
        "--gen-max-len", "32", "--gen-step-wait-s", "0.05",
        "--gen-paged", "--gen-page-tokens", "8",
        "--gen-spec-k", "4", "--gen-spec-mode", "ngram"))
    eps = [spawner.spawn() for _ in range(2)]
    router = RoutedClient(eps, probe_interval_s=0)
    try:
        # templated prompt: gives the n-gram drafter something to match
        # so the killed stream is genuinely speculating
        prompt = np.asarray([3, 9, 3, 9, 3], np.int32)
        ref = np.asarray(generate(model, prompt[None], 12))[0, 5:]
        sess = router.session("spec-victim")
        it = sess.generate("llm", prompt, 12, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it), next(it)]          # live speculating stream
        victim = sess.endpoint
        spawner.kill(victim)                 # real SIGKILL, no goodbye
        err = None
        try:
            toks += list(it)                 # resumes on the survivor
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("genspec/stream_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref),
              f"err={err} toks={toks} ref={ref.tolist()}")
        check("genspec/resume_counted",
              monitor.get_stat("serving/router/stream_resumes") >= 1,
              str(monitor.export_stats("serving/router/")))
        survivor = next(ep for ep in eps if ep != victim)
        g = {}
        with io.InferenceClient(survivor, timeout=5.0) as c:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                g = c.health()["generators"]["llm"]
                if (g.get("active") == 0 and g.get("pages_free", 0)
                        + g.get("prefix_entries", 0) == g.get("pages")):
                    break
                time.sleep(0.1)
        check("genspec/zero_leaked_pages_on_survivor",
              g.get("pages_free", -1) + g.get("prefix_entries", 0)
              == g.get("pages"), str(g))
        check("genspec/acceptance_stats_in_health",
              g.get("spec", {}).get("k") == 4
              and "accept_rate" in g.get("spec", {})
              and "tokens_per_step" in g, str(g))
    finally:
        router.close()
        for ep in list(spawner.procs):
            spawner.kill(ep)


def scenario_gen_sharded(tmp: str) -> None:
    """SIGKILL the tp=2 MESH-SHARDED subprocess replica holding a live
    stream under routed load: the stream resumes byte-identical on an
    UNSHARDED survivor. Cross-layout failover is the tentpole contract
    — the wire carries tokens + RNG position, never device layout, and
    sharded decode is bit-exact with unsharded decode — so a router may
    mix tp degrees freely in one fleet. The sharded replica's health
    (scraped before the kill) must ship the ``device`` block: mesh
    {'tp': 2}, 2 devices, per-device KV bytes exactly half the
    unsharded survivor's pool."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    monitor.reset_stats("serving/router/")
    base = ("--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
            "--gen-max-len", "32", "--gen-step-wait-s", "0.05")
    # one spawner per layout (replica_main forces the virtual host
    # device count itself when --mesh-tp > 0; startup pays the larger
    # 8-device backend init, hence the longer timeout)
    sharded = SubprocessSpawner(extra_args=base + ("--mesh-tp", "2"),
                                startup_timeout_s=120.0)
    plain = SubprocessSpawner(extra_args=base)
    ep_tp = sharded.spawn()
    ep_plain = plain.spawn()
    router = RoutedClient([ep_tp, ep_plain], probe_interval_s=0)
    try:
        devs = {}
        for ep in (ep_tp, ep_plain):
            with io.InferenceClient(ep, timeout=10.0) as c:
                devs[ep] = c.health()["generators"]["llm"]["device"]
        check("gensharded/device_block_topology",
              devs[ep_tp].get("mesh") == {"tp": 2}
              and devs[ep_tp].get("devices") == 2
              and devs[ep_plain].get("mesh") is None
              and devs[ep_plain].get("devices") == 1, str(devs))
        check("gensharded/per_device_kv_half_of_pool",
              devs[ep_tp]["kv_bytes"] == devs[ep_plain]["kv_bytes"]
              and devs[ep_tp]["kv_bytes_per_device"] * 2
              == devs[ep_plain]["kv_bytes"], str(devs))

        rs = np.random.RandomState(53)
        prompt = rs.randint(0, 96, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 12))[0, 5:]
        # pin the victim stream to the SHARDED replica deterministically
        # (cordon beats least-inflight tie-breaking races), then restore
        # the unsharded survivor to membership before the kill
        router.cordon(ep_plain)
        sess = router.session("kill-sharded")
        it = sess.generate("llm", prompt, 12, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it), next(it)]          # stream live on the mesh
        router.uncordon(ep_plain)
        check("gensharded/victim_is_sharded", sess.endpoint == ep_tp,
              f"pinned={sess.endpoint}")
        rider = router.session("rider")      # concurrent routed load
        it2 = rider.generate("llm", prompt, 12, poll_wait_s=0.05,
                             resume_budget=2)
        toks2 = [next(it2)]
        sharded.kill(ep_tp)                  # real SIGKILL, no goodbye
        err = None
        try:
            toks += list(it)                 # resumes on the unsharded
            toks2 += list(it2)               # survivor, byte-identical
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("gensharded/cross_layout_resume_byte_identical",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref)
              and np.array_equal(np.asarray(toks2, np.int32), ref),
              f"err={err} toks={toks} ref={ref.tolist()}")
        check("gensharded/resume_counted_no_failure_surfaced",
              err is None
              and monitor.get_stat("serving/router/stream_resumes") >= 1
              and monitor.get_stat("serving/router/resume_exhausted")
              == 0,
              str(monitor.export_stats("serving/router/")))
    finally:
        router.close()
        for sp in (sharded, plain):
            for ep in list(sp.procs):
                sp.kill(ep)


def scenario_obs_fleet(tmp: str) -> None:
    """SIGKILL a subprocess replica holding a live TRACED stream: the
    victim's span buffer is scraped moments before the kill (a dead
    replica can't be scraped), the stream resumes on the survivor under
    the SAME stream trace id, and obs_dump merges the two scrapes —
    taken at different times — into one Chrome trace with >= 1
    cross-endpoint stream ending in the survivor's retire(complete).
    A MetricsHub fed from routed health keeps answering through the
    membership churn and prunes the dead replica."""
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner
    from paddle_tpu.serving.metrics import MetricsHub

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import obs_dump

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    saved = get_flags(["trace", "trace_buffer"])
    # the replicas are subprocesses: they read tracing from the env they
    # inherit, so export BEFORE spawning; the parent traces too (the
    # router's gen/stream_resume marker lives in this process)
    os.environ["FLAGS_trace"] = "1"
    os.environ["FLAGS_trace_buffer"] = "4096"
    set_flags({"trace_buffer": 4096, "trace": True})
    trace.clear()
    spawner = SubprocessSpawner(extra_args=(
        "--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
        "--gen-max-len", "32", "--gen-step-wait-s", "0.05"))
    eps = [spawner.spawn() for _ in range(2)]
    router = RoutedClient(eps, probe_interval_s=0)
    hub = MetricsHub(fast_ticks=2, slow_ticks=6)
    try:
        rs = np.random.RandomState(53)
        prompt = rs.randint(0, 96, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 12))[0, 5:]
        sess = router.session("traced-kill")
        it = sess.generate("llm", prompt, 12, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it), next(it)]          # the stream is live
        victim = sess.endpoint
        hub.ingest(router.health(stats_prefix="gen/", histograms=True))
        # scrape the victim WHILE IT LIVES: its half of the stream's
        # life has to come out of its buffer before the SIGKILL
        pre = obs_dump.scrape(victim, clear=False, stats_prefix=None,
                              timeout=5.0)
        spawner.kill(victim)                 # real SIGKILL, no goodbye
        err = None
        try:
            toks += list(it)                 # resumes on the survivor
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("obsfleet/stream_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref),
              f"err={err} toks={len(toks)}")
        survivor = next(ep for ep in eps if ep != victim)
        post = obs_dump.scrape(survivor, clear=False, stats_prefix=None,
                               timeout=5.0)
        # two scrapes, two moments in time, ONE stream trace
        doc = obs_dump.merge_chrome([pre, post])
        parsed = json.loads(json.dumps(doc))
        check("obsfleet/merged_chrome_trace_parses",
              len(parsed.get("traceEvents", [])) > 0,
              f"events={len(parsed.get('traceEvents', []))}")
        report = obs_dump.build_report([pre, post], doc=doc)
        crossed = report["cross_endpoint_streams"]
        check("obsfleet/failover_stream_is_one_cross_replica_trace",
              report["cross_endpoint_stream_ids"] >= 1
              and any(d["retired"] == "complete"
                      and len(d["endpoints"]) == 2
                      and "gen/admitted" in d["names"]
                      for d in crossed.values()),
              json.dumps(crossed))
        check("obsfleet/resume_marker_traced_in_router",
              any(sp["name"] == "gen/stream_resume"
                  for sp in trace.get_spans()), "")
        # the hub keeps answering through the churn: the dead replica's
        # doc goes unreachable, the survivor's deltas keep flowing, and
        # a full slow window later the victim is pruned
        hub.ingest(router.health(stats_prefix="gen/", histograms=True))
        toks2 = list(router.generate("llm", prompt, 12,
                                     poll_wait_s=0.05))
        check("obsfleet/survivor_still_serves",
              np.array_equal(np.asarray(toks2, np.int32), ref),
              f"toks={len(toks2)}")
        # six more ticks: the victim (last seen tick 1) falls a full
        # slow window behind and is pruned at tick 8, while the
        # survivor's post-kill traffic delta (tick 3) is still inside
        # the slow window — churn must not blind the windowed series
        for _ in range(6):
            hub.ingest(router.health(stats_prefix="gen/",
                                     histograms=True))
        win = hub.window_histogram("gen/ttft_s", 6)
        burn = hub.burn_rates("gen/ttft_s", 0.5, budget=0.1)
        check("obsfleet/hub_series_survive_membership_churn",
              hub.endpoints() == [survivor]
              and win is not None and win["count"] >= 1
              and all(b >= 0.0 for b in burn),
              f"eps={hub.endpoints()} win={win and win['count']} "
              f"burn={burn}")
    finally:
        router.close()
        for ep in list(spawner.procs):
            spawner.kill(ep)
        del os.environ["FLAGS_trace"]
        del os.environ["FLAGS_trace_buffer"]
        set_flags(saved)
        trace.clear()


def scenario_ledger(tmp: str) -> None:
    """SIGKILL a replica holding a live TENANTED stream with the request
    ledger on: the stream resumes byte-identically on the survivor, and
    the survivor's ledger_dump shows a finalized record that (a) carries
    the resume sub-phase (this generation was a failover replay), (b)
    still belongs to the original tenant — the router re-sends the
    tenant header on every resume attempt, so attribution survives the
    kill — and (c) obeys the partition invariant: the phase seconds sum
    to the record's end-to-end latency exactly. The survivor's goodput
    taxonomy must likewise account 100% of its loop wall clock."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    saved = get_flags(["gen_ledger"])
    # subprocess replicas read the flag from the env they inherit, so
    # export BEFORE spawning; the parent flips it too for symmetry
    os.environ["FLAGS_gen_ledger"] = "1"
    set_flags({"gen_ledger": True})
    spawner = SubprocessSpawner(extra_args=(
        "--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
        "--gen-max-len", "32", "--gen-step-wait-s", "0.05"))
    eps = [spawner.spawn() for _ in range(2)]
    router = RoutedClient(eps, probe_interval_s=0)
    try:
        rs = np.random.RandomState(59)
        prompt = rs.randint(0, 96, (5,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 12))[0, 5:]
        sess = router.session("ledger-kill")
        it = sess.generate("llm", prompt, 12, poll_wait_s=0.05,
                           resume_budget=2, tenant="acme")
        toks = [next(it), next(it)]          # the stream is live
        victim = sess.endpoint
        spawner.kill(victim)                 # real SIGKILL, no goodbye
        err = None
        try:
            toks += list(it)                 # resumes on the survivor
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("ledger/stream_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref),
              f"err={err} toks={len(toks)}")
        survivor = next(ep for ep in eps if ep != victim)
        with io.InferenceClient(survivor, timeout=5.0) as cl:
            dump = cl.ledger_dump()
        eng = (dump.get("generators") or {}).get("llm") or {}
        recs = eng.get("records") or []
        resumed = [r for r in recs if r.get("resume")]
        check("ledger/survivor_finalized_resume_record",
              any(r["outcome"] == "complete"
                  and r["resume"].get("rng_skip", 0) >= 1
                  for r in resumed),
              json.dumps(resumed))
        check("ledger/tenant_attribution_survives_failover",
              all(r.get("tenant") == "acme" for r in resumed)
              and resumed != []
              and eng.get("tenants", {}).get("acme", {})
              .get("tokens", 0) >= len(ref) - 2,
              json.dumps(eng.get("tenants")))
        # partition invariant on the wire: phases sum to e2e exactly
        # (clamped telescoping boundaries, not independent timers)
        check("ledger/phases_partition_e2e",
              recs != []
              and all(abs(sum(r["phases"].values()) - r["e2e_s"]) < 1e-6
                      for r in recs),
              json.dumps(recs[:1]))
        gp = eng.get("goodput") or {}
        fr = gp.get("fractions") or {}
        check("ledger/goodput_accounts_all_wall_clock",
              gp.get("total_s", 0.0) > 0.0
              and abs(sum(fr.values()) - 1.0) < 1e-6,
              json.dumps(gp))
    finally:
        router.close()
        for ep in list(spawner.procs):
            spawner.kill(ep)
        del os.environ["FLAGS_gen_ledger"]
        set_flags(saved)


def scenario_gen_sched(tmp: str) -> None:
    """SIGKILL a scheduler-on replica mid-preempted-stream pair: a
    1-slot replica is decoding a batch stream when an interactive
    arrival preempts it (the batch stream parks via the prompt-fold
    contract); the replica is then SIGKILLed with BOTH streams live.
    Both resume on the survivor byte-identical, the survivor leaks no
    pages, and no parked slot is stranded anywhere."""
    import time
    import zlib

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    monitor.reset_stats("serving/router/")
    spawner = SubprocessSpawner(extra_args=(
        "--gen", "llm", "--gen-seed", "7", "--gen-slots", "1",
        "--gen-max-len", "32", "--gen-step-wait-s", "0.05",
        "--gen-paged", "--gen-page-tokens", "8", "--gen-sched"))
    eps = [spawner.spawn() for _ in range(2)]
    router = RoutedClient(eps, probe_interval_s=0)
    try:
        victim = sorted(eps)[0]
        vidx = sorted(eps).index(victim)

        def _sid(prefix):
            # sticky pin is crc32(sid) % len(healthy) over the sorted
            # membership: mint a session id that pins to the victim so
            # the interactive arrival actually contends with the batch
            # stream for its single slot
            for i in range(64):
                sid = f"{prefix}{i}"
                if zlib.crc32(sid.encode()) % len(eps) == vidx:
                    return sid
            raise AssertionError("no session id pinned to victim")

        p_batch = np.arange(1, 9, dtype=np.int32)
        p_inter = np.arange(10, 14, dtype=np.int32)
        ref_b = np.asarray(generate(model, p_batch[None], 16))[0, 8:]
        ref_i = np.asarray(generate(model, p_inter[None], 10))[0, 4:]

        sess_b = router.session(_sid("bulk-"))
        it_b = sess_b.generate("llm", p_batch, 16, poll_wait_s=0.05,
                               resume_budget=2, tenant="bulk",
                               priority="batch")
        toks_b = [next(it_b), next(it_b)]       # decoding mid-stream
        sess_i = router.session(_sid("live-"))
        it_i = sess_i.generate("llm", p_inter, 10, poll_wait_s=0.05,
                               resume_budget=2, tenant="live",
                               priority="interactive")
        # an interactive token on a 1-slot replica means the batch
        # stream was parked first — read the scheduler's own counter
        toks_i = [next(it_i)]
        with io.InferenceClient(victim, timeout=5.0) as c:
            sched = c.health()["generators"]["llm"].get("sched") or {}
        check("gensched/preempted_before_kill",
              sched.get("preemptions", 0) >= 1
              and sched.get("admitted", {}).get("interactive", 0) >= 1,
              json.dumps(sched))

        spawner.kill(victim)          # SIGKILL: interactive mid-stream,
        err = None                    # batch parked on the dead replica
        try:
            toks_i += list(it_i)
            toks_b += list(it_b)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("gensched/preempted_interactive_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks_i, np.int32), ref_i),
              f"err={err} toks={len(toks_i)}")
        check("gensched/parked_batch_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks_b, np.int32), ref_b),
              f"err={err} toks={len(toks_b)}")
        check("gensched/resumes_counted",
              monitor.get_stat("serving/router/stream_resumes") >= 2
              and monitor.get_stat("serving/router/resume_exhausted")
              == 0,
              str(monitor.export_stats("serving/router/")))
        survivor = next(ep for ep in eps if ep != victim)
        g = {}
        with io.InferenceClient(survivor, timeout=5.0) as c:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                g = c.health()["generators"]["llm"]
                if (g.get("active") == 0 and g.get("queued") == 0
                        and g.get("pages_free", 0)
                        + g.get("prefix_entries", 0) == g.get("pages")):
                    break
                time.sleep(0.1)
        check("gensched/no_leaked_pages_no_stranded_slots_on_survivor",
              g.get("active") == 0 and g.get("queued") == 0
              and g.get("pages_free", -1) + g.get("prefix_entries", 0)
              == g.get("pages"), str(g))
    finally:
        router.close()
        for ep in list(spawner.procs):
            spawner.kill(ep)


def scenario_gen_disagg(tmp: str) -> None:
    """SIGKILL a decode-tier replica holding a live stream with the
    tiered KV store on (two ``--role decode --kv-store`` replicas, one
    shared spill root): the victim's prefill PUBLISHED the page-aligned
    prompt's pages, so the resumed stream on the other decode replica
    admits via KV FETCH — byte-identical completion with ZERO
    recomputed prefill tokens and zero leaked pages on the survivor."""
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    monitor.reset_stats("serving/router/")
    # the router's KV-locality placement reads both at construction;
    # the subprocess replicas get their store via CLI args instead
    saved = get_flags(["gen_kv_store", "gen_page_tokens"])
    set_flags({"gen_kv_store": True, "gen_page_tokens": 8})
    spill = os.path.join(tmp, "kv_spill")
    spawner = SubprocessSpawner(extra_args=(
        "--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
        "--gen-max-len", "32", "--gen-step-wait-s", "0.05",
        "--gen-paged", "--gen-page-tokens", "8",
        "--role", "decode", "--kv-store", "--kv-spill-dir", spill))
    eps = [spawner.spawn() for _ in range(2)]
    router = RoutedClient(eps, probe_interval_s=0)
    try:
        rs = np.random.RandomState(61)
        # PAGE-ALIGNED prompt (8 tokens @ page_tokens 8): the victim's
        # prefill publishes the WHOLE original prompt, so the resumed
        # admission covers it entirely from the store — recompute debt 0
        prompt = rs.randint(0, 96, (8,)).astype(np.int32)
        ref = np.asarray(generate(model, prompt[None], 12))[0, 8:]
        sess = router.session("disagg-victim")
        it = sess.generate("llm", prompt, 12, poll_wait_s=0.05,
                           resume_budget=2)
        toks = [next(it), next(it)]          # the stream is live
        victim = sess.endpoint
        spawner.kill(victim)                 # real SIGKILL, no goodbye
        err = None
        try:
            toks += list(it)                 # resumes via KV fetch
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("disagg/stream_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref),
              f"err={err} toks={len(toks)}")
        check("disagg/resume_counted_no_failure_surfaced",
              err is None
              and monitor.get_stat("serving/router/stream_resumes") >= 1
              and monitor.get_stat("serving/router/resume_exhausted")
              == 0,
              str(monitor.export_stats("serving/router/")))
        survivor = next(ep for ep in eps if ep != victim)
        g = {}
        with io.InferenceClient(survivor, timeout=5.0) as c:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                g = c.health()["generators"]["llm"]
                if (g.get("active") == 0 and g.get("pages_free", 0)
                        + g.get("prefix_entries", 0) == g.get("pages")):
                    break
                time.sleep(0.1)
        kv = g.get("kv") or {}
        check("disagg/failover_is_kv_fetch_zero_recompute",
              kv.get("role") == "decode"
              and kv.get("fetched_pages", 0) >= 1
              and kv.get("prefill_recomputed", -1) == 0,
              str(kv))
        check("disagg/zero_leaked_pages_on_survivor",
              g.get("pages_free", -1) + g.get("prefix_entries", 0)
              == g.get("pages"), str(g))
    finally:
        router.close()
        for ep in list(spawner.procs):
            spawner.kill(ep)
        set_flags(saved)


def scenario_gen_hotloop(tmp: str) -> None:
    """SIGKILL the subprocess replica running the overhauled decode hot
    loop (``--gen-async-depth 2 --gen-device-pt``) while it holds a
    live SAMPLED stream: the delivered prefix — which under lookahead
    lags device progress by up to ``depth`` steps — resumes on a plain
    SYNCHRONOUS survivor byte-identical to the uninterrupted solo
    stream, and the survivor drains back to a full page pool. The wire
    contract (delivered tokens + rng_skip) never sees dispatch depth or
    page-table residency, which is exactly what this scenario pins."""
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)

    monitor.reset_stats("serving/router/")
    base = ("--gen", "llm", "--gen-seed", "7", "--gen-slots", "2",
            "--gen-max-len", "32", "--gen-step-wait-s", "0.05",
            "--gen-paged", "--gen-page-tokens", "8")
    # victim runs the full hot-loop overhaul; survivor is the plain
    # synchronous loop — failover must cross the dispatch-mode boundary
    hot = SubprocessSpawner(extra_args=base + ("--gen-async-depth", "2",
                                               "--gen-device-pt"))
    plain = SubprocessSpawner(extra_args=base)
    ep_hot = hot.spawn()
    ep_plain = plain.spawn()
    router = RoutedClient([ep_hot, ep_plain], probe_interval_s=0)
    try:
        rs = np.random.RandomState(53)
        prompt = rs.randint(0, 96, (5,)).astype(np.int32)
        import jax
        kw = dict(temperature=0.8, top_k=7, top_p=0.9, seed=42)
        ref = np.asarray(generate(
            model, prompt[None], 12, key=jax.random.PRNGKey(42),
            **{k: v for k, v in kw.items() if k != "seed"}))[0, 5:]
        # pin a session to the async replica so the kill hits the
        # lookahead loop mid-stream (routing hashes the session id —
        # try ids until one lands; the endpoint is set by the start)
        it = toks = None
        for n in range(32):
            sess = router.session(f"hot-victim-{n}")
            it = sess.generate("llm", prompt, 12, poll_wait_s=0.05,
                               resume_budget=2, **kw)
            first = next(it)             # start() ran: endpoint is real
            if sess.endpoint == ep_hot:
                toks = [first, next(it)]     # lookahead stream is live
                break
            list(it)                     # drain the mis-pinned stream
        check("genhot/victim_session_pinned", toks is not None,
              f"endpoint never hashed to {ep_hot}")
        hot.kill(ep_hot)                 # real SIGKILL, no goodbye
        err = None
        try:
            toks += list(it)             # resumes on the sync survivor
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        check("genhot/sampled_stream_byte_identical_through_kill",
              err is None
              and np.array_equal(np.asarray(toks, np.int32), ref),
              f"err={err} toks={toks} ref={ref.tolist()}")
        check("genhot/resume_counted",
              monitor.get_stat("serving/router/stream_resumes") >= 1,
              str(monitor.export_stats("serving/router/")))
        g = {}
        with io.InferenceClient(ep_plain, timeout=5.0) as c:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                g = c.health()["generators"]["llm"]
                if (g.get("active") == 0 and g.get("pages_free", 0)
                        + g.get("prefix_entries", 0) == g.get("pages")):
                    break
                time.sleep(0.1)
        check("genhot/zero_leaked_pages_on_survivor",
              g.get("pages_free", -1) + g.get("prefix_entries", 0)
              == g.get("pages"), str(g))
        check("genhot/survivor_is_synchronous",
              g.get("async_depth") == 0 and g.get("device_pt") is False
              and g.get("pending_steps") == 0, str(g))
    finally:
        router.close()
        for sp in (hot, plain):
            for ep in list(sp.procs):
                sp.kill(ep)


def _campaign_drain(engine, gid, wait_s=0.5):
    toks, n = [], 0
    while True:
        doc = engine.poll(gid, start=n, wait_s=wait_s)
        toks += doc["tokens"]
        n = len(toks)
        if doc["done"]:
            return toks, doc["error"]


def run_campaign(n: int, seed: int, tmp: str) -> None:
    """Seeded randomized chaos campaign over the KV failure domain.

    ``n`` scenarios, each drawn from ``random.Random(seed)``: a random
    store topology (shared spill root / one shared store object / peer
    tier), a random producer/consumer role pair, random hardening flags
    (fetch deadline, hedge threshold, breaker), and a random fault spec
    of 1-3 sites from the KV path (``kvstore.get``, ``kvstore.put``,
    ``kvstore.spill``, ``wire.kv_get``, ``fs.download``). A producer
    engine prefills-and-publishes a prompt, then a cold consumer engine
    serves the SAME prompt — admitting via KV fetch where the tiers
    survive, degrading to local recompute where they do not. Invariants
    asserted per scenario, whatever the faults did:

    - both streams byte-identical to solo ``generate()`` (degradation
      changes WHERE prefill ran, never a single byte of output);
    - zero leaked pages on both engines;
    - every fault that FIRED is visible in the degradation ledger (tier
      errors/timeouts on a store, or ``fetch_degraded`` on an engine) —
      silent slow paths are the bug this campaign exists to catch.

    Ends with a deterministic breaker-lifecycle check (open →
    backoff → half-open probe → closed, all observable in tier health)
    and a defaults check that a hardened-flags-off engine never reads a
    ``gen_kv_*`` flag on the hot path."""
    import random
    import time

    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import generate
    from paddle_tpu.serving import GenerationEngine
    from paddle_tpu.serving.kvstore import KVStore

    paddle_tpu.seed(7)
    cfg = LlamaConfig.tiny(vocab_size=96, hidden_size=32, num_layers=2,
                           num_heads=2, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    rng = random.Random(seed)
    refs: dict = {}

    def ref_for(pseed, plen, new):
        key = (pseed, plen, new)
        if key not in refs:
            p = np.random.RandomState(pseed).randint(
                0, 96, (plen,)).astype(np.int32)
            refs[key] = (p, np.asarray(generate(model, p[None],
                                                new))[0, plen:])
        return refs[key]

    sites = ("kvstore.get", "kvstore.put", "kvstore.spill",
             "wire.kv_get", "fs.download")
    role_pairs = (("both", "decode"), ("prefill", "decode"),
                  ("both", "both"))
    topos = ("shared_spill", "shared_store", "peer")

    for i in range(n):
        tag = f"campaign/{i:02d}"
        pseed = rng.randrange(1000)
        plen = rng.choice((16, 24))
        new = rng.choice((4, 6))
        prod_role, cons_role = rng.choice(role_pairs)
        topo = rng.choice(topos)
        hard = dict(fetch_timeout_s=rng.choice((0.0, 0.25)),
                    hedge_ms=rng.choice((0.0, 5.0)),
                    breaker=rng.choice((0, 2)), breaker_backoff_s=0.05)
        spec = {s: (rng.choice((0.3, 0.7, 1.0)), rng.choice((1, 2, 3)))
                for s in rng.sample(sites, rng.randint(1, 3))}
        desc = (f"topo={topo} roles={prod_role}/{cons_role} "
                f"prompt=({pseed},{plen})+{new} hard={hard} spec={spec}")
        prompt, ref = ref_for(pseed, plen, new)
        stores: list = []
        try:
            if topo == "shared_spill":
                spill = os.path.join(tmp, f"kvcamp{i}")
                prod_store = KVStore(pages=64, spill=spill, **hard)
                cons_store = KVStore(pages=64, spill=spill, **hard)
                stores = [prod_store, cons_store]
            elif topo == "shared_store":
                prod_store = cons_store = KVStore(pages=64, **hard)
                stores = [prod_store]
            else:                      # peer tier: consumer reaches the
                prod_store = KVStore(pages=64)       # producer directly
                cons_store = KVStore(
                    pages=64, spill=os.path.join(tmp, f"kvcamp{i}"),
                    peers=(prod_store.get,), **hard)
                stores = [prod_store, cons_store]
            with GenerationEngine(model, slots=2, max_len=64, paged=True,
                                  page_tokens=8, kv_store=prod_store,
                                  role=prod_role) as prod, \
                 GenerationEngine(model, slots=2, max_len=64, paged=True,
                                  page_tokens=8, kv_store=cons_store,
                                  role=cons_role) as cons:
                with fault.inject_faults(spec, seed=seed * 1000 + i):
                    pt, pe = _campaign_drain(prod, prod.start(prompt, new))
                    ct, ce = _campaign_drain(cons, cons.start(prompt, new))
                    fired = {s: f for s, (_, f)
                             in fault.site_counts().items() if f}
                check(f"{tag}/streams_byte_identical",
                      pe is None and ce is None
                      and np.array_equal(np.asarray(pt, np.int32), ref)
                      and np.array_equal(np.asarray(ct, np.int32), ref),
                      f"{desc} perr={pe} cerr={ce}")
                leaks = []
                for who, eng in (("producer", prod), ("consumer", cons)):
                    g = eng.stats()
                    if g["pages_free"] + g["prefix_entries"] != g["pages"]:
                        leaks.append((who, g["pages_free"],
                                      g["prefix_entries"], g["pages"]))
                check(f"{tag}/zero_leaked_pages", not leaks,
                      f"{desc} leaks={leaks}")
                booked = sum(s["errors"] + s["timeouts"]
                             for s in (st.snapshot() for st in stores))
                booked += sum(eng.stats()["kv"]["fetch_degraded"]
                              for eng in (prod, cons))
                check(f"{tag}/degradation_explained",
                      not fired or booked > 0,
                      f"{desc} fired={fired} booked={booked}")
        finally:
            for st in stores:
                st.close()

    # deterministic tail: the full breaker lifecycle, observable in tier
    # health — consecutive spill failures OPEN the breaker (the store
    # stops being placeable), the backoff elapses, ONE half-open probe
    # goes through, and a clean answer CLOSES it again
    st = KVStore(pages=8, spill=os.path.join(tmp, "kvcamp_breaker"),
                 breaker=2, breaker_backoff_s=0.05)
    try:
        st.put("warm", b"W" * 8)
        with fault.inject_faults({"kvstore.spill": 1.0}, seed=11):
            for k in ("c1", "c2", "c3"):
                st.get(k)
        h = st.snapshot()["health"]["spill"]
        check("campaign/breaker_opens",
              h["opens"] == 1 and h["state"] in ("open", "half_open")
              and not st.placeable, str(h))
        time.sleep(0.12)               # backoff elapses -> probe window
        st.get("c1")                   # clean absence closes the tier
        h = st.snapshot()["health"]["spill"]
        check("campaign/breaker_half_opens_then_closes",
              h["half_opens"] >= 1 and h["closes"] == 1
              and h["state"] == "closed" and st.placeable, str(h))
    finally:
        st.close()

    # defaults: a hardened-flags-off engine serves byte-identical and
    # never reads a gen_kv_* flag on the hot path (construction only)
    import paddle_tpu.serving.engine as engine_mod

    prompt, ref = ref_for(3, 16, 4)
    reads: list = []
    real_flag = engine_mod.flag
    engine_mod.flag = lambda name: (reads.append(name), real_flag(name))[1]
    try:
        with GenerationEngine(model, slots=2, max_len=64, paged=True,
                              page_tokens=8) as eng:
            ctor = [r for r in reads if r.startswith("gen_kv")]
            del reads[:]
            toks, err = _campaign_drain(eng, eng.start(prompt, 4))
            hot = [r for r in reads if r.startswith("gen_kv")]
    finally:
        engine_mod.flag = real_flag
    check("campaign/defaults_no_hot_path_flag_reads",
          err is None and np.array_equal(np.asarray(toks, np.int32), ref)
          and ctor and not hot,
          f"err={err} ctor_reads={len(ctor)} hot_reads={hot}")


def scenario_sparse_serve(tmp: str) -> None:
    """SIGKILL a sparse-serving replica mid-version-rollover under
    routed load: two subprocess replicas (``--emb-ps``) serve a CTR
    endpoint over one PS fleet; the trainer publishes v1 and one
    replica is SIGKILLed before it can flip — zero requests are
    dropped (the router fails idempotent infers over), no response
    ever mixes rows of two versions, the survivor converges to the
    published version on its health tick, and zero stale serves
    happen (the PS fleet stayed healthy throughout)."""
    import threading
    import time

    from paddle_tpu.distributed.ps import ParameterServer, PSClient
    from paddle_tpu.serving import RoutedClient, SubprocessSpawner

    monitor.reset_stats("serving/router/")
    ps_srv = ParameterServer().start()
    ps = PSClient(ps_srv.endpoint)
    rc = None
    spawner = SubprocessSpawner(extra_args=(
        "--emb-ps", ps_srv.endpoint, "--emb-table", "emb:8:3"))
    try:
        ps.create_table("emb", 8, optimizer="sgd", lr=0.5, seed=3)
        eps = [spawner.spawn() for _ in range(2)]
        rc = RoutedClient(eps, probe_interval_s=0.25, timeout=10.0)
        q = np.arange(12, dtype=np.int64).reshape(4, 3)
        stop = threading.Event()
        errors: list = []
        mixed: list = []
        seen: set = set()
        n_ok = [0]
        lock = threading.Lock()

        def hammer():
            try:
                while not stop.is_set():
                    scores, ver = rc.infer("ctr", q)
                    v = int(ver[0, 0])
                    with lock:
                        n_ok[0] += 1
                        seen.add(v)
                        if not (ver == v).all():
                            mixed.append(ver.tolist())
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)                    # serve a while at v0
        ps.publish_version("emb")          # the trainer's push...
        spawner.kill(eps[0])               # ...and a replica dies mid-
        survivor = eps[1]                  # rollover, before it flips
        emb = {}
        deadline = time.monotonic() + 10.0
        with io.InferenceClient(survivor, timeout=5.0) as c:
            while time.monotonic() < deadline:
                emb = c.health().get("emb", {})   # health tick = flip
                if emb.get("tables", {}).get("emb", {}) \
                        .get("version") == 1:
                    break
                time.sleep(0.1)
        time.sleep(0.4)                    # serve a while at v1
        stop.set()
        for t in threads:
            t.join(timeout=30)
        check("sparse/zero_dropped_requests",
              not errors and n_ok[0] > 10,
              f"errors={errors[:2]} n={n_ok[0]}")
        check("sparse/failover_fired",
              monitor.get_stat("serving/router/failovers") >= 1,
              str(monitor.export_stats("serving/router/")))
        check("sparse/zero_mixed_version_responses", not mixed,
              str(mixed[:2]))
        check("sparse/versions_converged",
              seen == {0, 1}
              and emb.get("tables", {}).get("emb", {}).get("version") == 1
              and emb.get("rollovers") == 1,
              f"seen={seen} emb={emb}")
        check("sparse/zero_stale_serves",
              emb.get("stale_serves", -1) == 0, str(emb))
    finally:
        if rc is not None:
            rc.close()
        for ep in list(spawner.procs):
            spawner.kill(ep)
        ps.close()
        ps_srv.stop()


def scenario_kv_campaign(tmp: str) -> None:
    """A small fixed slice of the randomized KV chaos campaign (see
    ``run_campaign``): 5 scenarios at seed 0, plus the deterministic
    breaker-lifecycle and defaults tails. ``--campaign N --seed S``
    runs a larger campaign standalone."""
    run_campaign(5, 0, tmp)


def _report() -> int:
    ok = all(c[1] for c in CHECKS)
    print(json.dumps({
        "ok": ok,
        "checks": {name: passed for name, passed, _ in CHECKS},
        "failures": [{"check": n, "detail": d}
                     for n, p, d in CHECKS if not p],
        "stats": {k: v for k, v in monitor.export_stats().items()
                  if k.split("/")[0] in ("wire", "ckpt", "fault", "train",
                                         "serving", "gen", "control",
                                         "kv")},
    }, indent=2))
    return 0 if ok else 1


SCENARIOS = (scenario_serving_wire, scenario_checkpoint,
             scenario_elastic_resume, scenario_overload,
             scenario_obs, scenario_serving_routed,
             scenario_gen_engine, scenario_gen_paged,
             scenario_control_plane, scenario_control_ha,
             scenario_gen_resilience,
             scenario_gen_spec, scenario_gen_sharded,
             scenario_obs_fleet, scenario_ledger,
             scenario_gen_disagg,
             scenario_gen_hotloop,
             scenario_gen_sched,
             scenario_sparse_serve,
             scenario_kv_campaign)


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    campaign_n = None
    seed = 0
    if "--campaign" in argv:
        campaign_n = int(argv[argv.index("--campaign") + 1])
    if "--seed" in argv:
        seed = int(argv[argv.index("--seed") + 1])
    # positional args name scenarios to run (e.g. ``control-ha``); the
    # defaults checks always run
    by_name = {fn.__name__[len("scenario_"):].replace("_", "-"): fn
               for fn in SCENARIOS}
    names, skip = [], False
    for a in argv:
        if skip:
            skip = False
        elif a in ("--campaign", "--seed"):
            skip = True
        elif not a.startswith("-"):
            if a not in by_name:
                print(f"unknown scenario {a!r}; one of "
                      f"{', '.join(sorted(by_name))}", file=sys.stderr)
                return 2
            names.append(a)
    scenarios = [by_name[n] for n in names] if names else SCENARIOS
    check_defaults_off()
    with tempfile.TemporaryDirectory(prefix="ptpu_chaos_") as tmp:
        os.environ["PADDLE_CKPT_CACHE_ROOT"] = os.path.join(tmp, "cache")
        if campaign_n is not None:     # campaign-only run: defaults +
            try:                       # the randomized KV campaign
                run_campaign(campaign_n, seed, tmp)
            except Exception as e:
                check("run_campaign/completed", False,
                      f"{type(e).__name__}: {e}")
            return _report()
        for scenario in scenarios:
            try:
                scenario(tmp)
            except Exception as e:   # a crash is a failed check, not a
                check(f"{scenario.__name__}/completed", False,   # traceback
                      f"{type(e).__name__}: {e}")
    return _report()


if __name__ == "__main__":
    sys.exit(main())
