#!/usr/bin/env python
"""Generation-serving benchmark: continuous-batching engine vs
sequential ``generate()`` at request concurrency 1 / 4 / 8 on CPU.

What it measures: N greedy generation requests arriving at once.

- **sequential** is the status-quo path (PR 4 and earlier): one
  compiled whole-loop ``generate`` (jitted once; compile excluded) runs
  each request to completion before the next starts — a long generation
  starves every caller behind it, and every decode step reads the full
  weight set for ONE sequence.
- **engine** is the continuous-batching ``GenerationEngine``: requests
  are admitted into KV-cache slots and stepped together, so each fused
  decode step reads the weights once for ALL active sequences
  (decode on CPU/TPU is memory-bound — that weight-read amortization,
  plus per-dispatch overhead amortization, is the whole win).

Per cell: aggregate tokens/s (total emitted tokens / wall time from
submission to last completion) and time-to-first-token p50/p99 across
requests — TTFT is when the caller can SEE a token: the engine streams,
so its TTFT is roughly one prefill + queue wait; the sequential path
only surfaces tokens when a request's whole loop finishes, so its tail
TTFT grows linearly with the queue. Each cell is the median of
``--reps`` runs after warmup (all compiles primed).

Writes ``BENCH_generation.json`` (repo root by default); the headline
metric is the concurrency-8 tokens/s speedup — acceptance floor 1.5x.

Usage: ``JAX_PLATFORMS=cpu python tools/bench_generation.py [-o OUT]``
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu                                      # noqa: E402
from paddle_tpu.models import (                        # noqa: E402
    LlamaConfig, LlamaForCausalLM,
)
from paddle_tpu.models.generation import generate      # noqa: E402
from paddle_tpu.serving import GenerationEngine        # noqa: E402

# Geometry: big enough that a decode step is weight-read-bound (the
# regime batching amortizes), small enough for a CPU bench run.
VOCAB, HIDDEN, LAYERS, HEADS = 512, 256, 4, 8
PROMPT_LEN, MAX_NEW, MAX_LEN, SLOTS = 16, 32, 64, 8


def _percentile(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    i = min(int(round(q * (len(ys) - 1))), len(ys) - 1)
    return ys[i]


def bench_sequential(solo, prompts) -> dict:
    t0 = time.perf_counter()
    ttft, tokens = [], 0
    for p in prompts:
        out = np.asarray(solo(p[None]))       # blocks to completion
        ttft.append(time.perf_counter() - t0)  # first visible token
        tokens += out.shape[1] - PROMPT_LEN
    wall = time.perf_counter() - t0
    return {"tokens": tokens, "wall_s": wall,
            "tokens_per_s": tokens / wall, "ttft": ttft}


def bench_engine(engine, prompts) -> dict:
    n = len(prompts)
    ttft = [0.0] * n
    counts = [0] * n
    done_at = [0.0] * n
    gate = threading.Barrier(n + 1)

    def worker(i):
        gate.wait()
        gid = engine.start(prompts[i], MAX_NEW)
        first, nread = None, 0
        while True:
            doc = engine.poll(gid, start=nread, wait_s=1.0)
            if doc["tokens"] and first is None:
                first = time.perf_counter()
            nread += len(doc["tokens"])
            if doc["done"]:
                if doc["error"]:
                    raise RuntimeError(doc["error"])
                break
        ttft[i] = first - t0
        counts[i] = nread
        done_at[i] = time.perf_counter()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    gate.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = max(done_at) - t0
    tokens = sum(counts)
    return {"tokens": tokens, "wall_s": wall,
            "tokens_per_s": tokens / wall, "ttft": ttft}


def summarize(runs: list[dict]) -> dict:
    ttft = runs[0]["ttft"]    # per-request spread from the first run
    return {
        "tokens_per_s": statistics.median(r["tokens_per_s"]
                                          for r in runs),
        "wall_s": statistics.median(r["wall_s"] for r in runs),
        "tokens": runs[0]["tokens"],
        "ttft_p50_s": _percentile(ttft, 0.50),
        "ttft_p99_s": _percentile(ttft, 0.99),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_generation.json"))
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--concurrency", type=int, nargs="*",
                    default=[1, 4, 8])
    args = ap.parse_args()

    import jax

    paddle_tpu.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, hidden_size=HIDDEN,
                           num_layers=LAYERS, num_heads=HEADS,
                           num_kv_heads=HEADS, max_seq_len=MAX_LEN)
    model = LlamaForCausalLM(cfg)
    rs = np.random.RandomState(0)
    all_prompts = rs.randint(0, VOCAB, (max(args.concurrency),
                                        PROMPT_LEN)).astype(np.int32)

    solo = jax.jit(lambda ids: generate(model, ids, MAX_NEW))
    engine = GenerationEngine(model, slots=SLOTS, max_len=MAX_LEN,
                              queue_max=32)

    # warmup: prime the solo compile, the engine prefill bucket + step,
    # and sanity-check engine output == solo output on the way
    ref = np.asarray(solo(all_prompts[:1]))[0, PROMPT_LEN:]
    gid = engine.start(all_prompts[0], MAX_NEW)
    toks, nread = [], 0
    while True:
        doc = engine.poll(gid, start=nread, wait_s=1.0)
        toks += doc["tokens"]
        nread = len(toks)
        if doc["done"]:
            break
    if not np.array_equal(np.asarray(toks, np.int32), ref):
        print("FATAL: engine output diverges from solo generate",
              file=sys.stderr)
        return 1

    report: dict = {
        "bench": "generation",
        "model": {"vocab": VOCAB, "hidden": HIDDEN, "layers": LAYERS,
                  "heads": HEADS},
        "prompt_len": PROMPT_LEN, "max_new_tokens": MAX_NEW,
        "slots": SLOTS, "reps": args.reps, "platform": "cpu",
        "ttft_definition": ("submission -> first token VISIBLE to the "
                            "caller (engine streams per step; "
                            "sequential only surfaces tokens when a "
                            "request's whole loop returns)"),
        "concurrency": {},
    }
    for n in args.concurrency:
        prompts = list(all_prompts[:n])
        seq_runs = [bench_sequential(solo, prompts)
                    for _ in range(args.reps)]
        eng_runs = [bench_engine(engine, prompts)
                    for _ in range(args.reps)]
        seq, eng = summarize(seq_runs), summarize(eng_runs)
        cell = {"sequential": seq, "engine": eng,
                "speedup_tokens_per_s": (eng["tokens_per_s"]
                                         / seq["tokens_per_s"])}
        report["concurrency"][str(n)] = cell
        print(f"concurrency {n}: sequential "
              f"{seq['tokens_per_s']:.0f} tok/s "
              f"(ttft p99 {seq['ttft_p99_s'] * 1e3:.0f} ms) | engine "
              f"{eng['tokens_per_s']:.0f} tok/s "
              f"(ttft p99 {eng['ttft_p99_s'] * 1e3:.0f} ms) | "
              f"speedup {cell['speedup_tokens_per_s']:.2f}x")

    top = str(max(args.concurrency))
    headline = report["concurrency"][top]["speedup_tokens_per_s"]
    report["headline"] = {f"conc{top}_speedup": headline, "floor": 1.5}
    engine.close()
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}; headline conc-{top} speedup "
          f"{headline:.2f}x (floor 1.5x)")
    return 0 if headline >= 1.5 else 1


if __name__ == "__main__":
    sys.exit(main())
